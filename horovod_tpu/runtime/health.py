"""Training-health plane: in-trace numerics telemetry, NaN culprit
attribution, divergence sentinels (docs/health.md).

The framework's other observability planes watch the machinery — wire
bytes, wall-clock, device cycles, crash forensics — but nothing watched
the *model*: a NaN injected by one rank poisons the whole fleet's
allreduce and surfaces as everyone's NaN, and divergence shows up as
accuracy-off-a-cliff days later.  This module is the fifth plane:

* **in-trace stat taps** — ``DistributedOptimizer`` (every ZeRO stage,
  overlap on or off) and the negotiated allreduce/reducescatter
  programs compute per-dtype-group statistics over the flat gradient
  buffers they already hold: finite-part global grad norm, max-abs and
  the **pre-reduction nonfinite count**, at near-zero cost (the stats
  ride the existing program; the only new communication is one small
  packed per-rank verdict vector allgathered per step).  Because the
  verdict is gathered *before* the reduction mixes ranks, a nonfinite
  names its culprit rank and dtype group instead of surfacing as
  everyone's NaN.
* **post-update update-to-weight ratio** — the classic divergence
  leading indicator, computed rank-locally (shard-locally under ZeRO),
  zero extra communication.
* **host-side HealthMonitor** — EWMA divergence sentinels with
  hysteresis over the loss trajectory and the grad norm
  (``HOROVOD_HEALTH_*`` knobs), publishing ``hvd_grad_norm`` /
  ``hvd_update_ratio`` / ``hvd_nonfinite_total{group,rank}`` /
  ``hvd_health_alert{reason}`` into the PR 6 registry (and therefore
  the launcher fleet merge), recording ``health`` events (first
  nonfinite, sentinel trips) onto the PR 8 flight rings, and feeding
  the real loss trajectory to the PR 10 compression guardrail as its
  primary signal.
* **skip-step contract** — ``HOROVOD_HEALTH_SKIP_NONFINITE=1`` makes
  the optimizer suppress a step whose verdict carries a nonfinite:
  the update is zeroed and the optimizer state (momenta, error-feedback
  residuals) is *held*, riding the same state-selection machinery the
  EF residual path uses — survivors' parameters stay finite while the
  culprit is named.

Import stays jax-free (the monitor runs in probe children and the
launcher); the trace-side taps import jax lazily.
"""

from __future__ import annotations

import functools
import json
import math
import os
import threading
import time

from horovod_tpu.common import config as _config
from horovod_tpu.common import logging as _log
from horovod_tpu.runtime import flight as _flight
from horovod_tpu.runtime import metrics as _metrics

# ---------------------------------------------------------------------------
# Metric surface (docs/metrics.md catalog)
# ---------------------------------------------------------------------------

_M_GRAD_NORM = _metrics.gauge(
    "hvd_grad_norm",
    "Pre-reduction global gradient norm per dtype group: sqrt of the "
    "sum over ranks of each rank's finite-part local ||g||^2 (from "
    "the health verdict allgather — zero extra full-size buffers).  "
    "group=all is the all-group total the divergence sentinel "
    "watches.")
_M_GRAD_MAXABS = _metrics.gauge(
    "hvd_grad_max_abs",
    "Largest finite |gradient| element across ranks per dtype group "
    "(pre-reduction).")
_M_UPDATE_RATIO = _metrics.gauge(
    "hvd_update_ratio",
    "Post-update ||update|| / ||param|| per dtype group, computed "
    "rank-locally (shard-locally under ZeRO) — the update-to-weight "
    "divergence leading indicator.")
_M_NONFINITE = _metrics.counter(
    "hvd_nonfinite_total",
    "Nonfinite gradient elements observed PRE-reduction, labeled by "
    "culprit rank and dtype group — the attribution a post-reduction "
    "NaN cannot give.")
_M_ALERT = _metrics.gauge(
    "hvd_health_alert",
    "1 while a health alert is active, labeled reason=nonfinite | "
    "loss_divergence | grad_norm_divergence | loss_nonfinite "
    "(docs/health.md sentinel semantics).")
_M_LOSS = _metrics.gauge(
    "hvd_loss",
    "Last loss value observed by hvd.health.observe_loss() — the real "
    "convergence signal the compression guardrail consumes.")
_M_SKIPPED = _metrics.counter(
    "hvd_health_skipped_steps_total",
    "Optimizer steps suppressed by HOROVOD_HEALTH_SKIP_NONFINITE "
    "(update zeroed, state held) after a nonfinite verdict.")

#: Samples a sentinel's EWMA must absorb before it may breach — a
#: noisy first loss value must not trip the alarm (docs/health.md).
WARMUP_SAMPLES = 5

_TINY = 1e-12


def enabled() -> bool:
    """The ``HOROVOD_HEALTH`` master switch (validated at the round-0
    handshake: the taps change the negotiated programs)."""
    return bool(_config.get("health"))


def skip_enabled() -> bool:
    return bool(_config.get("health_skip_nonfinite"))


# ---------------------------------------------------------------------------
# Divergence sentinel (EWMA + hysteresis)
# ---------------------------------------------------------------------------


class Sentinel:
    """One signal's divergence detector: an EWMA baseline and a
    trip/clear hysteresis counter pair.

    A sample *breaches* when it exceeds ``ratio x EWMA`` (or is
    nonfinite).  ``trip_steps`` consecutive breaches raise the alert;
    ``clear_steps`` consecutive healthy samples clear it.  The EWMA
    absorbs only healthy finite samples — a baseline that chased the
    divergence would never trip (pinned by the hysteresis unit
    tests)."""

    def __init__(self, reason: str, alpha: float, ratio: float,
                 trip_steps: int, clear_steps: int):
        self.reason = reason
        self.alpha = max(min(float(alpha), 1.0), 1e-6)
        self.ratio = float(ratio)
        self.trip_steps = max(1, int(trip_steps))
        self.clear_steps = max(1, int(clear_steps))
        self.mean: float | None = None
        self.samples = 0
        self.last: float | None = None
        self.breaches = 0
        self.healthy = 0
        self.active = False
        self.trips = 0

    def observe(self, value: float) -> str | None:
        """Feed one sample; returns ``"trip"`` / ``"clear"`` on a state
        change, else None."""
        self.last = value
        finite = isinstance(value, (int, float)) and math.isfinite(value)
        warm = self.samples >= WARMUP_SAMPLES and self.mean is not None
        # Ratio breaches need a POSITIVE baseline: against a negative
        # EWMA (e.g. an ELBO/negative-log-likelihood loss) the
        # threshold would collapse to ~0 and normal noise around zero
        # would false-trip — such signals rely on the nonfinite and
        # grad-norm sentinels instead (docs/health.md).
        breach = (not finite) or (
            warm and self.ratio > 0 and self.mean > _TINY
            and value > self.ratio * self.mean)
        event = None
        if breach:
            self.breaches += 1
            self.healthy = 0
            if not self.active and self.breaches >= self.trip_steps:
                self.active = True
                self.trips += 1
                event = "trip"
        else:
            self.healthy += 1
            self.breaches = 0
            if self.active and self.healthy >= self.clear_steps:
                self.active = False
                event = "clear"
        if finite and not breach:
            self.mean = (value if self.mean is None else
                         (1 - self.alpha) * self.mean
                         + self.alpha * value)
            self.samples += 1
        return event

    def state(self) -> dict:
        return {"reason": self.reason, "active": self.active,
                "trips": self.trips, "ewma": self.mean,
                "last": self.last, "samples": self.samples,
                "breaches": self.breaches}


class HealthMonitor:
    """Host-side consumer of the in-trace stats: sentinels, alert
    gauges, flight events, dumps and the guardrail's loss verdict.
    ``clock`` is injectable for the fake-clock unit tests."""

    def __init__(self, clock=time.time):
        self._lock = threading.RLock()
        self._clock = clock
        ratio = float(_config.get("health_sentinel_ratio"))
        alpha = float(_config.get("health_ewma_alpha"))
        trip = int(_config.get("health_trip_steps"))
        clear = int(_config.get("health_clear_steps"))
        self.loss = Sentinel("loss_divergence", alpha, ratio, trip, clear)
        self.grad = Sentinel("grad_norm_divergence", alpha, ratio, trip,
                             clear)
        self.nonfinite_events = 0      # verdicts that carried a nonfinite
        self.nonfinite_elems = 0.0
        self.culprits: dict = {}       # (rank, group) -> elem count
        self.first_nonfinite: dict | None = None
        # Clean-streak counters for the latched-alert clears: the
        # nonfinite alerts are raised by single events, so their
        # hysteresis rides consecutive CLEAN observations (clear_steps
        # verdicts without a nonfinite / finite losses) — a transient
        # NaN recovered by the skip contract must not pin the alert
        # (and the guardrail) for the rest of a long run.
        self._nf_clean_streak = 0
        self._loss_finite_streak = 0
        self._loss_obs_at_last_nf: int | None = None
        # Wire-round bookkeeping (eager regime): a negotiation round
        # whose dispatches produced no nonfinite verdict counts as one
        # clean step toward the clear hysteresis — per ROUND, not per
        # fused buffer, so K buffers per step cannot shrink the
        # configured clear window K-fold.
        self._wire_round: int | None = None
        self._nf_events_at_round = 0
        self.skipped_steps = 0
        self.last_grad_norm: float | None = None
        self.last_loss: float | None = None
        self.loss_observed = 0
        self._alerts: dict[str, bool] = {}
        self._alert_log: list = []

    # -- alert bookkeeping -------------------------------------------------

    def _raise_alert(self, reason: str, **detail) -> None:
        with self._lock:
            fresh = not self._alerts.get(reason)
            self._alerts[reason] = True
            if fresh:
                rec = {"reason": reason, "time": self._clock(), **detail}
                self._alert_log.append(rec)
        if fresh:
            _M_ALERT.set(1, reason=reason)
            _flight.record("health", event="sentinel_trip", reason=reason,
                           **{k: v for k, v in detail.items()
                              if isinstance(v, (int, float, str))})
            _log.warning(f"[health] alert {reason}: {detail}")

    def _clear_alert(self, reason: str) -> None:
        with self._lock:
            # Never INSERT the key: clearing a reason that never
            # tripped would publish a phantom hvd_health_alert series
            # at 0 on healthy runs (and live-endpoint reports count
            # every series toward the lifetime total).
            if not self._alerts.get(reason):
                return
            self._alerts[reason] = False
        _M_ALERT.set(0, reason=reason)
        _flight.record("health", event="sentinel_clear", reason=reason)

    def alerts_total(self) -> int:
        with self._lock:
            return len(self._alert_log)

    def active_alerts(self) -> list[str]:
        with self._lock:
            return sorted(r for r, on in self._alerts.items() if on)

    # -- observations ------------------------------------------------------

    def observe_loss(self, value: float, step: int | None = None) -> None:
        value = float(value)
        with self._lock:
            self.last_loss = value
            self.loss_observed += 1
        _M_LOSS.set(value)
        if not math.isfinite(value):
            with self._lock:
                self._loss_finite_streak = 0
            self._raise_alert("loss_nonfinite", value=repr(value),
                              step=step if step is not None else -1)
            return
        with self._lock:
            self._loss_finite_streak += 1
            clear_nf = (self._loss_finite_streak
                        >= self.loss.clear_steps)
            # The gradient-nonfinite alert's loss-streak clear (the
            # eager regime's recovery evidence — its per-buffer wire
            # verdicts deliberately do not drive the clear hysteresis,
            # see note_verdict) additionally requires clear_steps loss
            # observations since the LAST nonfinite event: under
            # persistent poisoning with the skip contract on, the loss
            # stays finite while verdicts keep arriving poisoned, and
            # clearing on the loss streak alone would flap the alert
            # (and momentarily unpin the compression guardrail) every
            # clear_steps losses.
            clear_grad_nf = clear_nf and (
                self._loss_obs_at_last_nf is None
                or self.loss_observed - self._loss_obs_at_last_nf
                >= self.loss.clear_steps)
        if clear_nf:
            self._clear_alert("loss_nonfinite")
        if clear_grad_nf:
            self._clear_alert("nonfinite")
        with self._lock:  # sentinel state must never tear in a dump
            ev = self.loss.observe(value)
        if ev == "trip":
            self._raise_alert(self.loss.reason, value=value,
                              ewma=self.loss.mean)
        elif ev == "clear":
            self._clear_alert(self.loss.reason)

    def observe_grad_norm(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self.last_grad_norm = value
        if not math.isfinite(value):
            return
        with self._lock:  # sentinel state must never tear in a dump
            ev = self.grad.observe(value)
        if ev == "trip":
            self._raise_alert(self.grad.reason, value=value,
                              ewma=self.grad.mean)
        elif ev == "clear":
            self._clear_alert(self.grad.reason)

    def note_verdict(self, had_nonfinite: bool) -> None:
        """Once per WHOLE-STEP verdict (the in-trace optimizer tap):
        drives the nonfinite alert's clear side — ``clear_steps``
        consecutive clean verdicts clear a latched nonfinite alert
        (the raise side is :meth:`note_nonfinite`).  Per-buffer wire
        verdicts must NOT call this: several fused buffers per step
        would shrink the configured hysteresis buffer-count-fold (the
        eager regime's clear evidence is the finite-loss streak in
        :meth:`observe_loss` instead)."""
        with self._lock:
            if had_nonfinite:
                self._nf_clean_streak = 0
                return
            self._nf_clean_streak += 1
            clear = self._nf_clean_streak >= self.loss.clear_steps
        if clear:
            self._clear_alert("nonfinite")

    def note_wire_round(self, rnd: int) -> None:
        """Once per negotiated data-plane round with health on (the
        background dispatch calls it): a COMPLETED round whose
        verdicts were all clean advances the nonfinite alert's clear
        streak by one — the eager regime's per-step clear evidence
        for jobs that never feed a loss (per round, not per fused
        buffer, so the configured hysteresis holds)."""
        with self._lock:
            if self._wire_round is None:
                self._wire_round = rnd
                self._nf_events_at_round = self.nonfinite_events
                return
            if rnd == self._wire_round:
                return
            clean = self.nonfinite_events == self._nf_events_at_round
            self._wire_round = rnd
            self._nf_events_at_round = self.nonfinite_events
            if clean:
                self._nf_clean_streak += 1
            clear = (clean and self._nf_clean_streak
                     >= self.loss.clear_steps)
        if clear:
            self._clear_alert("nonfinite")

    def note_nonfinite(self, count: float, group: str, rank: int) -> None:
        """One verdict row reported ``count`` nonfinite elements from
        ``rank``'s ``group`` buffer — culprit attribution."""
        first = False
        with self._lock:
            self._nf_clean_streak = 0
            self._loss_obs_at_last_nf = self.loss_observed
            self.nonfinite_events += 1
            self.nonfinite_elems += float(count)
            key = (int(rank), str(group))
            self.culprits[key] = self.culprits.get(key, 0.0) + float(count)
            if self.first_nonfinite is None:
                first = True
                self.first_nonfinite = {
                    "time": self._clock(), "rank": int(rank),
                    "group": str(group), "count": float(count)}
        if first:
            _flight.record("health", event="first_nonfinite",
                           culprit=int(rank), group=str(group),
                           count=float(count))
        self._raise_alert("nonfinite", rank=int(rank), group=str(group))

    def note_skip(self) -> None:
        with self._lock:
            self.skipped_steps += 1
        _M_SKIPPED.inc()
        _flight.record("health", event="skip_step")

    # -- guardrail / snapshot surfaces -------------------------------------

    def loss_guard(self) -> dict | None:
        """The compression guardrail's PRIMARY signal (docs/health.md,
        docs/compression.md): a verdict on the real loss trajectory,
        or None when no loss has been observed (the residual-ratio
        proxy then stays in charge as the fallback)."""
        with self._lock:
            if self.loss_observed < WARMUP_SAMPLES:
                return None
            diverged = (self._alerts.get("loss_divergence", False)
                        or self._alerts.get("loss_nonfinite", False)
                        or self._alerts.get("nonfinite", False))
            ratio = None
            if (self.loss.mean is not None and self.last_loss is not None
                    and math.isfinite(self.last_loss)):
                ratio = self.last_loss / max(self.loss.mean, _TINY)
            return {"diverged": bool(diverged), "ratio": ratio,
                    "samples": self.loss_observed}

    def refresh(self) -> None:
        """Metrics snapshot hook: re-publish the alert gauge series so
        every scrape/publish carries the current alert states (a rank
        that never re-observes after a trip must still export it)."""
        with self._lock:
            series = [({"reason": r}, 1.0 if on else 0.0)
                      for r, on in sorted(self._alerts.items())]
        if series:
            _M_ALERT.replace(series)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "time": self._clock(),
                "last_loss": self.last_loss,
                "last_grad_norm": self.last_grad_norm,
                "loss_observed": self.loss_observed,
                "nonfinite_events": self.nonfinite_events,
                "nonfinite_elems": self.nonfinite_elems,
                "culprits": [{"rank": r, "group": g, "count": c}
                             for (r, g), c in sorted(self.culprits.items())],
                "first_nonfinite": dict(self.first_nonfinite)
                if self.first_nonfinite else None,
                "skipped_steps": self.skipped_steps,
                "alerts_total": len(self._alert_log),
                "active_alerts": sorted(
                    r for r, on in self._alerts.items() if on),
                "alert_log": [dict(a) for a in self._alert_log],
                "sentinels": {"loss": self.loss.state(),
                              "grad_norm": self.grad.state()},
            }


_monitor: HealthMonitor | None = None
_monitor_lock = threading.Lock()


def monitor() -> HealthMonitor:
    global _monitor
    m = _monitor
    if m is None:
        with _monitor_lock:
            m = _monitor
            if m is None:
                m = _monitor = HealthMonitor()
                _metrics.add_snapshot_hook(_refresh_hook)
    return m


def _refresh_hook() -> None:
    m = _monitor
    if m is not None:
        m.refresh()


def reset() -> None:
    """Test hook: fresh monitor + cleared health gauge series."""
    global _monitor
    with _monitor_lock:
        _metrics.remove_snapshot_hook(_refresh_hook)
        _monitor = None
    for m in (_M_ALERT, _M_GRAD_NORM, _M_GRAD_MAXABS, _M_UPDATE_RATIO,
              _M_NONFINITE, _M_LOSS, _M_SKIPPED):
        m.reset()


def observe_loss(value: float, step: int | None = None) -> None:
    """Feed the real loss trajectory to the health plane — the
    divergence sentinel's and the compression guardrail's primary
    signal.  Host-side and cheap; call it once per step (bench does)."""
    monitor().observe_loss(value, step=step)


def loss_guard() -> dict | None:
    m = _monitor
    return m.loss_guard() if m is not None else None


def note_wire_round(rnd: int) -> None:
    """Background-dispatch hook (eager regime): see
    :meth:`HealthMonitor.note_wire_round`.  Touches the monitor only
    if one already exists — a clean round is only evidence once a
    verdict has been observed."""
    m = _monitor
    if m is not None:
        m.note_wire_round(int(rnd))


# ---------------------------------------------------------------------------
# Verdict publication (jax.debug.callback targets — host side)
# ---------------------------------------------------------------------------


def _own_rank() -> int:
    try:
        from horovod_tpu.common import basics as _basics

        st = _basics.state()
        if st.initialized:
            return int(st.rank)
    except Exception:
        pass
    return 0


def publish_verdict(gathered, idx=None, groups: tuple = (),
                    sentinel: bool = True) -> None:
    """Host side of the packed per-rank verdict allgather.  ``gathered``
    is ``(n, 1 + 3G)``: per rank ``[rank, (sumsq, maxabs, nonfinite)
    x G]`` with sumsq/maxabs over the FINITE part (NaN-proof) and the
    nonfinite element count carrying the poison signal.

    ``idx`` is the executing device's axis index: under a
    single-process multi-device mesh the host callback fires once per
    device with the identical replicated verdict, so counters would be
    multiplied device-fold — only the invocation whose device IS this
    process's rank publishes (exactly one publication per process in
    every regime; in the one-device-per-process regime idx == rank by
    the mesh construction).

    ``sentinel=False`` (the per-buffer wire taps): publish the gauges
    and culprit attribution but do NOT feed the grad-norm divergence
    sentinel — the eager wire fires once per negotiated fused buffer,
    and an EWMA fed per-buffer norms of wildly different magnitudes
    would false-trip on every big buffer.  The sentinel eats only
    whole-step verdicts (the in-trace optimizer tap) and the loss
    trajectory."""
    import numpy as np

    if idx is not None and int(np.asarray(idx)) != _own_rank():
        return
    arr = np.asarray(gathered, dtype=np.float64)
    g = max(1, len(groups))
    arr = arr.reshape(-1, 1 + 3 * g)
    m = monitor()
    total_sumsq = 0.0
    had_nonfinite = False
    for gi, gname in enumerate(groups):
        col = 1 + 3 * gi
        sumsq = float(np.sum(np.maximum(arr[:, col], 0.0)))
        maxab = float(np.max(arr[:, col + 1])) if arr.size else 0.0
        _M_GRAD_NORM.set(math.sqrt(max(sumsq, 0.0)), group=str(gname))
        if math.isfinite(maxab):
            _M_GRAD_MAXABS.set(maxab, group=str(gname))
        for row in arr:
            cnt = float(row[col + 2])
            if math.isfinite(cnt) and cnt > 0:
                had_nonfinite = True
                rk = int(row[0]) if math.isfinite(row[0]) else -1
                _M_NONFINITE.inc(cnt, group=str(gname), rank=str(rk))
                m.note_nonfinite(cnt, str(gname), rk)
        total_sumsq += max(sumsq, 0.0)
    if sentinel:
        # whole-step verdicts only: sentinel EWMA + the nonfinite
        # alert's clean-streak clear (per-buffer wire verdicts would
        # shrink the clear hysteresis buffer-count-fold)
        m.note_verdict(had_nonfinite)
        norm = math.sqrt(total_sumsq)
        _M_GRAD_NORM.set(norm, group="all")
        m.observe_grad_norm(norm)


def publish_update_ratio(ratios, groups: tuple) -> None:
    import numpy as np

    arr = np.asarray(ratios, dtype=np.float64).reshape(-1)
    for gname, v in zip(groups, arr):
        if math.isfinite(float(v)):
            _M_UPDATE_RATIO.set(float(v), group=str(gname))


def _note_skip_cb(bad, idx=None) -> None:
    import numpy as np

    if idx is not None and int(np.asarray(idx)) != _own_rank():
        return
    if bool(np.asarray(bad)):
        monitor().note_skip()


# ---------------------------------------------------------------------------
# Trace-side taps (jax imported lazily; pure observers — parity-proof)
# ---------------------------------------------------------------------------


def _axis_idx(axes):
    """Linearized rank index over one axis name or a tuple of them —
    delegated to :func:`~horovod_tpu.ops.collectives.shard_index` (the
    cross-major fold the data plane already uses), so the verdict's
    rank column can never drift from the shard assignment."""
    from horovod_tpu.ops.collectives import shard_index

    return shard_index(axes)


def _leaf_stats(leaf):
    """(sumsq, maxabs, nonfinite_count) of one leaf, NaN-proof: norm
    and max are over the finite part, the count carries the poison."""
    import jax.numpy as jnp

    x = leaf.astype(jnp.float32).reshape(-1)
    finite = jnp.isfinite(x)
    safe = jnp.where(finite, x, 0.0)
    return (jnp.sum(jnp.square(safe)),
            jnp.max(jnp.abs(safe)) if x.shape[0] else jnp.float32(0),
            jnp.sum((~finite).astype(jnp.float32)))


def _float_groups(leaves):
    """dtype-name -> leaves, float leaves only, insertion order (the
    fused-buffer group layout the optimizer already uses)."""
    import jax.numpy as jnp

    groups: dict = {}
    for leaf in leaves:
        leaf = jnp.asarray(leaf)
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            groups.setdefault(str(leaf.dtype), []).append(leaf)
    return groups


def tap_gradients(leaves, axis_name: str = "hvd"):
    """The in-trace stat tap: per-dtype-group finite-part sumsq /
    max-abs / nonfinite count of the PRE-reduCTION gradient leaves,
    packed into one small vector and allgathered over ``axis_name`` —
    the single new collective health adds to a step.  Publishes the
    verdict host-side via ``jax.debug.callback`` and returns the traced
    ``bad`` flag (any rank reported a nonfinite) for the skip-step
    contract, or None when there is nothing to tap.

    Zero extra full-size buffers by construction: every statistic is a
    scalar reduction per leaf — no gradient is concatenated or copied
    (the HLO proof in tests/test_health.py pins this)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    groups = _float_groups(leaves)
    if not groups:
        return None
    try:
        idx = _axis_idx(axis_name)
    except Exception:
        # axis unbound (plain jit without shard_map): local-only stats,
        # published as a one-row verdict.
        idx = None
    parts = [jnp.float32(0) if idx is None
             else idx.astype(jnp.float32)]
    for gname, ls in groups.items():
        stats = [_leaf_stats(l) for l in ls]
        parts.append(sum(s[0] for s in stats))
        parts.append(functools.reduce(jnp.maximum,
                                      [s[1] for s in stats]))
        parts.append(sum(s[2] for s in stats))
    vec = jnp.stack([jnp.asarray(p, jnp.float32) for p in parts])
    if idx is not None:
        gathered = lax.all_gather(vec, axis_name)
        if gathered.ndim > 2:  # tuple axes (hierarchical dp sub-axes)
            gathered = gathered.reshape(-1, vec.shape[0])
        cb_idx = idx
    else:
        gathered = vec.reshape(1, -1)
        cb_idx = jnp.int32(_own_rank())
    jax.debug.callback(
        functools.partial(publish_verdict, groups=tuple(groups)),
        gathered, cb_idx)
    # nonfinite-count columns are 3, 6, 9, ... of (rank, [ss, ma, nf]xG)
    bad = jnp.sum(gathered[:, 3::3]) > 0
    return bad, cb_idx


def tap_block(flat, axes, group: str) -> None:
    """The negotiated-program stat tap (ops/xla_exec builders): local
    stats of this rank's pre-reduction block, verdict allgathered over
    the program's own axis — stats ride the existing wire program, so
    a 2-proc eager run's metrics name the poisoned rank before the
    reduction mixes it into everyone's NaN."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    ss, ma, nf = _leaf_stats(flat)
    idx = _axis_idx(axes)
    vec = jnp.stack([idx.astype(jnp.float32), ss, ma, nf])
    gathered = lax.all_gather(vec, axes)
    if gathered.ndim > 2:  # tuple axes gather once per name
        gathered = gathered.reshape(-1, 4)
    jax.debug.callback(
        functools.partial(publish_verdict, groups=(group,),
                          sentinel=False), gathered, idx)


def tap_update_ratio(updates, params) -> None:
    """Post-update update-to-weight ratio per dtype group, computed
    over the local (shard-resident under ZeRO) views — zero extra
    communication.  Works traced (callback) and eager (one jitted
    call producing the small ratio vector, so the per-step eager cost
    is one dispatch, not a per-leaf op storm)."""
    import jax
    import jax.numpy as jnp

    if params is None:
        return
    ug = _float_groups(jax.tree_util.tree_leaves(updates))
    pg = _float_groups(jax.tree_util.tree_leaves(params))
    names = [g for g in ug if g in pg]
    if not names:
        return

    def ratios_of(ugl, pgl):
        out = []
        for uls, pls in zip(ugl, pgl):
            un = jnp.sqrt(sum(_leaf_stats(l)[0] for l in uls))
            pn = jnp.sqrt(sum(_leaf_stats(l)[0] for l in pls))
            out.append(un / jnp.maximum(pn, _TINY))
        return jnp.stack(out)

    ugl = [ug[g] for g in names]
    pgl = [pg[g] for g in names]
    if _in_trace_leaves(ugl):
        jax.debug.callback(
            functools.partial(publish_update_ratio, groups=tuple(names)),
            ratios_of(ugl, pgl))
    else:
        fn = _jitted.get("update_ratio")
        if fn is None:
            fn = _jitted["update_ratio"] = jax.jit(ratios_of)
        publish_update_ratio(fn(ugl, pgl), tuple(names))


def _in_trace_leaves(tree) -> bool:
    import jax

    return any(isinstance(l, jax.core.Tracer)
               for l in jax.tree_util.tree_leaves(tree))


def apply_skip_traced(bad, updates, old_state, new_state, idx=None):
    """In-trace skip-step: when the verdict flagged a nonfinite, zero
    the update and HOLD the optimizer state (momenta, EF residuals) —
    the same state-selection the EF residual path rides, so nothing
    the poisoned step produced survives into the trajectory."""
    import jax
    import jax.numpy as jnp

    def zero(u):
        return jnp.where(bad, jnp.zeros_like(u), u)

    def hold(old, new):
        return jnp.where(bad, old, new)

    if idx is None:
        jax.debug.callback(_note_skip_cb, bad)
    else:
        jax.debug.callback(_note_skip_cb, bad, idx)
    return (jax.tree_util.tree_map(zero, updates),
            jax.tree_util.tree_map(hold, old_state, new_state))


_jitted: dict = {}  # lazily-built jitted helpers (jax-free import)


def _nonfinite_count(leaves):
    """Jitted total nonfinite count over a list of float leaves — the
    verdict stays on-device; only one scalar crosses to host (the
    full-buffer D2H copy a host-side isfinite would pay is exactly the
    hot-path cost the plane promises not to add)."""
    fn = _jitted.get("nonfinite_count")
    if fn is None:
        import jax
        import jax.numpy as jnp

        fn = _jitted["nonfinite_count"] = jax.jit(
            lambda ls: sum(jnp.sum(~jnp.isfinite(l)) for l in ls))
    return fn(leaves)


def apply_skip_eager(updates, old_state, new_state):
    """Eager skip-step: a nonfinite that rode the negotiated wire
    poisons the reduced gradient — and therefore the update — on every
    rank identically, so finiteness of the updates IS the (consistent)
    skip verdict."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    leaves = [jnp.asarray(l) for l in jax.tree_util.tree_leaves(updates)
              ]
    floats = [l for l in leaves
              if jnp.issubdtype(l.dtype, jnp.floating)]
    if not floats or int(np.asarray(_nonfinite_count(floats))) == 0:
        return updates, new_state
    monitor().note_skip()
    return (jax.tree_util.tree_map(jnp.zeros_like, updates), old_state)


# ---------------------------------------------------------------------------
# Dumps + report (the `python -m horovod_tpu.perf health` surface)
# ---------------------------------------------------------------------------


def health_dir() -> str:
    return str(_config.get("health_dir") or "").strip() \
        or _flight.flight_dir()


def dump(reason: str = "explicit", directory: str | None = None
         ) -> str | None:
    """Write this rank's health snapshot as ``health-r<k>-g<g>.json``
    next to the flight dumps (idempotent per rank+generation, like the
    goodput ledger's).  Advisory — never takes a dying process further
    down."""
    d = directory or health_dir()
    if not d:
        return None
    try:
        meta = _flight._process_meta()
        snap = monitor().snapshot()
        snap["meta"] = {"rank": meta.get("rank", 0),
                        "size": meta.get("size", 1),
                        "generation": meta.get("generation", 0),
                        "host": meta.get("host", ""),
                        "reason": reason}
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"health-r{meta.get('rank', 0)}"
               f"-g{meta.get('generation', 0)}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, path)
        return path
    except Exception:
        return None


def from_metrics_snapshot(snap: dict) -> dict | None:
    """Health view from a ``/metrics.json`` (or KV-published) snapshot
    — the live-endpoint source of the report."""
    metrics = (snap or {}).get("metrics") or {}
    meta = (snap or {}).get("meta") or {}

    def series(name):
        return (metrics.get(name) or {}).get("series") or []

    if not any(series(n) for n in
               ("hvd_grad_norm", "hvd_nonfinite_total",
                "hvd_health_alert", "hvd_loss")):
        return None
    out = {"meta": {"rank": meta.get("rank", 0),
                    "size": meta.get("size", 1),
                    "generation": meta.get("generation", 0),
                    "host": meta.get("host", ""),
                    "reason": "metrics_snapshot"},
           "last_loss": None, "last_grad_norm": None,
           "culprits": [], "active_alerts": [], "alerts_total": 0,
           "nonfinite_elems": 0.0, "skipped_steps": 0,
           "update_ratio": {}}
    for s in series("hvd_loss"):
        out["last_loss"] = s.get("value")
    for s in series("hvd_grad_norm"):
        if (s.get("labels") or {}).get("group") == "all":
            out["last_grad_norm"] = s.get("value")
    for s in series("hvd_update_ratio"):
        out["update_ratio"][(s.get("labels") or {}).get("group", "?")] = \
            s.get("value")
    for s in series("hvd_nonfinite_total"):
        lab = s.get("labels") or {}
        cnt = float(s.get("value") or 0)
        out["nonfinite_elems"] += cnt
        try:
            rank = int(lab.get("rank", -1))
        except (TypeError, ValueError):  # merged pages relabel ranks
            rank = -1
        out["culprits"].append({"rank": rank,
                                "group": lab.get("group", "?"),
                                "count": cnt})
    for s in series("hvd_health_alert"):
        # every series counts toward the lifetime total: a cleared
        # alert's gauge persists at 0, so the reason set IS the
        # tripped-ever set (keeps live endpoints consistent with the
        # dump files' alerts_total after a trip-then-clear)
        out["alerts_total"] += 1
        if float(s.get("value") or 0) > 0:
            out["active_alerts"].append(
                (s.get("labels") or {}).get("reason", "?"))
    for s in series("hvd_health_skipped_steps_total"):
        out["skipped_steps"] += int(float(s.get("value") or 0))
    return out


def _snapshot_from_bench(obj: dict) -> dict | None:
    extra = (obj or {}).get("extra") or {}
    if "health_alerts" not in extra and "nonfinite_steps" not in extra:
        return None
    return {"meta": {"rank": 0, "size": 1, "generation": 0,
                     "reason": "bench_result"},
            "last_loss": None,
            "last_grad_norm": extra.get("grad_norm_final"),
            # bench records verdict EVENTS, not element counts — keep
            # the semantics distinct (format_report labels them apart)
            "nonfinite_events": extra.get("nonfinite_steps", 0),
            "culprits": [], "update_ratio": {},
            "active_alerts": extra.get("health_active_alerts") or [],
            "skipped_steps": extra.get("health_skipped_steps", 0),
            "alerts_total": extra.get("health_alerts", 0)}


def load_snapshots(path: str) -> list:
    """Per-rank health snapshots from: a directory of health-*.json
    dumps (deduped to each rank's newest generation), a single dump or
    bench-result JSON, or a live endpoint URL (``/metrics.json`` is
    fetched)."""
    if path.startswith(("http://", "https://")):
        from urllib.request import urlopen

        url = path.rstrip("/")
        if not url.endswith("/metrics.json"):
            url += "/metrics.json"
        with urlopen(url, timeout=10) as r:
            snap = json.loads(r.read().decode())
        out = from_metrics_snapshot(snap)
        return [out] if out else []
    if os.path.isdir(path):
        best: dict = {}
        for name in sorted(os.listdir(path)):
            if not (name.startswith("health-") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(path, name)) as f:
                    snap = json.load(f)
            except (OSError, ValueError):
                continue
            meta = snap.get("meta") or {}
            rank = int(meta.get("rank", 0))
            gen = int(meta.get("generation", 0))
            if rank not in best or gen >= best[rank][0]:
                best[rank] = (gen, snap)
        return [s for _, s in
                (best[r] for r in sorted(best))]
    with open(path) as f:
        obj = json.load(f)
    if "metric" in obj and "extra" in obj:  # bench result line
        snap = _snapshot_from_bench(obj)
        return [snap] if snap else []
    if "metrics" in obj and "meta" in obj:  # metrics snapshot
        snap = from_metrics_snapshot(obj)
        return [snap] if snap else []
    return [obj]


def load_report(path: str) -> dict:
    snaps = load_snapshots(path)
    culprits: dict = {}
    for s in snaps:
        for c in s.get("culprits") or []:
            key = (c.get("rank", -1), c.get("group", "?"))
            # MAX, not sum: every rank's monitor observed the SAME
            # allgathered verdict, so rank dumps carry identical
            # fleet-wide counts — summing them would multiply the
            # element count world-fold (the goodput double-counted-
            # wall bug class).
            culprits[key] = max(culprits.get(key, 0.0),
                                float(c.get("count", 0)))
    return {"ranks": snaps,
            "culprits": [{"rank": r, "group": g, "count": c}
                         for (r, g), c in sorted(culprits.items())],
            "alerts_total": max(
                (int(s.get("alerts_total", 0) or 0) for s in snaps),
                default=0)}


def format_report(report: dict) -> str:
    lines = ["=== training-health report ==="]
    ranks = report.get("ranks") or []
    if not ranks:
        return "=== training-health report ===\nno health data found"
    for s in ranks:
        meta = s.get("meta") or {}
        gn = s.get("last_grad_norm")
        loss = s.get("last_loss")
        alerts = s.get("active_alerts") or []
        gn_s = f"{gn:.4g}" if isinstance(gn, (int, float)) else "-"
        if "nonfinite_elems" in s:
            nf_s = f"nonfinite {float(s.get('nonfinite_elems') or 0):g}"
        else:  # bench artifacts record verdict events, not elements
            nf_s = (f"nonfinite_events "
                    f"{float(s.get('nonfinite_events', 0) or 0):g}")
        lines.append(
            f"  rank {meta.get('rank', '?')} g{meta.get('generation', 0)}"
            f": loss {loss if loss is not None else '-'}"
            f", grad_norm {gn_s}"
            f", {nf_s}"
            f", skipped {s.get('skipped_steps', 0)}"
            + (f", ALERTS: {','.join(alerts)}" if alerts else ""))
        ur = s.get("update_ratio") or {}
        for g, v in sorted(ur.items()):
            if isinstance(v, (int, float)):
                lines.append(f"      update_ratio[{g}] = {v:.3e}")
        fn = s.get("first_nonfinite")
        if fn:
            lines.append(
                f"      first nonfinite: rank {fn.get('rank')} "
                f"group {fn.get('group')} ({fn.get('count'):g} elems)")
    culprits = report.get("culprits") or []
    if culprits:
        lines.append("  culprit attribution (pre-reduction):")
        for c in culprits:
            lines.append(f"    rank {c['rank']} / {c['group']}: "
                         f"{c['count']:g} nonfinite element(s)")
    else:
        lines.append("  no nonfinite gradients observed")
    lines.append(f"  alerts (all ranks, lifetime): "
                 f"{report.get('alerts_total', 0)}")
    return "\n".join(lines)
