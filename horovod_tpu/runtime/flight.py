"""Distributed flight recorder: crash-surviving per-rank event rings.

The metrics plane (:mod:`horovod_tpu.runtime.metrics`) answers "how
much"; the Chrome timeline (:mod:`horovod_tpu.runtime.timeline`) shows
per-tensor lifecycles, but only on rank 0 and only while the process
lives.  Neither answers the postmortem question — *in what order, on
which rank* — when a round hangs, a re-form stalls, or a peer dies.

This module is the black box: every rank's runtime keeps a fixed-size
in-memory ring of structured events (negotiation rounds, coordinator
arrivals, wire messages, collective dispatches, heartbeats, clock
samples, stalls, elastic generation changes, eager handle waits),
each stamped with BOTH clocks — ``time.monotonic()`` for within-rank
precision and ``time.time()`` for cross-rank alignment.  The hot path
is one lock + one list-slot write: no syscalls, no IO, no allocation
growth (the ring is preallocated at ``HOROVOD_FLIGHT_EVENTS`` slots
and old events are overwritten in place) — enforced by
tests/test_flight.py the same way the metrics registry's cost bound
is.

On :class:`~horovod_tpu.common.types.RanksDownError`, coordinated
abort, a fatal signal (SIGTERM/SIGABRT — handlers installed at
``hvd.init()``), an elastic re-form, or an explicit
``hvd.dump_flight_recorder()``, the ring dumps atomically (tmp +
rename) as JSONL into ``HOROVOD_FLIGHT_DIR``; the launcher sweeps the
directory at wrap-up and on re-forms.  The offline tool
``python -m horovod_tpu.trace merge <dir>`` aligns rank clocks from
the heartbeat-piggybacked offset samples (``clk`` events), emits one
Perfetto/Chrome trace with a process per rank, and runs the
straggler / critical-path analyzer.  See docs/flight-recorder.md.

Import stays stdlib-only (no jax, no package siblings at import time):
the bench backend probe child records its ring before PJRT init, the
exact place a wedge makes everything else unobservable.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time

# Resolved lazily (module import must stay dependency-free); the knob
# names are owned by common/config.py.
_ENV_EVENTS = "HOROVOD_FLIGHT_EVENTS"
_ENV_DIR = "HOROVOD_FLIGHT_DIR"
_DEFAULT_EVENTS = 4096


class FlightRecorder:
    """Fixed-capacity event ring.

    ``record()`` is the hot path: stamp both clocks, take the lock,
    write one preallocated slot, bump the sequence counter.  Everything
    that costs (snapshotting, JSON, file IO) happens only in
    :meth:`dump` / :meth:`snapshot`, which copy under the lock and
    work outside it."""

    def __init__(self, capacity: int = _DEFAULT_EVENTS):
        self.capacity = max(0, int(capacity))
        # RLock, not Lock: the SIGTERM/SIGABRT dump handler runs on the
        # main thread between bytecodes — if the signal lands while the
        # main thread is inside record() (handle waits and trace_step
        # record from it), the handler's own record()/snapshot() would
        # self-deadlock on a non-reentrant lock and the dump would
        # never be written.
        self._lock = threading.RLock()
        self._slots: list = [None] * self.capacity
        self._seq = 0

    def record(self, kind: str, ph: str = "i", **fields) -> None:
        """Record one event.  ``ph`` follows Chrome-trace phases:
        ``"B"``/``"E"`` bracket a span on the same rank, ``"i"`` is an
        instant.  ``fields`` must be JSON-serializable scalars/lists."""
        if not self.capacity:
            return
        mono, wall = time.monotonic(), time.time()
        with self._lock:
            s = self._seq
            self._slots[s % self.capacity] = (s, mono, wall, kind, ph,
                                              fields or None)
            self._seq = s + 1

    def snapshot(self) -> list[dict]:
        """Ordered copy of the ring as dicts (oldest first)."""
        with self._lock:
            seq = self._seq
            slots = list(self._slots)
        if seq <= self.capacity:
            ordered = [s for s in slots[:seq] if s is not None]
        else:
            head = seq % self.capacity
            ordered = [s for s in slots[head:] + slots[:head]
                       if s is not None]
        out = []
        for s, mono, wall, kind, ph, fields in ordered:
            ev = {"seq": s, "mono": mono, "wall": wall, "kind": kind,
                  "ph": ph}
            if fields:
                ev.update(fields)
            out.append(ev)
        return out

    def recorded_total(self) -> int:
        """Events recorded over the ring's lifetime (>= len(snapshot))."""
        with self._lock:
            return self._seq

    def clear(self) -> None:
        """Drop every event (capacity unchanged).  Used after an
        elastic re-form dump: round numbers and rank identities restart
        with the new generation, so carrying the old generation's
        events into the next dump would duplicate them across trace
        processes and merge unrelated rounds in the straggler
        analyzer."""
        with self._lock:
            self._slots = [None] * self.capacity
            self._seq = 0

    def dump(self, path: str, meta: dict | None = None) -> str:
        """Atomically write the ring as JSONL: a ``{"meta": ...}``
        header line, then one event per line.  tmp + rename so a
        sweeper never reads a torn dump."""
        events = self.snapshot()
        header = {"meta": dict(meta or {})}
        header["meta"].setdefault("dump_wall", time.time())
        header["meta"].setdefault("dump_mono", time.monotonic())
        header["meta"]["events"] = len(events)
        header["meta"]["recorded_total"] = self.recorded_total()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(header) + "\n")
            for ev in events:
                f.write(json.dumps(ev) + "\n")
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------------
# Process-global recorder + dump surface
# ---------------------------------------------------------------------------

_recorder: FlightRecorder | None = None
# RLock for the same reason as the ring lock: the fatal-signal handler
# may create the recorder while the main thread is inside this very
# creation block.
_recorder_lock = threading.RLock()


def _capacity() -> int:
    raw = os.environ.get(_ENV_EVENTS, "")
    try:
        return int(raw) if raw else _DEFAULT_EVENTS
    except ValueError:
        return _DEFAULT_EVENTS


def recorder() -> FlightRecorder:
    """The process-global ring (created on first use at the
    ``HOROVOD_FLIGHT_EVENTS`` capacity in force then)."""
    global _recorder
    r = _recorder
    if r is None:
        with _recorder_lock:
            r = _recorder
            if r is None:
                r = _recorder = FlightRecorder(_capacity())
    return r


def record(kind: str, ph: str = "i", **fields) -> None:
    """Module-level hot-path record into the global ring."""
    recorder().record(kind, ph, **fields)


def reset() -> None:
    """Test hook: drop the global ring so the next record() rebuilds it
    at the current HOROVOD_FLIGHT_EVENTS capacity."""
    global _recorder
    with _recorder_lock:
        _recorder = None


def flight_dir() -> str:
    return os.environ.get(_ENV_DIR, "")


def _process_meta() -> dict:
    meta = {"pid": os.getpid()}
    try:
        import socket

        meta["host"] = socket.gethostname()
    except Exception:
        pass
    try:  # lazily: basics pulls numpy; the probe child has no world
        from horovod_tpu.common import basics as _basics

        st = _basics.state()
        if st.initialized or st.epoch:
            # epoch survives shutdown(): a rank dying AFTER teardown
            # still stamps the generation it lived in
            meta.update({"rank": st.rank, "size": st.size,
                         "generation": st.epoch,
                         "initialized": st.initialized})
    except Exception:
        pass
    for env_key, name in (("HOROVOD_RANK", "rank"),
                          ("HOROVOD_SIZE", "size")):
        if name not in meta and os.environ.get(env_key, "").isdigit():
            meta[name] = int(os.environ[env_key])
    meta.setdefault("rank", 0)
    meta.setdefault("size", 1)
    return meta


def dump(reason: str = "explicit", directory: str | None = None
         ) -> str | None:
    """Dump the global ring into ``HOROVOD_FLIGHT_DIR`` (or
    ``directory``).  Returns the dump path, or None when no directory
    is configured or the write failed — dumping is forensics and must
    never take a dying-but-recoverable process further down.

    Idempotent per (rank, generation): repeated dumps overwrite the
    same file, so abort + signal + teardown firing in sequence leave
    one coherent record whose reason is the LAST trigger."""
    d = directory or flight_dir()
    if not d:
        return None
    meta = _process_meta()
    meta["reason"] = reason
    # Ledger checkpoint event (docs/goodput.md): the wall-clock
    # attribution at dump time rides the postmortem record, so a
    # merged trace can say not just WHAT died but what the run's
    # seconds were spent on up to that point.  sys.modules lookup, not
    # an import: this can run inside the fatal-signal handler, where
    # entering the import machinery against a main thread that holds a
    # module lock would deadlock the dump (and an unimported goodput
    # module means no ledger exists to report anyway).  Skipped
    # entirely on the signal path: the ledger snapshot reads metrics
    # counters guarded by PLAIN locks — a signal landing while the
    # main thread holds one would deadlock the handler before the ring
    # dump lands (the ring itself is RLock'd for exactly this case).
    try:
        _goodput = (None if _in_signal_handler
                    else sys.modules.get("horovod_tpu.perf.goodput"))
        snap = (_goodput.ledger().snapshot()
                if _goodput is not None else {})
        if snap.get("elapsed_s"):
            record("goodput", reason=reason,
                   elapsed_s=round(snap["elapsed_s"], 3),
                   goodput_ratio=snap["goodput_ratio"],
                   unattributed_s=round(snap["unattributed_s"], 3),
                   **{f"{k}_s": round(v, 3)
                      for k, v in snap["phases"].items()})
    except Exception:
        pass
    # Health checkpoint event beside the goodput one (docs/health.md):
    # the postmortem record carries the model-health verdict at dump
    # time — nonfinite totals, active alerts — so the trace analyzer
    # can answer "did it die BECAUSE it diverged".  Same sys.modules +
    # signal-path rules as above (the monitor takes plain locks).
    try:
        _health = (None if _in_signal_handler
                   else sys.modules.get("horovod_tpu.runtime.health"))
        if _health is not None and _health._monitor is not None:
            hs = _health._monitor.snapshot()
            if hs.get("nonfinite_events") or hs.get("alerts_total") \
                    or hs.get("loss_observed"):
                record("health", event="checkpoint", reason=reason,
                       nonfinite_events=int(hs["nonfinite_events"]),
                       skipped_steps=int(hs["skipped_steps"]),
                       alerts_total=int(hs["alerts_total"]),
                       active_alerts=list(hs["active_alerts"]))
    except Exception:
        pass
    record("dump", reason=reason)
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"flight-r{meta['rank']}-g{meta.get('generation', 0)}"
               f"-p{meta['pid']}.jsonl")
        return recorder().dump(path, meta)
    except Exception:
        # Broad on purpose: record() asks for JSON scalars but nothing
        # enforces it, and a numpy int or set in a field would raise
        # TypeError out of json.dumps — which here would kill the
        # background thread before it fails outstanding handles (a
        # forever-hang), or crash the fatal-signal handler.
        return None


def _flush_metrics() -> None:
    """Best-effort final KV metrics snapshot (the metrics-plane
    terminal-flush companion of a dump): a process dying on an abort
    or a signal usually never reaches shutdown(), so the launcher
    aggregate would keep serving the last PERIODIC publish — missing
    the terminal counters (aborts, final staleness) that explain the
    death."""
    try:
        from horovod_tpu.common import basics as _basics

        pub = _basics.state().metrics_publisher
        if pub is not None:
            pub.publish()
    except Exception:
        pass


def dump_on_failure(reason: str, flush_metrics: bool = True) -> str | None:
    """The abnormal-exit dump path (coordinated abort, background
    failure, fatal signal): ring dump + terminal metrics flush.
    Callers that still hold threads blocked on pending handles pass
    ``flush_metrics=False`` and call :func:`flush_terminal_metrics`
    after releasing them — the KV publish retries with backoff against
    a possibly-dead store, and that wait must not delay handle
    failure."""
    path = dump(reason)
    # Goodput ledger dump beside the ring dump (docs/goodput.md): an
    # aborted/partial run must not lose its wall-clock accounting —
    # that is exactly when the attribution matters most.  sys.modules
    # lookup + signal-path skip for the same handler-safety reasons as
    # in dump() (coordinated aborts run on ordinary threads and keep
    # the ledger dump; a SIGTERM'd bench stamps its ledger from its
    # own SystemExit path instead).
    try:
        _goodput = (None if _in_signal_handler
                    else sys.modules.get("horovod_tpu.perf.goodput"))
        if _goodput is not None:
            _goodput.dump(reason)
    except Exception:
        pass
    # Health snapshot dump beside the ring + ledger dumps
    # (docs/health.md): a diverged or NaN-poisoned run's verdict must
    # survive the abort that it probably caused.
    try:
        _health = (None if _in_signal_handler
                   else sys.modules.get("horovod_tpu.runtime.health"))
        if _health is not None and _health._monitor is not None:
            _health.dump(reason)
    except Exception:
        pass
    if flush_metrics:
        _flush_metrics()
    return path


def flush_terminal_metrics() -> None:
    """Public alias for the terminal KV metrics flush (see
    :func:`dump_on_failure`)."""
    _flush_metrics()


# ---------------------------------------------------------------------------
# Fatal-signal handlers
# ---------------------------------------------------------------------------

_signals_installed = False
_prev_handlers: dict = {}
# True only while the fatal-signal handler runs: the goodput hooks in
# dump()/dump_on_failure() check it and stand down (their metric reads
# take plain locks the interrupted main thread may hold).
_in_signal_handler = False


def _on_fatal_signal(signum, frame):
    global _in_signal_handler
    del frame
    try:
        name = signal.Signals(signum).name
    except ValueError:
        name = str(signum)
    record("signal", sig=name)
    _in_signal_handler = True
    try:
        dump_on_failure(f"signal:{name}")
    finally:
        _in_signal_handler = False
    prev = _prev_handlers.get(signum)
    if callable(prev):
        prev(signum, None)
    elif prev == signal.SIG_IGN:
        return
    else:
        # Default disposition: re-deliver so the exit status still says
        # "killed by <sig>" (the launcher keys its blacklist on it).
        signal.signal(signum, signal.SIG_DFL)
        try:
            os.kill(os.getpid(), signum)
        except OSError:
            os._exit(128 + int(signum))


def install_signal_handlers() -> bool:
    """Install SIGTERM/SIGABRT dump handlers (idempotent; main thread
    only — ``signal.signal`` raises elsewhere, and a re-init from a
    worker thread must not kill the re-form).  SIGKILL is unhookable by
    design: a SIGKILLed rank's story is told by its PEERS' dumps, which
    is why every rank records, not just rank 0."""
    global _signals_installed
    if _signals_installed:
        return True
    try:
        for sig in (signal.SIGTERM, signal.SIGABRT):
            _prev_handlers[sig] = signal.getsignal(sig)
            signal.signal(sig, _on_fatal_signal)
    except (ValueError, OSError):  # not the main thread / exotic platform
        return False
    _signals_installed = True
    return True


# ---------------------------------------------------------------------------
# Launcher-side sweep
# ---------------------------------------------------------------------------


def sweep(directory: str) -> list[str]:
    """List the completed dumps under ``directory`` (sorted; tmp files
    from in-flight writers are skipped).  The launcher calls this at
    wrap-up and after observed re-forms to tell the operator what
    forensics exist and how to merge them."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted(
        os.path.join(directory, n) for n in names
        if n.startswith("flight-") and n.endswith(".jsonl"))
