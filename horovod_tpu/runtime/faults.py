"""Deterministic fault injection for the control-plane wire.

The reference has no equivalent — its fault-tolerance story (the
launcher killing the job when a rank dies, ``gloo_run.py:294-304``) is
only testable by killing real processes.  This module makes the failure
modes the fault-tolerant control plane must handle *injectable*: any
transport (JaxCoordTransport, KVStoreClient, or a test fake) can be
wrapped so that specific keys are delayed, specific writes are dropped,
or a specific rank crashes at a specific negotiation round — all
deterministic, so CI can assert exact behavior.

Spec grammar (``HOROVOD_FAULT_SPEC``, comma-separated)::

    delay:<keyglob>:<duration>     # sleep before matching ops
                                   #   delay:q/*:5s   delay:hb/*:250ms
    drop:<keyglob>[:<count>]       # swallow the first <count> (default
                                   # 1) matching WRITES (set/set_once):
                                   #   drop:p/3       drop:q/2/1:2
    die:rank<k>[:round<n>]         # rank k calls os._exit(137) at its
                                   # first transport op touching round
                                   # >= n (default 0 = first op):
                                   #   die:rank1:round4
    preempt:rank<k>[:round<n>][:grace<s>]
                                   # graceful advance notice instead of
                                   # die's hard exit: rank k receives a
                                   # preemption notice (runtime/
                                   # preemption.py) at its first
                                   # transport op touching round >= n
                                   # and DRAINS — emergency commit,
                                   # clean exit, proactive re-form —
                                   # inside the grace window (default
                                   # HOROVOD_PREEMPT_GRACE_SECONDS):
                                   #   preempt:rank1:round4:grace30s
    slow:<rank>:<delay>            # chronic straggler: rank k sleeps
                                   # <delay> before EVERY transport op
                                   # (key-independent, never expires) —
                                   # the signal the autopilot's
                                   # preemptive-blacklist rule keys on:
                                   #   slow:3:200ms   slow:rank3:200ms
    nan:<nameglob>[:round<n>]      # poison one element of matching
    inf:<nameglob>[:round<n>]      # float GRADIENT payloads to NaN/Inf
                                   # (docs/health.md culprit tests):
                                   #   nan@rank1:grad_buffer*:round2

``delay``, ``drop``, ``nan`` and ``inf`` accept an optional rank scope
— ``delay@rank<k>:...`` etc. — restricting the rule to one rank.  The
env spec is necessarily identical on every rank, so scoping is how a
test makes ONE rank slow/lossy/poisoned (a straggler, a NaN culprit)
while its peers stay healthy.

``nan``/``inf`` are DATA-plane rules: the glob matches payload names —
negotiated-wire buffer names (``grad_buffer.float32.6``,
``shard_rs.float32.128``) on the eager path, or the in-trace
pseudo-names ``grads.<dtype>`` the DistributedOptimizer's health tap
exposes.  With ``round<n>`` the rule fires ONCE at the first matching
dispatch of negotiation round >= n (deterministically testable culprit
attribution); without it, every matching payload is poisoned (in-trace
rules support only this round-less form — traced programs have no
negotiation round).

Key globs match against epoch-stripped keys (``q/<round>/<rank>``,
``p/<round>``, ``k/<round>``, ``hb/<rank>``, ``a``) via :mod:`fnmatch`,
so specs don't depend on the init generation.  Drops intercept only
mutations: a dropped write is the canonical lost-message fault (the
reader side then observes absence through its own deadline machinery).
"""

from __future__ import annotations

import fnmatch
import os
import re
import time
from dataclasses import dataclass, field

from horovod_tpu.common import config as _config
from horovod_tpu.common import logging as _log

_EPOCH_PREFIX = re.compile(r"^hvd\d+/")
_DURATION = re.compile(r"^(\d+(?:\.\d+)?)(ms|s)?$")


class FaultSpecError(ValueError):
    """Malformed ``HOROVOD_FAULT_SPEC`` entry."""


def parse_duration(text: str) -> float:
    """``5s`` / ``250ms`` / ``0.5`` (seconds) -> seconds."""
    m = _DURATION.match(text.strip())
    if not m:
        raise FaultSpecError(f"bad duration {text!r} (want e.g. 5s, 250ms)")
    value = float(m.group(1))
    return value / 1000.0 if m.group(2) == "ms" else value


#: Rule kinds that act on the data plane (gradient payloads), not the
#: control-plane transport — FaultyTransport ignores them.
DATA_KINDS = ("nan", "inf")


@dataclass
class Rule:
    kind: str                 # delay | drop | die | slow | nan | inf
    pattern: str = "*"
    delay_s: float = 0.0
    remaining: int | None = None   # None = unlimited (delay); drop: count
    rank: int = -1            # die / slow
    round: int = 0            # die / nan / inf round gate
    only_rank: int = -1       # delay/drop/nan/inf @rank scope; -1 = all
    fired: int = field(default=0)

    def take(self) -> bool:
        """Consume one application; False once the budget is spent."""
        if self.remaining is None:
            self.fired += 1
            return True
        if self.remaining <= 0:
            return False
        self.remaining -= 1
        self.fired += 1
        return True


def parse_spec(spec: str) -> list[Rule]:
    rules: list[Rule] = []
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        kind = parts[0].strip().lower()
        only_rank = -1
        if "@" in kind and kind.split("@", 1)[0] in \
                ("delay", "drop") + DATA_KINDS:
            kind, scope = kind.split("@", 1)
            if not scope.startswith("rank") \
                    or not scope[len("rank"):].isdigit():
                raise FaultSpecError(
                    f"bad rank scope in {raw!r} (want e.g. "
                    "delay@rank1:<glob>:<duration>)")
            only_rank = int(scope[len("rank"):])
        if kind == "delay":
            if len(parts) != 3:
                raise FaultSpecError(
                    f"delay spec {raw!r} wants delay:<glob>:<duration>")
            rules.append(Rule("delay", pattern=parts[1],
                              delay_s=parse_duration(parts[2]),
                              only_rank=only_rank))
        elif kind == "drop":
            if len(parts) not in (2, 3):
                raise FaultSpecError(
                    f"drop spec {raw!r} wants drop:<glob>[:<count>]")
            count = 1
            if len(parts) == 3:
                if not parts[2].isdigit() or int(parts[2]) < 1:
                    raise FaultSpecError(
                        f"drop count {parts[2]!r} must be a positive int")
                count = int(parts[2])
            rules.append(Rule("drop", pattern=parts[1], remaining=count,
                              only_rank=only_rank))
        elif kind == "die":
            if len(parts) not in (2, 3) or not parts[1].startswith("rank"):
                raise FaultSpecError(
                    f"die spec {raw!r} wants die:rank<k>[:round<n>]")
            rank_s = parts[1][len("rank"):]
            if not rank_s.isdigit():
                raise FaultSpecError(f"bad die rank in {raw!r}")
            round_n = 0
            if len(parts) == 3:
                if not parts[2].startswith("round") \
                        or not parts[2][len("round"):].isdigit():
                    raise FaultSpecError(f"bad die round in {raw!r}")
                round_n = int(parts[2][len("round"):])
            rules.append(Rule("die", rank=int(rank_s), round=round_n,
                              remaining=1))
        elif kind == "preempt":
            # Rule shape mirrors die: (same determinism contract), plus
            # an optional grace window carried in delay_s — the notice
            # is delivered instead of the process being killed.
            if len(parts) not in (2, 3, 4) \
                    or not parts[1].startswith("rank"):
                raise FaultSpecError(
                    f"preempt spec {raw!r} wants "
                    "preempt:rank<k>[:round<n>][:grace<s>]")
            rank_s = parts[1][len("rank"):]
            if not rank_s.isdigit():
                raise FaultSpecError(f"bad preempt rank in {raw!r}")
            round_n = 0
            grace_s = 0.0  # 0 = use HOROVOD_PREEMPT_GRACE_SECONDS
            for extra in parts[2:]:
                if extra.startswith("round") \
                        and extra[len("round"):].isdigit():
                    round_n = int(extra[len("round"):])
                elif extra.startswith("grace"):
                    grace_s = parse_duration(extra[len("grace"):])
                else:
                    raise FaultSpecError(
                        f"bad preempt modifier {extra!r} in {raw!r} "
                        "(want round<n> and/or grace<s>)")
            rules.append(Rule("preempt", rank=int(rank_s),
                              round=round_n, delay_s=grace_s,
                              remaining=1))
        elif kind == "slow":
            if len(parts) != 3:
                raise FaultSpecError(
                    f"slow spec {raw!r} wants slow:<rank>:<delay> "
                    "(e.g. slow:3:200ms)")
            rank_s = parts[1].strip()
            if rank_s.startswith("rank"):
                rank_s = rank_s[len("rank"):]
            if not rank_s.isdigit():
                raise FaultSpecError(f"bad slow rank in {raw!r}")
            rules.append(Rule("slow", rank=int(rank_s),
                              delay_s=parse_duration(parts[2])))
        elif kind in DATA_KINDS:
            if len(parts) not in (2, 3):
                raise FaultSpecError(
                    f"{kind} spec {raw!r} wants "
                    f"{kind}:<nameglob>[:round<n>]")
            round_n = 0
            remaining = None  # round-less: poison every matching payload
            if len(parts) == 3:
                if not parts[2].startswith("round") \
                        or not parts[2][len("round"):].isdigit():
                    raise FaultSpecError(f"bad {kind} round in {raw!r}")
                round_n = int(parts[2][len("round"):])
                remaining = 1  # round-scoped: fire once, deterministic
            rules.append(Rule(kind, pattern=parts[1], round=round_n,
                              remaining=remaining, only_rank=only_rank))
        else:
            raise FaultSpecError(
                f"unknown fault kind {kind!r} in {raw!r} "
                "(delay | drop | die | preempt | slow | nan | inf)")
    return rules


def strip_epoch(key: str) -> str:
    return _EPOCH_PREFIX.sub("", key)


def round_of(key: str) -> int | None:
    """Negotiation round a (stripped) controller key belongs to, or
    None for non-round keys (heartbeats, abort, run-func payloads).
    Covers both the flat keys (``q/<r>/<rank>``, ``p/<r>``,
    ``k/<r>``) and the hierarchical control plane's
    (``sq/<slice>/<r>/<rank>``, ``sp/<slice>/<r>``,
    ``sk/<slice>/<r>``, ``gq/<r>/<slice>``) so round-scoped rules
    (``die:rankK:roundN``) keep firing under either mode."""
    parts = key.split("/")
    if len(parts) >= 2 and parts[0] in ("q", "p", "k", "gq") \
            and parts[1].isdigit():
        return int(parts[1])
    if len(parts) >= 3 and parts[0] in ("sq", "sp", "sk") \
            and parts[2].isdigit():
        return int(parts[2])
    return None


class FaultyTransport:
    """Wraps any controller transport, applying the parsed rules.

    ``die`` rules fire on *any* transport op (read or write) of the
    matching rank once the op's key reaches the target round; ``delay``
    rules sleep on every matching op; ``slow`` rules sleep on EVERY op
    of the scoped rank (a chronic straggler); ``drop`` rules swallow
    matching writes while their budget lasts.  The wrapper is transparent
    otherwise — unknown attributes forward to the inner transport, so
    optional surfaces (``set_overwrite``, ``close``, ``ping``) survive
    wrapping.
    """

    def __init__(self, inner, rank: int, rules: list[Rule]):
        self.inner = inner
        self.rank = rank
        self.rules = rules

    # -- rule engine -------------------------------------------------------

    def _intercept(self, key: str, write: bool) -> bool:
        """Apply rules for one op; returns True when the op must be
        dropped."""
        stripped = strip_epoch(key)
        rnd = round_of(stripped)
        dropped = False
        for rule in self.rules:
            if rule.kind in DATA_KINDS:
                continue  # gradient poisoning never touches transport
            if rule.kind == "die":
                if rule.rank == self.rank and rule.remaining \
                        and (rule.round == 0
                             or (rnd is not None and rnd >= rule.round)):
                    _log.error(
                        f"[fault] die:rank{rule.rank}:round{rule.round} "
                        f"firing on key {stripped!r}", rank=self.rank)
                    os._exit(137)
                continue
            if rule.kind == "preempt":
                # die:'s graceful sibling — deliver the advance notice
                # (the rank publishes + drains at its next step
                # boundary) and let the op proceed.  take() so the
                # rule fires exactly once.
                if rule.rank == self.rank \
                        and (rule.round == 0
                             or (rnd is not None and rnd >= rule.round)) \
                        and rule.remaining and rule.take():
                    _log.warning(
                        f"[fault] preempt:rank{rule.rank}:"
                        f"round{rule.round} delivering notice on key "
                        f"{stripped!r}", rank=self.rank)
                    from horovod_tpu.runtime import preemption

                    preemption.notice(
                        source="fault",
                        grace_s=rule.delay_s or None)
                continue
            if rule.kind == "slow":
                # chronic straggler: key-independent, never expires —
                # every transport op of the scoped rank pays the tax
                if rule.rank == self.rank:
                    rule.fired += 1
                    time.sleep(rule.delay_s)
                continue
            if rule.only_rank >= 0 and rule.only_rank != self.rank:
                continue
            if not fnmatch.fnmatch(stripped, rule.pattern):
                continue
            if rule.kind == "delay":
                time.sleep(rule.delay_s)
            elif rule.kind == "drop" and write and rule.take():
                _log.warning(
                    f"[fault] dropping write of {stripped!r} "
                    f"({rule.remaining} drops left)", rank=self.rank)
                dropped = True
        return dropped

    # -- transport surface -------------------------------------------------

    def set(self, key: str, value: str) -> None:
        if self._intercept(key, write=True):
            return
        self.inner.set(key, value)

    def set_once(self, key: str, value: str) -> None:
        if self._intercept(key, write=True):
            return
        self.inner.set_once(key, value)

    def set_overwrite(self, key: str, value: str) -> None:
        if self._intercept(key, write=True):
            return
        fn = getattr(self.inner, "set_overwrite", None)
        if fn is not None:
            fn(key, value)
        else:
            self.inner.set(key, value)

    def get_blocking(self, key: str, timeout_s: float) -> str:
        self._intercept(key, write=False)
        return self.inner.get_blocking(key, timeout_s)

    def try_get(self, key: str):
        self._intercept(key, write=False)
        return self.inner.try_get(key)

    def delete(self, key: str) -> None:
        self._intercept(key, write=False)
        self.inner.delete(key)

    def __getattr__(self, name):
        return getattr(self.inner, name)


def maybe_wrap(transport, rank: int):
    """Wrap ``transport`` when ``HOROVOD_FAULT_SPEC`` is set (the single
    hook :func:`controller.make_controller` calls); identity otherwise."""
    spec = str(_config.get("fault_spec") or "").strip()
    if not spec:
        return transport
    rules = parse_spec(spec)
    _log.warning(
        f"HOROVOD_FAULT_SPEC active ({spec!r}): injecting "
        f"{len(rules)} fault rule(s) into the control-plane transport "
        "— testing mode, never production", rank=rank)
    return FaultyTransport(transport, rank, rules)


# ---------------------------------------------------------------------------
# Data-plane gradient poisoning (nan:/inf: — docs/health.md)
# ---------------------------------------------------------------------------

# Parsed nan/inf rules, cached per spec string: the background loop
# consults this on every dispatch and the common case (no spec) must be
# one string compare.  Rule state (remaining budgets) lives in the
# cached list, so round-scoped rules fire exactly once per process.
_data_cache: tuple[str, list[Rule]] = ("", [])


def data_rules() -> list[Rule]:
    """The active nan/inf poisoning rules ([] when no spec is set).

    A malformed spec RAISES (FaultSpecError) instead of degrading to
    no rules: in the single-process in-trace regime no FaultyTransport
    exists to surface the parse error, and a typo'd injection spec
    silently becoming a no-op would turn the very test that proves
    NaN detection into a vacuous pass."""
    global _data_cache
    spec = str(_config.get("fault_spec") or "").strip()
    cached_spec, cached = _data_cache
    if spec == cached_spec:
        return cached
    rules = [r for r in parse_spec(spec) if r.kind in DATA_KINDS] \
        if spec else []
    _data_cache = (spec, rules)
    return rules


def _poison_value(kind: str) -> float:
    return float("nan") if kind == "nan" else float("inf")


def poison_entries(entries: list, rank: int, rnd: int) -> list:
    """Eager-wire poisoning hook (background._execute): for each
    pending data-plane entry whose name matches an active nan/inf rule
    for this rank at this negotiation round, set element 0 of its float
    payload to NaN/Inf BEFORE dispatch — so the health tap inside the
    negotiated program observes the poison pre-reduction and the
    verdict names this rank (docs/health.md)."""
    rules = data_rules()
    if not rules:
        return entries
    import jax.numpy as jnp

    for i, entry in enumerate(entries):
        t = entry.tensor
        if t is None or not jnp.issubdtype(
                jnp.asarray(t).dtype, jnp.floating):
            continue
        for rule in rules:
            if rule.only_rank >= 0 and rule.only_rank != rank:
                continue
            if not fnmatch.fnmatch(entry.name, rule.pattern):
                continue
            if rule.round and rnd < rule.round:
                continue
            if not rule.take():
                continue
            flat = jnp.asarray(t).reshape(-1)
            if not flat.shape[0]:
                continue
            poisoned = flat.at[0].set(
                _poison_value(rule.kind)).reshape(jnp.asarray(t).shape)
            entry.tensor = poisoned
            _log.warning(
                f"[fault] {rule.kind}-poisoning payload "
                f"{entry.name!r} at round {rnd}", rank=rank)
            break
    return entries


def traced_poison(leaf, name: str, rank_index, only_round_less=True):
    """In-trace poisoning hook (the DistributedOptimizer health tap):
    returns ``leaf`` with element 0 set to NaN/Inf when a ROUND-LESS
    nan/inf rule matches ``name`` (``grads.<dtype>``) — applied as a
    traced ``where`` on ``rank_index`` so every rank still builds the
    identical SPMD program while only the scoped rank is poisoned.
    Round-scoped rules never apply here (no negotiation round exists
    inside a traced step)."""
    rules = [r for r in data_rules()
             if (not only_round_less or not r.round)
             and fnmatch.fnmatch(name, r.pattern)]
    if not rules:
        return leaf
    import jax.numpy as jnp

    flat = leaf.reshape(-1)
    if not flat.shape[0]:
        return leaf
    for rule in rules:
        val = jnp.asarray(_poison_value(rule.kind), flat.dtype)
        if rule.only_rank >= 0 and rank_index is not None:
            val = jnp.where(rank_index == rule.only_rank, val, flat[0])
        elif rule.only_rank >= 0:
            # rank scope but no axis index to target with — warn
            # loudly (once) instead of silently skipping, or the
            # injection test this rule exists for passes vacuously
            # (the data_rules raise-on-malformed contract's sibling).
            key = f"{rule.kind}@rank{rule.only_rank}:{rule.pattern}"
            if key not in _warned_untargetable:
                _warned_untargetable.add(key)
                _log.warning(
                    f"[fault] rank-scoped rule {key!r} matched "
                    f"{name!r} in a context with no bound mesh axis — "
                    "cannot target a rank, NOT poisoning (drop the "
                    "@rank scope for single-process in-trace runs)")
            continue
        flat = flat.at[0].set(val)
    return flat.reshape(leaf.shape)


_warned_untargetable: set = set()
