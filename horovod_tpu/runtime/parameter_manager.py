"""Runtime parameter manager (autotune).

Parity with reference ``horovod/common/parameter_manager.{h,cc}``
(251+528 LoC): when ``HOROVOD_AUTOTUNE`` is on, the coordinator scores
each sample window by negotiated bytes/sec, discards warmup windows,
and drives Bayesian optimization (GP + expected improvement,
``parameter_manager.h:186``) over the eager-path knobs, then pins the
best setting after ``HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES`` samples.
The winning parameters are broadcast to every rank by the coordinator
(reference ``SynchronizeParameters``, ``controller.cc:33-47``) — here
they ride the controller's response payload (``KVController.negotiate``)
so all ranks apply the same knobs at the same round boundary, which the
per-rank cache fast-path fusion requires.

Tuned space: fusion threshold, cycle time, response-cache on/off.  The
reference additionally tunes hierarchical allreduce/allgather; on TPU
the intra/inter-slice algorithm choice is XLA's (collectives lower onto
the static mesh-axis layout), so those two are user knobs, not runtime-
tunable dimensions.

Only rank 0 owns a ParameterManager; other ranks just apply received
updates via :func:`apply_params`.
"""

from __future__ import annotations

import time

import numpy as np

from horovod_tpu.common import config as _config
from horovod_tpu.common import logging as _log
from horovod_tpu.runtime.bayes_opt import BayesianOptimization

# Tuned dimensions, each mapped to the unit interval:
#   0: log2(fusion_threshold MB)   in [0, 7]   -> 1 MB .. 128 MB
#   1: cycle_time_ms               in [1, 25]
#   2: cache enabled               binary
_LOG2_MB_RANGE = (0.0, 7.0)
_CYCLE_RANGE = (1.0, 25.0)
_KNOB_NAMES = ("fusion_threshold", "cycle_time_ms", "cache_enabled")


def params_to_unit(threshold_bytes: int, cycle_ms: float,
                   cache: bool) -> np.ndarray:
    log2mb = np.log2(max(threshold_bytes, 1) / (1024.0 * 1024.0))
    u0 = (np.clip(log2mb, *_LOG2_MB_RANGE) - _LOG2_MB_RANGE[0]) / (
        _LOG2_MB_RANGE[1] - _LOG2_MB_RANGE[0])
    u1 = (np.clip(cycle_ms, *_CYCLE_RANGE) - _CYCLE_RANGE[0]) / (
        _CYCLE_RANGE[1] - _CYCLE_RANGE[0])
    return np.array([u0, u1, float(cache)])


def unit_to_params(u: np.ndarray) -> dict:
    """Unit coordinates -> physical knob values (binary rounded,
    threshold snapped to a whole power-of-two MB so fusion buckets stay
    stable between nearby samples)."""
    log2mb = round(_LOG2_MB_RANGE[0]
                   + float(u[0]) * (_LOG2_MB_RANGE[1] - _LOG2_MB_RANGE[0]))
    cycle = _CYCLE_RANGE[0] + float(u[1]) * (_CYCLE_RANGE[1] - _CYCLE_RANGE[0])
    return {
        "fusion_threshold": int(2 ** log2mb * 1024 * 1024),
        "cycle_time_ms": round(cycle, 2),
        "cache_enabled": bool(round(float(u[2]))),
    }


def canonical_unit(u: np.ndarray) -> np.ndarray:
    """Snap a proposed point to the coordinates of the config that will
    actually run, so the GP is trained on what was measured (a sample at
    u2=0.51 and one at u2=0.95 both ran with the cache on)."""
    p = unit_to_params(u)
    return params_to_unit(p["fusion_threshold"], p["cycle_time_ms"],
                          p["cache_enabled"])


def apply_params(params: dict) -> None:
    """Export received knob values to the process env (the single
    source of truth all config surfaces share, SURVEY §5.6).
    cache_enabled is applied by the controller, which owns the cache."""
    if "fusion_threshold" in params:
        _config.set_knob("fusion_threshold", params["fusion_threshold"])
    if "cycle_time_ms" in params:
        _config.set_knob("cycle_time_ms", params["cycle_time_ms"])


class ParameterManager:
    """Coordinator-side autotuner: feed per-cycle negotiated byte
    counts; every ``steps_per_sample`` cycles it closes a sample
    window, scores bytes/sec, and proposes the next knob setting."""

    def __init__(self, world: int = 1) -> None:
        self.enabled = bool(_config.get("autotune"))
        self.steps_per_sample = max(1, _config.get("autotune_steps_per_sample"))
        self.warmup = _config.get("autotune_warmup_samples")
        self.max_samples = _config.get("autotune_bayes_opt_max_samples")
        # cache_enabled only changes behavior when a multi-rank
        # negotiation cache exists; otherwise freeze the dim so the
        # bounded sample budget is spent on knobs that matter.
        cache_on = _config.get("cache_capacity") > 0
        self._tune_cache = cache_on and world > 1
        self._fixed_cache = None if self._tune_cache else cache_on
        self.bo = BayesianOptimization(
            dims=3 if self._tune_cache else 2,
            noise=_config.get("autotune_gaussian_process_noise"))
        self._cycles = 0
        self._bytes = 0
        self._window_start = time.monotonic()
        self._samples_seen = 0
        self._pinned = False
        full = params_to_unit(
            _config.get("fusion_threshold"), _config.get("cycle_time_ms"),
            cache_on)
        self._current = full if self._tune_cache else full[:2]
        self._log_path = _config.get("autotune_log")
        if self._log_path:
            with open(self._log_path, "w") as f:
                f.write("sample,score_bytes_per_sec," +
                        ",".join(_KNOB_NAMES) + ",pinned\n")

    # -- hot-loop interface ------------------------------------------------

    def record_bytes(self, nbytes: int) -> None:
        self._bytes += int(nbytes)

    def _full(self, u: np.ndarray) -> np.ndarray:
        """BO-space point -> full 3-dim unit coordinates."""
        if self._tune_cache:
            return u
        return np.append(u, float(self._fixed_cache))

    def tick(self) -> dict | None:
        """Called once per background cycle on rank 0.  Returns a knob
        dict to broadcast when the sample window closed with a new
        proposal, else None."""
        if not self.enabled or self._pinned:
            return None
        self._cycles += 1
        if self._cycles < self.steps_per_sample:
            return None
        now = time.monotonic()
        elapsed = max(now - self._window_start, 1e-6)
        score = self._bytes / elapsed
        self._cycles = 0
        self._bytes = 0
        self._window_start = now
        if score <= 0.0:
            return None  # idle window: nothing to learn from
        self._samples_seen += 1
        if self._samples_seen <= self.warmup:
            self._log(score, unit_to_params(self._full(self._current)),
                      pinned=False)
            return None
        self.bo.add_sample(self._current, score)
        if self._samples_seen - self.warmup >= self.max_samples:
            best_x, best_y = self.bo.best()
            self._pinned = True
            params = unit_to_params(self._full(best_x))
            self._log(best_y, params, pinned=True)
            _log.info(f"autotune converged: {params} "
                      f"(best {best_y / 1e6:.1f} MB/s)", rank=0)
        else:
            nxt = canonical_unit(self._full(self.bo.next_sample()))
            self._current = nxt if self._tune_cache else nxt[:2]
            params = unit_to_params(self._full(self._current))
            self._log(score, params, pinned=False)
        # NOT applied locally here: knobs take effect when the
        # coordinator's broadcast payload is received (all ranks,
        # rank 0 included, at the same round) — see BackgroundRuntime
        # for the world==1 direct-apply case.
        return params

    def _log(self, score: float, params: dict, pinned: bool) -> None:
        if not self._log_path:
            return
        with open(self._log_path, "a") as f:
            f.write(f"{self._samples_seen},{score:.1f}," +
                    ",".join(str(params[k]) for k in _KNOB_NAMES) +
                    f",{int(pinned)}\n")
