"""Runtime parameter manager (autotune).

Parity with reference ``horovod/common/parameter_manager.{h,cc}``
(251+528 LoC): when ``HOROVOD_AUTOTUNE`` is on, the coordinator scores
each sample window by negotiated bytes/sec, discards warmup windows,
and drives Bayesian optimization (GP + expected improvement,
``parameter_manager.h:186``) over the eager-path knobs, then pins the
best setting after ``HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES`` samples.
The winning parameters are broadcast to every rank by the coordinator
(reference ``SynchronizeParameters``, ``controller.cc:33-47``) — here
they ride the controller's response payload (``KVController.negotiate``)
so all ranks apply the same knobs at the same round boundary, which the
per-rank cache fast-path fusion requires.

Tuned space (reference ``parameter_manager.h:42-246``): fusion
threshold, cycle time, response-cache on/off, — when the rank
layout admits a 2-level (cross, local) decomposition — hierarchical
allreduce and hierarchical allgather on/off, and — when the overlap
engine (``HOROVOD_OVERLAP``) is active — the overlap chunk count
``HOROVOD_OVERLAP_CHUNKS`` (power-of-two snapped, 1..32; it trades
interleave granularity against per-collective latency and interacts
with the fusion threshold, which sets the bytes each bucket splits).
The hierarchical dims are
frozen out of the search when the topology can't use them
(single-host-style layouts), spending the bounded sample budget only on
knobs that can matter; the eager data plane re-reads the knobs per
bucket (``ops/xla_exec._hier_topology``) and caches one compiled
program per (knob, shape) point, so the tuner flipping them is cheap
after the first compile of each arm.

Only rank 0 owns a ParameterManager; other ranks just apply received
updates via :func:`apply_params`.
"""

from __future__ import annotations

import time

import numpy as np

from horovod_tpu.common import config as _config
from horovod_tpu.common import logging as _log
from horovod_tpu.runtime.bayes_opt import BayesianOptimization

# Full tuned space, each dim mapped to the unit interval:
#   0: log2(fusion_threshold MB)   in [0, 7]   -> 1 MB .. 128 MB
#   1: cycle_time_ms               in [1, 25]
#   2: cache enabled               binary
#   3: hierarchical allreduce      binary
#   4: hierarchical allgather      binary
#   5: log2(overlap_chunks)        in [0, 5]   -> 1 .. 32 buckets
#      (tuned only when HOROVOD_OVERLAP is on; interacts with dim 0 —
#      the eager bucket payload is ~fusion_threshold / chunks, so the
#      GP sees both coordinates of that trade-off)
#   6: log2(zero_prefetch_chunks)  in [0, 5]   -> 1 .. 32 buckets
#      (tuned only when HOROVOD_ZERO_STAGE >= 3: the stage-3 forward's
#      parameter-prefetch granularity — more buckets hide transfers
#      under finer layer slices but pay more per-collective latency)
_LOG2_MB_RANGE = (0.0, 7.0)
_CYCLE_RANGE = (1.0, 25.0)
_LOG2_CHUNKS_RANGE = (0.0, 5.0)
_KNOB_NAMES = ("fusion_threshold", "cycle_time_ms", "cache_enabled",
               "hierarchical_allreduce", "hierarchical_allgather",
               "overlap_chunks", "zero_prefetch_chunks")


def _unit_log2_chunks(chunks: int) -> float:
    log2k = np.log2(max(int(chunks), 1))
    return float(
        (np.clip(log2k, *_LOG2_CHUNKS_RANGE) - _LOG2_CHUNKS_RANGE[0])
        / (_LOG2_CHUNKS_RANGE[1] - _LOG2_CHUNKS_RANGE[0]))


def params_to_unit(threshold_bytes: int, cycle_ms: float, cache: bool,
                   hier_ar: bool = False,
                   hier_ag: bool = False,
                   overlap_chunks: int = 4,
                   zero_prefetch_chunks: int = 4) -> np.ndarray:
    log2mb = np.log2(max(threshold_bytes, 1) / (1024.0 * 1024.0))
    u0 = (np.clip(log2mb, *_LOG2_MB_RANGE) - _LOG2_MB_RANGE[0]) / (
        _LOG2_MB_RANGE[1] - _LOG2_MB_RANGE[0])
    u1 = (np.clip(cycle_ms, *_CYCLE_RANGE) - _CYCLE_RANGE[0]) / (
        _CYCLE_RANGE[1] - _CYCLE_RANGE[0])
    return np.array([u0, u1, float(cache), float(hier_ar),
                     float(hier_ag), _unit_log2_chunks(overlap_chunks),
                     _unit_log2_chunks(zero_prefetch_chunks)])


def unit_to_params(u: np.ndarray) -> dict:
    """Unit coordinates -> physical knob values (binaries rounded,
    threshold snapped to a whole power-of-two MB so fusion buckets stay
    stable between nearby samples; chunk count snapped to a power of
    two so bucket shapes — and the compiled overlap programs — stay
    stable the same way)."""
    log2mb = round(_LOG2_MB_RANGE[0]
                   + float(u[0]) * (_LOG2_MB_RANGE[1] - _LOG2_MB_RANGE[0]))
    cycle = _CYCLE_RANGE[0] + float(u[1]) * (_CYCLE_RANGE[1] - _CYCLE_RANGE[0])
    def _bit(i):  # tolerate legacy 3-dim points (hier dims default off)
        return bool(round(float(u[i]))) if len(u) > i else False

    def _log2k(i):  # tolerate legacy points missing trailing dims
        return round(_LOG2_CHUNKS_RANGE[0] + (float(u[i]) if len(u) > i
                                              else 0.4)
                     * (_LOG2_CHUNKS_RANGE[1] - _LOG2_CHUNKS_RANGE[0]))

    return {
        "fusion_threshold": int(2 ** log2mb * 1024 * 1024),
        "cycle_time_ms": round(cycle, 2),
        "cache_enabled": _bit(2),
        "hierarchical_allreduce": _bit(3),
        "hierarchical_allgather": _bit(4),
        "overlap_chunks": int(2 ** _log2k(5)),
        "zero_prefetch_chunks": int(2 ** _log2k(6)),
    }


def canonical_unit(u: np.ndarray) -> np.ndarray:
    """Snap a proposed point to the coordinates of the config that will
    actually run, so the GP is trained on what was measured (a sample at
    u2=0.51 and one at u2=0.95 both ran with the cache on)."""
    p = unit_to_params(u)
    return params_to_unit(*(p[k] for k in _KNOB_NAMES))


def apply_params(params: dict) -> None:
    """Export received knob values to the process env (the single
    source of truth all config surfaces share, SURVEY §5.6).
    cache_enabled is applied by the controller, which owns the cache;
    the hierarchical and overlap knobs are re-read by the data plane
    per bucket (``ops/xla_exec._hier_topology`` / ``overlap_cfg``, both
    part of the program cache keys)."""
    for k in ("fusion_threshold", "cycle_time_ms",
              "hierarchical_allreduce", "hierarchical_allgather",
              "overlap_chunks", "zero_prefetch_chunks"):
        if k in params:
            _config.set_knob(k, params[k])


class ParameterManager:
    """Coordinator-side autotuner: feed per-cycle negotiated byte
    counts; every ``steps_per_sample`` cycles it closes a sample
    window, scores bytes/sec, and proposes the next knob setting."""

    def __init__(self, world: int = 1,
                 hier_possible: bool | None = None) -> None:
        self.enabled = bool(_config.get("autotune"))
        self.steps_per_sample = max(1, _config.get("autotune_steps_per_sample"))
        self.warmup = _config.get("autotune_warmup_samples")
        self.max_samples = _config.get("autotune_bayes_opt_max_samples")
        # Dims that cannot change behavior are frozen out of the search
        # so the bounded sample budget is spent on knobs that matter:
        # the cache needs a multi-rank negotiation to skip, the
        # hierarchical decomposition needs a 2-level rank layout.
        cache_on = _config.get("cache_capacity") > 0
        if hier_possible is None:
            hier_possible = self._detect_hier_possible(world)
        tuned = [0, 1]
        if cache_on and world > 1:
            tuned.append(2)
        if hier_possible:
            tuned += [3, 4]
        # The chunk-count dim only matters when the overlap engine is
        # on and there is a wire to hide (world > 1); frozen otherwise
        # so the bounded sample budget is never spent splitting buffers
        # nobody transfers.
        if bool(_config.get("overlap")) and world > 1:
            tuned.append(5)
        # The stage-3 prefetch granularity only matters when parameters
        # actually live as shards and there is a wire to prefetch over.
        if int(_config.get("zero_stage")) >= 3 and world > 1:
            tuned.append(6)
        self._tuned = tuned
        self._fixed_full = params_to_unit(
            _config.get("fusion_threshold"), _config.get("cycle_time_ms"),
            cache_on, bool(_config.get("hierarchical_allreduce")),
            bool(_config.get("hierarchical_allgather")),
            int(_config.get("overlap_chunks")),
            int(_config.get("zero_prefetch_chunks")))
        self.bo = BayesianOptimization(
            dims=len(tuned),
            noise=_config.get("autotune_gaussian_process_noise"))
        self._cycles = 0
        self._bytes = 0
        self._window_start = time.monotonic()
        self._samples_seen = 0
        self._pinned = False
        self._current = self._fixed_full[self._tuned]
        self._log_path = _config.get("autotune_log")
        if self._log_path:
            with open(self._log_path, "w") as f:
                f.write("sample,score_bytes_per_sec," +
                        ",".join(_KNOB_NAMES) + ",pinned\n")

    @staticmethod
    def _detect_hier_possible(world: int) -> bool:
        """The data plane's own admissibility gate
        (``ops/xla_exec._hier_admissibility`` — one implementation,
        both consumers), so the tuner never spends samples on a
        dimension the collectives would ignore."""
        if world <= 1:
            return False
        from horovod_tpu.ops.xla_exec import hier_possible

        return hier_possible()

    # -- hot-loop interface ------------------------------------------------

    def record_bytes(self, nbytes: int) -> None:
        self._bytes += int(nbytes)

    def _full(self, u: np.ndarray) -> np.ndarray:
        """BO-space point -> full unit coordinates (frozen dims filled
        from the job's configured values)."""
        full = self._fixed_full.copy()
        full[self._tuned] = u
        return full

    def tick(self) -> dict | None:
        """Called once per background cycle on rank 0.  Returns a knob
        dict to broadcast when the sample window closed with a new
        proposal, else None."""
        if not self.enabled or self._pinned:
            return None
        self._cycles += 1
        if self._cycles < self.steps_per_sample:
            return None
        now = time.monotonic()
        elapsed = max(now - self._window_start, 1e-6)
        score = self._bytes / elapsed
        self._cycles = 0
        self._bytes = 0
        self._window_start = now
        if score <= 0.0:
            return None  # idle window: nothing to learn from
        self._samples_seen += 1
        if self._samples_seen <= self.warmup:
            self._log(score, unit_to_params(self._full(self._current)),
                      pinned=False)
            return None
        self.bo.add_sample(self._current, score)
        if self._samples_seen - self.warmup >= self.max_samples:
            best_x, best_y = self.bo.best()
            self._pinned = True
            params = unit_to_params(self._full(best_x))
            self._log(best_y, params, pinned=True)
            _log.info(f"autotune converged: {params} "
                      f"(best {best_y / 1e6:.1f} MB/s)", rank=0)
        else:
            nxt = canonical_unit(self._full(self.bo.next_sample()))
            self._current = nxt[self._tuned]
            params = unit_to_params(self._full(self._current))
            self._log(score, params, pinned=False)
        # NOT applied locally here: knobs take effect when the
        # coordinator's broadcast payload is received (all ranks,
        # rank 0 included, at the same round) — see BackgroundRuntime
        # for the world==1 direct-apply case.
        return params

    def _log(self, score: float, params: dict, pinned: bool) -> None:
        if not self._log_path:
            return
        with open(self._log_path, "a") as f:
            f.write(f"{self._samples_seen},{score:.1f}," +
                    ",".join(str(params[k]) for k in _KNOB_NAMES) +
                    f",{int(pinned)}\n")
