"""Runtime parameter manager (autotune).

Parity with reference ``horovod/common/parameter_manager.{h,cc}``
(251+528 LoC): when ``HOROVOD_AUTOTUNE`` is on, the coordinator scores
each sample window by negotiated bytes/sec, discards warmup windows,
and drives Bayesian optimization (GP + expected improvement,
``parameter_manager.h:186``) over the eager-path knobs, then pins the
best setting after ``HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES`` samples.
The winning parameters are broadcast to every rank by the coordinator
(reference ``SynchronizeParameters``, ``controller.cc:33-47``) — here
they ride the controller's response payload (``KVController.negotiate``)
so all ranks apply the same knobs at the same round boundary, which the
per-rank cache fast-path fusion requires.

Tuned space (reference ``parameter_manager.h:42-246``): fusion
threshold, cycle time, response-cache on/off, — when the rank
layout admits a 2-level (cross, local) decomposition — hierarchical
allreduce and hierarchical allgather on/off, and — when the overlap
engine (``HOROVOD_OVERLAP``) is active — the overlap chunk count
``HOROVOD_OVERLAP_CHUNKS`` (power-of-two snapped, 1..32; it trades
interleave granularity against per-collective latency and interacts
with the fusion threshold, which sets the bytes each bucket splits).
The hierarchical dims are
frozen out of the search when the topology can't use them
(single-host-style layouts), spending the bounded sample budget only on
knobs that can matter; the eager data plane re-reads the knobs per
bucket (``ops/xla_exec._hier_topology``) and caches one compiled
program per (knob, shape) point, so the tuner flipping them is cheap
after the first compile of each arm.

Only rank 0 owns a ParameterManager; other ranks just apply received
updates via :func:`apply_params`.
"""

from __future__ import annotations

import time

import numpy as np

from horovod_tpu.common import config as _config
from horovod_tpu.common import logging as _log
from horovod_tpu.runtime.bayes_opt import BayesianOptimization

# Full tuned space, each dim mapped to the unit interval:
#   0: log2(fusion_threshold MB)   in [0, 7]   -> 1 MB .. 128 MB
#   1: cycle_time_ms               in [1, 25]
#   2: cache enabled               binary
#   3: hierarchical allreduce      binary
#   4: hierarchical allgather      binary
#   5: log2(overlap_chunks)        in [0, 5]   -> 1 .. 32 buckets
#      (tuned only when HOROVOD_OVERLAP is on; interacts with dim 0 —
#      the eager bucket payload is ~fusion_threshold / chunks, so the
#      GP sees both coordinates of that trade-off)
#   6: log2(zero_prefetch_chunks)  in [0, 5]   -> 1 .. 32 buckets
#      (tuned only when HOROVOD_ZERO_STAGE >= 3: the stage-3 forward's
#      parameter-prefetch granularity — more buckets hide transfers
#      under finer layer slices but pay more per-collective latency)
#   7+: per-bucket compression-mode slots (HOROVOD_ADAPTIVE_COMPRESSION;
#      one slot per overlap bucket, capped at _MAX_MODE_SLOTS; slot s
#      governs buckets b with b % slots == s, matching the cycling of
#      HOROVOD_BUCKET_COMPRESSION) — each dim walks the aggressiveness
#      ladder none->bf16->fp16->int8->int4->topk (docs/compression.md),
#      subject to the bounded-loss guardrail below.
_LOG2_MB_RANGE = (0.0, 7.0)
_CYCLE_RANGE = (1.0, 25.0)
_LOG2_CHUNKS_RANGE = (0.0, 5.0)
_KNOB_NAMES = ("fusion_threshold", "cycle_time_ms", "cache_enabled",
               "hierarchical_allreduce", "hierarchical_allgather",
               "overlap_chunks", "zero_prefetch_chunks")
_N_BASE_DIMS = len(_KNOB_NAMES)
_MAX_MODE_SLOTS = 8

# Aggressiveness ladder for the mode dims (index 3 = int8 is the
# guardrail's pin-back target).
from horovod_tpu.ops.compression import MODE_LADDER as _MODE_LADDER  # noqa: E402

_INT8_IDX = _MODE_LADDER.index("int8")


def _mode_to_unit(mode: str) -> float:
    try:
        idx = _MODE_LADDER.index(str(mode).lower())
    except ValueError:
        idx = 0
    return idx / (len(_MODE_LADDER) - 1)


def _unit_to_mode(u: float) -> str:
    idx = int(round(float(np.clip(u, 0.0, 1.0))
                    * (len(_MODE_LADDER) - 1)))
    return _MODE_LADDER[idx]


def _unit_log2_chunks(chunks: int) -> float:
    log2k = np.log2(max(int(chunks), 1))
    return float(
        (np.clip(log2k, *_LOG2_CHUNKS_RANGE) - _LOG2_CHUNKS_RANGE[0])
        / (_LOG2_CHUNKS_RANGE[1] - _LOG2_CHUNKS_RANGE[0]))


def params_to_unit(threshold_bytes: int, cycle_ms: float, cache: bool,
                   hier_ar: bool = False,
                   hier_ag: bool = False,
                   overlap_chunks: int = 4,
                   zero_prefetch_chunks: int = 4,
                   bucket_modes=()) -> np.ndarray:
    log2mb = np.log2(max(threshold_bytes, 1) / (1024.0 * 1024.0))
    u0 = (np.clip(log2mb, *_LOG2_MB_RANGE) - _LOG2_MB_RANGE[0]) / (
        _LOG2_MB_RANGE[1] - _LOG2_MB_RANGE[0])
    u1 = (np.clip(cycle_ms, *_CYCLE_RANGE) - _CYCLE_RANGE[0]) / (
        _CYCLE_RANGE[1] - _CYCLE_RANGE[0])
    return np.array([u0, u1, float(cache), float(hier_ar),
                     float(hier_ag), _unit_log2_chunks(overlap_chunks),
                     _unit_log2_chunks(zero_prefetch_chunks)] +
                    [_mode_to_unit(m) for m in bucket_modes])


def unit_to_params(u: np.ndarray) -> dict:
    """Unit coordinates -> physical knob values (binaries rounded,
    threshold snapped to a whole power-of-two MB so fusion buckets stay
    stable between nearby samples; chunk count snapped to a power of
    two so bucket shapes — and the compiled overlap programs — stay
    stable the same way)."""
    log2mb = round(_LOG2_MB_RANGE[0]
                   + float(u[0]) * (_LOG2_MB_RANGE[1] - _LOG2_MB_RANGE[0]))
    cycle = _CYCLE_RANGE[0] + float(u[1]) * (_CYCLE_RANGE[1] - _CYCLE_RANGE[0])
    def _bit(i):  # tolerate legacy 3-dim points (hier dims default off)
        return bool(round(float(u[i]))) if len(u) > i else False

    def _log2k(i):  # tolerate legacy points missing trailing dims
        return round(_LOG2_CHUNKS_RANGE[0] + (float(u[i]) if len(u) > i
                                              else 0.4)
                     * (_LOG2_CHUNKS_RANGE[1] - _LOG2_CHUNKS_RANGE[0]))

    params = {
        "fusion_threshold": int(2 ** log2mb * 1024 * 1024),
        "cycle_time_ms": round(cycle, 2),
        "cache_enabled": _bit(2),
        "hierarchical_allreduce": _bit(3),
        "hierarchical_allgather": _bit(4),
        "overlap_chunks": int(2 ** _log2k(5)),
        "zero_prefetch_chunks": int(2 ** _log2k(6)),
    }
    if len(u) > _N_BASE_DIMS:
        params["bucket_compression"] = ":".join(
            _unit_to_mode(u[i]) for i in range(_N_BASE_DIMS, len(u)))
    return params


def canonical_unit(u: np.ndarray) -> np.ndarray:
    """Snap a proposed point to the coordinates of the config that will
    actually run, so the GP is trained on what was measured (a sample at
    u2=0.51 and one at u2=0.95 both ran with the cache on)."""
    p = unit_to_params(u)
    modes = [m for m in p.get("bucket_compression", "").split(":") if m]
    return params_to_unit(*(p[k] for k in _KNOB_NAMES),
                          bucket_modes=modes)


def apply_params(params: dict) -> None:
    """Export received knob values to the process env (the single
    source of truth all config surfaces share, SURVEY §5.6).
    cache_enabled is applied by the controller, which owns the cache;
    the hierarchical and overlap knobs are re-read by the data plane
    per bucket (``ops/xla_exec._hier_topology`` / ``overlap_cfg``, both
    part of the program cache keys)."""
    for k in ("fusion_threshold", "cycle_time_ms",
              "hierarchical_allreduce", "hierarchical_allgather",
              "overlap_chunks", "zero_prefetch_chunks",
              # Outer-sync period of the local-SGD regime
              # (docs/local-sgd.md): the autopilot's comm_retune may
              # double it at a commit boundary — H is in every scoped
              # program's cache key (ops/xla_exec.local_sgd_cfg) and
              # rides the round-0 handshake, so all ranks re-trace in
              # lockstep exactly like an overlap retune.
              "local_sgd_h",
              # The per-bucket mode vector (adaptive compression,
              # docs/compression.md): the data plane re-reads it per
              # dispatch and the vector is part of the program cache
              # keys, so a retune re-traces in lockstep on every rank
              # (all ranks apply at the same round boundary).
              "bucket_compression"):
        if k in params:
            _config.set_knob(k, params[k])


def _default_comm_signal():
    """Measured comm-exposed seconds per step for the adaptive
    compression objective, or ``None`` when no signal exists yet: the
    device-truth ``hvd_device_comm_exposed_seconds`` gauge when a
    sampled capture (``HOROVOD_PROFILE_EVERY_N_STEPS``, docs/perf.md)
    has published one, else the step-span subtraction fallback (the
    ``blocked`` phase of the last ``hvd.trace_step`` span — seconds the
    schedule failed to hide, docs/metrics.md)."""
    from horovod_tpu.runtime import metrics as _metrics

    try:
        snap = _metrics.registry().snapshot()
    except Exception:
        return None
    dev = snap.get("hvd_device_comm_exposed_seconds",
                   {}).get("series", [])
    if dev:
        return max(0.0, float(dev[0]["value"]))
    for e in snap.get("hvd_step_phase_seconds_last",
                      {}).get("series", []):
        if e.get("labels", {}).get("phase") == "blocked":
            return max(0.0, float(e["value"]))
    return None


class ParameterManager:
    """Coordinator-side autotuner: feed per-cycle negotiated byte
    counts; every ``steps_per_sample`` cycles it closes a sample
    window, scores the objective (see :meth:`_window_score`), and
    proposes the next knob setting — including, under
    ``HOROVOD_ADAPTIVE_COMPRESSION``, the per-bucket wire-compression
    mode vector (``HOROVOD_BUCKET_COMPRESSION``) subject to the
    bounded-loss guardrail (:meth:`_guard`)."""

    def __init__(self, world: int = 1,
                 hier_possible: bool | None = None,
                 comm_signal=None) -> None:
        self.enabled = bool(_config.get("autotune"))
        self.steps_per_sample = max(1, _config.get("autotune_steps_per_sample"))
        self.warmup = _config.get("autotune_warmup_samples")
        self.max_samples = _config.get("autotune_bayes_opt_max_samples")
        self._comm_signal = (comm_signal if comm_signal is not None
                             else _default_comm_signal)
        self._guard_ceiling = float(
            _config.get("compression_guard_ratio"))
        self._world = max(1, int(world))
        # Dims that cannot change behavior are frozen out of the search
        # so the bounded sample budget is spent on knobs that matter:
        # the cache needs a multi-rank negotiation to skip, the
        # hierarchical decomposition needs a 2-level rank layout.
        cache_on = _config.get("cache_capacity") > 0
        if hier_possible is None:
            hier_possible = self._detect_hier_possible(world)
        tuned = [0, 1]
        if cache_on and world > 1:
            tuned.append(2)
        if hier_possible:
            tuned += [3, 4]
        # The chunk-count dim only matters when the overlap engine is
        # on and there is a wire to hide (world > 1); frozen otherwise
        # so the bounded sample budget is never spent splitting buffers
        # nobody transfers.
        if bool(_config.get("overlap")) and world > 1:
            tuned.append(5)
        # The stage-3 prefetch granularity only matters when parameters
        # actually live as shards and there is a wire to prefetch over.
        if int(_config.get("zero_stage")) >= 3 and world > 1:
            tuned.append(6)
        # Adaptive compression (docs/compression.md): one mode dim per
        # overlap bucket slot (capped — slot s governs buckets b with
        # b % slots == s, the HOROVOD_BUCKET_COMPRESSION cycling), one
        # uniform slot without the overlap engine.  Frozen when the
        # knob is off or there is no wire to compress.
        self._mode_slots = 0
        if bool(_config.get("adaptive_compression")) and world > 1:
            self._mode_slots = (
                min(_MAX_MODE_SLOTS,
                    max(1, int(_config.get("overlap_chunks"))))
                if bool(_config.get("overlap")) else 1)
            tuned += list(range(_N_BASE_DIMS,
                                _N_BASE_DIMS + self._mode_slots))
        self._tuned = tuned
        init_modes = [m for m in str(
            _config.get("bucket_compression")).lower().split(":") if m]
        if not init_modes:
            base_mode = str(_config.get("compression")).lower() or "none"
            init_modes = [base_mode if base_mode in _MODE_LADDER
                          else "none"]
        self._fixed_full = params_to_unit(
            _config.get("fusion_threshold"), _config.get("cycle_time_ms"),
            cache_on, bool(_config.get("hierarchical_allreduce")),
            bool(_config.get("hierarchical_allgather")),
            int(_config.get("overlap_chunks")),
            int(_config.get("zero_prefetch_chunks")),
            bucket_modes=[init_modes[s % len(init_modes)]
                          for s in range(self._mode_slots)])
        self.bo = BayesianOptimization(
            dims=len(tuned),
            noise=_config.get("autotune_gaussian_process_noise"))
        self._cycles = 0
        self._bytes = 0
        self._logical_bytes = 0
        self._objective = None  # decided at the first scored window
        self._window_start = time.monotonic()
        self._samples_seen = 0
        self._pinned = False
        self._current = self._fixed_full[self._tuned]
        self._log_path = _config.get("autotune_log")
        if self._log_path:
            with open(self._log_path, "w") as f:
                f.write("sample,score,objective," +
                        ",".join(_KNOB_NAMES) +
                        ",bucket_compression,pinned\n")

    @staticmethod
    def _detect_hier_possible(world: int) -> bool:
        """The data plane's own admissibility gate
        (``ops/xla_exec._hier_admissibility`` — one implementation,
        both consumers), so the tuner never spends samples on a
        dimension the collectives would ignore."""
        if world <= 1:
            return False
        from horovod_tpu.ops.xla_exec import hier_possible

        return hier_possible()

    # -- hot-loop interface ------------------------------------------------

    def record_bytes(self, nbytes: int, logical_nbytes: int | None = None
                     ) -> None:
        self._bytes += int(nbytes)
        self._logical_bytes += int(nbytes if logical_nbytes is None
                                   else logical_nbytes)

    def _full(self, u: np.ndarray) -> np.ndarray:
        """BO-space point -> full unit coordinates (frozen dims filled
        from the job's configured values)."""
        full = self._fixed_full.copy()
        full[self._tuned] = u
        return full

    def _window_score(self, elapsed: float):
        """(score, objective) for the closing window.  With the mode
        dims in the search, bytes/sec is the WRONG objective —
        compression cuts counted wire bytes, so the GP would flee the
        very modes that help — hence the hierarchy (docs/autotune.md):

        * ``comm_exposed`` — 1 / measured comm-exposed seconds per step
          (device truth from a live PR 9 capture, the step-span
          subtraction fallback otherwise), when the signal exists;
        * ``logical_bytes`` — application payload bytes/sec (invariant
          to the wire encoding) when the mode dims are tuned but no
          exposed-comm signal is available;
        * ``wire_bytes`` — the classic bytes/sec, mode dims frozen.

        The objective is chosen once at the first scored window and
        kept, so the GP never regresses on mixed units."""
        if self._objective is None:
            if self._mode_slots and self._comm_signal() is not None:
                self._objective = "comm_exposed"
            elif self._mode_slots:
                self._objective = "logical_bytes"
            else:
                self._objective = "wire_bytes"
        if self._objective == "comm_exposed":
            comm = self._comm_signal()
            if comm is not None and comm >= 0:
                # eps floors the perfectly-hidden case (comm == 0)
                # instead of skipping its window.
                return 1.0 / (comm + 1e-4), self._objective
            return 0.0, self._objective  # signal gap: skip the window
        if self._objective == "logical_bytes":
            return self._logical_bytes / elapsed, self._objective
        return self._bytes / elapsed, self._objective

    def _guard(self, params: dict) -> dict:
        """Bounded-loss guardrail: a mode slot whose reported
        error-feedback residual-to-gradient norm ratio
        (``hvd_compression_residual_ratio``, published by the
        optimizer's EF paths) exceeds the
        ``HOROVOD_COMPRESSION_MAX_RESIDUAL_RATIO`` ceiling is pinned
        back from int4/topk to int8 (ceiling 0 disables the aggressive
        modes for every reported slot) before the proposal is
        broadcast.  The GP is then trained on the guarded point — the
        config that actually ran."""
        spec = params.get("bucket_compression", "")
        if not spec or not self._mode_slots:
            return params
        modes = spec.split(":")
        # PRIMARY signal: the real loss trajectory from the health
        # plane (docs/health.md).  When the job feeds its loss to
        # hvd.health.observe_loss(), the guardrail trusts the actual
        # convergence signal — a diverged/nonfinite trajectory pins
        # EVERY aggressive slot back to int8, a healthy one lets the
        # tuner explore — and the residual-ratio proxy is demoted to
        # the fallback for jobs that never report a loss.
        loss_verdict = None
        try:
            from horovod_tpu.runtime import health as _health

            loss_verdict = _health.loss_guard()
        except Exception:
            loss_verdict = None
        if loss_verdict is not None and loss_verdict.get("diverged"):
            ratios = {s: float("inf") for s in range(len(modes))}
        elif loss_verdict is not None and self._guard_ceiling > 0:
            ratios = {}  # residual proxy demoted: loss is in charge
        else:
            # No loss trajectory (the fallback), OR the explicit
            # ceiling-0 kill switch: the operator's "disable aggressive
            # modes for reported slots" contract outranks even a
            # healthy loss verdict.
            ratios = self._slot_residual_ratios(len(modes))
        # Topology clamp first: the block-scaled modes refuse axes with
        # no sum-safe headroom (7 // n for int4, 127 // n for int8 —
        # ops/quantization raises loudly), which is right for a
        # hand-set knob but must never let the tuner abort the very job
        # it is tuning mid-run.  The quantized axis is the world for a
        # flat proposal, the (smaller) cross axis when the same
        # proposal turns the hierarchical split on.  The GP then
        # trains on the clamped point.
        n_axis = (self._quantized_axis_size()
                  if params.get("hierarchical_allreduce")
                  else self._world)
        guarded = []
        for s, m in enumerate(modes):
            if m == "int4" and 7 // n_axis < 1:
                m = "int8"
            if m == "int8" and 127 // n_axis < 1:
                m = "fp16"
            r = ratios.get(s)
            if (r is not None and r > self._guard_ceiling
                    and _MODE_LADDER.index(m) > _INT8_IDX):
                m = "int8"
            guarded.append(m)
        params["bucket_compression"] = ":".join(guarded)
        return params

    def _quantized_axis_size(self) -> int:
        """Size of the axis a hierarchical proposal quantizes (the
        cross axis), falling back to the world when the two-level
        layout is unknown — the conservative answer for the clamp."""
        try:
            from horovod_tpu.ops.xla_exec import _hier_admissibility

            local, _ = _hier_admissibility()
            if local and self._world % int(local) == 0:
                return max(1, self._world // int(local))
        except Exception:
            pass
        return self._world

    @staticmethod
    def _slot_residual_ratios(slots: int) -> dict:
        """slot -> worst reported residual ratio (gauge series carry
        raw data-plane bucket indices; slot s owns b % slots == s)."""
        from horovod_tpu.runtime import metrics as _metrics

        out: dict = {}
        try:
            series = _metrics.registry().snapshot().get(
                "hvd_compression_residual_ratio", {}).get("series", [])
        except Exception:
            return out
        for entry in series:
            try:
                b = int(entry["labels"].get("bucket", 0))
            except (TypeError, ValueError):
                continue
            s = b % max(1, int(slots))
            v = float(entry["value"])
            if s not in out or v > out[s]:
                out[s] = v
        return out

    def tick(self) -> dict | None:
        """Called once per background cycle on rank 0.  Returns a knob
        dict to broadcast when the sample window closed with a new
        proposal, else None."""
        if not self.enabled or self._pinned:
            return None
        self._cycles += 1
        if self._cycles < self.steps_per_sample:
            return None
        now = time.monotonic()
        elapsed = max(now - self._window_start, 1e-6)
        busy = self._bytes > 0
        score, objective = self._window_score(elapsed)
        self._cycles = 0
        self._bytes = 0
        self._logical_bytes = 0
        self._window_start = now
        if score <= 0.0 or not busy:
            return None  # idle window (or signal gap): nothing to learn
        self._samples_seen += 1
        if self._samples_seen <= self.warmup:
            self._log(score, unit_to_params(self._full(self._current)),
                      pinned=False)
            return None
        self.bo.add_sample(self._current, score)
        if self._samples_seen - self.warmup >= self.max_samples:
            best_x, best_y = self.bo.best()
            self._pinned = True
            params = self._guard(unit_to_params(self._full(best_x)))
            self._log(best_y, params, pinned=True)
            _log.info(f"autotune converged: {params} "
                      f"(best {best_y:.4g} {objective}/s-score)", rank=0)
        else:
            nxt = canonical_unit(self._full(self.bo.next_sample()))
            params = self._guard(unit_to_params(nxt))
            # Train the GP on the guarded point — what actually runs.
            self._current = canonical_unit(params_to_unit(
                *(params[k] for k in _KNOB_NAMES),
                bucket_modes=[m for m in params.get(
                    "bucket_compression", "").split(":") if m])
                )[self._tuned]
            self._log(score, params, pinned=False)
        # NOT applied locally here: knobs take effect when the
        # coordinator's broadcast payload is received (all ranks,
        # rank 0 included, at the same round) — see BackgroundRuntime
        # for the world==1 direct-apply case.
        return params

    def _log(self, score: float, params: dict, pinned: bool) -> None:
        if not self._log_path:
            return
        with open(self._log_path, "a") as f:
            f.write(f"{self._samples_seen},{score:.4f},"
                    f"{self._objective}," +
                    ",".join(str(params[k]) for k in _KNOB_NAMES) +
                    f",{params.get('bucket_compression', '')}" +
                    f",{int(pinned)}\n")
