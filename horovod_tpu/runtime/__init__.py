"""horovod_tpu.runtime subpackage."""
