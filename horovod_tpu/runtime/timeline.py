"""Horovod Timeline: Chrome-tracing profile of every tensor's lifecycle.

Parity with reference ``horovod/common/timeline.{h,cc}``: per-tensor
rows (one trace "thread" per tensor name), NEGOTIATE_* → QUEUE → op
activity phases, optional cycle markers
(``HOROVOD_TIMELINE_MARK_CYCLES``, ``timeline.h:98``).  Records flow
through a queue to a dedicated writer thread so the background loop
never blocks on file IO (the reference uses a boost lock-free SPSC
queue, ``timeline.h:68-75``).  Rank 0 writes the file
(``operations.cc:403-411``); view in chrome://tracing or Perfetto.
"""

from __future__ import annotations

import json
import queue
import threading
import time


class NativeTimeline:
    """C++ writer (csrc/timeline.cc): record formatting and file IO run
    on a native thread, so the background loop pays only a ctypes call
    per event — the reference's native-writer design exactly."""

    def __init__(self, path: str) -> None:
        import ctypes

        from horovod_tpu.runtime import native_build

        lib = native_build.load_shared("libhvdtl.so", "timeline.cc")
        lib.hvd_tl_open.restype = ctypes.c_void_p
        lib.hvd_tl_open.argtypes = [ctypes.c_char_p]
        lib.hvd_tl_event.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_char_p, ctypes.c_char]
        lib.hvd_tl_marker.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.hvd_tl_close.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self._h = lib.hvd_tl_open(path.encode())
        if not self._h:
            raise OSError(f"timeline: cannot open {path}")

    def negotiate_start(self, name: str, kind: str) -> None:
        self._lib.hvd_tl_event(self._h, name.encode(),
                               f"NEGOTIATE_{kind.upper()}".encode(), b"B")

    def negotiate_end(self, name: str, kind: str) -> None:
        self._lib.hvd_tl_event(self._h, name.encode(),
                               f"NEGOTIATE_{kind.upper()}".encode(), b"E")

    def negotiate_rank_ready(self, name: str, rank: int) -> None:
        """Instant tick on the tensor's row: ``rank``'s request reached
        the coordinator (reference ``timeline.h:85-88`` — the straggler
        diagnostic: who was late for this negotiation)."""
        self._lib.hvd_tl_event(self._h, name.encode(),
                               f"RANK{rank}_READY".encode(), b"i")

    def activity_start(self, name: str, activity: str) -> None:
        self._lib.hvd_tl_event(self._h, name.encode(), activity.encode(),
                               b"B")

    def activity_end(self, name: str, activity: str) -> None:
        self._lib.hvd_tl_event(self._h, name.encode(), activity.encode(),
                               b"E")

    def mark_cycle(self) -> None:
        self._lib.hvd_tl_marker(self._h, b"CYCLE_START")

    def overlap_phase(self, name: str, bucket: int, phase: str,
                      elems: int = 0) -> None:
        """Instant tick on a per-bucket row: bucket ``bucket`` of the
        overlap schedule issued ``phase`` (``rs``/``compute``/``ag``).
        Issue order only — device-side durations ride the jax profiler's
        ``hvd_overlap_*`` named scopes (docs/overlap.md)."""
        del elems  # the native writer has no args payload
        self._lib.hvd_tl_event(
            self._h, f"{name}/bucket{bucket}".encode(),
            f"overlap/{phase}".encode(), b"i")

    def close(self) -> None:
        if self._h:
            self._lib.hvd_tl_close(self._h)
            self._h = None


class JaxProfilerBridge:
    """Device-side tracing via ``jax.profiler`` — the TPU-native analog
    of the reference's CUDA-event activity timing (its GPU op timings
    ride CUDA events drained by finalizer threads,
    ``gpu_operations.h:103-112``; on TPU the runtime's XLA profiler
    already records per-op device timelines, so the framework's job is
    to start/stop capture and label its collectives in the trace).

    Writes a TensorBoard-loadable xplane profile under
    ``<logdir>/rank<k>`` per process; view with TensorBoard's profile
    plugin, Perfetto, or ``python -m horovod_tpu.perf report``
    (docs/perf.md).  Enabled by ``HOROVOD_TIMELINE_JAX_PROFILER``
    (every rank captures: device activity is per-process, unlike the
    host-side Chrome timeline that only rank 0 aggregates).

    Elastic lifecycle: an elastic re-form tears the world down and
    re-enters ``init()`` in the same process — the old bridge is closed
    first (``teardown_distributed``, landing the old generation's
    capture on disk) and the new one opens under
    ``gen<g>/rank<k>`` so re-formed generations never write into a
    prior generation's directory (ranks are renumbered across re-forms:
    the new rank 0 may be a different host than the old rank 0's
    still-valuable capture).
    """

    def __init__(self, logdir: str, rank: int,
                 generation: int = 1) -> None:
        import atexit
        import os

        import jax

        self._jax_profiler = jax.profiler
        sub = (f"rank{rank}" if generation <= 1
               else os.path.join(f"gen{generation}", f"rank{rank}"))
        self._dir = os.path.join(logdir, sub)
        os.makedirs(self._dir, exist_ok=True)
        self._jax_profiler.start_trace(self._dir)
        self._active = True
        # The capture only lands at stop_trace; scripts that exit
        # without hvd.shutdown() must still get their profile.
        atexit.register(self.close)

    def annotate(self, label: str):
        """Context manager labelling framework work (e.g. the fused
        dispatch of one negotiated response) in the device trace."""
        return self._jax_profiler.TraceAnnotation(label)

    def close(self) -> None:
        if self._active:
            self._active = False
            try:
                self._jax_profiler.stop_trace()
            except RuntimeError:
                pass  # no trace running (e.g. double shutdown)


def make_timeline(path: str):
    """Native C++ writer when it builds, Python fallback otherwise."""
    try:
        return NativeTimeline(path)
    except Exception as exc:
        from horovod_tpu.common import logging as _log

        _log.warning("native timeline unavailable (%r); using the "
                     "Python writer" % (exc,))
        return Timeline(path)


class Timeline:
    def __init__(self, path: str) -> None:
        self._path = path
        self._q: queue.Queue = queue.Queue()
        self._tids: dict[str, int] = {}
        self._start = time.monotonic()
        self._file = open(path, "w")
        self._file.write("[\n")
        self._first = True
        self._closed = False
        self._writer = threading.Thread(target=self._write_loop,
                                        name="hvd-timeline", daemon=True)
        self._writer.start()

    # -- record API (called from the background thread) --------------------

    def _us(self) -> int:
        return int((time.monotonic() - self._start) * 1e6)

    def _tid(self, tensor_name: str) -> int:
        tid = self._tids.get(tensor_name)
        if tid is None:
            tid = len(self._tids) + 1
            self._tids[tensor_name] = tid
            self._q.put({"name": "thread_name", "ph": "M", "pid": 0,
                         "tid": tid,
                         "args": {"name": tensor_name}})
        return tid

    def negotiate_start(self, name: str, kind: str) -> None:
        self._q.put({"name": f"NEGOTIATE_{kind.upper()}", "ph": "B",
                     "pid": 0, "tid": self._tid(name), "ts": self._us()})

    def negotiate_end(self, name: str, kind: str) -> None:
        self._q.put({"name": f"NEGOTIATE_{kind.upper()}", "ph": "E",
                     "pid": 0, "tid": self._tid(name), "ts": self._us()})

    def negotiate_rank_ready(self, name: str, rank: int) -> None:
        """Instant tick: ``rank``'s request for ``name`` reached the
        coordinator (reference ``timeline.h:85-88``)."""
        self._q.put({"name": f"RANK{rank}_READY", "ph": "i", "pid": 0,
                     "tid": self._tid(name), "ts": self._us(), "s": "t",
                     "args": {"rank": rank}})

    def activity_start(self, name: str, activity: str) -> None:
        self._q.put({"name": activity, "ph": "B", "pid": 0,
                     "tid": self._tid(name), "ts": self._us()})

    def activity_end(self, name: str, activity: str) -> None:
        self._q.put({"name": activity, "ph": "E", "pid": 0,
                     "tid": self._tid(name), "ts": self._us()})

    def mark_cycle(self) -> None:
        self._q.put({"name": "CYCLE_START", "ph": "i", "pid": 0, "tid": 0,
                     "ts": self._us(), "s": "g"})

    def overlap_phase(self, name: str, bucket: int, phase: str,
                      elems: int = 0) -> None:
        """Per-bucket overlap-schedule tick (``overlap/rs``,
        ``overlap/compute``, ``overlap/ag``) on a ``<name>/bucket<k>``
        row, so the K-bucket pipeline is visible in the Chrome trace.
        These record host-side *issue* order — the whole schedule is
        one XLA program, so per-bucket device durations live in the
        ``hvd_overlap_*`` named scopes of the jax profiler capture
        (``HOROVOD_TIMELINE_JAX_PROFILER``); see docs/overlap.md."""
        self._q.put({"name": f"overlap/{phase}", "ph": "i", "pid": 0,
                     "tid": self._tid(f"{name}/bucket{bucket}"),
                     "ts": self._us(), "s": "t",
                     "args": {"bucket": bucket, "elems": int(elems)}})

    # -- writer ------------------------------------------------------------

    def _write_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                # Footer written by the owner of the file handle so
                # closing can't race a mid-backlog writer.
                self._file.write("\n]\n")
                self._file.close()
                return
            text = json.dumps(item)
            if self._first:
                self._first = False
                self._file.write(text)
            else:
                self._file.write(",\n" + text)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._writer.join(timeout=10)
