"""Horovod Timeline: Chrome-tracing profile of every tensor's lifecycle.

Parity with reference ``horovod/common/timeline.{h,cc}``: per-tensor
rows (one trace "thread" per tensor name), NEGOTIATE_* → QUEUE → op
activity phases, optional cycle markers
(``HOROVOD_TIMELINE_MARK_CYCLES``, ``timeline.h:98``).  Records flow
through a queue to a dedicated writer thread so the background loop
never blocks on file IO (the reference uses a boost lock-free SPSC
queue, ``timeline.h:68-75``).  Rank 0 writes the file
(``operations.cc:403-411``); view in chrome://tracing or Perfetto.
"""

from __future__ import annotations

import json
import queue
import threading
import time


class Timeline:
    def __init__(self, path: str) -> None:
        self._path = path
        self._q: queue.Queue = queue.Queue()
        self._tids: dict[str, int] = {}
        self._start = time.monotonic()
        self._file = open(path, "w")
        self._file.write("[\n")
        self._first = True
        self._closed = False
        self._writer = threading.Thread(target=self._write_loop,
                                        name="hvd-timeline", daemon=True)
        self._writer.start()

    # -- record API (called from the background thread) --------------------

    def _us(self) -> int:
        return int((time.monotonic() - self._start) * 1e6)

    def _tid(self, tensor_name: str) -> int:
        tid = self._tids.get(tensor_name)
        if tid is None:
            tid = len(self._tids) + 1
            self._tids[tensor_name] = tid
            self._q.put({"name": "thread_name", "ph": "M", "pid": 0,
                         "tid": tid,
                         "args": {"name": tensor_name}})
        return tid

    def negotiate_start(self, name: str, kind: str) -> None:
        self._q.put({"name": f"NEGOTIATE_{kind.upper()}", "ph": "B",
                     "pid": 0, "tid": self._tid(name), "ts": self._us()})

    def negotiate_end(self, name: str, kind: str) -> None:
        self._q.put({"name": f"NEGOTIATE_{kind.upper()}", "ph": "E",
                     "pid": 0, "tid": self._tid(name), "ts": self._us()})

    def activity_start(self, name: str, activity: str) -> None:
        self._q.put({"name": activity, "ph": "B", "pid": 0,
                     "tid": self._tid(name), "ts": self._us()})

    def activity_end(self, name: str, activity: str) -> None:
        self._q.put({"name": activity, "ph": "E", "pid": 0,
                     "tid": self._tid(name), "ts": self._us()})

    def mark_cycle(self) -> None:
        self._q.put({"name": "CYCLE_START", "ph": "i", "pid": 0, "tid": 0,
                     "ts": self._us(), "s": "g"})

    # -- writer ------------------------------------------------------------

    def _write_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                # Footer written by the owner of the file handle so
                # closing can't race a mid-backlog writer.
                self._file.write("\n]\n")
                self._file.close()
                return
            text = json.dumps(item)
            if self._first:
                self._first = False
                self._file.write(text)
            else:
                self._file.write(",\n" + text)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._writer.join(timeout=10)
