"""On-demand builder/loader for CPython extension modules in ``csrc/``.

The reference ships its native core as extensions compiled by a 1626-line
``setup.py``; here the toolchain is just ``g++`` against the running
interpreter's headers, building into the source tree (or a user cache
when the tree is read-only).  Python↔C++ binding is the CPython C API —
no pybind11 dependency.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig
import threading

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "csrc")
_lock = threading.Lock()
_loaded: dict = {}


def load_extension(mod_name: str, source: str):
    """Compile (once) and import ``csrc/<source>`` as ``mod_name``.
    Raises on any build failure — callers fall back to pure Python."""
    with _lock:
        if mod_name in _loaded:
            return _loaded[mod_name]
        src = os.path.join(_CSRC, source)
        suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
        out = os.path.join(_CSRC, mod_name + suffix)
        if (not os.path.exists(out)
                or os.path.getmtime(out) < os.path.getmtime(src)):
            try:
                _compile(src, out)
            except (OSError, subprocess.CalledProcessError):
                cache = os.path.join(
                    os.environ.get("XDG_CACHE_HOME",
                                   os.path.expanduser("~/.cache")),
                    "horovod_tpu")
                os.makedirs(cache, exist_ok=True)
                out = os.path.join(cache, mod_name + suffix)
                if (not os.path.exists(out)
                        or os.path.getmtime(out) < os.path.getmtime(src)):
                    _compile(src, out)
        spec = importlib.util.spec_from_file_location(mod_name, out)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _loaded[mod_name] = mod
        return mod


def load_shared(lib_name: str, source: str):
    """Compile (once) and dlopen ``csrc/<source>`` as a plain shared
    library (C ABI via ctypes, no Python.h).  Raises on build failure."""
    import ctypes

    with _lock:
        if lib_name in _loaded:
            return _loaded[lib_name]
        src = os.path.join(_CSRC, source)
        out = os.path.join(_CSRC, lib_name)
        if (not os.path.exists(out)
                or os.path.getmtime(out) < os.path.getmtime(src)):
            try:
                _compile(src, out, python_ext=False)
            except (OSError, subprocess.CalledProcessError):
                cache = os.path.join(
                    os.environ.get("XDG_CACHE_HOME",
                                   os.path.expanduser("~/.cache")),
                    "horovod_tpu")
                os.makedirs(cache, exist_ok=True)
                out = os.path.join(cache, lib_name)
                if (not os.path.exists(out)
                        or os.path.getmtime(out) < os.path.getmtime(src)):
                    _compile(src, out, python_ext=False)
        lib = ctypes.CDLL(out)
        _loaded[lib_name] = lib
        return lib


def _compile(src: str, out: str, python_ext: bool = True) -> None:
    include = sysconfig.get_paths()["include"]
    # per-process tmp: N ranks on one host may all compile on first use;
    # each builds privately and the atomic rename makes last-writer win
    # with a complete .so either way
    tmp = f"{out}.{os.getpid()}.tmp"
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17"]
    if python_ext:
        cmd.append(f"-I{include}")
    else:
        cmd.append("-pthread")
    cmd += [src, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, out)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
