"""Deterministic in-process fleet simulator (docs/control-plane.md).

No hardware run here can validate 1024 ranks (the TPU PJRT attempts
wedged at init — BENCH_r03/r04), so the scaling claims of the
hierarchical control plane are proven *in CI* instead: hundreds of
simulated ranks, each a cooperative thread driving a **real**
:class:`~horovod_tpu.runtime.controller.KVController` (not a mock)
over a simulated KV wire, through negotiation rounds, elastic re-form
storms, and coordinated aborts at 256–4096 ranks.

Determinism contract: same ``(world, fanout, seed, fault_spec)`` →
identical round trace, down to per-store message counts and simulated
latencies.  The trick is that nothing *observed* depends on thread
interleaving:

* The simulated stores count only **charged** ops — writes, deletes,
  and *successful* reads (the one observation that resolves a waiter
  or a fair-poll slot).  Poll misses are free: their count varies with
  scheduling, the set of charged ops does not.
* Per-op charges are attributed to the negotiation round parsed from
  the key (:func:`horovod_tpu.runtime.faults.round_of`), so no
  barrier between rounds is needed — threads may run ahead.
* Simulated round latency is computed *analytically* from the charged
  counts (hop depth × RTT + store service time × queue length +
  injected virtual delays + seeded jitter), never from wall clocks.
* Fault injection rides the ``HOROVOD_FAULT_SPEC`` grammar
  (:mod:`horovod_tpu.runtime.faults`) with simulation semantics:
  ``delay`` and ``slow`` charge virtual seconds to the acting rank
  instead of sleeping, ``drop`` swallows writes, ``die`` raises
  :class:`SimRankDied` in the rank's thread instead of ``os._exit``,
  and ``preempt`` records an advance notice in ``fleet.preempted``
  (the rank keeps negotiating — a noticed rank drains gracefully, it
  does not crash).

The coordinated-abort scenario is the one deliberate exception: it
exercises the *real* heartbeat sweep / abort broadcast machinery,
which is wall-clock based — its assertion is "every survivor raises
RanksDownError naming the victim", not a bit-exact trace.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import random
import threading
import time
from dataclasses import dataclass

import numpy as np

from horovod_tpu.common import config as _config
from horovod_tpu.common.types import RanksDownError, dtype_code
from horovod_tpu.runtime import faults as _faults
from horovod_tpu.runtime.controller import (KVController, Request,
                                            control_topology)

_F32 = dtype_code(np.dtype(np.float32))


class SimRankDied(Exception):
    """A ``die:`` fault rule fired for this simulated rank — the sim
    analog of ``os._exit(137)``: the rank's thread unwinds and stops
    participating (its heartbeat freezes, crash-style)."""


class SimStore:
    """One simulated KV server: dict + condition variable, counting
    charged ops per negotiation round.  ``set_once`` mirrors the real
    stores' at-most-once semantics (an existing key wins silently);
    plain ``set`` refuses overwrites like the jax coordination
    service, ``overwrite=True`` is the heartbeat path."""

    def __init__(self, name: str):
        self.name = name
        self._kv: dict[str, str] = {}
        self._cv = threading.Condition()
        # round (None = non-round keys: hb, abort) -> op -> count
        self._ops: dict[int | None, dict[str, int]] = {}
        self.total_ops = 0

    def _charge(self, op: str, key: str) -> None:
        rnd = _faults.round_of(_faults.strip_epoch(key))
        per = self._ops.setdefault(rnd, {})
        per[op] = per.get(op, 0) + 1
        self.total_ops += 1

    def set(self, key: str, value: str, overwrite: bool = False,
            once: bool = False) -> None:
        with self._cv:
            if key in self._kv and not overwrite:
                if once:
                    return
                raise KeyError(f"sim kv: {key} already exists")
            self._kv[key] = value
            self._charge("set", key)
            self._cv.notify_all()

    def get_blocking(self, key: str, timeout_s: float) -> str:
        with self._cv:
            deadline = time.monotonic() + timeout_s
            while key not in self._kv:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"sim kv: {key}")
                self._cv.wait(remaining)
            self._charge("get", key)
            return self._kv[key]

    def try_get(self, key: str):
        with self._cv:
            value = self._kv.get(key)
            if value is not None:
                # Only the successful observation is charged: the poll
                # *misses* leading up to it vary with thread timing,
                # the observations do not.
                self._charge("get", key)
            return value

    def delete(self, key: str) -> None:
        with self._cv:
            self._kv.pop(key, None)
            self._charge("delete", key)

    def ops_for_round(self, rnd: int) -> int:
        with self._cv:
            return sum(self._ops.get(rnd, {}).values())

    def ops_by_round(self) -> dict:
        with self._cv:
            return {r: dict(v) for r, v in self._ops.items()}


class SimTransport:
    """Per-rank transport routing controller keys to the fleet's
    stores and applying this rank's fault rules.  Matches the
    controller-facing surface of the real transports (``set`` /
    ``set_once`` / ``set_overwrite`` / ``get_blocking`` / ``try_get``
    / ``delete``)."""

    def __init__(self, fleet: "SimFleet", rank: int):
        self.fleet = fleet
        self.rank = rank
        # Per-rank rule state, like each real process parsing its own
        # env: drop budgets and die triggers are scoped to this rank.
        self._rules = _faults.parse_spec(fleet.fault_spec) \
            if fleet.fault_spec else []

    def _fault(self, key: str, write: bool) -> bool:
        """Apply die/delay/drop rules to one charged op on (stripped)
        ``key``; returns True when a drop rule swallowed a write."""
        stripped = _faults.strip_epoch(key)
        rnd = _faults.round_of(stripped)
        for rule in self._rules:
            if rule.kind == "die" and rule.rank == self.rank \
                    and rnd is not None and rnd >= rule.round \
                    and rule.take():
                raise SimRankDied(
                    f"rank {self.rank} died at round {rnd} ({stripped})")
            if rule.kind == "preempt" and rule.rank == self.rank \
                    and rnd is not None and rnd >= rule.round \
                    and rule.take():
                # Advance notice, not a death: record it and keep
                # going.  Deterministic because the rank's own charged
                # ops happen in program order within its thread.
                self.fleet.preempted.setdefault(self.rank, rnd)
        import fnmatch

        for rule in self._rules:
            if rule.kind == "slow":
                # Chronic straggler: every charged op of the scoped
                # rank pays the virtual tax, key-independent.
                if rule.rank == self.rank:
                    self.fleet.charge_delay(self.rank, rnd, rule.delay_s)
                continue
            if rule.only_rank not in (-1, self.rank):
                continue
            if rule.kind == "delay" \
                    and fnmatch.fnmatch(stripped, rule.pattern):
                # Virtual time, not a sleep: the charge feeds the
                # analytic latency model deterministically.
                self.fleet.charge_delay(self.rank, rnd, rule.delay_s)
            elif write and rule.kind == "drop" \
                    and fnmatch.fnmatch(stripped, rule.pattern) \
                    and rule.take():
                return True
        return False

    def set(self, key: str, value: str) -> None:
        if not self._fault(key, write=True):
            self.fleet.store_for(key).set(key, value)

    def set_once(self, key: str, value: str) -> None:
        if not self._fault(key, write=True):
            self.fleet.store_for(key).set(key, value, once=True)

    def set_overwrite(self, key: str, value: str) -> None:
        if not self._fault(key, write=True):
            self.fleet.store_for(key).set(key, value, overwrite=True)

    def get_blocking(self, key: str, timeout_s: float) -> str:
        self._fault(key, write=False)
        return self.fleet.store_for(key).get_blocking(key, timeout_s)

    def try_get(self, key: str):
        # No fault hook here: try_get is the *polled* op — a die/delay
        # applied per poll would fire a scheduling-dependent number of
        # times and break the determinism contract.  die rules still
        # trigger on the poller's own writes/blocking gets.
        return self.fleet.store_for(key).try_get(key)

    def delete(self, key: str) -> None:
        self._fault(key, write=True)
        self.fleet.store_for(key).delete(key)


@dataclass
class LatencyModel:
    """Analytic wire model: round-trip times, per-message store
    service time, and a seeded jitter amplitude.

    ``ici_rtt_ms``/``dcn_rtt_ms`` split the round trip by hop kind —
    intra-slice (slice store, the ICI analog) vs cross-slice (root
    store, the DCN analog) — so regimes that trade DCN rounds for ICI
    rounds (local-SGD, docs/local-sgd.md) price out honestly.  Both
    default to the legacy single ``rtt_ms``, so every pre-split
    construction (``LatencyModel(rtt_ms=...)``) keeps its exact
    numbers."""

    rtt_ms: float = 0.5
    per_msg_ms: float = 0.02
    jitter_ms: float = 0.2
    ici_rtt_ms: float | None = None
    dcn_rtt_ms: float | None = None

    def ici(self) -> float:
        return self.rtt_ms if self.ici_rtt_ms is None \
            else self.ici_rtt_ms

    def dcn(self) -> float:
        return self.rtt_ms if self.dcn_rtt_ms is None \
            else self.dcn_rtt_ms


@dataclass
class RoundTrace:
    round: int
    digest: str            # agreed NegotiationResult digest, all ranks
    root_ops: int          # charged ops at the root store this round
    slice_ops_max: int     # busiest slice store (0 in flat mode)
    latency_ms: float      # simulated, analytic

    def to_dict(self) -> dict:
        return {"round": self.round, "digest": self.digest,
                "root_ops": self.root_ops,
                "slice_ops_max": self.slice_ops_max,
                "latency_ms": round(self.latency_ms, 4)}


def default_requests(rnd: int, rank: int) -> list:
    """Two small allreduces per round, identical on every rank — the
    steady-state gradient-push shape.  Round 0 negotiates slow, later
    rounds resolve via the cache bitvector fast path, so both
    coordinator paths are exercised."""
    return [Request(f"sim_g{i}", "allreduce", 2, _F32, (4,))
            for i in range(2)]


def _digest(result) -> str:
    blob = json.dumps(
        {"resp": [p.wire() for p in result.responses],
         "aj": result.all_joined, "lj": result.last_joined,
         "x": result.should_stop}, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class SimFleet:
    """``world`` simulated ranks over a simulated KV wire, driving
    real KVControllers.  ``fanout=0`` forces flat mode; ``fanout>=2``
    with ``world > fanout`` builds the hierarchical plane (the same
    :func:`control_topology` the real controller uses)."""

    def __init__(self, world: int, fanout: int = 0, seed: int = 0,
                 fault_spec: str | None = None,
                 latency: LatencyModel | None = None,
                 hb_interval: float = 0.0, hb_timeout: float = 0.0,
                 wire_timeout_s: float = 60.0, epoch: int = 0):
        self.world = world
        self.fanout = fanout
        self.seed = seed
        self.fault_spec = (str(_config.get("fault_spec"))
                           if fault_spec is None else fault_spec)
        self.latency = latency or LatencyModel()
        self.hb_interval = hb_interval
        self.hb_timeout = hb_timeout
        self.wire_timeout_s = wire_timeout_s
        self.epoch = epoch
        self.topo = control_topology(world, fanout)
        self.root = SimStore("root")
        self.slices = ([SimStore(f"slice{s}")
                        for s in range(self.topo.n_slices)]
                       if self.topo is not None else [])
        self._delay_lock = threading.Lock()
        # round -> rank -> accumulated virtual delay seconds
        self._delays: dict[int | None, dict[int, float]] = {}
        self.dead: set[int] = set()
        # rank -> round its preempt: notice was delivered (the sim
        # analog of runtime/preemption.notice — the rank stays alive).
        self.preempted: dict[int, int] = {}
        self.errors: dict[int, BaseException] = {}
        # Ranks that observed a coordinated abort as an error
        # ResponseList (the fan-down path) rather than an exception.
        self.abort_stops: set[int] = set()

    # -- wiring ------------------------------------------------------------

    def store_for(self, key: str) -> SimStore:
        """Slice-scoped keys (sq/sp/sk, member heartbeats) live on
        their slice's store; everything else (q/p/k, gq, abort, rank
        0's beat) on the root store — so the root counter measures
        exactly the traffic a real root rendezvous server would
        serve."""
        if self.topo is None:
            return self.root
        parts = _faults.strip_epoch(key).split("/")
        if parts[0] in ("sq", "sp", "sk") and len(parts) >= 2 \
                and parts[1].isdigit():
            return self.slices[int(parts[1])]
        if parts[0] == "hb" and len(parts) >= 2 and parts[1].isdigit():
            rank = int(parts[1])
            if rank != 0:
                return self.slices[self.topo.slice_of(rank)]
        return self.root

    def charge_delay(self, rank: int, rnd: int | None,
                     delay_s: float) -> None:
        with self._delay_lock:
            per = self._delays.setdefault(rnd, {})
            per[rank] = per.get(rank, 0.0) + delay_s

    def rank_delays(self, rnd: int | None) -> dict[int, float]:
        """Accumulated virtual delay seconds per rank for one round —
        the coordinator-clock lateness signal the autopilot's
        straggler rule consumes."""
        with self._delay_lock:
            return dict(self._delays.get(rnd, {}))

    def make_controller(self, rank: int) -> KVController:
        ctl = KVController(SimTransport(self, rank), rank, self.world,
                           epoch=self.epoch, fanout=self.fanout)
        # Sim-scoped overrides, attr-level so no env/config mutation
        # leaks between fleets living in one process.
        ctl._timeout = self.wire_timeout_s
        ctl._hb_interval = self.hb_interval
        ctl._hb_timeout = self.hb_timeout
        return ctl

    # -- scenarios ---------------------------------------------------------

    def _rank_main(self, rank: int, n_rounds: int, requests_fn,
                   digests: list, heartbeats: bool) -> None:
        ctl = self.make_controller(rank)
        if heartbeats:
            ctl.start_heartbeat()
        try:
            for r in range(n_rounds):
                res = ctl.negotiate(requests_fn(r, rank), False, False)
                digests[rank].append(_digest(res))
                if res.should_stop:
                    if any(p.kind == "error" and p.error
                           and RanksDownError.WIRE_PREFIX in p.error
                           for p in res.responses):
                        self.abort_stops.add(rank)
                    break
        except SimRankDied:
            self.dead.add(rank)
            # Crash-style: freeze the beat (stop publishing, do NOT
            # delete the key) so peers observe staleness, exactly like
            # a SIGKILLed process.
            hb = ctl._heartbeat
            if hb is not None:
                hb._stop.set()
            return
        except BaseException as exc:  # timeout, RanksDownError, ...
            self.errors[rank] = exc
            hb = ctl._heartbeat
            if hb is not None:
                hb._stop.set()
            return
        if heartbeats:
            ctl.close()

    def run_rounds(self, n_rounds: int, requests_fn=None,
                   heartbeats: bool = False) -> list[RoundTrace]:
        """Drive every rank through ``n_rounds`` negotiations; returns
        the deterministic per-round trace.  Raises if any rank failed
        for a reason other than a scripted death."""
        requests_fn = requests_fn or default_requests
        digests: list[list[str]] = [[] for _ in range(self.world)]
        old_stack = threading.stack_size(512 * 1024)
        try:
            threads = [
                threading.Thread(
                    target=self._rank_main,
                    args=(rank, n_rounds, requests_fn, digests,
                          heartbeats),
                    name=f"sim-rank-{rank}", daemon=True)
                for rank in range(self.world)]
            for t in threads:
                t.start()
        finally:
            threading.stack_size(old_stack)
        for t in threads:
            t.join()
        return self._traces(n_rounds, digests)

    def _traces(self, n_rounds: int,
                digests: list[list[str]]) -> list[RoundTrace]:
        lm = self.latency
        # q↑p↓ on the root (DCN) flat; sq↑sp↓ intra-slice (ICI) +
        # gq↑p↓ on the root (DCN) hierarchical.  With the legacy
        # single-rtt model both spellings reduce to hops * rtt_ms.
        base_rtt = (2 * lm.dcn() if self.topo is None
                    else 2 * lm.ici() + 2 * lm.dcn())
        out: list[RoundTrace] = []
        for r in range(n_rounds):
            per_rank = {d[r] for rank, d in enumerate(digests)
                        if rank not in self.dead and len(d) > r}
            if not per_rank:
                break
            if len(per_rank) > 1:
                raise AssertionError(
                    f"round {r}: ranks disagree on the negotiated "
                    f"result ({sorted(per_rank)})")
            root_ops = self.root.ops_for_round(r)
            slice_ops = max((s.ops_for_round(r) for s in self.slices),
                            default=0)
            with self._delay_lock:
                inj = max(self._delays.get(r, {}).values(), default=0.0)
            jitter = random.Random(
                (self.seed << 20) ^ r).random() * lm.jitter_ms
            latency = (base_rtt
                       + (root_ops + slice_ops) * lm.per_msg_ms
                       + inj * 1000.0 + jitter)
            out.append(RoundTrace(r, per_rank.pop(), root_ops,
                                  slice_ops, latency))
        return out


# ---------------------------------------------------------------------------
# Canned scenarios (ci.sh `simfleet` stage, bench --sim-ranks, docs recipe)
# ---------------------------------------------------------------------------


def measure_scaling(world: int = 1024, fanout: int = 32,
                    rounds: int = 4, seed: int = 0) -> dict:
    """Root-store messages per steady-state round, flat vs
    hierarchical — the CI scaling assertion's data source.  The
    steady-state figure is the last round's (GC active, cache fast
    path warm)."""
    flat = SimFleet(world, fanout=0, seed=seed).run_rounds(rounds)
    hier = SimFleet(world, fanout=fanout, seed=seed).run_rounds(rounds)
    flat_ops = flat[-1].root_ops
    hier_ops = hier[-1].root_ops
    return {
        "world": world, "fanout": fanout, "rounds": rounds,
        "flat_root_ops_per_round": flat_ops,
        "hier_root_ops_per_round": hier_ops,
        "ratio": round(flat_ops / max(hier_ops, 1), 2),
        "flat_latency_ms": [t.to_dict()["latency_ms"] for t in flat],
        "hier_latency_ms": [t.to_dict()["latency_ms"] for t in hier],
    }


def local_sgd_scaling(world: int = 256, fanout: int = 16, h: int = 4,
                      windows: int = 2, seed: int = 0) -> dict:
    """Cross-slice round economy of the local-SGD regime
    (docs/local-sgd.md) at fleet scale: the synchronous fleet
    negotiates a cross-slice gradient round EVERY step, while a
    local-SGD fleet's inner steps are compiled intra-slice reductions
    that never touch the negotiated cross-slice wire — only every
    H-th step's outer pseudo-gradient sync does.  Simulates
    ``windows * h`` training steps both ways over the REAL controller
    with the split ICI/DCN latency model and reports the >= H× round
    reduction.  Deterministic: same inputs → byte-identical dict."""
    h = max(int(h), 2)
    steps = windows * h
    lm = LatencyModel(ici_rtt_ms=0.05, dcn_rtt_ms=2.5)
    sync = SimFleet(world, fanout=fanout, seed=seed,
                    latency=lm).run_rounds(steps)

    def outer_requests(rnd: int, rank: int) -> list:
        # The outer sync's negotiated shape: pseudo-gradient
        # allreduces under the cross-scope name contract
        # (controller.reduction_scope).
        return [Request(f"localsgd.cross.sim_g{i}", "allreduce", 2,
                        _F32, (4,)) for i in range(2)]

    outer = SimFleet(world, fanout=fanout, seed=seed,
                     latency=lm).run_rounds(windows,
                                            requests_fn=outer_requests)
    # Inner steps price at the ICI hop only — no negotiated round.
    inner_ms = 2 * lm.ici()
    sync_wall = sum(t.latency_ms for t in sync)
    lsgd_wall = sum(t.latency_ms for t in outer) + steps * inner_ms
    return {
        "world": world, "fanout": fanout, "h": h, "steps": steps,
        "ici_rtt_ms": lm.ici(), "dcn_rtt_ms": lm.dcn(),
        "sync_cross_rounds": len(sync),
        "localsgd_cross_rounds": len(outer),
        "cross_round_ratio": round(len(sync) / max(len(outer), 1), 2),
        "sync_wall_ms": round(sync_wall, 4),
        "localsgd_wall_ms": round(lsgd_wall, 4),
        "outer_trace": [t.to_dict() for t in outer],
    }


def reform_storm(world: int = 256, fanout: int = 16,
                 kill: int = 8, pre_rounds: int = 3,
                 post_rounds: int = 3, seed: int = 0) -> dict:
    """Elastic re-form storm: run ``pre_rounds`` at full strength,
    kill ``kill`` ranks simultaneously (scattered across slices, rank
    0's slice included), re-form the roster through the REAL
    :func:`horovod_tpu.elastic.plan_reform`, and run the survivor
    fleet.  Returns the plan + both traces; the roster must come out
    dense and deterministic."""
    from horovod_tpu.elastic import plan_reform

    fleet = SimFleet(world, fanout=fanout, seed=seed)
    pre = fleet.run_rounds(pre_rounds)
    stride = max(world // kill, 1)
    victims = sorted((1 + i * stride) % world for i in range(kill))
    hosts_of = (fleet.topo.slice_of if fleet.topo is not None
                else lambda r: r // 8)
    survivors = [(r, f"uid-{r:04d}", f"host-{hosts_of(r)}")
                 for r in range(world) if r not in set(victims)]
    plan = plan_reform(survivors, [])
    new_ranks = sorted(m["rank"] for m in plan["members"])
    if new_ranks != list(range(len(survivors))):
        raise AssertionError(f"re-formed roster not dense: {new_ranks}")
    post_fleet = SimFleet(plan["size"], fanout=fanout, seed=seed,
                          epoch=1)
    post = post_fleet.run_rounds(post_rounds)
    return {
        "world": world, "victims": victims, "new_world": plan["size"],
        "roster_digest": hashlib.sha256(json.dumps(
            plan["members"], sort_keys=True).encode()).hexdigest()[:16],
        "pre": [t.to_dict() for t in pre],
        "post": [t.to_dict() for t in post],
    }


def coordinated_abort(world: int = 32, fanout: int = 8,
                      victim: int = 5, seed: int = 0) -> dict:
    """Kill one rank mid-negotiation (``die:`` rule) with real
    heartbeats at sim-scale intervals; every survivor must observe
    the coordinated abort and raise RanksDownError naming the victim.
    Wall-clock based by design — excluded from determinism traces."""
    fleet = SimFleet(world, fanout=fanout, seed=seed,
                     fault_spec=f"die:rank{victim}:round1",
                     hb_interval=0.05, hb_timeout=1.0,
                     wire_timeout_s=30.0)
    fleet.run_rounds(3, heartbeats=True)
    survivors = [r for r in range(world) if r != victim]
    raised = [r for r in survivors
              if isinstance(fleet.errors.get(r), RanksDownError)]
    naming = [r for r in raised
              if victim in (fleet.errors[r].ranks or [])]
    # A survivor observes the abort either as a raised RanksDownError
    # or as the broadcast error ResponseList (should_stop fan-down).
    observed = set(raised) | fleet.abort_stops
    return {
        "world": world, "victim": victim,
        "died": sorted(fleet.dead),
        "survivors_aborted": len(observed),
        "survivors_raised": len(raised),
        "survivors_naming_victim": len(naming),
        "survivors_total": len(survivors),
    }


def straggler_drill(world: int = 256, fanout: int = 16,
                    straggler: int = 3, delay: str = "200ms",
                    rounds: int = 4, post_rounds: int = 2,
                    seed: int = 0, dry_run: bool = False) -> dict:
    """Autopilot drill (docs/autopilot.md): a chronic straggler
    (``slow:`` rule) accumulates virtual lateness round after round;
    the preemptive-blacklist rule must trip on the sustained breach
    and shed the host BEFORE any rank dies — the whole point of acting
    on lateness instead of on death.  The shrink re-forms the roster
    through the real :func:`horovod_tpu.elastic.plan_reform`.
    Deterministic: same (world, fanout, seed, delay) → byte-identical
    output, actions included (the engine runs on the virtual round
    clock)."""
    from horovod_tpu.elastic import plan_reform
    from horovod_tpu.runtime import autopilot as _autopilot

    fleet = SimFleet(world, fanout=fanout, seed=seed,
                     fault_spec=f"slow:{straggler}:{delay}")
    pre = fleet.run_rounds(rounds)
    hosts = {r: f"host-{r:04d}" for r in range(world)}
    blacklisted: list[str] = []
    ap = _autopilot.Autopilot(
        dry_run=dry_run, clock=lambda: 0.0,
        cooldown_s=float(rounds), rate_limit=4, rate_window_s=3600.0,
        trip_ticks=2, straggler_factor=4.0, straggler_floor_s=0.05,
        burn_threshold=2.0, comm_fraction=0.25,
        actuators={
            "straggler_blacklist": lambda a: blacklisted.append(
                a.target)})
    for r in range(rounds):
        delays = fleet.rank_delays(r)
        lateness = {k: delays.get(k, 0.0) for k in range(world)}
        ap.observe_stragglers(lateness, hosts=hosts, now=float(r))
    if fleet.dead:
        raise AssertionError(
            f"slow: rule must never kill a rank, got {fleet.dead}")
    survivors = [(r, f"uid-{r:04d}", hosts[r]) for r in range(world)
                 if hosts[r] not in blacklisted]
    plan = plan_reform(survivors, [])
    post_fleet = SimFleet(plan["size"], fanout=fanout, seed=seed,
                          epoch=1)
    post = post_fleet.run_rounds(post_rounds)
    return {
        "world": world, "straggler": straggler, "delay": delay,
        "dry_run": dry_run,
        "straggler_lateness_s": [
            round(fleet.rank_delays(r).get(straggler, 0.0), 6)
            for r in range(rounds)],
        "actions": [a.to_dict() for a in ap.actions],
        "blacklisted": blacklisted,
        "deaths": sorted(fleet.dead),
        "world_after": plan["size"],
        "roster_digest": hashlib.sha256(json.dumps(
            plan["members"], sort_keys=True).encode()).hexdigest()[:16],
        "pre_latency_ms": [t.to_dict()["latency_ms"] for t in pre],
        "post_latency_ms": [t.to_dict()["latency_ms"] for t in post],
    }


def preempt_storm(world: int = 256, fanout: int = 16, kill: int = 8,
                  rounds: int = 4, post_rounds: int = 2, seed: int = 0,
                  dry_run: bool = False) -> dict:
    """Autopilot drill (docs/fault-tolerance.md): ``kill`` ranks
    scattered across slices receive advance preemption notices
    (``preempt:`` rules) mid-run.  None of them may die and none of
    their hosts may be blacklisted — an announced departure is not a
    fault — instead the autopilot's ungated ``preempt_drain`` rule
    fires once per notice and the fleet sheds the noticed ranks
    proactively through the real
    :func:`horovod_tpu.elastic.plan_reform`.  Deterministic: same
    (world, fanout, kill, seed) → byte-identical output, actions and
    roster digest included."""
    from horovod_tpu.elastic import plan_reform
    from horovod_tpu.runtime import autopilot as _autopilot

    stride = max(world // max(kill, 1), 1)
    victims = sorted({(1 + i * stride) % world for i in range(kill)}
                     - {0})
    spec = ",".join(f"preempt:rank{v}:round1" for v in victims)
    fleet = SimFleet(world, fanout=fanout, seed=seed, fault_spec=spec)
    pre = fleet.run_rounds(rounds)
    if fleet.dead:
        raise AssertionError(
            f"preempt: rule must never kill a rank, got {fleet.dead}")
    if sorted(fleet.preempted) != victims:
        raise AssertionError(
            f"notices {sorted(fleet.preempted)} != victims {victims}")
    hosts = {r: f"host-{r:04d}" for r in range(world)}
    drained: list[int] = []
    ap = _autopilot.Autopilot(
        dry_run=dry_run, clock=lambda: 0.0,
        cooldown_s=3600.0, rate_limit=1, rate_window_s=3600.0,
        trip_ticks=2, straggler_factor=4.0, straggler_floor_s=0.05,
        burn_threshold=2.0, comm_fraction=0.25,
        actuators={"preempt_drain": lambda a: drained.append(
            int(a.target[len("rank"):]))})
    # Punitive cooldown/rate-limit settings above are the point of the
    # drill: preempt_drain is ungated, so every notice must still land.
    for v in victims:
        ap.observe_preemption(
            v, host=hosts[v], source="fault",
            now=float(fleet.preempted[v]))
    if not dry_run and sorted(drained) != victims:
        raise AssertionError(
            f"drained {sorted(drained)} != victims {victims}")
    shed = set(drained)
    survivors = [(r, f"uid-{r:04d}", hosts[r]) for r in range(world)
                 if r not in shed]
    plan = plan_reform(survivors, [])
    new_ranks = sorted(m["rank"] for m in plan["members"])
    if new_ranks != list(range(len(survivors))):
        raise AssertionError(f"re-formed roster not dense: {new_ranks}")
    post_fleet = SimFleet(plan["size"], fanout=fanout, seed=seed,
                          epoch=1)
    post = post_fleet.run_rounds(post_rounds)
    return {
        "world": world, "kill": kill, "victims": victims,
        "dry_run": dry_run, "fault_spec": spec,
        "notices": {str(r): fleet.preempted[r]
                    for r in sorted(fleet.preempted)},
        "actions": [a.to_dict() for a in ap.actions],
        "drained": sorted(drained),
        # The no-blacklist invariant: announced departures shed, their
        # (healthy) hosts stay eligible for re-join.
        "blacklisted": [],
        "deaths": sorted(fleet.dead),
        "world_after": plan["size"],
        "roster_digest": hashlib.sha256(json.dumps(
            plan["members"], sort_keys=True).encode()).hexdigest()[:16],
        "pre_latency_ms": [t.to_dict()["latency_ms"] for t in pre],
        "post_latency_ms": [t.to_dict()["latency_ms"] for t in post],
    }


def slo_burn_drill(world: int = 8, victim: int = 2, slo: float = 0.9,
                   ticks: int = 12, degrade_at: int = 3,
                   recover_at: int = 7, seed: int = 0,
                   dry_run: bool = False) -> dict:
    """Autopilot drill: one rank's exposed-comm stall drags windowed
    fleet goodput under the SLO; the sustained burn must shrink the
    fleet (shedding the dominant bottleneck), and the post-shrink
    recovery must grow it back — the full burn → shrink → recover →
    grow loop through a real :class:`~horovod_tpu.perf.goodput.
    FleetGoodput` on a virtual clock.  In ``dry_run`` the victim is
    never shed (no side effects), so the degradation ends only at
    ``recover_at``."""
    from horovod_tpu.perf.goodput import FleetGoodput
    from horovod_tpu.runtime import autopilot as _autopilot

    rng = random.Random(seed)
    events: list = []

    def _shrink(action) -> None:
        events.append(["shrink", action.evidence.get("bottleneck_rank")])

    def _grow(action) -> None:
        events.append(["grow", None])

    ap = _autopilot.Autopilot(
        dry_run=dry_run, clock=lambda: 0.0,
        cooldown_s=15.0, rate_limit=8, rate_window_s=3600.0,
        trip_ticks=2, straggler_factor=4.0, straggler_floor_s=0.05,
        burn_threshold=1.5, comm_fraction=0.25,
        actuators={"slo_burn_shrink": _shrink,
                   "slo_recover_grow": _grow})
    fleet_gp = FleetGoodput(slo=slo, window_s=30.0, clock=lambda: 0.0)
    cum = {r: {"elapsed": 0.0, "compute": 0.0, "exposed": 0.0}
           for r in range(world)}
    shed: set[int] = set()
    timeline: list[dict] = []
    for i in range(ticks):
        t = 10.0 * i
        degraded = degrade_at <= i < recover_at and victim not in shed
        snaps = []
        for r in range(world):
            if r in shed:
                continue
            c = cum[r]
            c["elapsed"] += 10.0
            jit = rng.random() * 0.05
            if r == victim and degraded:
                c["compute"] += 0.5 + jit
                c["exposed"] += 9.5 - jit
            else:
                c["compute"] += 9.5 + jit
                c["exposed"] += 0.5 - jit
            snaps.append({"rank": r, "elapsed_s": c["elapsed"],
                          "phases": {"compute": c["compute"],
                                     "comm_exposed": c["exposed"]},
                          "unattributed_s": 0.0})
        report = fleet_gp.update(snaps, now=t)
        before = len(events)
        ap.observe_goodput(report, now=t)
        if len(events) > before and events[-1][0] == "shrink" \
                and events[-1][1] is not None:
            shed.add(int(events[-1][1]))
        alert = report.get("alert") or {}
        timeline.append({
            "tick": i,
            "goodput": report["window"].get("goodput"),
            "burn": alert.get("burn_rate"),
            "firing": bool(alert.get("firing"))})
    return {
        "world": world, "victim": victim, "slo": slo,
        "dry_run": dry_run, "timeline": timeline,
        "actions": [a.to_dict() for a in ap.actions],
        "events": events, "shed": sorted(shed),
        "world_after": world - len(shed),
    }


def rollback_drill(steps: int = 12, poison_round: int = 7,
                   keep: int = 4, seed: int = 0,
                   dry_run: bool = False) -> dict:
    """Autopilot drill: an injected ``nan:`` fault (the real
    ``HOROVOD_FAULT_SPEC`` grammar, budget semantics included) poisons
    one training step; the health sentinel trips on the nonfinite
    loss, the commit is stamped ``poisoned`` in the checkpoint ring,
    and the autopilot rolls the pseudo-trainer back to the newest
    HEALTHY commit.  The resumed run must end **bit-exact** with a
    never-poisoned reference (same seed, same grad stream): every
    update surviving in the final params came from clean data.  In
    ``dry_run`` the verdict is recorded but nothing acts, so the NaN
    keeps the params poisoned and ``bit_exact`` is False — the shadow
    -mode parity check."""
    import fnmatch as _fnmatch
    import os as _os
    import tempfile

    from horovod_tpu import checkpoint as _ckpt
    from horovod_tpu.runtime import autopilot as _autopilot
    from horovod_tpu.runtime.health import HealthMonitor

    spec = f"nan:grad*:round{poison_round}"

    def train(fault_spec: str, ckpt: str, ap=None,
              commit_log: list | None = None) -> np.ndarray:
        rules = [r for r in _faults.parse_spec(fault_spec)
                 if r.kind in _faults.DATA_KINDS] if fault_spec else []
        mon = HealthMonitor(clock=lambda: 0.0)
        marks = [0, 0]

        def verdict() -> str:
            nf, al = mon.nonfinite_events, mon.alerts_total()
            poisoned = bool(mon.active_alerts()) \
                or nf > marks[0] or al > marks[1]
            marks[0], marks[1] = nf, al
            return "poisoned" if poisoned else "healthy"

        rolled: list = []
        if ap is not None:
            ap.actuators["health_rollback"] = rolled.append
        grads = np.random.default_rng(seed).standard_normal(
            (steps, 4)).astype(np.float64)
        params = np.zeros(4, dtype=np.float64)
        step = 0
        while step < steps:
            grad = grads[step].copy()
            for rule in rules:
                if rule.round and step < rule.round:
                    continue
                if not _fnmatch.fnmatch("grad", rule.pattern):
                    continue
                if not rule.take():
                    continue
                grad[0] = (float("nan") if rule.kind == "nan"
                           else float("inf"))
            params = params + 0.01 * grad
            mon.observe_loss(float(params @ params), step=step)
            if step % 2 == 1:
                v = verdict()
                _ckpt.save(ckpt, {"params": params, "step": step},
                           step=step, verdict=v)
                if commit_log is not None:
                    commit_log.append({"step": step, "verdict": v})
                # The rank_tick analogue: the autopilot evaluates at
                # the commit boundary, so the poisoned commit is
                # already in the ring when the rollback verdict lands
                # — exactly the state latest_healthy must skip over.
                if ap is not None:
                    ap.observe_health(mon.active_alerts(),
                                      mon.nonfinite_events,
                                      culprits=mon.culprits,
                                      now=float(step))
                    if rolled:
                        rolled.clear()
                        snap = _ckpt.restore(ckpt, healthy_only=True)
                        params = np.asarray(snap["params"])
                        step = int(snap["step"])
            step += 1
        return params

    def digest(params: np.ndarray) -> str:
        return hashlib.sha256(params.tobytes()).hexdigest()[:16]

    prev_keep = _os.environ.get("HOROVOD_CHECKPOINT_KEEP")
    _config.set_knob("checkpoint_keep", keep)
    try:
        with tempfile.TemporaryDirectory() as tmp:
            ap = _autopilot.Autopilot(
                dry_run=dry_run, clock=lambda: 0.0,
                cooldown_s=1e9, rate_limit=4, rate_window_s=1e9,
                trip_ticks=1, straggler_factor=4.0,
                straggler_floor_s=0.05, burn_threshold=2.0,
                comm_fraction=0.25)
            commits: list = []
            poisoned_dir = _os.path.join(tmp, "run")
            final = train(spec, poisoned_dir, ap=ap,
                          commit_log=commits)
            ring = _ckpt._complete_steps(poisoned_dir)
            ring_verdicts = {str(s): _ckpt.verdict_of(poisoned_dir, s)
                             for s in ring}
            reference = train("", _os.path.join(tmp, "ref"))
    finally:
        if prev_keep is None:
            _os.environ.pop("HOROVOD_CHECKPOINT_KEEP", None)
        else:
            _os.environ["HOROVOD_CHECKPOINT_KEEP"] = prev_keep
    rollbacks = [a for a in ap.actions
                 if a.rule == "health_rollback"
                 and a.outcome in ("applied", "dry_run")]
    return {
        "steps": steps, "fault_spec": spec, "keep": keep,
        "dry_run": dry_run, "commits": commits,
        "actions": [a.to_dict() for a in ap.actions],
        "rollbacks": len(rollbacks),
        "ring_steps": ring, "ring_verdicts": ring_verdicts,
        "final_finite": bool(np.isfinite(final).all()),
        "final_digest": digest(final),
        "reference_digest": digest(reference),
        "bit_exact": digest(final) == digest(reference),
    }


def run_trace(world: int, fanout: int, rounds: int, seed: int,
              fault_spec: str = "") -> list[dict]:
    """One deterministic negotiation trace — the shape the determinism
    test replays twice."""
    fleet = SimFleet(world, fanout=fanout, seed=seed,
                     fault_spec=fault_spec)
    return [t.to_dict() for t in fleet.run_rounds(rounds)]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.runtime.simfleet",
        description="Deterministic in-process fleet simulator "
                    "(docs/control-plane.md).")
    sub = p.add_subparsers(dest="cmd", required=True)
    t = sub.add_parser("trace", help="negotiation rounds -> round trace")
    t.add_argument("--world", type=int, default=256)
    t.add_argument("--fanout", type=int, default=16)
    t.add_argument("--rounds", type=int, default=4)
    t.add_argument("--seed", type=int, default=0)
    t.add_argument("--fault-spec", default="")
    s = sub.add_parser("scaling", help="flat vs hierarchical root load")
    s.add_argument("--world", type=int, default=1024)
    s.add_argument("--fanout", type=int, default=32)
    s.add_argument("--rounds", type=int, default=4)
    s.add_argument("--seed", type=int, default=0)
    ls = sub.add_parser(
        "localsgd", help="local-SGD cross-slice round economy")
    ls.add_argument("--world", type=int, default=256)
    ls.add_argument("--fanout", type=int, default=16)
    ls.add_argument("--h", type=int, default=4)
    ls.add_argument("--windows", type=int, default=2)
    ls.add_argument("--seed", type=int, default=0)
    r = sub.add_parser("storm", help="elastic re-form storm")
    r.add_argument("--world", type=int, default=256)
    r.add_argument("--fanout", type=int, default=16)
    r.add_argument("--kill", type=int, default=8)
    r.add_argument("--seed", type=int, default=0)
    a = sub.add_parser("abort", help="coordinated abort drill")
    a.add_argument("--world", type=int, default=32)
    a.add_argument("--fanout", type=int, default=8)
    a.add_argument("--victim", type=int, default=5)
    g = sub.add_parser(
        "straggler", help="autopilot preemptive-blacklist drill")
    g.add_argument("--world", type=int, default=256)
    g.add_argument("--fanout", type=int, default=16)
    g.add_argument("--straggler", type=int, default=3)
    g.add_argument("--delay", default="200ms")
    g.add_argument("--rounds", type=int, default=4)
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--dry-run", action="store_true")
    pe = sub.add_parser(
        "preempt", help="autopilot graceful-preemption storm drill")
    pe.add_argument("--world", type=int, default=256)
    pe.add_argument("--fanout", type=int, default=16)
    pe.add_argument("--kill", type=int, default=8)
    pe.add_argument("--rounds", type=int, default=4)
    pe.add_argument("--seed", type=int, default=0)
    pe.add_argument("--dry-run", action="store_true")
    b = sub.add_parser(
        "burn", help="autopilot SLO-burn shrink/grow drill")
    b.add_argument("--world", type=int, default=8)
    b.add_argument("--victim", type=int, default=2)
    b.add_argument("--slo", type=float, default=0.9)
    b.add_argument("--ticks", type=int, default=12)
    b.add_argument("--seed", type=int, default=0)
    b.add_argument("--dry-run", action="store_true")
    rb = sub.add_parser(
        "rollback", help="autopilot nan -> rollback -> bit-exact drill")
    rb.add_argument("--steps", type=int, default=12)
    rb.add_argument("--poison-round", type=int, default=7)
    rb.add_argument("--keep", type=int, default=4)
    rb.add_argument("--seed", type=int, default=0)
    rb.add_argument("--dry-run", action="store_true")
    args = p.parse_args(argv)
    if args.cmd == "trace":
        out = run_trace(args.world, args.fanout, args.rounds,
                        args.seed, args.fault_spec)
    elif args.cmd == "scaling":
        out = measure_scaling(args.world, args.fanout, args.rounds,
                              args.seed)
    elif args.cmd == "localsgd":
        out = local_sgd_scaling(args.world, args.fanout, args.h,
                                args.windows, args.seed)
    elif args.cmd == "storm":
        out = reform_storm(args.world, args.fanout, args.kill,
                           seed=args.seed)
    elif args.cmd == "straggler":
        out = straggler_drill(args.world, args.fanout, args.straggler,
                              args.delay, args.rounds, seed=args.seed,
                              dry_run=args.dry_run)
    elif args.cmd == "preempt":
        out = preempt_storm(args.world, args.fanout, args.kill,
                            args.rounds, seed=args.seed,
                            dry_run=args.dry_run)
    elif args.cmd == "burn":
        out = slo_burn_drill(args.world, args.victim, args.slo,
                             args.ticks, seed=args.seed,
                             dry_run=args.dry_run)
    elif args.cmd == "rollback":
        out = rollback_drill(args.steps, args.poison_round, args.keep,
                             args.seed, dry_run=args.dry_run)
    else:
        out = coordinated_abort(args.world, args.fanout, args.victim)
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
