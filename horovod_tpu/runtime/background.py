"""Background runtime: per-process coordinator thread + tensor queue.

Parity with the reference's core runtime (``horovod/common/operations.cc``):
framework threads only enqueue (``EnqueueTensorAllreduce``,
``operations.cc:803``) into a mutex-guarded tensor queue
(``tensor_queue.{h,cc}``); a single background thread drives ≤cycle-time
negotiation rounds (``RunLoopOnce``, ``operations.cc:550-600``), executes
the negotiated fused collectives, and completes handles.  Framework
threads never touch the wire — the design rationale documented at
``operations.cc:311-331``.
"""

from __future__ import annotations

import contextlib
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.common import basics as _basics
from horovod_tpu.common import config as _config
from horovod_tpu.common import logging as _log
from horovod_tpu.common.types import (DuplicateNameError, RanksDownError,
                                      Status, dtype_code, dtype_from_code)
from horovod_tpu.ops import xla_exec as _exec
from horovod_tpu.runtime import flight as _flight
from horovod_tpu.runtime import metrics as _metrics
from horovod_tpu.runtime.controller import (JOIN_NAME, RANKS_DOWN_PREFIX,
                                            Request, make_controller,
                                            reduction_scope, tensor_nbytes)


def _scope_of(resp) -> str | None:
    """Axis scope of a negotiated allreduce response (docs/local-sgd.md):
    ``"local"``/``"cross"`` for the local-SGD scoped reductions (derived
    from the negotiated tensor names, the wire contract), else None."""
    if resp.kind != "allreduce" or not resp.names:
        return None
    return reduction_scope(resp.names[0])

# Background-loop observability (docs/metrics.md).
_M_NEG_LAT = _metrics.histogram(
    "hvd_negotiation_seconds",
    "Wall time of one negotiation round (request post -> response "
    "list executed locally).")
_M_RESP_SIZE = _metrics.histogram(
    "hvd_response_list_size",
    "Responses per negotiated round (post-fusion launch count).",
    lo=0, hi=12)
_M_FAST_ROUNDS = _metrics.gauge(
    "hvd_negotiation_fast_rounds",
    "Rounds resolved via the cache-bit fast path since init.")
_M_DISPATCH = _metrics.counter(
    "hvd_comm_dispatch_seconds_total",
    "Background-thread seconds executing negotiated collectives.")
_M_WIRE_BYTES = _metrics.counter(
    "hvd_data_wire_bytes_total",
    "Data-plane bytes a negotiated response moves on the wire, after "
    "HOROVOD_COMPRESSION, labeled by collective kind and by axis "
    "(axis=local: ICI-only scoped reductions of the local-SGD inner "
    "step; axis=cross: everything that crosses slices over DCN — "
    "world-scoped collectives and local-SGD pseudo-gradient syncs).")
_M_LOGICAL_BYTES = _metrics.counter(
    "hvd_data_logical_bytes_total",
    "Uncompressed payload bytes of the same responses — "
    "wire/logical is the achieved compression ratio.")


class _Entry:
    __slots__ = ("name", "kind", "op", "root_rank", "tensor", "handle",
                 "postprocess")

    def __init__(self, name, kind, op, root_rank, tensor, handle,
                 postprocess):
        self.name = name
        self.kind = kind
        self.op = op
        self.root_rank = root_rank
        self.tensor = tensor
        self.handle = handle
        self.postprocess = postprocess


class TensorQueue:
    """Mutex-guarded name table + FIFO (reference ``tensor_queue.h:28-64``).
    Duplicate name before completion → error (reference ``common.h:161``)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._fifo: list[_Entry] = []
        self._table: dict[str, _Entry] = {}

    def add(self, entry: _Entry) -> None:
        with self._lock:
            if entry.name in self._table:
                raise DuplicateNameError(
                    f"Requested to {entry.kind} a tensor with the same name "
                    f"as another tensor that is currently being processed. "
                    f"If you want to request another tensor, pass a "
                    f"different tensor name. Tensor name: {entry.name}")
            self._table[entry.name] = entry
            self._fifo.append(entry)

    def pop_pending(self) -> list[_Entry]:
        with self._lock:
            out, self._fifo = self._fifo, []
            return out

    def drain_all(self) -> list[_Entry]:
        """Remove and return every outstanding entry — both queued and
        already-negotiating (used on shutdown/failure so no handle is
        left hanging)."""
        with self._lock:
            out = list(self._table.values())
            self._table.clear()
            self._fifo = []
            return out

    def finalize(self, name: str) -> "_Entry | None":
        with self._lock:
            return self._table.pop(name, None)

    def outstanding(self) -> int:
        with self._lock:
            return len(self._table)


class BackgroundRuntime:
    def __init__(self, handle_manager) -> None:
        st = _basics.state()
        self.rank = st.rank
        self.world = st.size
        self.hm = handle_manager
        self.queue = TensorQueue()
        self.controller = make_controller(self.rank, self.world, st.epoch)
        self._counters: dict[str, int] = {}
        self._counter_lock = threading.Lock()
        self._stop_requested = threading.Event()
        self._wake = threading.Event()
        self._stopped = threading.Event()
        self._join_requested = threading.Event()
        self._join_done = threading.Event()
        self._join_result = -1
        self._error: str | None = None
        self._error_class: type | None = None
        self._dumped_flight = False
        self.pm = None
        self._pending_tune: dict | None = None
        if self.rank == 0 and _config.get("autotune"):
            from horovod_tpu.runtime.parameter_manager import ParameterManager

            self.pm = ParameterManager(world=self.world)
        self.timeline = None
        tl_path = _config.get("timeline")
        if tl_path and self.rank == 0:
            from horovod_tpu.runtime.timeline import make_timeline

            self.timeline = make_timeline(tl_path)
            st.timeline = self.timeline
        # Created at hvd.init() (basics), shared here for dispatch
        # annotations; None when capture is disabled.
        self.profiler = getattr(st, "profiler", None)
        # Liveness: publish this rank's heartbeat for the duration of
        # the runtime (docs/fault-tolerance.md) — peers' controllers
        # sweep it and coordinate an abort when it goes stale.
        if hasattr(self.controller, "start_heartbeat"):
            self.controller.start_heartbeat()
        self._thread = threading.Thread(
            target=self._run, name="hvd-background", daemon=True)
        self._thread.start()

    # -- framework-thread API ---------------------------------------------

    def autoname(self, kind: str) -> str:
        with self._counter_lock:
            i = self._counters.get(kind, 0)
            self._counters[kind] = i + 1
        return f"{kind}.noname.{i}"

    def enqueue(self, kind, tensor, name, op, handle, postprocess,
                root_rank=-1) -> None:
        if self._stopped.is_set() or self._error:
            self.hm.mark_done(handle, Status.aborted(
                self._error or "Horovod-TPU runtime has been shut down.",
                self._error_class), None)
            return
        if not isinstance(tensor, jax.Array):
            # numpy/list inputs only: re-wrapping a jax.Array pays the
            # full jnp.array promotion machinery (~0.1 ms) per op
            tensor = jnp.asarray(tensor)
        name = name or self.autoname(kind)
        entry = _Entry(name, kind, op, root_rank, tensor, handle,
                       postprocess)
        if self.timeline:
            self.timeline.negotiate_start(name, kind)
        try:
            self.queue.add(entry)
        except DuplicateNameError:
            self.hm.mark_done(handle, Status.aborted("duplicate name"), None)
            raise
        # Close the race with a concurrent stop(): if the loop exited
        # between the check above and queue.add, nothing will ever
        # process this entry — fail it here.
        if self._stopped.is_set():
            if self.queue.finalize(name) is not None:
                self.hm.mark_done(handle, Status.aborted(
                    self._error or
                    "Horovod-TPU runtime has been shut down.",
                    self._error_class), None)
        # Wake the loop: a single op shouldn't pay the full cycle-time
        # sleep in dispatch latency (the cycle still bounds how often
        # negotiation rounds run under sustained load, the reference's
        # batching rationale, operations.cc:550-560).
        self._wake.set()

    def flush(self, timeout: float = 600.0) -> None:
        deadline = time.monotonic() + timeout
        while self.queue.outstanding() and time.monotonic() < deadline:
            time.sleep(0.001)

    def join(self) -> int:
        """Block until every rank joins (reference semantics §5.3)."""
        self._join_done.clear()
        self._join_requested.set()
        self._wake.set()
        self._join_done.wait()
        return self._join_result

    def stop(self) -> None:
        self._stop_requested.set()
        self._wake.set()
        self._thread.join(timeout=30)
        if hasattr(self.controller, "close"):
            self.controller.close()  # heartbeat publisher + transport
        if self.timeline:
            self.timeline.close()
        # profiler closed by basics.shutdown() (it owns the bridge)

    # -- background loop ---------------------------------------------------

    def _run(self) -> None:
        while True:
            # Re-read each cycle: autotune retunes it at runtime
            # (reference ParameterManager owns CycleTimeMs the same way).
            cycle_s = _config.get("cycle_time_ms") / 1000.0
            t0 = time.monotonic()
            if self.timeline and _config.get("timeline_mark_cycles"):
                self.timeline.mark_cycle()
            try:
                stop = self._run_cycle()
            except RanksDownError as exc:
                # Coordinated abort: peers are gone.  Every pending and
                # future handle fails with the diagnosable error (dead
                # ranks, round, staleness) instead of a generic
                # shutdown message or a 600 s hang.  The flight ring
                # dumps BEFORE handles fail: a survivor that catches
                # RanksDownError and os._exit()s immediately must still
                # find its dump on disk.
                _log.error(f"coordinated abort: {exc}", rank=self.rank)
                self._error = str(exc)
                self._error_class = RanksDownError
                # Ring dump first (cheap local file IO), handle failure
                # second, KV metrics flush LAST: the publish retries
                # with backoff against a possibly-dead store, and that
                # wait must not keep training threads blocked in
                # HandleManager.wait past the abort.
                _flight.dump_on_failure("ranks_down", flush_metrics=False)
                self._dumped_flight = True
                self._fail_outstanding()
                _flight.flush_terminal_metrics()
                stop = True
            except Exception as exc:  # never kill the loop silently
                _log.error(f"background loop error: {exc!r}", rank=self.rank)
                self._error = f"Horovod-TPU background failure: {exc!r}"
                _flight.dump_on_failure("background_failure",
                                        flush_metrics=False)
                self._dumped_flight = True
                self._fail_outstanding()
                _flight.flush_terminal_metrics()
                stop = True
            if stop:
                break
            elapsed = time.monotonic() - t0
            if elapsed < cycle_s:
                self._wake.wait(cycle_s - elapsed)
            self._wake.clear()
        self._stopped.set()
        self._fail_outstanding()
        if self._error:
            # A coordinated abort / background failure usually ends the
            # process before anyone calls stop(): flush and join the
            # timeline writer NOW so the dying rank's trace isn't
            # truncated mid-record (close() is idempotent — a later
            # stop()/shutdown() is a no-op), dump the flight-recorder
            # ring (the per-rank postmortem the trace merge tool
            # reads), and push one terminal KV metrics snapshot so the
            # launcher aggregate sees the abort counters instead of
            # the last periodic publish.
            if self.timeline:
                try:
                    self.timeline.close()
                except Exception:
                    pass
            if not self._dumped_flight:
                # The one _error path with no exception: a
                # coordinator-initiated stop (error ResponseList, e.g.
                # the round-0 handshake mismatch) — the except-branch
                # dumps already covered the abort/failure paths.
                _flight.dump_on_failure("coordinated_stop")
        if self._join_requested.is_set():
            self._join_done.set()

    def _run_cycle(self) -> bool:
        pending = self.queue.pop_pending()
        joined = self._join_requested.is_set()
        shutdown = self._stop_requested.is_set()
        have_work = bool(pending) or joined or shutdown
        ctl = self.controller
        if hasattr(ctl, "should_participate"):
            # Outstanding-but-unresolved entries (ours, or — on the
            # coordinator — another rank's half-arrived negotiation)
            # keep rounds running every cycle, like the reference's
            # unconditional ComputeResponseList: that is what lets the
            # stall inspector observe a rank that never shows up.
            waiting = bool(self.queue.outstanding()) or bool(
                getattr(ctl, "coordinator", None)
                and (ctl.coordinator.table.entries
                     or ctl.coordinator.joined))
            if not ctl.should_participate(have_work or waiting):
                return False
            if have_work or waiting:
                ctl.kick()
        elif not have_work and not self.queue.outstanding():
            return False

        requests = [Request(e.name, e.kind, e.op, dtype_code(e.tensor.dtype),
                            tuple(e.tensor.shape), e.root_rank)
                    for e in pending]
        tune, self._pending_tune = self._pending_tune, None
        neg_t0 = time.perf_counter()
        result = ctl.negotiate(requests, joined, shutdown, tune=tune)
        _M_NEG_LAT.observe(time.perf_counter() - neg_t0)
        _M_RESP_SIZE.observe(len(result.responses))
        fast = getattr(ctl, "fast_rounds", None)
        if fast is not None:
            _M_FAST_ROUNDS.set(fast)
        if result.should_stop and self._error is None and not shutdown:
            # A coordinator-initiated stop (e.g. the round-0 cfg
            # handshake mismatch) must surface its reason on EVERY
            # outstanding/late handle, not just the names already
            # negotiated — otherwise racing enqueues die with a generic
            # "runtime has been shut down".
            for resp in result.responses:
                if resp.kind == "error" and resp.error:
                    self._error = resp.error
                    if resp.error.startswith(RANKS_DOWN_PREFIX):
                        self._error_class = RanksDownError
                    break
        for resp in result.responses:
            self._execute(resp)
        if self.pm is not None:
            self._pending_tune = self.pm.tick()
            if self._pending_tune is not None and self.world == 1:
                # No wire to ride: apply directly.  Multi-process ranks
                # (rank 0 included) apply only on payload receipt so env
                # state can never diverge across ranks — a tune produced
                # on the final round is dropped everywhere alike.
                from horovod_tpu.runtime.parameter_manager import apply_params

                apply_params(self._pending_tune)
        if result.all_joined and self._join_requested.is_set():
            # Clear the flag here (not in the waiting thread) so the next
            # cycle doesn't re-mark this rank joined before the user
            # thread wakes.
            self._join_requested.clear()
            self._join_result = result.last_joined
            self._join_done.set()
        return result.should_stop

    def _fail_outstanding(self) -> None:
        msg = self._error or "Horovod-TPU runtime has been shut down."
        for entry in self.queue.drain_all():
            if entry.handle is not None:
                self.hm.mark_done(
                    entry.handle,
                    Status.aborted(msg, self._error_class), None)

    # -- response execution (the data plane) ------------------------------

    def _execute(self, resp) -> None:
        if resp.kind == "join":
            return
        if resp.kind == "error":
            exc_class = (RanksDownError if resp.error
                         and resp.error.startswith(RANKS_DOWN_PREFIX)
                         else None)
            for name in resp.names:
                entry = self.queue.finalize(name)
                if entry is not None:
                    if self.timeline:
                        self.timeline.negotiate_end(name, entry.kind)
                    self.hm.mark_done(
                        entry.handle,
                        Status.precondition(resp.error, exc_class), None)
            return

        entries = []
        dtype = dtype_from_code(resp.dtype_code)
        for name, shape in zip(resp.names, resp.shapes):
            entry = self.queue.finalize(name)
            if entry is None:
                # This rank joined: contribute zeros of the negotiated
                # shape (reference zero-fill,
                # ``tensor_queue.cc GetTensorEntriesFromResponse``).
                if resp.kind == "allgather":
                    shape = (0,) + tuple(shape[1:])
                zero = jnp.zeros(tuple(shape), dtype=dtype)
                entry = _Entry(name, resp.kind, resp.op, resp.root_rank,
                               zero, None, None)
            if self.timeline:
                self.timeline.negotiate_end(name, entry.kind)
            entries.append(entry)

        # Deterministic gradient poisoning (nan:/inf: fault rules,
        # docs/health.md): applied to the local payload BEFORE dispatch
        # so the health tap inside the negotiated program observes the
        # poison pre-reduction and the verdict names this rank.
        from horovod_tpu.runtime import faults as _faults

        rnd = int(getattr(self.controller, "round", 0) or 0)
        if _faults.data_rules():
            entries = _faults.poison_entries(entries, self.rank, rnd)
        if _config.get("health"):
            # Round marker for the eager clear hysteresis: a completed
            # clean round counts once toward HOROVOD_HEALTH_CLEAR_STEPS
            # regardless of how many fused buffers it dispatched.
            from horovod_tpu.runtime import health as _health

            _health.note_wire_round(rnd)

        wire_b = self._wire_nbytes(resp, dtype)
        logical_b = self._logical_nbytes(resp, dtype)
        if self.pm is not None:
            self.pm.record_bytes(wire_b, logical_b)
        # axis=local: ICI-scoped local-SGD inner reductions; axis=cross:
        # anything whose bytes cross slices over DCN (docs/local-sgd.md
        # — the bench's *_dcn_bytes_per_step extras read the cross
        # series, so the >= H x reduction is measured, not claimed).
        scope = _scope_of(resp)
        _M_WIRE_BYTES.inc(wire_b, kind=resp.kind,
                          axis="local" if scope == "local" else "cross")
        _M_LOGICAL_BYTES.inc(logical_b, kind=resp.kind)

        activity = f"XLA_{resp.kind.upper()}"
        if self.timeline:
            for e in entries:
                self.timeline.activity_start(e.name, activity)
            self._mark_overlap_schedule(resp, entries)
        annotate = (self.profiler.annotate(f"hvd_{resp.kind}")
                    if self.profiler else contextlib.nullcontext())
        _flight.record("dispatch", ph="B", collective=resp.kind,
                       n=len(entries), bytes=wire_b,
                       names=[e.name for e in entries[:8]])
        disp_t0 = time.perf_counter()
        try:
            with annotate:
                outs = self._dispatch(resp, entries)
            status = Status.ok()
        except Exception as exc:
            outs = [None] * len(entries)
            status = Status.unknown(
                f"Collective {resp.kind} failed: {exc!r}")
            _log.error(status.reason, rank=self.rank)
        _M_DISPATCH.inc(time.perf_counter() - disp_t0, kind=resp.kind)
        _flight.record("dispatch", ph="E", collective=resp.kind,
                       ok=status.ok_p())
        if self.timeline:
            for e in entries:
                self.timeline.activity_end(e.name, activity)
        for entry, out in zip(entries, outs):
            if entry.handle is None:
                continue
            if status.ok_p() and entry.postprocess is not None:
                out = entry.postprocess(out)
            self.hm.mark_done(entry.handle, status, out)

    def _mark_overlap_schedule(self, resp, entries) -> None:
        """Per-bucket ``overlap/rs|compute|ag`` timeline ticks for a
        fused response riding the overlap engine, so the K-bucket
        schedule is visible in the Chrome trace next to the response's
        negotiation/activity rows.  Ticks record issue order (the
        schedule is one XLA program); device-side bucket durations live
        in the profiler's ``hvd_overlap_*`` named scopes
        (docs/overlap.md)."""
        if resp.kind not in ("allreduce", "reducescatter") or \
                resp.op == _exec._ADASUM or self.world <= 1:
            return
        from horovod_tpu.ops import overlap as _ovl

        if not _ovl.enabled():
            return
        if resp.kind == "reducescatter":
            # The rs wire pads each tensor's LEADING dim to the world
            # size (ops/collectives.grouped_reducescatter), so the
            # per-rank bucket space is the sum of ceil(d0/n) rows per
            # tensor — padding the flat total is only right for
            # allreduce and would mislabel the very schedule these
            # events exist to visualize.
            shard = sum(-(-int(s[0]) // self.world)
                        * (int(np.prod(s[1:])) if len(s) > 1 else 1)
                        for s in resp.shapes)
        else:
            total = sum(int(np.prod(s)) if s else 1 for s in resp.shapes)
            shard = (total + (-total) % self.world) // self.world
        name = entries[0].name
        for b, (s, e) in enumerate(_ovl.bucket_bounds(shard)):
            for phase in ("rs", "compute", "ag"):
                self.timeline.overlap_phase(name, b, phase,
                                            (e - s) * self.world)

    @staticmethod
    def _logical_nbytes(resp, dtype) -> int:
        """Uncompressed payload bytes of a response — the denominator
        of the wire/logical compression ratio in the metrics plane."""
        if resp.kind == "allgather" and resp.first_dims:
            row = (tensor_nbytes(tuple(resp.shapes[0][1:]), dtype)
                   if len(resp.shapes[0]) > 1 else dtype.itemsize)
            return sum(int(d) for d in resp.first_dims) * row
        return sum(tensor_nbytes(s, dtype) for s in resp.shapes)

    def _wire_nbytes(self, resp, dtype) -> int:
        """Bytes this response actually moves on the wire, accounting
        for the compression knobs (``HOROVOD_COMPRESSION`` and the
        per-bucket ``HOROVOD_BUCKET_COMPRESSION`` vector) inside the
        allreduce/reducescatter programs — the autotuner scores
        throughput per wire byte, and the
        ``hvd_data_wire_bytes_total``/``hvd_data_logical_bytes_total``
        ratio is the achieved-compression metric, so int4's packed
        half-bytes and topk's ``k * (index + value)`` payloads must be
        counted as what they are, not as dense element-width payloads.
        Allgather counts the gathered payload (sum of every rank's
        negotiated rows), not one rank's submission: a reduce-scatter
        + allgather round trip (the sharded optimizer's wire pattern)
        then scores the same bytes an allreduce of the full buffer
        would."""
        import numpy as _np

        if resp.kind == "allgather" and resp.first_dims:
            row = (tensor_nbytes(tuple(resp.shapes[0][1:]), dtype)
                   if len(resp.shapes[0]) > 1 else dtype.itemsize)
            return sum(int(d) for d in resp.first_dims) * row
        nbytes = sum(tensor_nbytes(s, dtype) for s in resp.shapes)
        # Adasum programs never compress (xla_exec builds them with
        # comp=none): count their full-precision bytes.  Local-SGD
        # inner reductions (scope=local) are full precision on ICI by
        # contract, so they count dense too.
        scope = _scope_of(resp)
        if resp.kind not in ("allreduce", "reducescatter") \
                or resp.op == _exec._ADASUM or scope == "local" or \
                not jnp.issubdtype(_np.dtype(dtype), jnp.floating):
            return nbytes
        from horovod_tpu.ops import compression as _compression

        itemsize = _np.dtype(dtype).itemsize
        n_elems = nbytes // itemsize
        if scope == "cross":
            # The pseudo-gradient hop rides its own wire mode
            # (HOROVOD_LOCAL_SGD_COMPRESSION, inheriting
            # HOROVOD_COMPRESSION), never the per-bucket vector.
            ls = _exec.local_sgd_cfg()
            modes = [ls[3]] if ls is not None else ["none"]
        else:
            modes = _compression.effective_bucket_modes()
        return _compression.fused_wire_bytes(
            n_elems, itemsize, modes,
            block=max(1, int(_config.get("quant_block_size"))),
            ratio=float(_config.get("topk_ratio")),
            world=max(self.world, 1))

    def _dispatch(self, resp, entries):
        if resp.kind == "allreduce":
            return _exec.fused_allreduce([e.tensor for e in entries],
                                         resp.op, scope=_scope_of(resp))
        if resp.kind == "broadcast":
            return _exec.fused_broadcast([e.tensor for e in entries],
                                         resp.root_rank)
        if resp.kind == "allgather":
            sizes = list(resp.first_dims) or None
            return [_exec.allgather(e.tensor, sizes=sizes)
                    for e in entries]
        if resp.kind == "alltoall":
            return [_exec.alltoall(e.tensor) for e in entries]
        if resp.kind == "reducescatter":
            return [_exec.reducescatter(e.tensor, resp.op)
                    for e in entries]
        raise RuntimeError(f"unknown response kind {resp.kind}")
