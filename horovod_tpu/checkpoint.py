"""Checkpoint / resume helpers.

The reference has no core checkpoint subsystem — the documented
convention is rank-0-only saving plus ``broadcast_parameters`` /
``broadcast_optimizer_state`` / ``broadcast_object`` to restore and
resynchronize (``README.rst:197-244``, ``torch/__init__.py:451-647``);
its Spark estimators layer per-run-id store checkpoints on top
(``spark/common/store.py:83-95``).  This module packages both patterns
as a host-side pickle snapshot store:

* :func:`save` — rank-0-gated pytree save (params/opt_state/step/meta);
* :func:`restore` — load on every rank (or rank 0 + :func:`resync`);
* :func:`resync` — broadcast a restored pytree from rank 0 so all ranks
  start bit-identical (the reference's restore idiom);
* :func:`latest_step` — resume discovery;
* :func:`latest_healthy` / ``restore(healthy_only=True)`` — rollback
  discovery over the last-K retention ring (``HOROVOD_CHECKPOINT_KEEP``)
  with the health verdict stamped in each DONE marker
  (docs/autopilot.md).

Storage is a host-side pytree pickle snapshot.  A new step dir is
staged under a ``.tmp`` name and moved into place with ``os.replace``;
overwriting an existing step renames the old dir aside first, so no
crash point destroys the previous checkpoint before the new one is in
place (the ``.old`` dir is removed only after the swap).  orbax — which
coordinates *all* jax processes per save and would deadlock a
rank-0-gated write — is deliberately not in this path; for
fully-sharded in-step checkpointing of giant models use orbax directly
with every rank participating.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import pickle
import time

import numpy as np

from horovod_tpu.common import basics as _basics
from horovod_tpu.common import config as _config
from horovod_tpu.common import logging as _log
from horovod_tpu.common.types import HorovodTpuError

_FILE = "tree.pkl"
_SHARD_META = "shard_meta.json"
_DONE = "DONE"  # atomic completeness marker; see latest_complete()
_MANIFEST = "MANIFEST.json"  # per-file integrity stamps; see verify_snapshot()


@contextlib.contextmanager
def _goodput_span():
    """Attribute save/restore wall to the goodput ledger's
    ``checkpoint`` phase (docs/goodput.md).  Advisory — a ledger
    failure must never cost a checkpoint."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        try:
            from horovod_tpu.perf import goodput as _goodput

            _goodput.observe("checkpoint", time.perf_counter() - t0)
        except Exception:
            pass


def _world() -> tuple[int, int]:
    """(rank, size) — 0/1 before init so rank-0 tooling can still read
    checkpoints."""
    st = _basics.state()
    return (st.rank, st.size) if st.initialized else (0, 1)


def _dp_size() -> int:
    """dp-scoped shard count stamped into ``shard_meta.json``: the
    named mesh's dp extent when one is configured (shard layouts follow
    it, docs/mesh.md), else the flat world size.  Restore validates
    against the SAME resolution, so a mesh job refuses a flat-world
    snapshot of a different shard count and vice versa."""
    from horovod_tpu.parallel import mesh as _pmesh

    dp = _pmesh.data_parallel_size()
    if dp is not None:
        return int(dp)
    return _world()[1]


def _zero_stage() -> int:
    """Knob-resolved ZeRO stage (the restore side's expectation; the
    save side stamps from tree CONTENT, see :func:`_tree_zero_stage` —
    a stage-3 snapshot's tree holds shard-resident ``Zero3Params``, a
    lower stage's holds full parameter replicas, and restoring one as
    the other silently corrupts the run)."""
    from horovod_tpu.optim.distributed import _resolve_zero_stage

    return int(_resolve_zero_stage(None, None))


def _tree_zero_stage(tree) -> int:
    """Stage stamped into ``shard_meta.json``, from tree CONTENT: 3
    whenever the tree actually holds shard-resident params (robust for
    jobs that pass ``zero_stage=`` as an explicit optimizer argument
    with the env knob unset), else the knob-resolved stage capped at 2
    — a zp-free tree (e.g. sharded optimizer state committed alone by
    a stage-3 job) is layout-identical across stages 1-3 and must stay
    restorable by any of them."""
    from horovod_tpu.optim.distributed import (_contains_zero3,
                                               _is_host_zero3)
    import jax

    has_zp = _contains_zero3(tree) or any(
        _is_host_zero3(l) for l in
        jax.tree_util.tree_leaves(tree, is_leaf=_is_host_zero3))
    if has_zp:
        return 3
    return min(_zero_stage(), 2)


def save(path: str, tree, step: int, *, all_ranks: bool = False,
         verdict: str | None = None) -> str:
    """Save ``tree`` under ``path/step_<N>``.  Only rank 0 writes unless
    ``all_ranks`` (per-rank sharded state, e.g. the ZeRO-1 sharded
    optimizer's shard-local moments) — the reference's rank-0
    convention (``README.rst:197-244``).  ``all_ranks`` snapshots stamp
    a ``shard_meta.json`` sidecar with (rank, world_size) so
    :func:`restore` can refuse a world-size change instead of silently
    handing rank ``r`` a shard that belongs to a different layout.

    ``verdict`` (``"healthy"`` / ``"poisoned"``) is the health plane's
    judgment of the training state at save time, stamped into the DONE
    marker; :func:`latest_healthy` is the rollback primitive that reads
    it back (docs/autopilot.md).  ``None`` stamps nothing — and an
    absent verdict counts as healthy on the read side, so pre-ring
    snapshots stay eligible."""
    with _goodput_span():
        return _save(path, tree, step, all_ranks=all_ranks,
                     verdict=verdict)


def _save(path: str, tree, step: int, *, all_ranks: bool = False,
          verdict: str | None = None) -> str:
    rank, size = _world()
    if not all_ranks:
        # A rank-0-only snapshot of shard-resident (Zero3Params) state
        # would silently persist only rank 0's 1/world segment — every
        # later restore hands all ranks the wrong 7/8ths of the model.
        from horovod_tpu.optim.distributed import _contains_zero3

        if _contains_zero3(tree):
            raise HorovodTpuError(
                "checkpoint.save(all_ranks=False) on zero_stage=3 "
                "shard-resident params (Zero3Params): rank 0 holds "
                "only its 1/world segment, so a single-writer "
                "snapshot cannot capture the model. Use "
                "save(..., all_ranks=True) (each rank writes its "
                "shard) or snapshot the world-independent full tree "
                "via params_to_host first (docs/zero.md).")
    suffix = (f"step_{step}" if not all_ranks
              else os.path.join(f"step_{step}", f"rank_{rank}"))
    target = os.path.join(os.path.abspath(path), suffix)
    if not all_ranks and rank != 0:
        return target
    host = _to_host(tree)
    if all_ranks:
        # Overwriting a previously-complete step: the old step-level
        # DONE marker must fall BEFORE any rank replaces its shard dir,
        # or a crash mid-overwrite would leave mixed-generation shards
        # that latest_complete still vouches for.  Every rank attempts
        # the unlink (idempotent); the post-barrier stamp below
        # re-marks the step only once every new shard has landed.
        try:
            os.remove(os.path.join(os.path.abspath(path),
                                   f"step_{step}", _DONE))
        except OSError:
            pass
    tmp = target + f".tmp.{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with open(os.path.join(tmp, _FILE), "wb") as f:
        pickle.dump(host, f, protocol=pickle.HIGHEST_PROTOCOL)
    if all_ranks:
        with open(os.path.join(tmp, _SHARD_META), "w") as f:
            json.dump({"rank": rank, "world_size": size,
                       "dp_size": _dp_size(),
                       "zero_stage": _tree_zero_stage(tree)}, f)
    else:
        # Single-writer snapshot: the dir rename below is atomic, so
        # the DONE marker can ride inside it — present iff the whole
        # snapshot is.  (all_ranks snapshots get their marker from the
        # post-barrier stamp at the bottom: each rank dir landing
        # independently is exactly the torn state DONE exists to veto.)
        done = {"step": step, "world_size": size}
        if verdict is not None:
            done["verdict"] = verdict
        with open(os.path.join(tmp, _DONE), "w") as f:
            json.dump(done, f)
    # Integrity manifest, stamped INSIDE the staging dir so it rides
    # the atomic rename with the data it vouches for: per-file SHA-256
    # + size of every data file.  DONE is excluded — mark_complete may
    # legitimately re-stamp it (verdicts, external writers) after the
    # manifest is sealed.
    _write_manifest(tmp, step)
    olds = []
    for _ in range(8):  # bounded: racing recoverers can re-adopt at most
        # Rename aside instead of rmtree-before-replace: a crash
        # between the two renames leaves the previous data intact under
        # the .old name; an rmtree-first window would destroy it.
        # Uniquified so a stale .old from an earlier failed cleanup
        # can't make the rename raise ENOTEMPTY forever after; looped
        # because a concurrent latest_step() may adopt the .old dir
        # back to the step name between our two renames.
        if os.path.isdir(target):
            old = target + f".old.{os.getpid()}.{len(olds)}"
            while os.path.exists(old):
                old += "x"
            os.replace(target, old)
            olds.append(old)
        try:
            os.replace(tmp, target)
            break
        except OSError:
            continue
    else:
        raise OSError(f"could not move checkpoint into place at {target} "
                      "(concurrent recoverers kept re-adopting the old "
                      "step dir)")
    import shutil

    for old in olds:
        shutil.rmtree(old, ignore_errors=True)
    if all_ranks:
        # Ring-buddy shard replication (HOROVOD_CHECKPOINT_REPLICAS)
        # BEFORE the completeness stamp: a step vouched for by DONE
        # must already hold its replicas, or the durability guarantee
        # would have a window exactly when it matters (host loss
        # mid-save).
        _replicate_shards(os.path.abspath(path), step, target, rank,
                          size)
        # The step is complete only once EVERY rank's shard landed:
        # barrier, then rank 0 stamps the step-level DONE marker.  A
        # crash before the stamp leaves the step discoverable by
        # latest_step (debugging) but invisible to latest_complete
        # (restart discovery) — torn snapshots never get resumed.
        if _basics.state().initialized and size > 1:
            from horovod_tpu.ops import eager as _eager

            _eager.barrier()
        if rank == 0:
            mark_complete(path, step, verdict=verdict)
    if rank == 0:
        _prune_ring(os.path.abspath(path), step)
    return target


def mark_complete(path: str, step: int,
                  verdict: str | None = None) -> str:
    """Atomically stamp ``path/step_<N>`` as complete (``DONE`` marker
    written via tmp-file + rename).  :func:`save` calls this itself;
    exposed for external writers (e.g. orbax flows) that want their
    snapshots visible to the launcher's restart discovery.  ``verdict``
    records the health judgment at save time (see :func:`save`)."""
    rank, size = _world()
    step_dir = os.path.join(os.path.abspath(path), f"step_{step}")
    marker = os.path.join(step_dir, _DONE)
    tmp = marker + f".tmp.{os.getpid()}"
    done = {"step": step, "world_size": size, "rank": rank}
    if verdict is not None:
        done["verdict"] = verdict
    with open(tmp, "w") as f:
        json.dump(done, f)
    os.replace(tmp, marker)
    return marker


# ---------------------------------------------------------------------------
# Integrity manifests, quarantine, ring-buddy replication
# (docs/checkpoint.md — the durability half of the preemption plane)
# ---------------------------------------------------------------------------


def _verify_enabled() -> bool:
    try:
        return bool(_config.get("checkpoint_verify"))
    except Exception:
        return True


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _write_manifest(dirpath: str, step: int) -> None:
    """Stamp ``MANIFEST.json`` (per-file SHA-256 + size) over the data
    files in ``dirpath``.  DONE is excluded (re-stampable, see _save);
    the manifest cannot hash itself."""
    files = {}
    for name in sorted(os.listdir(dirpath)):
        if name in (_DONE, _MANIFEST):
            continue
        p = os.path.join(dirpath, name)
        if os.path.isfile(p):
            files[name] = {"sha256": _sha256(p),
                           "size": os.path.getsize(p)}
    with open(os.path.join(dirpath, _MANIFEST), "w") as f:
        json.dump({"step": int(step), "files": files}, f, sort_keys=True)


def _verify_dir(dirpath: str) -> list[str] | None:
    """Check ``dirpath`` against its manifest.  ``None`` = no manifest
    (a pre-manifest snapshot — the caller decides whether that warns or
    fails); ``[]`` = verified; else the list of problems."""
    manifest = os.path.join(dirpath, _MANIFEST)
    if not os.path.exists(manifest):
        return None
    try:
        with open(manifest) as f:
            man = json.load(f)
    except (OSError, ValueError) as exc:
        return [f"{_MANIFEST}: unreadable ({exc})"]
    problems = []
    for name, rec in sorted((man.get("files") or {}).items()):
        p = os.path.join(dirpath, name)
        if not os.path.isfile(p):
            problems.append(f"{name}: missing")
            continue
        size = os.path.getsize(p)
        if int(rec.get("size", -1)) != size:
            problems.append(
                f"{name}: size {size} != recorded {rec.get('size')}")
            continue
        if _sha256(p) != rec.get("sha256"):
            problems.append(f"{name}: sha256 mismatch")
    return problems


def verify_snapshot(path: str, step: int) -> bool:
    """Integrity-check ``step_<N>`` against its ``MANIFEST.json``
    stamps (the step dir itself plus every ``rank_<r>`` shard and
    ``rep_<o>_<h>`` replica).  Corruption logs loudly and returns
    False.  A snapshot with NO manifests anywhere (saved before
    manifest stamping existed) warns and passes — pre-manifest
    backward compatibility; see docs/checkpoint.md."""
    step_dir = os.path.join(os.path.abspath(path), f"step_{step}")
    if not os.path.isdir(step_dir):
        return False
    dirs = [step_dir]
    for d in sorted(os.listdir(step_dir)):
        full = os.path.join(step_dir, d)
        if os.path.isdir(full) and (d.startswith("rank_")
                                    or d.startswith("rep_")) \
                and ".corrupt" not in d and ".tmp." not in d \
                and ".old." not in d:
            dirs.append(full)
    results = {d: _verify_dir(d) for d in dirs}
    bad = {d: p for d, p in results.items() if p}
    if bad:
        for d, p in bad.items():
            _log.error(
                f"checkpoint: integrity verification FAILED for {d}: "
                f"{'; '.join(p[:4])}")
        return False
    if all(p is None for p in results.values()):
        _log.warning(
            f"checkpoint: step_{step} under {path} predates integrity "
            "manifests; accepting unverified (pre-manifest compat, "
            "docs/checkpoint.md)")
    return True


def _quarantine(path: str, step: int, why: str) -> None:
    """Set a corrupt snapshot aside as ``step_<N>.corrupt`` — the name
    fails every discovery filter, so it can never be restored, while
    the bytes stay on disk for the postmortem.  Loud by design."""
    step_dir = os.path.join(os.path.abspath(path), f"step_{step}")
    dst = step_dir + ".corrupt"
    while os.path.exists(dst):
        dst += "x"
    try:
        os.replace(step_dir, dst)
    except OSError:
        return
    _log.error(
        f"checkpoint: QUARANTINED corrupt snapshot step_{step} -> "
        f"{os.path.basename(dst)} ({why}); falling back to the next "
        "complete snapshot")
    try:
        from horovod_tpu.runtime import flight as _flight

        _flight.record("checkpoint", event="quarantine", step=int(step),
                       why=why)
    except Exception:
        pass
    try:
        from horovod_tpu.runtime import metrics as _metrics

        _metrics.counter(
            "hvd_checkpoint_corrupt_total",
            "Snapshots quarantined after failing manifest "
            "verification (docs/checkpoint.md).").inc()
    except Exception:
        pass


def _replicate_shards(path: str, step: int, shard_dir: str, rank: int,
                      size: int) -> None:
    """Ring-buddy replication of ``all_ranks`` shard dirs
    (``HOROVOD_CHECKPOINT_REPLICAS`` total copies, default 2): every
    rank broadcasts its landed shard's file payloads in turn, and the
    R-1 ring buddies (``(owner + k) % size``) write verbatim copies
    under ``step_<N>/rep_<owner>_<holder>/`` — on a per-host storage
    layout the buddy's host now holds the shard, so one host loss
    never takes the only copy of ZeRO shard-local state with it.
    Restore prefers the local ``rank_<r>`` dir and falls back to any
    verified replica.  Cost: one broadcast_object per owner per save
    (O(world) collectives); set the knob to 0/1 to disable."""
    try:
        replicas = int(_config.get("checkpoint_replicas"))
    except (TypeError, ValueError):
        replicas = 0
    if replicas <= 1 or size <= 1 or not _basics.state().initialized:
        return
    from horovod_tpu.optim.distributed import broadcast_object

    replicas = min(replicas, size)
    payload = {}
    for name in sorted(os.listdir(shard_dir)):
        p = os.path.join(shard_dir, name)
        if os.path.isfile(p):
            with open(p, "rb") as f:
                payload[name] = f.read()
    step_dir = os.path.join(path, f"step_{step}")
    import shutil

    for owner in range(size):
        blob = broadcast_object(payload if rank == owner else None,
                                root_rank=owner,
                                name="checkpoint.replicate")
        holders = {(owner + k) % size for k in range(1, replicas)}
        if rank not in holders or rank == owner or not blob:
            continue
        rep = os.path.join(step_dir, f"rep_{owner}_{rank}")
        tmp = rep + f".tmp.{os.getpid()}"
        os.makedirs(tmp, exist_ok=True)
        for name, data in blob.items():
            with open(os.path.join(tmp, name), "wb") as f:
                f.write(data)
        if os.path.isdir(rep):
            shutil.rmtree(rep, ignore_errors=True)
        os.replace(tmp, rep)


def _find_replica(step_dir: str, rank: int, verify: bool) -> str | None:
    """Newest-holder verified replica dir for ``rank``'s shard, or
    None."""
    try:
        entries = sorted(os.listdir(step_dir))
    except OSError:
        return None
    for d in entries:
        parts = d.split("_")
        if len(parts) != 3 or parts[0] != "rep" \
                or parts[1] != str(rank) or not parts[2].isdigit():
            continue
        full = os.path.join(step_dir, d)
        if not os.path.isdir(full):
            continue
        if verify and _verify_dir(full):
            _log.error(
                f"checkpoint: replica {full} failed verification; "
                "trying the next holder")
            continue
        return full
    return None


def _resolve_shard_source(path: str, step: int, step_dir: str,
                          rank: int) -> str:
    """Shard dir an ``all_ranks`` restore should read for ``rank``:
    the local ``rank_<r>`` copy when it verifies, else any verified
    ring-buddy replica (loudly — a replica restore means a host lost
    its tree).  A corrupt local shard is set aside first so nothing
    can silently restore it later."""
    primary = os.path.join(step_dir, f"rank_{rank}")
    verify = _verify_enabled()
    if os.path.isdir(primary):
        problems = _verify_dir(primary) if verify else []
        if problems is None:
            _log.warning(
                f"checkpoint: shard {primary} predates integrity "
                "manifests; restoring unverified (pre-manifest compat)")
            return primary
        if not problems:
            return primary
        aside = primary + ".corrupt"
        while os.path.exists(aside):
            aside += "x"
        try:
            os.replace(primary, aside)
        except OSError:
            pass
        _log.error(
            f"checkpoint: QUARANTINED corrupt shard rank_{rank} of "
            f"step_{step} ({'; '.join(problems[:4])}); falling back "
            "to a ring-buddy replica")
        try:
            from horovod_tpu.runtime import flight as _flight

            _flight.record("checkpoint", event="shard_quarantine",
                           step=int(step), rank=int(rank),
                           why="; ".join(problems[:4]))
        except Exception:
            pass
        try:
            from horovod_tpu.runtime import metrics as _metrics

            _metrics.counter(
                "hvd_checkpoint_corrupt_total",
                "Snapshots quarantined after failing manifest "
                "verification (docs/checkpoint.md).").inc()
        except Exception:
            pass
    rep = _find_replica(step_dir, rank, verify)
    if rep is None:
        raise HorovodTpuError(
            f"sharded checkpoint step_{step} under {path}: rank "
            f"{rank}'s shard is missing or corrupt and no verified "
            "ring-buddy replica exists (HOROVOD_CHECKPOINT_REPLICAS "
            "was <= 1 at save time, or every holder is gone too). "
            "The elastic re-shard path — restoring the full host-form "
            "snapshot at the new world size — is the remaining "
            "fallback; see docs/checkpoint.md.")
    _log.warning(
        f"checkpoint: restoring rank {rank}'s shard of step_{step} "
        f"from ring-buddy replica {os.path.basename(rep)} — the local "
        "copy was missing or corrupt (docs/checkpoint.md)")
    try:
        from horovod_tpu.runtime import flight as _flight

        _flight.record("checkpoint", event="replica_restore",
                       step=int(step), rank=int(rank),
                       replica=os.path.basename(rep))
    except Exception:
        pass
    try:
        from horovod_tpu.runtime import metrics as _metrics

        _metrics.counter(
            "hvd_checkpoint_replica_restores_total",
            "Shard restores served from a ring-buddy replica instead "
            "of the owner's copy (docs/checkpoint.md).").inc()
    except Exception:
        pass
    return rep


def _complete_steps(path: str) -> list[int]:
    """All complete (DONE-marked) steps under ``path``, sorted."""
    if not os.path.isdir(path):
        return []
    return sorted(
        int(d.split("_", 1)[1]) for d in os.listdir(path)
        if d.startswith("step_") and d.split("_", 1)[1].isdigit()
        and os.path.exists(os.path.join(path, d, _DONE)))


def _prune_ring(path: str, current_step: int) -> None:
    """Last-K retention (``HOROVOD_CHECKPOINT_KEEP``): after a save,
    drop complete steps beyond the newest K — but never the step just
    written, and never incomplete dirs (a torn ``all_ranks`` save mid-
    flight on another rank is not ours to delete).  Advisory: a prune
    failure must never fail the save that triggered it."""
    try:
        keep = int(_config.get("checkpoint_keep"))
    except (TypeError, ValueError):
        keep = 0
    depth = len(_complete_steps(path))
    if keep > 0:
        import shutil

        steps = _complete_steps(path)
        for s in steps[:-keep] if len(steps) > keep else []:
            if s == current_step:
                continue
            shutil.rmtree(os.path.join(path, f"step_{s}"),
                          ignore_errors=True)
        depth = len(_complete_steps(path))
    try:
        from horovod_tpu.runtime import metrics as _metrics

        _metrics.gauge(
            "hvd_checkpoint_ring_depth",
            "Complete snapshots currently retained in the checkpoint "
            "ring (docs/autopilot.md)").set(depth)
    except Exception:
        pass


def verdict_of(path: str, step: int) -> str | None:
    """Health verdict stamped in ``step``'s DONE marker, or None when
    the snapshot is incomplete or predates verdict stamping."""
    marker = os.path.join(os.path.abspath(path), f"step_{step}", _DONE)
    try:
        with open(marker) as f:
            return json.load(f).get("verdict")
    except (OSError, ValueError):
        return None


def latest_healthy(path: str) -> int | None:
    """Newest complete step whose verdict is not ``"poisoned"`` — the
    rollback target.  Snapshots without a verdict (pre-ring, or saved
    with the health plane off) count as healthy.  Under
    ``HOROVOD_CHECKPOINT_VERIFY`` (default on) candidates are also
    integrity-checked; corrupt ones are quarantined and skipped."""
    if not os.path.isdir(path):
        return None
    _recover_orphans(os.path.abspath(path))
    for s in reversed(_complete_steps(os.path.abspath(path))):
        if verdict_of(path, s) == "poisoned":
            continue
        if _verify_enabled() and not verify_snapshot(path, s):
            _quarantine(path, s, "manifest verification failed")
            continue
        return s
    return None


def is_complete(path: str, step: int) -> bool:
    return os.path.exists(os.path.join(
        os.path.abspath(path), f"step_{step}", _DONE))


def latest_complete(path: str) -> int | None:
    """Latest step whose snapshot finished completely — the restart
    discovery the launcher uses (``HOROVOD_RESTART_ATTEMPTS``).  Unlike
    :func:`latest_step`, torn snapshots (an ``all_ranks`` save some
    rank never finished, a crash before the DONE stamp) are skipped, so
    a resume can never load a half-written state.

    Under ``HOROVOD_CHECKPOINT_VERIFY`` (default on) the candidate is
    also integrity-checked against its ``MANIFEST.json``: a bit-rotted
    snapshot is quarantined (``step_<N>.corrupt``) and the next
    complete one is returned instead — DONE vetoes torn writes, the
    manifest vetoes rotted ones.  Pre-manifest snapshots (no
    ``MANIFEST.json``) still pass, with a warning, so an old
    checkpoint dir keeps resuming."""
    if not os.path.isdir(path):
        return None
    _recover_orphans(os.path.abspath(path))
    while True:
        steps = _complete_steps(os.path.abspath(path))
        if not steps:
            return None
        s = steps[-1]
        if not _verify_enabled() or verify_snapshot(path, s):
            return s
        _quarantine(path, s, "manifest verification failed")


def restore(path: str, step: int | None = None, *,
            all_ranks: bool = False, healthy_only: bool = False):
    """Load the pytree saved at ``path`` (``step=None`` → latest).

    ``all_ranks`` restores this rank's own shard and validates the
    snapshot's ``shard_meta.json``: restoring shard-local state onto a
    different world size is layout corruption (rank ``r``'s moments
    would pair with a differently-sized parameter shard), so a changed
    shard count fails with a clear error — re-shard offline or restart
    at the recorded world size.

    ``healthy_only`` with ``step=None`` targets the newest snapshot
    whose stamped health verdict is not ``"poisoned"``
    (:func:`latest_healthy`) — the rollback primitive, usable even
    with the autopilot off."""
    with _goodput_span():
        return _restore(path, step, all_ranks=all_ranks,
                        healthy_only=healthy_only)


class _CorruptSnapshot(Exception):
    """Internal: the snapshot failed verification and was quarantined;
    discovery-driven restores retry the next one."""


def _restore(path: str, step: int | None = None, *,
             all_ranks: bool = False, healthy_only: bool = False):
    explicit = step is not None
    if explicit:
        _recover_orphans(os.path.abspath(path))
    while True:
        s = step
        if s is None:
            # latest_healthy verifies + quarantines itself; latest_step
            # deliberately does not (it sees torn steps for debugging),
            # so _restore_step's own verification covers that path.
            s = latest_healthy(path) if healthy_only \
                else latest_step(path)
            if s is None:
                raise FileNotFoundError(
                    f"no {'healthy ' if healthy_only else ''}"
                    f"checkpoints under {path}")
        try:
            return _restore_step(path, s, all_ranks=all_ranks)
        except _CorruptSnapshot as exc:
            if explicit:
                raise HorovodTpuError(
                    f"checkpoint step_{s} under {path} failed "
                    f"integrity verification ({exc}) and was "
                    "quarantined as step_"
                    f"{s}.corrupt. Restore another step, or set "
                    "HOROVOD_CHECKPOINT_VERIFY=0 to load unverified "
                    "bytes at your own risk.") from None
            # discovered step: it is quarantined now, re-discover


def _restore_step(path: str, step: int, *, all_ranks: bool = False):
    rank, size = _world()
    suffix = (f"step_{step}" if not all_ranks
              else os.path.join(f"step_{step}", f"rank_{rank}"))
    target = os.path.join(os.path.abspath(path), suffix)
    if not all_ranks and _verify_enabled():
        problems = _verify_dir(target)
        if problems is None:
            _log.warning(
                f"checkpoint: step_{step} under {path} predates "
                "integrity manifests; restoring unverified "
                "(pre-manifest compat, docs/checkpoint.md)")
        elif problems:
            why = "; ".join(problems[:4])
            _quarantine(path, step, why)
            raise _CorruptSnapshot(why)
    if all_ranks:
        # Verified source resolution: the local shard when it checks
        # out, else a ring-buddy replica — BEFORE the topology
        # validation below, which must read the meta we will actually
        # load.
        target = _resolve_shard_source(
            path, step, os.path.dirname(target), rank)
    if all_ranks and _basics.state().initialized:
        # Only a live job has a real topology to validate against;
        # pre-init tooling (offline inspection / re-sharding — the
        # consumer the mismatch error points at) reads rank_0's shard
        # without tripping the placeholder (0, 1) world.
        step_dir = os.path.dirname(target)
        saved_ranks = [d for d in (os.listdir(step_dir)
                                   if os.path.isdir(step_dir) else [])
                       if d.startswith("rank_")
                       and d.split("_", 1)[1].isdigit()]
        meta_path = os.path.join(target, _SHARD_META)
        meta = None
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
        saved_world = (int(meta["world_size"]) if meta
                       else len(saved_ranks) or None)
        if saved_world is not None and saved_world != size:
            raise HorovodTpuError(
                f"sharded checkpoint at {step_dir} was saved from "
                f"world size {saved_world} but this job runs "
                f"{size} ranks; restoring would silently corrupt "
                "shard-local state (each rank holds 1/world of the "
                "fused buffers). Restart at the recorded world size "
                "or re-shard the snapshot offline.")
        saved_dp = int(meta["dp_size"]) if meta and "dp_size" in meta \
            else saved_world  # pre-mesh snapshots: shards spanned the world
        if saved_dp is not None and saved_dp != _dp_size():
            raise HorovodTpuError(
                f"sharded checkpoint at {step_dir} was saved with "
                f"{saved_dp} data-parallel shards but this job's "
                f"shard count is {_dp_size()} (ZeRO layouts follow "
                "the dp extent of the named mesh, docs/mesh.md); "
                "restoring would misassign shard-local state. Match "
                "the recorded dp extent or re-shard the snapshot "
                "offline.")
        if meta is not None and int(meta["rank"]) != rank:
            raise HorovodTpuError(
                f"sharded checkpoint dir {target} records rank "
                f"{meta['rank']} but rank {rank} is restoring it; "
                "the per-rank layout would be misassigned.")
        saved_stage = int(meta.get("zero_stage", 0)) if meta else 0
        # One-directional stage-3 residency guard: a snapshot stamped
        # >= 3 genuinely CONTAINS Zero3Params (content-based stamp),
        # so a job explicitly configured below stage 3 must not load
        # it; the reverse (a stage-3 job loading a zp-free snapshot)
        # is layout-compatible and allowed.  Checked only when this
        # job's intent is explicit (HOROVOD_ZERO_STAGE set): a job
        # configured purely via the zero_stage= optimizer argument
        # leaves the knob empty, and refusing its own correctly
        # stamped snapshot would be a false positive.
        env_explicit = _config.is_set("zero_stage")
        if env_explicit and saved_stage >= 3 and _zero_stage() < 3:
            raise HorovodTpuError(
                f"sharded checkpoint at {step_dir} was saved under "
                f"zero_stage={saved_stage} (it holds shard-resident "
                f"Zero3Params) but this job resolves "
                f"zero_stage={_zero_stage()}, which expects full "
                "parameter replicas — restoring across that boundary "
                "corrupts the run. Set HOROVOD_ZERO_STAGE=3 to match "
                "the snapshot (zp-free snapshots from stages 1 and 2 "
                "interchange freely at any stage).")
    with open(os.path.join(target, _FILE), "rb") as f:
        return pickle.load(f)


def _recover_orphans(path: str) -> None:
    """Adopt ``step_N.old.*`` dirs whose ``step_N`` is missing: a crash
    between save()'s two renames leaves the previous checkpoint only
    under the aside name — it must stay discoverable for resume."""
    try:
        entries = os.listdir(path)
    except OSError:
        return
    present = {d for d in entries
               if d.startswith("step_") and d.split("_", 1)[1].isdigit()}
    orphans: dict[str, list[str]] = {}
    for d in entries:
        stem = d.split(".old.", 1)[0]
        if ".old." in d and stem.startswith("step_") \
                and stem.split("_", 1)[1].isdigit() and stem not in present:
            orphans.setdefault(stem, []).append(d)
    for stem, cands in orphans.items():
        try:  # racing recoverers: first replace wins, ENOENT is fine
            os.replace(os.path.join(path, sorted(cands)[-1]),
                       os.path.join(path, stem))
        except OSError:
            pass


def latest_step(path: str) -> int | None:
    if not os.path.isdir(path):
        return None
    _recover_orphans(path)
    steps = [int(d.split("_", 1)[1]) for d in os.listdir(path)
             if d.startswith("step_") and d.split("_", 1)[1].isdigit()]
    return max(steps) if steps else None


def resync(tree, root_rank: int = 0):
    """Broadcast ``tree`` from ``root_rank`` so every rank resumes from
    identical state — the reference's restore-then-broadcast idiom.
    Shard-local (ZeRO-1) optimizer-state subtrees pass through
    untouched — each rank's shard is authoritative (it came from its
    own ``all_ranks`` snapshot), and a broadcast would overwrite every
    rank's moments with rank 0's segment — while everything around
    them (params, step counters, accumulation buffers) still resyncs
    from ``root_rank``."""
    from horovod_tpu.optim.distributed import broadcast_skipping_shards

    return broadcast_skipping_shards(tree, root_rank=root_rank)


def _to_host(tree):
    """Device arrays -> host numpy; everything else passes through
    unchanged.  Opaque host-side leaves (e.g. the elastic commit's
    ``_HostShardedState`` wrappers, which are not pytree nodes) must
    NOT be np.asarray'd — that wraps them in 0-d object ndarrays the
    restoring side no longer recognizes."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, tree)
