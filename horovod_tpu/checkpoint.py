"""Checkpoint / resume helpers.

The reference has no core checkpoint subsystem — the documented
convention is rank-0-only saving plus ``broadcast_parameters`` /
``broadcast_optimizer_state`` / ``broadcast_object`` to restore and
resynchronize (``README.rst:197-244``, ``torch/__init__.py:451-647``);
its Spark estimators layer per-run-id store checkpoints on top
(``spark/common/store.py:83-95``).  This module packages both patterns
as a host-side pickle snapshot store:

* :func:`save` — rank-0-gated pytree save (params/opt_state/step/meta);
* :func:`restore` — load on every rank (or rank 0 + :func:`resync`);
* :func:`resync` — broadcast a restored pytree from rank 0 so all ranks
  start bit-identical (the reference's restore idiom);
* :func:`latest_step` — resume discovery;
* :func:`latest_healthy` / ``restore(healthy_only=True)`` — rollback
  discovery over the last-K retention ring (``HOROVOD_CHECKPOINT_KEEP``)
  with the health verdict stamped in each DONE marker
  (docs/autopilot.md).

Storage is a host-side pytree pickle snapshot.  A new step dir is
staged under a ``.tmp`` name and moved into place with ``os.replace``;
overwriting an existing step renames the old dir aside first, so no
crash point destroys the previous checkpoint before the new one is in
place (the ``.old`` dir is removed only after the swap).  orbax — which
coordinates *all* jax processes per save and would deadlock a
rank-0-gated write — is deliberately not in this path; for
fully-sharded in-step checkpointing of giant models use orbax directly
with every rank participating.
"""

from __future__ import annotations

import contextlib
import json
import os
import pickle
import time

import numpy as np

from horovod_tpu.common import basics as _basics
from horovod_tpu.common import config as _config
from horovod_tpu.common.types import HorovodTpuError

_FILE = "tree.pkl"
_SHARD_META = "shard_meta.json"
_DONE = "DONE"  # atomic completeness marker; see latest_complete()


@contextlib.contextmanager
def _goodput_span():
    """Attribute save/restore wall to the goodput ledger's
    ``checkpoint`` phase (docs/goodput.md).  Advisory — a ledger
    failure must never cost a checkpoint."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        try:
            from horovod_tpu.perf import goodput as _goodput

            _goodput.observe("checkpoint", time.perf_counter() - t0)
        except Exception:
            pass


def _world() -> tuple[int, int]:
    """(rank, size) — 0/1 before init so rank-0 tooling can still read
    checkpoints."""
    st = _basics.state()
    return (st.rank, st.size) if st.initialized else (0, 1)


def _dp_size() -> int:
    """dp-scoped shard count stamped into ``shard_meta.json``: the
    named mesh's dp extent when one is configured (shard layouts follow
    it, docs/mesh.md), else the flat world size.  Restore validates
    against the SAME resolution, so a mesh job refuses a flat-world
    snapshot of a different shard count and vice versa."""
    from horovod_tpu.parallel import mesh as _pmesh

    dp = _pmesh.data_parallel_size()
    if dp is not None:
        return int(dp)
    return _world()[1]


def _zero_stage() -> int:
    """Knob-resolved ZeRO stage (the restore side's expectation; the
    save side stamps from tree CONTENT, see :func:`_tree_zero_stage` —
    a stage-3 snapshot's tree holds shard-resident ``Zero3Params``, a
    lower stage's holds full parameter replicas, and restoring one as
    the other silently corrupts the run)."""
    from horovod_tpu.optim.distributed import _resolve_zero_stage

    return int(_resolve_zero_stage(None, None))


def _tree_zero_stage(tree) -> int:
    """Stage stamped into ``shard_meta.json``, from tree CONTENT: 3
    whenever the tree actually holds shard-resident params (robust for
    jobs that pass ``zero_stage=`` as an explicit optimizer argument
    with the env knob unset), else the knob-resolved stage capped at 2
    — a zp-free tree (e.g. sharded optimizer state committed alone by
    a stage-3 job) is layout-identical across stages 1-3 and must stay
    restorable by any of them."""
    from horovod_tpu.optim.distributed import (_contains_zero3,
                                               _is_host_zero3)
    import jax

    has_zp = _contains_zero3(tree) or any(
        _is_host_zero3(l) for l in
        jax.tree_util.tree_leaves(tree, is_leaf=_is_host_zero3))
    if has_zp:
        return 3
    return min(_zero_stage(), 2)


def save(path: str, tree, step: int, *, all_ranks: bool = False,
         verdict: str | None = None) -> str:
    """Save ``tree`` under ``path/step_<N>``.  Only rank 0 writes unless
    ``all_ranks`` (per-rank sharded state, e.g. the ZeRO-1 sharded
    optimizer's shard-local moments) — the reference's rank-0
    convention (``README.rst:197-244``).  ``all_ranks`` snapshots stamp
    a ``shard_meta.json`` sidecar with (rank, world_size) so
    :func:`restore` can refuse a world-size change instead of silently
    handing rank ``r`` a shard that belongs to a different layout.

    ``verdict`` (``"healthy"`` / ``"poisoned"``) is the health plane's
    judgment of the training state at save time, stamped into the DONE
    marker; :func:`latest_healthy` is the rollback primitive that reads
    it back (docs/autopilot.md).  ``None`` stamps nothing — and an
    absent verdict counts as healthy on the read side, so pre-ring
    snapshots stay eligible."""
    with _goodput_span():
        return _save(path, tree, step, all_ranks=all_ranks,
                     verdict=verdict)


def _save(path: str, tree, step: int, *, all_ranks: bool = False,
          verdict: str | None = None) -> str:
    rank, size = _world()
    if not all_ranks:
        # A rank-0-only snapshot of shard-resident (Zero3Params) state
        # would silently persist only rank 0's 1/world segment — every
        # later restore hands all ranks the wrong 7/8ths of the model.
        from horovod_tpu.optim.distributed import _contains_zero3

        if _contains_zero3(tree):
            raise HorovodTpuError(
                "checkpoint.save(all_ranks=False) on zero_stage=3 "
                "shard-resident params (Zero3Params): rank 0 holds "
                "only its 1/world segment, so a single-writer "
                "snapshot cannot capture the model. Use "
                "save(..., all_ranks=True) (each rank writes its "
                "shard) or snapshot the world-independent full tree "
                "via params_to_host first (docs/zero.md).")
    suffix = (f"step_{step}" if not all_ranks
              else os.path.join(f"step_{step}", f"rank_{rank}"))
    target = os.path.join(os.path.abspath(path), suffix)
    if not all_ranks and rank != 0:
        return target
    host = _to_host(tree)
    if all_ranks:
        # Overwriting a previously-complete step: the old step-level
        # DONE marker must fall BEFORE any rank replaces its shard dir,
        # or a crash mid-overwrite would leave mixed-generation shards
        # that latest_complete still vouches for.  Every rank attempts
        # the unlink (idempotent); the post-barrier stamp below
        # re-marks the step only once every new shard has landed.
        try:
            os.remove(os.path.join(os.path.abspath(path),
                                   f"step_{step}", _DONE))
        except OSError:
            pass
    tmp = target + f".tmp.{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with open(os.path.join(tmp, _FILE), "wb") as f:
        pickle.dump(host, f, protocol=pickle.HIGHEST_PROTOCOL)
    if all_ranks:
        with open(os.path.join(tmp, _SHARD_META), "w") as f:
            json.dump({"rank": rank, "world_size": size,
                       "dp_size": _dp_size(),
                       "zero_stage": _tree_zero_stage(tree)}, f)
    else:
        # Single-writer snapshot: the dir rename below is atomic, so
        # the DONE marker can ride inside it — present iff the whole
        # snapshot is.  (all_ranks snapshots get their marker from the
        # post-barrier stamp at the bottom: each rank dir landing
        # independently is exactly the torn state DONE exists to veto.)
        done = {"step": step, "world_size": size}
        if verdict is not None:
            done["verdict"] = verdict
        with open(os.path.join(tmp, _DONE), "w") as f:
            json.dump(done, f)
    olds = []
    for _ in range(8):  # bounded: racing recoverers can re-adopt at most
        # Rename aside instead of rmtree-before-replace: a crash
        # between the two renames leaves the previous data intact under
        # the .old name; an rmtree-first window would destroy it.
        # Uniquified so a stale .old from an earlier failed cleanup
        # can't make the rename raise ENOTEMPTY forever after; looped
        # because a concurrent latest_step() may adopt the .old dir
        # back to the step name between our two renames.
        if os.path.isdir(target):
            old = target + f".old.{os.getpid()}.{len(olds)}"
            while os.path.exists(old):
                old += "x"
            os.replace(target, old)
            olds.append(old)
        try:
            os.replace(tmp, target)
            break
        except OSError:
            continue
    else:
        raise OSError(f"could not move checkpoint into place at {target} "
                      "(concurrent recoverers kept re-adopting the old "
                      "step dir)")
    import shutil

    for old in olds:
        shutil.rmtree(old, ignore_errors=True)
    if all_ranks:
        # The step is complete only once EVERY rank's shard landed:
        # barrier, then rank 0 stamps the step-level DONE marker.  A
        # crash before the stamp leaves the step discoverable by
        # latest_step (debugging) but invisible to latest_complete
        # (restart discovery) — torn snapshots never get resumed.
        if _basics.state().initialized and size > 1:
            from horovod_tpu.ops import eager as _eager

            _eager.barrier()
        if rank == 0:
            mark_complete(path, step, verdict=verdict)
    if rank == 0:
        _prune_ring(os.path.abspath(path), step)
    return target


def mark_complete(path: str, step: int,
                  verdict: str | None = None) -> str:
    """Atomically stamp ``path/step_<N>`` as complete (``DONE`` marker
    written via tmp-file + rename).  :func:`save` calls this itself;
    exposed for external writers (e.g. orbax flows) that want their
    snapshots visible to the launcher's restart discovery.  ``verdict``
    records the health judgment at save time (see :func:`save`)."""
    rank, size = _world()
    step_dir = os.path.join(os.path.abspath(path), f"step_{step}")
    marker = os.path.join(step_dir, _DONE)
    tmp = marker + f".tmp.{os.getpid()}"
    done = {"step": step, "world_size": size, "rank": rank}
    if verdict is not None:
        done["verdict"] = verdict
    with open(tmp, "w") as f:
        json.dump(done, f)
    os.replace(tmp, marker)
    return marker


def _complete_steps(path: str) -> list[int]:
    """All complete (DONE-marked) steps under ``path``, sorted."""
    if not os.path.isdir(path):
        return []
    return sorted(
        int(d.split("_", 1)[1]) for d in os.listdir(path)
        if d.startswith("step_") and d.split("_", 1)[1].isdigit()
        and os.path.exists(os.path.join(path, d, _DONE)))


def _prune_ring(path: str, current_step: int) -> None:
    """Last-K retention (``HOROVOD_CHECKPOINT_KEEP``): after a save,
    drop complete steps beyond the newest K — but never the step just
    written, and never incomplete dirs (a torn ``all_ranks`` save mid-
    flight on another rank is not ours to delete).  Advisory: a prune
    failure must never fail the save that triggered it."""
    try:
        keep = int(_config.get("checkpoint_keep"))
    except (TypeError, ValueError):
        keep = 0
    depth = len(_complete_steps(path))
    if keep > 0:
        import shutil

        steps = _complete_steps(path)
        for s in steps[:-keep] if len(steps) > keep else []:
            if s == current_step:
                continue
            shutil.rmtree(os.path.join(path, f"step_{s}"),
                          ignore_errors=True)
        depth = len(_complete_steps(path))
    try:
        from horovod_tpu.runtime import metrics as _metrics

        _metrics.gauge(
            "hvd_checkpoint_ring_depth",
            "Complete snapshots currently retained in the checkpoint "
            "ring (docs/autopilot.md)").set(depth)
    except Exception:
        pass


def verdict_of(path: str, step: int) -> str | None:
    """Health verdict stamped in ``step``'s DONE marker, or None when
    the snapshot is incomplete or predates verdict stamping."""
    marker = os.path.join(os.path.abspath(path), f"step_{step}", _DONE)
    try:
        with open(marker) as f:
            return json.load(f).get("verdict")
    except (OSError, ValueError):
        return None


def latest_healthy(path: str) -> int | None:
    """Newest complete step whose verdict is not ``"poisoned"`` — the
    rollback target.  Snapshots without a verdict (pre-ring, or saved
    with the health plane off) count as healthy."""
    if not os.path.isdir(path):
        return None
    _recover_orphans(os.path.abspath(path))
    for s in reversed(_complete_steps(os.path.abspath(path))):
        if verdict_of(path, s) != "poisoned":
            return s
    return None


def is_complete(path: str, step: int) -> bool:
    return os.path.exists(os.path.join(
        os.path.abspath(path), f"step_{step}", _DONE))


def latest_complete(path: str) -> int | None:
    """Latest step whose snapshot finished completely — the restart
    discovery the launcher uses (``HOROVOD_RESTART_ATTEMPTS``).  Unlike
    :func:`latest_step`, torn snapshots (an ``all_ranks`` save some
    rank never finished, a crash before the DONE stamp) are skipped, so
    a resume can never load a half-written state."""
    if not os.path.isdir(path):
        return None
    _recover_orphans(os.path.abspath(path))
    steps = [int(d.split("_", 1)[1]) for d in os.listdir(path)
             if d.startswith("step_") and d.split("_", 1)[1].isdigit()
             and os.path.exists(os.path.join(path, d, _DONE))]
    return max(steps) if steps else None


def restore(path: str, step: int | None = None, *,
            all_ranks: bool = False, healthy_only: bool = False):
    """Load the pytree saved at ``path`` (``step=None`` → latest).

    ``all_ranks`` restores this rank's own shard and validates the
    snapshot's ``shard_meta.json``: restoring shard-local state onto a
    different world size is layout corruption (rank ``r``'s moments
    would pair with a differently-sized parameter shard), so a changed
    shard count fails with a clear error — re-shard offline or restart
    at the recorded world size.

    ``healthy_only`` with ``step=None`` targets the newest snapshot
    whose stamped health verdict is not ``"poisoned"``
    (:func:`latest_healthy`) — the rollback primitive, usable even
    with the autopilot off."""
    with _goodput_span():
        return _restore(path, step, all_ranks=all_ranks,
                        healthy_only=healthy_only)


def _restore(path: str, step: int | None = None, *,
             all_ranks: bool = False, healthy_only: bool = False):
    rank, size = _world()
    if step is None:
        step = latest_healthy(path) if healthy_only else latest_step(path)
        if step is None:
            raise FileNotFoundError(
                f"no {'healthy ' if healthy_only else ''}checkpoints "
                f"under {path}")
    else:
        _recover_orphans(os.path.abspath(path))
    suffix = (f"step_{step}" if not all_ranks
              else os.path.join(f"step_{step}", f"rank_{rank}"))
    target = os.path.join(os.path.abspath(path), suffix)
    if all_ranks and _basics.state().initialized:
        # Only a live job has a real topology to validate against;
        # pre-init tooling (offline inspection / re-sharding — the
        # consumer the mismatch error points at) reads rank_0's shard
        # without tripping the placeholder (0, 1) world.
        step_dir = os.path.dirname(target)
        saved_ranks = [d for d in (os.listdir(step_dir)
                                   if os.path.isdir(step_dir) else [])
                       if d.startswith("rank_")
                       and d.split("_", 1)[1].isdigit()]
        meta_path = os.path.join(target, _SHARD_META)
        meta = None
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
        saved_world = (int(meta["world_size"]) if meta
                       else len(saved_ranks) or None)
        if saved_world is not None and saved_world != size:
            raise HorovodTpuError(
                f"sharded checkpoint at {step_dir} was saved from "
                f"world size {saved_world} but this job runs "
                f"{size} ranks; restoring would silently corrupt "
                "shard-local state (each rank holds 1/world of the "
                "fused buffers). Restart at the recorded world size "
                "or re-shard the snapshot offline.")
        saved_dp = int(meta["dp_size"]) if meta and "dp_size" in meta \
            else saved_world  # pre-mesh snapshots: shards spanned the world
        if saved_dp is not None and saved_dp != _dp_size():
            raise HorovodTpuError(
                f"sharded checkpoint at {step_dir} was saved with "
                f"{saved_dp} data-parallel shards but this job's "
                f"shard count is {_dp_size()} (ZeRO layouts follow "
                "the dp extent of the named mesh, docs/mesh.md); "
                "restoring would misassign shard-local state. Match "
                "the recorded dp extent or re-shard the snapshot "
                "offline.")
        if meta is not None and int(meta["rank"]) != rank:
            raise HorovodTpuError(
                f"sharded checkpoint dir {target} records rank "
                f"{meta['rank']} but rank {rank} is restoring it; "
                "the per-rank layout would be misassigned.")
        saved_stage = int(meta.get("zero_stage", 0)) if meta else 0
        # One-directional stage-3 residency guard: a snapshot stamped
        # >= 3 genuinely CONTAINS Zero3Params (content-based stamp),
        # so a job explicitly configured below stage 3 must not load
        # it; the reverse (a stage-3 job loading a zp-free snapshot)
        # is layout-compatible and allowed.  Checked only when this
        # job's intent is explicit (HOROVOD_ZERO_STAGE set): a job
        # configured purely via the zero_stage= optimizer argument
        # leaves the knob empty, and refusing its own correctly
        # stamped snapshot would be a false positive.
        env_explicit = _config.is_set("zero_stage")
        if env_explicit and saved_stage >= 3 and _zero_stage() < 3:
            raise HorovodTpuError(
                f"sharded checkpoint at {step_dir} was saved under "
                f"zero_stage={saved_stage} (it holds shard-resident "
                f"Zero3Params) but this job resolves "
                f"zero_stage={_zero_stage()}, which expects full "
                "parameter replicas — restoring across that boundary "
                "corrupts the run. Set HOROVOD_ZERO_STAGE=3 to match "
                "the snapshot (zp-free snapshots from stages 1 and 2 "
                "interchange freely at any stage).")
    with open(os.path.join(target, _FILE), "rb") as f:
        return pickle.load(f)


def _recover_orphans(path: str) -> None:
    """Adopt ``step_N.old.*`` dirs whose ``step_N`` is missing: a crash
    between save()'s two renames leaves the previous checkpoint only
    under the aside name — it must stay discoverable for resume."""
    try:
        entries = os.listdir(path)
    except OSError:
        return
    present = {d for d in entries
               if d.startswith("step_") and d.split("_", 1)[1].isdigit()}
    orphans: dict[str, list[str]] = {}
    for d in entries:
        stem = d.split(".old.", 1)[0]
        if ".old." in d and stem.startswith("step_") \
                and stem.split("_", 1)[1].isdigit() and stem not in present:
            orphans.setdefault(stem, []).append(d)
    for stem, cands in orphans.items():
        try:  # racing recoverers: first replace wins, ENOENT is fine
            os.replace(os.path.join(path, sorted(cands)[-1]),
                       os.path.join(path, stem))
        except OSError:
            pass


def latest_step(path: str) -> int | None:
    if not os.path.isdir(path):
        return None
    _recover_orphans(path)
    steps = [int(d.split("_", 1)[1]) for d in os.listdir(path)
             if d.startswith("step_") and d.split("_", 1)[1].isdigit()]
    return max(steps) if steps else None


def resync(tree, root_rank: int = 0):
    """Broadcast ``tree`` from ``root_rank`` so every rank resumes from
    identical state — the reference's restore-then-broadcast idiom.
    Shard-local (ZeRO-1) optimizer-state subtrees pass through
    untouched — each rank's shard is authoritative (it came from its
    own ``all_ranks`` snapshot), and a broadcast would overwrite every
    rank's moments with rank 0's segment — while everything around
    them (params, step counters, accumulation buffers) still resyncs
    from ``root_rank``."""
    from horovod_tpu.optim.distributed import broadcast_skipping_shards

    return broadcast_skipping_shards(tree, root_rank=root_rank)


def _to_host(tree):
    """Device arrays -> host numpy; everything else passes through
    unchanged.  Opaque host-side leaves (e.g. the elastic commit's
    ``_HostShardedState`` wrappers, which are not pytree nodes) must
    NOT be np.asarray'd — that wraps them in 0-d object ndarrays the
    restoring side no longer recognizes."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, tree)
