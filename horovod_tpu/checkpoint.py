"""Checkpoint / resume helpers.

The reference has no core checkpoint subsystem — the documented
convention is rank-0-only saving plus ``broadcast_parameters`` /
``broadcast_optimizer_state`` / ``broadcast_object`` to restore and
resynchronize (``README.rst:197-244``, ``torch/__init__.py:451-647``);
its Spark estimators layer per-run-id store checkpoints on top
(``spark/common/store.py:83-95``).  This module packages both patterns
as a host-side pickle snapshot store:

* :func:`save` — rank-0-gated pytree save (params/opt_state/step/meta);
* :func:`restore` — load on every rank (or rank 0 + :func:`resync`);
* :func:`resync` — broadcast a restored pytree from rank 0 so all ranks
  start bit-identical (the reference's restore idiom);
* :func:`latest_step` — resume discovery.

Storage is a host-side pytree pickle snapshot.  A new step dir is
staged under a ``.tmp`` name and moved into place with ``os.replace``;
overwriting an existing step renames the old dir aside first, so no
crash point destroys the previous checkpoint before the new one is in
place (the ``.old`` dir is removed only after the swap).  orbax — which
coordinates *all* jax processes per save and would deadlock a
rank-0-gated write — is deliberately not in this path; for
fully-sharded in-step checkpointing of giant models use orbax directly
with every rank participating.
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from horovod_tpu.common import basics as _basics

_FILE = "tree.pkl"


def save(path: str, tree, step: int, *, all_ranks: bool = False) -> str:
    """Save ``tree`` under ``path/step_<N>``.  Only rank 0 writes unless
    ``all_ranks`` (per-rank sharded state) — the reference's rank-0
    convention (``README.rst:197-244``)."""
    suffix = (f"step_{step}" if not all_ranks
              else os.path.join(f"step_{step}",
                                f"rank_{_basics.rank()}"))
    target = os.path.join(os.path.abspath(path), suffix)
    if not all_ranks and _basics.rank() != 0:
        return target
    host = _to_host(tree)
    tmp = target + f".tmp.{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with open(os.path.join(tmp, _FILE), "wb") as f:
        pickle.dump(host, f, protocol=pickle.HIGHEST_PROTOCOL)
    olds = []
    for _ in range(8):  # bounded: racing recoverers can re-adopt at most
        # Rename aside instead of rmtree-before-replace: a crash
        # between the two renames leaves the previous data intact under
        # the .old name; an rmtree-first window would destroy it.
        # Uniquified so a stale .old from an earlier failed cleanup
        # can't make the rename raise ENOTEMPTY forever after; looped
        # because a concurrent latest_step() may adopt the .old dir
        # back to the step name between our two renames.
        if os.path.isdir(target):
            old = target + f".old.{os.getpid()}.{len(olds)}"
            while os.path.exists(old):
                old += "x"
            os.replace(target, old)
            olds.append(old)
        try:
            os.replace(tmp, target)
            break
        except OSError:
            continue
    else:
        raise OSError(f"could not move checkpoint into place at {target} "
                      "(concurrent recoverers kept re-adopting the old "
                      "step dir)")
    import shutil

    for old in olds:
        shutil.rmtree(old, ignore_errors=True)
    return target


def restore(path: str, step: int | None = None, *,
            all_ranks: bool = False):
    """Load the pytree saved at ``path`` (``step=None`` → latest)."""
    if step is None:
        step = latest_step(path)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {path}")
    else:
        _recover_orphans(os.path.abspath(path))
    suffix = (f"step_{step}" if not all_ranks
              else os.path.join(f"step_{step}",
                                f"rank_{_basics.rank()}"))
    with open(os.path.join(os.path.abspath(path), suffix, _FILE),
              "rb") as f:
        return pickle.load(f)


def _recover_orphans(path: str) -> None:
    """Adopt ``step_N.old.*`` dirs whose ``step_N`` is missing: a crash
    between save()'s two renames leaves the previous checkpoint only
    under the aside name — it must stay discoverable for resume."""
    try:
        entries = os.listdir(path)
    except OSError:
        return
    present = {d for d in entries
               if d.startswith("step_") and d.split("_", 1)[1].isdigit()}
    orphans: dict[str, list[str]] = {}
    for d in entries:
        stem = d.split(".old.", 1)[0]
        if ".old." in d and stem.startswith("step_") \
                and stem.split("_", 1)[1].isdigit() and stem not in present:
            orphans.setdefault(stem, []).append(d)
    for stem, cands in orphans.items():
        try:  # racing recoverers: first replace wins, ENOENT is fine
            os.replace(os.path.join(path, sorted(cands)[-1]),
                       os.path.join(path, stem))
        except OSError:
            pass


def latest_step(path: str) -> int | None:
    if not os.path.isdir(path):
        return None
    _recover_orphans(path)
    steps = [int(d.split("_", 1)[1]) for d in os.listdir(path)
             if d.startswith("step_") and d.split("_", 1)[1].isdigit()]
    return max(steps) if steps else None


def resync(tree, root_rank: int = 0):
    """Broadcast ``tree`` from ``root_rank`` so every rank resumes from
    identical state — the reference's restore-then-broadcast idiom."""
    from horovod_tpu.optim.distributed import broadcast_parameters

    return broadcast_parameters(tree, root_rank=root_rank)


def _to_host(tree):
    import jax

    return jax.tree_util.tree_map(np.asarray, tree)
