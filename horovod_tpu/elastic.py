"""Elastic training: survivor-continue with dynamic world size.

The fault-tolerant control plane (docs/fault-tolerance.md) turned a dead
rank from a 600 s hang into a prompt, diagnosable
:class:`~horovod_tpu.common.types.RanksDownError` — but the job still
died and restarted whole.  At pod scale a single preempted host must not
cost every healthy chip a full teardown, rendezvous, re-init and
recompile.  This module is the next step: survivors KEEP their
processes, re-form the communicator at the new world size, resync state
from the last commit point, and keep training.

Public surface (mirrors Horovod's elastic API, TPU-native):

* :class:`ElasticState` — params / optimizer state / step / batch
  offset with ``commit()`` / ``restore()``.  ``commit()`` snapshots to
  host memory (ZeRO-1 shard-local optimizer state is allgathered into
  its re-shardable global form) and doubles as the admission boundary
  for rejoining ranks.
* :func:`run` — decorator / driver: runs ``train_fn(state, ...)``,
  catches :class:`RanksDownError`, and drives the coordinated re-form
  instead of dying.

The re-form ("generation" bump) protocol rides the launcher's
rendezvous KV server, the only piece of the control plane that outlives
a generation (the jax.distributed coordination service dies with the
world it coordinated):

1. every survivor posts presence under the NEXT generation's namespace;
2. the lowest surviving rank (leader) waits ``HOROVOD_ELASTIC_SETTLE_
   SECONDS`` for the expected survivors, folds in pending joiners, and
   publishes the roster: dense new ranks, local/cross topology, a fresh
   coordinator address, the generation number;
3. everyone tears down the old world (bounded — a dead peer can't be
   waited on), re-inits on the fresh KV epoch == generation (the
   epoch-namespaced keys in ``common/basics.py`` make old/new
   generations collision-free on the shared store), and resyncs state:
   the commit snapshot broadcasts from the new rank 0, ZeRO-1 state is
   re-sharded to the new world size, error-feedback residuals restart
   at zero, and every cached XLA collective program was invalidated by
   the teardown so collectives recompile at the new ``size()``.

Known limitation: the death of the OLD rank 0 (which hosts the
jax.distributed coordination service) cannot be survived in-process —
jaxlib's service-error poll terminates the survivors before Python sees
anything.  ``hvdrun --restart-attempts`` remains the fallback for that
(1/world_size) slice of failures; see docs/elastic.md.
"""

from __future__ import annotations

import functools
import json
import os
import socket
import time

from horovod_tpu.common import basics as _basics
from horovod_tpu.common import config as _config
from horovod_tpu.common import logging as _log
from horovod_tpu.common.types import HorovodTpuError, RanksDownError
from horovod_tpu.runtime import flight as _flight

# Module state: generation statistics (bench extras read these) and the
# lazily-created rendezvous transport.  ``_transport_factory`` is the
# test hook: single-process tests drive the whole admission protocol
# over an in-memory fake wire.
_stats = {"reforms": 0, "last_reform_s": None, "total_reform_s": 0.0,
          "dead_total": 0, "grown_total": 0, "preempt_drains": 0}
_rendezvous = None
_transport_factory = None


class HostsUpdatedInterrupt(Exception):
    """Raised out of ``ElasticState.commit()`` when the commit boundary
    admits joiners (Horovod's elastic uses the same name).  ``run``
    catches it, drives the grow re-form, and re-enters ``train_fn``
    from the just-committed state — EVERY rank restarts the loop at the
    same point, survivor and joiner alike; a survivor resuming
    mid-commit while the joiner enters at the loop top would sit one
    commit apart and deadlock.  Do not swallow it in ``train_fn``."""


def enabled() -> bool:
    """True when elastic mode is on (``HOROVOD_ELASTIC`` / ``hvdrun
    --elastic``)."""
    return bool(_config.get("elastic"))


def is_joiner() -> bool:
    """True in a replacement process spawned by the launcher to grow a
    running job back toward its original size."""
    return os.environ.get("HOROVOD_ELASTIC_JOINER") == "1"


def generation() -> int:
    """The current communicator generation — the KV epoch the world was
    (re)formed on.  Starts at 1; each re-form increments it."""
    st = _basics.state()
    return st.epoch


def stats() -> dict:
    """Re-form statistics for observability (bench extras): count, last
    and total re-form latency, ranks lost, ranks grown back."""
    out = dict(_stats)
    out["generation"] = generation()
    return out


def poll() -> None:
    """Raise :class:`RanksDownError` promptly if a peer is down, and
    drive the graceful-preemption drain protocol
    (:mod:`horovod_tpu.runtime.preemption` — may raise
    :class:`~horovod_tpu.runtime.preemption.PreemptionInterrupt`).

    The negotiated (eager) data plane notices dead peers by itself; a
    training loop whose steps are fully compiled may go many seconds
    without touching it.  Call this between compiled steps — at the
    SAME loop points on every rank, which is also what lets the
    preemption plane agree on one drain boundary fleet-wide — so the
    re-form starts within the heartbeat deadline either way."""
    from horovod_tpu.ops import eager as _eager
    from horovod_tpu.runtime import preemption as _preempt

    _eager.check_liveness()
    _preempt.maybe_interrupt()


# ---------------------------------------------------------------------------
# Rendezvous transport (outlives generations)
# ---------------------------------------------------------------------------


def _rv():
    global _rendezvous
    if _rendezvous is None:
        if _transport_factory is not None:
            _rendezvous = _transport_factory()
        else:
            addr = _config.get("rendezvous_addr")
            port = _config.get("rendezvous_port")
            if not addr or not port:
                raise HorovodTpuError(
                    "elastic mode needs the launcher's rendezvous KV "
                    "server to outlive re-forms (hvdrun --elastic "
                    "exports HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT); the "
                    "jax coordination service dies with the generation "
                    "it coordinated. See docs/elastic.md.")
            from horovod_tpu.runtime.kvstore import KVStoreClient

            _rendezvous = KVStoreClient(addr, port)
    return _rendezvous


def _bounded_get(t, key: str, timeout_s: float, liveness: bool = False):
    """Poll ``key`` until present or ``timeout_s``; with ``liveness``,
    also sweep peer heartbeats so a coordinator dying mid-wait raises
    :class:`RanksDownError` instead of riding out the deadline."""
    deadline = time.monotonic() + timeout_s
    while True:
        v = t.try_get(key)
        if v is not None:
            return v
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"elastic: rendezvous key {key} not published within "
                f"{timeout_s:.0f}s")
        if liveness:
            # Heartbeat sweep only — NOT poll(): the preemption drain
            # protocol counts poll() calls as step boundaries, and this
            # wait loop runs a variable number of iterations per rank.
            from horovod_tpu.ops import eager as _eager

            _eager.check_liveness()
        time.sleep(0.05)


def _uid() -> str:
    return os.environ.get("HOROVOD_ELASTIC_UID") or \
        f"{socket.gethostname()}-{os.getpid()}"


def _free_port() -> int:
    from horovod_tpu.common.util import free_port

    return free_port()


# ---------------------------------------------------------------------------
# Join registration / admission (KV-only: the store has no listing, so
# joiners claim dense slots under el/join/<i> via set_once)
# ---------------------------------------------------------------------------


def _join_cursor(t) -> int:
    """First join slot that can still hold a pending joiner — slots
    below it are all consumed.  Keeps the per-commit registry scan O(
    pending joiners), not O(all-time joiners): without it a long job on
    a flapping fleet pays two wire roundtrips per historical joiner at
    EVERY commit boundary."""
    try:
        return int(t.try_get("el/join_cursor") or 0)
    except (TypeError, ValueError):
        return 0


def register_join(t, uid: str, host: str) -> int:
    """Announce a joiner on the rendezvous; returns its join slot."""
    rec = json.dumps({"uid": uid, "host": host})
    start = _join_cursor(t)
    for i in range(start, start + 4096):
        t.set_once(f"el/join/{i}", rec)
        if t.try_get(f"el/join/{i}") == rec:
            return i
    raise HorovodTpuError("elastic: join registry full (4096 slots)")


def scan_joiners(t, limit: int = 4096,
                 advance_cursor: bool = False) -> list:
    """Pending (unadmitted) joiners, in registration order.  With
    ``advance_cursor`` (rank 0 / the re-form leader) the shared scan
    cursor moves past the leading run of consumed slots so future scans
    skip them."""
    start = _join_cursor(t)
    out = []
    prefix = start
    prefix_consumed = True
    for i in range(start, start + limit):
        v = t.try_get(f"el/join/{i}")
        if v is None:
            break
        rec = json.loads(v)
        consumed = t.try_get(f"el/admitted/{rec['uid']}") is not None
        if consumed and prefix_consumed:
            prefix = i + 1
        else:
            prefix_consumed = False
            if not consumed:
                out.append((rec["uid"], rec["host"]))
    if advance_cursor and prefix > start:
        try:
            t.set_overwrite("el/join_cursor", str(prefix))
        except Exception:
            pass  # scan-cost optimization only
    return out


# ---------------------------------------------------------------------------
# Roster planning (pure, unit-testable)
# ---------------------------------------------------------------------------


def plan_reform(survivors: list, joiners: list) -> dict:
    """Dense renumbering + local/cross topology for a new generation.

    ``survivors``: ``[(old_rank, uid, host)]`` — keep their relative
    order (so the lowest surviving old rank becomes new rank 0, the
    state-resync root).  ``joiners``: ``[(uid, host)]`` — numbered after
    the survivors, sorted by uid for determinism."""
    members = [{"uid": u, "host": h, "old_rank": r}
               for r, u, h in sorted(survivors)]
    members += [{"uid": u, "host": h, "old_rank": -1}
                for u, h in sorted(joiners)]
    hosts = [m["host"] for m in members]
    uniq = sorted(set(hosts), key=hosts.index)
    counts = {h: hosts.count(h) for h in uniq}
    seen: dict = {}
    for r, m in enumerate(members):
        h = m["host"]
        m["rank"] = r
        m["local_rank"] = seen.get(h, 0)
        seen[h] = m["local_rank"] + 1
        m["local_size"] = counts[h]
        m["cross_rank"] = uniq.index(h)
        m["cross_size"] = len(uniq)
    return {"size": len(members), "members": members,
            "homogeneous": len(set(counts.values())) == 1}


# ---------------------------------------------------------------------------
# ElasticState
# ---------------------------------------------------------------------------


class ElasticState:
    """Training state that survives re-forms: parameters, optimizer
    state, step counter and batch offset (plus arbitrary ``extra``
    host-side values).  ``commit()`` snapshots everything to host
    memory — the point a re-form (or a rejoining rank) resumes from —
    and ``restore()`` rebuilds device state from the snapshot,
    re-sharding ZeRO-1 optimizer state for the current world size.

    ``commit()`` is a collective call in elastic mode: it is also the
    admission boundary where every rank agrees (via rank 0's verdict on
    the rendezvous) whether pending joiners trigger a grow re-form, and
    where sharded optimizer state is allgathered.  Call it at the same
    loop points on every rank.  With ``checkpoint_dir`` set, each commit
    additionally lands a durable snapshot (rank 0) so ``hvdrun
    --restart-attempts`` — the fallback when a re-form is impossible —
    resumes from the same point the elastic layer would have.
    """

    def __init__(self, params=None, opt_state=None, step: int = 0,
                 batch_offset: int = 0, checkpoint_dir: str | None = None,
                 **extra):
        self.params = params
        self.opt_state = opt_state
        self.step = int(step)
        self.batch_offset = int(batch_offset)
        self.extra = dict(extra)
        self.checkpoint_dir = checkpoint_dir
        self.commits = 0
        self._commit = None
        # Health-plane counters at the previous commit, so the verdict
        # stamped on each durable snapshot reflects what happened SINCE
        # the last one (a long-cleared alert must not poison every
        # later commit).
        self._health_marks = (0, 0)

    def commit(self) -> None:
        self._snapshot()
        _autopilot_tick(self)
        _commit_boundary(self)

    def _snapshot(self) -> None:
        """The state-capture half of :meth:`commit` — collective, but
        without the admission boundary.  The preemption drain uses it
        directly (an emergency commit must not race a grow decision
        while ranks are leaving)."""
        from horovod_tpu.optim import distributed as _dist
        from horovod_tpu.optim import local_sgd as _lsgd

        self.commits += 1
        # Local-SGD regime contract (docs/local-sgd.md): commits happen
        # at outer-sync boundaries, where params == anchor, so a
        # re-form restores from the last anchor for free.  A commit
        # taken MID-window still works — but the mid-window params
        # become the new anchor on restore, silently discarding the
        # outer-momentum trajectory the window would have produced.
        pos = _lsgd.inner_window_position(self.opt_state)
        if pos:
            _log.warning(
                f"elastic commit #{self.commits} taken {pos} inner "
                "step(s) into a local-SGD window — the regime contract "
                "is to commit at outer-sync boundaries; a re-form will "
                "restore these mid-window params as the new anchor "
                "(docs/local-sgd.md)")
            _flight.record("elastic", event="localsgd_midwindow_commit",
                           commit=self.commits, inner_steps=int(pos),
                           step=int(self.step))
        # params_to_host handles stage-3 shard-resident params
        # (Zero3Params allgather into their world-independent full
        # form — collective, like the sharded-optimizer-state gather
        # below) and passes plain trees through as numpy.
        self._commit = {
            "params": _dist.params_to_host(self.params),
            "opt_state": _dist.sharded_state_to_host(self.opt_state),
            "step": int(self.step),
            "batch_offset": int(self.batch_offset),
            "extra": dict(self.extra),
            "commits": self.commits,
        }
        if self.checkpoint_dir:
            from horovod_tpu import checkpoint as _ckpt

            # The FULL snapshot, optimizer state included (in its
            # re-shardable host form): the --restart-attempts fallback
            # must resume from the same point a re-form would have,
            # moments and all.
            try:
                _ckpt.save(self.checkpoint_dir, self._commit,
                           step=self.step,
                           verdict=_commit_verdict(self))
            except OSError as exc:
                _log.warning(f"elastic commit checkpoint failed: {exc}")

    def restore(self) -> None:
        from horovod_tpu.optim import distributed as _dist

        snap = self._commit
        if snap is None:
            raise HorovodTpuError(
                "ElasticState.restore() without a commit: call "
                "state.commit() at least once before a failure can be "
                "survived.")
        # Stage-3 subtrees re-shard for the CURRENT world size (rank r
        # takes segment r of the re-padded fused buffers) — the
        # parameter half of a ZeRO re-form.
        self.params = _dist.params_from_host(snap["params"])
        self.opt_state = _dist.sharded_state_from_host(snap["opt_state"])
        self.step = int(snap["step"])
        self.batch_offset = int(snap["batch_offset"])
        self.extra = dict(snap["extra"])
        self.commits = int(snap["commits"])

    def rollback_to_healthy(self) -> int:
        """Auto-rollback primitive (docs/autopilot.md): load the newest
        durable commit whose stamped health verdict is not
        ``"poisoned"``, broadcast it from rank 0 so every rank rewinds
        to the SAME snapshot, and restore device state from it.
        Returns the step rolled back to.  Usable with the autopilot
        off; raises when no durable commits exist or none is healthy.
        The poisoned snapshots stay in the ring (verdict intact) for
        the post-mortem."""
        if not self.checkpoint_dir:
            raise HorovodTpuError(
                "rollback_to_healthy() needs "
                "ElasticState(checkpoint_dir=...): only durable "
                "commits carry health verdicts.")
        from horovod_tpu import checkpoint as _ckpt
        from horovod_tpu.optim.distributed import broadcast_object

        st = _basics.state()
        if st.initialized and st.size > 1:
            snap = _ckpt.restore(self.checkpoint_dir,
                                 healthy_only=True) \
                if st.rank == 0 else None
            snap = broadcast_object(snap, root_rank=0,
                                    name="autopilot.rollback")
        else:
            snap = _ckpt.restore(self.checkpoint_dir, healthy_only=True)
        step = int(snap["step"])
        _flight.record("elastic", event="rollback_to_healthy",
                       step=step, commits=int(snap.get("commits", 0)))
        _log.warning(
            f"elastic: rolled back to last healthy commit (step {step},"
            f" commit {snap.get('commits')})", rank=st.rank)
        self._commit = snap
        self.restore()
        return step


def _commit_verdict(state: ElasticState) -> str | None:
    """Health verdict stamped into a durable commit's DONE marker:
    ``None`` when the health plane is off (absent verdict counts
    healthy on the read side), ``"poisoned"`` when an alert is active
    or new nonfinite events / alert trips landed since the previous
    commit, else ``"healthy"``."""
    if not bool(_config.get("health")):
        return None
    try:
        from horovod_tpu.runtime import health as _health

        snap = _health.monitor().snapshot()
    except Exception:
        return None
    marks = (int(snap.get("nonfinite_events") or 0),
             int(snap.get("alerts_total") or 0))
    prev = state._health_marks
    state._health_marks = marks
    if snap.get("active_alerts") or marks[0] > prev[0] \
            or marks[1] > prev[1]:
        return "poisoned"
    return "healthy"


def _autopilot_tick(state: ElasticState) -> None:
    """Rank-side autopilot hook, evaluated once per commit: rank 0
    judges the health/comm rules, the decision broadcasts so every
    rank acts (or doesn't) together.  Advisory by construction — an
    autopilot failure must never fail the commit that hosted it."""
    if not bool(_config.get("autopilot")):
        return
    try:
        from horovod_tpu.runtime import autopilot as _ap

        _ap.rank_tick(state)
    except HorovodTpuError:
        raise
    except Exception as exc:
        _log.warning(f"autopilot rank tick failed: {exc}")


# ---------------------------------------------------------------------------
# run(): the elastic driver
# ---------------------------------------------------------------------------


def run(*args, **kwargs):
    """``hvd.elastic.run`` — decorator or direct driver.

    Decorator form (Horovod parity)::

        @hvd.elastic.run
        def train(state):
            while state.step < total: ...

        train(state)

    Direct form: ``hvd.elastic.run(state, train_fn, *args, **kwargs)``.

    Either way: runs ``train_fn(state, ...)``; on
    :class:`RanksDownError` the survivors re-form the world at the new
    size, ``state`` is restored from the last commit, and ``train_fn``
    is called again.  A joiner process first blocks for admission and
    enters the loop already resynced."""
    if len(args) == 1 and callable(args[0]) \
            and not isinstance(args[0], ElasticState):
        fn = args[0]

        @functools.wraps(fn)
        def wrapper(state, *a, **k):
            return _run_elastic(state, fn, a, k)

        return wrapper
    if len(args) < 2:
        raise TypeError(
            "hvd.elastic.run takes (train_fn) as a decorator or "
            "(state, train_fn, *args) directly")
    return _run_elastic(args[0], args[1], args[2:], kwargs)


def _run_elastic(state: ElasticState, fn, args, kwargs):
    if not enabled():
        raise HorovodTpuError(
            "hvd.elastic.run requires elastic mode (HOROVOD_ELASTIC=1 / "
            "hvdrun --elastic); see docs/elastic.md.")
    if not _basics.state().initialized:
        raise HorovodTpuError("hvd.init() must run before hvd.elastic.run")
    _rv()  # fail fast when no rendezvous outlives the generation
    from horovod_tpu.runtime import preemption as _preempt

    if _preempt.enabled():
        _preempt.install_signal_handlers()
    if is_joiner():
        _join(state)
    while True:
        try:
            return fn(state, *args, **kwargs)
        except RanksDownError as exc:
            _log.warning(
                f"elastic: rank(s) {list(exc.ranks)} down at generation "
                f"{generation()}; re-forming instead of aborting",
                rank=_basics.state().rank)
            _reform_with_retry(state, dead=exc.ranks, reason="failure")
        except HostsUpdatedInterrupt:
            _reform_with_retry(state, dead=(), reason="grow")
        except _preempt.PreemptionInterrupt as exc:
            _drain(state, exc)


def _reform_with_retry(state: ElasticState, dead, reason: str,
                       attempts: int = 5) -> None:
    """Drive a re-form, retrying when ANOTHER rank dies mid-re-form: a
    RanksDownError raised from inside _reform (e.g. during the resync
    broadcast over the freshly-formed world) names dead ranks in the
    CURRENT numbering — whatever generation the failure interrupted —
    so each retry starts over against the current world with only the
    newest dead set.  Bounded: cascading deaths eventually hit
    --min-ranks or exhaust the attempts and fall back to restart."""
    for attempt in range(attempts):
        try:
            _reform(state, dead=dead, reason=reason)
            return
        except RanksDownError as exc:
            if attempt + 1 >= attempts:
                raise
            dead = exc.ranks
            reason = "failure"
            _log.warning(
                f"elastic: rank(s) {list(dead)} died during the re-form "
                f"itself; retrying ({attempt + 2}/{attempts})",
                rank=_basics.state().rank)


# ---------------------------------------------------------------------------
# Graceful-preemption drain
# ---------------------------------------------------------------------------


def _drain(state: ElasticState, interrupt) -> None:
    """Notice-driven drain (docs/fault-tolerance.md): every rank raised
    :class:`~horovod_tpu.runtime.preemption.PreemptionInterrupt` at the
    same agreed step boundary, so one emergency snapshot (collective,
    durable when ``checkpoint_dir`` is set) captures the CURRENT state
    — nothing since the last scheduled commit is lost.  The noticed
    rank(s) then exit cleanly (the launcher reads their
    ``el/preempt/u/<uid>`` marker: no blacklist, no death) and the
    survivors re-form proactively, skipping the heartbeat-timeout
    settle cushion — the departure was announced, not detected."""
    st = _basics.state()
    ranks = sorted(int(r) for r in interrupt.ranks)
    me = st.rank in ranks
    gen = generation()
    _log.warning(
        f"elastic: draining preempted rank(s) {ranks} at generation "
        f"{gen}: emergency commit, then "
        f"{'clean exit' if me else 'proactive re-form'}", rank=st.rank)
    _flight.record("preempt", event="drain_start", gen=gen, ranks=ranks,
                   rank=st.rank, step=int(state.step),
                   deadline=interrupt.order.get("deadline"))
    state._snapshot()
    wall0 = interrupt.order.get("wall")
    drain_s = max(0.0, time.time() - float(wall0)) if wall0 else 0.0
    beat_grace = (interrupt.order.get("deadline") is None
                  or time.time() <= float(interrupt.order["deadline"]))
    _stats["preempt_drains"] += 1
    try:
        from horovod_tpu.runtime import metrics as _metrics

        _metrics.counter(
            "hvd_preempt_drains_total",
            "Emergency preemption drains this process took part "
            "in.").inc()
        _metrics.histogram(
            "hvd_preempt_drain_seconds",
            "Notice received -> emergency commit landed (the drain "
            "must beat HOROVOD_PREEMPT_GRACE_SECONDS).").observe(drain_s)
    except Exception:
        pass
    _flight.record("preempt", event="drain_commit", gen=gen,
                   step=int(state.step), commit=int(state.commits),
                   drain_s=round(drain_s, 3), beat_grace=beat_grace)
    if me:
        _log.warning(
            f"elastic: rank {st.rank} drained at commit step "
            f"{state.step} ({drain_s:.1f}s after notice); exiting "
            "cleanly for preemption", rank=st.rank)
        _flight.record("preempt", event="drain_exit", gen=gen,
                       rank=st.rank)
        _flight.dump(f"preempt:g{gen}")
        try:
            _basics.shutdown()
            _basics.teardown_distributed()
        except Exception:
            pass
        raise SystemExit(0)
    _reform_with_retry(state, dead=ranks, reason="preempt")


# ---------------------------------------------------------------------------
# The re-form itself
# ---------------------------------------------------------------------------


def _reform(state: ElasticState, dead=(), reason: str = "failure") -> None:
    """Coordinated generation bump: presence → roster → teardown →
    re-init on the fresh epoch → state resync."""
    st = _basics.state()
    t0 = time.monotonic()
    old_rank, old_size = st.rank, st.size
    gen = st.epoch + 1
    _flight.record("elastic", event="reform_start", gen=gen,
                   dead=sorted(int(r) for r in dead), reason=reason,
                   old_rank=old_rank, old_size=old_size)
    # Dump the OLD generation's ring before teardown scrambles it: the
    # launcher sweeps re-form dumps, and the pre-death record (who
    # stalled, which round hung) is exactly what a postmortem needs.
    # Then CLEAR it — round numbers and rank identities restart with
    # the new generation, and a later dump carrying both generations'
    # events would merge unrelated rounds in the straggler analyzer —
    # and re-record the re-form marker so the new record opens with
    # why the last one ended.
    _flight.dump(f"reform:g{gen}:{reason}")
    _flight.recorder().clear()
    _flight.record("elastic", event="reform_start", gen=gen,
                   dead=sorted(int(r) for r in dead), reason=reason,
                   old_rank=old_rank, old_size=old_size)
    t = _rv()
    dead = {int(r) for r in dead}
    uid = _uid()
    t_rv0 = time.monotonic()
    t.set_overwrite(
        f"el/g{gen}/s/{old_rank}",
        json.dumps({"uid": uid, "host": socket.gethostname(),
                    "old_rank": old_rank}))
    expected = sorted(set(range(old_size)) - dead)
    # Effective settle floor: a survivor blocked in an eager collective
    # notices the death within the heartbeat timeout, so the leader
    # must wait at least that long for stragglers — a shorter knob
    # would drop healthy ranks whose detection simply came later.
    # Fully-compiled loops whose steps outlast this window must raise
    # the knob past their step time (and call poll() between steps);
    # see docs/elastic.md.
    settle = max(float(_config.get("elastic_settle")),
                 float(_config.get("heartbeat_timeout") or 0), 0.5)
    if reason == "preempt":
        # Announced departure: every survivor raised at the SAME agreed
        # drain boundary, so presence skew is one step, not a detection
        # window — the heartbeat-timeout cushion above would only stall
        # the proactive shed.
        settle = max(float(_config.get("elastic_settle")), 0.5)
    if expected and old_rank == expected[0]:
        roster = _lead_reform(t, gen, expected, dead, settle, reason)
    else:
        roster = json.loads(_bounded_get(
            t, f"el/g{gen}/roster", settle + 60.0))
        if roster.get("error"):
            raise HorovodTpuError(
                f"elastic re-form to generation {gen} refused: "
                f"{roster['error']}")
    rendezvous_s = time.monotonic() - t_rv0
    mine = next((m for m in roster["members"] if m["uid"] == uid), None)
    if mine is None:
        raise HorovodTpuError(
            f"elastic: this rank (old rank {old_rank}) was dropped from "
            f"generation {roster['gen']} — its presence arrived after "
            "the settle window. A full restart (hvdrun "
            "--restart-attempts) is the only way back in.")
    phases = _apply_roster(state, roster, mine)
    phases["rendezvous_s"] = round(rendezvous_s, 3)
    dt = time.monotonic() - t0
    _stats["reforms"] += 1
    _stats["last_reform_s"] = round(dt, 2)
    _stats["total_reform_s"] = round(_stats["total_reform_s"] + dt, 2)
    _stats["dead_total"] += len(roster.get("dead") or ())
    _stats["grown_total"] += sum(
        1 for m in roster["members"] if m["old_rank"] < 0)
    _record_reform_metrics(roster, dt)
    # Downtime attribution (docs/aot-cache.md): the reform_done flight
    # event and the launcher's el/status record both carry the
    # teardown / rendezvous / compile / resync split, so the PR 8
    # analyzer (and an operator tailing el/status) can see whether a
    # slow re-form was XLA recompilation — the cost the AOT cache
    # exists to remove — or control-plane/resync time.  compile_s is
    # the hvd_compile_seconds_total delta across the re-form (programs
    # compiled by the resync broadcast itself; step programs rebuilt
    # lazily later land in the counter but not in this split).
    _flight.record("elastic", event="reform_done", gen=roster["gen"],
                   size=roster["size"], rank=mine["rank"],
                   dead=sorted(roster.get("dead") or []),
                   reform_s=round(dt, 2), **phases)
    # Goodput ledger (docs/goodput.md): the re-form wall is downtime
    # the fleet report must attribute.  The re-init() inside
    # _apply_roster already booked its own span on the "init" phase,
    # so only the remainder lands on "reform" (phases carried as the
    # split so the report can show teardown/rendezvous/compile/resync);
    # the split's compile_s tells the ledger those counter seconds are
    # already attributed here, not free to claim unattributed wall.
    try:
        from horovod_tpu.perf import goodput as _goodput

        _goodput.observe(
            "reform",
            max(0.0, dt - float(phases.get("init_s") or 0.0)),
            split=phases)
    except Exception:
        pass
    if mine["rank"] == 0:
        try:
            t.set_overwrite("el/status", json.dumps(dict({
                "gen": roster["gen"], "size": roster["size"],
                "dead": roster.get("dead") or [],
                "grown": [m["uid"] for m in roster["members"]
                          if m["old_rank"] < 0],
                "reforms": _stats["reforms"],
                "reform_s": round(dt, 2), "reason": reason}, **phases)))
        except Exception:
            pass  # observability only; the job itself is healthy
    _log.warning(
        f"elastic: re-formed generation {roster['gen']} in {dt:.1f}s — "
        f"size {old_size} -> {roster['size']} (rank {old_rank} -> "
        f"{mine['rank']}), dead={sorted(roster.get('dead') or [])}, "
        f"resumed from commit step {state.step}",
        rank=mine["rank"])


def _record_reform_metrics(roster: dict, dt: float) -> None:
    """Mirror re-form statistics into the metrics plane
    (docs/metrics.md); the generation/world gauges themselves were
    already refreshed by the re-init inside ``_apply_roster``."""
    from horovod_tpu.runtime import metrics as _metrics

    _metrics.counter(
        "hvd_elastic_reforms_total",
        "Elastic re-forms this process survived.").inc()
    _metrics.histogram(
        "hvd_elastic_reform_seconds",
        "Re-form latency: failure caught -> resynced at the new world "
        "size.").observe(dt)
    _metrics.counter(
        "hvd_elastic_dead_ranks_total",
        "Ranks lost across all re-forms.").inc(
            len(roster.get("dead") or ()))
    _metrics.counter(
        "hvd_elastic_joiner_admissions_total",
        "Replacement ranks folded into a roster across all "
        "re-forms.").inc(
            sum(1 for m in roster["members"] if m["old_rank"] < 0))


def _lead_reform(t, gen: int, expected: list, dead: set, settle: float,
                 reason: str) -> dict:
    """Leader (lowest expected survivor): collect presence, fold in
    joiners, publish the roster + joiner admissions."""
    deadline = time.monotonic() + settle
    present: dict = {}
    while len(present) < len(expected):
        for r in expected:
            if r not in present:
                v = t.try_get(f"el/g{gen}/s/{r}")
                if v is not None:
                    present[r] = json.loads(v)
        if len(present) >= len(expected) or time.monotonic() > deadline:
            break
        time.sleep(0.05)
    missing = sorted(set(expected) - set(present))
    if missing:
        _log.warning(
            f"elastic: rank(s) {missing} never announced for generation "
            f"{gen} within the {settle:.0f}s settle window; treating "
            "them as dead", rank=expected[0])
    survivors = [(r, present[r]["uid"], present[r]["host"])
                 for r in sorted(present)]
    joiners = scan_joiners(t, advance_cursor=True)
    roster = plan_reform(survivors, joiners)
    min_ranks = max(1, int(_config.get("min_ranks")))
    if roster["size"] < min_ranks:
        err = (f"only {roster['size']} rank(s) would remain, below "
               f"--min-ranks {min_ranks}")
        t.set_overwrite(f"el/g{gen}/roster",
                        json.dumps({"gen": gen, "error": err}))
        raise HorovodTpuError(f"elastic re-form refused: {err}")
    hosts = {m["host"] for m in roster["members"]}
    coord_host = (socket.gethostname() if len(hosts) > 1 else "127.0.0.1")
    roster.update({
        "gen": gen,
        "coord": f"{coord_host}:{_free_port()}",
        "dead": sorted(dead | set(missing)),
        "reason": reason,
    })
    for m in roster["members"]:
        if m["old_rank"] < 0:
            t.set_overwrite(f"el/admitted/{m['uid']}", str(gen))
    t.set_overwrite(f"el/g{gen}/roster", json.dumps(roster))
    for m in roster["members"]:
        if m["old_rank"] < 0:
            t.set_overwrite(f"el/admit/{m['uid']}",
                            json.dumps({"gen": gen}))
    return roster


def _apply_roster(state: ElasticState, roster: dict, mine: dict) -> dict:
    """Everyone: tear the old world down, re-init on the roster's
    generation, resync state from the new rank 0.  Returns the phase
    split (teardown/init/resync seconds + compile seconds and AOT
    cache hits across the re-form) for the reform_done record."""
    import jax

    from horovod_tpu.runtime import aot_cache as _aot

    aot0 = _aot.stats()
    t_td = time.monotonic()
    n, gen = int(roster["size"]), int(roster["gen"])
    _basics.shutdown()                # background runtime + heartbeats
    _basics.teardown_distributed()    # bounded; clears program caches
    teardown_s = time.monotonic() - t_td
    env = os.environ
    env["HOROVOD_RANK"] = str(mine["rank"])
    env["HOROVOD_SIZE"] = str(n)
    env["HOROVOD_LOCAL_RANK"] = str(mine["local_rank"])
    env["HOROVOD_LOCAL_SIZE"] = str(mine["local_size"])
    env["HOROVOD_CROSS_RANK"] = str(mine["cross_rank"])
    env["HOROVOD_CROSS_SIZE"] = str(mine["cross_size"])
    env["HOROVOD_IS_HOMOGENEOUS"] = "1" if roster["homogeneous"] else "0"
    env["HOROVOD_COORDINATOR_ADDR"] = roster["coord"]
    if env.get("HOROVOD_ELASTIC_JOINER") == "1":
        env["HOROVOD_ELASTIC_JOINER"] = "0"  # admitted: a survivor now
    if (env.get("HOROVOD_PLATFORM") == "cpu"
            or (jax.config.jax_platforms or "") == "cpu"):
        # Cross-process CPU collectives need gloo bound to the NEW
        # distributed client at backend build; a size-1 world must drop
        # back to in-process collectives (gloo binding requires a
        # client that no longer exists).
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo" if n > 1 else "none")
        except Exception:
            pass
    st = _basics.state()
    st.epoch = gen - 1  # init() increments: fresh KV epoch == generation
    t_init = time.monotonic()
    _basics.init()
    t_resync = time.monotonic()
    _resync(state)
    aot1 = _aot.stats()
    return {
        "teardown_s": round(teardown_s, 3),
        "init_s": round(t_resync - t_init, 3),
        "resync_s": round(time.monotonic() - t_resync, 3),
        "compile_s": round(
            (aot1["compile_s_cold"] + aot1["compile_s_warm"])
            - (aot0["compile_s_cold"] + aot0["compile_s_warm"]), 3),
        "aot_hits": aot1["hits"] - aot0["hits"],
    }


def _resync(state: ElasticState) -> None:
    """Broadcast the commit snapshot from the new rank 0 (the lowest
    surviving old rank — survivors all hold the same commit, but one
    authoritative copy keeps joiners and any raced commit honest), then
    restore device state from it at the new world size."""
    from horovod_tpu.optim.distributed import broadcast_object

    snap = state._commit
    if _basics.size() > 1:
        payload = snap if _basics.rank() == 0 else None
        snap = broadcast_object(payload, root_rank=0,
                                name="elastic.resync")
    if snap is None:
        raise HorovodTpuError(
            "elastic re-form without a committed state: call "
            "ElasticState.commit() before failures can be survived.")
    state._commit = snap
    state.restore()


# ---------------------------------------------------------------------------
# Commit boundary: grow admission
# ---------------------------------------------------------------------------


def _commit_boundary(state: ElasticState) -> None:
    """All ranks agree — via rank 0's verdict for THIS commit index —
    whether pending joiners trigger a grow re-form now.  The per-index
    key makes the decision deterministic across ranks: without it, two
    ranks could observe the join registry around different commits and
    re-form one step apart, deadlocking the stragglers."""
    if not enabled():
        return
    st = _basics.state()
    if not st.initialized:
        return
    t = _rv()
    c = state.commits
    if st.rank == 0:
        target = int(os.environ.get("HOROVOD_ELASTIC_NP", "0") or 0)
        joiners = scan_joiners(t, advance_cursor=True) \
            if (target <= 0 or st.size < target) else []
        t.set_overwrite(f"el/c/{c}", "grow" if joiners else "ok")
        if c > 2:
            t.delete(f"el/c/{c - 2}")
        grow = bool(joiners)
    else:
        from horovod_tpu.runtime.controller import wire_timeout

        grow = _bounded_get(t, f"el/c/{c}", wire_timeout(),
                            liveness=True) == "grow"
    if grow:
        _log.info(
            f"elastic: joiner(s) pending at commit {c}; growing the "
            f"world (generation {generation()} -> {generation() + 1})",
            rank=st.rank)
        # Raise instead of re-forming inline: run() re-enters train_fn
        # from this commit on EVERY rank, so survivors and the admitted
        # joiner restart their loops at the same point (a survivor
        # resuming mid-commit would sit one commit ahead of the joiner
        # and the two would deadlock on each other's collectives).
        raise HostsUpdatedInterrupt(
            f"joiners admitted at commit {c}")


# ---------------------------------------------------------------------------
# Joiner admission
# ---------------------------------------------------------------------------


def _join(state: ElasticState) -> None:
    """Replacement-process path: register on the rendezvous, block until
    a commit boundary admits us into a generation, then enter that
    world resynced.  On timeout the registration is RETRACTED (via the
    same ``el/admitted`` mark the leader uses to consume it) before
    failing — a later grow re-form must never fold a ghost joiner into
    the roster and hang every survivor's re-init on it."""
    t = _rv()
    uid = _uid()
    register_join(t, uid, socket.gethostname())
    _log.info(f"elastic: joiner {uid} registered; waiting for admission "
              "at the next commit boundary", rank=_basics.state().rank)
    timeout = max(float(_config.get("elastic_join_timeout")), 1.0)
    try:
        admit = json.loads(_bounded_get(t, f"el/admit/{uid}", timeout))
    except TimeoutError:
        try:
            t.set_overwrite(f"el/admitted/{uid}", "timeout")
        except Exception:
            pass
        raise HorovodTpuError(
            f"elastic: joiner {uid} was not admitted within "
            f"HOROVOD_ELASTIC_JOIN_TIMEOUT_SECONDS={timeout:.0f}s — the "
            "survivors' commit cadence must be shorter than this "
            "deadline; registration retracted.")
    gen = int(admit["gen"])
    roster = json.loads(_bounded_get(t, f"el/g{gen}/roster", 60.0))
    mine = next(m for m in roster["members"] if m["uid"] == uid)
    _flight.record("elastic", event="joiner_admitted", gen=gen,
                   rank=mine["rank"], size=roster["size"])
    _apply_roster(state, roster, mine)
    _log.warning(
        f"elastic: joiner {uid} admitted as rank {mine['rank']} of "
        f"{roster['size']} (generation {gen}), resynced at commit step "
        f"{state.step}", rank=mine["rank"])
