"""Training-loop callbacks (Keras-callback capability).

Parity with reference ``horovod/_keras/callbacks.py`` (185 LoC):
``BroadcastGlobalVariablesCallback`` (sync all ranks' initial state
once, on the first batch), ``MetricAverageCallback`` (allreduce the
epoch-end metric logs so every rank reports the same numbers),
``LearningRateScheduleCallback`` / ``LearningRateWarmupCallback``
(epoch/fractional-epoch LR schedule with the momentum-correction trick
from the large-minibatch SGD recipe).

Idiomatic-JAX shape: Keras mutates ``model.optimizer.lr`` through the
backend; here training state is functional, so callbacks operate on a
:class:`TrainingState` holder whose ``opt_state`` was built with
``optax.inject_hyperparams`` (see :func:`find_hyperparams`) — the
holder is the one mutable cell an explicit JAX training loop threads
through its epochs.  A minimal loop::

    opt = hvd.DistributedOptimizer(
        optax.inject_hyperparams(optax.sgd)(learning_rate=0.01,
                                            momentum=0.9))
    state = hvd.keras.TrainingState(params, opt.init(params))
    cbs = hvd.keras.CallbackList(
        [hvd.keras.BroadcastGlobalVariablesCallback(0),
         hvd.keras.MetricAverageCallback(),
         hvd.keras.LearningRateWarmupCallback(warmup_epochs=5,
                                              steps_per_epoch=steps)],
        state)
    cbs.on_train_begin()
    for epoch in range(epochs):
        cbs.on_epoch_begin(epoch)
        for batch in range(steps):
            cbs.on_batch_begin(batch)
            grads = jax.grad(loss)(state.params, ...)
            updates, state.opt_state = opt.update(grads, state.opt_state,
                                                  state.params)
            state.params = optax.apply_updates(state.params, updates)
            cbs.on_batch_end(batch, logs)
        cbs.on_epoch_end(epoch, logs)
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class TrainingState:
    """Mutable holder for the functional (params, opt_state) pair that
    callbacks rewrite in place of Keras' model/optimizer objects."""

    def __init__(self, params, opt_state) -> None:
        self.params = params
        self.opt_state = opt_state


def find_hyperparams(opt_state):
    """Locate the ``optax.inject_hyperparams`` state's mutable
    hyperparams dict anywhere inside a (possibly wrapped) optimizer
    state — DistributedOptimizer and chain/multi-transform wrappers
    nest it."""
    seen = set()

    def walk(obj):
        if id(obj) in seen:
            return None
        seen.add(id(obj))
        hp = getattr(obj, "hyperparams", None)
        if isinstance(hp, dict):
            return hp
        if isinstance(obj, (tuple, list)):
            for item in obj:
                found = walk(item)
                if found is not None:
                    return found
        elif isinstance(obj, dict):
            for item in obj.values():
                found = walk(item)
                if found is not None:
                    return found
        return None

    return walk(opt_state)


class Callback:
    """Hook protocol (the subset of the Keras callback surface the
    reference implements)."""

    state: TrainingState | None = None

    def set_state(self, state: TrainingState) -> None:
        self.state = state

    def on_train_begin(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_batch_begin(self, batch, logs=None):
        pass

    def on_batch_end(self, batch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, state: TrainingState) -> None:
        self.callbacks = list(callbacks)
        for cb in self.callbacks:
            cb.set_state(state)

    def __iter__(self):
        return iter(self.callbacks)

    def on_train_begin(self, logs=None):
        for cb in self.callbacks:
            cb.on_train_begin(logs)

    def on_epoch_begin(self, epoch, logs=None):
        for cb in self.callbacks:
            cb.on_epoch_begin(epoch, logs)

    def on_batch_begin(self, batch, logs=None):
        for cb in self.callbacks:
            cb.on_batch_begin(batch, logs)

    def on_batch_end(self, batch, logs=None):
        for cb in self.callbacks:
            cb.on_batch_end(batch, logs)

    def on_epoch_end(self, epoch, logs=None):
        for cb in self.callbacks:
            cb.on_epoch_end(epoch, logs)


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast rank-``root_rank``'s params + optimizer state to all
    ranks once, after the first processed batch (reference
    ``BroadcastGlobalVariablesCallbackImpl.on_batch_end``: deferred past
    batch 0 so any data-dependent initialization has happened)."""

    def __init__(self, root_rank: int = 0) -> None:
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done:
            return
        from horovod_tpu.optim.distributed import (broadcast_optimizer_state,
                                                   broadcast_parameters)

        self.state.params = broadcast_parameters(self.state.params,
                                                 self.root_rank)
        self.state.opt_state = broadcast_optimizer_state(self.state.opt_state,
                                                         self.root_rank)
        self.broadcast_done = True


class MetricAverageCallback(Callback):
    """Allreduce-average epoch-end metrics across ranks in place, sorted
    by name so every rank issues the same collective order (reference
    ``MetricAverageCallbackImpl._average_metrics_in_place``)."""

    def on_epoch_end(self, epoch, logs=None):
        if not logs:
            return
        from horovod_tpu.ops.eager import allreduce

        reduced = {}
        for metric in sorted(logs):
            value = logs[metric]
            if not isinstance(value, (int, float, np.floating, np.integer,
                                      jnp.ndarray, np.ndarray)):
                continue
            out = allreduce(jnp.asarray(value, jnp.float32),
                            name=f"metric.{metric}.{epoch}")
            reduced[metric] = float(np.asarray(out))
        logs.update(reduced)


class LearningRateScheduleCallback(Callback):
    """Multiply the injected learning rate by ``multiplier(epoch)``
    within [start_epoch, end_epoch); with ``staircase=False`` the
    multiplier sees fractional epochs per batch.  ``momentum_correction``
    rescales momentum by new_lr/old_lr for the batch the LR changed on
    and restores it after (reference
    ``LearningRateScheduleCallbackImpl``, citing the momentum-correction
    note of the large-minibatch SGD paper)."""

    def __init__(self, multiplier, start_epoch: int = 0, end_epoch=None,
                 staircase: bool = True, momentum_correction: bool = True,
                 steps_per_epoch=None) -> None:
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.initial_lr = None
        self.restore_momentum = None
        self.current_epoch = 0
        if not callable(multiplier):
            self.staircase = True
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    def _hp(self) -> dict:
        hp = find_hyperparams(self.state.opt_state)
        if hp is None or "learning_rate" not in hp:
            raise ValueError(
                "LearningRateScheduleCallback requires the optimizer to be "
                "built with optax.inject_hyperparams(...)(learning_rate=...) "
                "so the LR is a mutable hyperparameter.")
        return hp

    def _adjust_learning_rate(self, epoch) -> None:
        hp = self._hp()
        old_lr = float(np.asarray(hp["learning_rate"]))
        new_lr = self.initial_lr * self.multiplier(epoch)
        hp["learning_rate"] = jnp.asarray(
            new_lr, jnp.asarray(hp["learning_rate"]).dtype)
        if self.momentum_correction and "momentum" in hp and old_lr > 0:
            self.restore_momentum = float(np.asarray(hp["momentum"]))
            hp["momentum"] = jnp.asarray(
                self.restore_momentum * new_lr / old_lr,
                jnp.asarray(hp["momentum"]).dtype)

    def _restore_momentum_if_needed(self) -> None:
        if self.restore_momentum is not None:
            hp = self._hp()
            hp["momentum"] = jnp.asarray(
                self.restore_momentum, jnp.asarray(hp["momentum"]).dtype)
            self.restore_momentum = None

    def on_train_begin(self, logs=None):
        self.initial_lr = float(np.asarray(self._hp()["learning_rate"]))
        if not self.staircase and not self.steps_per_epoch:
            raise ValueError(
                "Could not autodetect the number of steps per epoch. Please "
                "specify the steps_per_epoch parameter to the "
                f"{self.__class__.__name__}().")

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch

    def on_batch_begin(self, batch, logs=None):
        if (self.current_epoch < self.start_epoch or
                (self.end_epoch is not None and
                 self.current_epoch >= self.end_epoch)):
            return
        if self.staircase and batch == 0:
            self._adjust_learning_rate(self.current_epoch)
        elif not self.staircase:
            epoch = self.current_epoch + float(batch) / self.steps_per_epoch
            self._adjust_learning_rate(epoch)

    def on_batch_end(self, batch, logs=None):
        self._restore_momentum_if_needed()

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = float(np.asarray(self._hp()["learning_rate"]))


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual warmup from lr/size to lr over ``warmup_epochs``
    (reference ``LearningRateWarmupCallbackImpl``; multiplier math kept
    identical: ``1/size * (epoch * (size-1)/warmup + 1)`` with the
    +1/steps epoch nudge that rounds the end-of-epoch value)."""

    def __init__(self, warmup_epochs: int = 5,
                 momentum_correction: bool = True, steps_per_epoch=None,
                 verbose: int = 0) -> None:
        from horovod_tpu.common.util import validate_warmup_epochs

        validate_warmup_epochs(warmup_epochs)

        def multiplier(epoch):
            from horovod_tpu.common.basics import size

            epoch += 1.0 / self.steps_per_epoch
            return 1.0 / size() * (epoch * (size() - 1) / warmup_epochs + 1)

        super().__init__(multiplier, start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)
        self.verbose = verbose

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose > 0:
            new_lr = float(np.asarray(self._hp()["learning_rate"]))
            print(f"\nEpoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to {new_lr:g}.")
