"""Keras-style high-level API: callbacks + optimizer wrapper.

Parity with reference ``horovod/keras/__init__.py`` +
``horovod/_keras/``: ``DistributedOptimizer`` (same object as the
top-level one — optax is the optimizer substrate here, so no separate
Keras wrapping is needed) and the callback set for explicit training
loops (:mod:`horovod_tpu.keras.callbacks`).
"""

from horovod_tpu.keras.callbacks import (  # noqa: F401
    BroadcastGlobalVariablesCallback,
    Callback,
    CallbackList,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
    TrainingState,
    find_hyperparams,
)
from horovod_tpu.optim.distributed import (  # noqa: F401
    DistributedOptimizer,
    broadcast_global_variables,
)
from horovod_tpu.ops.compression import Compression  # noqa: F401
from horovod_tpu import (  # noqa: F401
    allgather,
    allreduce,
    broadcast,
    init,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=None):
    """Reference ``keras/__init__.py:117``: load a saved Keras model
    with its optimizer re-wrapped for distributed retraining.  Keras
    serialization is a tf.keras feature, so this delegates to
    :func:`horovod_tpu.tensorflow.keras.load_model` (optax state lives
    in :mod:`horovod_tpu.checkpoint` pytree snapshots instead)."""
    try:
        from horovod_tpu.tensorflow.keras import load_model as _lm
    except ImportError as e:
        raise ImportError(
            "load_model needs tensorflow (keras serialization); for "
            "JAX/optax state use horovod_tpu.checkpoint.") from e
    return _lm(filepath, custom_optimizers=custom_optimizers,
               custom_objects=custom_objects, compression=compression)
