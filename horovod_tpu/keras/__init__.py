"""Keras-style high-level API: callbacks + optimizer wrapper.

Parity with reference ``horovod/keras/__init__.py`` +
``horovod/_keras/``: ``DistributedOptimizer`` (same object as the
top-level one — optax is the optimizer substrate here, so no separate
Keras wrapping is needed) and the callback set for explicit training
loops (:mod:`horovod_tpu.keras.callbacks`).
"""

from horovod_tpu.keras.callbacks import (  # noqa: F401
    BroadcastGlobalVariablesCallback,
    Callback,
    CallbackList,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
    TrainingState,
    find_hyperparams,
)
from horovod_tpu.optim.distributed import (  # noqa: F401
    DistributedOptimizer,
    broadcast_global_variables,
)
from horovod_tpu.ops.compression import Compression  # noqa: F401
