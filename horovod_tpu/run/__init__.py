"""horovod_tpu.run — launcher package.

``run(fn, args=(), kwargs=None, np=2, ...)`` is the run-function mode
(reference ``horovod.run.run()``, ``run/runner.py:719``): pickle ``fn``,
launch it on every rank through the normal launcher, collect per-rank
return values.
"""

from __future__ import annotations

import os
import pickle
import sys
import tempfile

from horovod_tpu.run.launcher import launch, main  # noqa: F401


def run(fn, args=(), kwargs=None, np: int = 1, hosts=None,
        env=None, verbose=False, use_gloo=None, use_mpi=None):
    """Execute ``fn(*args, **kwargs)`` on ``np`` ranks; returns the list
    of per-rank return values (rank order).  ``use_gloo``/``use_mpi``
    accepted for reference-API compatibility and ignored (the stack is
    always XLA + KV rendezvous)."""
    try:
        import cloudpickle as pickler  # type: ignore
    except ImportError:
        pickler = pickle

    if hosts:
        import socket as _socket

        local_names = ("localhost", "127.0.0.1", _socket.gethostname())
        from horovod_tpu.run.launcher import parse_host_spec

        if any(h not in local_names for h, _ in parse_host_spec(hosts, np)):
            raise NotImplementedError(
                "run(fn, hosts=...) with remote hosts needs a shared "
                "filesystem for the function/result exchange; launch a "
                "script with hvdrun instead.")

    with tempfile.TemporaryDirectory(prefix="hvdrun_fn_") as tmp:
        fn_path = os.path.join(tmp, "fn.pkl")
        with open(fn_path, "wb") as f:
            pickler.dump((fn, tuple(args), dict(kwargs or {})), f)
        cmd = [sys.executable, "-m", "horovod_tpu.run.exec_fn", fn_path, tmp]
        rc = launch(np, cmd, hosts=hosts, env=env, verbose=verbose)
        if rc != 0:
            raise RuntimeError(f"hvdrun function job failed (rc={rc})")
        results = []
        for r in range(np):
            with open(os.path.join(tmp, f"result.{r}.pkl"), "rb") as f:
                results.append(pickle.load(f))
        return results
