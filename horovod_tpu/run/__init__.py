"""horovod_tpu.run — launcher package.

``run(fn, args=(), kwargs=None, np=2, ...)`` is the run-function mode
(reference ``horovod.run.run()``, ``run/runner.py:719``): pickle ``fn``,
launch it on every rank through the normal launcher, collect per-rank
return values.

Function and results travel over the job KV store when the native store
is available (reference ``run/runner.py:631-657`` returns results
through its rendezvous server the same way), so multi-host run-func
needs no shared filesystem; a launcher-local tempdir is the fallback
transport when the KV store can't build.
"""

from __future__ import annotations

import base64
import os
import pickle
import sys
import tempfile

from horovod_tpu.run.launcher import launch, main  # noqa: F401

# KV key namespace for run-func payloads (distinct from the controller's
# negotiation keys, which are epoch/cycle-prefixed)
FN_KEY = "runfunc/fn"
RESULT_KEY = "runfunc/result/{rank}"


def run(fn, args=(), kwargs=None, np: int = 1, hosts=None,
        env=None, verbose=False, use_gloo=None, use_mpi=None):
    """Execute ``fn(*args, **kwargs)`` on ``np`` ranks; returns the list
    of per-rank return values (rank order).  ``use_gloo``/``use_mpi``
    accepted for reference-API compatibility and ignored (the stack is
    always XLA + KV rendezvous)."""
    try:
        import cloudpickle as pickler  # type: ignore
    except ImportError:
        pickler = pickle

    # Caller-owned KV server: fn ships to ranks and results ship back
    # through it, so remote ranks need no shared filesystem.
    import secrets as _secrets

    from horovod_tpu.runtime.kvstore import (KVStoreClient, KVStoreServer,
                                             decode_secret)

    env = dict(os.environ if env is None else env)
    job_secret = env.get("HOROVOD_SECRET_KEY") or _secrets.token_hex(32)
    env["HOROVOD_SECRET_KEY"] = job_secret
    server = client = None
    try:
        server = KVStoreServer(secret=decode_secret(job_secret))
        client = KVStoreClient("127.0.0.1", server.port,
                               secret=decode_secret(job_secret))
    except Exception:
        server = client = None  # no native KV: shared-dir transport only

    import socket as _socket

    local_names = ("localhost", "127.0.0.1", _socket.gethostname())
    from horovod_tpu.run.launcher import parse_host_spec

    has_remote = bool(hosts) and any(
        h not in local_names for h, _ in parse_host_spec(hosts, np))
    if has_remote and client is None:
        raise NotImplementedError(
            "run(fn, hosts=...) with remote hosts needs the native KV "
            "store (g++) for the function/result exchange; launch a "
            "script with hvdrun instead.")

    try:
        with tempfile.TemporaryDirectory(prefix="hvdrun_fn_") as tmp:
            payload = pickler.dumps((fn, tuple(args), dict(kwargs or {})))
            fn_path = os.path.join(tmp, "fn.pkl")
            with open(fn_path, "wb") as f:
                f.write(payload)
            # Publish fn over the KV wire only when some rank can't read
            # the local file (remote hosts, or the no-shared-fs test
            # mode) — local ranks read fn.pkl from disk for free.
            no_shared = env.get("HOROVOD_RUNFUNC_NO_SHARED_FS") == "1"
            if client is not None and (has_remote or no_shared):
                client.set(FN_KEY, base64.b64encode(payload).decode())
            cmd = [sys.executable, "-m", "horovod_tpu.run.exec_fn",
                   fn_path, tmp]
            rc = launch(np, cmd, hosts=hosts, env=env, verbose=verbose,
                        kv_server=server)
            if rc != 0:
                raise RuntimeError(f"hvdrun function job failed (rc={rc})")
            results = []
            for r in range(np):
                path = os.path.join(tmp, f"result.{r}.pkl")
                if os.path.exists(path):
                    with open(path, "rb") as f:
                        results.append(pickle.load(f))
                    continue
                blob = client.try_get(RESULT_KEY.format(rank=r)) \
                    if client is not None else None
                if blob is None:
                    raise RuntimeError(
                        f"rank {r} produced no result (neither shared-dir "
                        "file nor KV entry)")
                results.append(pickle.loads(base64.b64decode(blob)))
            return results
    finally:
        if client is not None:
            client.close()
        if server is not None:
            server.stop()
