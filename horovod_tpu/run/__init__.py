"""horovod_tpu.run subpackage."""
