"""``hvdrun`` — the launcher.

Parity with ``horovodrun`` (reference ``horovod/run/runner.py:221-453``
CLI; ``run/gloo_run.py`` process model): allocate
rank/local_rank/cross_rank from a ``host:slots`` spec
(``gloo_run.py:54-112``), start the rendezvous KV server, export the
``HOROVOD_*`` env per rank (``gloo_run.py:152-163``), spawn ranks
(localhost: subprocess; remote hosts: ssh, as the reference does at
``gloo_run.py:189-234``), capture per-rank output
(``--output-filename`` → ``dir/rank.N/stdout|stderr``, reference
``gloo_run.py:204-217``), and kill the job when any rank fails
(``gloo_run.py:294-304``).  ``horovod_tpu.run.run(fn)`` is the
run-function mode (reference ``run/runner.py:719``).

TPU divergence: no NIC-probe/driver-service fan-out — the XLA
coordination service (rank 0) plus the KV rendezvous replace it.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
from dataclasses import dataclass

from horovod_tpu.common import config as _config
from horovod_tpu.common import logging as _log


def _start_metrics_aggregator(base_env: dict, kv, local_only: bool,
                              kv_addr: str, job_secret: str):
    """Fleet-wide ``/metrics`` (docs/metrics.md): when the operator set
    ``HOROVOD_METRICS_PORT``, the launcher serves the aggregate on that
    port — merging every rank's KV-published snapshot with ``rank`` /
    ``host`` labels, following the rank-0 index across elastic
    generations — and exports ``base + 1`` to ranks so per-rank
    endpoints (base+1+rank) never collide with the aggregate on a
    shared host.  Returns (server, kv_client) or None."""
    try:
        port = int(base_env.get("HOROVOD_METRICS_PORT") or 0)
    except ValueError:
        port = 0
    if port <= 0:
        return None
    base_env["HOROVOD_METRICS_PORT"] = str(port + 1)
    if kv is None:
        print("[hvdrun] metrics aggregation disabled: no native KV "
              "rendezvous for ranks to publish through", file=sys.stderr)
        return None
    from horovod_tpu.runtime import metrics as _metrics
    from horovod_tpu.runtime.kvstore import KVStoreClient, decode_secret

    try:
        kvc = KVStoreClient("127.0.0.1" if local_only else kv_addr,
                            kv.port, connect_timeout_s=10.0,
                            secret=decode_secret(job_secret))
    except Exception as exc:
        print(f"[hvdrun] metrics aggregation disabled: {exc}",
              file=sys.stderr)
        return None
    host = socket.gethostname()
    # Fleet goodput plane (docs/goodput.md): merged per-rank wall-clock
    # ledgers -> fleet goodput ratio, sliding-window dominant-bottleneck
    # naming, and the SLO burn-rate alert gauges, all riding the same
    # aggregate /metrics page.
    try:
        from horovod_tpu.perf import goodput as _goodput

        fleet = _goodput.FleetGoodput()
    except Exception:
        fleet = None

    def render() -> str:
        mine = {"meta": {"rank": "launcher", "host": host},
                "metrics": _metrics.registry().snapshot()}
        return _metrics.aggregate_render(kvc.try_get, [mine],
                                         fleet=fleet)

    try:
        srv = _metrics.MetricsHTTPServer(render, port)
    except OSError as exc:
        print(f"[hvdrun] metrics aggregation disabled: port {port}: "
              f"{exc}", file=sys.stderr)
        kvc.close()
        return None
    print(f"[hvdrun] fleet metrics: http://{host}:{port}/metrics "
          f"(per-rank endpoints at {port + 1}+rank)", file=sys.stderr)
    return srv, kvc, fleet


def _stop_metrics_aggregator(agg) -> None:
    if agg is None:
        return
    srv, kvc, fleet = agg
    srv.close()
    try:
        kvc.close()
    except Exception:
        pass
    # Wrap-up evidence line (docs/goodput.md): the last fleet goodput
    # report the aggregate computed — one number plus one named culprit
    # for the operator scrolling the launcher log.
    try:
        if fleet is not None and fleet.last:
            from horovod_tpu.perf import goodput as _goodput

            print("[hvdrun] " + _goodput.evidence_line(
                fleet.last, window_s=fleet.window_s), file=sys.stderr)
    except Exception:
        pass


def _sweep_flight_dir(base_env: dict, context: str) -> list[str]:
    """Flight-recorder sweep (docs/flight-recorder.md): when the job
    ran with ``--flight-dir``, report which per-rank dumps landed there
    — at wrap-up and after observed re-forms — and print the one-liner
    that merges them into a fleet trace.  Purely informational: the
    dumps are the ranks' own atomic writes; the launcher just makes
    sure nobody has to remember where the black boxes fell."""
    d = base_env.get("HOROVOD_FLIGHT_DIR") or ""
    if not d:
        return []
    from horovod_tpu.runtime import flight as _flight

    dumps = _flight.sweep(d)
    if dumps:
        print(f"[hvdrun] flight recorder ({context}): "
              f"{len(dumps)} dump(s) under {d}: "
              + ", ".join(os.path.basename(p) for p in dumps),
              file=sys.stderr)
        print(f"[hvdrun] merge + analyze with: python -m "
              f"horovod_tpu.trace merge {d}", file=sys.stderr)
    return dumps


def _sweep_health_dir(base_env: dict) -> None:
    """Training-health sweep (docs/health.md): when ranks dumped
    health snapshots (``HOROVOD_HEALTH_DIR``, falling back to the
    flight dir), surface any nonfinite culprits / active alerts at
    wrap-up and print the report one-liner.  Informational only, like
    the flight sweep above — the fleet ``/metrics`` merge carried the
    live gauges; this is the after-the-fact pointer."""
    d = base_env.get("HOROVOD_HEALTH_DIR") \
        or base_env.get("HOROVOD_FLIGHT_DIR") or ""
    if not d or not os.path.isdir(d):
        return
    try:
        from horovod_tpu.runtime import health as _health

        rep = _health.load_report(d)
    except Exception:
        return
    if not rep.get("ranks"):
        return
    culprits = rep.get("culprits") or []
    if culprits:
        who = ", ".join(f"rank {c['rank']}/{c['group']} "
                        f"({c['count']:g})" for c in culprits[:8])
        print(f"[hvdrun] training health: NONFINITE gradients observed "
              f"pre-reduction — culprit(s): {who}", file=sys.stderr)
    alerts = sorted({a for s in rep["ranks"]
                     for a in (s.get("active_alerts") or [])})
    if alerts:
        print(f"[hvdrun] training health: active alert(s) at exit: "
              f"{', '.join(alerts)}", file=sys.stderr)
    if culprits or alerts:
        print(f"[hvdrun] health report: python -m horovod_tpu.perf "
              f"health {d}", file=sys.stderr)


def _sweep_profile_dir(base_env: dict) -> None:
    """Perf-observatory sweep (docs/perf.md): when the job sampled
    device captures (``--profile-every-n-steps``), say where the
    rotating per-rank capture dirs are and print the report one-liner.
    Informational only, like the flight sweep above."""
    try:
        every = int(base_env.get("HOROVOD_PROFILE_EVERY_N_STEPS",
                                 "0") or 0)
    except ValueError:
        every = 0
    if every <= 0:
        return
    d = base_env.get("HOROVOD_PROFILE_DIR") or "hvd_profile"
    if not os.path.isdir(d):
        return
    ranks = sorted(e for e in os.listdir(d) if e.startswith("rank"))
    if not ranks:
        return
    print(f"[hvdrun] perf observatory: sampled device captures for "
          f"{len(ranks)} rank(s) under {d}", file=sys.stderr)
    print(f"[hvdrun] attribution report: python -m horovod_tpu.perf "
          f"report {d}", file=sys.stderr)


@dataclass
class SlotInfo:
    """Rank allocation record (reference ``gloo_run.py:54-112``)."""
    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int
    homogeneous: bool = True


def parse_host_spec(spec: str | None, np_: int) -> list[tuple[str, int]]:
    """``host1:4,host2:4`` -> [(host, slots)]; default localhost:np."""
    if not spec:
        return [("localhost", np_)]
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            host, slots = part.rsplit(":", 1)
            out.append((host, int(slots)))
        else:
            out.append((part, 1))
    return out


def parse_hostfile(path: str) -> list[tuple[str, int]]:
    """Reference hostfile format: ``hostname slots=N`` per line
    (``runner.py:518-545``)."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=", 1)[1])
            hosts.append((parts[0], slots))
    return hosts


def allocate(hosts: list[tuple[str, int]], np_: int) -> list[SlotInfo]:
    """Round-robin-free block allocation identical in spirit to the
    reference ``_allocate``: fill each host's slots in order."""
    slots: list[SlotInfo] = []
    host_names = [h for h, _ in hosts]
    rank = 0
    for host, nslots in hosts:
        for local in range(nslots):
            if rank >= np_:
                break
            slots.append(SlotInfo(host, rank, local,
                                  host_names.index(host), np_, 0, 0))
            rank += 1
    if rank < np_:
        raise ValueError(
            f"not enough slots ({rank}) for -np {np_}; add hosts/slots")
    per_host: dict[str, int] = {}
    for s in slots:
        per_host[s.hostname] = per_host.get(s.hostname, 0) + 1
    used_hosts = [h for h in host_names if per_host.get(h)]
    homogeneous = len(set(per_host.values())) == 1
    for s in slots:
        s.local_size = per_host[s.hostname]
        s.cross_size = len(used_hosts)
        s.cross_rank = used_hosts.index(s.hostname)
        s.homogeneous = homogeneous
    return slots


# libc handle resolved at import time: preexec_fn runs between fork and
# exec while the parent may hold allocator/import locks in other threads
# (the KV server is live by spawn time) — importing ctypes there can
# deadlock the child.  Prewarm prctl with a harmless PR_GET_PDEATHSIG so
# the first post-fork call does no FFI setup.
_LIBC = None
if sys.platform.startswith("linux"):
    try:
        import ctypes as _ctypes

        _LIBC = _ctypes.CDLL(None, use_errno=True)
        _LIBC.prctl(2, _ctypes.byref(_ctypes.c_int()), 0, 0, 0)
    except Exception:
        _LIBC = None


def _rank_preexec():
    """Run in each rank child between fork and exec.

    Reference ``run/common/util/safe_shell_exec.py:1-120`` runs every
    child in its own process group and kills the whole group on
    termination, so a rank's forked helpers die with it.  Additionally,
    ``PR_SET_PDEATHSIG`` makes the kernel SIGKILL the rank if the
    launcher itself dies abnormally (SIGKILL) — the reference gets the
    same effect from its in-process middleman watching the parent.

    SIGKILL, not SIGTERM: libraries in the rank (PJRT plugins, coord
    services) register Python-level SIGTERM handlers, and a rank whose
    main thread is parked in a C++ futex (a dead peer's barrier, a
    wedged tunnel) never runs them — observed as multi-hour 2 GB
    orphans surviving a launcher kill -9.  PDEATHSIG fires only when
    the launcher is already gone, so there is nobody left to escalate
    TERM → KILL; every launcher-alive path still sends SIGTERM first
    (graceful drain) before the KILL deadline.
    """
    os.setpgid(0, 0)
    if _LIBC is not None:
        try:
            PR_SET_PDEATHSIG = 1
            _LIBC.prctl(PR_SET_PDEATHSIG, signal.SIGKILL, 0, 0, 0)
        except Exception:
            pass  # group-kill paths below still apply


def _group_has_members(pgid: int) -> bool:
    """True if any live process sits in process group ``pgid`` within
    this launcher's session.

    Guards the dead-leader killpg: once a rank has been ``wait()``ed its
    PID is free for reuse, and an unrelated new group could claim the
    same pgid.  Ranks never ``setsid``, so their helpers stay in our
    session — a same-pgid group in a different session is a stranger.
    """
    try:
        my_sid = os.getsid(0)
        entries = os.listdir("/proc")
    except OSError:
        return False  # no /proc: skip dead-leader group kills
    for d in entries:
        if not d.isdigit():
            continue
        try:
            with open(f"/proc/{d}/stat", "rb") as f:
                st = f.read()
        except OSError:
            continue
        # fields after the parenthesised comm (may contain spaces):
        # state ppid pgrp session ...
        rest = st[st.rfind(b")") + 2:].split()
        try:
            if int(rest[2]) == pgid and int(rest[3]) == my_sid:
                return True
        except (IndexError, ValueError):
            continue
    return False


def _pid_is_live(pid: int) -> bool:
    """True if a live (or zombie) process currently holds ``pid``."""
    try:
        return os.path.exists(f"/proc/{pid}")
    except OSError:
        return False


def _proc_starttime(pid: int) -> str | None:
    """Kernel start-tick of ``pid`` (``/proc/<pid>/stat`` field 22) —
    the cheap process-identity stamp: a recycled pid necessarily has a
    different starttime.  None when unreadable (gone, or no /proc)."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            st = f.read()
        rest = st[st.rfind(b")") + 2:].split()
        return rest[19].decode()
    except (OSError, IndexError, ValueError):
        return None


def _stamp_identity(proc) -> None:
    """Record the group leader's /proc starttime at spawn so later
    signals can verify the pid still names OUR rank (ADVICE round 4: a
    recycled pid claimed by a new same-session group must not be
    killpg'd by the final cleanup loop)."""
    pid = getattr(proc, "pid", None)
    if pid:
        try:
            proc._hvd_starttime = _proc_starttime(pid)
        except AttributeError:
            pass  # minimal fake process without settable attributes


def _signal_rank(proc: subprocess.Popen, sig: int) -> None:
    """Signal a rank's whole process group, falling back to the PID.

    Pid-reuse guards, in order of strength: (1) the leader's /proc
    starttime recorded at spawn — a live holder of the pid whose
    starttime differs recycled the number, so nothing about that pid
    is ours and the signal is skipped entirely; (2) while the rank is
    un-reaped its zombie pins the PID, so the pgid is unambiguously
    ours; (3) once reaped with no identity stamp to compare, a live
    holder is conservatively treated as a stranger, and a leaderless
    group is killed only when its members sit in this launcher's
    session (``_group_has_members``).

    ``getattr`` guards let tests substitute minimal fake processes."""
    pid = getattr(proc, "pid", None)
    if pid:
        reaped = getattr(proc, "returncode", None) is not None
        if _pid_is_live(pid):
            recorded = getattr(proc, "_hvd_starttime", None)
            current = _proc_starttime(pid)
            if recorded is not None and current is not None \
                    and current != recorded:
                return  # pid recycled by a stranger: not our group
            if reaped and (recorded is None or current is None):
                return  # reaped + unverifiable identity: assume stranger
        if not reaped or _group_has_members(pid):
            try:
                os.killpg(pid, sig)
                return
            except OSError:
                pass
        elif reaped:
            return  # leader reaped, group empty: nothing to signal
    sender = getattr(proc, "send_signal", None)
    if sender is None:
        return
    try:
        sender(sig)
    except OSError:
        pass


class HostUnreachableError(RuntimeError):
    """A remote host failed the pre-spawn reachability check."""


def _forward_stream(src, dst, rank: int, tag: str,
                    timestamp: bool = False) -> threading.Thread:
    """Pump one rank's pipe to the console, line-buffered, each line
    prefixed ``[rank]<stdout|stderr>:`` (reference
    ``safe_shell_exec.py:61-94``; timestamps with
    ``--prefix-output-with-timestamp``)."""
    import time as _time

    def pump():
        for line in iter(src.readline, b""):
            ctx = (_time.strftime("%a %b %d %H:%M:%S %Y ")
                   if timestamp else "")
            dst.write(f"{ctx}[{rank}]<{tag}>:"
                      f"{line.decode(errors='replace')}")
            dst.flush()
        try:
            src.close()
        except OSError:
            pass

    t = threading.Thread(target=pump, daemon=True,
                         name=f"hvd-out-{rank}-{tag}")
    t.start()
    return t


def preflight_hosts(host_list: list[tuple[str, int]], start_timeout: float,
                    this_host: str | None = None) -> None:
    """Probe every remote host over ssh in parallel before spawning the
    world (reference ``run/runner.py:61-112``: threaded reachability
    check honoring ``--start-timeout``).  An unreachable host fails the
    job in seconds with its name, instead of hanging until the KV
    negotiation timeout."""
    this_host = this_host or socket.gethostname()
    remote = sorted({h for h, _ in host_list
                     if h not in ("localhost", this_host, "127.0.0.1")})
    if not remote:
        return
    errors: dict[str, str] = {}

    def check(h: str) -> None:
        connect_t = max(1, min(int(start_timeout), 30))
        try:
            rc = subprocess.run(
                ["ssh", "-o", "BatchMode=yes",
                 "-o", "StrictHostKeyChecking=no",
                 "-o", f"ConnectTimeout={connect_t}", h, "true"],
                capture_output=True, timeout=start_timeout)
            if rc.returncode != 0:
                detail = rc.stderr.decode(errors="replace").strip()
                errors[h] = detail.splitlines()[-1] if detail else \
                    f"ssh exited {rc.returncode}"
        except subprocess.TimeoutExpired:
            errors[h] = f"no ssh response within {start_timeout:.0f}s"
        except OSError as exc:
            errors[h] = str(exc)

    threads = [threading.Thread(target=check, args=(h,), daemon=True)
               for h in remote]
    for t in threads:
        t.start()
    import time as _time

    deadline = _time.monotonic() + start_timeout + 5
    for t in threads:
        t.join(timeout=max(0.1, deadline - _time.monotonic()))
    for h, t in zip(remote, threads):
        if t.is_alive():
            errors.setdefault(h, f"probe still running after "
                                 f"{start_timeout:.0f}s")
    if errors:
        detail = "; ".join(f"{h}: {msg}" for h, msg in sorted(errors.items()))
        raise HostUnreachableError(
            f"host(s) unreachable before start-timeout "
            f"({start_timeout:.0f}s): {detail}")


def _free_port() -> int:
    from horovod_tpu.common.util import free_port

    return free_port()


def check_build() -> str:
    """``hvdrun --check-build`` report (reference ``runner.py:115-150``):
    which frontends and transports this installation provides."""
    import horovod_tpu as hvd
    from horovod_tpu.common import basics as _basics

    def mark(v):
        return "X" if v else " "

    try:
        import horovod_tpu.tensorflow as _tf_fe

        tf_ok = _tf_fe.tensorflow_built()
    except Exception:
        tf_ok = False
    try:
        import torch  # noqa: F401

        torch_ok = True
    except ImportError:
        torch_ok = False
    try:
        import horovod_tpu.mxnet as _mx_fe

        mx_ok = _mx_fe.mxnet_built()
    except Exception:
        mx_ok = False
    try:
        from horovod_tpu.runtime import kvstore as _kv

        _kv._load()
        kv_ok = True
    except Exception:
        kv_ok = False
    return f"""\
horovod_tpu v{hvd.__version__}:

Available Frontends:
    [X] JAX
    [{mark(tf_ok)}] TensorFlow
    [{mark(torch_ok)}] PyTorch
    [{mark(mx_ok)}] MXNet

Available Controllers:
    [X] XLA coordination service
    [{mark(kv_ok)}] Native KV store (C++)

Available Tensor Operations:
    [X] XLA collectives (ICI/DCN)
    [{mark(_basics.xla_built())}] XLA runtime"""


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu job (horovodrun-compatible).")
    p.add_argument("-np", "--num-proc", type=int, required=False,
                   dest="np")
    p.add_argument("-cb", "--check-build", action="store_true",
                   help="show which frontends/transports are available "
                        "and exit (reference horovodrun --check-build)")
    p.add_argument("-H", "--hosts", default=None,
                   help="host1:slots,host2:slots (default localhost)")
    p.add_argument("--hostfile", default=None)
    p.add_argument("--output-filename", default=None,
                   help="per-rank output dir (rank.N/stdout|stderr)")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--config-file", default=None)
    p.add_argument("--gloo", action="store_true",
                   help="accepted for horovodrun compatibility (the "
                        "controller is always the XLA/KV stack)")
    p.add_argument("--mpi", action="store_true",
                   help="accepted for compatibility; ignored")
    p.add_argument("--start-timeout", type=int, default=120)
    p.add_argument("--preempt", default=None, metavar="RANK[:GRACE]",
                   help="actuator mode: address a graceful preemption "
                        "notice to RANK of an already-running elastic "
                        "job (rendezvous via HOROVOD_GLOO_RENDEZVOUS_"
                        "ADDR/PORT + HOROVOD_SECRET_KEY) and exit; an "
                        "optional :GRACE overrides the grace window in "
                        "seconds, e.g. --preempt 1:45")
    p.add_argument("--prefix-output-with-timestamp", action="store_true",
                   help="prepend a timestamp to each forwarded rank "
                        "output line (reference runner.py flag)")
    # knob flags (reference runner.py:279-415 subset)
    for knob in _config.knobs().values():
        if knob.cli:
            if isinstance(knob.default, bool):
                # --flag / --no-flag so default-true knobs are disablable
                p.add_argument(knob.cli,
                               action=argparse.BooleanOptionalAction,
                               default=None, help=knob.help)
            else:
                p.add_argument(knob.cli, default=None, help=knob.help)
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command")
    return p


def _rank_env(slot: SlotInfo, coord_addr: str, kv_addr: str, kv_port: int,
              base_env: dict) -> dict:
    env = dict(base_env)
    env.update({
        "HOROVOD_RANK": str(slot.rank),
        "HOROVOD_SIZE": str(slot.size),
        "HOROVOD_LOCAL_RANK": str(slot.local_rank),
        "HOROVOD_LOCAL_SIZE": str(slot.local_size),
        "HOROVOD_CROSS_RANK": str(slot.cross_rank),
        "HOROVOD_CROSS_SIZE": str(slot.cross_size),
        "HOROVOD_IS_HOMOGENEOUS": "1" if slot.homogeneous else "0",
        "HOROVOD_COORDINATOR_ADDR": coord_addr,
        "HOROVOD_CONTROLLER": "xla",
    })
    if kv_port:
        env["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = kv_addr
        env["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(kv_port)
    else:
        env.pop("HOROVOD_GLOO_RENDEZVOUS_ADDR", None)
        env.pop("HOROVOD_GLOO_RENDEZVOUS_PORT", None)
    return env


def launch(np_: int, command: list[str], hosts=None, hostfile=None,
           output_filename=None, verbose=False, start_timeout=120,
           env=None, kv_server=None,
           prefix_timestamp: bool = False, restart_attempts=None,
           checkpoint_dir=None) -> int:
    """Launch ``command`` on np_ ranks; returns the job exit code.

    ``kv_server``: a caller-owned :class:`KVStoreServer` to use for the
    rendezvous instead of creating one (the caller keeps it alive after
    the job, e.g. ``run()`` collecting run-func results — reference
    ``run/runner.py:631-657`` returns results through its rendezvous
    server the same way).  The caller must also have put the matching
    ``HOROVOD_SECRET_KEY`` into ``env``.

    Recovery (docs/fault-tolerance.md): when a rank dies the whole job
    is torn down within the shutdown deadline; with
    ``restart_attempts > 0`` (``HOROVOD_RESTART_ATTEMPTS``) the job is
    relaunched — on a fresh rendezvous server, so no stale negotiation
    key survives — with ``HOROVOD_RESTART_ATTEMPT`` exported, plus
    ``HOROVOD_RESUME_STEP`` pointing at the latest *complete* snapshot
    under ``checkpoint_dir`` (``HOROVOD_CHECKPOINT_DIR``; torn
    snapshots are refused via :func:`checkpoint.latest_complete`)."""
    host_list = (parse_hostfile(hostfile) if hostfile
                 else parse_host_spec(hosts, np_))
    slots = allocate(host_list, np_)
    this_host = socket.gethostname()
    preflight_hosts(host_list, start_timeout, this_host)
    local_only = all(h in ("localhost", this_host, "127.0.0.1")
                     for h, _ in host_list)
    # The KV rendezvous server runs here (launcher host); the jax
    # coordination service runs inside RANK 0's process, so its
    # advertised address must be rank 0's host — the first host in the
    # spec — not the launcher's.  The port is picked here and assumed
    # free on that host (the reference launcher makes the same bet for
    # its rendezvous ports).
    kv_addr = "127.0.0.1" if local_only else this_host
    rank0_host = host_list[0][0]
    coord_host = ("127.0.0.1" if local_only else
                  (this_host if rank0_host in ("localhost", this_host)
                   else rank0_host))

    attempts = (max(0, _config.get("restart_attempts"))
                if restart_attempts is None
                else max(0, int(restart_attempts)))
    ckpt_dir = (checkpoint_dir if checkpoint_dir is not None
                else (_config.get("checkpoint_dir") or None))
    if kv_server is not None and attempts:
        # A caller-owned rendezvous server cannot be recycled: the dead
        # attempt's negotiation keys would collide with the restarted
        # ranks' epoch-0 keys.
        print("[hvdrun] restart attempts disabled: caller-owned KV "
              "server cannot be recycled across attempts",
              file=sys.stderr)
        attempts = 0

    def _envtruthy(key: str) -> bool:
        raw = (os.environ if env is None else env).get(key, "")
        return _config._parse_bool(str(raw))

    elastic = _envtruthy("HOROVOD_ELASTIC")
    extra_env: dict[str, str] = {}
    rc = 1
    for attempt in range(attempts + 1):
        if elastic:
            # Survivor-continue mode: a dead rank is blacklisted and
            # re-formed around instead of killing the job; a restart
            # attempt only fires when the world shrank below
            # --min-ranks (docs/elastic.md).
            rc = _launch_elastic(command, slots, this_host, local_only,
                                 kv_addr, coord_host, output_filename,
                                 verbose, env, kv_server,
                                 prefix_timestamp, extra_env, host_list)
        else:
            rc = _launch_once(command, slots, this_host, local_only,
                              kv_addr, coord_host, output_filename,
                              verbose, env, kv_server, prefix_timestamp,
                              extra_env)
        if rc == 0:
            return 0
        if attempt >= attempts:
            break
        resume = None
        if ckpt_dir:
            from horovod_tpu import checkpoint as _ckpt

            try:
                resume = _ckpt.latest_complete(ckpt_dir)
            except OSError as exc:
                print(f"[hvdrun] checkpoint discovery under {ckpt_dir} "
                      f"failed: {exc}", file=sys.stderr)
        extra_env = {"HOROVOD_RESTART_ATTEMPT": str(attempt + 1)}
        if resume is not None:
            extra_env["HOROVOD_RESUME_STEP"] = str(resume)
        print(f"[hvdrun] job failed; restart attempt {attempt + 1}/"
              f"{attempts}"
              + (f" resuming from complete checkpoint step {resume}"
                 if resume is not None else
                 (" (no complete checkpoint found under "
                  f"{ckpt_dir})" if ckpt_dir else "")),
              file=sys.stderr)
    return rc


def _spawn_proc(command: list[str], renv: dict, hostname: str,
                rank_label, this_host: str, output_filename,
                prefix_timestamp: bool, pumps: list) -> subprocess.Popen:
    """Spawn one rank process (local subprocess or ssh) with output
    capture wired up; shared by the classic fail-fast path and the
    elastic monitor."""
    if output_filename:
        d = os.path.join(output_filename, f"rank.{rank_label}")
        os.makedirs(d, exist_ok=True)
        stdout = open(os.path.join(d, "stdout"), "w")
        stderr = open(os.path.join(d, "stderr"), "w")
    else:
        # console mode: rank-prefixed line forwarding (reference
        # safe_shell_exec.py:61-94)
        stdout = stderr = subprocess.PIPE

    def attach(proc):
        if output_filename:
            return
        # getattr guards: tests substitute minimal fake processes
        if getattr(proc, "stdout", None) is not None:
            pumps.append(_forward_stream(proc.stdout, sys.stdout,
                                         rank_label, "stdout",
                                         prefix_timestamp))
        if getattr(proc, "stderr", None) is not None:
            pumps.append(_forward_stream(proc.stderr, sys.stderr,
                                         rank_label, "stderr",
                                         prefix_timestamp))

    if hostname in ("localhost", this_host, "127.0.0.1"):
        proc = subprocess.Popen(command, env=renv, stdout=stdout,
                                stderr=stderr, preexec_fn=_rank_preexec)
        _stamp_identity(proc)
        attach(proc)
        return proc
    # remote: ssh with env exported inline (reference gloo_run.py:189)
    # — except the job secret, which must never ride argv (any local
    # user could read it via ps/procfs and defeat the KV auth); it is
    # shipped over ssh stdin instead.
    exports = " ".join(
        f"{k}={subprocess.list2cmdline([v])}"
        for k, v in renv.items()
        if k.startswith(("HOROVOD_", "XLA_", "JAX_", "PYTHON"))
        and k != "HOROVOD_SECRET_KEY")
    import shlex

    remote = ("read -r HOROVOD_SECRET_KEY; export HOROVOD_SECRET_KEY; "
              f"cd {shlex.quote(os.getcwd())} && "
              f"env {exports} {subprocess.list2cmdline(command)}")
    # `sh -c` wrapper: the remote login shell may be csh/fish where
    # `read -r`/`export` are not valid; sh is POSIX everywhere.
    proc = subprocess.Popen(
        ["ssh", "-o", "StrictHostKeyChecking=no", hostname,
         "sh -c " + shlex.quote(remote)],
        stdin=subprocess.PIPE, stdout=stdout, stderr=stderr,
        preexec_fn=_rank_preexec)
    _stamp_identity(proc)
    try:
        proc.stdin.write(
            (renv.get("HOROVOD_SECRET_KEY", "") + "\n").encode())
        proc.stdin.close()
    except (BrokenPipeError, OSError):
        pass  # rank died instantly; the reaper reports it
    attach(proc)
    return proc


def _drain_pumps(pumps: list, deadline_s: float = 30.0) -> None:
    """Join output pumps once every rank is reaped (pipes EOF quickly
    after child exit) with a generous shared deadline; a pump that is
    still draining at exit is abandoned with a warning NAMING the rank
    and stream, so a dropped output tail is never silent."""
    import time as _time

    pump_deadline = _time.monotonic() + deadline_s
    for t in pumps:
        t.join(timeout=max(0.0, pump_deadline - _time.monotonic()))
    abandoned = [t.name for t in pumps if t.is_alive()]
    if abandoned:
        print("[hvdrun] warning: abandoning output pump(s) still "
              f"draining at exit: {', '.join(abandoned)}; trailing "
              "output from those ranks may be lost", file=sys.stderr)


def _launch_once(command: list[str], slots: list[SlotInfo], this_host: str,
                 local_only: bool, kv_addr: str, coord_host: str,
                 output_filename, verbose, env, kv_server,
                 prefix_timestamp: bool, extra_env: dict) -> int:
    """One job attempt: fresh rendezvous + coordinator port, spawn every
    rank, fan failures in, tear the world down on the shutdown
    deadline."""
    from horovod_tpu.runtime.kvstore import KVStoreServer
    # Per-job HMAC secret for the KV wire (reference
    # run/common/util/secret.py:26: every launcher-service message is
    # HMAC-signed).  Generated fresh per job and handed to ranks via
    # env; a stray TCP client without it cannot touch negotiation state.
    import secrets as _secrets

    from horovod_tpu.runtime.kvstore import decode_secret

    kv = kv_server
    owns_kv = kv_server is None
    if owns_kv:
        job_secret = os.environ.get("HOROVOD_SECRET_KEY") or \
            _secrets.token_hex(32)
        try:
            kv = KVStoreServer(secret=decode_secret(job_secret))
            kv_port = kv.port
        except Exception as exc:  # no g++/unwritable dir: JaxCoordTransport
            print(f"[hvdrun] native KV store unavailable ({exc}); ranks "
                  "will use the coordination-service transport",
                  file=sys.stderr)
            kv = None
            kv_port = 0
    else:
        job_secret = (env or os.environ).get("HOROVOD_SECRET_KEY", "")
        kv_port = kv.port
    coord = f"{coord_host}:{_free_port()}"

    base_env = dict(os.environ if env is None else env)
    base_env["HOROVOD_SECRET_KEY"] = job_secret
    # Ranks must import horovod_tpu even when it isn't pip-installed and
    # the command is `python script.py` (sys.path[0] = the script's dir,
    # not our root).  The reference ssh launcher gets this for free by
    # cd'ing into an installed environment; here the package root rides
    # PYTHONPATH.
    import horovod_tpu as _pkg

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
        _pkg.__file__)))
    existing = base_env.get("PYTHONPATH", "")
    if pkg_root not in existing.split(os.pathsep):
        base_env["PYTHONPATH"] = (pkg_root + os.pathsep + existing
                                  if existing else pkg_root)
    # Restart metadata (attempt counter, resume step) for this attempt.
    for stale in ("HOROVOD_RESTART_ATTEMPT", "HOROVOD_RESUME_STEP"):
        base_env.pop(stale, None)
    base_env.update(extra_env)
    metrics_agg = _start_metrics_aggregator(base_env, kv, local_only,
                                            kv_addr, job_secret)
    procs: list[subprocess.Popen] = []
    pumps: list[threading.Thread] = []
    failed = threading.Event()
    exit_codes: dict[int, int] = {}

    def spawn(slot: SlotInfo) -> subprocess.Popen:
        renv = _rank_env(slot, coord, kv_addr, kv_port, base_env)
        return _spawn_proc(command, renv, slot.hostname, slot.rank,
                           this_host, output_filename, prefix_timestamp,
                           pumps)

    for slot in slots:
        if verbose:
            print(f"[hvdrun] starting rank {slot.rank} on {slot.hostname}",
                  file=sys.stderr)
        procs.append(spawn(slot))

    def reap(rank: int, proc: subprocess.Popen):
        rc = proc.wait()
        exit_codes[rank] = rc
        if rc != 0:
            failed.set()

    threads = [threading.Thread(target=reap, args=(s.rank, p), daemon=True)
               for s, p in zip(slots, procs)]
    for t in threads:
        t.start()

    try:
        while any(t.is_alive() for t in threads):
            if failed.is_set():
                # one dead rank kills the job (reference gloo_run.py:294)
                # Signal every rank's GROUP, even ranks that already
                # exited — a dead group leader can still leave live
                # helpers in its group (killpg targets the pgid, which
                # outlives the leader while members remain).
                for p in procs:
                    _signal_rank(p, signal.SIGTERM)
                break
            for t in threads:
                t.join(timeout=0.2)
        # TERM -> KILL escalation on one shared deadline (a rank stuck
        # in a shutdown barrier must not stall the whole job); the
        # deadline is HOROVOD_SHUTDOWN_TIMEOUT_SECONDS, the same knob
        # bounding the ranks' own distributed-shutdown barrier.
        import time as _time

        deadline = _time.monotonic() + max(
            1, _config.get("shutdown_timeout"))
        for t in threads:
            t.join(timeout=max(0.0, deadline - _time.monotonic()))
        for p in procs:
            _signal_rank(p, signal.SIGKILL)
        for t in threads:
            t.join(timeout=5)
        _drain_pumps(pumps)
    finally:
        _sweep_flight_dir(base_env, "wrap-up")
        _sweep_health_dir(base_env)
        _sweep_profile_dir(base_env)
        _stop_metrics_aggregator(metrics_agg)
        if kv is not None and owns_kv:
            kv.stop()
    bad = {r: c for r, c in exit_codes.items() if c != 0}
    if bad:
        print(f"[hvdrun] ranks failed: {bad}", file=sys.stderr)
        return 1
    return 0


class Blacklist:
    """Elastic-mode host blacklist with cooldown
    (``HOROVOD_BLACKLIST_COOLDOWN_SECONDS``): a host whose rank died is
    inadmissible for replacement spawns until the cooldown expires —
    a flapping host must not churn respawn/die cycles.  ``clock`` is
    injectable for tests."""

    def __init__(self, cooldown_s: float, clock=None):
        import time as _time

        self.cooldown_s = float(cooldown_s)
        self._clock = clock if clock is not None else _time.monotonic
        self._until: dict[str, float] = {}

    def add(self, host: str) -> None:
        self._until[host] = self._clock() + self.cooldown_s

    def admissible(self, host: str) -> bool:
        return self._clock() >= self._until.get(host, 0.0)

    def active(self) -> list[str]:
        now = self._clock()
        return sorted(h for h, t in self._until.items() if t > now)


def _exit_disposition(rc: int, *, cancelled: bool = False,
                      preempted: bool = False,
                      joiner_gave_up: bool = False) -> str:
    """Classify one elastic rank exit.  Exactly one disposition
    blacklists the host: ``died``.  A ``preempted`` exit — the rank's
    ``el/preempt/u/<uid>`` marker was present when it went away — is an
    announced departure from a HEALTHY host: not a death, not a job
    finish, and never a blacklist (the whole point of the graceful
    plane, docs/fault-tolerance.md; blacklisting it would bar the
    capacity that comes back after the maintenance event)."""
    if preempted:
        return "preempted"
    if rc == 0:
        return "finished"
    if cancelled:
        return "cancelled"
    if joiner_gave_up:
        return "join_timeout"
    return "died"


@dataclass
class _ElasticProc:
    proc: subprocess.Popen
    host: str
    label: str          # "0".."N-1" for seed ranks, "j<k>" for joiners
    uid: str
    joiner: bool
    cancelled: bool = False   # TERM'd waiting-room joiner, not a death


def _launch_elastic(command: list[str], slots: list[SlotInfo],
                    this_host: str, local_only: bool, kv_addr: str,
                    coord_host: str, output_filename, verbose, env,
                    kv_server, prefix_timestamp: bool,
                    extra_env: dict, host_list: list) -> int:
    """Elastic job attempt (``--elastic``): a dead rank does NOT kill
    the job.  The launcher keeps the rendezvous KV server alive across
    re-forms (survivors re-negotiate generations through it), blacklists
    the dead rank's host for the cooldown, and — once a non-blacklisted
    slot frees up — respawns a replacement process that registers as a
    joiner and is admitted at the survivors' next commit boundary,
    growing the world back toward the original ``-np``.  The job fails
    only when live membership falls below ``--min-ranks`` (at which
    point ``--restart-attempts`` is the fallback, as before)."""
    import json
    import secrets as _secrets
    import time as _time

    from horovod_tpu.runtime.kvstore import (KVStoreClient, KVStoreServer,
                                             decode_secret)

    kv = kv_server
    owns_kv = kv_server is None
    if owns_kv:
        job_secret = os.environ.get("HOROVOD_SECRET_KEY") or \
            _secrets.token_hex(32)
        try:
            kv = KVStoreServer(secret=decode_secret(job_secret))
        except Exception as exc:
            # Elastic re-forms need a rendezvous that outlives the jax
            # coordination service; without the native KV server there
            # is none, so degrade to the classic fail-fast job.
            print(f"[hvdrun] elastic mode needs the native KV store "
                  f"({exc}); falling back to fail-fast launch",
                  file=sys.stderr)
            return _launch_once(command, slots, this_host, local_only,
                                kv_addr, coord_host, output_filename,
                                verbose, env, kv_server, prefix_timestamp,
                                extra_env)
    else:
        job_secret = (env or os.environ).get("HOROVOD_SECRET_KEY", "")
    kv_port = kv.port
    np_ = len(slots)
    coord = f"{coord_host}:{_free_port()}"
    base_env = dict(os.environ if env is None else env)
    base_env["HOROVOD_SECRET_KEY"] = job_secret
    import horovod_tpu as _pkg

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
        _pkg.__file__)))
    existing = base_env.get("PYTHONPATH", "")
    if pkg_root not in existing.split(os.pathsep):
        base_env["PYTHONPATH"] = (pkg_root + os.pathsep + existing
                                  if existing else pkg_root)
    for stale in ("HOROVOD_RESTART_ATTEMPT", "HOROVOD_RESUME_STEP"):
        base_env.pop(stale, None)
    base_env.update(extra_env)
    base_env["HOROVOD_ELASTIC"] = "1"
    base_env["HOROVOD_ELASTIC_NP"] = str(np_)
    metrics_agg = _start_metrics_aggregator(base_env, kv, local_only,
                                            kv_addr, job_secret)
    # Launcher-side fleet-health metrics: merged into the aggregate
    # /metrics with rank="launcher" (docs/metrics.md) and mirrored by
    # the structured el/status log lines below.
    from horovod_tpu.runtime import metrics as _metrics

    m_deaths = _metrics.counter(
        "hvd_launcher_rank_deaths_total",
        "Rank processes the elastic launcher saw die.")
    m_respawns = _metrics.counter(
        "hvd_launcher_respawns_total",
        "Replacement joiner processes the elastic launcher spawned.")
    m_blacklist = _metrics.gauge(
        "hvd_elastic_blacklist_size",
        "Hosts currently under the elastic blacklist cooldown.")
    m_reforms = _metrics.counter(
        "hvd_launcher_reforms_total",
        "Re-forms observed via el/status.")
    m_gen = _metrics.gauge(
        "hvd_launcher_reform_generation",
        "Latest generation reported on el/status.")
    m_size = _metrics.gauge(
        "hvd_launcher_reform_size",
        "World size of the latest re-form on el/status.")
    m_reform_s = _metrics.gauge(
        "hvd_launcher_last_reform_seconds",
        "Latency of the latest re-form on el/status.")
    m_preempted = _metrics.counter(
        "hvd_launcher_preempted_total",
        "Ranks that exited after a graceful preemption drain (host "
        "NOT blacklisted; docs/fault-tolerance.md).")
    try:
        min_ranks = max(1, int(base_env.get("HOROVOD_MIN_RANKS") or 1))
    except ValueError:
        min_ranks = 1
    try:
        cooldown = float(
            base_env.get("HOROVOD_BLACKLIST_COOLDOWN_SECONDS") or 120.0)
    except ValueError:
        cooldown = 120.0
    blacklist = Blacklist(cooldown)
    capacity: dict[str, int] = {}
    for s in slots:
        capacity[s.hostname] = capacity.get(s.hostname, 0) + 1

    pumps: list[threading.Thread] = []
    live: dict[str, _ElasticProc] = {}
    finished: list[str] = []
    deaths: list[str] = []
    preempted: list[str] = []
    join_seq = 0
    spawn_budget = np_ * 3  # bound replacement churn
    aborted: str | None = None

    for slot in slots:
        renv = _rank_env(slot, coord, kv_addr, kv_port, base_env)
        renv["HOROVOD_ELASTIC_UID"] = f"rank{slot.rank}"
        if verbose:
            print(f"[hvdrun] starting rank {slot.rank} on {slot.hostname}",
                  file=sys.stderr)
        proc = _spawn_proc(command, renv, slot.hostname, slot.rank,
                           this_host, output_filename, prefix_timestamp,
                           pumps)
        live[str(slot.rank)] = _ElasticProc(
            proc, slot.hostname, str(slot.rank), f"rank{slot.rank}", False)

    def spawn_joiner(host: str, seq: int) -> None:
        uid = f"joiner{seq}"
        renv = dict(base_env)
        renv.update({
            "HOROVOD_RANK": "0", "HOROVOD_SIZE": "1",
            "HOROVOD_LOCAL_RANK": "0", "HOROVOD_LOCAL_SIZE": "1",
            "HOROVOD_CROSS_RANK": "0", "HOROVOD_CROSS_SIZE": "1",
            "HOROVOD_IS_HOMOGENEOUS": "1",
            "HOROVOD_ELASTIC_JOINER": "1",
            "HOROVOD_ELASTIC_UID": uid,
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": kv_addr,
            "HOROVOD_GLOO_RENDEZVOUS_PORT": str(kv_port),
            "HOROVOD_CONTROLLER": "xla",
        })
        renv.pop("HOROVOD_COORDINATOR_ADDR", None)
        label = f"j{seq}"
        proc = _spawn_proc(command, renv, host, label, this_host,
                           output_filename, prefix_timestamp, pumps)
        live[label] = _ElasticProc(proc, host, label, uid, True)
        m_respawns.inc()
        print(f"[hvdrun elastic] respawned replacement {label} on {host}"
              " (admitted at the survivors' next commit boundary)",
              file=sys.stderr)

    kvc = None
    try:
        kvc = KVStoreClient("127.0.0.1" if local_only else kv_addr,
                            kv_port, connect_timeout_s=10.0,
                            secret=decode_secret(job_secret))
    except Exception:
        kvc = None  # observability only; the job runs without it

    def admitted(uid: str) -> bool:
        if kvc is None:
            return True
        try:
            return kvc.try_get(f"el/admitted/{uid}") is not None
        except OSError:
            return True

    def joiner_timed_out(uid: str) -> bool:
        """True when the joiner retracted itself on the admission
        deadline (it writes the 'timeout' mark before exiting)."""
        if kvc is None:
            return False
        try:
            return kvc.try_get(f"el/admitted/{uid}") == "timeout"
        except OSError:
            return False

    def retract_joiner(uid: str) -> None:
        """Mark a dead/cancelled waiting-room joiner consumed: a later
        grow re-form scanning the join registry must never admit a
        ghost into the roster (the survivors would hang their re-init
        on a process that can never connect)."""
        if kvc is None:
            return
        try:
            kvc.set(f"el/admitted/{uid}", "dead")
        except OSError:
            pass

    def live_members() -> int:
        return sum(1 for r in live.values()
                   if not r.joiner or admitted(r.uid))

    # Closed-loop autopilot (docs/autopilot.md): the policy engine that
    # turns the evidence the launcher already aggregates — KV-published
    # heartbeat-staleness rankings, the FleetGoodput SLO burn — into
    # fleet actions through the machinery right above: preemptive host
    # blacklist + coordinated shrink, SLO-burn shrink, recovery grow.
    # ``want`` is the elastic target size the respawn sweep steers
    # toward; shrink/grow move it between --min-ranks and -np.
    from horovod_tpu.runtime import autopilot as _autopilot
    from horovod_tpu.runtime import preemption as _preemption

    want = {"np": np_}

    def _env_float(key: str, default: float) -> float:
        try:
            return float(base_env.get(key) or default)
        except ValueError:
            return default

    def _ap_blacklist(action) -> None:
        host = action.evidence.get("host")
        if host is None:
            rec = live.get(str(action.evidence.get("rank")))
            if rec is None:
                raise LookupError(
                    f"no live process for {action.target}")
            host = rec.host
        doomed = [lb for lb, r in live.items()
                  if r.host == host and r.proc.poll() is None]
        if live_members() - len(doomed) < min_ranks:
            raise RuntimeError(
                f"shedding {host} would drop below --min-ranks "
                f"{min_ranks}")
        blacklist.add(host)
        m_blacklist.set(len(blacklist.active()))
        for lb in doomed:
            # cancelled=False: the reap path records the death and
            # re-stamps the blacklist — the audit story stays coherent
            _signal_rank(live[lb].proc, signal.SIGKILL)
        action.evidence["killed"] = doomed
        print(f"[hvdrun autopilot] preemptive blacklist of straggler "
              f"host {host}: killed {doomed or 'no'} process(es); "
              f"survivors re-form without it", file=sys.stderr)

    def _ap_shrink(action) -> None:
        if live_members() <= min_ranks:
            raise RuntimeError(f"at the --min-ranks {min_ranks} floor")
        rank = action.evidence.get("bottleneck_rank")
        label = str(rank) if rank is not None \
            and str(rank) in live else None
        if label is None:
            label = next((lb for lb, r in live.items()
                          if r.proc.poll() is None), None)
        if label is None:
            raise LookupError("no live process to shed")
        rec = live[label]
        rec.cancelled = True  # deliberate shed: host stays admissible
        _signal_rank(rec.proc, signal.SIGKILL)
        want["np"] = max(min_ranks, want["np"] - 1)
        action.evidence["killed"] = [label]
        action.evidence["target_np"] = want["np"]
        print(f"[hvdrun autopilot] SLO-burn shrink: shed rank {label} "
              f"on {rec.host} (elastic target now {want['np']})",
              file=sys.stderr)

    def _ap_grow(action) -> None:
        if want["np"] >= np_:
            raise RuntimeError(f"already at the launched -np {np_}")
        want["np"] += 1
        action.evidence["target_np"] = want["np"]
        print(f"[hvdrun autopilot] SLO recovered: elastic target back "
              f"to {want['np']} (respawn sweep grows on its next "
              f"pass)", file=sys.stderr)

    def _resolve_uid(rank: int) -> str:
        """Current-generation rank -> stable elastic uid (the address
        ``request_drain`` wants).  Seed ranks start life as uid
        ``rank<k>``, so that is also the safe fallback before the
        first re-form publishes a roster."""
        if kvc is not None:
            try:
                status = kvc.try_get("el/status")
                if status:
                    gen = json.loads(status).get("gen")
                    roster = kvc.try_get(f"el/g{gen}/roster")
                    if roster:
                        for m in json.loads(roster).get("members") or []:
                            if int(m.get("rank", -1)) == int(rank):
                                return str(m["uid"])
            except (OSError, ValueError, TypeError, KeyError):
                pass
        return f"rank{rank}"

    def _ap_preempt(action) -> None:
        if kvc is None:
            raise RuntimeError("no KV client to address the notice")
        rank = int(action.evidence.get("rank"))
        uid = _resolve_uid(rank)
        _preemption.request_drain(
            kvc, uid, grace_s=action.evidence.get("grace_s"),
            source=str(action.evidence.get("source") or "launcher"))
        action.evidence["uid"] = uid
        print(f"[hvdrun autopilot] graceful drain ordered for rank "
              f"{rank} (uid {uid})", file=sys.stderr)

    ap = _autopilot.Autopilot.from_env(base_env, actuators={
        "straggler_blacklist": _ap_blacklist,
        "slo_burn_shrink": _ap_shrink,
        "slo_recover_grow": _ap_grow,
        "preempt_drain": _ap_preempt,
    })
    ap_fleet = None
    ap_next = 0.0
    if ap is not None:
        from horovod_tpu.perf import goodput as _goodput

        # Dedicated FleetGoodput with the job-env SLO: the aggregate
        # /metrics fleet updates only when scraped, and the autopilot
        # must not depend on an operator polling a dashboard.
        ap_fleet = _goodput.FleetGoodput(
            slo=_env_float("HOROVOD_GOODPUT_SLO", 0.0),
            window_s=_env_float("HOROVOD_GOODPUT_WINDOW_SECONDS",
                                300.0))
        print(f"[hvdrun autopilot] engaged"
              f"{' (dry-run)' if ap.dry_run else ''}: rules "
              f"{', '.join(_autopilot.RULES[:3] + ('preempt_drain',))}",
              file=sys.stderr)

    # Satellite (docs/fault-tolerance.md): the launcher's OWN SIGTERM
    # triggers a fleet-wide grace drain — notice every live rank over
    # the rendezvous KV, wait out min(grace, shutdown deadline) for
    # clean drain exits, and only then fall through to the existing
    # TERM -> KILL escalation below.  A second SIGTERM skips the wait.
    grace_s = _env_float("HOROVOD_PREEMPT_GRACE_SECONDS", 30.0)
    shutdown_s = _env_float("HOROVOD_SHUTDOWN_TIMEOUT_SECONDS",
                            float(_config.get("shutdown_timeout")))
    term_signals = {"n": 0}
    drain = {"on": False, "deadline": 0.0}
    term_installed = False
    prev_term = None
    if threading.current_thread() is threading.main_thread():
        try:
            prev_term = signal.signal(
                signal.SIGTERM,
                lambda signum, frame: term_signals.__setitem__(
                    "n", term_signals["n"] + 1))
            term_installed = True
        except (ValueError, OSError):
            term_installed = False

    preempt_req = {"last": None}
    last_status = None
    try:
        while live:
            _time.sleep(0.25)
            for label, rec in list(live.items()):
                rc = rec.proc.poll()
                if rc is None:
                    continue
                del live[label]
                disp = _exit_disposition(
                    rc, cancelled=rec.cancelled,
                    preempted=(kvc is not None
                               and _preemption.drain_requested(
                                   kvc, rec.uid)),
                    joiner_gave_up=(rec.joiner
                                    and joiner_timed_out(rec.uid)))
                if disp == "preempted":
                    preempted.append(label)
                    m_preempted.inc()
                    # Announced departure: the host stays admissible,
                    # and the elastic target shrinks so the respawn
                    # sweep doesn't re-place a rank on doomed capacity.
                    want["np"] = max(min_ranks, want["np"] - 1)
                    print(f"[hvdrun elastic] rank {label} on {rec.host} "
                          f"exited after graceful preemption drain "
                          f"(rc={rc}); host NOT blacklisted, elastic "
                          f"target now {want['np']}", file=sys.stderr)
                    continue
                if disp == "finished":
                    finished.append(label)
                    if verbose:
                        print(f"[hvdrun elastic] rank {label} finished",
                              file=sys.stderr)
                elif disp == "cancelled":
                    pass  # waiting-room joiner we TERM'd at wrap-up
                elif disp == "join_timeout":
                    # Admission-timeout exit: the joiner self-retracted
                    # because no commit boundary came within its
                    # deadline — a cadence mismatch, not a host fault.
                    # Blacklisting the (healthy) host would compound it.
                    print(f"[hvdrun elastic] replacement {label} gave "
                          "up waiting for admission (commit cadence > "
                          "HOROVOD_ELASTIC_JOIN_TIMEOUT_SECONDS?); "
                          f"host {rec.host} NOT blacklisted",
                          file=sys.stderr)
                else:
                    deaths.append(label)
                    blacklist.add(rec.host)
                    m_deaths.inc()
                    m_blacklist.set(len(blacklist.active()))
                    if rec.joiner and not admitted(rec.uid):
                        retract_joiner(rec.uid)
                    # a dead leader can leave live helpers in its group
                    _signal_rank(rec.proc, signal.SIGKILL)
                    wrapup = (" — died during wrap-up, no survivor "
                              "loop left to re-form around it"
                              if finished else "")
                    print(f"[hvdrun elastic] rank {label} on {rec.host} "
                          f"died (rc={rc}); blacklisting {rec.host} for "
                          f"{cooldown:.0f}s; {len(live)} process(es) "
                          f"still live (min-ranks {min_ranks}){wrapup}",
                          file=sys.stderr)
            if kvc is not None:
                try:
                    status = kvc.try_get("el/status")
                except OSError:
                    status = None
                if status and status != last_status:
                    last_status = status
                    try:
                        d = json.loads(status)
                    except ValueError:
                        d = None
                    if d is not None:
                        # Structured re-form record: key=value fields
                        # (machine-parseable, docs/metrics.md) instead
                        # of the old ad-hoc prose print; force=True
                        # keeps it visible at the default log level.
                        _log.info(
                            "elastic re-form complete", force=True,
                            gen=d.get("gen"), size=d.get("size"),
                            dead=d.get("dead") or [],
                            grown=d.get("grown") or [],
                            reform_s=d.get("reform_s"),
                            compile_s=d.get("compile_s"),
                            aot_hits=d.get("aot_hits"),
                            reforms=d.get("reforms"),
                            reason=d.get("reason"),
                            blacklist=blacklist.active())
                        m_reforms.inc()
                        for gauge, key in ((m_gen, "gen"),
                                           (m_size, "size"),
                                           (m_reform_s, "reform_s")):
                            try:
                                gauge.set(float(d.get(key) or 0))
                            except (TypeError, ValueError):
                                pass
                        m_blacklist.set(len(blacklist.active()))
                        # Re-forming ranks dumped their old-generation
                        # rings just before teardown — surface them now
                        # so the postmortem exists before the job ends.
                        _sweep_flight_dir(
                            base_env,
                            f"re-form gen {d.get('gen')}")
            if ap is not None and kvc is not None:
                nowm = _time.monotonic()
                if nowm >= ap_next:
                    # Evidence sweep on its own cadence (the 0.25s poll
                    # is for reaping): pull the ranks' KV-published
                    # snapshots, derive lateness + the SLO report, let
                    # the engine judge.  Failures only cost this sweep.
                    ap_next = nowm + 2.0
                    try:
                        snaps, _ = _metrics.aggregate_snapshots(
                            kvc.try_get)
                    except Exception:
                        snaps = []
                    try:
                        _autopilot.launcher_observe(ap, snaps,
                                                    fleet=ap_fleet)
                    except Exception as exc:
                        print(f"[hvdrun autopilot] sweep failed: "
                              f"{exc}", file=sys.stderr)
                    ap.refresh_gauges()
            if kvc is not None:
                # --preempt actuator requests posted over the KV:
                # resolve the current rank to its stable uid and order
                # the graceful drain (through the autopilot's ungated
                # preempt_drain rule when engaged, so the verdict +
                # evidence land on the audit trail; directly otherwise).
                try:
                    req = kvc.try_get("el/preempt_req")
                except OSError:
                    req = None
                if req and req != preempt_req["last"]:
                    preempt_req["last"] = req
                    try:
                        d = json.loads(req)
                        rank = int(d["rank"])
                    except (ValueError, TypeError, KeyError):
                        d, rank = {}, None
                    if rank is not None:
                        if ap is not None:
                            ap.observe_preemption(
                                rank,
                                source=str(d.get("source") or "cli"),
                                grace_s=d.get("grace_s"))
                        else:
                            _preemption.request_drain(
                                kvc, _resolve_uid(rank),
                                grace_s=d.get("grace_s"),
                                source=str(d.get("source") or "cli"))
                            print(f"[hvdrun elastic] graceful drain "
                                  f"ordered for rank {rank} "
                                  f"(--preempt)", file=sys.stderr)
            if term_signals["n"] and not drain["on"]:
                drain["on"] = True
                wait_s = max(0.0, min(grace_s, shutdown_s))
                drain["deadline"] = _time.monotonic() + wait_s
                print(f"[hvdrun elastic] SIGTERM: fleet-wide graceful "
                      f"drain — noticing {len(live)} rank(s), waiting "
                      f"up to {wait_s:.0f}s for clean drain exits "
                      f"before TERM/KILL escalation", file=sys.stderr)
                if kvc is not None:
                    for rec in live.values():
                        try:
                            _preemption.request_drain(
                                kvc, rec.uid, grace_s=grace_s,
                                source="launcher:SIGTERM")
                        except OSError:
                            pass
                else:
                    # No KV to address notices through: the ranks' own
                    # SIGTERM handlers are the fallback notice path.
                    for rec in live.values():
                        _signal_rank(rec.proc, signal.SIGTERM)
            if drain["on"] and live \
                    and (term_signals["n"] > 1
                         or _time.monotonic() >= drain["deadline"]):
                aborted = (f"graceful drain window closed with "
                           f"{len(live)} rank(s) still live")
                break
            if not live:
                break
            members = live_members()
            if deaths and members < min_ranks and not finished:
                aborted = (f"live membership {members} fell below "
                           f"--min-ranks {min_ranks}")
                break
            if finished:
                # Job is wrapping up: a joiner still in the admission
                # waiting room will never be admitted — release it so
                # the launcher doesn't wait out its rendezvous timeout.
                for rec in live.values():
                    if rec.joiner and not rec.cancelled \
                            and not admitted(rec.uid):
                        rec.cancelled = True
                        retract_joiner(rec.uid)
                        _signal_rank(rec.proc, signal.SIGTERM)
            elif spawn_budget > 0 and not drain["on"]:
                waiting = sum(1 for r in live.values()
                              if r.joiner and not admitted(r.uid))
                missing = want["np"] - (members + waiting)
                per_host = {h: 0 for h in capacity}
                for r in live.values():
                    per_host[r.host] = per_host.get(r.host, 0) + 1
                for _ in range(max(0, missing)):
                    host = next(
                        (h for h, _n in host_list
                         if per_host.get(h, 0) < capacity.get(h, 0)
                         and blacklist.admissible(h)), None)
                    if host is None:
                        break
                    join_seq += 1
                    spawn_budget -= 1
                    per_host[host] = per_host.get(host, 0) + 1
                    spawn_joiner(host, join_seq)
        if aborted:
            print(f"[hvdrun elastic] aborting job: {aborted}",
                  file=sys.stderr)
            for rec in live.values():
                _signal_rank(rec.proc, signal.SIGTERM)
            deadline = _time.monotonic() + max(
                1, _config.get("shutdown_timeout"))
            for rec in live.values():
                while rec.proc.poll() is None \
                        and _time.monotonic() < deadline:
                    _time.sleep(0.1)
            for rec in live.values():
                _signal_rank(rec.proc, signal.SIGKILL)
        _drain_pumps(pumps)
    finally:
        if term_installed:
            try:
                signal.signal(signal.SIGTERM,
                              prev_term or signal.SIG_DFL)
            except (ValueError, OSError):
                pass
        if ap is not None and ap.actions:
            # The verdicts live on the launcher's own flight ring —
            # land them beside the rank dumps so the merged trace
            # carries every autopilot action with its evidence tuple.
            from horovod_tpu.runtime import flight as _flight

            _flight.dump("launcher wrap-up",
                         directory=base_env.get(
                             "HOROVOD_FLIGHT_DIR") or None)
            ap_stats = ap.stats()
            print(f"[hvdrun autopilot] "
                  f"{ap_stats['actions_total']} verdict(s): "
                  f"{ap_stats['by_outcome']}", file=sys.stderr)
        _sweep_flight_dir(base_env, "wrap-up")
        _sweep_health_dir(base_env)
        _sweep_profile_dir(base_env)
        _stop_metrics_aggregator(metrics_agg)
        if kvc is not None:
            try:
                kvc.close()
            except Exception:
                pass
        if kv is not None and owns_kv:
            kv.stop()
    if deaths:
        print(f"[hvdrun elastic] job saw {len(deaths)} rank death(s) "
              f"({deaths}); blacklisted host(s): "
              f"{blacklist.active() or 'none (cooldowns expired)'}",
              file=sys.stderr)
    if preempted:
        print(f"[hvdrun elastic] {len(preempted)} rank(s) left via "
              f"graceful preemption drain ({preempted}); their hosts "
              "were NOT blacklisted", file=sys.stderr)
    if aborted is None and finished:
        return 0
    if aborted is None and preempted and not deaths:
        # Every exit was a clean announced drain (the launcher-SIGTERM
        # fleet drain ends exactly here): a successful wrap-up, with
        # the emergency commit on disk for the resume.
        return 0
    if aborted is None:
        print("[hvdrun elastic] no rank finished successfully",
              file=sys.stderr)
    return 1


def preempt_request(spec: str, env: dict) -> int:
    """``hvdrun --preempt RANK[:GRACE]`` — the operator actuator:
    connect to a RUNNING elastic job's rendezvous KV (address, port and
    secret from the environment, exactly what the job exported to its
    ranks) and post the preemption request the launcher's monitor loop
    turns into a graceful drain.  Returns immediately; the drain
    itself is asynchronous (watch the job log / flight trace)."""
    import json as _json
    import time as _time

    from horovod_tpu.runtime.kvstore import KVStoreClient, decode_secret

    part = spec.split(":", 1)
    try:
        rank = int(part[0])
        grace = float(part[1]) if len(part) > 1 else None
    except ValueError:
        print(f"hvdrun: bad --preempt spec {spec!r} (want RANK or "
              "RANK:GRACE_SECONDS)", file=sys.stderr)
        return 2
    addr = env.get("HOROVOD_GLOO_RENDEZVOUS_ADDR") or "127.0.0.1"
    try:
        port = int(env.get("HOROVOD_GLOO_RENDEZVOUS_PORT") or 0)
    except ValueError:
        port = 0
    if port <= 0:
        print("hvdrun: --preempt needs HOROVOD_GLOO_RENDEZVOUS_ADDR/"
              "PORT (and HOROVOD_SECRET_KEY) of the running job",
              file=sys.stderr)
        return 2
    try:
        kvc = KVStoreClient(addr, port, connect_timeout_s=10.0,
                            secret=decode_secret(
                                env.get("HOROVOD_SECRET_KEY", "")))
    except Exception as exc:
        print(f"hvdrun: cannot reach the job rendezvous at "
              f"{addr}:{port}: {exc}", file=sys.stderr)
        return 1
    try:
        kvc.set_overwrite("el/preempt_req", _json.dumps(
            {"rank": rank, "grace_s": grace, "source": "cli",
             "wall": _time.time()}, sort_keys=True))
    except OSError as exc:
        print(f"hvdrun: preemption request failed: {exc}",
              file=sys.stderr)
        return 1
    finally:
        try:
            kvc.close()
        except Exception:
            pass
    print(f"[hvdrun] graceful preemption requested for rank {rank}"
          + (f" (grace {grace:.0f}s)" if grace is not None else ""),
          file=sys.stderr)
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.check_build:
        print(check_build())
        return 0
    if args.preempt is not None:
        if args.config_file:
            _config.load_config_file(args.config_file)
        return preempt_request(
            args.preempt, _config.set_env_from_args(args,
                                                    dict(os.environ)))
    if args.np is None:
        print("hvdrun: -np is required (unless --check-build)",
              file=sys.stderr)
        return 2
    if args.config_file:
        _config.load_config_file(args.config_file)
    env = _config.set_env_from_args(args, dict(os.environ))
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("hvdrun: no command given", file=sys.stderr)
        return 2
    # Restart knobs ride the launch env dict (set_env_from_args exports
    # CLI flags there, not into os.environ, which _config.get reads) —
    # resolve them here so --restart-attempts/--checkpoint-dir work.
    try:
        restart_attempts = int(
            env.get("HOROVOD_RESTART_ATTEMPTS") or 0)
    except ValueError:
        restart_attempts = 0
    return launch(args.np, command, hosts=args.hosts,
                  hostfile=args.hostfile,
                  output_filename=args.output_filename,
                  verbose=args.verbose,
                  start_timeout=args.start_timeout, env=env,
                  prefix_timestamp=args.prefix_output_with_timestamp,
                  restart_attempts=restart_attempts,
                  checkpoint_dir=env.get("HOROVOD_CHECKPOINT_DIR") or None)


if __name__ == "__main__":
    sys.exit(main())
