"""``python -m horovod_tpu.run`` == ``hvdrun``."""

import sys

from horovod_tpu.run.launcher import main

sys.exit(main())
