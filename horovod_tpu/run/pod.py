"""Cloud TPU pod environment discovery — the TPU analog of the
reference's cluster integrations (``run/util/lsf.py`` LSF introspection,
``run/js_run.py`` jsrun): when a process starts under a TPU pod
orchestrator (GCE TPU VM workers, GKE megascale), rank/size/coordinator
come from the pod metadata environment instead of launcher-exported
``HOROVOD_*`` vars or hostfiles.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

# jax's own cluster auto-detect uses this port for the coordinator on
# TPU pods; keep the same convention so mixed launches agree.
_COORD_PORT = 8476


@dataclass
class PodInfo:
    rank: int
    size: int
    coordinator: str      # host:port of rank 0 ("" when auto)
    source: str           # which metadata convention matched
    auto: bool = False    # let jax.distributed auto-detect topology


def detect(env=None) -> PodInfo | None:
    """Return pod topology if this process runs inside a TPU pod
    orchestrator, else None.  Checked conventions, most specific first:

    * GKE megascale (multislice): ``MEGASCALE_*`` present — topology is
      multi-dimensional (slice × host), so detection returns
      ``auto=True`` and init hands off to
      ``jax.distributed.initialize()``'s own cluster resolution (it
      understands megascale natively).  Checked FIRST: multislice
      workers also carry slice-local ``TPU_WORKER_*`` vars, which would
      otherwise split the job into per-slice worlds.
    * GCE TPU VM workers: ``TPU_WORKER_ID`` + ``TPU_WORKER_HOSTNAMES``
      (comma-separated, index = worker id).
    * Generic cloud: ``CLOUD_TPU_TASK_ID`` + ``TPU_PROCESS_ADDRESSES``.
    """
    env = os.environ if env is None else env
    if ("MEGASCALE_COORDINATOR_ADDRESS" in env
            and "MEGASCALE_NUM_SLICES" in env):
        return PodInfo(-1, -1, "", "megascale", auto=True)
    # Malformed metadata (empty/non-numeric ids) means "not a pod", not
    # a crash: callers fall back to single-process init.
    if "TPU_WORKER_ID" in env and "TPU_WORKER_HOSTNAMES" in env:
        try:
            hosts = [h.strip()
                     for h in env["TPU_WORKER_HOSTNAMES"].split(",")
                     if h.strip()]
            rank = int(env["TPU_WORKER_ID"])
            if hosts and 0 <= rank < len(hosts):
                return PodInfo(rank, len(hosts),
                               f"{hosts[0]}:{_COORD_PORT}", "tpu_worker")
        except ValueError:
            pass
    if "CLOUD_TPU_TASK_ID" in env and "TPU_PROCESS_ADDRESSES" in env:
        try:
            addrs = [a.strip()
                     for a in env["TPU_PROCESS_ADDRESSES"].split(",")
                     if a.strip()]
            rank = int(env["CLOUD_TPU_TASK_ID"])
            if addrs and 0 <= rank < len(addrs):
                coord = addrs[0]
                if ":" not in coord:
                    coord = f"{coord}:{_COORD_PORT}"
                return PodInfo(rank, len(addrs), coord, "cloud_tpu")
        except ValueError:
            pass
    return None
