"""Per-rank entry for run-function mode: load fn, init, execute, send
the return value back to the launcher.

The reference returns results through its KVStore server
(``run/runner.py:631-657``); here both the pickled function (when the
launcher's tempdir isn't visible on this host) and the result ride the
job KV store, base64-coded over its string wire.  The shared-dir file
is kept as the no-native-KV fallback.
"""

from __future__ import annotations

import base64
import os
import pickle
import sys

FN_KEY = "runfunc/fn"
RESULT_KEY = "runfunc/result/{rank}"


def _kv_client():
    """Job KV client from the launcher-exported env, or None."""
    from horovod_tpu.common import config

    addr = config.get("rendezvous_addr")
    port = config.get("rendezvous_port")
    if not addr or not port:
        return None
    try:
        from horovod_tpu.runtime.kvstore import KVStoreClient

        return KVStoreClient(addr, int(port))
    except Exception:
        return None


def main() -> int:
    fn_path, out_dir = sys.argv[1], sys.argv[2]
    no_shared = os.environ.get("HOROVOD_RUNFUNC_NO_SHARED_FS") == "1"
    kv = _kv_client()
    # The launcher serializes fn with cloudpickle (closures, lambdas);
    # plain pickle can load those payloads only when cloudpickle is
    # importable here.  ANY unpickling failure on a host without
    # cloudpickle gets the clear diagnosis (chaining the original) —
    # the raw failure mode varies by payload (ModuleNotFoundError,
    # AttributeError on a _cloudpickle lookup, bare UnpicklingError)
    # and every spelling used to surface as an opaque stack from deep
    # inside pickle.
    def _load(raw: bytes):
        try:
            return pickle.loads(raw)
        except Exception as e:
            if isinstance(e, ModuleNotFoundError) \
                    and "cloudpickle" not in str(e):
                # a missing USER module (by-reference payload): the real
                # fix is installing that module, not cloudpickle —
                # surface it untouched
                raise
            try:
                import cloudpickle  # noqa: F401
                has_cloudpickle = True
            except ImportError:
                has_cloudpickle = False
            if not has_cloudpickle or "cloudpickle" in str(e):
                raise RuntimeError(
                    "cloudpickle required on remote hosts for run-func "
                    "mode: the launcher serialized the function with "
                    "cloudpickle and this host "
                    f"({os.uname().nodename}) could not deserialize it "
                    f"({type(e).__name__}: {e}). Install 'cloudpickle' "
                    "on every host in the job.") from e
            raise

    if os.path.exists(fn_path) and not no_shared:
        with open(fn_path, "rb") as f:
            fn, args, kwargs = _load(f.read())
    elif kv is not None:
        blob = kv.get_blocking(FN_KEY, timeout_s=60.0)
        fn, args, kwargs = _load(base64.b64decode(blob))
    else:
        print(f"[exec_fn] no function source: {fn_path} absent and no KV",
              file=sys.stderr)
        return 1
    import horovod_tpu as hvd

    hvd.init()
    rank = hvd.rank()
    try:
        result = fn(*args, **kwargs)
    finally:
        hvd.shutdown()
    payload = pickle.dumps(result)
    # shared-dir file first (free on the common localhost path); the KV
    # wire carries the result only when the launcher's dir isn't
    # reachable from this host — the case the KV transport exists for
    sent = False
    if not no_shared:
        try:
            tmp = os.path.join(out_dir, f".result.{rank}.tmp")
            with open(tmp, "wb") as f:
                f.write(payload)
            os.replace(tmp, os.path.join(out_dir, f"result.{rank}.pkl"))
            sent = True
        except OSError:
            pass  # out_dir not on this host: try the KV wire
    if not sent and kv is not None:
        try:
            kv.set(RESULT_KEY.format(rank=rank),
                   base64.b64encode(payload).decode())
            sent = True
        except OSError:
            pass
    if kv is not None:
        kv.close()
    return 0 if sent else 2


if __name__ == "__main__":
    sys.exit(main())
