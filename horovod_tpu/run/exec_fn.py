"""Per-rank entry for run-function mode: unpickle fn, init, execute,
persist the return value for the launcher to collect (the reference
returns results through its KVStore server, ``run/runner.py:631-657``;
a shared filesystem path does the same job on one host)."""

from __future__ import annotations

import os
import pickle
import sys


def main() -> int:
    fn_path, out_dir = sys.argv[1], sys.argv[2]
    with open(fn_path, "rb") as f:
        fn, args, kwargs = pickle.load(f)
    import horovod_tpu as hvd

    hvd.init()
    rank = hvd.rank()
    try:
        result = fn(*args, **kwargs)
    finally:
        hvd.shutdown()
    tmp = os.path.join(out_dir, f".result.{rank}.tmp")
    with open(tmp, "wb") as f:
        pickle.dump(result, f)
    os.replace(tmp, os.path.join(out_dir, f"result.{rank}.pkl"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
