"""MXNet tensor ops over the shared eager engine.

Parity with reference ``horovod/mxnet/mpi_ops.py`` (246 LoC): sync and
in-place collectives on ``mx.nd.NDArray``.  The reference pushes ops
through the MXNet engine asynchronously with a ``priority`` argument
(``mpi_ops.cc``); here NDArrays bridge via numpy into the negotiated
eager engine (the same wire every frontend shares), and ``priority`` is
accepted for API compatibility — submission order already encodes it,
and the controller fuses per cycle regardless.

MXNet itself is imported lazily: the module is importable (for
``mxnet_built()`` probing) without MXNet installed.
"""

from __future__ import annotations

import numpy as np

from horovod_tpu.common.basics import rank, size  # noqa: F401
from horovod_tpu.ops import eager as _eager
from horovod_tpu.ops.collectives import Adasum, Average, Sum  # noqa: F401


def _np(tensor) -> np.ndarray:
    if hasattr(tensor, "asnumpy"):  # mx.nd.NDArray
        return tensor.asnumpy()
    return np.asarray(tensor)


def _like(arr: np.ndarray, template):
    """Build an NDArray shaped like ``arr`` in ``template``'s context."""
    import mxnet as mx

    ctx = getattr(template, "context", None)
    return mx.nd.array(arr, ctx=ctx, dtype=arr.dtype)


def allreduce(tensor, average=None, name=None, priority=0, op=None):
    """Allreduce returning a new NDArray (reference ``mpi_ops.py``)."""
    out = _eager.allreduce(_np(tensor), average=average,
                           name=name, op=op)
    return _like(np.asarray(out), tensor)


def allreduce_(tensor, average=None, name=None, priority=0, op=None):
    """In-place allreduce: the reference mutates the NDArray the MXNet
    engine hands it; here the reduced values are written back."""
    a = _np(tensor)  # one host copy, reused for wire and dtype
    out = _eager.allreduce(a, average=average, name=name, op=op)
    tensor[:] = _like(np.asarray(out, dtype=a.dtype), tensor)
    return tensor


def allgather(tensor, name=None, priority=0):
    out = _eager.allgather(_np(tensor), name=name)
    return _like(np.asarray(out), tensor)


def broadcast(tensor, root_rank, name=None, priority=0):
    out = _eager.broadcast(_np(tensor), root_rank, name=name)
    return _like(np.asarray(out), tensor)


def broadcast_(tensor, root_rank, name=None, priority=0):
    a = _np(tensor)
    out = _eager.broadcast(a, root_rank, name=name)
    tensor[:] = _like(np.asarray(out, dtype=a.dtype), tensor)
    return tensor


def alltoall(tensor, name=None, priority=0):
    out = _eager.alltoall(_np(tensor), name=name)
    return _like(np.asarray(out), tensor)
