"""MXNet frontend gate.

The reference ships ``horovod.mxnet`` (``mxnet/__init__.py``:
``DistributedOptimizer`` wrapping ``mx.optimizer``,
``DistributedTrainer`` for Gluon).  MXNet reached end-of-life upstream
and is not part of the TPU image; this module fails with an actionable
pointer instead of an opaque ImportError.
"""

from __future__ import annotations

try:
    import mxnet  # noqa: F401
except ImportError as e:
    raise ImportError(
        "horovod_tpu.mxnet requires MXNet, which is not installed (the "
        "project is retired upstream). Use the JAX core API "
        "(import horovod_tpu as hvd) or the PyTorch frontend "
        "(import horovod_tpu.torch as hvd) — both provide the same "
        "DistributedOptimizer/broadcast_parameters surface.") from e
