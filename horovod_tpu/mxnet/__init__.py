"""MXNet frontend.

Parity surface of reference ``horovod/mxnet/__init__.py`` (124 LoC):
``DistributedOptimizer`` wrapping an ``mx.optimizer.Optimizer`` so every
``update`` allreduces the gradient first; ``DistributedTrainer`` (Gluon)
overriding ``_allreduce_grads``; ``broadcast_parameters`` for
``get_params()`` dicts and Gluon ``ParameterDict``s.  The wire is the
shared negotiated eager engine → XLA collectives (numpy bridge, like
the torch and tensorflow frontends).

MXNet reached end-of-life upstream and is not in the TPU image, so
everything that needs ``import mxnet`` is constructed lazily: this
module imports cleanly for probing (``mxnet_built()`` → False), and
only the entry points that truly need MXNet raise, with a pointer at
the JAX/torch equivalents.

Validation scope: API-shape parity, exercised against a stubbed mxnet
module (``tests/test_mxnet_frontend.py``) — the real library has never
run against this frontend (it cannot be installed here), so treat it
as interface-complete rather than battle-tested.
"""

from __future__ import annotations

import warnings

from horovod_tpu import (  # noqa: F401
    init,
    join,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    rank,
    shutdown,
    size,
)
from horovod_tpu.common.types import HorovodTpuError


def mxnet_built() -> bool:
    try:
        import mxnet  # noqa: F401

        return True
    except ImportError:
        return False


def _require_mx():
    try:
        import mxnet

        return mxnet
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.mxnet requires MXNet, which is not installed "
            "(the project is retired upstream). Use the JAX core API "
            "(import horovod_tpu as hvd) or the PyTorch frontend "
            "(import horovod_tpu.torch as hvd) — both provide the same "
            "DistributedOptimizer/broadcast_parameters surface.") from e


def __getattr__(name):
    # Tensor ops live in mpi_ops (importable without mxnet); resolve
    # them lazily so `hvd.allreduce_` etc. work as module attributes.
    if name in ("allreduce", "allreduce_", "allgather", "broadcast",
                "broadcast_", "alltoall", "Average", "Sum", "Adasum"):
        from horovod_tpu.mxnet import mpi_ops

        return getattr(mpi_ops, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


class DistributedOptimizer:
    """Wrap an ``mx.optimizer.Optimizer``: every ``update`` allreduces
    the gradient (sum), with averaging folded into ``rescale_grad``
    (reference ``mxnet/__init__.py:40-77`` — dividing rescale_grad by
    the world size is equivalent to averaging and cheaper)."""

    def __init__(self, optimizer):
        _require_mx()
        from horovod_tpu.mxnet import mpi_ops as _ops

        self._optimizer = optimizer
        self._ops = _ops
        self._optimizer.rescale_grad /= size()

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def create_state_multi_precision(self, index, weight):
        return self._optimizer.create_state_multi_precision(index, weight)

    def _do_allreduce(self, index, grad):
        if size() == 1:
            return
        if isinstance(index, (tuple, list)):
            for i in range(len(index)):
                self._ops.allreduce_(grad[i], average=False,
                                     name=str(index[i]), priority=-i)
        else:
            self._ops.allreduce_(grad, average=False, name=str(index))

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)


def DistributedTrainer(params, optimizer, optimizer_params=None):
    """Gluon trainer whose gradient reduction rides this framework's
    allreduce instead of a kvstore (reference
    ``mxnet/__init__.py:86-110``).  Factory function: the subclass is
    created lazily because its base is ``mx.gluon.Trainer``."""
    mx = _require_mx()
    from horovod_tpu.mxnet import mpi_ops as _ops

    if isinstance(optimizer, DistributedOptimizer):
        optimizer = optimizer._optimizer
        warnings.warn("DistributedTrainer does not take "
                      "DistributedOptimizer as its optimizer. It has "
                      "been unwrapped for you.")

    class _DistributedTrainer(mx.gluon.Trainer):
        def __init__(self):
            super().__init__(params, optimizer,
                             optimizer_params=optimizer_params,
                             kvstore=None)
            # averaging folded into the step scale, as in the optimizer
            self._scale /= size()

        def _allreduce_grads(self):
            if size() == 1:
                return
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    _ops.allreduce_(param.list_grad()[0], average=False,
                                    name=param.name, priority=-i)

    return _DistributedTrainer()


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Broadcast ``Module.get_params()`` dicts or Gluon
    ``ParameterDict``s from ``root_rank`` (reference
    ``mxnet/__init__.py`` broadcast_parameters)."""
    _require_mx()
    from horovod_tpu.mxnet import mpi_ops as _ops

    if isinstance(params, dict):
        tensors = sorted(params.items())
    elif hasattr(params, "items"):  # gluon ParameterDict
        tensors = []
        for name, p in sorted(params.items()):
            try:
                tensors.append((name, p.data()))
            except Exception:
                # deferred-init parameter: broadcast when initialized
                continue
    else:
        raise HorovodTpuError(
            f"Cannot broadcast parameters of type {type(params)!r}; "
            "expected a dict of NDArrays or a gluon ParameterDict.")
    for name, tensor in tensors:
        _ops.broadcast_(tensor, root_rank, name=f"param.{name}")
