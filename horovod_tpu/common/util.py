"""Small shared helpers for the Python frontends (name-parity with
reference ``horovod/common/util.py``, which holds the cross-frontend
argument/compat helpers)."""

from __future__ import annotations

import numbers
import socket


def free_port() -> int:
    """Pick a currently-free TCP port (bind-to-0 probe).  Shared by the
    launcher (rendezvous/coordinator ports) and the elastic re-form
    leader (fresh coordinator per generation) so fixes to the probe
    land everywhere at once."""
    s = socket.socket()
    s.bind(("0.0.0.0", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def validate_warmup_epochs(warmup_epochs) -> None:
    """Loud failure for callers of the removed ``(initial_lr, epochs)``
    positional LearningRateWarmupCallback signature: a fractional count
    like ``0.001`` is the tell, and would otherwise silently explode
    the LR on the first batch.  Integer-like values (``np.int64``,
    ``5.0``) are fine."""
    integral = (isinstance(warmup_epochs, numbers.Integral)
                or (isinstance(warmup_epochs, float)
                    and warmup_epochs.is_integer()))
    if not integral or warmup_epochs < 1:
        raise TypeError(
            f"warmup_epochs must be a positive integer, got "
            f"{warmup_epochs!r}. (The optimizer should carry the "
            "size-scaled LR; this callback no longer takes "
            "initial_lr.)")
