"""Compatibility shims for older jax releases.

The codebase targets the modern public surface (``jax.shard_map`` with
``check_vma``, ``lax.axis_size``, ``pallas.tpu.CompilerParams``).  Some
deployment images pin older jax (0.4.x) where those names live under
``jax.experimental`` or differ in spelling; :func:`install` bridges the
gap in-place so every module (and user code importing ``from jax import
shard_map`` after us) sees one consistent API.  On a current jax this is
a no-op — each patch is guarded by a ``hasattr`` probe, so nothing is
ever overwritten.
"""

from __future__ import annotations

import functools

import jax


def _shard_map_compat():
    from jax.experimental.shard_map import shard_map as _sm

    @functools.wraps(_sm)
    def shard_map(f, **kwargs):
        # modern spelling `check_vma` == legacy `check_rep`
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _sm(f, **kwargs)

    return shard_map


def _axis_size_compat(axis_name):
    """``lax.axis_size`` for jax<0.4.38: psum of the Python constant 1
    const-folds to the (static) axis size without touching the wire."""
    return jax.lax.psum(1, axis_name)


def install() -> None:
    """Idempotently patch missing modern-API names onto jax modules."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_compat()
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = _axis_size_compat
    try:
        from jax.experimental.pallas import tpu as pltpu

        if not hasattr(pltpu, "CompilerParams") and \
                hasattr(pltpu, "TPUCompilerParams"):
            pltpu.CompilerParams = pltpu.TPUCompilerParams
    except ImportError:  # pallas not built into this jax: kernels
        pass             # fall back to their jnp paths anyway


install()
