"""Process/topology state and lifecycle: the ``hvd.init()`` surface.

Parity with the reference's ``HorovodBasics`` (``horovod/common/basics.py:22-66``
backed by the C ABI in ``horovod/common/operations.cc:661-799``):
``init/shutdown/size/local_size/rank/local_rank`` plus build/enabled
introspection.  The TPU build keeps the same one-process-per-accelerator
model, but "rank negotiation" is jax.distributed's coordination service
plus launcher-provided env (the reference's gloo launcher exports the
same ``HOROVOD_RANK/SIZE/LOCAL_RANK/...`` names, ``run/gloo_run.py:152-163``),
and the "communicator" is a `jax.sharding.Mesh` whose single ``hvd`` axis
spans one lead device per process.
"""

from __future__ import annotations

import os
import socket
import threading

import numpy as np

from horovod_tpu.common import config as _config
from horovod_tpu.common import logging as _log
from horovod_tpu.common.platform import ensure_platform
from horovod_tpu.common.types import HorovodTpuError


class _State:
    """Process-global singleton (reference ``global_state.h:42-122``)."""

    def __init__(self) -> None:
        self.initialized = False
        self.rank = 0
        self.size = 1
        self.local_rank = 0
        self.local_size = 1
        self.cross_rank = 0
        self.cross_size = 1
        self.mesh = None            # world Mesh over per-process lead devices
        self.local_mesh = None      # Mesh over this process's local devices
        self.data_mesh = None       # named (dp,pp,tp,sp) mesh (docs/mesh.md)
        self.data_axes = None       # its axis sizes, e.g. {'dp':4,'tp':2,...}
        self.lead_device = None
        self.joined = False
        self.epoch = 0              # increments per init(); namespaces KV keys
        self.controller = None      # runtime controller (lazy)
        self.background = None      # async op background thread (lazy)
        self.timeline = None
        self.profiler = None        # JaxProfilerBridge (init-time)
        self.metrics_server = None  # per-rank /metrics HTTP endpoint
        self.metrics_publisher = None  # KV snapshot publisher
        self.homogeneous = True     # equal ranks per node (set at init)
        self.lock = threading.Lock()


_state = _State()

# Epoch at which this process first opened each profiler logdir: the
# bridge's generation subdir is epoch-relative-to-first-open, so only
# elastic re-forms over the same dir leave the rank<k> layout.
_PROF_DIR_EPOCH0: dict = {}


def _check_initialized() -> None:
    if not _state.initialized:
        raise HorovodTpuError(
            "Horovod-TPU has not been initialized; use hvd.init().")


def state() -> _State:
    return _state


def init(comm=None, mesh=None) -> None:
    """Initialize the framework.

    ``comm`` is accepted for API compatibility with the reference's
    ``hvd.init(comm=...)`` (``basics.py:33-66``); passing a rank subset is
    not supported on TPU (the ICI mesh is global) and raises.

    ``mesh`` names the data mesh (docs/mesh.md): a spec string
    ('dp:4,tp:2'), an axis dict ({'dp': 4, 'tp': 2}), or a prebuilt
    `jax.sharding.Mesh` whose axis names come from ``parallel.mesh.AXES``.
    Equivalent to exporting ``HOROVOD_MESH`` — the value is canonicalized
    through that knob so the round-0 handshake and the AOT cache key see
    programmatic meshes too.  When set, the gradient stack reduces over
    the ``dp`` axis only.

    Multi-process wiring: if ``HOROVOD_SIZE`` > 1 (exported by the
    launcher), connects to the jax.distributed coordinator at
    ``HOROVOD_COORDINATOR_ADDR`` so every chip joins one XLA runtime.
    """
    if comm not in (None, 0):
        raise HorovodTpuError(
            "init(comm=...) with a rank subset is not supported on TPU; "
            "the device mesh is global.")
    with _state.lock:
        if _state.initialized:
            return
        # Goodput ledger (docs/goodput.md): the wall clock starts at
        # the first init() and the bring-up wall lands in the "init"
        # phase; a re-init (elastic re-form) adds its own init span to
        # the same run-long ledger.  Advisory: observability must never
        # fail init.
        import time as _time

        _t_init_gp = _time.monotonic()
        try:
            from horovod_tpu.perf import goodput as _goodput

            _goodput.start()
        except Exception:
            _goodput = None
        ensure_platform()
        import jax

        env_size = int(os.environ.get("HOROVOD_SIZE", "1"))
        env_rank = int(os.environ.get("HOROVOD_RANK", "0"))
        pod_auto = False
        if ("HOROVOD_SIZE" not in os.environ
                and "HOROVOD_RANK" not in os.environ):
            # TPU-pod orchestrator (no launcher): rank/size/coordinator
            # from pod metadata env — the LSF/jsrun-introspection analog
            # (reference run/util/lsf.py).  An explicitly exported
            # HOROVOD_SIZE (even =1, a forced single-process debug run)
            # suppresses auto-detection.
            from horovod_tpu.run import pod as _pod

            info = _pod.detect()
            if info is not None and info.auto:
                # multislice topology: jax's own cluster resolution
                # understands it natively; hand off below.
                pod_auto = True
                _log.info(f"pod metadata ({info.source}): deferring "
                          "topology to jax.distributed auto-detect")
            elif info is not None and info.size > 1:
                env_size, env_rank = info.size, info.rank
                os.environ.setdefault("HOROVOD_COORDINATOR_ADDR",
                                      info.coordinator)
                # export like the launcher would: rank-tagged logging
                # and child tools read these
                os.environ["HOROVOD_RANK"] = str(info.rank)
                os.environ["HOROVOD_SIZE"] = str(info.size)
                _log.info(f"pod metadata ({info.source}): rank="
                          f"{info.rank} size={info.size}", rank=info.rank)
        # NB: must not touch the backend (jax.devices/process_count)
        # before jax.distributed.initialize — probe the distributed
        # client state instead.
        from jax._src import distributed as _jd

        if (env_size > 1 or pod_auto) and _jd.global_state.client is None:
            # Tight failure-detection timeouts: with jax's defaults
            # (heartbeat 100s, shutdown barrier 300s) a crashed peer
            # stalls the job for minutes; the reference's launcher kills
            # the whole job as soon as one rank dies
            # (gloo_run.py:294-304) and these knobs make that prompt.
            import inspect

            kwargs = {}
            sig = inspect.signature(jax.distributed.initialize)
            if "heartbeat_timeout_seconds" in sig.parameters:
                # When the control-plane liveness layer is on (its own
                # hb/<epoch>/<rank> heartbeats + coordinated abort,
                # docs/fault-tolerance.md), it must win the race to
                # report a dead peer — jax's service detection QFATALs
                # the survivors with an undiagnosable abort.  Keep the
                # service as a loose backstop (3x) in that case; with
                # liveness disabled it stays the primary detector.
                hb = max(int(_config.get("heartbeat_timeout")), 1)
                if float(_config.get("heartbeat_interval")) > 0:
                    hb = max(hb * 3, 30)
                kwargs["heartbeat_timeout_seconds"] = hb
            if "shutdown_timeout_seconds" in sig.parameters:
                kwargs["shutdown_timeout_seconds"] = int(
                    _config.get("shutdown_timeout"))
            if pod_auto:
                jax.distributed.initialize(**kwargs)
            elif _config.get("elastic"):
                # Elastic mode builds the distributed runtime by hand:
                # jax.distributed.initialize's client has no bounded
                # shutdown (a re-form around a dead peer would hang in
                # its 60 s barrier and leave the error-poll thread
                # alive to QFATAL the survivor later).
                coord = _config.get("coordinator_addr")
                if not coord:
                    raise HorovodTpuError(
                        "HOROVOD_SIZE > 1 but HOROVOD_COORDINATOR_ADDR "
                        "is not set (the launcher exports it).")
                _elastic_distributed_init(coord, env_size, env_rank)
            else:
                coord = _config.get("coordinator_addr")
                if not coord:
                    raise HorovodTpuError(
                        "HOROVOD_SIZE > 1 but HOROVOD_COORDINATOR_ADDR "
                        "is not set (the launcher exports it).")
                jax.distributed.initialize(
                    coordinator_address=coord,
                    num_processes=env_size,
                    process_id=env_rank,
                    **kwargs)

        _state.rank = jax.process_index()
        _state.size = jax.process_count()
        if pod_auto:
            os.environ["HOROVOD_RANK"] = str(_state.rank)
            os.environ["HOROVOD_SIZE"] = str(_state.size)
        elif env_size > 1 and (_state.rank != env_rank
                               or _state.size != env_size):
            raise HorovodTpuError(
                f"Launcher env rank/size ({env_rank}/{env_size}) disagrees "
                f"with XLA runtime ({_state.rank}/{_state.size}).")

        _state.epoch += 1
        _compute_local_cross_topology()
        _build_meshes()
        _apply_mesh_arg(mesh)
        _build_data_mesh()
        # Device-side capture starts here, not in the background
        # runtime: at size 1 that runtime is lazy, and a compiled-only
        # training run would otherwise record nothing.
        prof_dir = _config.get("jax_profiler")
        if prof_dir:
            from horovod_tpu.runtime.timeline import JaxProfilerBridge

            if _state.profiler is not None:
                # A prior generation's bridge still holds the profiler
                # (e.g. a teardown path that never ran): close it so the
                # old capture lands and start_trace can't collide.
                try:
                    _state.profiler.close()
                except Exception:
                    pass
                _state.profiler = None
            # Generation is relative to the first time THIS process
            # opened THIS logdir — epoch counts every init() in the
            # process, so a plain shutdown()+init() against a fresh dir
            # must still get the documented rank<k> layout; only a
            # re-form over the same dir (where a prior generation's
            # capture lives) moves to gen<g>/rank<k>.
            base = _PROF_DIR_EPOCH0.setdefault(str(prof_dir),
                                               _state.epoch)
            try:
                _state.profiler = JaxProfilerBridge(
                    prof_dir, _state.rank,
                    generation=_state.epoch - base + 1)
            except Exception as exc:  # capture is advisory, never fatal
                _log.warning(f"jax profiler capture unavailable: {exc!r}")
        # Metrics plane (docs/metrics.md): topology gauges always; the
        # per-rank HTTP endpoint only when HOROVOD_METRICS_PORT is set.
        # An elastic re-form re-enters init() with a new rank/epoch, so
        # the endpoint follows the rank to its new port and the gauges
        # reflect the new generation.
        from horovod_tpu.runtime import metrics as _metrics

        _metrics.gauge(
            "hvd_world_size", "Current world size.").set(_state.size)
        _metrics.gauge(
            "hvd_generation",
            "Communicator generation (KV epoch; bumps on every "
            "elastic re-form).").set(_state.epoch)
        if _state.metrics_server is not None:
            _state.metrics_server.close()
        _state.metrics_server = _metrics.start_rank_endpoint(_state.rank)
        # KV snapshot publisher for the launcher's fleet aggregate —
        # controller-independent so a size-1 elastic survivor (whose
        # LocalController has no transport) still reports its
        # generation/size to the launcher.
        if _state.metrics_publisher is not None:
            _state.metrics_publisher.stop()
        _state.metrics_publisher = _metrics.maybe_start_kv_publisher(
            _state.rank, _state.size, _state.epoch)
        # Flight recorder (docs/flight-recorder.md): lifecycle event +
        # fatal-signal dump handlers (SIGTERM/SIGABRT), so a killed or
        # aborting rank leaves its event ring in HOROVOD_FLIGHT_DIR.
        # Installed here (main thread at first init); an elastic
        # re-init from a worker thread is a no-op.
        from horovod_tpu.runtime import flight as _flight

        _flight.install_signal_handlers()
        _flight.record("init", rank=_state.rank, size=_state.size,
                       generation=_state.epoch)
        # Persistent AOT executable cache (docs/aot-cache.md): nothing
        # to open — entries are keyed per program on demand — but the
        # operator should see where warm starts will come from, and a
        # re-init (elastic re-form) must announce under the NEW
        # topology (the key context includes world size, so the old
        # generation's entries simply stop matching).
        from horovod_tpu.runtime import aot_cache as _aot

        if _aot.enabled():
            _log.info(
                f"aot-cache: {_aot.cache_dir()} (mode={_aot.mode()}) — "
                "negotiated programs will load from cache when keys "
                "match", rank=_state.rank)
            _flight.record("aot", event="enabled", dir=_aot.cache_dir(),
                           mode=_aot.mode())
        if _goodput is not None:
            try:
                _goodput.observe("init",
                                 _time.monotonic() - _t_init_gp)
            except Exception:
                pass
        _state.initialized = True
        _log.info(
            "horovod_tpu initialized: rank=%d size=%d local_rank=%d "
            "local_size=%d cross_rank=%d cross_size=%d platform=%s"
            % (_state.rank, _state.size, _state.local_rank,
               _state.local_size, _state.cross_rank, _state.cross_size,
               _state.lead_device.platform), rank=_state.rank)
    if _state.size > 1:
        # Spawn the background runtime now, like the reference's
        # InitializeHorovodOnce (operations.cc:604-650) — NOT lazily on
        # first enqueue: every rank must participate in negotiation
        # rounds from the start or the coordinator blocks mid-round on
        # a rank that simply hasn't submitted anything yet, and the
        # stall inspector can never observe the hold-out.
        from horovod_tpu.ops import eager as _eager

        _eager._runtime()


def _compute_local_cross_topology() -> None:
    """Local/cross ranks: launcher env wins; else derive from hostnames.

    Mirrors the reference where the launcher computes the full
    rank/local/cross allocation up front (``run/gloo_run.py:54-112``) and
    MPI mode derives it from shared-memory communicator splits
    (``mpi_controller.cc:25-81``).
    """
    env = os.environ
    if "HOROVOD_LOCAL_RANK" in env and "HOROVOD_LOCAL_SIZE" in env:
        _state.local_rank = int(env["HOROVOD_LOCAL_RANK"])
        _state.local_size = int(env["HOROVOD_LOCAL_SIZE"])
        _state.cross_rank = int(env.get("HOROVOD_CROSS_RANK", 0))
        _state.cross_size = int(env.get("HOROVOD_CROSS_SIZE", 1))
        # The launcher computed the full allocation, so it knows true
        # homogeneity; a single rank's local_size*cross_size==size test
        # would wrongly say True on e.g. {3,2,1} ranks over 3 nodes.
        flag = env.get("HOROVOD_IS_HOMOGENEOUS")
        _state.homogeneous = (flag == "1" if flag is not None else
                              _state.local_size * _state.cross_size
                              == _state.size)
        return
    if _state.size == 1:
        _state.local_rank = 0
        _state.local_size = 1
        _state.cross_rank = 0
        _state.cross_size = 1
        _state.homogeneous = True
        return
    # Derive from per-process hostnames via the coordination service's
    # key-value store (no collective needed at init time).
    from jax._src import distributed as _jd

    client = _jd.global_state.client
    host = socket.gethostname()
    # epoch-namespaced keys: shutdown()+init() must not collide with a
    # previous generation's keys on the still-live coordination service
    ep = _state.epoch
    client.key_value_set(f"hvd_host/{ep}/{_state.rank}", host)
    client.wait_at_barrier(f"hvd_topology_{ep}", timeout_in_ms=60_000)
    hosts = [client.blocking_key_value_get(f"hvd_host/{ep}/{r}", 60_000)
             for r in range(_state.size)]
    same = [r for r, h in enumerate(hosts) if h == host]
    _state.local_rank = same.index(_state.rank)
    _state.local_size = len(same)
    uniq = sorted(set(hosts), key=hosts.index)
    _state.cross_rank = uniq.index(host)
    _state.cross_size = len(uniq)
    counts = {h: hosts.count(h) for h in uniq}
    _state.homogeneous = len(set(counts.values())) == 1


def _build_meshes() -> None:
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    leads = []
    for p in range(_state.size):
        mine = [d for d in devices if d.process_index == p]
        if not mine:
            raise HorovodTpuError(f"process {p} exposes no devices")
        leads.append(mine[0])
    _state.mesh = Mesh(np.array(leads), ("hvd",))
    local = [d for d in devices if d.process_index == _state.rank]
    _state.local_mesh = Mesh(np.array(local), ("local",))
    _state.lead_device = local[0]


def _apply_mesh_arg(mesh) -> None:
    """Canonicalize an ``init(mesh=...)`` argument through the ``mesh``
    knob (docs/mesh.md): the round-0 handshake and the AOT cache key
    read the config registry, so a programmatic mesh must be exactly as
    visible there as an env-configured one."""
    if mesh is None:
        return
    from horovod_tpu.parallel import mesh as _pmesh

    if isinstance(mesh, str):
        axes = _pmesh.parse_mesh_spec(mesh)
    elif isinstance(mesh, dict):
        axes = _pmesh.parse_mesh_spec(
            ",".join(f"{k}:{v}" for k, v in mesh.items()))
    else:
        names = getattr(mesh, "axis_names", None)
        devs = getattr(mesh, "devices", None)
        if names is None or devs is None:
            raise HorovodTpuError(
                "init(mesh=...) wants a spec string ('dp:4,tp:2'), an "
                "axis dict, or a jax.sharding.Mesh; got "
                f"{type(mesh).__name__}")
        shape = dict(zip(names, devs.shape))
        bad = sorted(n for n in shape if n not in _pmesh.AXES)
        if bad:
            raise HorovodTpuError(
                f"init(mesh=...) axis names must come from "
                f"{'/'.join(_pmesh.AXES)}; got {bad}")
        if _pmesh.DATA_AXIS not in shape:
            raise HorovodTpuError(
                "init(mesh=...) mesh has no 'dp' axis; the gradient "
                "stack reduces over dp")
        axes = {a: int(shape.get(a, 1)) for a in _pmesh.AXES}
    canon = _pmesh.canonical_spec(axes)
    knob = str(_config.get("mesh") or "").strip()
    if knob and _pmesh.canonical_spec(_pmesh.parse_mesh_spec(knob)) != canon:
        raise HorovodTpuError(
            f"init(mesh=...) ({canon!r}) disagrees with HOROVOD_MESH "
            f"({knob!r}); set one, not both")
    _config.set_knob("mesh", canon)


def _build_data_mesh() -> None:
    """Build the named data mesh from the ``mesh`` knob, if set.

    The in-process :class:`Mesh` only exists when this process sees
    every device the spec covers (the single-controller shard_map
    regime).  In the one-process-per-chip eager regime the knob still
    scopes shard counts and rides the round-0 handshake, but there is
    no global mesh to build locally — accepted when the spec covers
    exactly the world size.  Anything else is a mis-sized spec and
    raises: silently training on it would shard gradients against the
    wrong replica groups."""
    spec = str(_config.get("mesh") or "").strip()
    if not spec:
        _state.data_mesh = None
        _state.data_axes = None
        return
    from horovod_tpu.parallel import mesh as _pmesh

    axes = _pmesh.parse_mesh_spec(spec)
    n = 1
    for v in axes.values():
        n *= int(v)
    import jax

    if n == len(jax.devices()):
        m = _pmesh.build_data_mesh(axes)
        _state.data_mesh = m
        _state.data_axes = dict(zip(m.axis_names, m.devices.shape))
        _log.info(f"data mesh: {_pmesh.canonical_spec(axes)} over "
                  f"{m.devices.size} devices (axes {_state.data_axes}); "
                  "gradient collectives ride the dp axis",
                  rank=_state.rank)
    elif n == _state.size:
        _state.data_mesh = None
        _state.data_axes = dict(axes)
        _log.info(f"data mesh: {_pmesh.canonical_spec(axes)} spans the "
                  f"{n}-process world (eager regime; no in-process "
                  "global mesh)", rank=_state.rank)
    else:
        raise HorovodTpuError(
            f"HOROVOD_MESH {_pmesh.canonical_spec(axes)!r} covers {n} "
            f"devices but this process sees {len(jax.devices())} and "
            f"the world has {_state.size} ranks; every device must "
            "belong to exactly one mesh coordinate")


def _elastic_distributed_init(coord: str, n: int, rank: int) -> None:
    """Hand-built jax.distributed runtime for elastic worlds.

    Mirrors ``jax.distributed.initialize`` but with a *bounded* client
    shutdown deadline (``HOROVOD_SHUTDOWN_TIMEOUT_SECONDS``) so a
    re-form around a dead peer returns promptly, and jax-layer liveness
    kept a loose 3x backstop behind the control plane's own heartbeats
    (the PR3 rationale: the diagnosable RanksDownError abort must win
    the race against jax's undiagnosable fatal teardown)."""
    from jax._src import distributed as _jd
    from jax._src.lib import xla_extension as _xe

    gs = _jd.global_state
    hb_int = max(1, int(float(_config.get("heartbeat_interval")) or 1))
    hb_to = max(int(_config.get("heartbeat_timeout")), 1)
    missing = max(3, (max(hb_to * 3, 30) + hb_int - 1) // hb_int)
    if rank == 0 and gs.service is None:
        port = coord.rsplit(":", 1)[1]
        gs.service = _xe.get_distributed_runtime_service(
            "[::]:" + port, n, heartbeat_interval=hb_int,
            max_missing_heartbeats=missing)
    gs.client = _xe.get_distributed_runtime_client(
        coord, rank, init_timeout=120,
        shutdown_timeout=max(2, int(_config.get("shutdown_timeout"))),
        heartbeat_interval=hb_int, max_missing_heartbeats=missing,
        shutdown_on_destruction=False, use_compression=True)
    gs.client.connect()
    gs.process_id = rank
    gs.num_processes = n
    gs.coordinator_address = coord


def teardown_distributed(bound_s: float | None = None) -> None:
    """Bounded teardown of the jax.distributed runtime + XLA backends so
    :func:`init` can re-form the world at a different size in the SAME
    process (the elastic re-form path, docs/elastic.md).

    Each shutdown call runs in a daemon thread joined for ``bound_s``
    (default ``HOROVOD_SHUTDOWN_TIMEOUT_SECONDS``): with a dead peer the
    client's shutdown barrier can never complete, and a survivor must
    not ride it out.  Afterwards the distributed global state is
    force-reset and every backend/device cache is cleared — process
    topology getters (``jax.process_count`` et al.) are lru-cached on
    top of the backend cache, so clearing only the backends would leave
    them vouching for the dead world."""
    import jax

    if bound_s is None:
        bound_s = max(2, int(_config.get("shutdown_timeout")))
    if _state.timeline is not None:
        # Elastic teardown path: flush and join the timeline writer
        # before the world is torn down, so a re-forming rank's trace
        # ends on a complete record instead of truncating mid-event
        # (close() is idempotent; shutdown() may already have run).
        try:
            _state.timeline.close()
        except Exception:
            pass
        _state.timeline = None
    if _state.profiler is not None:
        # Stop the device capture BEFORE the world is torn down: the
        # old generation's xplane profile only lands at stop_trace, and
        # the re-init's new bridge (under gen<g+1>/rank<k>) cannot
        # start while this one holds the profiler — leaving it open
        # used to lose the re-formed generation's capture entirely
        # (start_trace raised, the advisory catch swallowed it).
        try:
            _state.profiler.close()
        except Exception:
            pass
        _state.profiler = None
    from jax._src import distributed as _jd

    gs = _jd.global_state

    def _swallow(fn):
        try:
            fn()
        except Exception:
            pass

    for obj in (gs.client, gs.service):
        if obj is not None:
            t = threading.Thread(target=_swallow, args=(obj.shutdown,),
                                 daemon=True)
            t.start()
            t.join(bound_s)
    gs.client = None
    gs.service = None
    gs.process_id = 0
    gs.num_processes = 1
    gs.coordinator_address = None
    gs.preemption_sync_manager = None
    jax.clear_caches()
    from horovod_tpu.ops import xla_exec as _exec

    _exec.clear_cache()
    try:
        from jax._src import xla_bridge as _xb

        _xb._clear_backends()
        cached = [_xb.get_backend, _xb.local_devices, _xb.process_count]
    except Exception:  # newer jax: public surface only
        cached = []
        clear = getattr(getattr(getattr(jax, "extend", None), "backend",
                                None), "clear_backends", None)
        if clear is not None:
            _swallow(clear)
    cached += [jax.process_count, jax.process_index, jax.device_count,
               jax.local_device_count, jax.devices, jax.local_devices]
    for fn in cached:
        cc = getattr(fn, "cache_clear", None)
        if cc is not None:
            _swallow(cc)
    _state.mesh = None
    _state.local_mesh = None
    _state.data_mesh = None
    _state.data_axes = None
    _state.lead_device = None


def shutdown() -> None:
    """Tear down background machinery (reference ``horovod_shutdown``,
    ``operations.cc:688``)."""
    with _state.lock:
        if not _state.initialized:
            return
        from horovod_tpu.runtime import flight as _flight

        _flight.record("shutdown", rank=_state.rank,
                       generation=_state.epoch)
        # The goodput ledger's final accounting: a clean shutdown dumps
        # the wall-clock attribution next to the flight dumps so the
        # `python -m horovod_tpu.perf goodput <dir>` report covers
        # healthy runs too (abort paths dump via flight.dump_on_failure).
        try:
            from horovod_tpu.perf import goodput as _goodput

            _goodput.dump("shutdown")
        except Exception:
            pass
        # ...and the health monitor's (docs/health.md): a clean
        # shutdown leaves the per-rank health verdict next to the
        # goodput ledger so `python -m horovod_tpu.perf health <dir>`
        # covers healthy runs too.
        try:
            from horovod_tpu.runtime import health as _health

            if _health._monitor is not None:
                _health.dump("shutdown")
        except Exception:
            pass
        if _state.background is not None:
            _state.background.stop()
            _state.background = None
        if _state.timeline is not None:
            _state.timeline.close()
            _state.timeline = None
        if _state.profiler is not None:
            _state.profiler.close()
            _state.profiler = None
        if _state.metrics_server is not None:
            _state.metrics_server.close()
            _state.metrics_server = None
        if _state.metrics_publisher is not None:
            _state.metrics_publisher.stop()
            _state.metrics_publisher = None
        _state.controller = None
        _state.data_mesh = None
        _state.data_axes = None
        _state.initialized = False
        _state.joined = False


def is_initialized() -> bool:
    return _state.initialized


def rank() -> int:
    _check_initialized()
    return _state.rank


def size() -> int:
    _check_initialized()
    return _state.size


def local_rank() -> int:
    _check_initialized()
    return _state.local_rank


def local_size() -> int:
    _check_initialized()
    return _state.local_size


def cross_rank() -> int:
    _check_initialized()
    return _state.cross_rank


def cross_size() -> int:
    _check_initialized()
    return _state.cross_size


def is_homogeneous() -> bool:
    """True iff every node runs the same number of ranks (reference
    ``basics.py:122-129``; hierarchical collectives and Adasum assume
    it).  Computed from the launcher's full allocation or the gathered
    per-host rank counts — never from one rank's local view."""
    _check_initialized()
    return bool(_state.homogeneous)


def world_mesh():
    """The 1-D ``('hvd',)`` mesh over per-process lead devices that backs
    the eager collective path."""
    _check_initialized()
    return _state.mesh


def local_mesh():
    """Mesh over this process's local devices (for intra-process model
    parallelism)."""
    _check_initialized()
    return _state.local_mesh


def data_mesh():
    """The named (dp,pp,tp,sp) data mesh (docs/mesh.md) when one is
    configured via ``hvd.init(mesh=...)`` / ``HOROVOD_MESH``, else
    ``None`` (flat-world regime).  Under hierarchical mode the dp axis
    appears as the ('dpc','dpl') sub-axis pair."""
    _check_initialized()
    return _state.data_mesh


def data_parallel_size() -> int:
    """Replica count of the gradient reduction: the mesh's dp extent
    when a data mesh is configured, else the world size.  This is the
    shard count ZeRO layouts and checkpoint shard metadata use."""
    from horovod_tpu.parallel import mesh as _pmesh

    dp = _pmesh.data_parallel_size()
    if dp is not None:
        return dp
    return _state.size if _state.initialized else 1


def lead_device():
    _check_initialized()
    return _state.lead_device


# --- build/enabled introspection (reference basics.py:90-150) -------------

def mpi_threads_supported() -> bool:
    """No MPI in the TPU build; collective dispatch is thread-safe."""
    return False


def mpi_built() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def gloo_built() -> bool:
    """True when cross-process CPU collectives are available (test mode)."""
    return True


def gloo_enabled() -> bool:
    import jax

    return _state.initialized and _state.lead_device.platform == "cpu"


def nccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def xla_built() -> bool:
    """TPU-build addition: the data plane is XLA collectives."""
    return True


def ici_enabled() -> bool:
    """True when collectives ride a real TPU interconnect."""
    return _state.initialized and _state.lead_device.platform == "tpu"
