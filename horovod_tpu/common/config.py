"""Config / knob system.

The reference exposes every runtime knob through three equivalent
surfaces that all converge on ``HOROVOD_*`` env vars (SURVEY §5.6):
env vars read by the C++ core (reference ``common.h:61-88``,
``operations.cc:403-500``), ``horovodrun`` CLI flags mapped via
``config_parser.set_env_from_args`` (reference
``run/common/util/config_parser.py:141-190``), and a YAML config file
with CLI-override precedence.  This module is the single registry those
three surfaces share in the TPU build.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Callable

# ---------------------------------------------------------------------------
# Knob registry
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Knob:
    env: str            # HOROVOD_* env var (reference-compatible name)
    default: Any
    parse: Callable[[str], Any]
    cli: str | None = None       # horovodrun-style CLI flag
    config_key: str | None = None  # dotted key in the config file
    help: str = ""


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


_KNOBS: dict[str, Knob] = {}


def _register(name: str, knob: Knob) -> None:
    _KNOBS[name] = knob


# Names follow the reference env vars (common.h:61-88) so existing Horovod
# deployment configs carry over unchanged.
_register("fusion_threshold", Knob(
    "HOROVOD_FUSION_THRESHOLD", 64 * 1024 * 1024, int,
    cli="--fusion-threshold-mb", config_key="tensor_fusion.threshold",
    help="Eager-path fusion buffer threshold in bytes (default 64MB, "
         "reference operations.cc:419).  Must agree on every rank "
         "(validated at the round-0 handshake: fusion decides the "
         "fused buffer shapes every rank must build identically)."))
_register("cycle_time_ms", Knob(
    "HOROVOD_CYCLE_TIME", 5.0, float,
    cli="--cycle-time-ms", config_key="tensor_fusion.cycle_time",
    help="Background-loop cycle time in ms (default 5, reference "
         "operations.cc:427)."))
_register("cache_capacity", Knob(
    "HOROVOD_CACHE_CAPACITY", 1024, int,
    cli="--cache-capacity", config_key="cache.capacity",
    help="Response-cache capacity; 0 disables (reference "
         "response_cache.h:44).  Must agree on every rank (validated "
         "at the round-0 handshake: the cache fast path decides which "
         "rounds skip negotiation, so a divergence desynchronizes the "
         "control plane)."))
_register("ragged_allgather", Knob(
    "HOROVOD_RAGGED_ALLGATHER", "auto", str,
    cli="--ragged-allgather", config_key="ragged_allgather",
    help="Ragged-allgather strategy: auto (bandwidth heuristic), "
         "psum (scatter into exact offsets + one psum, bytes ~ "
         "2*sum(sizes)), pad (pad to max + trim, bytes ~ max*nranks). "
         " Must agree on every rank (validated at the round-0 "
         "handshake: the strategy picks which collective program a "
         "ragged gather runs)."))
_register("hierarchical_allreduce", Knob(
    "HOROVOD_HIERARCHICAL_ALLREDUCE", False, _parse_bool,
    cli="--hierarchical-allreduce", config_key="hierarchical.allreduce",
    help="Two-level (intra-slice ICI + cross-slice DCN) allreduce.  "
         "Must agree on every rank (validated at the round-0 "
         "handshake: a rank running the two-level program while "
         "another runs the flat one deadlocks in mismatched "
         "collectives)."))
_register("hierarchical_allgather", Knob(
    "HOROVOD_HIERARCHICAL_ALLGATHER", False, _parse_bool,
    cli="--hierarchical-allgather", config_key="hierarchical.allgather",
    help="Two-level allgather.  Must agree on every rank (validated "
         "at the round-0 handshake, like hierarchical allreduce)."))
_register("hierarchical_local_size", Knob(
    "HOROVOD_HIERARCHICAL_LOCAL_SIZE", 0, int,
    cli="--hierarchical-local-size", config_key="hierarchical.local_size",
    help="Override the detected local group size for hierarchical "
         "collectives (0 = use launcher/hostname topology).  Must "
         "agree on every rank when a hierarchical mode is on "
         "(validated at the round-0 handshake: it reshapes the "
         "ICI/DCN axis split every rank's program is built from)."))
_register("local_sgd_h", Knob(
    "HOROVOD_LOCAL_SGD_H", 0, int,
    cli="--local-sgd-h", config_key="local_sgd.h",
    help="Outer-sync period H of the local-SGD/DiLoCo training regime "
         "(docs/local-sgd.md): 0/1 = off (every step fully "
         "synchronous); H >= 2 makes inner steps reduce over the "
         "local/ICI axis only and exchanges pseudo-gradients across "
         "slices (DCN) every H-th step.  Must agree on every rank "
         "(validated at the round-0 handshake: a rank running inner "
         "ICI-only programs while another reduces across slices "
         "deadlocks in mismatched collectives)."))
_register("outer_lr", Knob(
    "HOROVOD_OUTER_LR", 0.7, float,
    cli="--outer-lr", config_key="local_sgd.outer_lr",
    help="Outer-optimizer learning rate applied to the cross-slice "
         "pseudo-gradient at each local-SGD outer sync (DiLoCo's "
         "published sweet spot is ~0.7 with Nesterov momentum).  Must "
         "agree on every rank when local-SGD is active (validated at "
         "the round-0 handshake: it selects the parameter trajectory "
         "every slice must walk identically after a sync)."))
_register("outer_momentum", Knob(
    "HOROVOD_OUTER_MOMENTUM", 0.9, float,
    cli="--outer-momentum", config_key="local_sgd.outer_momentum",
    help="Nesterov momentum of the local-SGD outer optimizer "
         "(docs/local-sgd.md).  Must agree on every rank when "
         "local-SGD is active (validated at the round-0 handshake, "
         "like the outer learning rate)."))
_register("local_sgd_compression", Knob(
    "HOROVOD_LOCAL_SGD_COMPRESSION", "", str,
    cli="--local-sgd-compression", config_key="local_sgd.compression",
    help="Wire compression for the cross-slice pseudo-gradient hop of "
         "the local-SGD outer sync: none | fp16 | bf16 | int8 | int4 "
         "| topk (empty = inherit HOROVOD_COMPRESSION).  Only the DCN "
         "hop is compressed — inner ICI reductions stay full "
         "precision.  Must agree on every rank when local-SGD is "
         "active (validated at the round-0 handshake: the mode picks "
         "which collective program the outer sync runs)."))
_register("mesh", Knob(
    "HOROVOD_MESH", "", str,
    cli="--mesh", config_key="mesh.axes",
    help="Named data-mesh axis sizes as 'axis:size' pairs, e.g. "
         "'dp:4,tp:2' (axes dp/pp/tp/sp; empty = flat world).  When "
         "set, every gradient collective, the optimizer, and the ZeRO "
         "shard layouts reduce/scatter over the dp axis only, so "
         "params sharded over tp/pp/sp islands are never averaged "
         "across them; see docs/mesh.md.  Must agree on every rank "
         "(validated at the round-0 handshake: a rank reducing over a "
         "different axis split runs a different collective program and "
         "deadlocks or corrupts tp-sharded params)."))
_register("compression", Knob(
    "HOROVOD_COMPRESSION", "none", str,
    cli="--compression", config_key="compression.mode",
    help="Gradient wire compression for allreduce: none | fp16 | bf16 "
         "(dtype casts, reference Compression API) | int8 "
         "(EQuARX-style block-scaled quantization with shared per-block "
         "scales; under hierarchical allreduce only the cross-slice DCN "
         "hop is quantized).  Applies as the DistributedOptimizer "
         "default and to the negotiated eager data plane; must agree "
         "on every rank (validated at the round-0 handshake)."))
_register("quant_block_size", Knob(
    "HOROVOD_QUANT_BLOCK_SIZE", 256, int,
    cli="--quant-block-size", config_key="compression.quant_block_size",
    help="Elements per int8 quantization block (one fp32 scale each; "
         "default 256).  Multiples of 128 keep the Pallas "
         "quantize/dequantize kernels lane-aligned on TPU.  Must "
         "agree on every rank when a block-quantized mode is active "
         "(validated at the round-0 handshake: block size sets the "
         "scale-sidecar shapes on the wire)."))
_register("sharded_optimizer", Knob(
    "HOROVOD_SHARDED_OPTIMIZER", False, _parse_bool,
    cli="--sharded-optimizer", config_key="optimizer.sharded",
    help="ZeRO-1 sharded weight update: DistributedOptimizer "
         "reduce-scatters gradients, runs the optimizer step on the "
         "rank-local 1/world_size shard (optimizer state memory drops "
         "~world_size-fold), and allgathers the updated parameter "
         "shards.  Must agree on every rank (validated at the round-0 "
         "handshake): one rank reduce-scattering while another "
         "allreduces would deadlock.  See docs/zero.md."))
_register("zero_stage", Knob(
    "HOROVOD_ZERO_STAGE", 0, int,
    cli="--zero-stage", config_key="optimizer.zero_stage",
    help="ZeRO sharding stage for DistributedOptimizer (0-3, default "
         "0).  0: replicated update.  1: weight-update sharding "
         "(optimizer state lives as rank-local 1/world shards; same as "
         "HOROVOD_SHARDED_OPTIMIZER=1).  2: additionally keeps "
         "gradients shard-resident — the fused gradient buffers are "
         "reduce-scattered bucket-by-bucket and no full-size fused "
         "buffer ever materializes.  3: additionally shards the "
         "parameters themselves (1/world flat shards between steps, "
         "bucket-wise allgather prefetched under the forward pass; "
         "see hvd.zero3_shard_params / hvd.zero3_full_params).  Must "
         "agree on every rank (validated at the round-0 handshake).  "
         "See docs/zero.md."))
_register("zero_prefetch_chunks", Knob(
    "HOROVOD_ZERO_PREFETCH_CHUNKS", 4, int,
    cli="--zero-prefetch-chunks", config_key="optimizer.zero_prefetch_chunks",
    help="Bucket count for the ZeRO-2/3 bucket pipelines (default 4; "
         "autotuned under HOROVOD_AUTOTUNE when zero_stage >= 3, "
         "bounds 1..32): stage-2 gradients reduce-scatter in this many "
         "barrier-separated buckets, and the stage-3 forward gathers "
         "parameters bucket-wise so bucket k+1's allgather rides under "
         "bucket k's layer math.  Must agree on every rank when any "
         "optimizer runs stage >= 2 (bucket shapes are part of the "
         "negotiated wire).  The round-0 handshake validates it when "
         "HOROVOD_ZERO_STAGE >= 2; a job that selects the stage only "
         "via the zero_stage= optimizer argument should set the env "
         "knob too — like a per-call overlap=True, argument-driven "
         "modes are outside the handshake's view (a divergence "
         "surfaces as a wire timeout naming the mismatched bucket "
         "tensors, not a fail-fast)."))
_register("overlap", Knob(
    "HOROVOD_OVERLAP", False, _parse_bool,
    cli="--overlap", config_key="overlap.enabled",
    help="Overlapped chunked gradient communication: fused gradient "
         "buffers split into HOROVOD_OVERLAP_CHUNKS buckets riding a "
         "software-pipelined ppermute ring reduce-scatter/allgather "
         "schedule instead of one monolithic end-of-step collective, "
         "with lax.optimization_barrier between buckets so XLA's "
         "latency-hiding scheduler can float bucket i+1's transfer "
         "under bucket i's compute.  Applies to the in-trace "
         "DistributedOptimizer path and the negotiated eager data "
         "plane; must agree on every rank (validated at the round-0 "
         "handshake: one rank ring-permuting while another psums would "
         "deadlock).  See docs/overlap.md."))
_register("overlap_chunks", Knob(
    "HOROVOD_OVERLAP_CHUNKS", 4, int,
    cli="--overlap-chunks", config_key="overlap.chunks",
    help="Bucket count K for the overlap schedule (default 4; "
         "autotuned under HOROVOD_AUTOTUNE, bounds 1..32).  More "
         "chunks interleave compute and communication more finely but "
         "pay more per-collective latency; interacts with "
         "HOROVOD_FUSION_THRESHOLD on the eager path (bucket bytes ~= "
         "fused buffer bytes / K).  Must agree on every rank."))
_register("quant_pallas", Knob(
    "HOROVOD_QUANT_PALLAS", "auto", str,
    cli="--quant-pallas", config_key="compression.quant_pallas",
    help="Pallas kernel selection for the quantize/dequantize codecs "
         "AND the fused optimizer tail (HOROVOD_FUSED_UPDATE): auto "
         "(Pallas on TPU, jnp elsewhere), 1 (force Pallas; interpret "
         "mode off-TPU — test hook), 0 (force the jnp path)."))
_register("topk_ratio", Knob(
    "HOROVOD_TOPK_RATIO", 0.01, float,
    cli="--topk-ratio", config_key="compression.topk_ratio",
    help="Top-k sparsification density: each payload (or overlap "
         "bucket) transmits max(1, round(ratio * n_elems)) "
         "(index, value) pairs, the rest accumulating in the "
         "error-feedback residual (default 0.01 = top 1%%).  Must "
         "agree on every rank when the topk mode is active (payload "
         "shapes are part of the negotiated wire; validated at the "
         "round-0 handshake)."))
_register("bucket_compression", Knob(
    "HOROVOD_BUCKET_COMPRESSION", "", str,
    cli="--bucket-compression", config_key="compression.bucket_modes",
    help="Per-overlap-bucket wire modes, colon-separated (e.g. "
         "'int8:int4:topk', cycled over the K buckets); empty (default) "
         "means every bucket rides HOROVOD_COMPRESSION.  Normally "
         "owned by the adaptive autotuner "
         "(HOROVOD_ADAPTIVE_COMPRESSION); settable by hand for "
         "experiments.  Must agree on every rank (validated at the "
         "round-0 handshake).  See docs/compression.md."))
_register("adaptive_compression", Knob(
    "HOROVOD_ADAPTIVE_COMPRESSION", False, _parse_bool,
    cli="--adaptive-compression", config_key="compression.adaptive",
    help="Let the GP autotuner (HOROVOD_AUTOTUNE) choose the wire "
         "compression mode per overlap bucket from measured "
         "comm-exposed seconds (device truth when a sampled capture "
         "is live, the step-span subtraction otherwise), walking the "
         "none->bf16->fp16->int8->int4->topk ladder under the "
         "bounded-loss guardrail "
         "(HOROVOD_COMPRESSION_MAX_RESIDUAL_RATIO).  Must agree on "
         "every rank (validated at the round-0 handshake: a rank "
         "without it would never apply the tuner's mode broadcasts "
         "and drift into mismatched programs at the next retrace).  "
         "See docs/compression.md and docs/autotune.md."))
_register("compression_guard_ratio", Knob(
    "HOROVOD_COMPRESSION_MAX_RESIDUAL_RATIO", 0.5, float,
    cli="--compression-max-residual-ratio",
    config_key="compression.max_residual_ratio",
    help="Bounded-loss guardrail for adaptive compression: when a "
         "bucket's reported error-feedback residual-to-gradient norm "
         "ratio exceeds this ceiling, the tuner pins that bucket back "
         "to int8 instead of int4/topk (0 disables the aggressive "
         "modes entirely for reported buckets)."))
_register("timeline", Knob(
    "HOROVOD_TIMELINE", "", str,
    cli="--timeline-filename", config_key="profiling.timeline_filename",
    help="Chrome-trace timeline output path (rank 0 writes; reference "
         "operations.cc:403-411)."))
_register("timeline_mark_cycles", Knob(
    "HOROVOD_TIMELINE_MARK_CYCLES", False, _parse_bool,
    cli="--timeline-mark-cycles", config_key="profiling.timeline_mark_cycles",
    help="Emit background-cycle markers into the timeline."))
_register("attn_xla_score_bytes", Knob(
    "HOROVOD_ATTN_XLA_SCORE_BYTES", 4 << 30, int,
    cli="--attn-xla-score-bytes", config_key="attention.xla_score_bytes",
    help="Ring attention auto-impl threshold: per-ring-step fp32 "
         "score+softmax bytes up to which XLA's fused attention is "
         "used; beyond it the streaming Pallas kernel takes over."))
_register("attn_block_q", Knob(
    "HOROVOD_ATTN_BLOCK_Q", 0, int,
    cli="--attn-block-q", config_key="attention.block_q",
    help="Pallas attention Q tile size (0 = auto: largest MXU-friendly "
         "divisor of the chunk, preferring 128). Bench/tuning hook for "
         "the on-chip tile sweep; must divide the local sequence "
         "chunk, else auto applies."))
_register("attn_pallas_bwd", Knob(
    "HOROVOD_ATTN_PALLAS_BWD", "kernel", str,
    cli="--attn-pallas-bwd", config_key="attention.pallas_bwd",
    help="Backward strategy for the Pallas ring-attention impl: "
         "'kernel' (default — saved-LSE flash backward kernels, O(L) "
         "residuals) or 'remat' (XLA block-step VJP rematerializing "
         "the fp32 score block per ring step; A/B hook)."))
_register("attn_block_k", Knob(
    "HOROVOD_ATTN_BLOCK_K", 0, int,
    cli="--attn-block-k", config_key="attention.block_k",
    help="Pallas attention K tile size (0 = auto, see "
         "--attn-block-q)."))
_register("jax_profiler", Knob(
    "HOROVOD_TIMELINE_JAX_PROFILER", "", str,
    cli="--jax-profiler-dir", config_key="profiling.jax_profiler_dir",
    help="Directory for device-side jax.profiler capture (xplane, "
         "TensorBoard profile plugin); every rank writes rank<k>/. "
         "The TPU analog of the reference's CUDA-event op timings."))
_register("profile_every_n", Knob(
    "HOROVOD_PROFILE_EVERY_N_STEPS", 0, int,
    cli="--profile-every-n-steps", config_key="profiling.every_n_steps",
    help="Sampled continuous device capture (docs/perf.md): every N-th "
         "hvd.trace_step() span is captured with the jax profiler into "
         "a rotating per-rank directory (HOROVOD_PROFILE_DIR), "
         "analyzed in the background by the stdlib xplane reader, and "
         "published as hvd_device_*/hvd_mfu gauges on the metrics "
         "plane.  0 (default) disables.  Mutually exclusive with the "
         "whole-run HOROVOD_TIMELINE_JAX_PROFILER capture, which owns "
         "the profiler when set."))
_register("profile_dir", Knob(
    "HOROVOD_PROFILE_DIR", "", str,
    cli="--profile-dir", config_key="profiling.profile_dir",
    help="Root directory for sampled step captures "
         "(HOROVOD_PROFILE_EVERY_N_STEPS); each rank writes "
         "rank<k>/step<n>/ with the raw xplane capture plus its "
         "analysis.json.  Empty (default) means ./hvd_profile.  "
         "Inspect with `python -m horovod_tpu.perf report <dir>`."))
_register("profile_keep", Knob(
    "HOROVOD_PROFILE_KEEP", 4, int,
    cli="--profile-keep", config_key="profiling.keep",
    help="How many sampled step captures each rank keeps "
         "(oldest rotated out), bounding disk use on long runs."))
_register("peak_flops", Knob(
    "HOROVOD_PEAK_FLOPS_PER_CHIP", 0.0, float,
    cli="--peak-flops-per-chip", config_key="profiling.peak_flops",
    help="Peak chip FLOP/s used as the MFU denominator by the perf "
         "observatory; 0 (default) auto-detects from the TPU "
         "generation's spec sheet.  Set explicitly for hardware the "
         "table predates, or to give CPU test runs an MFU number."))
_register("flight_dir", Knob(
    "HOROVOD_FLIGHT_DIR", "", str,
    cli="--flight-dir", config_key="flight.dir",
    help="Directory for flight-recorder dumps (docs/flight-recorder.md)."
         "  Every rank keeps a crash-surviving in-memory ring of runtime"
         " events (rounds, wire, collectives, heartbeats, stalls,"
         " elastic generations) and atomically dumps it here as JSONL on"
         " a coordinated abort, RanksDownError, SIGTERM/SIGABRT, an"
         " elastic re-form, or hvd.dump_flight_recorder().  Merge and"
         " analyze with `python -m horovod_tpu.trace merge <dir>`."
         "  Empty (default) disables dumping; the in-memory ring still"
         " records."))
_register("flight_events", Knob(
    "HOROVOD_FLIGHT_EVENTS", 4096, int,
    cli="--flight-events", config_key="flight.events",
    help="Flight-recorder ring capacity in events (default 4096; 0"
         " disables recording).  Memory stays bounded at this many"
         " entries regardless of run length — old events are"
         " overwritten in place."))
_register("goodput_dir", Knob(
    "HOROVOD_GOODPUT_DIR", "", str,
    cli="--goodput-dir", config_key="goodput.dir",
    help="Directory for per-rank goodput ledger dumps "
         "(goodput-r<k>-g<g>.json, written on shutdown and on every "
         "abort/fatal-signal flight dump).  Empty (default) falls back "
         "to HOROVOD_FLIGHT_DIR so wall-clock attribution lands next "
         "to the postmortem rings; with neither set, dumps are off "
         "(the in-memory ledger and its gauges still run).  Report "
         "with `python -m horovod_tpu.perf goodput <dir>`.  See "
         "docs/goodput.md."))
_register("goodput_slo", Knob(
    "HOROVOD_GOODPUT_SLO", 0.0, float,
    cli="--goodput-slo", config_key="goodput.slo",
    help="Fleet goodput SLO in (0, 1]: when the sliding-window fleet "
         "goodput (useful compute seconds / world x wall-clock) falls "
         "below it, the launcher aggregate raises "
         "hvd_goodput_alert{reason=<dominant phase>}=1 with the "
         "error-budget burn rate beside it.  0 (default) disarms the "
         "alert; the goodput gauges publish either way.  See "
         "docs/goodput.md."))
_register("goodput_window", Knob(
    "HOROVOD_GOODPUT_WINDOW_SECONDS", 300.0, float,
    cli="--goodput-window-seconds", config_key="goodput.window",
    help="Sliding window for the fleet goodput / dominant-bottleneck / "
         "SLO-burn computation on the launcher aggregate (default "
         "300 s).  Shorter windows react faster but alert on transient "
         "dips; pair with the SLO like a burn-rate alert policy.  See "
         "docs/goodput.md."))
_register("goodput_unattributed_max", Knob(
    "HOROVOD_GOODPUT_UNATTRIBUTED_MAX", 0.10, float,
    cli="--goodput-unattributed-max", config_key="goodput.unattributed_max",
    help="Honest-accounting ceiling: when the goodput ledger's "
         "unattributed share of wall-clock exceeds this ratio "
         "(default 0.10), the rank logs one warning — an "
         "uninstrumented phase is eating the run and the ledger's "
         "other numbers understate it.  0 disables the warning; the "
         "hvd_goodput_unattributed_ratio gauge publishes regardless.  "
         "See docs/goodput.md."))
_register("data_wait_min", Knob(
    "HOROVOD_DATA_WAIT_MIN_SECONDS", 0.0, float,
    cli="--data-wait-min-seconds", config_key="goodput.data_wait_min",
    help="Noise floor for hvd.data_wait() / hvd.wrap_data_loader "
         "spans: waits shorter than this many seconds are not "
         "recorded (they stay attributed to compute).  Default 0 "
         "records every span; raise it when a fast in-memory iterator "
         "makes the per-next() timing overhead itself the signal.  "
         "See docs/goodput.md."))
_register("health", Knob(
    "HOROVOD_HEALTH", False, _parse_bool,
    cli="--health", config_key="health.enabled",
    help="Training-health plane (docs/health.md): in-trace numerics "
         "stat taps in DistributedOptimizer (all ZeRO stages, overlap "
         "on/off) and the negotiated allreduce/reducescatter programs "
         "— per-dtype-group grad norm, max-abs and PRE-reduction "
         "nonfinite count published as hvd_grad_norm / "
         "hvd_nonfinite_total{group,rank} with culprit-rank "
         "attribution, plus the post-update update-to-weight ratio "
         "and the EWMA divergence sentinels.  Near-zero cost: stats "
         "ride the existing programs; the only new communication is "
         "one small packed per-rank verdict vector allgathered per "
         "step.  Must agree on every rank (validated at the round-0 "
         "handshake: the tap adds a small allgather to the negotiated "
         "programs — a rank without it would build a mismatched "
         "collective schedule and deadlock)."))
_register("health_skip_nonfinite", Knob(
    "HOROVOD_HEALTH_SKIP_NONFINITE", False, _parse_bool,
    cli="--health-skip-nonfinite", config_key="health.skip_nonfinite",
    help="Skip-step contract (docs/health.md): when the health "
         "verdict reports a nonfinite gradient on ANY rank, the "
         "optimizer suppresses the step — update zeroed, optimizer "
         "state (momenta, error-feedback residuals) held — so "
         "survivors' parameters stay finite while hvd_nonfinite_total "
         "names the culprit.  Requires HOROVOD_HEALTH=1.  Must agree "
         "on every rank (validated at the round-0 handshake: a rank "
         "skipping while another applies would fork the replicated "
         "parameter trajectory)."))
_register("health_ewma_alpha", Knob(
    "HOROVOD_HEALTH_EWMA_ALPHA", 0.1, float,
    cli="--health-ewma-alpha", config_key="health.ewma_alpha",
    help="EWMA smoothing factor for the divergence sentinels' "
         "loss/grad-norm baselines (default 0.1; the baseline absorbs "
         "only healthy samples so it cannot chase a divergence).  See "
         "docs/health.md."))
_register("health_sentinel_ratio", Knob(
    "HOROVOD_HEALTH_SENTINEL_RATIO", 4.0, float,
    cli="--health-sentinel-ratio", config_key="health.sentinel_ratio",
    help="Divergence sentinel threshold: a loss/grad-norm sample "
         "breaches when it exceeds this multiple of its EWMA baseline "
         "(default 4.0; 0 disables ratio breaches — nonfinite values "
         "still alert immediately).  See docs/health.md."))
_register("health_trip_steps", Knob(
    "HOROVOD_HEALTH_TRIP_STEPS", 3, int,
    cli="--health-trip-steps", config_key="health.trip_steps",
    help="Sentinel hysteresis, trip side: consecutive breaching "
         "samples before hvd_health_alert raises (default 3 — one "
         "noisy batch must not page anyone).  See docs/health.md."))
_register("health_clear_steps", Knob(
    "HOROVOD_HEALTH_CLEAR_STEPS", 20, int,
    cli="--health-clear-steps", config_key="health.clear_steps",
    help="Sentinel hysteresis, clear side: consecutive healthy "
         "samples before an active alert clears (default 20 — an "
         "alert must not flap across the breach boundary).  See "
         "docs/health.md."))
_register("health_dir", Knob(
    "HOROVOD_HEALTH_DIR", "", str,
    cli="--health-dir", config_key="health.dir",
    help="Directory for per-rank health snapshot dumps "
         "(health-r<k>-g<g>.json, written on shutdown and on every "
         "abort/flight dump).  Empty (default) falls back to "
         "HOROVOD_FLIGHT_DIR; with neither set, dumps are off (the "
         "in-memory monitor and its gauges still run).  Report with "
         "`python -m horovod_tpu.perf health <dir>`.  See "
         "docs/health.md."))
_register("metrics_port", Knob(
    "HOROVOD_METRICS_PORT", 0, int,
    cli="--metrics-port", config_key="metrics.port",
    help="Prometheus-text metrics endpoint base port; 0 (default) "
         "disables.  Each rank serves /metrics on base + rank; under "
         "hvdrun the launcher serves the fleet-wide aggregate on the "
         "given port and exports base + 1 to ranks so nothing collides "
         "on a shared host.  See docs/metrics.md."))
_register("metrics_publish_interval", Knob(
    "HOROVOD_METRICS_PUBLISH_INTERVAL", 5.0, float,
    cli="--metrics-publish-interval",
    config_key="metrics.publish_interval",
    help="Seconds between each rank's metric-snapshot publishes into "
         "the rendezvous KV (hvd<epoch>/metrics/<rank>, merged by the "
         "launcher's aggregate /metrics endpoint); 0 disables "
         "publishing.  See docs/metrics.md."))
_register("stall_check_disable", Knob(
    "HOROVOD_STALL_CHECK_DISABLE", False, _parse_bool,
    cli="--no-stall-check", config_key="stall_check.disable",
    help="Disable the stall inspector."))
_register("stall_warning_time", Knob(
    "HOROVOD_STALL_CHECK_TIME_SECONDS", 60.0, float,
    cli="--stall-timeout-seconds", config_key="stall_check.warning_time_seconds",
    help="Seconds before warning about ranks missing a collective "
         "(reference stall_inspector.h:74)."))
_register("stall_shutdown_time", Knob(
    "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", 0.0, float,
    cli="--stall-shutdown-timeout-seconds",
    config_key="stall_check.shutdown_time_seconds",
    help="Seconds before a stall escalates to shutdown; 0 disables "
         "(reference stall_inspector.h:78)."))
_register("wire_timeout", Knob(
    "HOROVOD_WIRE_TIMEOUT_SECONDS", 600.0, float,
    cli="--wire-timeout-seconds", config_key="fault_tolerance.wire_timeout",
    help="Deadline for one control-plane KV wait (a rank's request "
         "list, the coordinator's response).  Decoupled from "
         "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS, which used to double "
         "as the wire timeout.  See docs/fault-tolerance.md."))
_register("heartbeat_interval", Knob(
    "HOROVOD_HEARTBEAT_INTERVAL", 2.0, float,
    cli="--heartbeat-interval", config_key="fault_tolerance.heartbeat_interval",
    help="Seconds between control-plane heartbeat publishes "
         "(hb/<epoch>/<rank> keys); 0 disables liveness tracking and "
         "coordinated abort.  Must agree on every rank (validated at "
         "the round-0 handshake: a rank with liveness off would be "
         "declared dead by peers expecting beats).  See "
         "docs/fault-tolerance.md."))
_register("control_fanout", Knob(
    "HOROVOD_CONTROL_FANOUT", 8, int,
    cli="--control-fanout", config_key="control_plane.fanout",
    help="Hierarchical control plane (docs/control-plane.md): worlds "
         "larger than this negotiate through per-slice sub-"
         "coordinators (one merged message per slice per round reaches "
         "rank 0) instead of the flat rank-0 star; 0 forces flat mode "
         "at any size.  Must agree on every rank (validated at the "
         "round-0 handshake: a rank negotiating flat against "
         "hierarchical peers would wait on keys nobody writes)."))
_register("fault_spec", Knob(
    "HOROVOD_FAULT_SPEC", "", str,
    cli="--fault-spec", config_key="fault_tolerance.fault_spec",
    help="Deterministic fault injection on the control-plane wire "
         "(testing only): comma-separated delay:<glob>:<dur>, "
         "drop:<glob>[:<n>], die:rank<k>[:round<n>], "
         "preempt:rank<k>[:round<n>][:grace<s>] (graceful advance "
         "notice instead of die's hard exit), "
         "slow:<rank>:<delay> (chronic straggler), "
         "nan:<nameglob>[:round<n>], inf:<nameglob>[:round<n>] "
         "specs.  See docs/fault-tolerance.md."))
_register("kv_retries", Knob(
    "HOROVOD_KV_RETRIES", 3, int,
    cli="--kv-retries", config_key="fault_tolerance.kv_retries",
    help="Bounded retries (exponential backoff + jitter, reconnect "
         "between attempts) for native KV-store wire failures."))
_register("elastic", Knob(
    "HOROVOD_ELASTIC", False, _parse_bool,
    cli="--elastic", config_key="fault_tolerance.elastic",
    help="Elastic mode: survivors of a dead rank re-form the job at the "
         "new world size in-process (hvd.elastic.run) instead of the "
         "whole job restarting; the launcher keeps the rendezvous "
         "server alive across re-forms, blacklists hosts whose ranks "
         "died, and respawns replacements that rejoin at the next "
         "commit boundary.  Must agree on every rank (validated at "
         "the round-0 handshake: an elastic survivor re-forming "
         "against a non-elastic peer would hang the rendezvous).  See "
         "docs/elastic.md."))
_register("min_ranks", Knob(
    "HOROVOD_MIN_RANKS", 1, int,
    cli="--min-ranks", config_key="fault_tolerance.min_ranks",
    help="Elastic mode: smallest world size the job may shrink to; a "
         "re-form that would leave fewer survivors fails the job "
         "(falling back to --restart-attempts when set)."))
_register("blacklist_cooldown", Knob(
    "HOROVOD_BLACKLIST_COOLDOWN_SECONDS", 120.0, float,
    cli="--blacklist-cooldown-seconds",
    config_key="fault_tolerance.blacklist_cooldown",
    help="Elastic mode: how long the launcher refuses to respawn ranks "
         "on a host after one of its ranks died.  After the cooldown "
         "the host is admissible again and the job grows back toward "
         "its original size."))
_register("elastic_settle", Knob(
    "HOROVOD_ELASTIC_SETTLE_SECONDS", 10.0, float,
    cli="--elastic-settle-seconds",
    config_key="fault_tolerance.elastic_settle",
    help="Elastic mode: how long the re-form leader waits for every "
         "expected survivor to announce presence before declaring "
         "stragglers dead and publishing the new-generation roster.  "
         "Survivors hit the failure at different points of the same "
         "training step, so this bounds that skew."))
_register("elastic_join_timeout", Knob(
    "HOROVOD_ELASTIC_JOIN_TIMEOUT_SECONDS", 3600.0, float,
    cli="--elastic-join-timeout-seconds",
    config_key="fault_tolerance.elastic_join_timeout",
    help="Elastic mode: how long a replacement process waits in the "
         "admission waiting room for a survivors' commit boundary to "
         "admit it.  Must exceed the training loop's commit cadence; "
         "on timeout the joiner retracts its registration (so a later "
         "grow re-form never admits a ghost) and exits."))
_register("restart_attempts", Knob(
    "HOROVOD_RESTART_ATTEMPTS", 0, int,
    cli="--restart-attempts", config_key="fault_tolerance.restart_attempts",
    help="hvdrun: relaunch the whole job up to N times after a failed "
         "attempt, resuming from the latest complete checkpoint when "
         "--checkpoint-dir is set (HOROVOD_RESUME_STEP is exported to "
         "the restarted ranks)."))
_register("checkpoint_dir", Knob(
    "HOROVOD_CHECKPOINT_DIR", "", str,
    cli="--checkpoint-dir", config_key="fault_tolerance.checkpoint_dir",
    help="Checkpoint store the launcher consults on restart "
         "(checkpoint.latest_complete: only snapshots with an atomic "
         "DONE marker count; torn snapshots are refused)."))
_register("checkpoint_keep", Knob(
    "HOROVOD_CHECKPOINT_KEEP", 0, int,
    cli="--checkpoint-keep", config_key="fault_tolerance.checkpoint_keep",
    help="Last-K checkpoint retention ring: after each durable save, "
         "complete older snapshots beyond the newest K are pruned "
         "(0 = keep everything, the pre-ring behavior).  K >= 2 is "
         "what makes auto-rollback useful — the newest snapshot may "
         "carry a poisoned health verdict, the ring must still hold a "
         "healthy ancestor.  See docs/autopilot.md."))
_register("checkpoint_verify", Knob(
    "HOROVOD_CHECKPOINT_VERIFY", True, _parse_bool,
    cli="--checkpoint-verify",
    config_key="fault_tolerance.checkpoint_verify",
    help="Integrity verification on restore/discovery: every save "
         "stamps a MANIFEST.json (per-file SHA-256 + sizes) inside "
         "the atomic rename, and restore()/latest_complete()/"
         "latest_healthy() verify against it — a bit-rotted snapshot "
         "is quarantined (step_<N>.corrupt, loud log, flight event) "
         "and the next complete one is used instead.  Pre-manifest "
         "snapshots warn and pass.  0 restores unverified bytes.  "
         "See docs/checkpoint.md."))
_register("checkpoint_replicas", Knob(
    "HOROVOD_CHECKPOINT_REPLICAS", 2, int,
    cli="--checkpoint-replicas",
    config_key="fault_tolerance.checkpoint_replicas",
    help="Total copies of each all_ranks ZeRO shard dir per snapshot "
         "(default 2 = owner + one ring-buddy replica under "
         "step_<N>/rep_<owner>_<holder>/), so one host loss never "
         "takes the only copy of shard-local state; restore prefers "
         "the local copy and falls back to any verified replica.  "
         "0/1 disables replication.  Must agree on every rank "
         "(validated at the round-0 handshake: replication is a "
         "broadcast round per owner inside all_ranks save, so a rank "
         "skipping it while peers replicate deadlocks the save).  "
         "See docs/checkpoint.md."))
_register("preempt_grace", Knob(
    "HOROVOD_PREEMPT_GRACE_SECONDS", 30.0, float,
    cli="--preempt-grace-seconds",
    config_key="fault_tolerance.preempt_grace",
    help="Graceful-preemption plane (docs/fault-tolerance.md): the "
         "advance-notice window a drain must finish inside.  A "
         "noticed rank (SIGTERM/SIGUSR1, hvdrun --preempt, a "
         "preempt: fault rule, or the pluggable metadata source) "
         "publishes el/preempt/<rank>; the fleet takes one emergency "
         "commit at the next agreed step boundary, the noticed rank "
         "exits cleanly, and survivors re-form proactively — no "
         "heartbeat-timeout stall, no blacklist.  <= 0 disables the "
         "plane (SIGTERM means death again)."))
_register("autopilot", Knob(
    "HOROVOD_AUTOPILOT", False, _parse_bool,
    cli="--autopilot", config_key="autopilot.enabled",
    help="Closed-loop supervisor (docs/autopilot.md): the launcher "
         "aggregate loop and the rank-side elastic driver act on the "
         "observability planes — preemptive host blacklist on "
         "sustained straggling, elastic shrink/grow on goodput SLO "
         "burn, auto-rollback to the newest healthy commit on a "
         "divergence sentinel trip, and comm-knob retune from "
         "measured exposed communication.  Every action lands on the "
         "flight ring with its evidence tuple."))
_register("autopilot_dry_run", Knob(
    "HOROVOD_AUTOPILOT_DRY_RUN", False, _parse_bool,
    cli="--autopilot-dry-run", config_key="autopilot.dry_run",
    help="Autopilot shadow mode: every rule still evaluates, paces "
         "its cooldowns, and records would-have-acted verdicts on the "
         "flight ring, but NO actuator fires — the audit trail for "
         "building trust before enabling closed-loop actions.  See "
         "docs/autopilot.md."))
_register("autopilot_cooldown", Knob(
    "HOROVOD_AUTOPILOT_COOLDOWN_SECONDS", 60.0, float,
    cli="--autopilot-cooldown-seconds", config_key="autopilot.cooldown",
    help="Per-rule refractory period: after a rule fires (or dry-run "
         "fires), it cannot fire again for this long — the flap guard "
         "between hysteresis (entry) and the global rate limit "
         "(fleet-wide ceiling).  See docs/autopilot.md."))
_register("autopilot_rate_limit", Knob(
    "HOROVOD_AUTOPILOT_RATE_LIMIT", 4, int,
    cli="--autopilot-rate-limit", config_key="autopilot.rate_limit",
    help="Global action ceiling: at most this many autopilot actions "
         "(all rules combined) per HOROVOD_AUTOPILOT_RATE_WINDOW_"
         "SECONDS; excess verdicts are recorded as suppressed.  See "
         "docs/autopilot.md."))
_register("autopilot_rate_window", Knob(
    "HOROVOD_AUTOPILOT_RATE_WINDOW_SECONDS", 600.0, float,
    cli="--autopilot-rate-window-seconds",
    config_key="autopilot.rate_window",
    help="Sliding window over which HOROVOD_AUTOPILOT_RATE_LIMIT "
         "counts actions.  See docs/autopilot.md."))
_register("autopilot_trip_ticks", Knob(
    "HOROVOD_AUTOPILOT_TRIP_TICKS", 3, int,
    cli="--autopilot-trip-ticks", config_key="autopilot.trip_ticks",
    help="Hysteresis: consecutive evaluation ticks a condition must "
         "hold (same candidate for the straggler rule) before the "
         "rule fires — one noisy sample must not shrink a fleet.  See "
         "docs/autopilot.md."))
_register("autopilot_straggler_factor", Knob(
    "HOROVOD_AUTOPILOT_STRAGGLER_FACTOR", 4.0, float,
    cli="--autopilot-straggler-factor",
    config_key="autopilot.straggler_factor",
    help="Preemptive-blacklist breach multiple: a rank is a chronic "
         "straggler when its coordinator-clock lateness exceeds this "
         "multiple of the fleet median (or supplied baseline), "
         "sustained for HOROVOD_AUTOPILOT_TRIP_TICKS.  See "
         "docs/autopilot.md."))
_register("autopilot_straggler_floor", Knob(
    "HOROVOD_AUTOPILOT_STRAGGLER_FLOOR", 0.05, float,
    cli="--autopilot-straggler-floor",
    config_key="autopilot.straggler_floor",
    help="Absolute lateness floor (seconds) below which the straggler "
         "rule never fires regardless of the relative factor — "
         "microsecond jitter on an idle fleet is not a straggler.  See "
         "docs/autopilot.md."))
_register("autopilot_burn_threshold", Knob(
    "HOROVOD_AUTOPILOT_BURN_THRESHOLD", 2.0, float,
    cli="--autopilot-burn-threshold",
    config_key="autopilot.burn_threshold",
    help="SLO-burn elastic trigger: the shrink rule arms when the "
         "fleet goodput alert is firing AND its burn_rate (lost "
         "goodput over SLO headroom) sustains at or above this "
         "value for HOROVOD_AUTOPILOT_TRIP_TICKS.  Requires "
         "HOROVOD_GOODPUT_SLO.  See docs/autopilot.md."))
_register("autopilot_comm_fraction", Knob(
    "HOROVOD_AUTOPILOT_COMM_FRACTION", 0.25, float,
    cli="--autopilot-comm-fraction",
    config_key="autopilot.comm_fraction",
    help="Retune trigger: when measured exposed-communication time "
         "exceeds this fraction of exposed+compute, sustained for "
         "HOROVOD_AUTOPILOT_TRIP_TICKS, the autopilot proposes a "
         "comm-knob retune through the autotuner's knob ownership "
         "(parameter_manager.apply_params).  See docs/autopilot.md."))
_register("autotune", Knob(
    "HOROVOD_AUTOTUNE", False, _parse_bool,
    cli="--autotune", config_key="autotune.enabled",
    help="Bayesian autotuning of fusion/cycle knobs (reference "
         "parameter_manager.h:42)."))
_register("autotune_log", Knob(
    "HOROVOD_AUTOTUNE_LOG", "", str,
    cli="--autotune-log-file", config_key="autotune.log_file",
    help="CSV log of autotune samples."))
_register("autotune_warmup_samples", Knob(
    "HOROVOD_AUTOTUNE_WARMUP_SAMPLES", 3, int,
    cli="--autotune-warmup-samples", config_key="autotune.warmup_samples",
    help="Discarded warmup windows before scoring."))
_register("autotune_steps_per_sample", Knob(
    "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", 10, int,
    cli="--autotune-steps-per-sample", config_key="autotune.steps_per_sample",
    help="Background cycles per autotune scoring window."))
_register("autotune_bayes_opt_max_samples", Knob(
    "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", 20, int,
    cli="--autotune-bayes-opt-max-samples", config_key="autotune.bayes_opt_max_samples",
    help="Max Bayesian-optimization samples before pinning best."))
_register("autotune_gaussian_process_noise", Knob(
    "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE", 0.8, float,
    cli="--autotune-gaussian-process-noise", config_key="autotune.gaussian_process_noise",
    help="GP observation-noise prior."))
_register("log_level", Knob(
    "HOROVOD_LOG_LEVEL", "warning", str,
    cli="--log-level", config_key="logging.level",
    help="trace/debug/info/warning/error/fatal."))
_register("log_hide_time", Knob(
    "HOROVOD_LOG_HIDE_TIME", False, _parse_bool,
    cli="--log-hide-timestamp", config_key="logging.hide_timestamp",
    help="Hide timestamps in log lines."))

# TPU-build-specific knobs.
_register("platform", Knob(
    "HOROVOD_PLATFORM", "", str,
    cli="--platform", config_key="tpu.platform",
    help="Force JAX platform (cpu for tests, tpu in production)."))
_register("coordinator_addr", Knob(
    "HOROVOD_COORDINATOR_ADDR", "", str, help="jax.distributed coordinator address host:port."))
_register("rendezvous_addr", Knob(
    "HOROVOD_GLOO_RENDEZVOUS_ADDR", "", str,
    help="KV-store rendezvous server address (reference env name kept "
         "for drop-in compatibility, gloo_run.py:152)."))
_register("rendezvous_port", Knob(
    "HOROVOD_GLOO_RENDEZVOUS_PORT", 0, int, help="KV-store rendezvous port."))
_register("heartbeat_timeout", Knob(
    "HOROVOD_HEARTBEAT_TIMEOUT_SECONDS", 20.0, float,
    cli="--heartbeat-timeout-seconds",
    config_key="fault_tolerance.heartbeat_timeout",
    help="How fast a crashed peer is detected: a rank whose "
         "control-plane heartbeat goes stale for this long triggers a "
         "coordinated abort (RanksDownError on every survivor).  Also "
         "passed to jax.distributed's own heartbeat machinery at "
         "init().  Must agree on every rank (validated at the round-0 "
         "handshake, like the heartbeat interval).  See "
         "docs/fault-tolerance.md."))
_register("shutdown_timeout", Knob(
    "HOROVOD_SHUTDOWN_TIMEOUT_SECONDS", 10, int,
    help="Max seconds a terminating process waits at the distributed "
         "shutdown barrier (jax default of 300s stalls crashed jobs)."))
_register("aot_cache_dir", Knob(
    "HOROVOD_AOT_CACHE_DIR", "", str,
    cli="--aot-cache-dir", config_key="aot_cache.dir",
    help="Persistent AOT executable cache for the negotiated data "
         "plane (docs/aot-cache.md): compiled collective programs are "
         "serialized here keyed by (round-0 cfg vector, topology, "
         "jax/jaxlib/libtpu versions, program signature), so a restart "
         "or elastic re-form loads executables in seconds instead of "
         "recompiling every program from scratch.  Fail-closed: any "
         "deserialize error, version skew or key mismatch evicts the "
         "entry and recompiles — a stale program can never run.  Empty "
         "(default) disables.  Inspect/prune with `python -m "
         "horovod_tpu.runtime.aot_cache list|prune`."))
_register("aot_cache_mode", Knob(
    "HOROVOD_AOT_CACHE_MODE", "auto", str,
    cli="--aot-cache-mode", config_key="aot_cache.mode",
    help="AOT cache serialization format: auto (default: 'exec'), "
         "exec (serialized compiled executable — warm loads skip XLA "
         "entirely), export (serialized lowered StableHLO via "
         "jax.export — the escape hatch when executable serialization "
         "misbehaves on a platform; warm loads still pay the XLA "
         "compile and only skip Python tracing), off (disable even "
         "when HOROVOD_AOT_CACHE_DIR is set).  Both formats key on "
         "the exact jax/jaxlib/libtpu versions — a version bump "
         "always recompiles."))
_register("fused_update", Knob(
    "HOROVOD_FUSED_UPDATE", False, _parse_bool,
    cli="--fused-update", config_key="optimizer.fused_update",
    help="Pallas-fused optimizer tail (docs/zero.md): collapse the "
         "post-reduction update chain — unscale, dtype cast, momentum/"
         "Adam moment update, bias correction, step — into one fused "
         "kernel per flat per-dtype buffer instead of a chain of small "
         "HBM-round-tripping XLA ops.  Applies across ZeRO stages 0-3 "
         "when the wrapped optimizer is fusable (built by "
         "hvd.fused_update.sgd/adam — bit-exact vs the unfused optax "
         "chain); silently falls back with one warning otherwise.  "
         "Local-only knob (the update runs after the wire), so it "
         "needs no cross-rank handshake."))
# (HOROVOD_EAGER_PAD_POW2 was registered here through PR 11 but never
# had a reader — the eager path pads fused buffers to world-size
# multiples, not powers of two.  analysis.knob_lint's KNOB-DEAD rule
# now flags registered knobs nothing reads; the dead entry is gone.)


def get(name: str) -> Any:
    """Read a knob: env var wins, else default."""
    k = _KNOBS[name]
    raw = os.environ.get(k.env)
    if raw is None or raw == "":
        return k.default
    try:
        return k.parse(raw)
    except (ValueError, TypeError):
        return k.default


def is_set(name: str) -> bool:
    """True when the knob's env var is explicitly set to a non-blank
    value — the registry-sanctioned way to distinguish an operator's
    explicit choice from the default (raw ``os.environ`` probes
    outside this module are flagged by ``analysis.knob_lint``).
    Whitespace-only counts as unset: ``get()`` would fall back to the
    default for it, and an "explicit" flag that resolves to the
    default is exactly the false positive callers use this to
    avoid."""
    k = _KNOBS[name]
    return bool(os.environ.get(k.env, "").strip())


def set_knob(name: str, value: Any) -> None:
    """Set a knob by exporting its env var (the single source of truth,
    like the reference where all surfaces converge on env)."""
    k = _KNOBS[name]
    if isinstance(value, bool):
        os.environ[k.env] = "1" if value else "0"
    else:
        os.environ[k.env] = str(value)


def knobs() -> dict[str, Knob]:
    return dict(_KNOBS)


# ---------------------------------------------------------------------------
# Config file -> env (reference config_parser.py:38-130)
# ---------------------------------------------------------------------------


def _flatten(d: dict, prefix: str = "") -> dict[str, Any]:
    out: dict[str, Any] = {}
    for key, val in d.items():
        dotted = f"{prefix}.{key}" if prefix else key
        if isinstance(val, dict):
            out.update(_flatten(val, dotted))
        else:
            out[dotted] = val
    return out


def load_config_file(path: str, override: bool = False) -> dict[str, Any]:
    """Load a YAML/JSON config file and export matching knobs to env.

    CLI flags take precedence over the file (reference
    ``runner.py:274-277``): the launcher loads the file first, then
    applies CLI flags on top.  Returns the applied mapping.
    """
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        try:
            import yaml  # type: ignore

            data = yaml.safe_load(text)
        except ImportError as exc:
            raise RuntimeError(
                "config file is not JSON and PyYAML is unavailable") from exc
    flat = _flatten(data or {})
    applied = {}
    by_key = {k.config_key: (name, k) for name, k in _KNOBS.items() if k.config_key}
    for dotted, value in flat.items():
        if dotted in by_key:
            name, knob = by_key[dotted]
            if not override and os.environ.get(knob.env):
                continue
            set_knob(name, value)
            applied[name] = value
    return applied


def set_env_from_args(args, env: dict | None = None) -> dict:
    """Map parsed launcher CLI args onto HOROVOD_* env (reference
    ``config_parser.py:141-190``)."""
    env = env if env is not None else os.environ  # type: ignore[assignment]
    for name, knob in _KNOBS.items():
        if knob.cli is None:
            continue
        attr = knob.cli.lstrip("-").replace("-", "_")
        if hasattr(args, attr):
            val = getattr(args, attr)
            if val is None:
                continue
            if name == "fusion_threshold":
                val = int(val) * 1024 * 1024  # CLI flag is in MB
            if isinstance(val, bool):
                # explicit False (--no-flag) must override a truthy default
                env[knob.env] = "1" if val else "0"
            else:
                env[knob.env] = str(val)
    return env
