"""Leveled, rank-prefixed logging.

Capability parity with the reference's C++ logger
(``horovod/common/logging.h:10-56``): levels TRACE/DEBUG/INFO/WARNING/
ERROR/FATAL selected by ``HOROVOD_LOG_LEVEL``, optional timestamp
suppression via ``HOROVOD_LOG_HIDE_TIME``.
"""

from __future__ import annotations

import os
import sys
import threading
import time

TRACE, DEBUG, INFO, WARNING, ERROR, FATAL = 0, 1, 2, 3, 4, 5

_LEVEL_NAMES = {
    "trace": TRACE,
    "debug": DEBUG,
    "info": INFO,
    "warning": WARNING,
    "error": ERROR,
    "fatal": FATAL,
}
_LEVEL_TAGS = {TRACE: "T", DEBUG: "D", INFO: "I", WARNING: "W", ERROR: "E", FATAL: "F"}

_lock = threading.Lock()


def format_fields(fields: dict) -> str:
    """Structured ``key=value`` suffix for log lines: scalars verbatim,
    everything else (lists, dicts, strings with spaces) as compact
    JSON, so lines stay grep-able AND machine-parseable."""
    import json

    parts = []
    for key in sorted(fields):
        val = fields[key]
        if isinstance(val, bool):
            text = "1" if val else "0"
        elif isinstance(val, (int, float)):
            text = str(val)
        elif isinstance(val, str) and val and " " not in val \
                and "=" not in val and '"' not in val:
            text = val
        else:
            try:
                text = json.dumps(val, separators=(",", ":"),
                                  sort_keys=True, default=str)
            except (TypeError, ValueError):
                text = repr(val)
        parts.append(f"{key}={text}")
    return " ".join(parts)


def _min_level() -> int:
    # Lazy registry import: logging is imported by nearly everything,
    # so the dependency edge points at the (stdlib-only) config module
    # only when a line actually renders.
    from horovod_tpu.common import config as _config

    return _LEVEL_NAMES.get(str(_config.get("log_level")).lower(),
                            WARNING)


def _hide_time() -> bool:
    from horovod_tpu.common import config as _config

    return bool(_config.get("log_hide_time"))


def log(level: int, msg: str, rank: int | None = None,
        force: bool = False, **fields) -> None:
    """``fields`` render as a sorted ``key=value`` suffix
    (:func:`format_fields`).  ``force=True`` bypasses the level gate —
    for operator-facing events (launcher re-form status) that must stay
    visible at the default log level."""
    if not force and level < _min_level():
        return
    if fields:
        msg = f"{msg} {format_fields(fields)}"
    parts = ["[", _LEVEL_TAGS[level], "]"]
    if not _hide_time():
        t = time.time()
        stamp = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(t))
        parts.insert(0, "%s.%06d " % (stamp, int((t % 1) * 1e6)))
    if rank is None:
        rank = int(os.environ.get("HOROVOD_RANK", os.environ.get("HOROVOD_TPU_RANK", -1)))
    if rank >= 0:
        parts.append("[%d]" % rank)
    parts.append(": ")
    parts.append(msg)
    line = "".join(parts)
    with _lock:
        print(line, file=sys.stderr, flush=True)
    if level == FATAL:
        raise SystemExit(line)


def trace(msg: str, rank: int | None = None, **kw) -> None:
    log(TRACE, msg, rank, **kw)


def debug(msg: str, rank: int | None = None, **kw) -> None:
    log(DEBUG, msg, rank, **kw)


def info(msg: str, rank: int | None = None, **kw) -> None:
    log(INFO, msg, rank, **kw)


def warning(msg: str, rank: int | None = None, **kw) -> None:
    log(WARNING, msg, rank, **kw)


def error(msg: str, rank: int | None = None, **kw) -> None:
    log(ERROR, msg, rank, **kw)


def fatal(msg: str, rank: int | None = None, **kw) -> None:
    log(FATAL, msg, rank, **kw)
