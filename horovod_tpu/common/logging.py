"""Leveled, rank-prefixed logging.

Capability parity with the reference's C++ logger
(``horovod/common/logging.h:10-56``): levels TRACE/DEBUG/INFO/WARNING/
ERROR/FATAL selected by ``HOROVOD_LOG_LEVEL``, optional timestamp
suppression via ``HOROVOD_LOG_HIDE_TIME``.
"""

from __future__ import annotations

import os
import sys
import threading
import time

TRACE, DEBUG, INFO, WARNING, ERROR, FATAL = 0, 1, 2, 3, 4, 5

_LEVEL_NAMES = {
    "trace": TRACE,
    "debug": DEBUG,
    "info": INFO,
    "warning": WARNING,
    "error": ERROR,
    "fatal": FATAL,
}
_LEVEL_TAGS = {TRACE: "T", DEBUG: "D", INFO: "I", WARNING: "W", ERROR: "E", FATAL: "F"}

_lock = threading.Lock()


def _min_level() -> int:
    return _LEVEL_NAMES.get(os.environ.get("HOROVOD_LOG_LEVEL", "warning").lower(), WARNING)


def _hide_time() -> bool:
    return os.environ.get("HOROVOD_LOG_HIDE_TIME", "0") in ("1", "true", "True")


def log(level: int, msg: str, rank: int | None = None) -> None:
    if level < _min_level():
        return
    parts = ["[", _LEVEL_TAGS[level], "]"]
    if not _hide_time():
        t = time.time()
        stamp = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(t))
        parts.insert(0, "%s.%06d " % (stamp, int((t % 1) * 1e6)))
    if rank is None:
        rank = int(os.environ.get("HOROVOD_RANK", os.environ.get("HOROVOD_TPU_RANK", -1)))
    if rank >= 0:
        parts.append("[%d]" % rank)
    parts.append(": ")
    parts.append(msg)
    line = "".join(parts)
    with _lock:
        print(line, file=sys.stderr, flush=True)
    if level == FATAL:
        raise SystemExit(line)


def trace(msg: str, rank: int | None = None) -> None:
    log(TRACE, msg, rank)


def debug(msg: str, rank: int | None = None) -> None:
    log(DEBUG, msg, rank)


def info(msg: str, rank: int | None = None) -> None:
    log(INFO, msg, rank)


def warning(msg: str, rank: int | None = None) -> None:
    log(WARNING, msg, rank)


def error(msg: str, rank: int | None = None) -> None:
    log(ERROR, msg, rank)


def fatal(msg: str, rank: int | None = None) -> None:
    log(FATAL, msg, rank)
