"""Platform selection and XLA client bootstrap.

The reference selects its transport stack at runtime from env vars
(``HOROVOD_CONTROLLER``/``HOROVOD_CPU_OPERATIONS``, see
reference ``horovod/common/utils/env_parser.cc:41-109``).  On TPU the
"transport" is the XLA runtime itself, so the analogous choice is which
PJRT platform backs the process (``tpu`` in production, ``cpu`` with a
forced device count for tests) and whether cross-process CPU collectives
are enabled (gloo — the same library the reference uses for its CPU data
plane, ``horovod/common/ops/gloo_operations.cc``).

This must run BEFORE any JAX backend is initialized.
"""

from __future__ import annotations

import os

_configured = False

# XLA-side half of the overlap engine (docs/overlap.md): the bucketed
# ppermute schedule only hides communication when the TPU compiler may
# (a) run collective-permutes asynchronously and (b) re-order compute
# under the in-flight transfers (the latency-hiding scheduler).  Both
# are libtpu flags and must be in the environment before PJRT init.
_OVERLAP_LIBTPU_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_permute=true",
)


def _enable_overlap_xla_flags() -> None:
    """Append the overlap engine's libtpu flags to LIBTPU_INIT_ARGS,
    never overriding a flag the operator already pinned."""
    existing = os.environ.get("LIBTPU_INIT_ARGS", "")
    added = [f for f in _OVERLAP_LIBTPU_FLAGS
             if f.split("=", 1)[0] not in existing]
    if added:
        os.environ["LIBTPU_INIT_ARGS"] = " ".join(
            filter(None, [existing] + added))


def ensure_platform() -> None:
    """Apply HOROVOD_PLATFORM / CPU-collective config before backend init.

    Idempotent.  Called from :func:`horovod_tpu.init` and from test
    conftest.  ``HOROVOD_PLATFORM=cpu`` forces the host platform (used by
    the launcher for CPU-only test jobs, the way the reference CI runs
    ``horovodrun -np 2 pytest`` on localhost,
    reference ``.buildkite/gen-pipeline.sh:210``).
    """
    global _configured
    if _configured:
        return
    _configured = True

    from horovod_tpu.common import config as _config

    if _config.get("overlap"):
        _enable_overlap_xla_flags()

    platform = str(_config.get("platform") or "")
    import jax

    if platform:
        # Late config.update is required: plugin site hooks may have
        # already overridden jax_platforms at interpreter start.
        jax.config.update("jax_platforms", platform)
    effective = jax.config.jax_platforms or ""
    if platform == "cpu" or effective == "cpu":
        # Cross-process CPU collectives ride gloo, mirroring the
        # reference's gloo CPU data plane.  Only in a multi-process
        # launch: recent jaxlib gloo bindings require the
        # jax.distributed client at backend init, so a single-process
        # run (forced-device-count tests) must stay on the default
        # in-process collectives.
        multiproc = (_config.get("coordinator_addr")
                     or int(os.environ.get("HOROVOD_SIZE", "1") or 1) > 1)
        if multiproc:
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except Exception:  # older jaxlib without gloo support
                pass


def platform_name() -> str:
    import jax

    return jax.devices()[0].platform
