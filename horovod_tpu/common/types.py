"""Status / error types and dtype tables.

Parity with the reference's ``horovod/common/common.h``:
``Status`` kinds (``common.h:122-136``), the error taxonomy surfaced to
users (duplicate names ``common.h:161``, crashed-rank semantics
``common.h:154-159``), and the supported dtype table.  On TPU, dtypes
map to JAX/XLA dtypes rather than framework enums; bfloat16 is
first-class (the MXU's native accumulation format) where the reference
special-cases IEEE fp16 (``horovod/common/half.h``).
"""

from __future__ import annotations

import enum

import jax.numpy as jnp
import numpy as np


class StatusType(enum.Enum):
    OK = 0
    UNKNOWN_ERROR = 1
    PRECONDITION_ERROR = 2
    ABORTED = 3
    INVALID_ARGUMENT = 4
    IN_PROGRESS = 5


class Status:
    """Result of an enqueued operation (reference ``common.h:122-152``).

    ``exc_class`` optionally names the exception type a waiting user
    thread should raise (e.g. :class:`RanksDownError` after a
    coordinated abort) so failure causes stay diagnosable through the
    handle layer instead of collapsing into a generic error."""

    __slots__ = ("type", "reason", "exc_class")

    def __init__(self, type_: StatusType = StatusType.OK, reason: str = "",
                 exc_class: type | None = None):
        self.type = type_
        self.reason = reason
        self.exc_class = exc_class

    @staticmethod
    def ok() -> "Status":
        return Status(StatusType.OK)

    @staticmethod
    def unknown(msg: str) -> "Status":
        return Status(StatusType.UNKNOWN_ERROR, msg)

    @staticmethod
    def precondition(msg: str, exc_class: type | None = None) -> "Status":
        return Status(StatusType.PRECONDITION_ERROR, msg, exc_class)

    @staticmethod
    def aborted(msg: str, exc_class: type | None = None) -> "Status":
        return Status(StatusType.ABORTED, msg, exc_class)

    @staticmethod
    def invalid_argument(msg: str) -> "Status":
        return Status(StatusType.INVALID_ARGUMENT, msg)

    @staticmethod
    def in_progress() -> "Status":
        return Status(StatusType.IN_PROGRESS)

    def ok_p(self) -> bool:
        return self.type == StatusType.OK

    def in_progress_p(self) -> bool:
        return self.type == StatusType.IN_PROGRESS

    def __repr__(self) -> str:
        return f"Status({self.type.name}, {self.reason!r})"


class HorovodTpuError(RuntimeError):
    """Base error surfaced to user threads."""


class HorovodInternalError(HorovodTpuError):
    """Collective failed after enqueue (analog of the reference's
    exception raised from ``synchronize``)."""


class TensorShapeMismatchError(HorovodTpuError):
    """Coordinator-validated mismatch: same tensor name submitted with
    different shapes on different ranks (reference ``controller.cc:477-533``)."""


class DuplicateNameError(HorovodTpuError):
    """Same tensor name submitted twice before completion
    (reference ``common.h:161``, ``tensor_queue.cc``)."""


class StalledError(HorovodTpuError):
    """Stall inspector escalation (reference ``stall_inspector.h:74-80``)."""


class RanksDownError(HorovodTpuError):
    """One or more peer ranks stopped heartbeating and the job was
    coordinately aborted (the crashed-rank semantics the reference
    documents at ``common.h:154-159``, made prompt: survivors fail
    within ``HOROVOD_HEARTBEAT_TIMEOUT_SECONDS`` instead of hanging in
    a wire timeout).  Carries which ranks died, the negotiation round
    the abort fired in, and how long the heartbeats had been stale.

    Abort messages open with ``WIRE_PREFIX`` followed by a JSON header
    (``{"ranks": [...], "round": r, "elapsed": s, ...}``); when the
    structured fields aren't passed explicitly — the exception is
    often rebuilt from just the message after riding a wire Response
    or a handle Status — they are rehydrated from that header."""

    WIRE_PREFIX = "RanksDownError:"

    def __init__(self, msg: str, ranks: tuple = (), round: int = -1,
                 elapsed: float = 0.0):
        super().__init__(msg)
        if not ranks and msg.startswith(self.WIRE_PREFIX):
            try:
                import json

                blob = msg[len(self.WIRE_PREFIX):].strip()
                meta = json.loads(blob[:blob.index("}") + 1])
                ranks = tuple(meta.get("ranks", ()))
                round = int(meta.get("round", round))
                elapsed = float(meta.get("elapsed", elapsed))
            except (ValueError, TypeError):
                pass
        self.ranks = tuple(ranks)
        self.round = round
        self.elapsed = elapsed


class JoinedRankError(HorovodTpuError):
    """Operation submitted after this rank joined."""


# Supported wire dtypes (reference Request dtype field, message.h:47-100).
SUPPORTED_DTYPES = (
    jnp.uint8,
    jnp.int8,
    jnp.uint16,
    jnp.int16,
    jnp.int32,
    jnp.int64,
    jnp.float16,
    jnp.bfloat16,
    jnp.float32,
    jnp.float64,
    jnp.bool_,
)

_DTYPE_CODES = {np.dtype(d): i for i, d in enumerate(SUPPORTED_DTYPES)}
_CODE_DTYPES = {i: np.dtype(d) for i, d in enumerate(SUPPORTED_DTYPES)}


def dtype_code(dtype) -> int:
    """Stable small-int code for a dtype (wire format for negotiation)."""
    d = np.dtype(dtype)
    if d not in _DTYPE_CODES:
        raise HorovodTpuError(f"Unsupported dtype for collective: {dtype}")
    return _DTYPE_CODES[d]


def dtype_from_code(code: int):
    return _CODE_DTYPES[code]
