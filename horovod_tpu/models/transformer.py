"""Flagship model: decoder-only transformer LM with composable
dp / tp / sp / pp / ep parallelism, written TPU-first.

The reference ships CNN benchmark models driven by DP alone
(``examples/tensorflow2_synthetic_benchmark.py``); this model is the
framework's demonstration that every SURVEY §2.7 strategy composes in
one train step:

  * **dp** — batch sharded; gradients psum over ``dp`` (the Horovod
    core capability, here traced into the step).
  * **tp** — Megatron-style: QKV/MLP-in column-parallel, proj/MLP-out
    row-parallel with one psum per block over ``tp``.
  * **sp** — sequence sharded; ring attention over ``sp``
    (:mod:`horovod_tpu.parallel.ring_attention`).
  * **pp** — layer stack split into stages, GPipe microbatching
    (:mod:`horovod_tpu.parallel.pipeline`) when the ``pp`` axis > 1.
  * **ep** — optional Switch-MoE MLP with experts sharded over the
    ``dp`` axis (:mod:`horovod_tpu.parallel.moe`).

Everything is bf16 matmuls with fp32 accumulation/norms — MXU-native.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.moe import moe_layer
from horovod_tpu.parallel.pipeline import gpipe, interleaved_pipeline
from horovod_tpu.parallel.ring_attention import ring_attention
from horovod_tpu.parallel.sharding import (copy_to_tp, grad_reduce_axes,
                                           reduce_from_tp,
                                           tree_map_with_specs)


@dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    d_model: int = 512
    n_heads: int = 8
    head_dim: int = 64
    n_layers: int = 8
    d_ff: int = 2048
    max_seq: int = 2048
    dtype: str = "bfloat16"
    # attention block-step impl: None = auto (pallas on TPU, xla
    # elsewhere); "xla" | "pallas" to force
    attn_impl: str | None = None
    # MoE (ep over the dp axis); 0 disables
    moe_every: int = 0
    experts_per_rank: int = 2
    pp_microbatches: int = 2  # microbatches per pipeline stage when pp>1
    # pipeline schedule when pp>1: "gpipe" (fill-drain) or "interleaved"
    # (Megatron virtual stages, pp_virtual chunks per rank — bubble
    # shrinks ~pp_virtual-fold; layer storage is round-robin permuted by
    # shard_params so each rank's contiguous pp shard holds its chunks)
    pp_schedule: str = "gpipe"
    pp_virtual: int = 1
    # rematerialize each pipeline stage in backward (jax.checkpoint):
    # activation memory stops scaling with stage internals, at one
    # extra forward per stage
    pp_remat: bool = False

    def __post_init__(self):
        if self.pp_schedule not in ("gpipe", "interleaved"):
            raise ValueError(
                f"pp_schedule must be 'gpipe' or 'interleaved', got "
                f"{self.pp_schedule!r}")
        if self.pp_schedule == "gpipe" and self.pp_virtual != 1:
            raise ValueError(
                "pp_virtual > 1 requires pp_schedule='interleaved'")
        if self.pp_virtual < 1:
            raise ValueError(f"pp_virtual must be >= 1: {self.pp_virtual}")

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


def init_params(rng: np.random.RandomState, cfg: TransformerConfig,
                ep: int = 1) -> dict:
    """Full (unsharded) parameter pytree; shard_map in_specs split the
    tp/pp dimensions at dispatch."""
    dm, hd, nh, ff, nl = (cfg.d_model, cfg.head_dim, cfg.n_heads,
                          cfg.d_ff, cfg.n_layers)

    def norm(*shape, scale):
        return (rng.randn(*shape) * scale).astype(np.float32)

    p = {
        "embed": norm(cfg.vocab, dm, scale=0.02),
        "pos": norm(cfg.max_seq, dm, scale=0.02),
        "ln_f": np.ones(dm, np.float32),
        "layers": {
            "wqkv": norm(nl, dm, 3 * nh * hd, scale=dm ** -0.5),
            "wo": norm(nl, nh * hd, dm, scale=(nh * hd) ** -0.5),
            "w1": norm(nl, dm, ff, scale=dm ** -0.5),
            "w2": norm(nl, ff, dm, scale=ff ** -0.5),
            "ln1": np.ones((nl, dm), np.float32),
            "ln2": np.ones((nl, dm), np.float32),
        },
    }
    if cfg.moe_every:
        n_moe = sum(1 for i in range(nl) if (i + 1) % cfg.moe_every == 0)
        e = ep * cfg.experts_per_rank
        p["moe"] = {
            "router": norm(n_moe, dm, e, scale=dm ** -0.5),
            "w_in": norm(n_moe, e, dm, ff, scale=dm ** -0.5),
            "w_out": norm(n_moe, e, ff, dm, scale=ff ** -0.5),
        }
    return jax.tree_util.tree_map(jnp.asarray, p)


def param_specs(cfg: TransformerConfig):
    """PartitionSpecs for shard_map in_specs: tp shards the
    column/row-parallel matrices; MoE experts shard over dp (=ep)."""
    from jax.sharding import PartitionSpec as P

    specs = {
        "embed": P(),
        "pos": P(),
        "ln_f": P(),
        # layer stacks shard over pp (each stage holds only its layers)
        # and tp (column/row parallel matrices)
        "layers": {
            "wqkv": P("pp", None, "tp"),
            "wo": P("pp", "tp", None),
            "w1": P("pp", None, "tp"),
            "w2": P("pp", "tp", None),
            "ln1": P("pp"),
            "ln2": P("pp"),
        },
    }
    if cfg.moe_every:
        specs["moe"] = {
            "router": P(),
            "w_in": P(None, "dp"),
            "w_out": P(None, "dp"),
        }
    return specs


def _rmsnorm(x, g):
    x32 = x.astype(jnp.float32)
    rms = jnp.sqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return ((x32 / rms) * g).astype(x.dtype)


def _block(cfg: TransformerConfig, lp, x, moe_params=None):
    """One transformer block, per-device view.  x: (b, lc, dm)."""
    b, lc, dm = x.shape
    cd = cfg.compute_dtype
    tp = lax.axis_size("tp")
    nh_local = cfg.n_heads // tp

    h = _rmsnorm(x, lp["ln1"])
    h = copy_to_tp(h, "tp")  # Megatron "f": bwd sums shard contributions
    qkv = (h.astype(cd) @ lp["wqkv"].astype(cd))
    qkv = qkv.reshape(b, lc, 3, nh_local, cfg.head_dim)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    attn = ring_attention(q, k, v, "sp", causal=True, impl=cfg.attn_impl)
    attn = attn.reshape(b, lc, nh_local * cfg.head_dim)
    proj = (attn.astype(cd) @ lp["wo"].astype(cd)).astype(jnp.float32)
    proj = reduce_from_tp(proj, "tp")  # Megatron "g": row-parallel reduce
    x = x + proj.astype(x.dtype)

    h = _rmsnorm(x, lp["ln2"])
    if moe_params is not None:
        tokens = h.reshape(b * lc, dm)
        out, aux = moe_layer(tokens, moe_params["router"],
                             moe_params["w_in"], moe_params["w_out"],
                             axis_name="dp")
        mlp = out.reshape(b, lc, dm).astype(jnp.float32)
    else:
        h = copy_to_tp(h, "tp")
        ff = jax.nn.gelu((h.astype(cd) @ lp["w1"].astype(cd))
                         .astype(jnp.float32)).astype(cd)
        mlp = (ff @ lp["w2"].astype(cd)).astype(jnp.float32)
        mlp = reduce_from_tp(mlp, "tp")
        aux = jnp.float32(0.0)
    x = x + mlp.astype(x.dtype)
    return x, aux


def forward(params, tokens, cfg: TransformerConfig):
    """Per-device forward inside shard_map over ('dp','pp','tp','sp').

    tokens: (b_local, lc_local) int32.  Returns (logits fp32
    (b, lc, vocab), aux_loss).
    """
    cd = cfg.compute_dtype
    sp_idx = lax.axis_index("sp")
    nstages = lax.axis_size("pp")
    b, lc = tokens.shape
    pos = sp_idx * lc + jnp.arange(lc)
    x = (params["embed"][tokens] + params["pos"][pos]).astype(cd)

    layers = params["layers"]
    moe = params.get("moe")
    local_layers = layers["ln1"].shape[0]  # n_layers / pp per stage

    if nstages == 1:
        aux = jnp.float32(0.0)
        for i in range(local_layers):
            lp = jax.tree_util.tree_map(lambda a: a[i], layers)
            mp = None
            if moe is not None and (i + 1) % cfg.moe_every == 0:
                idx = sum(1 for j in range(i + 1)
                          if (j + 1) % cfg.moe_every == 0) - 1
                mp = jax.tree_util.tree_map(lambda a: a[idx], moe)
            x, a = _block(cfg, lp, x, mp)
            aux = aux + a
    else:
        if moe is not None:
            raise NotImplementedError(
                "MoE layers under pipeline parallelism are not supported "
                "yet; use moe_every=0 when pp > 1.")

        m = cfg.pp_microbatches
        micro = x.reshape(m, b // m, lc, cfg.d_model)
        if cfg.pp_schedule == "interleaved":
            V = cfg.pp_virtual
            per = local_layers // V
            # this rank's contiguous shard holds its V chunks in slot
            # order (shard_params applied interleave_layer_order)
            stacks = jax.tree_util.tree_map(
                lambda a: a.reshape((V, per) + a.shape[1:]), layers)

            def chunk_fn(cp, h):
                def one(j, hh):
                    lp = jax.tree_util.tree_map(lambda a: a[j], cp)
                    hh, _ = _block(cfg, lp, hh)
                    return hh

                return lax.fori_loop(0, per, one, h)

            x = interleaved_pipeline(chunk_fn, stacks, micro, V, "pp",
                                     remat=cfg.pp_remat)
        else:
            def stage_fn(_, h):
                def one(j, hh):
                    lp = jax.tree_util.tree_map(lambda a: a[j], layers)
                    hh, _ = _block(cfg, lp, hh)
                    return hh

                return lax.fori_loop(0, local_layers, one, h)

            x = gpipe(stage_fn, None, micro, "pp", remat=cfg.pp_remat)
        x = x.reshape(b, lc, cfg.d_model)
        aux = jnp.float32(0.0)

    x = _rmsnorm(x, params["ln_f"])
    logits = (x.astype(cd) @ params["embed"].astype(cd).T).astype(jnp.float32)
    return logits, aux


def loss_fn(params, tokens, targets, cfg: TransformerConfig):
    """LOCAL slice of the global-mean cross entropy.

    Deliberately psum-free: local token-loss sum divided by the GLOBAL
    token count (a static number), so that one explicit psum of the
    gradients reconstructs exactly the global-mean gradient.  Putting a
    psum inside the differentiated loss would double-count — psum
    transposes to psum, inflating gradients by the data-axis size.
    Report the global loss by psumming this value outside the grad.
    """
    logits, aux = forward(params, tokens, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    data_ranks = lax.axis_size("dp") * lax.axis_size("sp")
    global_tokens = jnp.float32(nll.size) * data_ranks
    return jnp.sum(nll) / global_tokens + 0.01 * aux / data_ranks


def make_train_step(cfg: TransformerConfig, mesh, optimizer,
                    steps_per_dispatch: int = 1):
    """Build the jitted SPMD train step over a ('dp','pp','tp','sp')
    mesh.

    ``steps_per_dispatch > 1`` chains that many optimizer steps on the
    same batch inside one compiled program (``lax.scan``), returning the
    last loss — for synthetic benchmarking over host-mediated PJRT
    tunnels, where each dispatch pays a host round-trip (cf. the
    reference's fixed-batch synthetic bench,
    ``examples/tensorflow2_synthetic_benchmark.py:119-132``).

    shard_map covers loss+grad (where the collectives live); the optax
    update runs outside it under the same jit, so XLA propagates the
    parameter shardings through the elementwise optimizer math — the
    "weight update sharding" pattern (cf. PAPERS.md, automatic
    cross-replica weight-update sharding).

    Returns step_fn(params, opt_state, tokens, targets) ->
    (params, opt_state, loss_scalar).  params/opt_state must be placed
    with :func:`shard_params` before the first call.
    """
    import optax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    pspecs = param_specs(cfg)
    data_spec = P("dp", "sp")

    def per_device_grads(params, tokens, targets):
        local_loss, grads = jax.value_and_grad(loss_fn)(params, tokens,
                                                        targets, cfg)
        # Reduce each gradient over the data axes it is replicated on —
        # the Horovod allreduce traced into the step.  Params sharded on
        # a data axis (MoE experts over dp) keep their shard-local
        # gradient on that axis; tp/pp shards stay local.  loss_fn is
        # local/psum-free, so this is the only cross-rank reduction of
        # the backward pass.
        def reduce(g, spec):
            axes = grad_reduce_axes(spec)
            return lax.psum(g, axes) if axes else g

        grads = tree_map_with_specs(reduce, grads, pspecs)
        loss = lax.psum(local_loss, ("dp", "sp"))
        return grads, loss.reshape(1)

    grad_fn = shard_map(per_device_grads, mesh=mesh, check_vma=False,
                        in_specs=(pspecs, data_spec, data_spec),
                        out_specs=(pspecs, P()))

    # Donating params/opt_state lets XLA update weights in place
    # instead of allocating fresh buffers every step (same move as the
    # bench ResNet step, +~2% measured there); callers follow the
    # params, opt_state, loss = step(params, opt_state, ...) reassign
    # pattern, so the invalidated buffers are never re-read.
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens, targets):
        def one(carry, _):
            p, s = carry
            grads, loss = grad_fn(p, tokens, targets)
            updates, s = optimizer.update(grads, s, p)
            p = optax.apply_updates(p, updates)
            return (p, s), loss[0]

        if steps_per_dispatch <= 1:
            (params, opt_state), loss = one((params, opt_state), None)
            return params, opt_state, loss
        (params, opt_state), losses = jax.lax.scan(
            one, (params, opt_state), None, length=steps_per_dispatch)
        return params, opt_state, losses[-1]

    return step


def interleave_layer_order(n_layers: int, pp: int, n_virtual: int):
    """Storage permutation for the interleaved pipeline: rank p's
    contiguous pp shard must hold global chunks p, p+pp, ... in slot
    order (each chunk = n_layers/(pp*n_virtual) consecutive layers)."""
    D = pp * n_virtual
    if n_layers % D:
        raise ValueError(f"n_layers {n_layers} not divisible by "
                         f"{pp} stages x {n_virtual} virtual chunks")
    per = n_layers // D
    order = []
    for p in range(pp):
        for v in range(n_virtual):
            c = v * pp + p
            order.extend(range(c * per, (c + 1) * per))
    return np.asarray(order)


def shard_params(params, cfg: TransformerConfig, mesh):
    """Place a full parameter pytree onto the mesh with the model's
    shardings (tp/pp split, everything else replicated).

    With ``pp_schedule="interleaved"`` the layer stacks are round-robin
    permuted first (`interleave_layer_order`) so each pp shard carries
    its non-adjacent chunks; checkpoints of such runs store the permuted
    order and must be reloaded under the same pp/pp_virtual config (true
    of pp-sharded layouts in general)."""
    from jax.sharding import NamedSharding

    pp = mesh.shape.get("pp", 1)
    if cfg.pp_schedule == "interleaved" and pp > 1:
        order = interleave_layer_order(cfg.n_layers, pp, cfg.pp_virtual)
        params = dict(params)
        params["layers"] = jax.tree_util.tree_map(
            lambda a: a[jnp.asarray(order)], params["layers"])
    specs = param_specs(cfg)
    return jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, specs)
