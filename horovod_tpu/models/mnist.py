"""Small MNIST CNN — the reference's smoke-test model
(``examples/tensorflow2_mnist.py``, ``examples/pytorch_mnist.py``): two
convs + two dense layers, used by examples and integration tests.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class SmallCNN(nn.Module):
    """3-conv benchmark smoke model — the analog of the reference's
    ``SmallCNN`` in ``examples/tensorflow2_synthetic_benchmark.py``
    (a CPU-friendly stand-in for ResNet in the synthetic benchmark).
    Same interface as the ConvNet zoo: ``dtype`` compute, ``train``
    kwarg, BatchNorm stats under ``batch_stats``."""

    num_classes: int = 1000
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        for feat in (16, 32, 64):
            x = nn.Conv(feat, (3, 3), strides=(2, 2), use_bias=False,
                        dtype=self.dtype)(x)
            x = nn.BatchNorm(use_running_average=not train,
                             dtype=self.dtype)(x)
            x = nn.relu(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


class MnistCNN(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes)(x)
        return x
