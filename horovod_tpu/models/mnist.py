"""Small MNIST CNN — the reference's smoke-test model
(``examples/tensorflow2_mnist.py``, ``examples/pytorch_mnist.py``): two
convs + two dense layers, used by examples and integration tests.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes)(x)
        return x
