"""VGG in Flax — the reference's third headline benchmark model
(``docs/benchmarks.rst:13`` quotes VGG-16 at 68% scaling on 512 GPUs;
its dense 138M-parameter gradient is the classic allreduce stress
test).  bf16 compute / fp32 params, NHWC.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# stage configs: number of 3x3 convs per block, doubling widths
_CFG = {
    11: (1, 1, 2, 2, 2),
    13: (2, 2, 2, 2, 2),
    16: (2, 2, 3, 3, 3),
    19: (2, 2, 4, 4, 4),
}


class VGG(nn.Module):
    depth: int = 16
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    widths: Sequence[int] = (64, 128, 256, 512, 512)

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, kernel_size=(3, 3), dtype=self.dtype,
                       param_dtype=jnp.float32)
        x = x.astype(self.dtype)
        for stage, n_convs in enumerate(_CFG[self.depth]):
            for i in range(n_convs):
                x = nn.relu(conv(self.widths[stage],
                                 name=f"conv{stage}_{i}")(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype,
                             param_dtype=jnp.float32)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype,
                             param_dtype=jnp.float32)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32)(x)


VGG11 = partial(VGG, depth=11)
VGG13 = partial(VGG, depth=13)
VGG16 = partial(VGG, depth=16)
VGG19 = partial(VGG, depth=19)
