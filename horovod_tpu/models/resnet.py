"""ResNet-50 (v1.5) in Flax — the reference's headline benchmark model
(``examples/tensorflow2_synthetic_benchmark.py`` uses
``applications.ResNet50``; ``docs/benchmarks.rst`` quotes ResNet-101
scaling).  bf16 compute / fp32 params+stats, NHWC (TPU-native conv
layout).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic block (ResNet-18/34)."""
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """Bottleneck block (ResNet-50/101/152), v1.5: stride on the 3x3."""
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        # BN in the compute dtype with fp32 params/stats (param_dtype
        # default): bf16 activations stay bf16 through normalization
        # instead of round-tripping to fp32 at every BN, which costs
        # ~2x HBM bandwidth on the layer.
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        act = nn.relu

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2),
                 padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i,
                                   conv=conv, norm=norm, act=act,
                                   strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=ResNetBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)
