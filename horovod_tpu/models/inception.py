"""Inception V3 in Flax — the reference's headline 90%-scaling model
(``README.rst:73-79``, ``docs/benchmarks.rst:11-13``).  Standard
Szegedy et al. 2015 topology (mixed 5b-7c), bf16 compute / fp32
params+stats, NHWC; the final pool is a spatial mean so any input
>= 75 px works (canonical size 299).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ConvBN(nn.Module):
    features: int
    kernel: tuple
    strides: tuple = (1, 1)
    padding: str | tuple = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.features, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype, param_dtype=jnp.float32)(x)
        # BN in compute dtype, fp32 params/stats: keeps bf16
        # activations bf16 through normalization (no fp32 round-trip)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype)(x)
        return nn.relu(x)


def _avgpool3(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class MixedA(nn.Module):           # mixed 5b/5c/5d
    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        cbn = partial(ConvBN, dtype=self.dtype)
        b1 = cbn(64, (1, 1))(x, train)
        b5 = cbn(48, (1, 1))(x, train)
        b5 = cbn(64, (5, 5))(b5, train)
        b3 = cbn(64, (1, 1))(x, train)
        b3 = cbn(96, (3, 3))(b3, train)
        b3 = cbn(96, (3, 3))(b3, train)
        bp = cbn(self.pool_features, (1, 1))(_avgpool3(x), train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class ReductionA(nn.Module):       # mixed 6a
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        cbn = partial(ConvBN, dtype=self.dtype)
        b3 = cbn(384, (3, 3), (2, 2), "VALID")(x, train)
        bd = cbn(64, (1, 1))(x, train)
        bd = cbn(96, (3, 3))(bd, train)
        bd = cbn(96, (3, 3), (2, 2), "VALID")(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class MixedB(nn.Module):           # mixed 6b-6e (factorized 7x7)
    channels_7x7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        cbn = partial(ConvBN, dtype=self.dtype)
        c = self.channels_7x7
        b1 = cbn(192, (1, 1))(x, train)
        b7 = cbn(c, (1, 1))(x, train)
        b7 = cbn(c, (1, 7))(b7, train)
        b7 = cbn(192, (7, 1))(b7, train)
        bd = cbn(c, (1, 1))(x, train)
        bd = cbn(c, (7, 1))(bd, train)
        bd = cbn(c, (1, 7))(bd, train)
        bd = cbn(c, (7, 1))(bd, train)
        bd = cbn(192, (1, 7))(bd, train)
        bp = cbn(192, (1, 1))(_avgpool3(x), train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class ReductionB(nn.Module):       # mixed 7a
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        cbn = partial(ConvBN, dtype=self.dtype)
        b3 = cbn(192, (1, 1))(x, train)
        b3 = cbn(320, (3, 3), (2, 2), "VALID")(b3, train)
        b7 = cbn(192, (1, 1))(x, train)
        b7 = cbn(192, (1, 7))(b7, train)
        b7 = cbn(192, (7, 1))(b7, train)
        b7 = cbn(192, (3, 3), (2, 2), "VALID")(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class MixedC(nn.Module):           # mixed 7b/7c (expanded filter bank)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        cbn = partial(ConvBN, dtype=self.dtype)
        b1 = cbn(320, (1, 1))(x, train)
        b3 = cbn(384, (1, 1))(x, train)
        b3 = jnp.concatenate([cbn(384, (1, 3))(b3, train),
                              cbn(384, (3, 1))(b3, train)], axis=-1)
        bd = cbn(448, (1, 1))(x, train)
        bd = cbn(384, (3, 3))(bd, train)
        bd = jnp.concatenate([cbn(384, (1, 3))(bd, train),
                              cbn(384, (3, 1))(bd, train)], axis=-1)
        bp = cbn(192, (1, 1))(_avgpool3(x), train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = cbn(32, (3, 3), (2, 2), "VALID")(x, train)
        x = cbn(32, (3, 3), padding="VALID")(x, train)
        x = cbn(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = cbn(80, (1, 1), padding="VALID")(x, train)
        x = cbn(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = MixedA(32, self.dtype)(x, train)
        x = MixedA(64, self.dtype)(x, train)
        x = MixedA(64, self.dtype)(x, train)
        x = ReductionA(self.dtype)(x, train)
        x = MixedB(128, self.dtype)(x, train)
        x = MixedB(160, self.dtype)(x, train)
        x = MixedB(160, self.dtype)(x, train)
        x = MixedB(192, self.dtype)(x, train)
        x = ReductionB(self.dtype)(x, train)
        x = MixedC(self.dtype)(x, train)
        x = MixedC(self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32)(x)
