"""Pallas-fused optimizer tail (``HOROVOD_FUSED_UPDATE=1``).

The post-reduction weight-update chain — unscale by world size, dtype
cast, momentum / Adam moment update, bias correction, step scaling —
lowers as a string of small elementwise XLA ops, each one a full HBM
round trip over every flat gradient buffer.  arXiv:2004.13336 showed
the fused weight-update path is the lever that dominates at scale;
this module collapses that chain into **one Pallas kernel per flat
per-dtype buffer** (the :mod:`horovod_tpu.ops.quantization` idiom:
fused TPU kernel, bit-identical jnp fallback off-TPU, interpret-mode
test hook via ``HOROVOD_QUANT_PALLAS=1``).

**Bit-exactness contract.** The fused math mirrors optax's update
expressions verbatim (``optax.sgd`` / ``optax.trace`` /
``optax.scale_by_adam`` + ``scale_by_learning_rate``), so
``HOROVOD_FUSED_UPDATE=1`` is bit-exact against the unfused chain —
the parity matrix in ``tests/test_fused_update.py`` proves it per
dtype-group x optimizer x ZeRO stage x int8-EF cell.  That contract is
only possible when the hyperparameters are knowable, so fusion applies
to optimizers built by :func:`sgd` / :func:`adam` below (plain optax
``GradientTransformation``s are closures — their hyperparameters are
not introspectable).  They ARE the optax optimizers (same init, same
update, same state pytree) plus a :class:`FusedSpec` tag; with the
knob off, or wrapped by ``optax.chain``, they behave identically to
``optax.sgd``/``optax.adam``.  ``HOROVOD_FUSED_UPDATE=1`` with an
untagged optimizer warns once and runs unfused — the knob can never
change results, only fuse them.

The fused tail is the third piece of the update path's kernel story:
the wire side (residual-add into the fused buffer, quant pack/unpack)
is already fused by the PR 1/PR 10 Pallas codecs; this closes the
optimizer side.  Selection is local to each rank (the update runs
after the wire), so no round-0 handshake entry is needed.
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

from horovod_tpu.common import config as _config
from horovod_tpu.common import logging as _log
from horovod_tpu.runtime import metrics as _metrics

# Row tile: (16, 128) covers the native f32 (8, 128) and bf16 (16, 128)
# tilings; flat buffers are padded up to one tile and sliced back.
_ROW_TILE = 16
_LANES = 128

_M_FUSED = _metrics.gauge(
    "hvd_fused_update",
    "1 when the Pallas-fused optimizer tail is active for the "
    "last-constructed DistributedOptimizer, 0 when requested but "
    "unavailable (untagged optimizer / unrecognized state).")

_warned: set = set()


class FusedSpec(NamedTuple):
    """Hyperparameters of a fusable update, attached to the optimizer
    at construction (kind: ``sgd`` | ``momentum`` | ``adam``)."""
    kind: str
    lr: float
    momentum: float = 0.0
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    eps_root: float = 0.0


class FusableTransformation(NamedTuple):
    """An optax ``GradientTransformation`` (same ``init``/``update``
    fields, duck-type compatible everywhere) carrying the
    :class:`FusedSpec` the fused tail needs.  A separate NamedTuple
    because optax's has ``__slots__`` — attributes cannot be attached
    to it after the fact."""
    init: Callable
    update: Callable
    fused_spec: FusedSpec


def sgd(learning_rate: float, momentum: float | None = None
        ) -> FusableTransformation:
    """``optax.sgd`` tagged for the fused tail (momentum ``None``/0
    means plain SGD; schedules are not fusable — pass a float)."""
    import optax

    _require_float("learning_rate", learning_rate)
    if momentum is not None:
        _require_float("momentum", momentum)
    inner = optax.sgd(learning_rate, momentum=momentum)
    # optax adds the trace transform for ANY non-None momentum —
    # including 0.0 — so the spec kind must follow the same rule or the
    # state layout never matches and fusion silently disables.
    spec = FusedSpec("sgd" if momentum is None else "momentum",
                     float(learning_rate), float(momentum or 0.0))
    return FusableTransformation(inner.init, inner.update, spec)


def adam(learning_rate: float, b1: float = 0.9, b2: float = 0.999,
         eps: float = 1e-8, eps_root: float = 0.0
         ) -> FusableTransformation:
    """``optax.adam`` tagged for the fused tail (float hyperparameters
    only — schedules are not fusable)."""
    import optax

    for name, v in (("learning_rate", learning_rate), ("b1", b1),
                    ("b2", b2), ("eps", eps), ("eps_root", eps_root)):
        _require_float(name, v)
    inner = optax.adam(learning_rate, b1=b1, b2=b2, eps=eps,
                       eps_root=eps_root)
    spec = FusedSpec("adam", float(learning_rate), 0.0, float(b1),
                     float(b2), float(eps), float(eps_root))
    return FusableTransformation(inner.init, inner.update, spec)


def _require_float(name: str, v) -> None:
    if callable(v):
        raise TypeError(
            f"fused_update.{name} must be a float (schedules change "
            "per step and cannot be baked into the fused kernel); use "
            "plain optax for scheduled runs.")


def spec_of(optimizer) -> FusedSpec | None:
    return getattr(optimizer, "fused_spec", None)


def enabled() -> bool:
    return bool(_config.get("fused_update"))


def active() -> bool:
    """Whether the fused tail actually ran for the last-constructed
    optimizer (the ``hvd_fused_update`` gauge): ``enabled()`` records
    the request, this records the outcome — trace-time fallbacks
    (untagged optimizer, unrecognized state layout, non-float group)
    clear it."""
    return bool(_M_FUSED.value())


def _warn_once(category: str, msg: str) -> None:
    if category not in _warned:
        _warned.add(category)
        _log.warning(f"fused-update: {msg}")


def resolve_spec(optimizer) -> FusedSpec | None:
    """The spec the DistributedOptimizer should fuse with, or ``None``
    (knob off, or optimizer untagged — warned once, never fatal: the
    knob can only fuse results, not change them)."""
    if not enabled():
        _M_FUSED.set(0)
        return None
    spec = spec_of(optimizer)
    if spec is None:
        _M_FUSED.set(0)
        _warn_once(
            "untagged",
            "HOROVOD_FUSED_UPDATE=1 but the wrapped optimizer carries "
            "no FusedSpec (its hyperparameters are closure-internal, "
            "so a bit-exact fused kernel cannot be built); construct "
            "it with hvd.fused_update.sgd/adam to fuse. Running the "
            "unfused optax chain.")
        return None
    _M_FUSED.set(1)
    return spec


# ---------------------------------------------------------------------------
# Kernel / fallback selection — the quantization-module contract:
# HOROVOD_QUANT_PALLAS = auto (Pallas on TPU, jnp elsewhere) | 1 (force
# Pallas; interpret mode off-TPU — the bit-identity test hook) | 0.
# ---------------------------------------------------------------------------


def _use_pallas() -> bool:
    mode = str(_config.get("quant_pallas")).strip().lower()
    if mode in ("0", "off", "jnp", "false"):
        return False
    if mode in ("1", "on", "force", "true"):
        return True
    return jax.default_backend() == "tpu"


def _pad2d(flat):
    n = flat.shape[0]
    pad = (-n) % (_ROW_TILE * _LANES)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(-1, _LANES), n


def _unpad(x2d, n: int):
    return x2d.reshape(-1)[:n]


# --- the update math, written once -----------------------------------------
# These expressions mirror optax bit-for-bit (optax.scale ->
# ``(-lr) * g``; optax.trace -> ``g + decay * t``; optax.scale_by_adam
# -> the moment/bias-correction/step lines below).  The Pallas kernels
# and the jnp fallback both call them, so the two paths cannot drift.


def _prep_grad(g, navg: int, dtype):
    # the unfused chain's ``shard = shard / n`` (Average only, wire
    # dtype) followed by ``shard.astype(group_dtype)``
    if navg > 1:
        g = g / navg
    return g.astype(dtype)


def _sgd_math(g, neg_lr: float):
    return neg_lr * g


def _momentum_math(g, t, decay: float, neg_lr: float):
    t2 = g + decay * t
    return neg_lr * t2, t2


def _adam_math(g, mu, nu, bc1, bc2, spec: FusedSpec):
    mu2 = (1 - spec.b1) * g + spec.b1 * mu
    nu2 = (1 - spec.b2) * (g * g) + spec.b2 * nu
    mu_hat = mu2 / bc1.astype(mu2.dtype)
    nu_hat = nu2 / bc2.astype(nu2.dtype)
    u = (-spec.lr) * (mu_hat / (jnp.sqrt(nu_hat + spec.eps_root)
                                + spec.eps))
    return u, mu2, nu2


def _safe_int32_increment(count):
    maxi = jnp.iinfo(jnp.int32).max
    return jnp.where(count < maxi, count + jnp.array(1, jnp.int32),
                     maxi)


@functools.partial(jax.jit, static_argnums=(0, 1), inline=True)
def _bias_correction_pair(b1: float, b2: float, count_inc):
    return 1 - b1 ** count_inc, 1 - b2 ** count_inc


def bias_corrections(spec: FusedSpec, count_inc):
    """(1 - b**t) pair, computed exactly like optax's
    ``tree_bias_correction`` (f32 scalar, cast to the moment dtype at
    the division site inside the kernel).  Jitted like optax's helper
    on purpose: on the eager path XLA's compiled scalar ``pow`` and
    the op-by-op dispatch path can differ in the last ulp, and the
    bit-exactness contract needs both sides to take the compiled
    one."""
    return _bias_correction_pair(spec.b1, spec.b2, count_inc)


# --- Pallas kernels ---------------------------------------------------------


def _sgd_kernel(g_ref, o_ref, *, navg: int, neg_lr: float):
    g = _prep_grad(g_ref[...], navg, o_ref.dtype)
    o_ref[...] = _sgd_math(g, neg_lr)


def _momentum_kernel(g_ref, t_ref, o_ref, t_out_ref, *, navg: int,
                     decay: float, neg_lr: float):
    g = _prep_grad(g_ref[...], navg, t_ref.dtype)
    u, t2 = _momentum_math(g, t_ref[...], decay, neg_lr)
    o_ref[...] = u
    t_out_ref[...] = t2


def _adam_kernel(g_ref, mu_ref, nu_ref, aux_ref, o_ref, mu_out, nu_out,
                 *, navg: int, spec: FusedSpec):
    g = _prep_grad(g_ref[...], navg, mu_ref.dtype)
    bc1 = aux_ref[0, 0]
    bc2 = aux_ref[1, 0]
    u, mu2, nu2 = _adam_math(g, mu_ref[...], nu_ref[...], bc1, bc2,
                             spec)
    o_ref[...] = u
    mu_out[...] = mu2
    nu_out[...] = nu2


def _row_spec(rows):
    from jax.experimental import pallas as pl

    return pl.BlockSpec((_ROW_TILE, _LANES), lambda i: (i, 0))


def _aux_spec():
    from jax.experimental import pallas as pl

    # every grid step reads the same (bc1, bc2) scalar block
    return pl.BlockSpec((_ROW_TILE, _LANES), lambda i: (0, 0))


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def _sgd_pallas(g2d, dtype, navg: int, neg_lr: float, interpret: bool):
    from jax.experimental import pallas as pl

    rows = g2d.shape[0]
    return pl.pallas_call(
        functools.partial(_sgd_kernel, navg=navg, neg_lr=neg_lr),
        grid=(rows // _ROW_TILE,),
        in_specs=[_row_spec(rows)],
        out_specs=_row_spec(rows),
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), dtype),
        interpret=interpret,
    )(g2d)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def _momentum_pallas(g2d, t2d, navg: int, decay: float, neg_lr: float,
                     interpret: bool = False):
    from jax.experimental import pallas as pl

    rows = g2d.shape[0]
    return pl.pallas_call(
        functools.partial(_momentum_kernel, navg=navg, decay=decay,
                          neg_lr=neg_lr),
        grid=(rows // _ROW_TILE,),
        in_specs=[_row_spec(rows)] * 2,
        out_specs=[_row_spec(rows)] * 2,
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), t2d.dtype)] * 2,
        interpret=interpret,
    )(g2d, t2d)


@functools.partial(jax.jit, static_argnums=(4, 5, 6))
def _adam_pallas(g2d, mu2d, nu2d, aux, navg: int, spec: FusedSpec,
                 interpret: bool = False):
    from jax.experimental import pallas as pl

    rows = g2d.shape[0]
    return pl.pallas_call(
        functools.partial(_adam_kernel, navg=navg, spec=spec),
        grid=(rows // _ROW_TILE,),
        in_specs=[_row_spec(rows)] * 3 + [_aux_spec()],
        out_specs=[_row_spec(rows)] * 3,
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), mu2d.dtype)] * 3,
        interpret=interpret,
    )(g2d, mu2d, nu2d, aux)


def _aux_block(bc1, bc2):
    aux = jnp.zeros((_ROW_TILE, _LANES), jnp.float32)
    return aux.at[0, :].set(bc1.astype(jnp.float32)).at[1, :].set(
        bc2.astype(jnp.float32))


# --- per-buffer dispatch ----------------------------------------------------


def _apply_buffer(spec: FusedSpec, g, mu, nu, bc1, bc2, navg: int,
                  dtype):
    """One flat buffer through the fused tail: ``(update, new_mu,
    new_nu)`` (``None`` moments for kinds without them)."""
    dtype = jnp.dtype(dtype)
    if g.size == 0:
        z = jnp.zeros((0,), dtype)
        return z, (z if mu is not None else None), \
            (z if nu is not None else None)
    if _use_pallas():
        interpret = jax.default_backend() != "tpu"
        g2d, n = _pad2d(g.reshape(-1))
        if spec.kind == "sgd":
            o = _sgd_pallas(g2d, dtype, navg, -spec.lr, interpret)
            return _unpad(o, n), None, None
        if spec.kind == "momentum":
            t2d, _ = _pad2d(mu.reshape(-1))
            o, t2 = _momentum_pallas(g2d, t2d, navg, spec.momentum,
                                     -spec.lr, interpret)
            return _unpad(o, n), _unpad(t2, n), None
        mu2d, _ = _pad2d(mu.reshape(-1))
        nu2d, _ = _pad2d(nu.reshape(-1))
        o, m2, v2 = _adam_pallas(g2d, mu2d, nu2d, _aux_block(bc1, bc2),
                                 navg, spec, interpret)
        return _unpad(o, n), _unpad(m2, n), _unpad(v2, n)
    # jnp fallback: the same math, op for op
    g = _prep_grad(g, navg, dtype)
    if spec.kind == "sgd":
        return _sgd_math(g, -spec.lr), None, None
    if spec.kind == "momentum":
        u, t2 = _momentum_math(g, mu, spec.momentum, -spec.lr)
        return u, t2, None
    u, m2, v2 = _adam_math(g, mu, nu, bc1, bc2, spec)
    return u, m2, v2


# --- state structure recognition -------------------------------------------


def _split_state(spec: FusedSpec, inner_state, grads):
    """Match the wrapped optax state against ``grads`` (a list of flat
    buffers or gradient leaves): ``(count, mus, nus, treedef)`` or
    ``None`` when the structure is not the expected optax layout
    (chain(trace?, scale) / chain(scale_by_adam, scale)) — the caller
    then runs the unfused update (fail-open, like the AOT cache's
    fail-closed compile)."""
    leaves, treedef = jax.tree_util.tree_flatten(inner_state)
    k = len(grads)

    # ``grads`` entries only need .shape/.dtype (arrays, tracers, or
    # jax.ShapeDtypeStruct views — the groups path passes structs so no
    # casted copy is ever materialized just for matching)
    def match(sub):
        return len(sub) == k and all(
            tuple(jnp.shape(a)) == tuple(g.shape)
            and jnp.asarray(a).dtype == jnp.dtype(g.dtype)
            for a, g in zip(sub, grads))

    if spec.kind == "sgd":
        if not leaves:
            return None, None, None, treedef
    elif spec.kind == "momentum":
        if match(leaves):
            return None, list(leaves), None, treedef
    elif spec.kind == "adam":
        if len(leaves) == 1 + 2 * k and jnp.shape(leaves[0]) == () \
                and match(leaves[1:1 + k]) and match(leaves[1 + k:]):
            return leaves[0], list(leaves[1:1 + k]), \
                list(leaves[1 + k:]), treedef
    return None


def _rebuild_state(spec: FusedSpec, treedef, count_inc, mus, nus):
    if spec.kind == "sgd":
        leaves = []
    elif spec.kind == "momentum":
        leaves = mus
    else:
        leaves = [count_inc] + mus + nus
    return jax.tree_util.tree_unflatten(treedef, leaves)


# --- the two entry points the DistributedOptimizer calls -------------------


def fused_update_groups(spec: FusedSpec, shards, inner_state,
                        navg: int, dtypes):
    """Fused replacement for ``update_fn(gshards, inner_state)`` on the
    ZeRO (stage >= 1) paths: ``shards`` are the raw post-scatter flat
    buffers (wire dtype, pre-unscale), ``dtypes`` the per-group target
    dtypes, ``navg`` the Average divisor (1 for Sum / already-averaged
    eager shards).  Returns ``(update_shards, new_inner_state)`` or
    ``None`` when a group is non-float or the state layout is
    unrecognized."""
    if not shards or not all(
            jnp.issubdtype(jnp.dtype(d), jnp.floating) for d in dtypes):
        # same guard as the tree path: float update math into an
        # integer dtype group would crash the kernel (or silently
        # drift the unfused chain's integer state dtype to float)
        _M_FUSED.set(0)
        _warn_once(
            "int-group",
            "a non-float dtype group is present; running the unfused "
            "chain")
        return None
    # moments live in the GROUP dtype (the unfused chain casts before
    # update_fn), so match against shape/dtype VIEWS in that dtype —
    # no casted copy is materialized for the comparison
    views = [jax.ShapeDtypeStruct(tuple(jnp.shape(s)), jnp.dtype(d))
             for s, d in zip(shards, dtypes)]
    parts = _split_state(spec, inner_state, views)
    if parts is None:
        _M_FUSED.set(0)
        _warn_once(
            "state",
            f"wrapped {spec.kind} state does not match the expected "
            "optax layout; running the unfused chain")
        return None
    count, mus, nus, treedef = parts
    count_inc = bc1 = bc2 = None
    if spec.kind == "adam":
        count_inc = _safe_int32_increment(count)
        bc1, bc2 = bias_corrections(spec, count_inc)
    outs, new_mus, new_nus = [], [], []
    for i, s in enumerate(shards):
        u, m2, v2 = _apply_buffer(
            spec, jnp.asarray(s),
            mus[i] if mus is not None else None,
            nus[i] if nus is not None else None,
            bc1, bc2, navg, dtypes[i])
        outs.append(u)
        if m2 is not None:
            new_mus.append(m2)
        if v2 is not None:
            new_nus.append(v2)
    return outs, _rebuild_state(spec, treedef, count_inc, new_mus,
                                new_nus)


def fused_update_tree(spec: FusedSpec, grads, inner_state):
    """Fused replacement for the replicated (stage 0) update: one
    kernel per gradient leaf (the leaves ARE the flat buffers there —
    reduction already averaged, so no unscale).  Returns ``(updates,
    new_inner_state)`` or ``None`` when a leaf is non-float or the
    state layout is unrecognized."""
    leaves, gdef = jax.tree_util.tree_flatten(grads)
    leaves = [jnp.asarray(g) for g in leaves]
    if not leaves or not all(
            jnp.issubdtype(g.dtype, jnp.floating) for g in leaves):
        _M_FUSED.set(0)
        _warn_once(
            "int-group",
            "a non-float gradient leaf is present; running the "
            "unfused chain")
        return None
    parts = _split_state(spec, inner_state, leaves)
    if parts is None:
        _M_FUSED.set(0)
        _warn_once(
            "state",
            f"wrapped {spec.kind} state does not match the expected "
            "optax layout; running the unfused chain")
        return None
    count, mus, nus, treedef = parts
    count_inc = bc1 = bc2 = None
    if spec.kind == "adam":
        count_inc = _safe_int32_increment(count)
        bc1, bc2 = bias_corrections(spec, count_inc)
    outs, new_mus, new_nus = [], [], []
    for i, g in enumerate(leaves):
        u, m2, v2 = _apply_buffer(
            spec, g.reshape(-1),
            mus[i].reshape(-1) if mus is not None else None,
            nus[i].reshape(-1) if nus is not None else None,
            bc1, bc2, 1, g.dtype)
        outs.append(u.reshape(g.shape))
        if m2 is not None:
            new_mus.append(m2.reshape(g.shape))
        if v2 is not None:
            new_nus.append(v2.reshape(g.shape))
    return (jax.tree_util.tree_unflatten(gdef, outs),
            _rebuild_state(spec, treedef, count_inc, new_mus, new_nus))
