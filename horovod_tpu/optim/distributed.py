"""DistributedOptimizer / gradient-aggregation surface.

Parity targets:
  * ``hvd.DistributedOptimizer`` (reference ``horovod/torch/__init__.py:66-221``
    and ``horovod/tensorflow/__init__.py:266-311``): wrap an optimizer so
    gradients are averaged across ranks before the update, with
    ``backward_passes_per_step`` local accumulation.
  * ``hvd.DistributedGradientTape`` (reference
    ``horovod/tensorflow/__init__.py:475-531``): wrap gradient
    computation itself.

JAX mapping: optimizers are optax ``GradientTransformation``s, and
"wrapping backward" is wrapping ``jax.grad``.  Two execution regimes,
chosen automatically:

  * **compiled** — inside `shard_map` with a named mesh axis: gradients
    reduce with `lax.psum` traced into the step (XLA overlaps them with
    backprop compute; the role of the reference's hook-per-gradient
    eager pipeline).
  * **eager** — concrete arrays: gradients fuse into per-dtype flat
    buffers and go through the background runtime's negotiated
    collectives (tensor fusion, reference ``FuseResponses``).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.common import basics as _basics
from horovod_tpu.ops import collectives as _coll
from horovod_tpu.ops import eager as _eager
from horovod_tpu.ops import quantization as _quant
from horovod_tpu.ops.collectives import Adasum, Average, Sum
from horovod_tpu.ops.compression import (Compression, active_compression,
                                         is_quantized)


def _in_trace(tree) -> bool:
    return any(isinstance(x, jax.core.Tracer) for x in jax.tree_util.tree_leaves(tree))


def _resolve_compression(compression):
    """``None`` → the ``HOROVOD_COMPRESSION`` knob's compressor (so the
    launcher/config surface reaches every default-argument call site);
    an explicit compressor always wins."""
    return active_compression() if compression is None else compression


def allreduce_gradients(grads, op: int = Average, axis_name: str = "hvd",
                        compression=None):
    """Allreduce a gradient pytree.

    In-trace: one grouped psum (XLA fuses into large ICI transfers);
    ``Compression.int8`` routes through the fused quantized reduction.
    Eager: leaves grouped by dtype, each group raveled into one flat
    buffer -> one negotiated fused collective per dtype (tensor fusion,
    reference ``fusion_buffer_manager.h``); the eager wire applies the
    ``HOROVOD_COMPRESSION`` knob inside the negotiated program.
    """
    compression = _resolve_compression(compression)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    if _in_trace(leaves):
        reduced = _coll.grouped_allreduce(leaves, axis_name=axis_name,
                                          op=op, compression=compression)
        return jax.tree_util.tree_unflatten(treedef, reduced)
    # Quantized wire on the eager path is knob-driven inside the
    # negotiated program (xla_exec); the per-leaf compressor must be a
    # pass-through here.
    eager_comp = Compression.none if is_quantized(compression) \
        else compression
    return jax.tree_util.tree_unflatten(
        treedef, _eager_fused_pytree_allreduce(leaves, op, eager_comp))


def allreduce_gradients_with_feedback(grads, residuals, op: int = Average,
                                      axis_name: str = "hvd"):
    """Quantized (int8) gradient allreduce with error feedback: returns
    ``(reduced, new_residuals)``.  Last step's residuals are re-injected
    before reduction; the new residuals carry this step's local
    compression error (see :mod:`horovod_tpu.ops.quantization`).
    In-trace only — the eager negotiated program does not expose the
    local quantization error, so eager calls reduce without feedback
    and return the residuals unchanged."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads, residuals
    if not _in_trace(leaves):
        return (allreduce_gradients(grads, op=op, axis_name=axis_name,
                                    compression=Compression.int8),
                residuals)
    injected = _quant.apply_error_feedback(grads, residuals)
    ileaves = jax.tree_util.tree_flatten(injected)[0]
    outs, errs = _coll.grouped_quantized_allreduce(
        ileaves, axis_name=axis_name, op=op, with_error=True)
    return (jax.tree_util.tree_unflatten(treedef, outs),
            jax.tree_util.tree_unflatten(treedef, errs))


def _fused_pytree_collective(leaves, submit_async):
    """Shared eager fusion: group leaves by dtype, ravel each group into
    one flat buffer, run one async collective per group via
    ``submit_async(flat, label) -> handle``, split results back."""
    groups: dict[Any, list[int]] = {}
    leaves = [jnp.asarray(l) for l in leaves]
    for i, leaf in enumerate(leaves):
        groups.setdefault(np.dtype(leaf.dtype), []).append(i)
    out: list[Any] = [None] * len(leaves)
    handles = []
    for dtype, idxs in groups.items():
        flat = (leaves[idxs[0]].reshape(-1) if len(idxs) == 1 else
                jnp.concatenate([leaves[i].reshape(-1) for i in idxs]))
        handles.append((idxs, submit_async(flat, f"{dtype}.{len(idxs)}")))
    for idxs, h in handles:
        red = _eager.synchronize(h)
        off = 0
        for i in idxs:
            size = int(np.prod(leaves[i].shape)) if leaves[i].ndim else 1
            out[i] = red[off:off + size].reshape(leaves[i].shape)
            off += size
    return out


def _eager_fused_pytree_allreduce(leaves, op, compression):
    return _fused_pytree_collective(
        leaves,
        lambda flat, label: _eager.allreduce_async(
            flat, op=op, name=f"grad_buffer.{label}",
            compression=compression))


class _AccumulationState(NamedTuple):
    counter: jnp.ndarray
    accum: Any
    inner_state: Any


class _FeedbackState(NamedTuple):
    """Optimizer state wrapper carrying the persistent error-feedback
    residual pytree for quantized (int8) gradient reduction."""
    residual: Any
    inner_state: Any


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=None,
                         backward_passes_per_step: int = 1,
                         op: int = Average, axis_name: str = "hvd"):
    """Wrap an optax optimizer with cross-rank gradient aggregation.

    Keeps the reference's keyword surface
    (``horovod/torch/__init__.py:395-449``); ``named_parameters`` is
    accepted and ignored (pytrees carry structure).  With
    ``backward_passes_per_step > 1`` gradients accumulate locally and
    communicate only every N steps (reference grad-accumulation,
    ``torch/__init__.py:127-162``); intermediate steps return zero
    updates.

    ``compression=None`` (default) resolves from the
    ``HOROVOD_COMPRESSION`` knob.  With ``Compression.int8`` and
    ``backward_passes_per_step == 1`` the optimizer state additionally
    carries a persistent error-feedback residual pytree: each step's
    quantization error is re-injected into the next step's gradients,
    so compression error averages out over training instead of being
    lost (EQuARX/1-bit-Adam-style EF; state is a
    :class:`_FeedbackState` wrapping the inner optax state).
    """
    del named_parameters
    try:
        init_fn, update_fn = optimizer.init, optimizer.update
    except AttributeError as exc:
        raise TypeError(
            "DistributedOptimizer expects an optax GradientTransformation "
            f"(got {type(optimizer)!r})") from exc

    compression = _resolve_compression(compression)
    k = int(backward_passes_per_step)

    def reduce_grads(grads):
        return allreduce_gradients(grads, op=op, axis_name=axis_name,
                                   compression=compression)

    if k == 1 and is_quantized(compression) and op != Adasum:
        import optax

        def init_ef(params):
            return _FeedbackState(_quant.init_error_feedback(params),
                                  init_fn(params))

        def update_ef(grads, state, params=None, **extra):
            reduced, new_res = allreduce_gradients_with_feedback(
                grads, state.residual, op=op, axis_name=axis_name)
            upd, inner = update_fn(reduced, state.inner_state, params,
                                   **extra)
            return upd, _FeedbackState(new_res, inner)

        return optax.GradientTransformation(init_ef, update_ef)

    if k == 1:
        def init1(params):
            return init_fn(params)

        def update1(grads, state, params=None, **extra):
            return update_fn(reduce_grads(grads), state, params, **extra)

        import optax

        return optax.GradientTransformationExtraArgs(init1, update1) \
            if hasattr(optax, "GradientTransformationExtraArgs") \
            else optax.GradientTransformation(init1, update1)

    import optax

    def init_k(params):
        accum = jax.tree_util.tree_map(jnp.zeros_like, params)
        return _AccumulationState(jnp.zeros((), jnp.int32), accum,
                                  init_fn(params))

    def update_k(grads, state, params=None, **extra):
        counter = state.counter + 1
        accum = jax.tree_util.tree_map(lambda a, g: a + g, state.accum, grads)
        sync = counter >= k

        if _in_trace(grads):
            def do_sync(acc, inner):
                mean = jax.tree_util.tree_map(lambda a: a / k, acc)
                upd, new_inner = update_fn(reduce_grads(mean), inner,
                                           params, **extra)
                zeros = jax.tree_util.tree_map(jnp.zeros_like, acc)
                return upd, zeros, new_inner

            def no_sync(acc, inner):
                zeros = jax.tree_util.tree_map(jnp.zeros_like, acc)
                return zeros, acc, inner

            upd, accum2, inner2 = jax.lax.cond(
                sync, do_sync, no_sync, accum, state.inner_state)
            new_counter = jnp.where(sync, 0, counter)
            return upd, _AccumulationState(new_counter, accum2, inner2)

        if bool(sync):
            mean = jax.tree_util.tree_map(lambda a: a / k, accum)
            upd, inner2 = update_fn(reduce_grads(mean), state.inner_state,
                                    params, **extra)
            zeros = jax.tree_util.tree_map(jnp.zeros_like, accum)
            return upd, _AccumulationState(jnp.zeros((), jnp.int32),
                                           zeros, inner2)
        zeros = jax.tree_util.tree_map(jnp.zeros_like, grads)
        return zeros, _AccumulationState(counter, accum, state.inner_state)

    return optax.GradientTransformation(init_k, update_k)


class DistributedGradientTape:
    """JAX analog of the reference's TF ``DistributedGradientTape``
    (``tensorflow/__init__.py:475-531``): wraps a loss function so its
    gradients come back allreduced."""

    def __init__(self, loss_fn, compression=None,
                 op: int = Average, axis_name: str = "hvd",
                 has_aux: bool = False):
        self._loss_fn = loss_fn
        self._compression = _resolve_compression(compression)
        self._op = op
        self._axis_name = axis_name
        self._has_aux = has_aux

    def gradient(self, *args, argnums=0, **kwargs):
        g = jax.grad(self._loss_fn, argnums=argnums,
                     has_aux=self._has_aux)(*args, **kwargs)
        if self._has_aux:
            grads, aux = g
            return allreduce_gradients(grads, self._op, self._axis_name,
                                       self._compression), aux
        return allreduce_gradients(g, self._op, self._axis_name,
                                   self._compression)


def grad(loss_fn, argnums=0, op: int = Average, axis_name: str = "hvd",
         compression=None, has_aux: bool = False):
    """``jax.grad`` with cross-rank averaging — functional spelling of
    DistributedGradientTape."""
    compression = _resolve_compression(compression)

    gfn = jax.grad(loss_fn, argnums=argnums, has_aux=has_aux)

    def wrapped(*args, **kwargs):
        g = gfn(*args, **kwargs)
        if has_aux:
            g, aux = g
            return allreduce_gradients(g, op, axis_name, compression), aux
        return allreduce_gradients(g, op, axis_name, compression)

    return wrapped


# ---------------------------------------------------------------------------
# Parameter / object broadcast (reference torch/__init__.py:451-647)
# ---------------------------------------------------------------------------


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast a parameter pytree from ``root_rank`` to all ranks and
    return the synchronized pytree (functional; the reference mutates
    ``state_dict`` in place, ``torch/__init__.py:451-481``).  Tensors are
    fused per dtype into single transfers."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    if not leaves:
        return params
    out = _fused_pytree_collective(
        leaves,
        lambda flat, label: _eager.broadcast_async(
            flat, root_rank, name=f"bcast_buffer.{label}"))
    return jax.tree_util.tree_unflatten(treedef, out)


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Broadcast optimizer state (reference ``torch/__init__.py:483-604``;
    trivial here because optax state is already a pytree of arrays)."""
    return broadcast_parameters(opt_state, root_rank)


# TF-parity alias (reference ``BroadcastGlobalVariablesHook`` semantics).
def broadcast_global_variables(variables, root_rank: int = 0):
    return broadcast_parameters(variables, root_rank)


def broadcast_object(obj, root_rank: int = 0, name: str | None = None):
    """Broadcast an arbitrary picklable object
    (reference ``torch/__init__.py:607-647``: cloudpickle → size bcast →
    payload bcast)."""
    import io
    import pickle

    try:
        import cloudpickle as pickler  # type: ignore
    except ImportError:
        pickler = pickle
    name = name or "broadcast_object"
    if _basics.rank() == root_rank:
        buf = io.BytesIO()
        pickler.dump(obj, buf)
        payload = np.frombuffer(buf.getvalue(), dtype=np.uint8)
        length = np.array([payload.size], dtype=np.int32)
    else:
        payload = None
        length = np.zeros((1,), dtype=np.int32)
    length = np.asarray(_eager.broadcast(jnp.asarray(length), root_rank,
                                         name=f"{name}.len"))
    n = int(length[0])
    if payload is None:
        payload = np.zeros((n,), dtype=np.uint8)
    wire = _eager.broadcast(jnp.asarray(payload), root_rank,
                            name=f"{name}.payload")
    data = np.asarray(wire).tobytes()
    return pickle.loads(data)
