"""DistributedOptimizer / gradient-aggregation surface.

Parity targets:
  * ``hvd.DistributedOptimizer`` (reference ``horovod/torch/__init__.py:66-221``
    and ``horovod/tensorflow/__init__.py:266-311``): wrap an optimizer so
    gradients are averaged across ranks before the update, with
    ``backward_passes_per_step`` local accumulation.
  * ``hvd.DistributedGradientTape`` (reference
    ``horovod/tensorflow/__init__.py:475-531``): wrap gradient
    computation itself.

JAX mapping: optimizers are optax ``GradientTransformation``s, and
"wrapping backward" is wrapping ``jax.grad``.  Two execution regimes,
chosen automatically:

  * **compiled** — inside `shard_map` with a named mesh axis: gradients
    reduce with `lax.psum` traced into the step (XLA overlaps them with
    backprop compute; the role of the reference's hook-per-gradient
    eager pipeline).
  * **eager** — concrete arrays: gradients fuse into per-dtype flat
    buffers and go through the background runtime's negotiated
    collectives (tensor fusion, reference ``FuseResponses``).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.common import basics as _basics
from horovod_tpu.common import config as _config
from horovod_tpu.common.types import HorovodTpuError
from horovod_tpu.ops import collectives as _coll
from horovod_tpu.ops import eager as _eager
from horovod_tpu.ops import quantization as _quant
from horovod_tpu.ops.collectives import Adasum, Average, Sum
from horovod_tpu.ops.compression import (Compression, active_compression,
                                         is_quantized)
from horovod_tpu.runtime import metrics as _metrics

_M_FUSED_BYTES = _metrics.gauge(
    "hvd_fusion_buffer_bytes",
    "Flat fused-gradient buffer size per dtype group on the eager "
    "path.")


def _in_trace(tree) -> bool:
    return any(isinstance(x, jax.core.Tracer) for x in jax.tree_util.tree_leaves(tree))


def _resolve_compression(compression):
    """``None`` → the ``HOROVOD_COMPRESSION`` knob's compressor (so the
    launcher/config surface reaches every default-argument call site);
    an explicit compressor always wins."""
    return active_compression() if compression is None else compression


def allreduce_gradients(grads, op: int = Average, axis_name: str = "hvd",
                        compression=None, overlap=None):
    """Allreduce a gradient pytree.

    In-trace: one grouped psum (XLA fuses into large ICI transfers);
    ``Compression.int8`` routes through the fused quantized reduction,
    and ``overlap`` (default: the ``HOROVOD_OVERLAP`` knob) swaps the
    monolithic collective for the bucketed ppermute ring schedule
    (:mod:`horovod_tpu.ops.overlap`) so communication hides behind
    compute.  Eager: leaves grouped by dtype, each group raveled into
    one flat buffer -> one negotiated fused collective per dtype
    (tensor fusion, reference ``fusion_buffer_manager.h``); the eager
    wire applies the ``HOROVOD_COMPRESSION`` / ``HOROVOD_OVERLAP``
    knobs inside the negotiated program (per-call arguments cannot
    guarantee cross-rank agreement there — the knobs are validated at
    the round-0 handshake).
    """
    compression = _resolve_compression(compression)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    if _in_trace(leaves):
        reduced = _coll.grouped_allreduce(leaves, axis_name=axis_name,
                                          op=op, compression=compression,
                                          overlap=overlap)
        return jax.tree_util.tree_unflatten(treedef, reduced)
    # Quantized wire on the eager path is knob-driven inside the
    # negotiated program (xla_exec); the per-leaf compressor must be a
    # pass-through here.
    eager_comp = Compression.none if is_quantized(compression) \
        else compression
    return jax.tree_util.tree_unflatten(
        treedef, _eager_fused_pytree_allreduce(leaves, op, eager_comp))


def allreduce_gradients_with_feedback(grads, residuals, op: int = Average,
                                      axis_name: str = "hvd",
                                      overlap=None):
    """Quantized (int8) gradient allreduce with error feedback: returns
    ``(reduced, new_residuals)``.  Last step's residuals are re-injected
    before reduction; the new residuals carry this step's local
    compression error (see :mod:`horovod_tpu.ops.quantization`).
    In-trace only — the eager negotiated program does not expose the
    local quantization error, so eager calls reduce without feedback
    and return the residuals unchanged."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads, residuals
    if not _in_trace(leaves):
        return (allreduce_gradients(grads, op=op, axis_name=axis_name,
                                    compression=Compression.int8),
                residuals)
    injected = _quant.apply_error_feedback(grads, residuals)
    ileaves = jax.tree_util.tree_flatten(injected)[0]
    outs, errs = _coll.grouped_quantized_allreduce(
        ileaves, axis_name=axis_name, op=op, with_error=True,
        overlap=overlap)
    return (jax.tree_util.tree_unflatten(treedef, outs),
            jax.tree_util.tree_unflatten(treedef, errs))


def _fused_pytree_collective(leaves, submit_async):
    """Shared eager fusion: group leaves by dtype, ravel each group into
    one flat buffer, run one async collective per group via
    ``submit_async(flat, label) -> handle``, split results back."""
    groups: dict[Any, list[int]] = {}
    leaves = [jnp.asarray(l) for l in leaves]
    for i, leaf in enumerate(leaves):
        groups.setdefault(np.dtype(leaf.dtype), []).append(i)
    out: list[Any] = [None] * len(leaves)
    handles = []
    for dtype, idxs in groups.items():
        flat = (leaves[idxs[0]].reshape(-1) if len(idxs) == 1 else
                jnp.concatenate([leaves[i].reshape(-1) for i in idxs]))
        _M_FUSED_BYTES.set(int(flat.size) * dtype.itemsize,
                           dtype=str(dtype))
        handles.append((idxs, submit_async(flat, f"{dtype}.{len(idxs)}")))
    for idxs, h in handles:
        red = _eager.synchronize(h)
        off = 0
        for i in idxs:
            size = int(np.prod(leaves[i].shape)) if leaves[i].ndim else 1
            out[i] = red[off:off + size].reshape(leaves[i].shape)
            off += size
    return out


def _eager_fused_pytree_allreduce(leaves, op, compression):
    return _fused_pytree_collective(
        leaves,
        lambda flat, label: _eager.allreduce_async(
            flat, op=op, name=f"grad_buffer.{label}",
            compression=compression))


class _AccumulationState(NamedTuple):
    counter: jnp.ndarray
    accum: Any
    inner_state: Any


class _FeedbackState(NamedTuple):
    """Optimizer state wrapper carrying the persistent error-feedback
    residual pytree for quantized (int8) gradient reduction."""
    residual: Any
    inner_state: Any


# ---------------------------------------------------------------------------
# ZeRO-1 sharded weight update (arXiv:2004.13336): reduce-scatter the
# fused gradient buffers, run the wrapped optimizer on only the
# rank-local 1/world_size shard (optimizer state — Adam moments etc. —
# is initialized and carried shard-local), allgather the update shards.
# ---------------------------------------------------------------------------


class _ShardLayout(NamedTuple):
    """Static fused-buffer layout shared by init and update: per dtype
    group, the member leaf indices and flat sizes, the buffer length
    padded to a multiple of world size, and the per-rank shard length."""
    keys: tuple      # dtype names, insertion (leaf) order
    idxs: tuple      # tuple[int, ...] per group
    sizes: tuple     # tuple[int, ...] per group (flat leaf sizes)
    padded: tuple    # int per group
    shard: tuple     # int per group (padded // world)


@jax.tree_util.register_pytree_node_class
class _ShardedState:
    """Optimizer state for the sharded update.  ``inner_state`` is the
    wrapped optimizer's state over the rank-local shard buffers (the
    ~1/world_size optimizer-state footprint ZeRO-1 exists for);
    ``residual`` is the int8 error-feedback residual over the full
    fused buffers (input-side EF needs the full local quantization
    error — it is one flat fp32 buffer per float group, not a
    leaf-per-parameter tree; ``None`` without quantization); ``layout``
    is the static :class:`_ShardLayout` (pytree aux data)."""

    def __init__(self, inner_state, residual, layout: _ShardLayout):
        self.inner_state = inner_state
        self.residual = residual
        self.layout = layout

    def tree_flatten(self):
        return (self.inner_state, self.residual), self.layout

    @classmethod
    def tree_unflatten(cls, layout, children):
        return cls(children[0], children[1], layout)

    def __repr__(self) -> str:  # keep state dumps readable
        return (f"_ShardedState(inner_state={self.inner_state!r}, "
                f"residual={self.residual!r})")


def _is_sharded_state(x) -> bool:
    return isinstance(x, _ShardedState)


def _contains_sharded_state(tree) -> bool:
    return any(_is_sharded_state(l) for l in
               jax.tree_util.tree_leaves(tree, is_leaf=_is_sharded_state))


def _shard_layout(leaves, n: int) -> _ShardLayout:
    groups: dict = {}
    for i, leaf in enumerate(leaves):
        groups.setdefault(str(jnp.dtype(leaf.dtype)), []).append(i)
    keys, idxs, sizes, padded, shard = [], [], [], [], []
    for key, ii in groups.items():
        sz = tuple(int(np.prod(leaves[i].shape)) if leaves[i].ndim else 1
                   for i in ii)
        total = sum(sz)
        p = total + (-total) % n
        keys.append(key)
        idxs.append(tuple(ii))
        sizes.append(sz)
        padded.append(p)
        shard.append(p // n)
    return _ShardLayout(tuple(keys), tuple(idxs), tuple(sizes),
                        tuple(padded), tuple(shard))


def _fuse_group(leaves, layout: _ShardLayout, g: int):
    """One flat buffer for group ``g``, zero-padded to the layout's
    world-divisible length."""
    flats = [leaves[i].reshape(-1) for i in layout.idxs[g]]
    flat = flats[0] if len(flats) == 1 else jnp.concatenate(flats)
    pad = layout.padded[g] - flat.shape[0]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def _shard_position(axis_name):
    """(shard index, world size, in_trace) for the current regime.

    The axis binding — not leaf tracer-ness — decides the regime:
    inside ``shard_map`` the gradient leaves can be trace-constants
    (closed-over parameters) while the mesh axis is still what shards
    the update, so probe ``lax.axis_index`` first and fall back to the
    process rank only when the axis is unbound (the eager
    one-process-per-chip regime, or state init outside the step)."""
    try:
        return (_coll.shard_index(axis_name),
                _quant._axis_prod(axis_name), True)
    except Exception:
        pass
    st = _basics.state()
    if st.initialized:
        return st.rank, st.size, False
    return 0, 1, False


def _make_sharded_fns(init_fn, update_fn, op: int, axis_name,
                      compression, overlap=None):
    """(init, update) pair implementing the sharded weight update around
    the wrapped optimizer's ``init_fn``/``update_fn``.  With ``overlap``
    (default: the ``HOROVOD_OVERLAP`` knob) the scatter and gather run
    as bucketed ppermute ring pipelines (``HOROVOD_OVERLAP_CHUNKS``
    buckets, barrier-separated) instead of one monolithic
    psum_scatter/all_gather per dtype group — the shard layout is
    bucket-independent, so state, checkpoints and specs are identical
    either way."""
    from jax import lax

    quantized = is_quantized(compression)

    def _float_group(key: str) -> bool:
        return jnp.issubdtype(jnp.dtype(key), jnp.floating)

    def _param_shards(params, layout, idx):
        if params is None:
            return None
        pleaves = jax.tree_util.tree_leaves(params)
        shards = []
        for g in range(len(layout.keys)):
            buf = _fuse_group(pleaves, layout, g)
            shards.append(lax.dynamic_slice_in_dim(
                buf, idx * layout.shard[g], layout.shard[g]))
        return shards

    def init(params):
        leaves = jax.tree_util.tree_leaves(params)
        idx, n, in_tr = _shard_position(axis_name)
        layout = _shard_layout(leaves, n)
        shards = []
        for g in range(len(layout.keys)):
            buf = _fuse_group(leaves, layout, g)
            shards.append(lax.dynamic_slice_in_dim(
                buf, idx * layout.shard[g], layout.shard[g]))
        residual = None
        if quantized and in_tr:
            # Error feedback runs only in-trace (the eager negotiated
            # program does not expose the local quantization error), so
            # eager-initialized state must not carry dead full-model
            # fp32 residual buffers — the 1/N-memory goal this mode
            # exists for.
            residual = [jnp.zeros((layout.padded[g] if _float_group(k)
                                   else 0,), jnp.float32)
                        for g, k in enumerate(layout.keys)]
        return _ShardedState(init_fn(shards), residual, layout)

    def update(grads, state, params=None, **extra):
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        idx, n, in_tr = _shard_position(axis_name)
        if not in_tr and _in_trace(leaves):
            raise HorovodTpuError(
                "sharded optimizer update traced without the "
                f"{axis_name!r} mesh axis in scope; run the step inside "
                "shard_map over that axis (or call it eagerly).")
        layout = _shard_layout(leaves, n)
        if layout != state.layout:
            raise HorovodTpuError(
                "sharded optimizer state layout does not match the "
                "gradient pytree (did world size or parameter "
                f"dtypes/shapes change?): {state.layout} vs {layout}")
        gshards: list = []
        new_res = list(state.residual) if state.residual is not None \
            else None
        ef = new_res is not None  # EF state exists (in-trace init)
        if in_tr:
            for g, key in enumerate(layout.keys):
                buf = _fuse_group(leaves, layout, g)
                q = quantized and _float_group(key)
                if q and ef:
                    buf = buf.astype(jnp.float32) + state.residual[g]
                shard, err = _coll._scatter_flat_buffer(
                    buf, axis_name, quantized=q, with_error=q and ef,
                    overlap=overlap)
                if err is not None:
                    new_res[g] = err
                if op == Average:
                    shard = shard / n
                gshards.append(shard.astype(jnp.dtype(key)))
        else:
            # Negotiated eager wire: one fused reduce-scatter per dtype
            # group; the HOROVOD_COMPRESSION knob applies inside the
            # negotiated program (like the eager allreduce path, the
            # local quantization error is not exposed, so the residual
            # rides along unchanged).
            handles = []
            for g, key in enumerate(layout.keys):
                buf = _fuse_group(leaves, layout, g)
                handles.append(_eager.reducescatter_async(
                    buf, op=op,
                    name=f"shard_rs.{key}.{layout.padded[g]}"))
            gshards = [_eager.synchronize(h).astype(jnp.dtype(key))
                       for h, key in zip(handles, layout.keys)]
        upd_shards, inner = update_fn(gshards, state.inner_state,
                                      _param_shards(params, layout, idx),
                                      **extra)
        out: list = [None] * len(leaves)
        fulls: list = []
        if in_tr:
            for g in range(len(layout.keys)):
                fulls.append(_coll._gather_flat_shard(
                    upd_shards[g], axis_name, overlap=overlap))
        else:
            handles = [_eager.allgather_async(
                upd_shards[g],
                name=f"shard_ag.{layout.keys[g]}.{layout.padded[g]}")
                for g in range(len(layout.keys))]
            fulls = [_eager.synchronize(h) for h in handles]
        for g in range(len(layout.keys)):
            off = 0
            for i, sz in zip(layout.idxs[g], layout.sizes[g]):
                out[i] = fulls[g][off:off + sz].reshape(
                    leaves[i].shape).astype(leaves[i].dtype)
                off += sz
        return (jax.tree_util.tree_unflatten(treedef, out),
                _ShardedState(inner, new_res, layout))

    return init, update


def sharded_state_specs(opt_state, axis_name: str = "hvd"):
    """``PartitionSpec`` pytree for threading a sharded optimizer state
    through ``jit``/``shard_map`` over the world mesh: shard-buffer
    leaves map to ``P(axis_name)`` (the global view is the full fused
    buffer, rank ``r`` holding segment ``r``); step counters and other
    scalars are replicated ``P()``.  Error-feedback residuals are
    per-rank values — not shards of one global array — and cannot ride
    a spec: thread int8+EF states inside a single shard_map program
    instead (see docs/zero.md)."""
    from jax.sharding import PartitionSpec as P

    def one(node):
        if _is_sharded_state(node):
            if node.residual is not None and \
                    jax.tree_util.tree_leaves(node.residual):
                raise HorovodTpuError(
                    "sharded_state_specs cannot express the int8 "
                    "error-feedback residual (per-rank state, not a "
                    "sharding of one global array); keep the state "
                    "inside one shard_map program for int8+EF.")
            shard_lens = set(node.layout.shard)
            inner = jax.tree_util.tree_map(
                lambda l: (P(axis_name)
                           if getattr(l, "ndim", 0) == 1
                           and l.shape[0] in shard_lens else P()),
                node.inner_state)
            return _ShardedState(inner, None, node.layout)
        return jax.tree_util.tree_map(lambda _: P(), node)

    return jax.tree_util.tree_map(one, opt_state,
                                  is_leaf=_is_sharded_state)


def sharded_state_to_global(opt_state, mesh=None, axis_name: str = "hvd"):
    """Assemble this process's shard-buffer leaves into global arrays
    over the world mesh (rank ``r`` holds segment ``r``) so a sharded
    optimizer state can cross a jit boundary at world size > 1 with the
    specs from :func:`sharded_state_specs`.  No-op at size 1."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    st = _basics.state()
    if not st.initialized or st.size == 1:
        return opt_state
    mesh = mesh if mesh is not None else st.mesh

    def one(node):
        if not _is_sharded_state(node):
            return node
        shard_lens = set(node.layout.shard)

        def g(leaf):
            leaf = jnp.asarray(leaf)
            if leaf.ndim == 1 and leaf.shape[0] in shard_lens:
                local = jax.device_put(leaf, st.lead_device)
                return jax.make_array_from_single_device_arrays(
                    (st.size * leaf.shape[0],),
                    NamedSharding(mesh, P(axis_name)), [local])
            return leaf

        return _ShardedState(jax.tree_util.tree_map(g, node.inner_state),
                             node.residual, node.layout)

    return jax.tree_util.tree_map(one, opt_state,
                                  is_leaf=_is_sharded_state)


class _HostShardedState:
    """Host-side commit snapshot of a :class:`_ShardedState`: the inner
    state with every shard-buffer leaf allgathered into its full fused
    (global) form, plus the layout it was sharded under.  A plain class
    (not a pytree/NamedTuple) on purpose — blind ``tree_map`` passes
    over a commit snapshot must treat it as one opaque leaf.  Picklable,
    so it rides the elastic resync broadcast."""

    def __init__(self, inner, layout: _ShardLayout, had_residual: bool):
        self.inner = inner
        self.layout = layout
        self.had_residual = had_residual


def _is_host_sharded(x) -> bool:
    return isinstance(x, _HostShardedState)


def sharded_state_to_host(opt_state, gather=None):
    """Host snapshot of an optimizer state for elastic commit points
    (docs/elastic.md).  Plain leaves become numpy; ZeRO-1
    :class:`_ShardedState` subtrees have their shard-buffer leaves
    **allgathered** back into the full fused buffers, so a later
    :func:`sharded_state_from_host` can re-shard them to a *different*
    world size (the commit survives rank death).  Collective when the
    state is sharded and the world is >1 — every rank must call it.
    ``gather`` overrides the eager allgather (tests / offline tools)."""
    st = _basics.state()

    def default_gather(leaf):
        if st.initialized and st.size > 1:
            return _eager.allgather(jnp.asarray(leaf).reshape(-1))
        return jnp.asarray(leaf)

    gather = default_gather if gather is None else gather

    def one(node):
        if _is_sharded_state(node):
            shard_lens = {s for s in node.layout.shard if s > 0}

            def g(leaf):
                leaf = jnp.asarray(leaf)
                if leaf.ndim == 1 and leaf.shape[0] in shard_lens:
                    return np.asarray(gather(leaf))
                return np.asarray(leaf)

            inner = jax.tree_util.tree_map(g, node.inner_state)
            return _HostShardedState(inner, node.layout,
                                     node.residual is not None)
        return jax.tree_util.tree_map(np.asarray, node)

    return jax.tree_util.tree_map(one, opt_state,
                                  is_leaf=_is_sharded_state)


def sharded_state_from_host(host_state, world: int | None = None,
                            rank: int | None = None):
    """Rebuild a device optimizer state from a
    :func:`sharded_state_to_host` snapshot, re-slicing ZeRO-1 subtrees
    for the CURRENT world size: commit-point global buffers are
    re-padded to the new world-divisible length and this rank takes its
    dense segment.  Error-feedback residuals restart at zero — the
    compression error accumulated before the commit point is already
    folded into the committed parameters, and a stale residual sized
    for the old world would be layout garbage anyway."""
    st = _basics.state()
    n = world if world is not None else (st.size if st.initialized else 1)
    r = rank if rank is not None else (st.rank if st.initialized else 0)

    def one(node):
        if _is_host_sharded(node):
            old = node.layout
            totals = tuple(sum(sz) for sz in old.sizes)
            padded = tuple(t + (-t) % n for t in totals)
            new = _ShardLayout(old.keys, old.idxs, old.sizes, padded,
                               tuple(p // n for p in padded))
            gathered_lens = {p for p in old.padded if p > 0}

            def g(leaf):
                a = np.asarray(leaf)
                if a.ndim == 1 and a.shape[0] in gathered_lens:
                    # Which group produced this buffer: padded length
                    # first; on a collision (two dtype groups padding to
                    # the same length) equal totals make the choice
                    # irrelevant (identical trim/re-pad/slice), else the
                    # leaf dtype picks the group (groups are keyed by
                    # dtype, and optax moments keep the param dtype).
                    # A collision with UNEQUAL totals and no dtype match
                    # is genuinely ambiguous — trimming with the wrong
                    # total would silently drop real state, so refuse.
                    cands = [i for i in range(len(old.keys))
                             if old.padded[i] == a.shape[0]]
                    gi = cands[0]
                    if len(cands) > 1 and \
                            len({totals[i] for i in cands}) > 1:
                        m = [i for i in cands
                             if np.dtype(old.keys[i]) == a.dtype]
                        if len(m) == 1:
                            gi = m[0]
                        else:
                            raise HorovodTpuError(
                                "cannot re-shard optimizer state: a "
                                f"{a.dtype} buffer of length "
                                f"{a.shape[0]} matches several dtype "
                                f"groups ({[old.keys[i] for i in cands]}"
                                ") with different true sizes "
                                f"({[totals[i] for i in cands]}); "
                                "restoring with the wrong size would "
                                "corrupt state. Restart at the recorded "
                                "world size instead.")
                    buf = a[:totals[gi]]
                    pad = new.padded[gi] - totals[gi]
                    if pad:
                        buf = np.concatenate(
                            [buf, np.zeros((pad,), a.dtype)])
                    return jnp.asarray(
                        buf[r * new.shard[gi]:(r + 1) * new.shard[gi]])
                return jnp.asarray(a)

            inner = jax.tree_util.tree_map(g, node.inner)
            residual = None
            if node.had_residual:
                residual = [
                    jnp.zeros((new.padded[g]
                               if jnp.issubdtype(jnp.dtype(k),
                                                 jnp.floating) else 0,),
                              jnp.float32)
                    for g, k in enumerate(new.keys)]
            return _ShardedState(inner, residual, new)
        return jax.tree_util.tree_map(jnp.asarray, node)

    return jax.tree_util.tree_map(one, host_state,
                                  is_leaf=_is_host_sharded)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=None,
                         backward_passes_per_step: int = 1,
                         op: int = Average, axis_name: str = "hvd",
                         sharded: bool | None = None,
                         overlap: bool | None = None):
    """Wrap an optax optimizer with cross-rank gradient aggregation.

    Keeps the reference's keyword surface
    (``horovod/torch/__init__.py:395-449``); ``named_parameters`` is
    accepted and ignored (pytrees carry structure).  With
    ``backward_passes_per_step > 1`` gradients accumulate locally and
    communicate only every N steps (reference grad-accumulation,
    ``torch/__init__.py:127-162``); intermediate steps return zero
    updates.

    ``compression=None`` (default) resolves from the
    ``HOROVOD_COMPRESSION`` knob.  With ``Compression.int8`` and
    ``backward_passes_per_step == 1`` the optimizer state additionally
    carries a persistent error-feedback residual pytree: each step's
    quantization error is re-injected into the next step's gradients,
    so compression error averages out over training instead of being
    lost (EQuARX/1-bit-Adam-style EF; state is a
    :class:`_FeedbackState` wrapping the inner optax state).

    ``sharded=None`` (default) resolves from the
    ``HOROVOD_SHARDED_OPTIMIZER`` knob; ``True`` enables the ZeRO-1
    sharded weight update (arXiv:2004.13336): gradients are fused into
    per-dtype flat buffers and **reduce-scattered** instead of
    allreduced, the wrapped optimizer runs on only the rank-local
    ``1/world_size`` shard — its state (Adam moments, …) is initialized
    and carried shard-local, cutting optimizer-state memory
    ~``world_size``-fold — and the updated parameter shards are
    **allgathered** back into the full update pytree.  Composes with
    compression (under int8 + hierarchical only the cross-slice hop is
    quantized) and with ``backward_passes_per_step``; incompatible with
    ``op=Adasum`` (the projection needs the full reduction).  See
    ``docs/zero.md``.

    ``overlap=None`` (default) resolves from the ``HOROVOD_OVERLAP``
    knob; ``True`` replaces the single end-of-step fused collective
    with the bucketed ppermute ring schedule of
    :mod:`horovod_tpu.ops.overlap` (``HOROVOD_OVERLAP_CHUNKS``
    buckets, barrier-separated so XLA's latency-hiding scheduler can
    float bucket ``i+1``'s transfer under bucket ``i``'s compute).
    Composes with ``sharded`` (bucket-wise scatter -> shard update ->
    gather pipeline; state layout unchanged), with int8 (per-bucket
    quantization, EF residuals bucket-aligned) and with hierarchical
    allreduce (only the cross-slice hop rides the ring); ignored for
    ``op=Adasum``.  On the eager path the knob governs (it rides the
    round-0 handshake); a per-call argument applies in-trace only.
    See ``docs/overlap.md``.
    """
    del named_parameters
    try:
        init_fn, update_fn = optimizer.init, optimizer.update
    except AttributeError as exc:
        raise TypeError(
            "DistributedOptimizer expects an optax GradientTransformation "
            f"(got {type(optimizer)!r})") from exc

    compression = _resolve_compression(compression)
    if sharded is None:
        sharded = bool(_config.get("sharded_optimizer"))
    k = int(backward_passes_per_step)

    # Observability (docs/metrics.md): record the resolved schedule so
    # hvd.metrics() shows what the optimizer actually runs with (the
    # env knobs record only the request).
    _ovl = (bool(_config.get("overlap")) if overlap is None
            else bool(overlap))
    _metrics.gauge(
        "hvd_overlap_chunks",
        "Bucket count of the overlap ring schedule (0 = overlap "
        "off).").set(
            int(_config.get("overlap_chunks")) if _ovl else 0)
    _metrics.gauge(
        "hvd_sharded_optimizer",
        "1 when the ZeRO-1 sharded weight update is active.").set(
            1 if sharded else 0)

    def reduce_grads(grads):
        return allreduce_gradients(grads, op=op, axis_name=axis_name,
                                   compression=compression,
                                   overlap=overlap)

    if sharded:
        if op == Adasum:
            raise HorovodTpuError(
                "sharded=True does not compose with op=Adasum: the "
                "projection's dot/norm math needs the full reduction, "
                "not a scatter. Use op=Average/Sum with the sharded "
                "optimizer.")
        import optax

        core_init, core_update = _make_sharded_fns(
            init_fn, update_fn, op, axis_name, compression,
            overlap=overlap)
        if k == 1:
            return optax.GradientTransformation(core_init, core_update)
        # k > 1: the accumulation wrapper below drives the sharded core
        # (which reduces internally), so the pre-reduce hook is a no-op.
        init_fn, update_fn = core_init, core_update

        def reduce_grads(grads):  # noqa: F811 — accumulation path hook
            return grads

    if not sharded and k == 1 and is_quantized(compression) \
            and op != Adasum:
        import optax

        def init_ef(params):
            return _FeedbackState(_quant.init_error_feedback(params),
                                  init_fn(params))

        def update_ef(grads, state, params=None, **extra):
            reduced, new_res = allreduce_gradients_with_feedback(
                grads, state.residual, op=op, axis_name=axis_name,
                overlap=overlap)
            upd, inner = update_fn(reduced, state.inner_state, params,
                                   **extra)
            return upd, _FeedbackState(new_res, inner)

        return optax.GradientTransformation(init_ef, update_ef)

    if k == 1:
        def init1(params):
            return init_fn(params)

        def update1(grads, state, params=None, **extra):
            return update_fn(reduce_grads(grads), state, params, **extra)

        import optax

        return optax.GradientTransformationExtraArgs(init1, update1) \
            if hasattr(optax, "GradientTransformationExtraArgs") \
            else optax.GradientTransformation(init1, update1)

    import optax

    def init_k(params):
        accum = jax.tree_util.tree_map(jnp.zeros_like, params)
        return _AccumulationState(jnp.zeros((), jnp.int32), accum,
                                  init_fn(params))

    def update_k(grads, state, params=None, **extra):
        counter = state.counter + 1
        accum = jax.tree_util.tree_map(lambda a, g: a + g, state.accum, grads)
        sync = counter >= k

        if _in_trace(grads):
            def do_sync(acc, inner):
                mean = jax.tree_util.tree_map(lambda a: a / k, acc)
                upd, new_inner = update_fn(reduce_grads(mean), inner,
                                           params, **extra)
                zeros = jax.tree_util.tree_map(jnp.zeros_like, acc)
                return upd, zeros, new_inner

            def no_sync(acc, inner):
                zeros = jax.tree_util.tree_map(jnp.zeros_like, acc)
                return zeros, acc, inner

            upd, accum2, inner2 = jax.lax.cond(
                sync, do_sync, no_sync, accum, state.inner_state)
            new_counter = jnp.where(sync, 0, counter)
            return upd, _AccumulationState(new_counter, accum2, inner2)

        if bool(sync):
            mean = jax.tree_util.tree_map(lambda a: a / k, accum)
            upd, inner2 = update_fn(reduce_grads(mean), state.inner_state,
                                    params, **extra)
            zeros = jax.tree_util.tree_map(jnp.zeros_like, accum)
            return upd, _AccumulationState(jnp.zeros((), jnp.int32),
                                           zeros, inner2)
        zeros = jax.tree_util.tree_map(jnp.zeros_like, grads)
        return zeros, _AccumulationState(counter, accum, state.inner_state)

    return optax.GradientTransformation(init_k, update_k)


class DistributedGradientTape:
    """JAX analog of the reference's TF ``DistributedGradientTape``
    (``tensorflow/__init__.py:475-531``): wraps a loss function so its
    gradients come back allreduced."""

    def __init__(self, loss_fn, compression=None,
                 op: int = Average, axis_name: str = "hvd",
                 has_aux: bool = False):
        self._loss_fn = loss_fn
        self._compression = _resolve_compression(compression)
        self._op = op
        self._axis_name = axis_name
        self._has_aux = has_aux

    def gradient(self, *args, argnums=0, **kwargs):
        g = jax.grad(self._loss_fn, argnums=argnums,
                     has_aux=self._has_aux)(*args, **kwargs)
        if self._has_aux:
            grads, aux = g
            return allreduce_gradients(grads, self._op, self._axis_name,
                                       self._compression), aux
        return allreduce_gradients(g, self._op, self._axis_name,
                                   self._compression)


def grad(loss_fn, argnums=0, op: int = Average, axis_name: str = "hvd",
         compression=None, has_aux: bool = False):
    """``jax.grad`` with cross-rank averaging — functional spelling of
    DistributedGradientTape."""
    compression = _resolve_compression(compression)

    gfn = jax.grad(loss_fn, argnums=argnums, has_aux=has_aux)

    def wrapped(*args, **kwargs):
        g = gfn(*args, **kwargs)
        if has_aux:
            g, aux = g
            return allreduce_gradients(g, op, axis_name, compression), aux
        return allreduce_gradients(g, op, axis_name, compression)

    return wrapped


# ---------------------------------------------------------------------------
# Parameter / object broadcast (reference torch/__init__.py:451-647)
# ---------------------------------------------------------------------------


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast a parameter pytree from ``root_rank`` to all ranks and
    return the synchronized pytree (functional; the reference mutates
    ``state_dict`` in place, ``torch/__init__.py:451-481``).  Tensors are
    fused per dtype into single transfers."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    if not leaves:
        return params
    out = _fused_pytree_collective(
        leaves,
        lambda flat, label: _eager.broadcast_async(
            flat, root_rank, name=f"bcast_buffer.{label}"))
    return jax.tree_util.tree_unflatten(treedef, out)


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Broadcast optimizer state (reference ``torch/__init__.py:483-604``;
    trivial here because optax state is already a pytree of arrays).

    Shard-local (ZeRO-1) subtrees pass through unchanged: each rank's
    shard is authoritative — broadcasting rank 0's moments would
    silently overwrite every other rank's shard with the wrong
    segment.  Everything around them (accumulation buffers, schedules,
    a params tree resynced in the same call) still broadcasts.
    Restore shard-local state with ``checkpoint.save/restore(...,
    all_ranks=True)`` instead (see docs/zero.md)."""
    return broadcast_skipping_shards(opt_state, root_rank)


def broadcast_skipping_shards(tree, root_rank: int = 0):
    """Broadcast every leaf of ``tree`` from ``root_rank`` EXCEPT those
    inside shard-local (:class:`_ShardedState`) subtrees, which are
    per-rank by construction.  Returns ``tree`` itself when there is
    nothing to broadcast."""
    leaves, treedef = jax.tree_util.tree_flatten(
        tree, is_leaf=_is_sharded_state)
    plain = [i for i, l in enumerate(leaves)
             if not _is_sharded_state(l)]
    if not plain:
        return tree
    synced = broadcast_parameters([leaves[i] for i in plain],
                                  root_rank=root_rank)
    for i, v in zip(plain, synced):
        leaves[i] = v
    return jax.tree_util.tree_unflatten(treedef, leaves)


# TF-parity alias (reference ``BroadcastGlobalVariablesHook`` semantics).
def broadcast_global_variables(variables, root_rank: int = 0):
    return broadcast_parameters(variables, root_rank)


def broadcast_object(obj, root_rank: int = 0, name: str | None = None):
    """Broadcast an arbitrary picklable object
    (reference ``torch/__init__.py:607-647``: cloudpickle → size bcast →
    payload bcast)."""
    import io
    import pickle

    try:
        import cloudpickle as pickler  # type: ignore
    except ImportError:
        pickler = pickle
    name = name or "broadcast_object"
    if _basics.rank() == root_rank:
        buf = io.BytesIO()
        pickler.dump(obj, buf)
        payload = np.frombuffer(buf.getvalue(), dtype=np.uint8)
        length = np.array([payload.size], dtype=np.int32)
    else:
        payload = None
        length = np.zeros((1,), dtype=np.int32)
    length = np.asarray(_eager.broadcast(jnp.asarray(length), root_rank,
                                         name=f"{name}.len"))
    n = int(length[0])
    if payload is None:
        payload = np.zeros((n,), dtype=np.uint8)
    wire = _eager.broadcast(jnp.asarray(payload), root_rank,
                            name=f"{name}.payload")
    data = np.asarray(wire).tobytes()
    return pickle.loads(data)
