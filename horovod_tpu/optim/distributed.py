"""DistributedOptimizer / gradient-aggregation surface.

Parity targets:
  * ``hvd.DistributedOptimizer`` (reference ``horovod/torch/__init__.py:66-221``
    and ``horovod/tensorflow/__init__.py:266-311``): wrap an optimizer so
    gradients are averaged across ranks before the update, with
    ``backward_passes_per_step`` local accumulation.
  * ``hvd.DistributedGradientTape`` (reference
    ``horovod/tensorflow/__init__.py:475-531``): wrap gradient
    computation itself.

JAX mapping: optimizers are optax ``GradientTransformation``s, and
"wrapping backward" is wrapping ``jax.grad``.  Two execution regimes,
chosen automatically:

  * **compiled** — inside `shard_map` with a named mesh axis: gradients
    reduce with `lax.psum` traced into the step (XLA overlaps them with
    backprop compute; the role of the reference's hook-per-gradient
    eager pipeline).
  * **eager** — concrete arrays: gradients fuse into per-dtype flat
    buffers and go through the background runtime's negotiated
    collectives (tensor fusion, reference ``FuseResponses``).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.common import basics as _basics
from horovod_tpu.common import config as _config
from horovod_tpu.common.types import HorovodTpuError
from horovod_tpu.optim import fused_update as _fused
from horovod_tpu.ops import collectives as _coll
from horovod_tpu.ops import eager as _eager
from horovod_tpu.ops import quantization as _quant
from horovod_tpu.ops.collectives import Adasum, Average, Sum
from horovod_tpu.ops.compression import (Compression, active_compression,
                                         is_quantized, wire_mode)
from horovod_tpu.parallel import mesh as _pmesh
from horovod_tpu.runtime import metrics as _metrics

_M_FUSED_BYTES = _metrics.gauge(
    "hvd_fusion_buffer_bytes",
    "Flat fused-gradient buffer size per dtype group on the eager "
    "path.")

# ZeRO residency gauges (docs/metrics.md / docs/zero.md): the N-fold
# memory claim as scrapeable numbers.  Stamped from the static fused
# layout at optimizer-state init, so they are exact byte counts of what
# is resident per chip — params (stage 3 shards vs replicated), the
# gradient reduction's resident form (stage >= 2 shard vs full fused
# buffer), and the wrapped optimizer's state (sharded from stage 1 on).
_M_ZERO_STAGE = _metrics.gauge(
    "hvd_zero_stage",
    "Resolved ZeRO stage of the last-constructed DistributedOptimizer "
    "(0 = replicated update).")
_M_ZERO_PARAM_BYTES = _metrics.gauge(
    "hvd_zero_param_bytes_per_chip",
    "Resident parameter bytes per chip (1/world flat shards under "
    "zero_stage=3, full replicas below).")
_M_ZERO_GRAD_BYTES = _metrics.gauge(
    "hvd_zero_grad_bytes_per_chip",
    "Resident reduced-gradient bytes per chip (the rank-local shard "
    "under zero_stage>=2; the full fused buffer below).")
_M_ZERO_OPT_BYTES = _metrics.gauge(
    "hvd_zero_opt_state_bytes_per_chip",
    "Wrapped optimizer-state bytes per chip (shard-local from "
    "zero_stage>=1 on).")
_M_RESID_RATIO = _metrics.gauge(
    "hvd_compression_residual_ratio",
    "Per-bucket error-feedback residual-to-reduced-gradient norm "
    "ratio, published while HOROVOD_ADAPTIVE_COMPRESSION is on; the "
    "adaptive tuner's bounded-loss guardrail pins a bucket back to "
    "int8 when this exceeds "
    "HOROVOD_COMPRESSION_MAX_RESIDUAL_RATIO (docs/compression.md).")


def _publish_residual_ratios(ratios) -> None:
    """Host side of the in-trace guardrail signal (jax.debug.callback
    target): one gauge series per bucket index."""
    arr = np.asarray(ratios).reshape(-1)
    for b in range(arr.shape[0]):
        v = float(arr[b])
        if np.isfinite(v):
            _M_RESID_RATIO.set(v, bucket=str(b))


def _report_bucket_residual_ratios(err, ref, n, axis_name,
                                   chunks: int = 1) -> None:
    """In-trace guardrail signal for the adaptive compression stack:
    per-bucket ``||EF residual|| / ||reduced gradient||`` published to
    the metrics registry via a host callback.  ``err`` is the
    full-size ``(n*L,)`` fp32 residual in segment layout; ``ref`` is
    either this rank's ``(L,)`` reduced shard (ZeRO paths — bucket
    norms are psum'd to global) or the full ``(n*L,)`` reduced buffer
    (replicated path — already global).  Bucket bounds mirror the
    scatter chain that produced ``err``, so ratios land on the same
    bucket indices the tuner's mode vector cycles over.  Gated on the
    ``HOROVOD_ADAPTIVE_COMPRESSION`` knob — zero cost otherwise."""
    if not _config.get("adaptive_compression"):
        return
    from jax import lax

    from horovod_tpu.ops import overlap as _ovl

    n = max(int(n), 1)
    L = err.shape[0] // n
    if L == 0:
        return
    bounds = _ovl.bucket_bounds(L, max(1, int(chunks)))
    e2d = err.reshape(n, L)
    full_ref = ref.shape[0] == err.shape[0]
    ref = ref.astype(jnp.float32)
    r2d = ref.reshape(n, L) if full_ref else None
    rs, gs = [], []
    for (s, e) in bounds:
        rs.append(jnp.sum(jnp.square(e2d[:, s:e])))
        gs.append(jnp.sum(jnp.square(r2d[:, s:e] if full_ref
                                     else ref[s:e])))
    rvec, gvec = jnp.stack(rs), jnp.stack(gs)
    rvec = lax.psum(rvec, axis_name)  # residuals are per-rank local
    if not full_ref:
        gvec = lax.psum(gvec, axis_name)  # shard slices are 1/n each
    ratios = jnp.sqrt(rvec) / jnp.maximum(jnp.sqrt(gvec), 1e-12)
    jax.debug.callback(_publish_residual_ratios, ratios)


def _maybe_report_residual_ratio(new_res, reduced, axis_name,
                                 overlap=None) -> None:
    """Replicated-path wrapper for :func:`_report_bucket_residual_
    ratios`: rebuilds the fused float-buffer view the grouped lossy
    allreduce ran on (float leaves raveled fp32 in leaf order, padded
    to the axis size) from the per-leaf residual/reduced trees."""
    if not _config.get("adaptive_compression"):
        return
    from horovod_tpu.ops import overlap as _ovl

    res_l = jax.tree_util.tree_leaves(new_res)
    red_l = jax.tree_util.tree_leaves(reduced)
    if not res_l or len(res_l) != len(red_l) or not _in_trace(res_l):
        return
    # Pair leaf-wise and keep the float ones: the residual tree carries
    # zero entries for integer leaves (they bypass the lossy wire), and
    # dropping the PAIR — not just the gradient side — keeps the two
    # fused views aligned for models with mixed-dtype grads.
    pairs = [(jnp.asarray(r).astype(jnp.float32).reshape(-1),
              jnp.asarray(g).astype(jnp.float32).reshape(-1))
             for r, g in zip(res_l, red_l)
             if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating)]
    if not pairs:
        return
    rl = [r for r, _ in pairs]
    gl = [g for _, g in pairs]
    ferr = rl[0] if len(rl) == 1 else jnp.concatenate(rl)
    fred = gl[0] if len(gl) == 1 else jnp.concatenate(gl)
    n = _coll._axis_total(axis_name)
    pad = (-ferr.shape[0]) % max(n, 1)
    if pad:
        z = jnp.zeros((pad,), jnp.float32)
        ferr = jnp.concatenate([ferr, z])
        fred = jnp.concatenate([fred, z])
    chunks = (_ovl.configured_chunks() if _ovl.enabled(overlap) else 1)
    _report_bucket_residual_ratios(ferr, fred, n, axis_name,
                                   chunks=chunks)


def _in_trace(tree) -> bool:
    return any(isinstance(x, jax.core.Tracer) for x in jax.tree_util.tree_leaves(tree))


def _check_eager_mesh() -> None:
    """The eager/negotiated wire is flat-world (one lead device per
    process over the ``hvd`` axis); with tp/pp/sp extents on the data
    mesh it would average model-sharded values across islands.  Fail
    loudly instead of corrupting params (docs/mesh.md)."""
    if _pmesh.model_parallel_size() > 1:
        raise HorovodTpuError(
            "eager collectives are flat-world and cannot honor a data "
            f"mesh with model-parallel axes ({_pmesh.canonical_spec(_pmesh.active_spec())!r}); "
            "run the gradient reduction in-trace (shard_map over the "
            "data mesh) or drop the tp/pp/sp extents from HOROVOD_MESH")


def _health_wrap(tx, axis_name: str):
    """Training-health plane (docs/health.md): wrap the finished
    DistributedOptimizer transformation with the in-trace stat taps.

    Knob-gated at TRACE time (``HOROVOD_HEALTH``, validated at the
    round-0 handshake), zero-cost when off.  In-trace, the tap computes
    per-dtype-group finite-part grad norm / max-abs / PRE-reduction
    nonfinite count over the incoming gradient leaves — this rank's
    local gradients, before any reduction, on every ZeRO stage and
    overlap setting — packs them into one small per-rank verdict
    vector, allgathers it (the single collective health adds to the
    step) and publishes via host callback, so a nonfinite names its
    culprit rank + dtype group.  Post-update it publishes the
    update-to-weight ratio (local, zero comm).  On the eager regime the
    negotiated allreduce/reducescatter programs carry the tap instead
    (ops/xla_exec), so nothing is double-counted here.

    ``HOROVOD_HEALTH_SKIP_NONFINITE=1`` adds the skip-step contract:
    a step whose verdict carries a nonfinite applies a zero update and
    HOLDS the optimizer state (momenta, EF residuals) — the same
    state-selection machinery the error-feedback path rides — so
    survivors' parameters stay finite.

    Pure observers otherwise: with the skip knob off, enabling stats
    changes no trained parameter bit (the parity matrix in
    tests/test_health.py pins this across stage 0-3 x overlap x
    int8/int4/topk)."""
    from horovod_tpu.runtime import faults as _faults
    from horovod_tpu.runtime import health as _health

    def update(grads, state, params=None, **extra):
        if not _health.enabled():
            return tx.update(grads, state, params, **extra)
        leaves = jax.tree_util.tree_leaves(grads)
        in_tr = _in_trace(leaves)
        bad = idx = None
        if in_tr:
            if _faults.data_rules():
                # Deterministic in-trace poisoning (nan:/inf: rules,
                # testing only — docs/fault-tolerance.md).
                try:
                    ridx = _coll.shard_index(axis_name)
                except Exception:
                    ridx = None
                leaves2, treedef = jax.tree_util.tree_flatten(grads)
                leaves2 = [
                    _faults.traced_poison(l, f"grads.{l.dtype}", ridx)
                    if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
                    else l for l in leaves2]
                grads = jax.tree_util.tree_unflatten(treedef, leaves2)
                leaves = leaves2
            tap = _health.tap_gradients(leaves, axis_name)
            if tap is not None:
                bad, idx = tap
        upd, new_state = tx.update(grads, state, params, **extra)
        try:
            _health.tap_update_ratio(upd, params)
        except Exception:  # a stat must never cost the step
            pass
        if _health.skip_enabled():
            if in_tr and bad is not None:
                upd, new_state = _health.apply_skip_traced(
                    bad, upd, state, new_state, idx=idx)
            elif not in_tr:
                upd, new_state = _health.apply_skip_eager(
                    upd, state, new_state)
        return upd, new_state

    return type(tx)(tx.init, update)


def _resolve_compression(compression):
    """``None`` → the ``HOROVOD_COMPRESSION`` knob's compressor (so the
    launcher/config surface reaches every default-argument call site);
    an explicit compressor always wins."""
    return active_compression() if compression is None else compression


def allreduce_gradients(grads, op: int = Average,
                        axis_name: str | None = None,
                        compression=None, overlap=None):
    """Allreduce a gradient pytree.

    ``axis_name=None`` resolves to the configured data mesh's ``dp``
    axis (docs/mesh.md), else the flat world axis ``"hvd"``.

    In-trace: one grouped psum (XLA fuses into large ICI transfers);
    ``Compression.int8`` routes through the fused quantized reduction,
    and ``overlap`` (default: the ``HOROVOD_OVERLAP`` knob) swaps the
    monolithic collective for the bucketed ppermute ring schedule
    (:mod:`horovod_tpu.ops.overlap`) so communication hides behind
    compute.  Eager: leaves grouped by dtype, each group raveled into
    one flat buffer -> one negotiated fused collective per dtype
    (tensor fusion, reference ``fusion_buffer_manager.h``); the eager
    wire applies the ``HOROVOD_COMPRESSION`` / ``HOROVOD_OVERLAP``
    knobs inside the negotiated program (per-call arguments cannot
    guarantee cross-rank agreement there — the knobs are validated at
    the round-0 handshake).
    """
    compression = _resolve_compression(compression)
    axis_name = _pmesh.resolve_axis(axis_name)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    if _in_trace(leaves):
        reduced = _coll.grouped_allreduce(leaves, axis_name=axis_name,
                                          op=op, compression=compression,
                                          overlap=overlap)
        return jax.tree_util.tree_unflatten(treedef, reduced)
    _check_eager_mesh()
    # Quantized wire on the eager path is knob-driven inside the
    # negotiated program (xla_exec); the per-leaf compressor must be a
    # pass-through here.
    eager_comp = Compression.none if is_quantized(compression) \
        else compression
    return jax.tree_util.tree_unflatten(
        treedef, _eager_fused_pytree_allreduce(leaves, op, eager_comp))


def allreduce_gradients_with_feedback(grads, residuals, op: int = Average,
                                      axis_name: str | None = None,
                                      overlap=None, compression=None):
    """Lossy (int8/int4/topk) gradient allreduce with error feedback:
    returns ``(reduced, new_residuals)``.  Last step's residuals are
    re-injected before reduction; the new residuals carry this step's
    local compression error (see :mod:`horovod_tpu.ops.quantization`).
    ``compression=None`` resolves from the ``HOROVOD_COMPRESSION``
    knob, defaulting to int8 when the knob names a non-lossy mode (this
    entry point exists for the EF contract).  In-trace only — the eager
    negotiated program does not expose the local compression error, so
    eager calls reduce without feedback and return the residuals
    unchanged."""
    compression = _resolve_compression(compression)
    axis_name = _pmesh.resolve_axis(axis_name)
    if not is_quantized(compression):
        compression = Compression.int8
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads, residuals
    if not _in_trace(leaves):
        return (allreduce_gradients(grads, op=op, axis_name=axis_name,
                                    compression=compression),
                residuals)
    injected = _quant.apply_error_feedback(grads, residuals)
    ileaves = jax.tree_util.tree_flatten(injected)[0]
    outs, errs = _coll.grouped_quantized_allreduce(
        ileaves, axis_name=axis_name, op=op, with_error=True,
        overlap=overlap, mode=wire_mode(compression))
    return (jax.tree_util.tree_unflatten(treedef, outs),
            jax.tree_util.tree_unflatten(treedef, errs))


def _fused_pytree_collective(leaves, submit_async):
    """Shared eager fusion: group leaves by dtype, ravel each group into
    one flat buffer, run one async collective per group via
    ``submit_async(flat, label) -> handle``, split results back."""
    groups: dict[Any, list[int]] = {}
    leaves = [jnp.asarray(l) for l in leaves]
    for i, leaf in enumerate(leaves):
        groups.setdefault(np.dtype(leaf.dtype), []).append(i)
    out: list[Any] = [None] * len(leaves)
    handles = []
    for dtype, idxs in groups.items():
        flat = (leaves[idxs[0]].reshape(-1) if len(idxs) == 1 else
                jnp.concatenate([leaves[i].reshape(-1) for i in idxs]))
        _M_FUSED_BYTES.set(int(flat.size) * dtype.itemsize,
                           dtype=str(dtype))
        handles.append((idxs, submit_async(flat, f"{dtype}.{len(idxs)}")))
    for idxs, h in handles:
        red = _eager.synchronize(h)
        off = 0
        for i in idxs:
            size = int(np.prod(leaves[i].shape)) if leaves[i].ndim else 1
            out[i] = red[off:off + size].reshape(leaves[i].shape)
            off += size
    return out


def _eager_fused_pytree_allreduce(leaves, op, compression,
                                  scope: str | None = None):
    # Scoped local-SGD reductions ride the name-prefix wire contract
    # (controller.reduction_scope, docs/local-sgd.md): the negotiated
    # names pin every rank's program to the same (local | cross)
    # sub-axis, and the controller never fuses across scopes.
    prefix = "grad_buffer" if scope is None else f"localsgd.{scope}"
    return _fused_pytree_collective(
        leaves,
        lambda flat, label: _eager.allreduce_async(
            flat, op=op, name=f"{prefix}.{label}",
            compression=compression))


class _AccumulationState(NamedTuple):
    counter: jnp.ndarray
    accum: Any
    inner_state: Any


class _FeedbackState(NamedTuple):
    """Optimizer state wrapper carrying the persistent error-feedback
    residual pytree for quantized (int8) gradient reduction."""
    residual: Any
    inner_state: Any


def _resolve_zero_stage(zero_stage, sharded) -> int:
    """Resolve the ZeRO stage for a DistributedOptimizer: an explicit
    ``zero_stage`` wins (and must agree with an explicit ``sharded``);
    the legacy ``sharded`` boolean pins stage 1/0 exactly; otherwise the
    ``HOROVOD_ZERO_STAGE`` knob applies, with ``HOROVOD_SHARDED_OPTIMIZER``
    kept as the stage-1 spelling it always was."""
    if zero_stage is not None:
        stage = int(zero_stage)
        if stage not in (0, 1, 2, 3):
            raise HorovodTpuError(
                f"zero_stage must be 0..3, got {zero_stage!r} "
                "(0 replicated, 1 sharded optimizer state, 2 + sharded "
                "gradients, 3 + sharded parameters; docs/zero.md)")
        if sharded is not None and bool(sharded) != (stage >= 1):
            raise HorovodTpuError(
                f"conflicting DistributedOptimizer arguments: "
                f"sharded={sharded!r} but zero_stage={stage} "
                f"({'implies' if stage >= 1 else 'disables'} sharding); "
                "drop the legacy sharded= argument.")
        return stage
    if sharded is not None:
        return 1 if sharded else 0
    stage = int(_config.get("zero_stage"))
    if stage not in (0, 1, 2, 3):
        raise HorovodTpuError(
            f"HOROVOD_ZERO_STAGE must be 0..3, got {stage!r}")
    if stage == 0 and bool(_config.get("sharded_optimizer")):
        stage = 1
    return stage


def _zero_chunks(chunks=None) -> int:
    """Bucket count of the ZeRO-2/3 pipelines (scatter of gradients as
    they form, prefetch of parameters under the forward)."""
    if chunks is not None:
        return max(1, int(chunks))
    return max(1, int(_config.get("zero_prefetch_chunks")))


def _leaf_nbytes(leaves) -> int:
    return int(sum(
        (int(np.prod(l.shape)) if getattr(l, "ndim", 0) else 1)
        * np.dtype(l.dtype).itemsize for l in leaves))


def _stamp_zero_bytes(stage: int, layout, inner_state) -> None:
    """Per-chip residency gauges from the static layout (trace-safe:
    everything here is a Python int)."""
    try:
        pbytes = gbytes = 0
        for g, key in enumerate(layout.keys):
            item = jnp.dtype(key).itemsize
            total = sum(layout.sizes[g])
            pbytes += (layout.shard[g] if stage >= 3 else total) * item
            gbytes += (layout.shard[g] if stage >= 2
                       else layout.padded[g]) * item
        _M_ZERO_PARAM_BYTES.set(pbytes)
        _M_ZERO_GRAD_BYTES.set(gbytes)
        _M_ZERO_OPT_BYTES.set(
            _leaf_nbytes(jax.tree_util.tree_leaves(inner_state)))
    except Exception:  # pragma: no cover — metrics must never cost a step
        pass


def _stamp_zero_bytes_replicated(params, state) -> None:
    try:
        n = _leaf_nbytes(jax.tree_util.tree_leaves(params))
        _M_ZERO_PARAM_BYTES.set(n)
        _M_ZERO_GRAD_BYTES.set(n)
        _M_ZERO_OPT_BYTES.set(
            _leaf_nbytes(jax.tree_util.tree_leaves(state)))
    except Exception:  # pragma: no cover
        pass


# ---------------------------------------------------------------------------
# ZeRO-1/2 sharded weight update (arXiv:2004.13336 and beyond):
# reduce-scatter the fused gradient buffers, run the wrapped optimizer
# on only the rank-local 1/world_size shard (optimizer state — Adam
# moments etc. — is initialized and carried shard-local), allgather the
# update shards.  Stage 2 keeps the gradients shard-resident too: the
# fused buffers are scattered bucket-by-bucket as they form and no
# full-size fused gradient buffer ever materializes (docs/zero.md).
# ---------------------------------------------------------------------------


class _ShardLayout(NamedTuple):
    """Static fused-buffer layout shared by init and update: per dtype
    group, the member leaf indices and flat sizes, the buffer length
    padded to a multiple of world size, and the per-rank shard length."""
    keys: tuple      # dtype names, insertion (leaf) order
    idxs: tuple      # tuple[int, ...] per group
    sizes: tuple     # tuple[int, ...] per group (flat leaf sizes)
    padded: tuple    # int per group
    shard: tuple     # int per group (padded // world)


@jax.tree_util.register_pytree_node_class
class _ShardedState:
    """Optimizer state for the sharded update.  ``inner_state`` is the
    wrapped optimizer's state over the rank-local shard buffers (the
    ~1/world_size optimizer-state footprint ZeRO-1 exists for);
    ``residual`` is the int8 error-feedback residual over the full
    fused buffers (input-side EF needs the full local quantization
    error — it is one flat fp32 buffer per float group, not a
    leaf-per-parameter tree; ``None`` without quantization); ``layout``
    is the static :class:`_ShardLayout` (pytree aux data)."""

    def __init__(self, inner_state, residual, layout: _ShardLayout):
        self.inner_state = inner_state
        self.residual = residual
        self.layout = layout

    def tree_flatten(self):
        return (self.inner_state, self.residual), self.layout

    @classmethod
    def tree_unflatten(cls, layout, children):
        return cls(children[0], children[1], layout)

    def __repr__(self) -> str:  # keep state dumps readable
        return (f"_ShardedState(inner_state={self.inner_state!r}, "
                f"residual={self.residual!r})")


def _is_sharded_state(x) -> bool:
    return isinstance(x, _ShardedState)


def _contains_sharded_state(tree) -> bool:
    return any(_is_sharded_state(l) for l in
               jax.tree_util.tree_leaves(tree, is_leaf=_is_sharded_state))


def _shard_layout(leaves, n: int) -> _ShardLayout:
    groups: dict = {}
    for i, leaf in enumerate(leaves):
        groups.setdefault(str(jnp.dtype(leaf.dtype)), []).append(i)
    keys, idxs, sizes, padded, shard = [], [], [], [], []
    for key, ii in groups.items():
        sz = tuple(int(np.prod(leaves[i].shape)) if leaves[i].ndim else 1
                   for i in ii)
        total = sum(sz)
        p = total + (-total) % n
        keys.append(key)
        idxs.append(tuple(ii))
        sizes.append(sz)
        padded.append(p)
        shard.append(p // n)
    return _ShardLayout(tuple(keys), tuple(idxs), tuple(sizes),
                        tuple(padded), tuple(shard))


def _fuse_group(leaves, layout: _ShardLayout, g: int):
    """One flat buffer for group ``g``, zero-padded to the layout's
    world-divisible length."""
    flats = [leaves[i].reshape(-1) for i in layout.idxs[g]]
    flat = flats[0] if len(flats) == 1 else jnp.concatenate(flats)
    pad = layout.padded[g] - flat.shape[0]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def _shard_position(axis_name):
    """(shard index, world size, in_trace) for the current regime.

    The axis binding — not leaf tracer-ness — decides the regime:
    inside ``shard_map`` the gradient leaves can be trace-constants
    (closed-over parameters) while the mesh axis is still what shards
    the update, so probe ``lax.axis_index`` first and fall back to the
    process rank only when the axis is unbound (the eager
    one-process-per-chip regime, or state init outside the step)."""
    try:
        return (_coll.shard_index(axis_name),
                _quant._axis_prod(axis_name), True)
    except Exception:
        pass
    _check_eager_mesh()
    st = _basics.state()
    if st.initialized:
        return st.rank, st.size, False
    return 0, 1, False


def _bucketed_scatter_group(leaves, layout, g: int, n: int, axis_name,
                            quantized, with_error: bool,
                            residual, overlap=None, chunks=None,
                            scope: str = "hvd_zero2_rs"):
    """Stage-2 gradient scatter for dtype group ``g``: the fused buffer
    is never concatenated — K bucket pieces (column slices of the
    ``(n, L)`` segment view) are assembled span-wise straight from the
    gradient leaves (:func:`~horovod_tpu.ops.collectives
    .fuse_bucket_piece`), reduce-scattered one by one in a
    barrier-separated chain (so XLA neither re-fuses them into one
    full-size buffer nor hoists every transfer to the front), and only
    the concatenation of the rank-local bucket shards — the 1/n shard —
    is ever a live value.  Error-feedback residual slices ride into the
    pieces via ``inject`` (the lossy EF contract is unchanged; the
    residual itself is optimizer state and stays full-size, as under
    ZeRO-1).  ``quantized`` accepts the historical bool or a wire-mode
    string, and each bucket of the chain may carry its OWN mode
    (``HOROVOD_BUCKET_COMPRESSION`` — the adaptive stack,
    docs/compression.md).  Returns ``(shard, err)`` with the exact
    ``_scatter_flat_buffer`` layout."""
    from jax import lax

    from horovod_tpu.ops import overlap as _ovl
    from horovod_tpu.ops import quantization as _quantz

    L = layout.padded[g] // n
    bounds = _ovl.bucket_bounds(L, _zero_chunks(chunks))
    lossy_any = _quantz.norm_mode(quantized) in _quantz.LOSSY_MODES
    dtype = jnp.float32 if lossy_any else jnp.dtype(layout.keys[g])
    bmodes = _ovl.resolve_bucket_modes(None, len(bounds), quantized,
                                       dtype)
    inject = None
    if residual is not None:
        inject = lambda lo, hi: residual[lo:hi]  # noqa: E731
    # Already bucketed here: one ring (overlap on) OR one monolithic
    # psum_scatter (off) per bucket — never a second level of
    # sub-buckets (mirrors prefetched_gather_flat_shard's gather side).
    ring = _ovl.enabled(overlap)
    shards: list = [None] * len(bounds)
    errs: list = [None] * len(bounds)
    prev = None
    for k, (s, e) in enumerate(bounds):
        piece = _coll.fuse_bucket_piece(
            leaves, layout.idxs[g], layout.sizes[g], layout.padded[g],
            n, s, e, dtype, inject=inject)
        if prev is not None:
            piece, shards[prev] = lax.optimization_barrier(
                (piece, shards[prev]))
        with jax.named_scope(f"{scope}{k}"):
            if ring:
                shards[k], errs[k] = _ovl.scatter_bucket(
                    piece, axis_name, quantized=bmodes[k],
                    with_error=with_error)
            else:
                shards[k], errs[k] = _coll._scatter_flat_buffer(
                    piece, axis_name, quantized=bmodes[k],
                    with_error=with_error, overlap=False)
            shards[k] = shards[k].astype(dtype)
        prev = k
    shard = shards[0] if len(shards) == 1 else jnp.concatenate(shards)
    err = None
    if with_error:
        err = _ovl._concat_columns(
            _ovl._zero_errs(errs, bounds, n), n)
    return shard, err


def _bucketed_eager_scatter(leaves, layout, op: int, chunks=None):
    """Stage-2 scatter on the negotiated eager wire: one reducescatter
    response per bucket piece (assembled span-wise, so the full fused
    buffer never materializes host-side either); bucket count rides the
    round-0 handshake, so every rank submits the same K names."""
    from horovod_tpu.ops import overlap as _ovl

    st = _basics.state()
    n = st.size if st.initialized else 1
    handles = []
    for g, key in enumerate(layout.keys):
        L = layout.padded[g] // n
        bounds = _ovl.bucket_bounds(L, _zero_chunks(chunks))
        hs = []
        for k, (s, e) in enumerate(bounds):
            piece = _coll.fuse_bucket_piece(
                leaves, layout.idxs[g], layout.sizes[g],
                layout.padded[g], n, s, e, jnp.dtype(key))
            hs.append(_eager.reducescatter_async(
                piece, op=op,
                name=f"shard_rs.{key}.{layout.padded[g]}"
                     f".{k}of{len(bounds)}"))
        handles.append(hs)
    return [jnp.concatenate([_eager.synchronize(h) for h in hs])
            if len(hs) > 1 else _eager.synchronize(hs[0])
            for hs in handles]


def _bucketed_eager_gather(upd_shards, layout, chunks=None):
    """Stage-2 gather on the negotiated eager wire: one allgather per
    bucket of the update shard; returns ``(bucket_outs, bounds)`` per
    group for :func:`~horovod_tpu.ops.collectives.leaf_from_buckets`
    reassembly (no full fused update buffer either)."""
    from horovod_tpu.ops import overlap as _ovl

    per_group = []
    for g, key in enumerate(layout.keys):
        bounds = _ovl.bucket_bounds(int(upd_shards[g].shape[0]),
                                    _zero_chunks(chunks))
        hs = [_eager.allgather_async(
            upd_shards[g][s:e],
            name=f"shard_ag.{key}.{layout.padded[g]}"
                 f".{k}of{len(bounds)}")
            for k, (s, e) in enumerate(bounds)]
        per_group.append(([_eager.synchronize(h) for h in hs], bounds))
    return per_group


def _make_sharded_fns(init_fn, update_fn, op: int, axis_name,
                      compression, overlap=None, zero_stage: int = 1,
                      fused_spec=None):
    """(init, update) pair implementing the sharded weight update around
    the wrapped optimizer's ``init_fn``/``update_fn``.  With ``overlap``
    (default: the ``HOROVOD_OVERLAP`` knob) the scatter and gather run
    as bucketed ppermute ring pipelines (``HOROVOD_OVERLAP_CHUNKS``
    buckets, barrier-separated) instead of one monolithic
    psum_scatter/all_gather per dtype group — the shard layout is
    bucket-independent, so state, checkpoints and specs are identical
    either way.

    ``zero_stage=2`` additionally keeps gradients shard-resident: the
    fused buffer is never concatenated — ``HOROVOD_ZERO_PREFETCH_CHUNKS``
    bucket pieces are assembled span-wise straight from the gradient
    leaves and reduce-scattered as they form, and the update shards
    come back bucket-wise with per-leaf reassembly, so no full-size
    fused buffer exists on either side of the update (the shard itself
    and the layout are bit-identical to stage 1)."""
    from jax import lax

    from horovod_tpu.ops import overlap as _ovl

    quantized = is_quantized(compression)
    qmode = wire_mode(compression) if quantized else "none"

    def _float_group(key: str) -> bool:
        return jnp.issubdtype(jnp.dtype(key), jnp.floating)

    def _param_shards(params, layout, idx):
        if params is None:
            return None
        pleaves = jax.tree_util.tree_leaves(params)
        shards = []
        for g in range(len(layout.keys)):
            buf = _fuse_group(pleaves, layout, g)
            shards.append(lax.dynamic_slice_in_dim(
                buf, idx * layout.shard[g], layout.shard[g]))
        return shards

    def init(params):
        leaves = jax.tree_util.tree_leaves(params)
        idx, n, in_tr = _shard_position(axis_name)
        layout = _shard_layout(leaves, n)
        shards = []
        for g in range(len(layout.keys)):
            buf = _fuse_group(leaves, layout, g)
            shards.append(lax.dynamic_slice_in_dim(
                buf, idx * layout.shard[g], layout.shard[g]))
        residual = None
        if quantized and in_tr:
            # Error feedback runs only in-trace (the eager negotiated
            # program does not expose the local quantization error), so
            # eager-initialized state must not carry dead full-model
            # fp32 residual buffers — the 1/N-memory goal this mode
            # exists for.
            residual = [jnp.zeros((layout.padded[g] if _float_group(k)
                                   else 0,), jnp.float32)
                        for g, k in enumerate(layout.keys)]
        inner = init_fn(shards)
        _stamp_zero_bytes(zero_stage, layout, inner)
        return _ShardedState(inner, residual, layout)

    def update(grads, state, params=None, **extra):
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        idx, n, in_tr = _shard_position(axis_name)
        if not in_tr and _in_trace(leaves):
            raise HorovodTpuError(
                "sharded optimizer update traced without the "
                f"{axis_name!r} mesh axis in scope; run the step inside "
                "shard_map over that axis (or call it eagerly).")
        layout = _shard_layout(leaves, n)
        if layout != state.layout:
            raise HorovodTpuError(
                "sharded optimizer state layout does not match the "
                "gradient pytree (did world size or parameter "
                f"dtypes/shapes change?): {state.layout} vs {layout}")
        gshards: list = []
        new_res = list(state.residual) if state.residual is not None \
            else None
        ef = new_res is not None  # EF state exists (in-trace init)
        if in_tr:
            for g, key in enumerate(layout.keys):
                q = quantized and _float_group(key)
                if zero_stage >= 2 and n > 1:
                    # Stage 2: bucket pieces assembled span-wise from
                    # the gradient leaves — the full fused buffer never
                    # materializes; only the 1/n shard is resident.
                    res = state.residual[g] if (q and ef) else None
                    shard, err = _bucketed_scatter_group(
                        leaves, layout, g, n, axis_name,
                        qmode if q else False, q and ef,
                        res, overlap=overlap)
                else:
                    buf = _fuse_group(leaves, layout, g)
                    if q and ef:
                        buf = buf.astype(jnp.float32) + state.residual[g]
                    shard, err = _coll._scatter_flat_buffer(
                        buf, axis_name, quantized=qmode if q else False,
                        with_error=q and ef, overlap=overlap)
                if err is not None:
                    new_res[g] = err
                    if zero_stage >= 2 and n > 1:
                        _rchunks = _zero_chunks()
                    elif _ovl.enabled(overlap):
                        _rchunks = _ovl.configured_chunks()
                    else:
                        _rchunks = 1
                    _report_bucket_residual_ratios(
                        err, shard, n, axis_name, chunks=_rchunks)
                gshards.append(shard)
        elif zero_stage >= 2:
            gshards = _bucketed_eager_scatter(leaves, layout, op)
        else:
            # Negotiated eager wire: one fused reduce-scatter per dtype
            # group; the HOROVOD_COMPRESSION knob applies inside the
            # negotiated program (like the eager allreduce path, the
            # local quantization error is not exposed, so the residual
            # rides along unchanged).
            handles = []
            for g, key in enumerate(layout.keys):
                buf = _fuse_group(leaves, layout, g)
                handles.append(_eager.reducescatter_async(
                    buf, op=op,
                    name=f"shard_rs.{key}.{layout.padded[g]}"))
            gshards = [_eager.synchronize(h)
                       for h in handles]
        # The optimizer tail.  ``gshards`` holds the RAW post-scatter
        # buffers (wire dtype; summed in-trace, op-applied on the
        # negotiated eager wire) — unscale and group-dtype cast belong
        # to the tail so the fused kernel can collapse them into the
        # update (docs/zero.md).  navg: the in-trace scatter returns
        # the SUM, so Average divides by n here; the eager wire
        # already applied the op.
        navg = n if (op == Average and in_tr) else 1
        fused = None
        if fused_spec is not None:
            fused = _fused.fused_update_groups(
                fused_spec, gshards, state.inner_state, navg,
                [jnp.dtype(k) for k in layout.keys])
        if fused is not None:
            upd_shards, inner = fused
        else:
            cast = []
            for s, key in zip(gshards, layout.keys):
                if navg > 1:
                    s = s / navg
                cast.append(s.astype(jnp.dtype(key)))
            upd_shards, inner = update_fn(
                cast, state.inner_state,
                _param_shards(params, layout, idx), **extra)
        out: list = [None] * len(leaves)
        buckets = None
        fulls: list = []
        if zero_stage >= 2:
            # Stage 2 gather side: update shards come back bucket by
            # bucket and leaves reassemble straight from the bucket
            # outputs — the full fused update buffer never exists.
            if in_tr:
                buckets = [_ovl.prefetched_gather_flat_shard(
                    upd_shards[g], axis_name, chunks=_zero_chunks(),
                    overlap=overlap, scope="hvd_zero2_ag")
                    for g in range(len(layout.keys))]
            else:
                buckets = _bucketed_eager_gather(upd_shards, layout)
        elif in_tr:
            for g in range(len(layout.keys)):
                fulls.append(_coll._gather_flat_shard(
                    upd_shards[g], axis_name, overlap=overlap))
        else:
            handles = [_eager.allgather_async(
                upd_shards[g],
                name=f"shard_ag.{layout.keys[g]}.{layout.padded[g]}")
                for g in range(len(layout.keys))]
            fulls = [_eager.synchronize(h) for h in handles]
        for g in range(len(layout.keys)):
            off = 0
            for i, sz in zip(layout.idxs[g], layout.sizes[g]):
                if buckets is not None:
                    outs_g, bounds_g = buckets[g]
                    flat = _coll.leaf_from_buckets(
                        outs_g, bounds_g, n, layout.shard[g], off, sz)
                else:
                    flat = fulls[g][off:off + sz]
                out[i] = flat.reshape(
                    leaves[i].shape).astype(leaves[i].dtype)
                off += sz
        return (jax.tree_util.tree_unflatten(treedef, out),
                _ShardedState(inner, new_res, layout))

    return init, update


# ---------------------------------------------------------------------------
# ZeRO-3: parameters themselves live as 1/world flat shards between
# steps; the forward gathers them bucket-wise with prefetch (the
# overlap engine run in reverse, ops/overlap.prefetched_gather_flat_shard)
# and the backward reduce-scatters gradients straight into shard form
# via the gather's custom VJP — no full fused parameter or gradient
# buffer is ever resident.  See docs/zero.md.
# ---------------------------------------------------------------------------


@jax.tree_util.register_pytree_node_class
class Zero3Params:
    """Stage-3 resident parameter form: per-dtype-group 1/world flat
    shard buffers (this rank's contiguous segment of the padded fused
    buffer — the exact :class:`_ShardLayout` segment the ZeRO-1/2
    optimizer state uses), plus the static metadata needed to rebuild
    the full pytree (layout, treedef, per-leaf shapes).  A registered
    pytree: ``jax.grad`` of a loss over a ``Zero3Params`` returns
    shard-shaped cotangents (via :func:`zero3_full_params`'s custom
    VJP), and ``optax.apply_updates`` applies shard-shaped updates
    directly."""

    def __init__(self, shards, layout: _ShardLayout, treedef, shapes):
        self.shards = list(shards)
        self.layout = layout
        self.treedef = treedef
        self.shapes = tuple(tuple(s) for s in shapes)

    def tree_flatten(self):
        return tuple(self.shards), (self.layout, self.treedef,
                                    self.shapes)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(list(children), *aux)

    def __repr__(self) -> str:
        return (f"Zero3Params(groups={list(self.layout.keys)}, "
                f"shard_elems={list(self.layout.shard)})")


def _is_zero3(x) -> bool:
    return isinstance(x, Zero3Params)


def _contains_zero3(tree) -> bool:
    return any(_is_zero3(l) for l in
               jax.tree_util.tree_leaves(tree, is_leaf=_is_zero3))


def zero3_shard_params(params, axis_name: str | None = None) -> Zero3Params:
    """Slice a full parameter pytree into this rank's stage-3 resident
    form (:class:`Zero3Params`).  In-trace: the bound mesh axis picks
    the segment; eager: the process rank does.  One-time at setup (or
    re-form) — the full pytree exists here anyway; from then on only
    the 1/world shards persist."""
    axis_name = _pmesh.resolve_axis(axis_name)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    if not leaves:
        raise HorovodTpuError("zero3_shard_params: empty parameter tree")
    idx, n, _ = _shard_position(axis_name)
    layout = _shard_layout(leaves, n)
    shapes = tuple(tuple(l.shape) for l in leaves)
    from jax import lax

    shards = []
    for g in range(len(layout.keys)):
        buf = _fuse_group(leaves, layout, g)
        shards.append(lax.dynamic_slice_in_dim(
            buf, idx * layout.shard[g], layout.shard[g]))
    return Zero3Params(shards, layout, treedef, shapes)


def zero3_full_params(zp: Zero3Params, axis_name: str | None = None,
                      compression=None, chunks: int | None = None,
                      overlap: bool | None = None):
    """Materialize the full parameter pytree from stage-3 shards for
    the forward pass — bucket-wise, with prefetch.

    In-trace the gather runs as ``HOROVOD_ZERO_PREFETCH_CHUNKS``
    barrier-chained bucket allgathers (``hvd_zero3_ag<k>`` named
    scopes; the ppermute ring under ``HOROVOD_OVERLAP``), each layer's
    parameters sliced out of its bucket's output so XLA frees bucket
    ``k`` while bucket ``k+1``'s transfer is still in flight — at no
    point does one full-size fused parameter buffer exist.
    Differentiating through it (``jax.grad`` of a loss w.r.t. ``zp``)
    triggers the custom VJP: cotangents are reduce-scattered bucket by
    bucket straight into shard form (under ``compression=int8`` the
    scatter rides the block-scaled wire, without error feedback), which
    is ZeRO-2/3 gradient sharding for free — pass the result straight
    to the stage-3 optimizer's ``update``.  Eager (one process per
    chip): negotiated per-bucket allgathers; gradients are computed
    against the full tree and the optimizer scatters them instead."""
    compression = _resolve_compression(compression)
    axis_name = _pmesh.resolve_axis(axis_name)
    idx, n, in_tr = _shard_position(axis_name)
    if not in_tr or n == 1:
        return _zero3_full_eager(zp, n, chunks)
    return _zero3_full_traced(zp, axis_name, n, compression, chunks,
                              overlap)


def _zero3_unfuse(bucket_sets, lay, shapes):
    """Full leaves from per-group ``(bucket_outs, bounds, n)`` gather
    results — per-leaf slicing, never a full fused buffer."""
    out = [None] * len(shapes)
    for g in range(len(lay.keys)):
        outs_g, bounds_g, n = bucket_sets[g]
        off = 0
        for i, sz in zip(lay.idxs[g], lay.sizes[g]):
            out[i] = _coll.leaf_from_buckets(
                outs_g, bounds_g, n, lay.shard[g], off,
                sz).reshape(shapes[i])
            off += sz
    return out


def _zero3_full_eager(zp: Zero3Params, n: int, chunks=None):
    from horovod_tpu.ops import overlap as _ovl

    lay = zp.layout
    bucket_sets = []
    for g in range(len(lay.keys)):
        bounds = _ovl.bucket_bounds(lay.shard[g], _zero_chunks(chunks))
        if n == 1:
            outs = [zp.shards[g][s:e] for s, e in bounds]
        else:
            handles = [_eager.allgather_async(
                zp.shards[g][s:e],
                name=f"zero3_ag.{lay.keys[g]}.{lay.padded[g]}"
                     f".{k}of{len(bounds)}")
                for k, (s, e) in enumerate(bounds)]
            outs = [_eager.synchronize(h) for h in handles]
        bucket_sets.append((outs, bounds, n))
    return jax.tree_util.tree_unflatten(
        zp.treedef, _zero3_unfuse(bucket_sets, lay, zp.shapes))


def _zero3_full_traced(zp: Zero3Params, axis_name, n: int, compression,
                       chunks, overlap):
    from horovod_tpu.ops import overlap as _ovl

    lay, treedef, shapes = zp.layout, zp.treedef, zp.shapes
    quantized = is_quantized(compression)
    qmode = wire_mode(compression) if quantized else "none"
    kchunks = _zero_chunks(chunks)

    def impl(shards):
        bucket_sets = []
        for g in range(len(lay.keys)):
            outs, bounds = _ovl.prefetched_gather_flat_shard(
                shards[g], axis_name, chunks=kchunks, overlap=overlap)
            bucket_sets.append((outs, bounds, n))
        return jax.tree_util.tree_unflatten(
            treedef, _zero3_unfuse(bucket_sets, lay, shapes))

    @jax.custom_vjp
    def gather(shards):
        return impl(shards)

    def fwd(shards):
        return impl(shards), None

    def bwd(_, ct):
        # The transpose of the bucketed allgather IS the ZeRO-2
        # bucketed reduce-scatter: per-rank cotangents of the full
        # pytree come back as this rank's summed 1/n shard per dtype
        # group, assembled span-wise so no full fused gradient buffer
        # materializes (named scopes hvd_zero3_rs<k>).
        cleaves = [jnp.asarray(c) for c in
                   jax.tree_util.tree_leaves(ct)]
        gshards = []
        for g, key in enumerate(lay.keys):
            q = quantized and jnp.issubdtype(jnp.dtype(key),
                                             jnp.floating)
            shard, _ = _bucketed_scatter_group(
                cleaves, lay, g, n, axis_name, qmode if q else False,
                False, None, overlap=overlap, chunks=kchunks,
                scope="hvd_zero3_rs")
            gshards.append(shard.astype(jnp.dtype(key)))
        return (gshards,)

    gather.defvjp(fwd, bwd)
    return gather(list(zp.shards))


def _make_zero3_fns(init_fn, update_fn, op: int, axis_name, compression,
                    overlap=None, fused_spec=None):
    """(init, update) pair for the stage-3 optimizer: the training
    loop's "params" are the :class:`Zero3Params` shards; updates come
    back shard-shaped (NO allgather of updates — the next forward's
    prefetched gather is the only place full parameters transiently
    exist) and apply directly via ``optax.apply_updates``."""
    quantized = is_quantized(compression)
    qmode = wire_mode(compression) if quantized else "none"

    def init(params):
        if not _is_zero3(params):
            raise HorovodTpuError(
                "zero_stage=3: DistributedOptimizer.init expects the "
                "shard-resident parameter form — call "
                "hvd.zero3_shard_params(params) once at setup and "
                "train on the returned Zero3Params (docs/zero.md).")
        inner = init_fn(list(params.shards))
        _stamp_zero_bytes(3, params.layout, inner)
        return _ShardedState(inner, None, params.layout)

    def update(grads, state, params=None, **extra):
        idx, n, in_tr = _shard_position(axis_name)
        aux_src = params if _is_zero3(params) else (
            grads if _is_zero3(grads) else None)
        if aux_src is None:
            raise HorovodTpuError(
                "zero_stage=3 update needs the Zero3Params metadata: "
                "pass params=<the Zero3Params> (or gradients produced "
                "by differentiating through zero3_full_params).")
        layout = aux_src.layout
        if layout != state.layout:
            raise HorovodTpuError(
                "zero_stage=3 optimizer state layout does not match "
                "the parameter shards (did world size or parameter "
                f"dtypes/shapes change?): {state.layout} vs {layout}")
        if _is_zero3(grads):
            # Shard-resident cotangents from zero3_full_params's VJP:
            # already summed across ranks by the bucketed scatter.
            gshards = list(grads.shards)
        elif in_tr:
            leaves = jax.tree_util.tree_flatten(grads)[0]
            gshards = []
            for g, key in enumerate(layout.keys):
                q = quantized and jnp.issubdtype(jnp.dtype(key),
                                                 jnp.floating)
                shard, _ = _bucketed_scatter_group(
                    leaves, layout, g, n, axis_name,
                    qmode if q else False, False, None,
                    overlap=overlap, scope="hvd_zero3_rs")
                gshards.append(shard)
        else:
            leaves = jax.tree_util.tree_flatten(grads)[0]
            gshards = _bucketed_eager_scatter(leaves, layout, Sum)
        # Optimizer tail on the raw summed shards: fused kernel when a
        # FusedSpec is attached (unscale + cast + moment update + step
        # in one launch per group), the unfused divide/cast/optax
        # chain otherwise — bit-exact either way (docs/zero.md).
        navg = n if op == Average else 1
        fused = None
        if fused_spec is not None:
            fused = _fused.fused_update_groups(
                fused_spec, gshards, state.inner_state, navg,
                [jnp.dtype(k) for k in layout.keys])
        if fused is not None:
            upd_shards, inner = fused
        else:
            if navg > 1:
                gshards = [s / navg for s in gshards]
            gshards = [s.astype(jnp.dtype(key))
                       for s, key in zip(gshards, layout.keys)]
            pshards = list(params.shards) if _is_zero3(params) else None
            upd_shards, inner = update_fn(gshards, state.inner_state,
                                          pshards, **extra)
        upd = Zero3Params(
            [u.astype(jnp.dtype(key))
             for u, key in zip(upd_shards, layout.keys)],
            aux_src.layout, aux_src.treedef, aux_src.shapes)
        return upd, _ShardedState(inner, None, layout)

    return init, update


def zero3_params_specs(zp: Zero3Params, axis_name: str = "hvd"):
    """``PartitionSpec`` tree for threading stage-3 shards through
    ``jit``/``shard_map``: every shard buffer is ``P(axis_name)`` (the
    global view is the full fused buffer, rank ``r`` holding segment
    ``r``)."""
    from jax.sharding import PartitionSpec as P

    return Zero3Params([P(axis_name)] * len(zp.shards), zp.layout,
                       zp.treedef, zp.shapes)


def zero3_params_to_global(zp: Zero3Params, mesh=None,
                           axis_name: str = "hvd"):
    """Assemble this process's stage-3 shards into global arrays over
    the world mesh (the :func:`sharded_state_to_global` analog for
    parameters).  No-op at size 1."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    st = _basics.state()
    if not st.initialized or st.size == 1:
        return zp
    mesh = mesh if mesh is not None else st.mesh
    shards = []
    for leaf in zp.shards:
        leaf = jnp.asarray(leaf)
        local = jax.device_put(leaf, st.lead_device)
        shards.append(jax.make_array_from_single_device_arrays(
            (st.size * leaf.shape[0],),
            NamedSharding(mesh, P(axis_name)), [local]))
    return Zero3Params(shards, zp.layout, zp.treedef, zp.shapes)


class _HostZero3Params:
    """Host-side commit snapshot of a :class:`Zero3Params`: the FULL
    parameter pytree as numpy (world-size-independent, so an elastic
    re-form re-shards it for any new world).  A plain opaque class —
    not a pytree — so blind ``tree_map`` passes over a commit snapshot
    leave it intact.  Picklable; rides the elastic resync broadcast."""

    def __init__(self, tree):
        self.tree = tree


def _is_host_zero3(x) -> bool:
    return isinstance(x, _HostZero3Params)


def zero3_params_to_host(zp: Zero3Params, gather=None):
    """Allgather stage-3 shards into the full parameter pytree on host
    (elastic commit points; collective at world > 1 — every rank must
    call it).  ``gather`` overrides the eager allgather (tests)."""
    st = _basics.state()

    def default_gather(leaf):
        if st.initialized and st.size > 1:
            return _eager.allgather(jnp.asarray(leaf).reshape(-1))
        return jnp.asarray(leaf)

    gather = default_gather if gather is None else gather
    lay = zp.layout
    leaves = [None] * len(zp.shapes)
    for g in range(len(lay.keys)):
        full = np.asarray(gather(zp.shards[g]))
        off = 0
        for i, sz in zip(lay.idxs[g], lay.sizes[g]):
            leaves[i] = full[off:off + sz].reshape(zp.shapes[i])
            off += sz
    return _HostZero3Params(
        jax.tree_util.tree_unflatten(zp.treedef, leaves))


def _default_shard_world() -> int:
    """Default shard count for host re-shard helpers: the data mesh's
    dp extent when one is configured (ZeRO shards are dp-scoped,
    docs/mesh.md), else the world size."""
    if not _basics.state().initialized:
        return 1
    return _basics.data_parallel_size()


def zero3_params_from_host(host: _HostZero3Params,
                           world: int | None = None,
                           rank: int | None = None) -> Zero3Params:
    """Re-shard a :func:`zero3_params_to_host` snapshot for the CURRENT
    world size — the stage-3 half of an elastic re-form (rank ``r`` of
    the new world takes segment ``r`` of the re-padded fused buffers).
    ``world`` defaults to the dp extent when a data mesh is configured
    (shards are dp-scoped), else the world size."""
    st = _basics.state()
    n = world if world is not None else _default_shard_world()
    r = rank if rank is not None else (st.rank if st.initialized else 0)
    tree = jax.tree_util.tree_map(jnp.asarray, host.tree)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    layout = _shard_layout(leaves, n)
    shapes = tuple(tuple(l.shape) for l in leaves)
    shards = []
    for g in range(len(layout.keys)):
        buf = _fuse_group(leaves, layout, g)
        shards.append(buf[r * layout.shard[g]:(r + 1) * layout.shard[g]])
    return Zero3Params(shards, layout, treedef, shapes)


def params_to_host(tree, gather=None):
    """Host snapshot of a parameter tree for elastic commits: plain
    leaves become numpy; :class:`Zero3Params` subtrees allgather into
    their world-independent full form (collective at world > 1)."""
    def one(node):
        if _is_zero3(node):
            return zero3_params_to_host(node, gather)
        return jax.tree_util.tree_map(np.asarray, node)

    return jax.tree_util.tree_map(one, tree, is_leaf=_is_zero3)


def params_from_host(tree, world: int | None = None,
                     rank: int | None = None):
    """Rebuild device parameters from a :func:`params_to_host`
    snapshot, re-sharding stage-3 subtrees for the current world."""
    def one(node):
        if _is_host_zero3(node):
            return zero3_params_from_host(node, world, rank)
        return jax.tree_util.tree_map(jnp.asarray, node)

    return jax.tree_util.tree_map(one, tree, is_leaf=_is_host_zero3)


def sharded_state_specs(opt_state, axis_name: str = "hvd"):
    """``PartitionSpec`` pytree for threading a sharded optimizer state
    through ``jit``/``shard_map`` over the world mesh: shard-buffer
    leaves map to ``P(axis_name)`` (the global view is the full fused
    buffer, rank ``r`` holding segment ``r``); step counters and other
    scalars are replicated ``P()``.  Error-feedback residuals are
    per-rank values — not shards of one global array — and cannot ride
    a spec: thread int8+EF states inside a single shard_map program
    instead (see docs/zero.md)."""
    from jax.sharding import PartitionSpec as P

    def one(node):
        if _is_sharded_state(node):
            if node.residual is not None and \
                    jax.tree_util.tree_leaves(node.residual):
                raise HorovodTpuError(
                    "sharded_state_specs cannot express the int8 "
                    "error-feedback residual (per-rank state, not a "
                    "sharding of one global array); keep the state "
                    "inside one shard_map program for int8+EF.")
            shard_lens = set(node.layout.shard)
            inner = jax.tree_util.tree_map(
                lambda l: (P(axis_name)
                           if getattr(l, "ndim", 0) == 1
                           and l.shape[0] in shard_lens else P()),
                node.inner_state)
            return _ShardedState(inner, None, node.layout)
        return jax.tree_util.tree_map(lambda _: P(), node)

    return jax.tree_util.tree_map(one, opt_state,
                                  is_leaf=_is_sharded_state)


def sharded_state_to_global(opt_state, mesh=None, axis_name: str = "hvd"):
    """Assemble this process's shard-buffer leaves into global arrays
    over the world mesh (rank ``r`` holds segment ``r``) so a sharded
    optimizer state can cross a jit boundary at world size > 1 with the
    specs from :func:`sharded_state_specs`.  No-op at size 1."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    st = _basics.state()
    if not st.initialized or st.size == 1:
        return opt_state
    mesh = mesh if mesh is not None else st.mesh

    def one(node):
        if not _is_sharded_state(node):
            return node
        shard_lens = set(node.layout.shard)

        def g(leaf):
            leaf = jnp.asarray(leaf)
            if leaf.ndim == 1 and leaf.shape[0] in shard_lens:
                local = jax.device_put(leaf, st.lead_device)
                return jax.make_array_from_single_device_arrays(
                    (st.size * leaf.shape[0],),
                    NamedSharding(mesh, P(axis_name)), [local])
            return leaf

        return _ShardedState(jax.tree_util.tree_map(g, node.inner_state),
                             node.residual, node.layout)

    return jax.tree_util.tree_map(one, opt_state,
                                  is_leaf=_is_sharded_state)


class _HostShardedState:
    """Host-side commit snapshot of a :class:`_ShardedState`: the inner
    state with every shard-buffer leaf allgathered into its full fused
    (global) form, plus the layout it was sharded under.  A plain class
    (not a pytree/NamedTuple) on purpose — blind ``tree_map`` passes
    over a commit snapshot must treat it as one opaque leaf.  Picklable,
    so it rides the elastic resync broadcast."""

    def __init__(self, inner, layout: _ShardLayout, had_residual: bool):
        self.inner = inner
        self.layout = layout
        self.had_residual = had_residual


def _is_host_sharded(x) -> bool:
    return isinstance(x, _HostShardedState)


def sharded_state_to_host(opt_state, gather=None):
    """Host snapshot of an optimizer state for elastic commit points
    (docs/elastic.md).  Plain leaves become numpy; ZeRO-1
    :class:`_ShardedState` subtrees have their shard-buffer leaves
    **allgathered** back into the full fused buffers, so a later
    :func:`sharded_state_from_host` can re-shard them to a *different*
    world size (the commit survives rank death).  Collective when the
    state is sharded and the world is >1 — every rank must call it.
    ``gather`` overrides the eager allgather (tests / offline tools)."""
    st = _basics.state()

    def default_gather(leaf):
        if st.initialized and st.size > 1:
            return _eager.allgather(jnp.asarray(leaf).reshape(-1))
        return jnp.asarray(leaf)

    gather = default_gather if gather is None else gather

    def one(node):
        if _is_sharded_state(node):
            shard_lens = {s for s in node.layout.shard if s > 0}

            def g(leaf):
                leaf = jnp.asarray(leaf)
                if leaf.ndim == 1 and leaf.shape[0] in shard_lens:
                    return np.asarray(gather(leaf))
                return np.asarray(leaf)

            inner = jax.tree_util.tree_map(g, node.inner_state)
            return _HostShardedState(inner, node.layout,
                                     node.residual is not None)
        return jax.tree_util.tree_map(np.asarray, node)

    return jax.tree_util.tree_map(one, opt_state,
                                  is_leaf=_is_sharded_state)


def sharded_state_from_host(host_state, world: int | None = None,
                            rank: int | None = None):
    """Rebuild a device optimizer state from a
    :func:`sharded_state_to_host` snapshot, re-slicing ZeRO-1 subtrees
    for the CURRENT world size: commit-point global buffers are
    re-padded to the new world-divisible length and this rank takes its
    dense segment.  Error-feedback residuals restart at zero — the
    compression error accumulated before the commit point is already
    folded into the committed parameters, and a stale residual sized
    for the old world would be layout garbage anyway.  ``world``
    defaults to the dp extent when a data mesh is configured (shards
    are dp-scoped, docs/mesh.md), else the world size."""
    st = _basics.state()
    n = world if world is not None else _default_shard_world()
    r = rank if rank is not None else (st.rank if st.initialized else 0)

    def one(node):
        if _is_host_sharded(node):
            old = node.layout
            totals = tuple(sum(sz) for sz in old.sizes)
            padded = tuple(t + (-t) % n for t in totals)
            new = _ShardLayout(old.keys, old.idxs, old.sizes, padded,
                               tuple(p // n for p in padded))
            gathered_lens = {p for p in old.padded if p > 0}

            def g(leaf):
                a = np.asarray(leaf)
                if a.ndim == 1 and a.shape[0] in gathered_lens:
                    # Which group produced this buffer: padded length
                    # first; on a collision (two dtype groups padding to
                    # the same length) equal totals make the choice
                    # irrelevant (identical trim/re-pad/slice), else the
                    # leaf dtype picks the group (groups are keyed by
                    # dtype, and optax moments keep the param dtype).
                    # A collision with UNEQUAL totals and no dtype match
                    # is genuinely ambiguous — trimming with the wrong
                    # total would silently drop real state, so refuse.
                    cands = [i for i in range(len(old.keys))
                             if old.padded[i] == a.shape[0]]
                    gi = cands[0]
                    if len(cands) > 1 and \
                            len({totals[i] for i in cands}) > 1:
                        m = [i for i in cands
                             if np.dtype(old.keys[i]) == a.dtype]
                        if len(m) == 1:
                            gi = m[0]
                        else:
                            raise HorovodTpuError(
                                "cannot re-shard optimizer state: a "
                                f"{a.dtype} buffer of length "
                                f"{a.shape[0]} matches several dtype "
                                f"groups ({[old.keys[i] for i in cands]}"
                                ") with different true sizes "
                                f"({[totals[i] for i in cands]}); "
                                "restoring with the wrong size would "
                                "corrupt state. Restart at the recorded "
                                "world size instead.")
                    buf = a[:totals[gi]]
                    pad = new.padded[gi] - totals[gi]
                    if pad:
                        buf = np.concatenate(
                            [buf, np.zeros((pad,), a.dtype)])
                    return jnp.asarray(
                        buf[r * new.shard[gi]:(r + 1) * new.shard[gi]])
                return jnp.asarray(a)

            inner = jax.tree_util.tree_map(g, node.inner)
            residual = None
            if node.had_residual:
                residual = [
                    jnp.zeros((new.padded[g]
                               if jnp.issubdtype(jnp.dtype(k),
                                                 jnp.floating) else 0,),
                              jnp.float32)
                    for g, k in enumerate(new.keys)]
            return _ShardedState(inner, residual, new)
        return jax.tree_util.tree_map(jnp.asarray, node)

    return jax.tree_util.tree_map(one, host_state,
                                  is_leaf=_is_host_sharded)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=None,
                         backward_passes_per_step: int = 1,
                         op: int = Average, axis_name: str | None = None,
                         sharded: bool | None = None,
                         overlap: bool | None = None,
                         zero_stage: int | None = None):
    """Wrap an optax optimizer with cross-rank gradient aggregation.

    ``axis_name=None`` (default) resolves to the configured data mesh's
    ``dp`` axis (``HOROVOD_MESH`` / ``hvd.init(mesh=...)``, see
    docs/mesh.md) — the reduction, the ZeRO shard layouts, the health
    verdict allgather and the error-feedback residuals all scope to the
    dp replicas only, leaving tp/pp/sp-sharded params untouched — else
    to the flat world axis ``"hvd"``.

    Keeps the reference's keyword surface
    (``horovod/torch/__init__.py:395-449``); ``named_parameters`` is
    accepted and ignored (pytrees carry structure).  With
    ``backward_passes_per_step > 1`` gradients accumulate locally and
    communicate only every N steps (reference grad-accumulation,
    ``torch/__init__.py:127-162``); intermediate steps return zero
    updates.

    ``compression=None`` (default) resolves from the
    ``HOROVOD_COMPRESSION`` knob.  With ``Compression.int8`` and
    ``backward_passes_per_step == 1`` the optimizer state additionally
    carries a persistent error-feedback residual pytree: each step's
    quantization error is re-injected into the next step's gradients,
    so compression error averages out over training instead of being
    lost (EQuARX/1-bit-Adam-style EF; state is a
    :class:`_FeedbackState` wrapping the inner optax state).

    ``sharded=None`` (default) resolves from the
    ``HOROVOD_SHARDED_OPTIMIZER`` knob; ``True`` enables the ZeRO-1
    sharded weight update (arXiv:2004.13336): gradients are fused into
    per-dtype flat buffers and **reduce-scattered** instead of
    allreduced, the wrapped optimizer runs on only the rank-local
    ``1/world_size`` shard — its state (Adam moments, …) is initialized
    and carried shard-local, cutting optimizer-state memory
    ~``world_size``-fold — and the updated parameter shards are
    **allgathered** back into the full update pytree.  Composes with
    compression (under int8 + hierarchical only the cross-slice hop is
    quantized) and with ``backward_passes_per_step``; incompatible with
    ``op=Adasum`` (the projection needs the full reduction).  See
    ``docs/zero.md``.

    ``zero_stage=None`` (default) resolves from the
    ``HOROVOD_ZERO_STAGE`` knob (with ``sharded=True`` kept as the
    stage-1 spelling).  Stage 1 is the sharded weight update above;
    **stage 2** additionally keeps gradients shard-resident — the fused
    buffers are reduce-scattered bucket-by-bucket
    (``HOROVOD_ZERO_PREFETCH_CHUNKS`` pieces assembled span-wise from
    the gradient leaves) so no full-size fused gradient buffer ever
    materializes; **stage 3** additionally shards the parameters
    themselves: train on :func:`zero3_shard_params`' ``Zero3Params``,
    materialize the forward's view with :func:`zero3_full_params`
    (bucket-wise prefetched allgather), and this optimizer's ``update``
    returns shard-shaped updates that apply directly — parameters,
    gradients and optimizer state all live as 1/world shards between
    steps.  Stage 3 does not compose with
    ``backward_passes_per_step > 1`` (accumulate full-gradient trees
    outside the optimizer instead).  See ``docs/zero.md``.

    ``overlap=None`` (default) resolves from the ``HOROVOD_OVERLAP``
    knob; ``True`` replaces the single end-of-step fused collective
    with the bucketed ppermute ring schedule of
    :mod:`horovod_tpu.ops.overlap` (``HOROVOD_OVERLAP_CHUNKS``
    buckets, barrier-separated so XLA's latency-hiding scheduler can
    float bucket ``i+1``'s transfer under bucket ``i``'s compute).
    Composes with ``sharded`` (bucket-wise scatter -> shard update ->
    gather pipeline; state layout unchanged), with int8 (per-bucket
    quantization, EF residuals bucket-aligned) and with hierarchical
    allreduce (only the cross-slice hop rides the ring); ignored for
    ``op=Adasum``.  On the eager path the knob governs (it rides the
    round-0 handshake); a per-call argument applies in-trace only.
    See ``docs/overlap.md``.
    """
    del named_parameters
    try:
        init_fn, update_fn = optimizer.init, optimizer.update
    except AttributeError as exc:
        raise TypeError(
            "DistributedOptimizer expects an optax GradientTransformation "
            f"(got {type(optimizer)!r})") from exc

    compression = _resolve_compression(compression)
    axis_name = _pmesh.resolve_axis(axis_name)
    stage = _resolve_zero_stage(zero_stage, sharded)
    sharded = stage >= 1
    k = int(backward_passes_per_step)
    # Pallas-fused optimizer tail (HOROVOD_FUSED_UPDATE=1, docs/
    # zero.md): non-None only when the knob is on AND the wrapped
    # optimizer carries a FusedSpec (hvd.fused_update.sgd/adam) —
    # otherwise one warning and the unfused optax chain runs, so the
    # knob can never change results, only fuse them.
    fspec = _fused.resolve_spec(optimizer)
    if fspec is not None and stage == 0:
        # Replicated tail: substitute the fused per-leaf kernel for the
        # wrapped update BEFORE the EF / accumulation wrappers below,
        # so every stage-0 regime (plain, int8+EF, k>1) composes with
        # it.  Falls back leaf-for-leaf when the state layout is not
        # the recognized optax shape (fail-open).
        _base_update = update_fn

        def update_fn(grads, state, params=None, **extra):  # noqa: F811
            res = _fused.fused_update_tree(fspec, grads, state)
            if res is None:
                return _base_update(grads, state, params, **extra)
            return res

    # Observability (docs/metrics.md): record the resolved schedule so
    # hvd.metrics() shows what the optimizer actually runs with (the
    # env knobs record only the request).
    _ovl = (bool(_config.get("overlap")) if overlap is None
            else bool(overlap))
    _metrics.gauge(
        "hvd_overlap_chunks",
        "Bucket count of the overlap ring schedule (0 = overlap "
        "off).").set(
            int(_config.get("overlap_chunks")) if _ovl else 0)
    _metrics.gauge(
        "hvd_sharded_optimizer",
        "1 when the ZeRO-1 sharded weight update is active.").set(
            1 if sharded else 0)
    _M_ZERO_STAGE.set(stage)

    def reduce_grads(grads):
        return allreduce_gradients(grads, op=op, axis_name=axis_name,
                                   compression=compression,
                                   overlap=overlap)

    if sharded:
        if op == Adasum:
            raise HorovodTpuError(
                "zero_stage>=1 (sharded=True) does not compose with "
                "op=Adasum: the projection's dot/norm math needs the "
                "full reduction, not a scatter. Use op=Average/Sum "
                "with the sharded optimizer.")
        import optax

        if stage >= 3:
            if k != 1:
                raise HorovodTpuError(
                    "zero_stage=3 does not compose with "
                    "backward_passes_per_step > 1: the accumulation "
                    "wrapper holds full-gradient trees, exactly the "
                    "residency stage 3 eliminates. Accumulate "
                    "full-gradient pytrees outside the optimizer and "
                    "feed the mean to update() instead.")
            core_init, core_update = _make_zero3_fns(
                init_fn, update_fn, op, axis_name, compression,
                overlap=overlap, fused_spec=fspec)
            return _health_wrap(
                optax.GradientTransformation(core_init, core_update),
                axis_name)
        core_init, core_update = _make_sharded_fns(
            init_fn, update_fn, op, axis_name, compression,
            overlap=overlap, zero_stage=stage, fused_spec=fspec)
        if k == 1:
            return _health_wrap(
                optax.GradientTransformation(core_init, core_update),
                axis_name)
        # k > 1: the accumulation wrapper below drives the sharded core
        # (which reduces internally), so the pre-reduce hook is a no-op.
        init_fn, update_fn = core_init, core_update

        def reduce_grads(grads):  # noqa: F811 — accumulation path hook
            return grads

    if not sharded and k == 1 and is_quantized(compression) \
            and op != Adasum:
        import optax

        def init_ef(params):
            st = _FeedbackState(_quant.init_error_feedback(params),
                                init_fn(params))
            _stamp_zero_bytes_replicated(params, st.inner_state)
            return st

        def update_ef(grads, state, params=None, **extra):
            reduced, new_res = allreduce_gradients_with_feedback(
                grads, state.residual, op=op, axis_name=axis_name,
                overlap=overlap, compression=compression)
            _maybe_report_residual_ratio(new_res, reduced, axis_name,
                                         overlap=overlap)
            upd, inner = update_fn(reduced, state.inner_state, params,
                                   **extra)
            return upd, _FeedbackState(new_res, inner)

        return _health_wrap(
            optax.GradientTransformation(init_ef, update_ef), axis_name)

    if k == 1:
        def init1(params):
            st = init_fn(params)
            _stamp_zero_bytes_replicated(params, st)
            return st

        def update1(grads, state, params=None, **extra):
            return update_fn(reduce_grads(grads), state, params, **extra)

        import optax

        return _health_wrap(
            optax.GradientTransformationExtraArgs(init1, update1)
            if hasattr(optax, "GradientTransformationExtraArgs")
            else optax.GradientTransformation(init1, update1), axis_name)

    import optax

    def init_k(params):
        accum = jax.tree_util.tree_map(jnp.zeros_like, params)
        inner = init_fn(params)
        if not sharded:  # sharded core already stamped shard-local sizes
            _stamp_zero_bytes_replicated(params, inner)
        return _AccumulationState(jnp.zeros((), jnp.int32), accum, inner)

    def update_k(grads, state, params=None, **extra):
        counter = state.counter + 1
        accum = jax.tree_util.tree_map(lambda a, g: a + g, state.accum, grads)
        sync = counter >= k

        if _in_trace(grads):
            def do_sync(acc, inner):
                mean = jax.tree_util.tree_map(lambda a: a / k, acc)
                upd, new_inner = update_fn(reduce_grads(mean), inner,
                                           params, **extra)
                zeros = jax.tree_util.tree_map(jnp.zeros_like, acc)
                return upd, zeros, new_inner

            def no_sync(acc, inner):
                zeros = jax.tree_util.tree_map(jnp.zeros_like, acc)
                return zeros, acc, inner

            upd, accum2, inner2 = jax.lax.cond(
                sync, do_sync, no_sync, accum, state.inner_state)
            new_counter = jnp.where(sync, 0, counter)
            return upd, _AccumulationState(new_counter, accum2, inner2)

        if bool(sync):
            mean = jax.tree_util.tree_map(lambda a: a / k, accum)
            upd, inner2 = update_fn(reduce_grads(mean), state.inner_state,
                                    params, **extra)
            zeros = jax.tree_util.tree_map(jnp.zeros_like, accum)
            return upd, _AccumulationState(jnp.zeros((), jnp.int32),
                                           zeros, inner2)
        zeros = jax.tree_util.tree_map(jnp.zeros_like, grads)
        return zeros, _AccumulationState(counter, accum, state.inner_state)

    return _health_wrap(
        optax.GradientTransformation(init_k, update_k), axis_name)


class DistributedGradientTape:
    """JAX analog of the reference's TF ``DistributedGradientTape``
    (``tensorflow/__init__.py:475-531``): wraps a loss function so its
    gradients come back allreduced."""

    def __init__(self, loss_fn, compression=None,
                 op: int = Average, axis_name: str | None = None,
                 has_aux: bool = False):
        self._loss_fn = loss_fn
        self._compression = _resolve_compression(compression)
        self._op = op
        self._axis_name = _pmesh.resolve_axis(axis_name)
        self._has_aux = has_aux

    def gradient(self, *args, argnums=0, **kwargs):
        g = jax.grad(self._loss_fn, argnums=argnums,
                     has_aux=self._has_aux)(*args, **kwargs)
        if self._has_aux:
            grads, aux = g
            return allreduce_gradients(grads, self._op, self._axis_name,
                                       self._compression), aux
        return allreduce_gradients(g, self._op, self._axis_name,
                                   self._compression)


def grad(loss_fn, argnums=0, op: int = Average,
         axis_name: str | None = None,
         compression=None, has_aux: bool = False):
    """``jax.grad`` with cross-rank averaging — functional spelling of
    DistributedGradientTape."""
    compression = _resolve_compression(compression)
    axis_name = _pmesh.resolve_axis(axis_name)

    gfn = jax.grad(loss_fn, argnums=argnums, has_aux=has_aux)

    def wrapped(*args, **kwargs):
        g = gfn(*args, **kwargs)
        if has_aux:
            g, aux = g
            return allreduce_gradients(g, op, axis_name, compression), aux
        return allreduce_gradients(g, op, axis_name, compression)

    return wrapped


# ---------------------------------------------------------------------------
# Parameter / object broadcast (reference torch/__init__.py:451-647)
# ---------------------------------------------------------------------------


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast a parameter pytree from ``root_rank`` to all ranks and
    return the synchronized pytree (functional; the reference mutates
    ``state_dict`` in place, ``torch/__init__.py:451-481``).  Tensors are
    fused per dtype into single transfers.

    Refuses stage-3 shard-resident parameters (:class:`Zero3Params`):
    each rank's shard is a *different* segment of the fused buffers, so
    broadcasting rank 0's would corrupt every other rank — and a silent
    full-gather here would defeat the residency contract.  Resync
    stage-3 params through the elastic commit/restore path
    (:func:`params_to_host` / :func:`params_from_host`, or
    ``checkpoint.save/restore(..., all_ranks=True)``)."""
    _refuse_zero3(params, "broadcast_parameters")
    leaves, treedef = jax.tree_util.tree_flatten(params)
    if not leaves:
        return params
    out = _fused_pytree_collective(
        leaves,
        lambda flat, label: _eager.broadcast_async(
            flat, root_rank, name=f"bcast_buffer.{label}"))
    return jax.tree_util.tree_unflatten(treedef, out)


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Broadcast optimizer state (reference ``torch/__init__.py:483-604``;
    trivial here because optax state is already a pytree of arrays).

    Shard-local (ZeRO-1) subtrees pass through unchanged: each rank's
    shard is authoritative — broadcasting rank 0's moments would
    silently overwrite every other rank's shard with the wrong
    segment.  Everything around them (accumulation buffers, schedules,
    a params tree resynced in the same call) still broadcasts.
    Restore shard-local state with ``checkpoint.save/restore(...,
    all_ranks=True)`` instead (see docs/zero.md)."""
    return broadcast_skipping_shards(opt_state, root_rank)


def _refuse_zero3(tree, what: str) -> None:
    if _contains_zero3(tree):
        raise HorovodTpuError(
            f"{what} called on zero_stage=3 shard-resident parameters "
            "(Zero3Params): every rank holds a DIFFERENT 1/world "
            "segment, so a broadcast would corrupt all but the root "
            "and a full-gather would silently defeat the residency "
            "contract. Outside an elastic re-form, move stage-3 state "
            "with the commit/restore path instead: params_to_host / "
            "params_from_host (hvd.elastic commits do this for you) "
            "or checkpoint.save/restore(..., all_ranks=True). See "
            "docs/zero.md.")


def broadcast_skipping_shards(tree, root_rank: int = 0):
    """Broadcast every leaf of ``tree`` from ``root_rank`` EXCEPT those
    inside shard-local (:class:`_ShardedState`) subtrees, which are
    per-rank by construction.  Returns ``tree`` itself when there is
    nothing to broadcast.  Stage-3 :class:`Zero3Params` anywhere in the
    tree is refused loudly (see :func:`broadcast_parameters`)."""
    _refuse_zero3(tree, "broadcast_skipping_shards")
    leaves, treedef = jax.tree_util.tree_flatten(
        tree, is_leaf=_is_sharded_state)
    plain = [i for i, l in enumerate(leaves)
             if not _is_sharded_state(l)]
    if not plain:
        return tree
    synced = broadcast_parameters([leaves[i] for i in plain],
                                  root_rank=root_rank)
    for i, v in zip(plain, synced):
        leaves[i] = v
    return jax.tree_util.tree_unflatten(treedef, leaves)


# TF-parity alias (reference ``BroadcastGlobalVariablesHook`` semantics).
def broadcast_global_variables(variables, root_rank: int = 0):
    return broadcast_parameters(variables, root_rank)


def broadcast_object(obj, root_rank: int = 0, name: str | None = None):
    """Broadcast an arbitrary picklable object
    (reference ``torch/__init__.py:607-647``: cloudpickle → size bcast →
    payload bcast)."""
    import io
    import pickle

    try:
        import cloudpickle as pickler  # type: ignore
    except ImportError:
        pickler = pickle
    name = name or "broadcast_object"
    if _basics.rank() == root_rank:
        buf = io.BytesIO()
        pickler.dump(obj, buf)
        payload = np.frombuffer(buf.getvalue(), dtype=np.uint8)
        length = np.array([payload.size], dtype=np.int32)
    else:
        payload = None
        length = np.zeros((1,), dtype=np.int32)
    length = np.asarray(_eager.broadcast(jnp.asarray(length), root_rank,
                                         name=f"{name}.len"))
    n = int(length[0])
    if payload is None:
        payload = np.zeros((n,), dtype=np.uint8)
    wire = _eager.broadcast(jnp.asarray(payload), root_rank,
                            name=f"{name}.payload")
    data = np.asarray(wire).tobytes()
    return pickle.loads(data)
