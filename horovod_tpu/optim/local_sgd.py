"""Cross-slice local-SGD / DiLoCo outer loop (docs/local-sgd.md).

The multi-slice regime this module exists for: inner steps reduce
gradients ONLY over the intra-slice ICI axis (the ``dpl`` sub-axis of
the hierarchical data mesh, full precision), and every
``HOROVOD_LOCAL_SGD_H``-th step an **outer sync** exchanges
pseudo-gradients — each rank's parameter delta since the last sync —
across slices over the DCN ``dpc`` axis, through the compression
ladder with persistent error-feedback residuals, applied with outer
Nesterov momentum (arXiv:2311.08105 DiLoCo; local SGD
arXiv:1805.09767).  Between syncs NO traffic crosses a slice: the
inner-step program provably contains zero cross-slice collectives
(``hlo_lint`` preset ``local_sgd_inner_rules`` pins this).

Two-program structure, deliberately: the inner step
(:meth:`LocalSGDOptimizer.update`) and the outer sync
(:meth:`LocalSGDOptimizer.outer_sync`) are SEPARATE jit programs and
the H-boundary is decided host-side (``step % H == 0``, H static from
the round-0-validated knob) — a ``lax.cond`` would bake the DCN
collectives into every inner step's HLO and forfeit the proof.

Composes with ZeRO 0-3 over the LOCAL axis: the inner
``DistributedOptimizer`` gets ``axis_name=dpl`` so its state shards
1/L per slice, and the outer anchors / velocity / residuals shard the
same way (shard position ``l`` holds the same parameter segment on
every slice, so the per-shard cross-reduce is exact and the new
parameters come back from ONE intra-slice allgather).  Stage 3 trains
on local-axis :class:`~horovod_tpu.optim.distributed.Zero3Params` and
the outer sync runs shard-buffer-wise with no gather at all.

The eager/negotiated regime rides the ``localsgd.local.`` /
``localsgd.cross.`` tensor-name scope contract
(:func:`horovod_tpu.runtime.controller.reduction_scope`): stage 0
only, no eager error feedback (same precedent as
:func:`~horovod_tpu.optim.distributed
.allreduce_gradients_with_feedback`).
"""

from __future__ import annotations

import time
import warnings
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.common import config as _config
from horovod_tpu.common.types import HorovodTpuError
from horovod_tpu.optim import distributed as _dist
from horovod_tpu.ops import collectives as _coll
from horovod_tpu.ops.collectives import Average, Sum
from horovod_tpu.ops.compression import Compression, is_quantized
from horovod_tpu.parallel import mesh as _pmesh
from horovod_tpu.runtime import metrics as _metrics

__all__ = [
    "LocalSGD", "LocalSGDOptimizer", "LocalSGDState", "OuterState",
    "resolved_h", "outer_compression", "is_local_sgd_state",
    "inner_window_position",
]

_M_OUTER_H = _metrics.gauge(
    "hvd_local_sgd_h",
    "Resolved outer-sync period H of the local-SGD regime (0 = "
    "synchronous training, the regime is off).")


def resolved_h(h=None) -> int:
    """The outer-sync period: an explicit ``h`` wins, else the
    ``HOROVOD_LOCAL_SGD_H`` knob.  ``<= 1`` means the regime is off
    (every step is an ordinary synchronous step)."""
    v = int(_config.get("local_sgd_h") if h is None else h)
    return max(v, 0)


def outer_compression(compression=None):
    """The outer sync's DCN wire compressor: an explicit compressor
    wins; else ``HOROVOD_LOCAL_SGD_COMPRESSION`` when set; else the
    regime inherits ``HOROVOD_COMPRESSION``."""
    if compression is not None:
        return compression
    name = str(_config.get("local_sgd_compression") or "").strip()
    if name:
        return Compression.lookup(name)
    return _dist._resolve_compression(None)


class LocalSGDState(NamedTuple):
    """Optimizer state of the local-SGD regime: the inner
    DistributedOptimizer's state, the outer-loop :class:`OuterState`
    (``None`` when the regime is off or degenerate), and the count of
    inner steps since the last outer sync (0 exactly at an outer-sync
    boundary — the elastic commit contract, docs/local-sgd.md)."""
    inner_state: Any
    outer: Any
    inner_steps: jnp.ndarray


@jax.tree_util.register_pytree_node_class
class OuterState:
    """Outer-loop state: per-dtype-group flat buffers (anchor
    parameter snapshot in the parameter dtype, fp32 Nesterov velocity,
    fp32 error-feedback residual or ``None`` for lossless wires) over
    the shared :class:`~horovod_tpu.optim.distributed._ShardLayout`.

    ``kind`` picks the residency: ``"full"`` (stage 0 — full fused
    buffers, layout n=1), ``"local"`` (stage 1/2 — 1/L shards over the
    local axis, exactly the inner ZeRO state's layout) or ``"zero3"``
    (buffers mirror the ``Zero3Params`` shard buffers)."""

    def __init__(self, anchor, velocity, residual, layout, treedef,
                 shapes, kind: str):
        self.anchor = list(anchor)
        self.velocity = list(velocity)
        self.residual = None if residual is None else list(residual)
        self.layout = layout
        self.treedef = treedef
        self.shapes = tuple(tuple(s) for s in shapes)
        self.kind = kind

    def tree_flatten(self):
        return ((tuple(self.anchor), tuple(self.velocity),
                 None if self.residual is None else tuple(self.residual)),
                (self.layout, self.treedef, self.shapes, self.kind))

    @classmethod
    def tree_unflatten(cls, aux, children):
        anchor, velocity, residual = children
        return cls(list(anchor), list(velocity),
                   None if residual is None else list(residual), *aux)

    def __repr__(self) -> str:
        return (f"OuterState(kind={self.kind!r}, "
                f"groups={list(self.layout.keys)})")


def is_local_sgd_state(x) -> bool:
    return isinstance(x, LocalSGDState)


def inner_window_position(state) -> int | None:
    """Inner steps since the last outer sync (0 = at a boundary), or
    ``None`` when ``state`` is not a local-SGD state / the regime is
    off.  Host-side (concretizes the counter) — the elastic plane uses
    it to enforce the commit-at-boundary contract."""
    if not is_local_sgd_state(state) or state.outer is None:
        return None
    try:
        return int(state.inner_steps)
    except Exception:
        return None


def _is_pair(axis_name) -> bool:
    return isinstance(axis_name, (tuple, list)) and len(axis_name) == 2


def _unfuse(bufs, layout, shapes, treedef):
    """Split per-group flat buffers back into the parameter pytree."""
    n = sum(len(ii) for ii in layout.idxs)
    leaves: list = [None] * n
    for g in range(len(layout.keys)):
        dt = jnp.dtype(layout.keys[g])
        off = 0
        for i, sz in zip(layout.idxs[g], layout.sizes[g]):
            leaves[i] = bufs[g][off:off + sz].reshape(shapes[i]).astype(dt)
            off += sz
    return jax.tree_util.tree_unflatten(treedef, leaves)


class LocalSGDOptimizer:
    """The object :func:`LocalSGD` returns.  ``init``/``update`` make
    it optax-shaped for the INNER step (drop-in where a
    ``DistributedOptimizer`` goes); the outer loop is explicit:

    .. code-block:: python

        opt = hvd.LocalSGD(optax.adam(1e-3))          # H from the knob
        state = opt.init(params)
        for step in range(1, steps + 1):
            params, state = train_step(params, state, batch)  # inner
            params, state = opt.maybe_outer_sync(step, params, state)

    ``maybe_outer_sync`` is host-side sugar over
    :meth:`outer_sync` — it fires on ``step % H == 0``, times the sync
    wall into the goodput ledger's ``comm_exposed`` and bumps the
    ``hvd_outer_sync_total`` counter.  Jit ``outer_sync`` yourself
    (``shard_map`` over the same mesh as the step) and pass it via
    ``sync_fn=`` to keep the boundary compiled."""

    def __init__(self, optimizer, h=None, axis_name=None, outer_lr=None,
                 outer_momentum=None, compression=None, op: int = Average,
                 overlap=None, sharded=None, zero_stage=None,
                 backward_passes_per_step: int = 1):
        try:
            self._raw_init, self._raw_update = optimizer.init, optimizer.update
        except AttributeError as exc:
            raise TypeError(
                "LocalSGD expects an optax GradientTransformation "
                f"(got {type(optimizer)!r})") from exc
        self.h = resolved_h(h)
        self.active = self.h > 1
        self.outer_lr = float(_config.get("outer_lr")
                              if outer_lr is None else outer_lr)
        self.outer_momentum = float(_config.get("outer_momentum")
                                    if outer_momentum is None
                                    else outer_momentum)
        self._op = op
        self._stage = _dist._resolve_zero_stage(zero_stage, sharded)
        self._degenerate = False
        resolved = _pmesh.resolve_axis(axis_name)
        self._pair = tuple(resolved) if _is_pair(resolved) else None
        _M_OUTER_H.set(self.h if self.active else 0)

        if not self.active:
            # Synchronous regime: pure delegation, bit-exact with a
            # plain DistributedOptimizer by construction.
            self._comp = _dist._resolve_compression(compression)
            self._inner = _dist.DistributedOptimizer(
                optimizer, compression=compression, op=op,
                axis_name=axis_name, overlap=overlap,
                zero_stage=self._stage,
                backward_passes_per_step=backward_passes_per_step)
            self._inner_axis = resolved
            return

        if int(backward_passes_per_step) != 1:
            raise HorovodTpuError(
                "local-SGD (HOROVOD_LOCAL_SGD_H > 1) does not compose "
                "with backward_passes_per_step > 1: the inner window IS "
                "the accumulation — raise H instead (docs/local-sgd.md)")
        if op not in (Average, Sum):
            raise HorovodTpuError(
                "local-SGD supports op=Average/Sum: the pseudo-gradient "
                f"exchange has no Adasum projection (got op={op})")
        self._comp = outer_compression(compression)

        # Cross extent, when knowable here: a single-slice world has no
        # second slice to sync with — warn loudly and run the inner
        # loop as plain synchronous training with a no-op outer sync.
        cross_extent = None
        if self._pair is not None:
            spec = _pmesh.active_spec() or {}
            if self._pair == tuple(_pmesh.HIER_DATA_AXES):
                cross_extent = spec.get(_pmesh.HIER_DATA_AXES[0])
        else:
            from horovod_tpu.ops import xla_exec as _exec
            topo = _exec.local_sgd_topology()
            if topo is None:
                cross_extent = 1  # no hierarchical split: one "slice"
            else:
                cross_extent = topo[0]
        if cross_extent is not None and int(cross_extent) <= 1:
            warnings.warn(
                "HOROVOD_LOCAL_SGD_H=%d but the world is a single "
                "slice (no cross/DCN axis to sync over) — the outer "
                "sync is a NO-OP and training runs as plain "
                "synchronous SGD over the local axis "
                "(docs/local-sgd.md)" % self.h, stacklevel=3)
            self._degenerate = True

        # Inner optimizer scopes to the LOCAL sub-axis, full precision:
        # the compression ladder belongs to the DCN hop, not ICI
        # (docs/local-sgd.md).  ZeRO state therefore shards 1/L.
        self._inner_axis = (self._pair[1] if self._pair is not None
                            else resolved)
        self._inner = _dist.DistributedOptimizer(
            optimizer, compression=Compression.none, op=op,
            axis_name=self._inner_axis, overlap=overlap,
            zero_stage=self._stage)

    # -- optax surface (inner step) ------------------------------------

    def init(self, params) -> LocalSGDState:
        inner = self._inner.init(params)
        outer = None
        if self.active and not self._degenerate:
            outer = self._outer_init(params)
        return LocalSGDState(inner, outer, jnp.zeros((), jnp.int32))

    def update(self, grads, state: LocalSGDState, params=None, **extra):
        leaves = jax.tree_util.tree_leaves(grads)
        if (self.active and leaves and not _dist._in_trace(leaves)
                and self._pair is None):
            # Eager/negotiated regime: the inner reduction must ride
            # the `localsgd.local.` scope contract, not the inner
            # DistributedOptimizer's world-scoped eager wire.
            if self._stage != 0:
                raise HorovodTpuError(
                    "eager local-SGD composes with zero_stage=0 only; "
                    "run the step in-trace (shard_map over the "
                    "hierarchical mesh) for ZeRO 1-3 "
                    "(docs/local-sgd.md)")
            _dist._check_eager_mesh()
            ls, treedef = jax.tree_util.tree_flatten(grads)
            red = _dist._eager_fused_pytree_allreduce(
                ls, self._op, Compression.none, scope="local")
            reduced = jax.tree_util.tree_unflatten(treedef, red)
            upd, inner2 = self._raw_update(reduced, state.inner_state,
                                           params, **extra)
        else:
            upd, inner2 = self._inner.update(grads, state.inner_state,
                                             params, **extra)
        steps = state.inner_steps + (1 if self.active else 0)
        return upd, LocalSGDState(inner2, state.outer, steps)

    # -- outer loop ----------------------------------------------------

    def _outer_init(self, params) -> OuterState:
        lossy = is_quantized(self._comp)
        if _dist._is_zero3(params):
            anchors = [jnp.asarray(s) for s in params.shards]
            return OuterState(
                anchors,
                [jnp.zeros(a.shape, jnp.float32) for a in anchors],
                ([jnp.zeros(a.shape, jnp.float32) for a in anchors]
                 if lossy else None),
                params.layout, params.treedef, params.shapes, "zero3")
        leaves, treedef = jax.tree_util.tree_flatten(params)
        bad = sorted({str(jnp.dtype(l.dtype)) for l in leaves
                      if not jnp.issubdtype(jnp.asarray(l).dtype,
                                            jnp.floating)})
        if bad:
            raise HorovodTpuError(
                "local-SGD pseudo-gradients need floating parameters; "
                f"got leaves of dtype {bad} (docs/local-sgd.md)")
        shapes = tuple(tuple(l.shape) for l in leaves)
        if self._stage >= 1:
            idx, n, _ = _dist._shard_position(self._inner_axis)
            layout = _dist._shard_layout(leaves, n)
            anchors = [lax.dynamic_slice_in_dim(
                _dist._fuse_group(leaves, layout, g),
                idx * layout.shard[g], layout.shard[g])
                for g in range(len(layout.keys))]
            kind = "local"
        else:
            layout = _dist._shard_layout(leaves, 1)
            anchors = [_dist._fuse_group(leaves, layout, g)
                       for g in range(len(layout.keys))]
            kind = "full"
        return OuterState(
            anchors,
            [jnp.zeros(a.shape, jnp.float32) for a in anchors],
            ([jnp.zeros(a.shape, jnp.float32) for a in anchors]
             if lossy else None),
            layout, treedef, shapes, kind)

    def outer_sync(self, params, state: LocalSGDState):
        """One outer DiLoCo step: pseudo-gradient = anchor − params
        (+ EF residual), averaged across slices over the cross/DCN
        axis through the compression ladder, applied with outer
        Nesterov momentum; the anchor resets to the new parameters and
        the inner window restarts.  Pure — jit/shard_map it over the
        same mesh as the inner step.  Returns ``(params, state)``
        unchanged (except the window counter) when the regime is off
        or degenerate."""
        if not self.active or self._degenerate or state.outer is None:
            return params, LocalSGDState(state.inner_state, state.outer,
                                         jnp.zeros((), jnp.int32))
        outer = state.outer
        leaves = jax.tree_util.tree_leaves(params)
        eager = not _dist._in_trace(leaves)
        if eager:
            new_params, new_outer = self._outer_sync_eager(params, outer)
        else:
            new_params, new_outer = self._outer_sync_trace(params, outer)
        return new_params, LocalSGDState(
            state.inner_state, new_outer, jnp.zeros((), jnp.int32))

    def _current_bufs(self, params, outer: OuterState):
        """Per-group buffers of the CURRENT parameters in the outer
        state's residency (full fused / local shard / zero3 shard)."""
        if outer.kind == "zero3":
            return [jnp.asarray(s) for s in params.shards]
        leaves = jax.tree_util.tree_leaves(params)
        layout = outer.layout
        if outer.kind == "local":
            idx, _, _ = _dist._shard_position(self._inner_axis)
            return [lax.dynamic_slice_in_dim(
                _dist._fuse_group(leaves, layout, g),
                idx * layout.shard[g], layout.shard[g])
                for g in range(len(layout.keys))]
        return [_dist._fuse_group(leaves, layout, g)
                for g in range(len(layout.keys))]

    def _nesterov(self, red, g, outer: OuterState):
        """Outer Nesterov over one group buffer: returns the new
        anchor (group dtype) and velocity (fp32)."""
        mu = self.outer_momentum
        v = mu * outer.velocity[g] + red
        upd = red + mu * v
        anchor32 = outer.anchor[g].astype(jnp.float32)
        new_anchor = (anchor32 - self.outer_lr * upd).astype(
            outer.anchor[g].dtype)
        return new_anchor, v

    def _outer_sync_trace(self, params, outer: OuterState):
        pair = self._pair
        if pair is None:
            raise HorovodTpuError(
                "in-trace local-SGD outer sync needs the hierarchical "
                "(dpc, dpl) data mesh — configure "
                "HOROVOD_HIERARCHICAL_ALLREDUCE/HOROVOD_MESH or pass "
                "axis_name=(cross, local) (docs/local-sgd.md)")
        with_err = outer.residual is not None
        cur_bufs = self._current_bufs(params, outer)
        anchors, vels, resids = [], [], []
        for g in range(len(outer.layout.keys)):
            delta = outer.anchor[g].astype(jnp.float32) - \
                cur_bufs[g].astype(jnp.float32)
            if with_err:
                delta = delta + outer.residual[g]
            with jax.named_scope(f"hvd_localsgd_outer{g}"):
                out = _coll.cross_allreduce(
                    delta, axis_name=pair, op=self._op,
                    compression=self._comp, with_error=with_err)
            red, err = out if with_err else (out, None)
            new_anchor, v = self._nesterov(red, g, outer)
            anchors.append(new_anchor)
            vels.append(v)
            if with_err:
                resids.append(err)
        new_outer = OuterState(anchors, vels, resids if with_err else None,
                               outer.layout, outer.treedef, outer.shapes,
                               outer.kind)
        return self._rebuild_params(params, new_outer), new_outer

    def _outer_sync_eager(self, params, outer: OuterState):
        # Negotiated wire: one scoped cross-reduce per group buffer;
        # knob-driven compression rides inside the negotiated program
        # (no error feedback on the eager wire — residuals, if
        # allocated, pass through untouched).
        if outer.kind != "full":
            raise HorovodTpuError(
                "eager local-SGD outer sync composes with zero_stage=0 "
                "only (docs/local-sgd.md)")
        _dist._check_eager_mesh()
        cur = self._current_bufs(params, outer)
        deltas = [outer.anchor[g].astype(jnp.float32) - c.astype(jnp.float32)
                  for g, c in enumerate(cur)]
        reds = _dist._eager_fused_pytree_allreduce(
            deltas, self._op, Compression.none, scope="cross")
        anchors, vels = [], []
        for g, red in enumerate(reds):
            new_anchor, v = self._nesterov(red, g, outer)
            anchors.append(new_anchor)
            vels.append(v)
        new_outer = OuterState(anchors, vels, outer.residual,
                               outer.layout, outer.treedef, outer.shapes,
                               outer.kind)
        return self._rebuild_params(params, new_outer), new_outer

    def _rebuild_params(self, params, outer: OuterState):
        """New parameters == the new anchor (the DiLoCo reset): stage 0
        splits the full buffers; stage 1/2 allgathers the anchor shards
        over the LOCAL axis (the one intra-slice collective of the
        sync); stage 3 rebuilds the shard-resident ``Zero3Params``."""
        if outer.kind == "zero3":
            return _dist.Zero3Params(list(outer.anchor), outer.layout,
                                     outer.treedef, outer.shapes)
        bufs = outer.anchor
        if outer.kind == "local":
            bufs = [lax.all_gather(b, self._inner_axis, tiled=True)
                    for b in bufs]
        return _unfuse(bufs, outer.layout, outer.shapes, outer.treedef)

    # -- host-side boundary sugar --------------------------------------

    def should_sync(self, step: int) -> bool:
        """True when global ``step`` (1-based, counted in inner steps)
        lands on an outer-sync boundary."""
        return (self.active and not self._degenerate
                and step > 0 and step % self.h == 0)

    def maybe_outer_sync(self, step: int, params, state: LocalSGDState,
                         sync_fn=None):
        """Fire :meth:`outer_sync` when ``step`` is a boundary; time
        the sync wall into the goodput ledger (``comm_exposed``) and
        the ``hvd_outer_sync_total`` / ``hvd_outer_sync_seconds_total``
        series.  ``sync_fn`` (default: the un-jitted
        :meth:`outer_sync`) lets callers pass a compiled boundary
        program."""
        if not self.should_sync(step):
            return params, state
        from horovod_tpu.perf import goodput as _goodput

        fn = self.outer_sync if sync_fn is None else sync_fn
        t0 = time.perf_counter()
        params, state = fn(params, state)
        jax.block_until_ready(
            (jax.tree_util.tree_leaves(params),
             jax.tree_util.tree_leaves(state)))
        _goodput.record_outer_sync(time.perf_counter() - t0)
        return params, state


def LocalSGD(optimizer, h=None, axis_name=None, outer_lr=None,
             outer_momentum=None, compression=None, op: int = Average,
             overlap=None, sharded=None, zero_stage=None,
             backward_passes_per_step: int = 1) -> LocalSGDOptimizer:
    """Wrap an optax optimizer in the local-SGD / DiLoCo regime
    (docs/local-sgd.md).

    ``h=None`` resolves from ``HOROVOD_LOCAL_SGD_H`` (validated at the
    round-0 handshake); ``h <= 1`` degenerates to a plain
    :func:`~horovod_tpu.optim.distributed.DistributedOptimizer` —
    bit-exact, so the knob can be flipped without touching code.
    ``outer_lr``/``outer_momentum`` default to the
    ``HOROVOD_OUTER_LR``/``HOROVOD_OUTER_MOMENTUM`` knobs (0.7/0.9,
    the DiLoCo sweet spot); ``compression`` defaults to
    ``HOROVOD_LOCAL_SGD_COMPRESSION`` falling back to
    ``HOROVOD_COMPRESSION`` and applies to the cross-slice DCN hop
    ONLY — the inner ICI reduction always runs full precision."""
    return LocalSGDOptimizer(
        optimizer, h=h, axis_name=axis_name, outer_lr=outer_lr,
        outer_momentum=outer_momentum, compression=compression, op=op,
        overlap=overlap, sharded=sharded, zero_stage=zero_stage,
        backward_passes_per_step=backward_passes_per_step)
