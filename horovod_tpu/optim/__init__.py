"""horovod_tpu.optim subpackage."""
