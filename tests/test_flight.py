"""Flight recorder + trace merge/analyze tests (docs/flight-recorder.md).

Unit layer: ring semantics (bounded memory, order, overwrite), the
no-syscall hot-path cost bound (mirror of the metrics registry's
lock-cheap test), atomic JSONL dumps, fatal-signal dumps, NTP-style
clock-offset math, Chrome-trace schema, and the straggler / death
analyzers over synthetic dumps.

Multiprocess layer: the two acceptance scenarios — a ``delay@rank1``
fault-injected straggler the analyzer must rank first with the
injected lateness, and a SIGKILL whose survivors' dumps must merge
into a valid trace and a death report naming the dead rank.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from horovod_tpu.runtime import flight
from horovod_tpu.trace.analyze import analyze, format_report
from horovod_tpu.trace.merge import (RankDump, compute_offsets,
                                     load_dumps, merge)
from horovod_tpu.trace.perfetto import chrome_trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Ring semantics
# ---------------------------------------------------------------------------


def test_ring_is_bounded_and_ordered():
    r = flight.FlightRecorder(8)
    for i in range(21):
        r.record("x", i=i)
    snap = r.snapshot()
    assert len(snap) == 8
    assert [e["i"] for e in snap] == list(range(13, 21))
    assert [e["seq"] for e in snap] == list(range(13, 21))
    assert r.recorded_total() == 21
    # memory bound: the slot list never grows past capacity
    assert len(r._slots) == 8


def test_ring_partial_fill_and_both_clocks():
    r = flight.FlightRecorder(16)
    r.record("a", ph="B")
    r.record("b")
    snap = r.snapshot()
    assert [e["kind"] for e in snap] == ["a", "b"]
    assert snap[0]["ph"] == "B" and snap[1]["ph"] == "i"
    for ev in snap:
        assert ev["mono"] > 0 and ev["wall"] > 0


def test_clear_resets_ring_for_next_generation():
    """An elastic re-form dumps the old generation's ring then clears
    it: round numbers restart with the new generation, and a later
    dump carrying both generations' events would merge unrelated
    rounds in the straggler analyzer."""
    r = flight.FlightRecorder(8)
    r.record("round", ph="B", round=5)
    r.clear()
    assert r.snapshot() == [] and r.recorded_total() == 0
    r.record("round", ph="B", round=0)
    assert [e["round"] for e in r.snapshot()] == [0]


def test_record_reentrant_from_signal_context():
    """The fatal-signal handler records/dumps on the main thread; if
    the signal lands while that thread is inside record(), the ring
    lock must be reentrant or the dump deadlocks."""
    r = flight.FlightRecorder(8)
    with r._lock:  # simulate: interrupted mid-record
        r.record("signal", sig="SIGTERM")   # must not deadlock
        assert len(r.snapshot()) == 1


def test_overlapping_wait_spans_counted_and_async_in_trace(tmp_path):
    """Two framework threads blocked on different handles at once: the
    analyzer must count both spans (keyed by handle), and the trace
    writer must emit waits as async b/e pairs (sync B/E on one row
    would be matched stack-wise by Chrome and swap the durations)."""
    _dump(tmp_path, 0, [
        {"kind": "wait", "ph": "B", "handle": 1, "wall": 1.0, "mono": 1.0},
        {"kind": "wait", "ph": "B", "handle": 2, "wall": 1.5, "mono": 1.5},
        {"kind": "wait", "ph": "E", "handle": 1, "wall": 2.0, "mono": 2.0},
        {"kind": "wait", "ph": "E", "handle": 2, "wall": 3.5, "mono": 3.5},
    ], size=1)
    dumps = load_dumps(str(tmp_path))
    report = analyze(dumps, compute_offsets(dumps))
    # 1.0 s (h1) + 2.0 s (h2), not just the last-opened span
    assert abs(report["phases"][0]["blocked_s"] - 3.0) < 1e-6
    trace = chrome_trace(dumps, compute_offsets(dumps))
    waits = [e for e in trace["traceEvents"]
             if e["name"].startswith("wait h")]
    assert {e["ph"] for e in waits} == {"b", "e"}
    assert all("id" in e and "cat" in e for e in waits), waits


def test_zero_capacity_disables_recording():
    r = flight.FlightRecorder(0)
    r.record("x")
    assert r.snapshot() == [] and r.recorded_total() == 0


def test_record_is_syscall_free_and_bounded():
    """Acceptance: recording performs no syscalls (open/socket banned
    during a burst) and ring memory stays at HOROVOD_FLIGHT_EVENTS
    entries regardless of run length — the PR 6 lock-cheap registry
    bound, applied to the flight ring."""
    import builtins

    r = flight.FlightRecorder(64)
    real_open, real_socket = builtins.open, socket.socket

    def no_open(*a, **k):
        raise AssertionError("open() on the flight-recorder hot path")

    class NoSocket(socket.socket):
        def __init__(self, *a, **k):
            raise AssertionError("socket() on the flight-recorder hot path")

    builtins.open = no_open
    socket.socket = NoSocket
    try:
        t0 = time.perf_counter()
        for i in range(30000):
            r.record("hot", round=i, n_req=2)
        dt = time.perf_counter() - t0
    finally:
        builtins.open = real_open
        socket.socket = real_socket
    assert r.recorded_total() == 30000
    assert len(r.snapshot()) == 64
    assert len(r._slots) == 64  # no allocation growth with run length
    # generous bound for a loaded CI image; a hidden syscall per record
    # would blow far past it
    assert dt < 5.0, f"hot path too slow: {dt:.2f}s for 30k records"


# ---------------------------------------------------------------------------
# Dumps
# ---------------------------------------------------------------------------


def test_dump_atomic_jsonl_roundtrip(tmp_path):
    r = flight.FlightRecorder(32)
    r.record("round", ph="B", round=0, n_req=1, names=["t"])
    r.record("round", ph="E", round=0, path="slow", n_resp=1)
    path = str(tmp_path / "flight-r0-g1-p1.jsonl")
    out = r.dump(path, {"rank": 0, "size": 2, "generation": 1,
                        "reason": "test"})
    assert out == path and os.path.exists(path)
    assert not [n for n in os.listdir(tmp_path) if ".tmp" in n]
    d = load_dumps(str(tmp_path))[0]
    assert d.rank == 0 and d.generation == 1 and d.size == 2
    assert d.meta["reason"] == "test" and d.meta["events"] == 2
    assert [e["kind"] for e in d.events] == ["round", "round"]
    # dump is idempotent: a second trigger overwrites the same file
    r.record("abort", ranks=[1])
    r.dump(path, {"rank": 0, "size": 2, "generation": 1,
                  "reason": "later"})
    d = load_dumps(str(tmp_path))[0]
    assert d.meta["reason"] == "later" and len(d.events) == 3


def test_global_dump_respects_env_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("HOROVOD_FLIGHT_DIR", raising=False)
    flight.reset()
    flight.record("x")
    assert flight.dump("nodir") is None  # no dir -> no-op, no crash
    monkeypatch.setenv("HOROVOD_FLIGHT_DIR", str(tmp_path / "sub"))
    path = flight.dump("explicit")
    assert path and os.path.exists(path)
    d = load_dumps(os.path.dirname(path))[0]
    assert d.meta["reason"] == "explicit"
    # the dump trigger itself is on the record
    assert d.events[-1]["kind"] == "dump"
    flight.reset()


def test_flight_events_knob_sizes_global_ring(monkeypatch):
    monkeypatch.setenv("HOROVOD_FLIGHT_EVENTS", "5")
    flight.reset()
    for i in range(9):
        flight.record("k", i=i)
    assert len(flight.recorder().snapshot()) == 5
    monkeypatch.setenv("HOROVOD_FLIGHT_EVENTS", "0")
    flight.reset()
    flight.record("k")
    assert flight.recorder().snapshot() == []
    flight.reset()


def test_sigterm_dumps_ring(tmp_path):
    """A fatal signal dumps the ring before the process dies with the
    signal's own exit status (the launcher keys on it)."""
    script = (
        "import os, signal, time\n"
        "from horovod_tpu.runtime import flight\n"
        "assert flight.install_signal_handlers()\n"
        "flight.record('round', ph='B', round=7)\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
        "time.sleep(10)\n")
    env = dict(os.environ)
    env.update({"HOROVOD_FLIGHT_DIR": str(tmp_path),
                "HOROVOD_RANK": "3", "HOROVOD_SIZE": "4",
                "PYTHONPATH": REPO + os.pathsep
                + env.get("PYTHONPATH", "")})
    p = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == -signal.SIGTERM, (p.returncode, p.stderr)
    dumps = load_dumps(str(tmp_path))
    assert len(dumps) == 1, os.listdir(tmp_path)
    d = dumps[0]
    assert d.rank == 3 and d.meta["reason"] == "signal:SIGTERM"
    kinds = [e["kind"] for e in d.events]
    assert kinds[0] == "round" and "signal" in kinds


def test_failure_dump_flushes_terminal_metrics(tmp_path, monkeypatch):
    """Satellite regression: the abort/fatal-signal dump path must push
    one LAST KV metrics snapshot (the launcher aggregate otherwise
    keeps serving the final periodic publish, missing the terminal
    abort counters) — the metrics-plane mirror of PR 6's
    timeline-flush fix."""
    from horovod_tpu.common import basics

    published = []

    class FakePublisher:
        def publish(self):
            published.append(1)

    monkeypatch.setattr(basics.state(), "metrics_publisher",
                        FakePublisher())
    monkeypatch.setenv("HOROVOD_FLIGHT_DIR", str(tmp_path))
    flight.reset()
    path = flight.dump_on_failure("ranks_down")
    assert path and os.path.exists(path)
    assert published == [1]
    # no publisher configured: still dumps, still no crash
    monkeypatch.setattr(basics.state(), "metrics_publisher", None)
    assert flight.dump_on_failure("ranks_down") is not None
    flight.reset()


# ---------------------------------------------------------------------------
# Clock alignment
# ---------------------------------------------------------------------------


def _dump(tmp_path, rank, events, gen=1, size=2, **meta):
    r = flight.FlightRecorder(256)
    for ev in events:
        fields = {k: v for k, v in ev.items()
                  if k not in ("kind", "ph", "wall", "mono")}
        r.record(ev["kind"], ph=ev.get("ph", "i"), **fields)
    # overwrite the auto stamps with the scripted clocks
    with r._lock:
        for i, ev in enumerate(events):
            s, _, _, kind, ph, fields = r._slots[i]
            r._slots[i] = (s, ev.get("mono", float(i)),
                           ev.get("wall", float(i)), kind, ph, fields)
    path = str(tmp_path / f"flight-r{rank}-g{gen}-p{100 + rank}.jsonl")
    m = {"rank": rank, "size": size, "generation": gen}
    m.update(meta)
    r.dump(path, m)
    return path


def test_clock_offsets_two_way_ntp_bound(tmp_path):
    """Known true offset + asymmetric delays: the estimate must land
    within the reported bound of the truth, and the bound must equal
    (d1 + d2) / 2."""
    true = 0.8       # rank 1's clock runs 0.8 s behind rank 0's
    d1, d2 = 0.030, 0.010
    # rank 0 observed rank 1's beat: sample = (c0 - c1) + d1
    _dump(tmp_path, 0, [
        {"kind": "clk", "peer": 1, "wall": 100.0 + true + d1,
         "peer_wall": 100.0},
        {"kind": "clk", "peer": 1, "wall": 102.0 + true + d1 + 0.5,
         "peer_wall": 102.0},  # a slower sample: min() must win
    ])
    # rank 1 observed rank 0: sample = (c1 - c0) + d2
    _dump(tmp_path, 1, [
        {"kind": "clk", "peer": 0, "wall": 101.0 - true + d2,
         "peer_wall": 101.0},
    ])
    offsets = compute_offsets(load_dumps(str(tmp_path)))
    info = next(v for v in offsets.values() if v["rank"] == 1)
    assert info["mode"] == "two-way"
    est, bound = info["offset_s"], info["bound_s"]
    assert abs(bound - (d1 + d2) / 2) < 1e-9
    assert abs(est - true) <= bound + 1e-9
    ref = next(v for v in offsets.values() if v["rank"] == 0)
    assert ref["offset_s"] == 0.0 and ref["bound_s"] == 0.0


def test_clock_offsets_one_way_and_none(tmp_path):
    _dump(tmp_path, 0, [{"kind": "init"}])  # no samples at all
    _dump(tmp_path, 1, [
        {"kind": "clk", "peer": 0, "wall": 50.0, "peer_wall": 49.9}])
    offsets = compute_offsets(load_dumps(str(tmp_path)))
    one = next(v for v in offsets.values() if v["rank"] == 1)
    assert one["mode"] == "one-way"
    assert abs(one["offset_s"] - (-0.1)) < 1e-6
    _dump(tmp_path, 2, [{"kind": "init"}], size=3)
    offsets = compute_offsets(load_dumps(str(tmp_path)))
    none = next(v for v in offsets.values() if v["rank"] == 2)
    assert none["mode"] == "none" and none["bound_s"] is None


# ---------------------------------------------------------------------------
# Chrome trace + analyzer over synthetic dumps
# ---------------------------------------------------------------------------


def _synthetic_job(tmp_path):
    """Rank 0 (coordinator) saw 3 rounds; rank 1 arrived ~1 s late in
    each; rank 1's dump is missing (SIGKILL) and rank 0 aborted on it."""
    events = [{"kind": "init", "rank": 0}]
    for rnd in range(3):
        base = 10.0 * (rnd + 1)
        events += [
            {"kind": "round", "ph": "B", "round": rnd,
             "wall": base, "mono": base},
            {"kind": "arrive", "peer": 0, "round": rnd,
             "wall": base + 0.01, "mono": base + 0.01},
            {"kind": "arrive", "peer": 1, "round": rnd,
             "wall": base + 1.01, "mono": base + 1.01},
            {"kind": "round", "ph": "E", "round": rnd, "path": "slow",
             "wall": base + 1.2, "mono": base + 1.2},
            {"kind": "dispatch", "ph": "B", "wall": base + 1.3,
             "mono": base + 1.3},
            {"kind": "dispatch", "ph": "E", "wall": base + 1.5,
             "mono": base + 1.5},
        ]
    events += [
        {"kind": "round", "ph": "B", "round": 3, "wall": 40.0,
         "mono": 40.0},  # left open: rank 1 never arrived
        {"kind": "abort", "ranks": [1], "round": 3, "wall": 45.0,
         "mono": 45.0},
    ]
    return _dump(tmp_path, 0, events, reason="ranks_down")


def test_chrome_trace_schema_and_unfinished_spans(tmp_path):
    _synthetic_job(tmp_path)
    dumps = load_dumps(str(tmp_path))
    trace = chrome_trace(dumps, compute_offsets(dumps))
    evs = trace["traceEvents"]
    assert evs, "empty trace"
    for ev in evs:
        assert {"ts", "pid", "tid", "ph"} <= set(ev), ev
    names = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert any(n.startswith("rank 0 gen 1") for n in names), names
    # B/E balanced per (pid, tid): the open round 3 was closed
    depth = {}
    for e in evs:
        k = (e["pid"], e["tid"])
        if e["ph"] == "B":
            depth[k] = depth.get(k, 0) + 1
        elif e["ph"] == "E":
            depth[k] = depth.get(k, 0) - 1
            assert depth[k] >= 0, e
    assert all(v == 0 for v in depth.values()), depth
    unfinished = [e for e in evs
                  if (e.get("args") or {}).get("unfinished")]
    assert unfinished, "open round 3 span was not closed at dump time"


def test_chrome_trace_async_ids_scoped_per_rank(tmp_path):
    """Legacy Chrome async events pair globally by (cat, id), not per
    pid — and HandleManager numbering restarts per rank, so two ranks'
    'wait h1' spans must not share an id (the viewer would cross
    them)."""
    wait = [{"kind": "wait", "ph": "B", "handle": 1, "mono": 0.0},
            {"kind": "wait", "ph": "E", "handle": 1, "mono": 1.0}]
    _dump(tmp_path, 0, wait)
    _dump(tmp_path, 1, wait)
    dumps = load_dumps(str(tmp_path))
    trace = chrome_trace(dumps, compute_offsets(dumps))
    ids = {e["pid"]: e["id"] for e in trace["traceEvents"]
           if e["ph"] == "b"}
    assert len(ids) == 2 and len(set(ids.values())) == 2, ids


def test_trace_package_does_not_shadow_merge_submodule():
    import horovod_tpu.trace
    import horovod_tpu.trace.merge as m

    assert callable(m.load_dumps)  # module, not the merge() function
    assert callable(horovod_tpu.trace.merge_dumps)


def test_merge_writes_loadable_trace(tmp_path):
    _synthetic_job(tmp_path)
    out, dumps, offsets = merge(str(tmp_path))
    with open(out) as f:
        trace = json.load(f)
    assert trace["traceEvents"]
    assert "clock_offsets" in trace["otherData"]


def test_merge_empty_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        merge(str(tmp_path))


def test_analyzer_straggler_ranking(tmp_path):
    _synthetic_job(tmp_path)
    dumps = load_dumps(str(tmp_path))
    report = analyze(dumps, compute_offsets(dumps))
    st = report["stragglers"]
    assert st["rounds"] == 3
    top = st["ranking"][0]
    assert top["rank"] == 1 and top["last_count"] == 3
    assert 2.9 <= top["total_lateness_s"] <= 3.1
    assert 0.9 <= top["max_lateness_s"] <= 1.1
    assert sum(top["hist"].values()) == 3


def test_analyzer_stragglers_never_merge_generations(tmp_path):
    """Rank identities are reassigned at each elastic re-form: gen-1
    "rank 1" (a dead slow host) and gen-2 "rank 1" (an innocent
    replacement) must get SEPARATE ranking entries, not one summed
    "rank 1" blaming the new host for the old host's lateness."""
    def arrivals(rnd, late_by):
        return ([{"kind": "round", "ph": "B", "round": rnd,
                  "mono": 10.0 * rnd}]
                + [{"kind": "arrive", "peer": p, "round": rnd,
                    "mono": 10.0 * rnd + off}
                   for p, off in late_by.items()]
                + [{"kind": "round", "ph": "E", "round": rnd,
                    "mono": 10.0 * rnd + 9.0}])
    # gen 1: rank 1 is 2s late every round (the host that then dies)
    _dump(tmp_path, 0,
          arrivals(0, {0: 0.0, 1: 2.0}) + arrivals(1, {0: 0.0, 1: 2.0}),
          gen=1, reason="reform:2")
    # gen 2: the NEW rank 1 is on time; rank 0 is 0.1s late
    _dump(tmp_path, 0,
          arrivals(0, {0: 0.1, 1: 0.0}) + arrivals(1, {0: 0.1, 1: 0.0}),
          gen=2, reason="explicit")
    dumps = load_dumps(str(tmp_path))
    report = analyze(dumps, compute_offsets(dumps))
    # clock section must keep both generations' entries apart too
    # (rank-only keys would overwrite one with the other)
    assert sorted(report["clock"]) == ["0@g1", "0@g2"], report["clock"]
    st = report["stragglers"]
    by_key = {(r["generation"], r["rank"]): r for r in st["ranking"]}
    assert len(by_key) == 4, st["ranking"]
    assert by_key[(1, 1)]["total_lateness_s"] == pytest.approx(4.0)
    assert by_key[(2, 1)]["total_lateness_s"] == 0.0  # innocent
    assert st["ranking"][0]["generation"] == 1  # worst entry is gen-1
    text = format_report({"stragglers": st})
    assert "rank 1 g1: last-in 2x" in text  # multi-gen labels the gen


def test_analyzer_death_report_names_dead_rank(tmp_path):
    _synthetic_job(tmp_path)
    dumps = load_dumps(str(tmp_path))
    report = analyze(dumps, compute_offsets(dumps))
    deaths = report["deaths"]
    assert deaths["dead"] == [1]
    assert deaths["missing_dumps"] == [1]  # SIGKILL left no dump
    assert deaths["last_round"]["1"] == 2  # never arrived for round 3
    text = format_report(report)
    assert "DEAD rank(s): [1]" in text
    assert "last participated in round 2" in text
    assert "rank 1: last-in 3x" in text


def test_jaxcoord_try_get_fallback_deadline_covers_a_round_trip():
    """Regression for the bug that blinded clock sampling: on jaxlib
    builds without ``key_value_try_get`` the fallback blocking get used
    a 1 ms deadline no real gRPC round trip meets, so PRESENT keys
    read as absent — heartbeat sweeps never observed a beat value and
    liveness silently degraded to absence-only.  The fallback deadline
    must cover an actual round trip."""
    from horovod_tpu.runtime.controller import JaxCoordTransport

    class FakeClient:  # no key_value_try_get attribute
        def __init__(self):
            self.deadlines = []

        def blocking_key_value_get(self, key, ms):
            self.deadlines.append(ms)
            return "beat"

    t = JaxCoordTransport.__new__(JaxCoordTransport)
    t._c = FakeClient()
    assert t.try_get("hvd1/hb/1") == "beat"
    assert t._c.deadlines and t._c.deadlines[0] >= 20, t._c.deadlines


def test_analyzer_step_split(tmp_path):
    """hvd.trace_step() spans land on the record and the analyzer
    reports the per-step comm/compute/blocked split per rank."""
    _dump(tmp_path, 0, [
        {"kind": "step", "ph": "B", "step": 0, "wall": 1.0, "mono": 1.0},
        {"kind": "step", "ph": "E", "step": 0, "wall": 2.0, "mono": 2.0,
         "wall_s": 1.0, "compute_s": 0.7, "comm_s": 0.2,
         "blocked_s": 0.3},
        {"kind": "step", "ph": "B", "step": 1, "wall": 2.0, "mono": 2.0},
        {"kind": "step", "ph": "E", "step": 1, "wall": 4.0, "mono": 4.0,
         "wall_s": 2.0, "compute_s": 1.5, "comm_s": 0.1,
         "blocked_s": 0.5},
    ])
    dumps = load_dumps(str(tmp_path))
    report = analyze(dumps, compute_offsets(dumps))
    p = report["phases"][0]
    assert p["steps"] == 2
    assert abs(p["step_mean_s"] - 1.5) < 1e-6
    assert abs(p["step_max_s"] - 2.0) < 1e-6
    assert abs(p["step_blocked_total_s"] - 0.8) < 1e-6
    assert abs(p["step_compute_total_s"] - 2.2) < 1e-6
    text = format_report(report)
    assert "2 steps" in text


def test_trace_step_records_flight_events(hvd_single):
    """Integration: the live hvd.trace_step() span writes B/E step
    events with the split fields into the global ring."""
    flight.reset()
    with hvd_single.trace_step(step=7):
        time.sleep(0.01)
    evs = [e for e in flight.recorder().snapshot()
           if e["kind"] == "step"]
    assert [e["ph"] for e in evs] == ["B", "E"]
    assert evs[0]["step"] == 7 and evs[1]["step"] == 7
    assert evs[1]["wall_s"] >= 0.01
    for k in ("compute_s", "comm_s", "blocked_s"):
        assert k in evs[1]
    flight.reset()


def test_analyzer_no_false_deaths_without_failure_evidence(tmp_path):
    """A healthy job where only rank 0 dumped explicitly must not read
    as a massacre: missing dumps count as deaths only when surviving
    dumps corroborate an abnormal end (abort event, or a dump whose
    own trigger was a failure path / fatal signal / re-form)."""
    _dump(tmp_path, 0, [{"kind": "init"}], size=4, reason="explicit")
    dumps = load_dumps(str(tmp_path))
    report = analyze(dumps, compute_offsets(dumps))
    assert report["deaths"]["dead"] == [], report["deaths"]
    assert "no rank deaths observed" in format_report(report)
    # ...but the same hole in the dump set IS a death once a survivor
    # dumped on a fatal signal (the launcher's fail-fast teardown)
    _dump(tmp_path, 0, [{"kind": "init"}], size=4,
          reason="signal:SIGTERM")
    dumps = load_dumps(str(tmp_path))
    report = analyze(dumps, compute_offsets(dumps))
    assert report["deaths"]["dead"] == [1, 2, 3], report["deaths"]


def test_trace_cli_merge(tmp_path, capsys):
    from horovod_tpu.trace.__main__ import main

    _synthetic_job(tmp_path)
    assert main(["merge", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "wrote" in out and "flight-recorder report" in out
    assert os.path.exists(tmp_path / "trace.json")
    assert main(["analyze", str(tmp_path), "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["deaths"]["dead"] == [1]


def test_launcher_flight_sweep(tmp_path, capsys):
    from horovod_tpu.run import launcher

    assert launcher._sweep_flight_dir({}, "wrap-up") == []
    _synthetic_job(tmp_path)
    found = launcher._sweep_flight_dir(
        {"HOROVOD_FLIGHT_DIR": str(tmp_path)}, "wrap-up")
    assert len(found) == 1
    err = capsys.readouterr().err
    assert "flight recorder (wrap-up)" in err
    assert "horovod_tpu.trace merge" in err


# ---------------------------------------------------------------------------
# Multiprocess acceptance: straggler attribution + SIGKILL postmortem
# ---------------------------------------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_n(script: str, extra_env: dict, np_: int = 2,
             timeout: int = 240):
    port = _free_port()
    procs = []
    for r in range(np_):
        env = dict(os.environ)
        env.update({
            "HOROVOD_PLATFORM": "cpu",
            "HOROVOD_RANK": str(r),
            "HOROVOD_SIZE": str(np_),
            "HOROVOD_LOCAL_RANK": str(r),
            "HOROVOD_LOCAL_SIZE": str(np_),
            "HOROVOD_COORDINATOR_ADDR": f"localhost:{port}",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        env.update(extra_env)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for r, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError(f"rank {r} timed out")
        outs.append(out)
    return procs, outs


_spawn_two = _spawn_n


@pytest.mark.multiprocess
def test_straggler_attribution_2proc(tmp_path):
    """Acceptance: under ``delay@rank1:q/*:1s`` fault injection the
    analyzer must rank rank 1 first, with attributed lateness above
    0.5 s and within 2x of the injected 1 s delay."""
    flight_dir = str(tmp_path / "fl")
    script = r"""
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
for i in range(3):
    out = hvd.allreduce(jnp.ones(2), op=hvd.Sum, name="t%d" % i)
    assert np.allclose(np.asarray(out), 2.0), out
hvd.dump_flight_recorder()
print("DONE-%d" % hvd.rank(), flush=True)
hvd.shutdown()
"""
    procs, outs = _spawn_two(script, {
        "HOROVOD_FLIGHT_DIR": flight_dir,
        "HOROVOD_FAULT_SPEC": "delay@rank1:q/*:1s",
        # the delayed rank must not be declared dead mid-test
        "HOROVOD_HEARTBEAT_INTERVAL": "0.5",
        "HOROVOD_HEARTBEAT_TIMEOUT_SECONDS": "60",
        # cache off: every round ships explicit requests, so each
        # delayed q/<r>/<rank1> write is a measurable arrival
        "HOROVOD_CACHE_CAPACITY": "0",
    })
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"DONE-{r}" in out, out
    dumps = load_dumps(flight_dir)
    assert {d.rank for d in dumps} == {0, 1}, flight_dir
    report = analyze(dumps, compute_offsets(dumps))
    st = report["stragglers"]
    assert st["rounds"] >= 2, st
    top = st["ranking"][0]
    assert top["rank"] == 1, st["ranking"]
    # injected 1 s per round: attributed lateness in (0.5 s, 2 s)
    assert top["max_lateness_s"] > 0.5, top
    assert top["max_lateness_s"] < 2.0, top
    assert top["last_count"] >= 2, top
    # both ranks' clocks were sampled: offsets carry a measured bound,
    # and — same host, same physical clock — the estimated offset must
    # sit within that bound of the true offset (zero)
    clock = report["clock"]
    two_way = [v for v in clock.values() if v["mode"] == "two-way"]
    assert two_way, clock
    for v in two_way:
        assert v["bound_ms"] is not None
        assert abs(v["offset_ms"]) <= v["bound_ms"] + 1e-6, v


@pytest.mark.multiprocess
def test_straggler_attribution_3proc_blames_only_the_straggler(tmp_path):
    """World > 2 regression: with rank-ordered blocking gets, ranks
    that arrived DURING rank 1's injected delay were stamped when the
    coordinator's wait on rank 1 returned — blaming an innocent higher
    rank.  The fair-poll gather must attribute the lateness to rank 1
    alone."""
    flight_dir = str(tmp_path / "fl")
    script = r"""
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
for i in range(2):
    out = hvd.allreduce(jnp.ones(2), op=hvd.Sum, name="t%d" % i)
    assert np.allclose(np.asarray(out), 3.0), out
hvd.dump_flight_recorder()
print("DONE-%d" % hvd.rank(), flush=True)
hvd.shutdown()
"""
    procs, outs = _spawn_n(script, {
        "HOROVOD_FLIGHT_DIR": flight_dir,
        "HOROVOD_FAULT_SPEC": "delay@rank1:q/*:1s",
        "HOROVOD_HEARTBEAT_INTERVAL": "0.5",
        "HOROVOD_HEARTBEAT_TIMEOUT_SECONDS": "60",
        "HOROVOD_CACHE_CAPACITY": "0",
    }, np_=3)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
    dumps = load_dumps(flight_dir)
    report = analyze(dumps, compute_offsets(dumps))
    ranking = report["stragglers"]["ranking"]
    by_rank = {rec["rank"]: rec for rec in ranking}
    assert ranking[0]["rank"] == 1, ranking
    assert by_rank[1]["max_lateness_s"] > 0.5, by_rank
    # the innocent bystander must NOT inherit rank 1's delay
    assert by_rank[2]["max_lateness_s"] < 0.4, by_rank


@pytest.mark.multiprocess
def test_sigkill_postmortem_2proc(tmp_path):
    """Acceptance: SIGKILL rank 1 mid-job.  The survivor must write a
    dump on the coordinated abort, the dumps must merge into a valid
    Perfetto trace whose clocks agree within the measured bound, and
    the death report must name rank 1 and the last round it
    participated in."""
    flight_dir = str(tmp_path / "fl")
    hb_timeout = 5.0
    script = r"""
import os, signal, sys, time
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
rank = hvd.rank()
for i in range(2):
    out = hvd.allreduce(jnp.ones(2), op=hvd.Sum, name="warm%d" % i)
    assert np.allclose(np.asarray(out), 2.0), out
if rank == 1:
    print("RANK1-DYING", flush=True)
    os.kill(os.getpid(), signal.SIGKILL)
time.sleep(0.5)
try:
    hvd.allreduce(jnp.ones(2), op=hvd.Sum, name="after-death")
    print("NO-ERROR", flush=True)
except hvd.RanksDownError as e:
    assert 1 in e.ranks, (e.ranks, str(e))
    print("RANKSDOWN-OK", flush=True)
sys.stdout.flush()
os._exit(0)  # skip the shutdown barrier against a dead peer
"""
    procs, outs = _spawn_two(script, {
        "HOROVOD_FLIGHT_DIR": flight_dir,
        "HOROVOD_HEARTBEAT_INTERVAL": "0.5",
        "HOROVOD_HEARTBEAT_TIMEOUT_SECONDS": str(int(hb_timeout)),
        "HOROVOD_CACHE_CAPACITY": "0",
    })
    assert procs[1].returncode == -9, (procs[1].returncode, outs[1])
    assert procs[0].returncode == 0, outs[0]
    assert "RANKSDOWN-OK" in outs[0], outs[0]
    # every survivor wrote a dump; the dead rank could not
    dumps = load_dumps(flight_dir)
    assert {d.rank for d in dumps} == {0}, os.listdir(flight_dir)
    assert dumps[0].meta["reason"] == "ranks_down"
    # merge -> one valid Perfetto-loadable JSON
    out_path, dumps, offsets = merge(flight_dir)
    with open(out_path) as f:
        trace = json.load(f)
    for ev in trace["traceEvents"]:
        assert {"ts", "pid", "tid", "ph"} <= set(ev), ev
    # clock agreement: the survivor holds samples of the dead peer's
    # clock; same-host processes share a clock, so the estimated
    # offset must sit within the measured bound
    report = analyze(dumps, offsets)
    deaths = report["deaths"]
    assert deaths["dead"] == [1], deaths
    assert "last_round" in deaths and deaths["last_round"].get("1") \
        is not None, deaths
    assert int(deaths["last_round"]["1"]) >= 0
    text = format_report(report)
    assert "DEAD rank(s): [1]" in text, text
    # abort forensics on the survivor's ring
    kinds = {e["kind"] for e in dumps[0].events}
    assert "abort" in kinds and "hb_stale" in kinds, kinds
