"""Keras-style callbacks (reference ``horovod/_keras/callbacks.py``;
behavior asserted the way ``test/test_keras.py`` exercises warmup /
metric averaging, but against explicit optax loops)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest


def _sgd_state(lr=0.1, momentum=0.9):
    import horovod_tpu as hvd

    opt = hvd.DistributedOptimizer(
        optax.inject_hyperparams(optax.sgd)(learning_rate=lr,
                                            momentum=momentum))
    params = {"w": jnp.ones((4,))}
    return opt, params, opt.init(params)


def test_find_hyperparams_through_wrapper(hvd_single):
    from horovod_tpu.keras import TrainingState, find_hyperparams

    _, params, opt_state = _sgd_state()
    hp = find_hyperparams(opt_state)
    assert hp is not None
    assert float(np.asarray(hp["learning_rate"])) == pytest.approx(0.1)
    assert float(np.asarray(hp["momentum"])) == pytest.approx(0.9)
    assert find_hyperparams({"no": "hyperparams"}) is None
    TrainingState(params, opt_state)  # constructible


def test_schedule_requires_injected_lr(hvd_single):
    import horovod_tpu as hvd
    from horovod_tpu.keras import (CallbackList, LearningRateScheduleCallback,
                                   TrainingState)

    opt = hvd.DistributedOptimizer(optax.sgd(0.1))
    params = {"w": jnp.ones(2)}
    state = TrainingState(params, opt.init(params))
    cbs = CallbackList([LearningRateScheduleCallback(0.5)], state)
    with pytest.raises(ValueError):
        cbs.on_train_begin()


def test_staircase_schedule_and_lr_log(hvd_single):
    from horovod_tpu.keras import (CallbackList, LearningRateScheduleCallback,
                                   TrainingState, find_hyperparams)

    _, params, opt_state = _sgd_state(lr=0.2)
    state = TrainingState(params, opt_state)
    cb = LearningRateScheduleCallback(
        lambda epoch: 0.5 ** epoch, staircase=True, momentum_correction=False)
    cbs = CallbackList([cb], state)
    cbs.on_train_begin()
    lrs = {}
    for epoch in range(3):
        cbs.on_epoch_begin(epoch)
        cbs.on_batch_begin(0)
        cbs.on_batch_end(0)
        logs = {}
        cbs.on_epoch_end(epoch, logs)
        lrs[epoch] = logs["lr"]
    assert lrs[0] == pytest.approx(0.2)
    assert lrs[1] == pytest.approx(0.1)
    assert lrs[2] == pytest.approx(0.05)


def test_schedule_window_and_constant_multiplier(hvd_single):
    from horovod_tpu.keras import (CallbackList, LearningRateScheduleCallback,
                                   TrainingState, find_hyperparams)

    _, params, opt_state = _sgd_state(lr=0.1)
    state = TrainingState(params, opt_state)
    cb = LearningRateScheduleCallback(10.0, start_epoch=2, end_epoch=3,
                                      momentum_correction=False)
    cbs = CallbackList([cb], state)
    cbs.on_train_begin()
    hp = find_hyperparams(state.opt_state)
    for epoch in range(4):
        cbs.on_epoch_begin(epoch)
        cbs.on_batch_begin(0)
        cbs.on_batch_end(0)
    # only epoch 2 is inside [start, end)
    assert float(np.asarray(hp["learning_rate"])) == pytest.approx(1.0)


def test_momentum_correction_restores_after_batch(hvd_single):
    from horovod_tpu.keras import (CallbackList, LearningRateScheduleCallback,
                                   TrainingState, find_hyperparams)

    _, params, opt_state = _sgd_state(lr=0.1, momentum=0.9)
    state = TrainingState(params, opt_state)
    cb = LearningRateScheduleCallback(2.0, momentum_correction=True)
    cbs = CallbackList([cb], state)
    cbs.on_train_begin()
    hp = find_hyperparams(state.opt_state)
    cbs.on_epoch_begin(0)
    cbs.on_batch_begin(0)
    # during the adjusted batch: momentum scaled by new_lr/old_lr = 2
    assert float(np.asarray(hp["momentum"])) == pytest.approx(1.8)
    cbs.on_batch_end(0)
    assert float(np.asarray(hp["momentum"])) == pytest.approx(0.9)


def test_warmup_reaches_full_lr(hvd_single):
    """At size==1 the warmup multiplier is identically 1 (no rescale);
    the schedule math itself is checked against the closed form."""
    from horovod_tpu.keras import (CallbackList, LearningRateWarmupCallback,
                                   TrainingState, find_hyperparams)

    _, params, opt_state = _sgd_state(lr=0.4)
    state = TrainingState(params, opt_state)
    steps = 5
    cb = LearningRateWarmupCallback(warmup_epochs=3, steps_per_epoch=steps,
                                    momentum_correction=False)
    cbs = CallbackList([cb], state)
    cbs.on_train_begin()
    hp = find_hyperparams(state.opt_state)
    for epoch in range(4):
        cbs.on_epoch_begin(epoch)
        for b in range(steps):
            cbs.on_batch_begin(b)
            cbs.on_batch_end(b)
        cbs.on_epoch_end(epoch, {})
    assert float(np.asarray(hp["learning_rate"])) == pytest.approx(0.4)


def test_warmup_multiplier_math_multirank(monkeypatch, hvd_single):
    """Check the reference multiplier formula against a faked size=4."""
    import horovod_tpu.common.basics as basics
    from horovod_tpu.keras import LearningRateWarmupCallback

    cb = LearningRateWarmupCallback(warmup_epochs=5, steps_per_epoch=10)
    monkeypatch.setattr(basics, "size", lambda: 4)
    m0 = cb.multiplier(0.0)
    m_end = cb.multiplier(5.0 - 1.0 / 10)
    # epoch~0: ~1/size; end of warmup: 1.0
    assert m0 == pytest.approx((1 / 4) * ((0.1 * 3 / 5) + 1))
    assert m_end == pytest.approx(1.0)


def test_metric_average_identity_single(hvd_single):
    from horovod_tpu.keras import (CallbackList, MetricAverageCallback,
                                   TrainingState)

    _, params, opt_state = _sgd_state()
    cbs = CallbackList([MetricAverageCallback()],
                       TrainingState(params, opt_state))
    logs = {"loss": 2.5, "acc": 0.75, "name": "skipme"}
    cbs.on_epoch_end(0, logs)
    assert logs["loss"] == pytest.approx(2.5)
    assert logs["acc"] == pytest.approx(0.75)
    assert logs["name"] == "skipme"


def test_broadcast_callback_runs_once(hvd_single):
    from horovod_tpu.keras import (BroadcastGlobalVariablesCallback,
                                   CallbackList, TrainingState)

    _, params, opt_state = _sgd_state()
    state = TrainingState(params, opt_state)
    cb = BroadcastGlobalVariablesCallback(0)
    cbs = CallbackList([cb], state)
    assert not cb.broadcast_done
    cbs.on_batch_end(0)
    assert cb.broadcast_done
    np.testing.assert_allclose(np.asarray(state.params["w"]), 1.0)
    cbs.on_batch_end(1)  # no-op second time


def test_full_loop_trains(hvd_single):
    """Integration: warmup + metric averaging + broadcast on a real
    optimization loop reduces the loss."""
    import horovod_tpu as hvd
    from horovod_tpu.keras import (BroadcastGlobalVariablesCallback,
                                   CallbackList, LearningRateWarmupCallback,
                                   MetricAverageCallback, TrainingState)

    opt = hvd.DistributedOptimizer(
        optax.inject_hyperparams(optax.sgd)(learning_rate=0.3, momentum=0.5))
    params = {"w": jnp.array([2.0, -3.0])}
    state = TrainingState(params, opt.init(params))
    cbs = CallbackList([BroadcastGlobalVariablesCallback(0),
                        MetricAverageCallback(),
                        LearningRateWarmupCallback(warmup_epochs=2,
                                                   steps_per_epoch=4)],
                       state)

    def loss_fn(p):
        return jnp.sum(p["w"] ** 2)

    cbs.on_train_begin()
    losses = []
    for epoch in range(3):
        cbs.on_epoch_begin(epoch)
        for b in range(4):
            cbs.on_batch_begin(b)
            grads = jax.grad(loss_fn)(state.params)
            updates, state.opt_state = opt.update(grads, state.opt_state,
                                                  state.params)
            state.params = optax.apply_updates(state.params, updates)
            cbs.on_batch_end(b)
        logs = {"loss": float(loss_fn(state.params))}
        cbs.on_epoch_end(epoch, logs)
        losses.append(logs["loss"])
    assert losses[-1] < losses[0] * 0.1


def test_warmup_guard_matches_tf_sibling():
    """Fractional warmup_epochs (the removed (initial_lr, epochs)
    positional signature) fails loudly; integer-likes pass."""
    import numpy as np
    import pytest

    from horovod_tpu.keras.callbacks import LearningRateWarmupCallback

    LearningRateWarmupCallback(warmup_epochs=np.int64(3))
    LearningRateWarmupCallback(warmup_epochs=3.0)
    with pytest.raises(TypeError, match="positive integer"):
        LearningRateWarmupCallback(0.001, 1)
