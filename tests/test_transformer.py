"""Flagship transformer: composed dp*tp*sp (+pp, +ep) training on the
8-device mesh.  This is the capability the reference never had (DP
only) exercised end to end: loss decreases under every mesh layout and
the layouts agree with each other.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.models.transformer import (TransformerConfig, init_params,
                                            make_train_step, shard_params)
from horovod_tpu.parallel.mesh import make_mesh

CFG = TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                        n_layers=4, d_ff=64, max_seq=64)


def _data(mesh, batch=8, seq=32, seed=0):
    rng = np.random.RandomState(seed)
    tokens = jnp.asarray(rng.randint(0, CFG.vocab, (batch, seq)),
                         dtype=jnp.int32)
    targets = jnp.asarray(rng.randint(0, CFG.vocab, (batch, seq)),
                          dtype=jnp.int32)
    sh = NamedSharding(mesh, P("dp", "sp"))
    return jax.device_put(tokens, sh), jax.device_put(targets, sh)


def _train(cfg, mesh, steps=8, seed=0):
    params = init_params(np.random.RandomState(seed), cfg,
                         ep=mesh.shape["dp"])
    params = shard_params(params, cfg, mesh)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    step = make_train_step(cfg, mesh, opt)
    tokens, targets = _data(mesh)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    return losses


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_dp_tp_sp_training_loss_decreases():
    mesh = make_mesh(dp=2, pp=1, tp=2, sp=2)
    losses = _train(CFG, mesh)
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] - 0.1, losses


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_steps_per_dispatch_matches_single_step():
    """k chained steps in one program (steps_per_dispatch, the
    tunnel-amortizing bench mode) must walk the same trajectory as k
    separate dispatches."""
    mesh = make_mesh(dp=2, pp=1, tp=2, sp=2)

    def run(spd, calls):
        params = init_params(np.random.RandomState(0), cfg=CFG,
                             ep=mesh.shape["dp"])
        params = shard_params(params, CFG, mesh)
        opt = optax.sgd(1e-2)  # stateless-ish, deterministic
        opt_state = opt.init(params)
        step = make_train_step(CFG, mesh, opt, steps_per_dispatch=spd)
        tokens, targets = _data(mesh)
        for _ in range(calls):
            params, opt_state, loss = step(params, opt_state, tokens,
                                           targets)
        return float(loss), params

    loss_a, params_a = run(spd=1, calls=4)
    loss_b, params_b = run(spd=4, calls=1)
    assert np.isclose(loss_a, loss_b, rtol=1e-4), (loss_a, loss_b)
    flat_a = jax.tree_util.tree_leaves(params_a)
    flat_b = jax.tree_util.tree_leaves(params_b)
    for a, b in zip(flat_a, flat_b):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.slow  # ~15 s pipeline training compile (ci.sh full suite)
def test_pipeline_parallel_training():
    mesh = make_mesh(dp=1, pp=2, tp=2, sp=2)
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=4, d_ff=64, max_seq=64,
                            pp_microbatches=2)
    losses = _train(cfg, mesh)
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] - 0.1, losses


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_pipeline_interleaved_matches_gpipe():
    """Interleaved schedule (pp_virtual=2) is the same math as GPipe —
    identical loss trajectory on the same model/data — with a V-fold
    smaller bubble (schedule length asserted in test_pipeline_moe).
    n_layers=8 puts TWO layers in every chunk (per=2), covering the
    within-chunk fori_loop and the layer storage permutation at
    per > 1."""
    mesh = make_mesh(dp=1, pp=2, tp=2, sp=2)
    base = dict(vocab=64, d_model=32, n_heads=4, head_dim=8,
                n_layers=8, d_ff=64, max_seq=64, pp_microbatches=2)
    l_gpipe = _train(TransformerConfig(**base), mesh, steps=4)
    l_inter = _train(TransformerConfig(**base, pp_schedule="interleaved",
                                       pp_virtual=2), mesh, steps=4)
    assert np.isfinite(l_inter).all(), l_inter
    assert l_inter[-1] < l_inter[0] - 0.1, l_inter
    np.testing.assert_allclose(l_inter, l_gpipe, rtol=2e-2)


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_moe_expert_parallel_training():
    mesh = make_mesh(dp=4, pp=1, tp=1, sp=2)
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=4, d_ff=64, max_seq=64,
                            moe_every=2, experts_per_rank=2)
    losses = _train(cfg, mesh)
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] - 0.1, losses


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_layouts_agree():
    """Same model/data, different mesh layouts -> same loss trajectory
    (SPMD correctness of the tp/sp decomposition)."""
    l_dp = _train(CFG, make_mesh(dp=8, pp=1, tp=1, sp=1), steps=3)
    l_tpsp = _train(CFG, make_mesh(dp=2, pp=1, tp=2, sp=2), steps=3)
    np.testing.assert_allclose(l_dp, l_tpsp, rtol=2e-2)


def _train_sgd(cfg, mesh, steps):
    """Scale-sensitive trainer: plain SGD exposes any world-size factor
    in the gradients that adam's normalization would hide."""
    params = init_params(np.random.RandomState(0), cfg,
                         ep=mesh.shape["dp"])
    params = shard_params(params, cfg, mesh)
    opt = optax.sgd(0.5)
    opt_state = opt.init(params)
    step = make_train_step(cfg, mesh, opt)
    tokens, targets = _data(mesh)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    return losses


@pytest.mark.slow  # ~15 s compile; parity also covered per-op (ci.sh full)
def test_gradient_scale_matches_single_device():
    """Distributed gradients must equal the single-device global-mean
    gradient exactly — no dp/sp/tp world-size inflation (the Megatron
    f/g transpose discipline + psum-free local loss)."""
    import jax

    golden = _train_sgd(CFG, make_mesh(dp=1, pp=1, tp=1, sp=1,
                                       devices=jax.devices()[:1]), 3)
    distributed = _train_sgd(CFG, make_mesh(dp=2, pp=1, tp=2, sp=2), 3)
    # rtol bounds bf16 reduction-order noise while still failing on any
    # world-size factor (which would be 2x-8x)
    np.testing.assert_allclose(distributed, golden, rtol=1e-2)


def test_bad_pp_schedule_config_raises():
    base = dict(vocab=64, d_model=32, n_heads=4, head_dim=8,
                n_layers=4, d_ff=64, max_seq=64)
    with pytest.raises(ValueError):
        TransformerConfig(**base, pp_schedule="1f1b")
    with pytest.raises(ValueError):
        TransformerConfig(**base, pp_virtual=2)  # gpipe + virtual>1


def test_moe_under_pp_raises():
    mesh = make_mesh(dp=2, pp=2, tp=1, sp=2)
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=4, d_ff=64, max_seq=64, moe_every=2)
    with pytest.raises(Exception):
        _train(cfg, mesh, steps=1)
