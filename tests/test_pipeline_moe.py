"""Pipeline (GPipe over 'pp') and MoE (expert-parallel over 'ep')
correctness on the 8-device mesh.  Both are TPU extensions beyond the
reference (SURVEY §2.7); validated against single-device golden models.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.parallel.moe import moe_layer, moe_reference
from horovod_tpu.parallel.pipeline import (gpipe, interleaved_schedule,
                                           interleaved_stage_split,
                                           pipeline)

NSTAGES = 8
M, MB, F = 4, 2, 3  # microbatches, microbatch size, features


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:NSTAGES]), ("pp",))


def test_gpipe_matches_sequential(mesh):
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(NSTAGES, F, F).astype(np.float32)) * 0.5
    b = jnp.asarray(rng.randn(NSTAGES, F).astype(np.float32)) * 0.1
    x = jnp.asarray(rng.randn(M, MB, F).astype(np.float32))

    def stage(params, h):
        wp, bp = params
        return jnp.tanh(h @ wp[0] + bp[0])

    def per_rank(wp, bp, xin):
        return gpipe(stage, (wp, bp), xin, "pp")

    fn = jax.jit(shard_map(per_rank, mesh=mesh, check_vma=False,
                           in_specs=(P("pp"), P("pp"), P()),
                           out_specs=P()))
    out = np.asarray(fn(w, b, x))

    expected = np.asarray(x)
    for s in range(NSTAGES):
        expected = np.tanh(expected @ np.asarray(w[s]) + np.asarray(b[s]))
    np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)


def test_gpipe_trains(mesh):
    """Pipeline is differentiable end-to-end: a few SGD steps reduce a
    regression loss."""
    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.randn(NSTAGES, F, F).astype(np.float32)) * 0.3
    x = jnp.asarray(rng.randn(M, MB, F).astype(np.float32))
    target = jnp.asarray(rng.randn(M, MB, F).astype(np.float32))

    def stage(wp, h):
        return jnp.tanh(h @ wp[0])

    def per_rank(wp, xin, tgt):
        def loss(wl):
            out = gpipe(stage, wl, xin, "pp")
            return jnp.mean((out - tgt) ** 2)

        l, g = jax.value_and_grad(loss)(wp)
        return l.reshape(1), g

    fn = jax.jit(shard_map(per_rank, mesh=mesh, check_vma=False,
                           in_specs=(P("pp"), P(), P()),
                           out_specs=(P(), P("pp"))))
    losses = []
    for _ in range(5):
        l, g = fn(w, x, target)
        losses.append(float(l[0]))
        assert np.isfinite(np.asarray(g)).all()
        w = w - 0.2 * g
    assert losses[-1] < losses[0], losses


IP, IV, IM = 4, 2, 8  # interleaved: ranks, virtual chunks, microbatches


def _check_schedule(P, V, M):
    """Validity invariants: ready-respecting, each (chunk, mb) exactly
    once, chunks on their owner ranks."""
    steps, run = interleaved_schedule(P, V, M)
    done = {}
    for t, row in enumerate(run):
        assert len(row) == P
        for p, item in enumerate(row):
            if item is None:
                continue
            c, mb = item
            assert 0 <= c < P * V and 0 <= mb < M
            assert c % P == p  # chunk lives on its owner rank
            assert item not in done
            if c > 0:  # activation produced strictly earlier
                assert done[(c - 1, mb)] < t
            done[item] = t
    assert len(done) == P * V * M
    return steps


def test_interleaved_schedule_valid_and_shorter():
    """Greedy schedule is ready-respecting, covers every (chunk, mb)
    exactly once, and beats GPipe's bubble: M*V + P - 1 chunk-steps vs
    (M + P - 1) * V (VERDICT r4 #5: step-count improvement at P=4,
    M=8)."""
    steps = _check_schedule(IP, IV, IM)
    assert steps == IM * IV + IP - 1 == 19
    assert steps < (IM + IP - 1) * IV == 22


def test_interleaved_schedule_property_grid():
    """Validity holds across the (P, V, M) grid, including M < P and
    V=1 (which must reproduce GPipe's M + P - 1 length); at M >= P the
    greedy schedule stays work-optimal-plus-fill."""
    for P in (1, 2, 3, 4):
        for V in (1, 2, 3):
            for M in (1, 2, 4, 8):
                steps = _check_schedule(P, V, M)
                if V == 1:
                    assert steps == M + P - 1, (P, V, M, steps)
                if M >= P:
                    assert steps == M * V + P - 1, (P, V, M, steps)


@pytest.fixture(scope="module")
def imesh():
    return Mesh(np.array(jax.devices()[:IP]), ("pp",))


def _interleaved_params(rng, scale=0.5):
    """(P, V, 1, F, F) weight stack: [p, v] holds chunk v*P + p (one
    layer per chunk, D = P*V layers total), laid out by the canonical
    `interleaved_stage_split` helper."""
    w_layers = jnp.asarray(
        rng.randn(IP * IV, 1, F, F).astype(np.float32) * scale)
    stacked = jnp.stack([
        interleaved_stage_split(w_layers.reshape(IP * IV, F, F), IP, IV, p)
        for p in range(IP)])
    return w_layers, stacked


def test_interleaved_matches_sequential(imesh):
    rng = np.random.RandomState(4)
    w_layers, stacked = _interleaved_params(rng)
    x = jnp.asarray(rng.randn(IM, MB, F).astype(np.float32))

    def stage(wp, h):
        return jnp.tanh(h @ wp[0])

    def per_rank(wp, xin):
        return pipeline(stage, wp[0], xin, "pp",
                        schedule="interleaved", n_virtual=IV)

    fn = jax.jit(shard_map(per_rank, mesh=imesh, check_vma=False,
                           in_specs=(P("pp"), P()), out_specs=P()))
    out = np.asarray(fn(stacked, x))

    expected = np.asarray(x)
    for c in range(IP * IV):
        expected = np.tanh(expected @ np.asarray(w_layers[c, 0]))
    np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)


def test_interleaved_trains(imesh):
    """Interleaved pipeline is differentiable: SGD reduces a regression
    loss and grads reach every chunk."""
    rng = np.random.RandomState(5)
    _, stacked = _interleaved_params(rng, scale=0.3)
    x = jnp.asarray(rng.randn(IM, MB, F).astype(np.float32))
    target = jnp.asarray(rng.randn(IM, MB, F).astype(np.float32))

    def stage(wp, h):
        return jnp.tanh(h @ wp[0])

    def per_rank(wp, xin, tgt):
        def loss(wl):
            out = pipeline(stage, wl[0], xin, "pp",
                           schedule="interleaved", n_virtual=IV)
            return jnp.mean((out - tgt) ** 2)

        l, g = jax.value_and_grad(loss)(wp)
        return l.reshape(1), g

    fn = jax.jit(shard_map(per_rank, mesh=imesh, check_vma=False,
                           in_specs=(P("pp"), P(), P()),
                           out_specs=(P(), P("pp"))))
    w = stacked
    losses = []
    for _ in range(5):
        l, g = fn(w, x, target)
        g_np = np.asarray(g)
        assert np.isfinite(g_np).all()
        # every chunk's weights receive gradient signal
        assert (np.abs(g_np).reshape(IP * IV, -1).max(axis=1) > 0).all()
        losses.append(float(l[0]))
        w = w - 0.2 * g
    assert losses[-1] < losses[0], losses


def test_remat_grads_match(imesh):
    """remat=True recomputes stage internals in backward; gradients
    must be bit-for-bit the same math (fp-noise tolerance) for both
    schedules."""
    rng = np.random.RandomState(6)
    w_layers, stacked = _interleaved_params(rng, scale=0.3)
    x = jnp.asarray(rng.randn(IM, MB, F).astype(np.float32))
    target = jnp.asarray(rng.randn(IM, MB, F).astype(np.float32))

    def one_layer(wp, h):  # interleaved chunk: wp (1, F, F)
        return jnp.tanh(h @ wp[0])

    def two_layers(wp, h):  # gpipe stage: wp (2, F, F), layers in order
        return jnp.tanh(jnp.tanh(h @ wp[0]) @ wp[1])

    def grads(schedule, stage, w, n_virtual, remat):
        def per_rank(wp, xin, tgt):
            def loss(wl):
                out = pipeline(stage, wl[0], xin, "pp",
                               schedule=schedule, n_virtual=n_virtual,
                               remat=remat)
                return jnp.mean((out - tgt) ** 2)

            return jax.grad(loss)(wp)

        fn = jax.jit(shard_map(per_rank, mesh=imesh, check_vma=False,
                               in_specs=(P("pp"), P(), P()),
                               out_specs=P("pp")))
        return np.asarray(fn(w, x, target))

    # interleaved: stacked (P, V, 1, F, F); gpipe: rank p holds layers
    # (2p, 2p+1) contiguously
    w_gpipe = jnp.asarray(np.asarray(w_layers).reshape(IP, 2, F, F))

    gi = grads("interleaved", one_layer, stacked, IV, remat=False)
    gi_r = grads("interleaved", one_layer, stacked, IV, remat=True)
    np.testing.assert_allclose(gi_r, gi, rtol=1e-6, atol=1e-7)

    gg = grads("gpipe", two_layers, w_gpipe, 1, remat=False)
    gg_r = grads("gpipe", two_layers, w_gpipe, 1, remat=True)
    np.testing.assert_allclose(gg_r, gg, rtol=1e-6, atol=1e-7)


EP = 8
T, DIM, FFH = 32, 8, 16
E_LOCAL = 2
E = EP * E_LOCAL


@pytest.fixture(scope="module")
def ep_mesh():
    return Mesh(np.array(jax.devices()[:EP]), ("ep",))


def test_moe_matches_reference(ep_mesh):
    rng = np.random.RandomState(2)
    router = jnp.asarray(rng.randn(DIM, E).astype(np.float32)) * 0.5
    w_in = jnp.asarray(rng.randn(E, DIM, FFH).astype(np.float32)) * 0.3
    w_out = jnp.asarray(rng.randn(E, FFH, DIM).astype(np.float32)) * 0.3
    x = jnp.asarray(rng.randn(EP, T, DIM).astype(np.float32))

    def per_rank(xb, wi, wo):
        out, aux = moe_layer(xb[0], router, wi, wo, "ep",
                             capacity_factor=1.5)
        return out[None], aux.reshape(1)

    fn = jax.jit(shard_map(per_rank, mesh=ep_mesh, check_vma=False,
                           in_specs=(P("ep"), P("ep"), P("ep")),
                           out_specs=(P("ep"), P("ep"))))
    out, aux = fn(x, w_in, w_out)
    out = np.asarray(out)
    assert np.isfinite(np.asarray(aux)).all()

    # Golden: per-rank routing/capacity is local, expert math global.
    for r in range(EP):
        ref = moe_reference(x[r], router, w_in, w_out,
                            capacity_factor=1.5)
        np.testing.assert_allclose(out[r], np.asarray(ref), rtol=1e-4,
                                   atol=1e-5)


def test_moe_grads_flow(ep_mesh):
    rng = np.random.RandomState(3)
    router = jnp.asarray(rng.randn(DIM, E).astype(np.float32)) * 0.5
    w_in = jnp.asarray(rng.randn(E, DIM, FFH).astype(np.float32)) * 0.3
    w_out = jnp.asarray(rng.randn(E, FFH, DIM).astype(np.float32)) * 0.3
    x = jnp.asarray(rng.randn(EP, T, DIM).astype(np.float32))

    def per_rank(xb, wi, wo):
        def loss(wi_, wo_):
            out, aux = moe_layer(xb[0], router, wi_, wo_, "ep")
            return jnp.sum(out ** 2) + 0.01 * aux

        gi, go = jax.grad(loss, argnums=(0, 1))(wi, wo)
        return gi, go

    fn = jax.jit(shard_map(per_rank, mesh=ep_mesh, check_vma=False,
                           in_specs=(P("ep"), P("ep"), P("ep")),
                           out_specs=(P("ep"), P("ep"))))
    gi, go = fn(x, w_in, w_out)
    assert np.isfinite(np.asarray(gi)).all()
    assert np.abs(np.asarray(gi)).max() > 0
    assert np.isfinite(np.asarray(go)).all()
