"""Estimator/Store tests — the analog of reference
``test_spark_keras.py``/``test_spark_torch.py`` (Estimator fit/transform
on tiny data with a local store) without the Spark dependency."""

import os

import numpy as np
import pytest

from horovod_tpu.estimator import (JaxEstimator, LocalStore, Store,
                                   TorchEstimator)

pytestmark = pytest.mark.multiprocess


def test_local_store_layout(tmp_path):
    store = Store.create(str(tmp_path / "store"))
    assert isinstance(store, LocalStore)
    ckpt = store.get_checkpoint_path("run1")
    logs = store.get_logs_path("run1")
    train = store.get_train_data_path("run1")
    assert ckpt != logs != train
    for p in (ckpt, logs, train):
        assert p.startswith(store.prefix_path)
        store.make_dir(p)
        assert store.exists(p)
    store.cleanup_run("run1")
    assert not store.exists(train)
    assert store.exists(ckpt)      # checkpoints survive cleanup


def test_jax_estimator_fit_predict(tmp_path):
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(16)(x))
            return nn.Dense(3)(x)

    rng = np.random.RandomState(0)
    x = rng.rand(64, 8).astype(np.float32)
    y = rng.randint(0, 3, 64)

    store = LocalStore(str(tmp_path / "store"))
    est = JaxEstimator(model=MLP(), loss="softmax_cross_entropy",
                       lr=1e-2, store=store, num_proc=2, batch_size=16,
                       epochs=2, run_id="jaxrun")
    model = est.fit(x, y)
    preds = model.predict(x)
    assert preds.shape == (64, 3)
    assert len(model.history) == 2
    assert np.isfinite(model.history).all()
    # checkpoint written by rank 0 per epoch; intermediate data cleaned
    ckpt = os.path.join(store.get_checkpoint_path("jaxrun"), "last.ckpt")
    assert os.path.exists(ckpt)
    assert not store.exists(store.get_train_data_path("jaxrun"))


def test_torch_estimator_fit_predict(tmp_path):
    import torch.nn as tnn

    model = tnn.Sequential(tnn.Linear(4, 8), tnn.ReLU(), tnn.Linear(8, 2))
    rng = np.random.RandomState(1)
    x = rng.rand(48, 4).astype(np.float32)
    y = rng.randint(0, 2, 48)

    store = LocalStore(str(tmp_path / "store"))
    est = TorchEstimator(model=model, lr=1e-2, store=store, num_proc=2,
                         batch_size=8, epochs=2, run_id="torchrun")
    trained = est.fit(x, y)
    preds = trained.predict(x)
    assert preds.shape == (48, 2)
    assert len(trained.history) == 2
    ckpt = os.path.join(store.get_checkpoint_path("torchrun"),
                        "last.ckpt")
    assert os.path.exists(ckpt)


def test_spark_gate_message():
    import horovod_tpu.spark as hspark

    with pytest.raises(ImportError, match="horovod_tpu.estimator"):
        hspark.run(lambda: None, num_proc=1)


def test_checkpoint_save_restore_resync(tmp_path, hvd_single):
    import jax.numpy as jnp

    from horovod_tpu import checkpoint as ckpt

    tree = {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones(3)}
    path = str(tmp_path / "ckpts")
    ckpt.save(path, tree, step=1)
    ckpt.save(path, {"w": tree["w"] * 2, "b": tree["b"]}, step=5)
    assert ckpt.latest_step(path) == 5
    restored = ckpt.restore(path)           # latest
    assert np.allclose(restored["w"], np.arange(6.0).reshape(2, 3) * 2)
    old = ckpt.restore(path, step=1)
    assert np.allclose(old["w"], np.arange(6.0).reshape(2, 3))
    synced = ckpt.resync(restored)
    assert np.allclose(np.asarray(synced["b"]), 1.0)


def test_checkpoint_resume_2proc(tmp_path):
    from test_multiprocess import run_ranks

    run_ranks("""
        from horovod_tpu import checkpoint as ckpt
        shared = os.environ["HVD_TEST_CKPT_DIR"]
        tree = {"w": jnp.full((4,), float(rank + 1))}
        ckpt.save(shared, tree, step=3)         # only rank 0 writes
        hvd.barrier()
        restored = ckpt.restore(shared)
        restored = ckpt.resync(restored)        # all ranks -> rank 0's
        assert np.allclose(np.asarray(restored["w"]), 1.0)
        assert ckpt.latest_step(shared) == 3
    """, extra_env={"HVD_TEST_CKPT_DIR": str(tmp_path / "shared")})
