"""Estimator/Store tests — the analog of reference
``test_spark_keras.py``/``test_spark_torch.py`` (Estimator fit/transform
on tiny data with a local store) without the Spark dependency."""

import os

import numpy as np
import pytest

from horovod_tpu.estimator import (JaxEstimator, KVStore, LocalStore,
                                   Store, TorchEstimator)

pytestmark = pytest.mark.multiprocess


def test_kv_store_blob_roundtrip():
    """KVStore (the HDFSStore analog): blob IO over the authed TCP KV
    wire, picklable into a training spec, cleanup drops intermediate
    data only."""
    import pickle

    store = KVStore()
    try:
        train = store.get_train_data_path("r1")
        ckpt = store.get_checkpoint_path("r1")
        store.write_bytes(f"{train}/part.0.npz", b"\x00shardbytes\xff")
        store.write_bytes(f"{ckpt}/last.ckpt", b"ckptbytes")
        assert store.read_bytes(f"{train}/part.0.npz") == \
            b"\x00shardbytes\xff"
        assert store.exists(f"{train}/part.0.npz")
        assert store.exists(train)  # directory = tracked-key prefix
        # a rank's view: pickled copy carries (addr, port, secret) only
        remote = pickle.loads(pickle.dumps(store))
        assert remote._server is None
        assert remote.read_bytes(f"{ckpt}/last.ckpt") == b"ckptbytes"
        store.cleanup_run("r1")
        assert store._kv().try_get(f"{train}/part.0.npz") is None
        assert store.read_bytes(f"{ckpt}/last.ckpt") == b"ckptbytes"
        remote.stop()
    finally:
        store.stop()


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_jax_estimator_fit_predict_kvstore(tmp_path, monkeypatch):
    """2-proc estimator fit/predict with NO shared filesystem: shards
    and checkpoints ride the KV store; the working dir stays empty
    (VERDICT r4 #3 done-criterion)."""
    import flax.linen as nn

    monkeypatch.chdir(tmp_path)  # any stray file writes would land here

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(3)(nn.relu(nn.Dense(16)(x)))

    rng = np.random.RandomState(1)
    x = rng.rand(64, 8).astype(np.float32)
    y = rng.randint(0, 3, 64)

    store = KVStore()
    try:
        est = JaxEstimator(model=MLP(), loss="softmax_cross_entropy",
                           lr=1e-2, store=store, num_proc=2,
                           batch_size=16, epochs=2, run_id="kvrun")
        model = est.fit(x, y)
        preds = model.predict(x)
        assert preds.shape == (64, 3)
        assert len(model.history) == 2
        assert np.isfinite(model.history).all()
        # checkpoint lives in the KV store, not on disk
        import pickle

        ckpt = pickle.loads(store.read_bytes(
            f"{store.get_checkpoint_path('kvrun')}/last.ckpt"))
        assert ckpt["epoch"] == 1
        # intermediate shards were cleaned; nothing ever hit the fs
        assert store._kv().try_get(
            f"{store.get_train_data_path('kvrun')}/part.0.npz") is None
        stray = [p for p in tmp_path.rglob("*") if p.is_file()]
        assert not stray, stray
    finally:
        store.stop()


def test_local_store_layout(tmp_path):
    store = Store.create(str(tmp_path / "store"))
    assert isinstance(store, LocalStore)
    ckpt = store.get_checkpoint_path("run1")
    logs = store.get_logs_path("run1")
    train = store.get_train_data_path("run1")
    assert ckpt != logs != train
    for p in (ckpt, logs, train):
        assert p.startswith(store.prefix_path)
        store.make_dir(p)
        assert store.exists(p)
    store.cleanup_run("run1")
    assert not store.exists(train)
    assert store.exists(ckpt)      # checkpoints survive cleanup


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_jax_estimator_fit_predict(tmp_path):
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(16)(x))
            return nn.Dense(3)(x)

    rng = np.random.RandomState(0)
    x = rng.rand(64, 8).astype(np.float32)
    y = rng.randint(0, 3, 64)

    store = LocalStore(str(tmp_path / "store"))
    est = JaxEstimator(model=MLP(), loss="softmax_cross_entropy",
                       lr=1e-2, store=store, num_proc=2, batch_size=16,
                       epochs=2, run_id="jaxrun")
    model = est.fit(x, y)
    preds = model.predict(x)
    assert preds.shape == (64, 3)
    assert len(model.history) == 2
    assert np.isfinite(model.history).all()
    # checkpoint written by rank 0 per epoch; intermediate data cleaned
    ckpt = os.path.join(store.get_checkpoint_path("jaxrun"), "last.ckpt")
    assert os.path.exists(ckpt)
    assert not store.exists(store.get_train_data_path("jaxrun"))


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_torch_estimator_fit_predict(tmp_path):
    import torch.nn as tnn

    model = tnn.Sequential(tnn.Linear(4, 8), tnn.ReLU(), tnn.Linear(8, 2))
    rng = np.random.RandomState(1)
    x = rng.rand(48, 4).astype(np.float32)
    y = rng.randint(0, 2, 48)

    store = LocalStore(str(tmp_path / "store"))
    est = TorchEstimator(model=model, lr=1e-2, store=store, num_proc=2,
                         batch_size=8, epochs=2, run_id="torchrun")
    trained = est.fit(x, y)
    preds = trained.predict(x)
    assert preds.shape == (48, 2)
    assert len(trained.history) == 2
    ckpt = os.path.join(store.get_checkpoint_path("torchrun"),
                        "last.ckpt")
    assert os.path.exists(ckpt)


def test_spark_gate_message():
    import horovod_tpu.spark as hspark

    with pytest.raises(ImportError, match="horovod_tpu.estimator"):
        hspark.run(lambda: None, num_proc=1)


def test_spark_estimator_namespaces():
    """Reference name parity: horovod.spark.keras.KerasEstimator /
    horovod.spark.torch.TorchEstimator import under the same paths,
    as real adapters (param-spelling translation) over the framework
    estimators — not bare aliases (VERDICT r3 padding finding)."""
    import horovod_tpu.spark.keras as sk
    import horovod_tpu.spark.torch as st
    from horovod_tpu.estimator import JaxEstimator, TorchEstimator

    assert issubclass(sk.KerasEstimator, JaxEstimator)
    assert sk.KerasEstimator is not JaxEstimator
    assert issubclass(st.TorchEstimator, TorchEstimator)
    assert st.TorchEstimator is not TorchEstimator
    assert hasattr(sk, "LocalStore") and hasattr(st, "LocalStore")
    assert hasattr(sk, "KerasModel") and hasattr(st, "TorchModel")


def test_spark_slot_env_topology():
    """Rank topology from barrier task addresses (pure helper; the
    reference groups tasks by host hash, spark/runner.py:187-201)."""
    from horovod_tpu.spark import _slot_env

    addrs = ["nodeA:35001", "nodeA:35002", "nodeB:35001", "nodeB:35002"]
    e1 = _slot_env(1, addrs)
    assert e1["HOROVOD_RANK"] == "1" and e1["HOROVOD_SIZE"] == "4"
    assert e1["HOROVOD_LOCAL_RANK"] == "1"
    assert e1["HOROVOD_LOCAL_SIZE"] == "2"
    assert e1["HOROVOD_CROSS_RANK"] == "0"
    assert e1["HOROVOD_CROSS_SIZE"] == "2"
    e2 = _slot_env(2, addrs)
    assert e2["HOROVOD_LOCAL_RANK"] == "0"
    assert e2["HOROVOD_CROSS_RANK"] == "1"
    # single host, no ports in addresses
    e = _slot_env(0, ["h", "h"])
    assert e["HOROVOD_LOCAL_SIZE"] == "2"
    assert e["HOROVOD_CROSS_SIZE"] == "1"


def test_checkpoint_overwrite_same_step_no_window(tmp_path, hvd_single,
                                                  monkeypatch):
    """Overwriting an existing step renames the old dir aside before the
    swap (ADVICE r1: the old rmtree-first code had a crash window that
    destroyed the previous checkpoint before the new one was in place).
    Simulate a crash at the swap point and check the data survives."""
    import jax.numpy as jnp

    from horovod_tpu import checkpoint as ckpt

    path = str(tmp_path / "ckpts")
    ckpt.save(path, {"w": jnp.ones(3)}, step=1)
    # normal overwrite works and leaves no droppings
    ckpt.save(path, {"w": jnp.full(3, 2.0)}, step=1)
    assert np.allclose(ckpt.restore(path, step=1)["w"], 2.0)
    assert sorted(os.listdir(path)) == ["step_1"]

    # crash injected at the tmp->target swap (every attempt, emulating
    # a process dying mid-save): old data must still exist afterwards
    real_replace = os.replace

    def crashing_replace(src, dst):
        if ".tmp." in src:  # the staged-dir -> step-dir swap
            raise OSError("simulated crash mid-save")
        return real_replace(src, dst)

    monkeypatch.setattr(os, "replace", crashing_replace)
    with pytest.raises(OSError, match="simulated crash|could not move"):
        ckpt.save(path, {"w": jnp.full(3, 3.0)}, step=1)
    monkeypatch.undo()
    survivors = [d for d in os.listdir(path) if d.startswith("step_1.old")]
    assert survivors, "previous checkpoint destroyed by failed overwrite"
    # resume must adopt the orphaned .old dir transparently
    assert ckpt.latest_step(path) == 1
    assert np.allclose(ckpt.restore(path, step=1)["w"], 2.0)
    assert not [d for d in os.listdir(path) if ".old." in d]


def test_checkpoint_save_restore_resync(tmp_path, hvd_single):
    import jax.numpy as jnp

    from horovod_tpu import checkpoint as ckpt

    tree = {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones(3)}
    path = str(tmp_path / "ckpts")
    ckpt.save(path, tree, step=1)
    ckpt.save(path, {"w": tree["w"] * 2, "b": tree["b"]}, step=5)
    assert ckpt.latest_step(path) == 5
    restored = ckpt.restore(path)           # latest
    assert np.allclose(restored["w"], np.arange(6.0).reshape(2, 3) * 2)
    old = ckpt.restore(path, step=1)
    assert np.allclose(old["w"], np.arange(6.0).reshape(2, 3))
    synced = ckpt.resync(restored)
    assert np.allclose(np.asarray(synced["b"]), 1.0)


def test_checkpoint_resume_2proc(tmp_path):
    from test_multiprocess import run_ranks

    run_ranks("""
        from horovod_tpu import checkpoint as ckpt
        shared = os.environ["HVD_TEST_CKPT_DIR"]
        tree = {"w": jnp.full((4,), float(rank + 1))}
        ckpt.save(shared, tree, step=3)         # only rank 0 writes
        hvd.barrier()
        restored = ckpt.restore(shared)
        restored = ckpt.resync(restored)        # all ranks -> rank 0's
        assert np.allclose(np.asarray(restored["w"]), 1.0)
        assert ckpt.latest_step(shared) == 3
    """, extra_env={"HVD_TEST_CKPT_DIR": str(tmp_path / "shared")})


@pytest.mark.slow  # ~16 s; the uneven-shards deadlock twin stays tier-1
def test_jax_estimator_validation_split(tmp_path):
    """validation= holds a fraction out per shard and scores it per
    epoch (reference estimator validation param); val_history lands on
    the trained model alongside history."""
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(x)

    rng = np.random.RandomState(2)
    x = rng.rand(64, 4).astype(np.float32)
    y = rng.randint(0, 2, 64)
    est = JaxEstimator(model=Tiny(), lr=1e-2,
                       store=LocalStore(str(tmp_path / "s")), num_proc=2,
                       batch_size=8, epochs=2, validation=0.25,
                       run_id="valrun")
    model = est.fit(x, y)
    assert len(model.history) == 2
    assert len(model.val_history) == 2
    assert np.isfinite(model.val_history).all()


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_torch_estimator_validation_split(tmp_path):
    import torch.nn as tnn

    model = tnn.Linear(4, 2)
    rng = np.random.RandomState(3)
    x = rng.rand(40, 4).astype(np.float32)
    y = rng.randint(0, 2, 40)
    est = TorchEstimator(model=model, lr=1e-2,
                         store=LocalStore(str(tmp_path / "s")),
                         num_proc=2, batch_size=8, epochs=1,
                         validation=0.2, run_id="tval")
    trained = est.fit(x, y)
    assert len(trained.val_history) == 1
    assert np.isfinite(trained.val_history).all()


def test_estimator_rejects_bad_validation(tmp_path):
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(x)

    with pytest.raises(ValueError, match="validation"):
        JaxEstimator(model=Tiny(), store=LocalStore(str(tmp_path / "s")),
                     validation=1.5)


def test_validation_split_uneven_shards_no_deadlock(tmp_path):
    """3 samples over 2 ranks with validation=0.25: one rank's split is
    empty.  The (sum, count) allreduce must run on every rank anyway —
    a conditional collective would hang fit() forever."""
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(x)

    rng = np.random.RandomState(4)
    x = rng.rand(3, 4).astype(np.float32)
    y = rng.randint(0, 2, 3)
    est = JaxEstimator(model=Tiny(), lr=1e-2,
                       store=LocalStore(str(tmp_path / "s")), num_proc=2,
                       batch_size=2, epochs=1, validation=0.25,
                       run_id="uneven")
    model = est.fit(x, y)
    assert len(model.val_history) == 1
    assert np.isfinite(model.val_history[0])


def test_spark_slot_env_homogeneity_flag():
    from horovod_tpu.spark import _slot_env

    het = ["a:1", "a:2", "a:3", "b:1"]
    assert _slot_env(0, het)["HOROVOD_IS_HOMOGENEOUS"] == "0"
    hom = ["a:1", "a:2", "b:1", "b:2"]
    assert _slot_env(0, hom)["HOROVOD_IS_HOMOGENEOUS"] == "1"
