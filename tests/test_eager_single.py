"""Eager/handle API behavior in a single process (size == 1).

Covers the API-surface contracts the reference asserts in
``test/test_torch.py`` that don't need a second rank: handle lifecycle,
duplicate-name errors (``test_torch.py`` duplicate-name cases), identity
semantics at size 1, broadcast_parameters/object round-trips, join, and
the uninitialized-use error.  Multi-rank value correctness lives in
test_multiprocess.py.
"""

import numpy as np
import pytest

import jax.numpy as jnp


def test_uninitialized_raises():
    import horovod_tpu as hvd
    from horovod_tpu.common.types import HorovodTpuError

    if hvd.is_initialized():
        hvd.shutdown()
    with pytest.raises(HorovodTpuError):
        hvd.rank()
    with pytest.raises(HorovodTpuError):
        hvd.allreduce(jnp.ones(3))


def test_basics(hvd_single):
    hvd = hvd_single
    assert hvd.size() == 1
    assert hvd.rank() == 0
    assert hvd.local_rank() == 0
    assert hvd.local_size() == 1
    assert hvd.cross_rank() == 0
    assert hvd.is_initialized()
    assert hvd.xla_built()
    assert not hvd.mpi_built()
    assert not hvd.mpi_threads_supported()
    assert hvd.world_mesh().shape == {"hvd": 1}


def test_allreduce_identity(hvd_single):
    hvd = hvd_single
    x = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
    for op in (hvd.Average, hvd.Sum):
        out = hvd.allreduce(x, op=op)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))
    # deprecated average= spelling
    out = hvd.allreduce(x, average=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_average_and_op_conflict(hvd_single):
    hvd = hvd_single
    from horovod_tpu.common.types import HorovodTpuError

    with pytest.raises(HorovodTpuError):
        hvd.allreduce(jnp.ones(3), average=True, op=hvd.Sum)


def test_async_handles(hvd_single):
    hvd = hvd_single
    handles = [hvd.allreduce_async(jnp.full((4,), float(i)), op=hvd.Sum,
                                   name=f"t{i}") for i in range(10)]
    for i, h in enumerate(handles):
        out = hvd.synchronize(h)
        np.testing.assert_allclose(np.asarray(out), np.full((4,), float(i)))


def test_poll_completes(hvd_single):
    hvd = hvd_single
    h = hvd.allreduce_async(jnp.ones(8), name="pollme")
    import time

    deadline = time.time() + 10
    while not hvd.poll(h) and time.time() < deadline:
        time.sleep(0.005)
    assert hvd.poll(h)
    hvd.synchronize(h)


def test_duplicate_name_error():
    """Queue-level contract: same name twice before completion errors
    (reference ``common.h:161`` DUPLICATE_NAME_ERROR)."""
    from horovod_tpu.common.types import DuplicateNameError
    from horovod_tpu.runtime.background import TensorQueue, _Entry

    q = TensorQueue()
    e = _Entry("dup", "allreduce", 2, -1, jnp.ones(4), 0, None)
    q.add(e)
    with pytest.raises(DuplicateNameError):
        q.add(_Entry("dup", "allreduce", 2, -1, jnp.ones(4), 1, None))
    q.finalize("dup")
    q.add(_Entry("dup", "allreduce", 2, -1, jnp.ones(4), 2, None))


def test_same_name_sequential_ok(hvd_single):
    hvd = hvd_single
    for _ in range(3):
        out = hvd.allreduce(jnp.ones(4), name="reused")
        np.testing.assert_allclose(np.asarray(out), np.ones(4))


def test_allgather_single(hvd_single):
    hvd = hvd_single
    x = jnp.arange(6, dtype=jnp.int32).reshape(2, 3)
    out = hvd.allgather(x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_broadcast_single(hvd_single):
    hvd = hvd_single
    x = jnp.arange(5, dtype=jnp.float32)
    out = hvd.broadcast(x, root_rank=0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_join_single(hvd_single):
    assert hvd_single.join() == 0


def test_broadcast_parameters_roundtrip(hvd_single):
    hvd = hvd_single
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros(4),
              "nested": {"scale": jnp.asarray(2.0)}}
    out = hvd.broadcast_parameters(params, root_rank=0)
    assert set(out) == {"w", "b", "nested"}
    np.testing.assert_allclose(np.asarray(out["nested"]["scale"]), 2.0)


def test_broadcast_object(hvd_single):
    hvd = hvd_single
    obj = {"lr": 0.1, "sched": [1, 2, 3], "name": "adamw"}
    assert hvd.broadcast_object(obj, root_rank=0) == obj


def test_barrier(hvd_single):
    hvd_single.barrier()


def test_compression_fp16_eager(hvd_single):
    hvd = hvd_single
    x = jnp.full((16,), 1.5, jnp.float32)
    out = hvd.allreduce(x, compression=hvd.Compression.fp16)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_timeline_written(tmp_path):
    import json
    import os

    os.environ["HOROVOD_TIMELINE"] = str(tmp_path / "timeline.json")
    import horovod_tpu as hvd

    hvd.init()
    try:
        hvd.allreduce(jnp.ones(4), name="tl_tensor")
    finally:
        hvd.shutdown()
        os.environ.pop("HOROVOD_TIMELINE")
    data = json.loads((tmp_path / "timeline.json").read_text())
    names = {e.get("name") for e in data}
    assert "NEGOTIATE_ALLREDUCE" in names
    assert "XLA_ALLREDUCE" in names
    # tensor row labeled via metadata event (reference timeline format)
    assert any(e.get("ph") == "M" and
               e.get("args", {}).get("name") == "tl_tensor" for e in data)


def test_jax_profiler_capture(tmp_path):
    """HOROVOD_TIMELINE_JAX_PROFILER starts a device-side jax.profiler
    capture (xplane under rank0/) and stops it at shutdown."""
    import os

    os.environ["HOROVOD_TIMELINE_JAX_PROFILER"] = str(tmp_path)
    import horovod_tpu as hvd

    hvd.init()
    try:
        hvd.allreduce(jnp.ones(8), name="prof_tensor")
    finally:
        hvd.shutdown()
        os.environ.pop("HOROVOD_TIMELINE_JAX_PROFILER")
    rank_dir = tmp_path / "rank0"
    assert rank_dir.is_dir()
    captured = [p for p in rank_dir.rglob("*") if p.is_file()]
    assert captured, "no profile artifacts written"
    assert any("xplane" in p.name for p in captured), captured


def test_is_homogeneous_and_keras_surface(hvd_single):
    """Reference basics.py:122 is_homogeneous + keras namespace ops."""
    assert hvd_single.is_homogeneous() is True
    import horovod_tpu.keras as hk

    out = hk.allreduce(jnp.ones(3), op=hvd_single.Sum)
    assert float(out[0]) == 1.0
    for name in ("allgather", "broadcast", "load_model",
                 "DistributedOptimizer"):
        assert hasattr(hk, name), name
