"""Overlapped chunked gradient communication (docs/overlap.md).

Covers the acceptance bar of the overlap PR:
  * ring reduce-scatter / allgather primitives match the monolithic
    psum_scatter / all_gather exactly;
  * with ``overlap=True`` and K chunks the lowered step contains >= K
    ppermute/collective-permute stages and ZERO monolithic full-buffer
    all-reduce;
  * fp32 overlap-on vs overlap-off parity is bit-exact (integer-valued
    data, so every summation order is exact in fp32);
  * composition: ZeRO-1 shard math unchanged (same shards, same state
    layout), int8 EF residuals telescoping bound unchanged, hierarchical
    int8 still quantizes only the cross-slice hop — now on a ppermute
    ring;
  * knob surfaces: program-cache keying, autotuner dim, handshake
    agreement (2-proc), timeline per-bucket events.
"""

import re

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.analysis import hlo_lint as HL
from horovod_tpu.common import config as _config
from horovod_tpu.ops import collectives as coll
from horovod_tpu.ops import overlap as ovl

N, CROSS, LOCAL = 8, 2, 4


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:N]), ("hvd",))


@pytest.fixture(scope="module")
def hmesh():
    return Mesh(np.array(jax.devices()[:N]).reshape(CROSS, LOCAL),
                ("cross", "local"))


def _int_valued(shape, lo=-8, hi=8, seed=0):
    """Integer-valued fp32 data: every summation order is exact, so
    ring-vs-psum comparisons can demand bit equality."""
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(lo, hi, shape), jnp.float32)


# ---------------------------------------------------------------------------
# Ring primitives
# ---------------------------------------------------------------------------


def test_ring_reduce_scatter_matches_psum_scatter(mesh):
    x = _int_valued((N, N, 6))

    def body(b):
        ring = ovl.ring_reduce_scatter(b[0], "hvd")
        mono = jax.lax.psum_scatter(b[0].reshape(-1), "hvd",
                                    scatter_dimension=0, tiled=True)
        return ring.reshape(1, -1), mono.reshape(1, -1)

    ring, mono = jax.jit(shard_map(
        body, mesh=mesh, check_vma=False, in_specs=P("hvd"),
        out_specs=(P("hvd"),) * 2))(x)
    np.testing.assert_array_equal(np.asarray(ring), np.asarray(mono))
    np.testing.assert_array_equal(np.asarray(ring), np.asarray(x).sum(0))


def test_ring_allgather_matches_all_gather(mesh):
    shards = _int_valued((N, 4))

    def body(b):
        return ovl.ring_allgather(b[0], "hvd").reshape(1, N, 4)

    got = jax.jit(shard_map(body, mesh=mesh, check_vma=False,
                            in_specs=P("hvd"), out_specs=P("hvd")))(shards)
    for r in range(N):
        np.testing.assert_array_equal(np.asarray(got)[r],
                                      np.asarray(shards))


@pytest.mark.parametrize("total,chunks,op", [
    (37, 4, coll.Sum),     # pad path (37 % 8 != 0)
    (64, 3, coll.Average),  # uneven buckets
    (5, 16, coll.Sum),     # more chunks than the shard has elements
    (8, 1, coll.Average),  # K=1 degenerates to one ring
], ids=["pad", "uneven", "chunks>shard", "k1"])
def test_overlapped_flat_reduce_exact(mesh, total, chunks, op):
    buf = _int_valued((N, total))

    def body(b):
        out, _ = ovl.overlapped_flat_reduce(b[0], "hvd", op=op,
                                            chunks=chunks)
        return out.reshape(1, -1)

    got = jax.jit(shard_map(body, mesh=mesh, check_vma=False,
                            in_specs=P("hvd"), out_specs=P("hvd")))(buf)
    exp = np.asarray(buf).sum(0)
    if op == coll.Average:
        exp = exp / N
    for r in range(N):
        np.testing.assert_array_equal(np.asarray(got)[r], exp)


def test_overlapped_scatter_gather_matches_monolithic(mesh):
    """The bucketed scatter produces the IDENTICAL contiguous per-rank
    shard as _scatter_flat_buffer (so ZeRO-1 layout/state never depends
    on the overlap knob), and the bucketed gather inverts it."""
    buf = _int_valued((N, N * 5))

    def body(b):
        s1, _ = ovl.overlapped_scatter_flat_buffer(b[0], "hvd", chunks=3)
        s2, _ = coll._scatter_flat_buffer(b[0], "hvd")
        g1 = ovl.overlapped_gather_flat_shard(s1, "hvd", chunks=2)
        g2 = coll._gather_flat_shard(s2, "hvd")
        return (s1.reshape(1, -1), s2.reshape(1, -1),
                g1.reshape(1, -1), g2.reshape(1, -1))

    s1, s2, g1, g2 = jax.jit(shard_map(
        body, mesh=mesh, check_vma=False, in_specs=P("hvd"),
        out_specs=(P("hvd"),) * 4))(buf)
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))


def test_bucket_bounds():
    assert ovl.bucket_bounds(10, 4) == [(0, 3), (3, 6), (6, 8), (8, 10)]
    assert ovl.bucket_bounds(3, 8) == [(0, 1), (1, 2), (2, 3)]
    assert ovl.bucket_bounds(5, 1) == [(0, 5)]
    # knob default
    assert len(ovl.bucket_bounds(1024)) == ovl.configured_chunks()


# ---------------------------------------------------------------------------
# The schedule proof: >= K ppermute stages, zero monolithic all-reduce
# ---------------------------------------------------------------------------


def _optimizer_hlo(mesh, sharded: bool, chunks: int) -> str:
    opt = hvd.DistributedOptimizer(optax.sgd(0.1), axis_name="hvd",
                                   sharded=sharded, overlap=True)
    params = {"w": jnp.linspace(-1.0, 1.0, 21, dtype=jnp.float32),
              "b": jnp.zeros((3, 3), jnp.float32)}

    def per_rank(t):
        state = opt.init(params)
        grads = jax.tree_util.tree_map(lambda p: 2.0 * (p - t[0, 0]),
                                       params)
        upd, _ = opt.update(grads, state, params)
        return upd["w"].reshape(1, -1)

    fn = jax.jit(shard_map(per_rank, mesh=mesh, check_vma=False,
                           in_specs=P("hvd"), out_specs=P("hvd")))
    old = _config.get("overlap_chunks")
    _config.set_knob("overlap_chunks", chunks)
    try:
        return fn.lower(
            jnp.zeros((N, 1), jnp.float32)).as_text("hlo").lower()
    finally:
        _config.set_knob("overlap_chunks", old)


@pytest.mark.parametrize("sharded", [False, True],
                         ids=["replicated", "zero1"])
def test_hlo_k_permute_stages_no_allreduce(mesh, sharded):
    """Acceptance bar, as structural checker verdicts
    (analysis.hlo_lint): with overlap=True and K chunks the lowered
    step contains >= K ppermute/collective-permute stages and ZERO
    monolithic full-buffer all-reduce (the fp32 step has no psum at
    all — ring RS + ring AG replace it end to end)."""
    k = 3
    hlo = _optimizer_hlo(mesh, sharded, k)
    assert HL.check_program(hlo, HL.overlap_rules(k)) == []


def test_hlo_off_still_monolithic(mesh):
    """Regression guard for the knob-off path: overlap=False keeps the
    single fused collective (no ppermute ring).

    This is the overlap family's checker-vs-regex CROSS-VALIDATION
    test (docs/analysis.md): the regex asserts run alongside the
    hlo_lint verdicts on the same text and must agree — including the
    NEGATIVE direction, where the overlap rule set must FLAG this
    monolithic program (the checker can still fail)."""
    opt = hvd.DistributedOptimizer(optax.sgd(0.1), axis_name="hvd",
                                   overlap=False)
    params = {"w": jnp.zeros((16,), jnp.float32)}

    def per_rank(t):
        state = opt.init(params)
        upd, _ = opt.update({"w": jnp.full((16,), t[0, 0])}, state,
                            params)
        return upd["w"].reshape(1, -1)

    fn = jax.jit(shard_map(per_rank, mesh=mesh, check_vma=False,
                           in_specs=P("hvd"), out_specs=P("hvd")))
    hlo = fn.lower(jnp.zeros((N, 1), jnp.float32)).as_text("hlo").lower()
    # regex side (kept for cross-validation)
    assert "all-reduce" in hlo
    assert "collective-permute" not in hlo
    # checker side agrees: monolithic program passes the monolithic
    # rules and FAILS the overlap rules
    assert HL.check_program(
        hlo, [HL.min_collectives("all-reduce", 1),
              HL.no_collective("collective-permute")]) == []
    flagged = HL.check_program(hlo, HL.overlap_rules(1))
    assert {f.rule for f in flagged} == {"HLO-BUCKETS",
                                         "HLO-MONOLITHIC"}


# ---------------------------------------------------------------------------
# Parity: overlap on == overlap off
# ---------------------------------------------------------------------------


def _run_steps(opt, t, steps=3, params=None):
    if params is None:
        params = {"w": jnp.linspace(-1.0, 1.0, 21, dtype=jnp.float32),
                  "b": jnp.zeros((3, 3), jnp.float32)}
    state = opt.init(params)
    for _ in range(steps):
        grads = jax.tree_util.tree_map(lambda p: 2.0 * (p - t), params)
        upd, state = opt.update(grads, state, params)
        params = optax.apply_updates(params, upd)
    return params


@pytest.mark.parametrize("sharded", [False, True],
                         ids=["replicated", "zero1"])
def test_fp32_parity_bitexact(mesh, sharded):
    """fp32 overlap-on vs overlap-off walks the bit-identical
    trajectory.  Data is dyadic by construction (integer params and
    targets, power-of-two lr/momentum), so every intermediate —
    gradients, partial sums in ANY order, updates — is exactly
    representable in fp32 and the ring's summation order cannot diverge
    from the monolithic psum's: any difference would be a real schedule
    bug, not float noise."""
    maker = lambda: optax.sgd(0.5, momentum=0.5)  # noqa: E731
    on = hvd.DistributedOptimizer(maker(), axis_name="hvd",
                                  sharded=sharded, overlap=True)
    off = hvd.DistributedOptimizer(maker(), axis_name="hvd",
                                   sharded=sharded, overlap=False)
    targets = jnp.arange(N, dtype=jnp.float32).reshape(N, 1)
    params = {"w": jnp.arange(21, dtype=jnp.float32),
              "b": jnp.ones((3, 3), jnp.float32)}

    def per_rank(t):
        a = _run_steps(on, t[0, 0], params=params)
        b = _run_steps(off, t[0, 0], params=params)
        return (a["w"].reshape(1, -1), b["w"].reshape(1, -1),
                a["b"].reshape(1, -1), b["b"].reshape(1, -1))

    fn = jax.jit(shard_map(per_rank, mesh=mesh, check_vma=False,
                           in_specs=P("hvd"), out_specs=(P("hvd"),) * 4))
    wa, wb, ba, bb = fn(targets)
    np.testing.assert_array_equal(np.asarray(wa), np.asarray(wb))
    np.testing.assert_array_equal(np.asarray(ba), np.asarray(bb))
    # and the update is still replicated across ranks
    assert np.ptp(np.asarray(wa), axis=0).max() == 0.0


@pytest.mark.parametrize("sharded", [False, True],
                         ids=["replicated", "zero1"])
@pytest.mark.parametrize("maker", [
    lambda: optax.sgd(0.1, momentum=0.9),
    lambda: optax.adam(1e-2),
], ids=["sgd-momentum", "adam"])
def test_optimizer_parity_close(mesh, maker, sharded):
    """General (non-dyadic) data: Adam's sqrt/eps and lr=0.1 make
    params non-dyadic after step 1, so later reductions are
    order-sensitive — the bar is the same rtol the sharded-vs-replicated
    parity tests use."""
    on = hvd.DistributedOptimizer(maker(), axis_name="hvd",
                                  sharded=sharded, overlap=True)
    off = hvd.DistributedOptimizer(maker(), axis_name="hvd",
                                   sharded=sharded, overlap=False)
    targets = jnp.arange(N, dtype=jnp.float32).reshape(N, 1)

    def per_rank(t):
        a = _run_steps(on, t[0, 0])
        b = _run_steps(off, t[0, 0])
        return a["w"].reshape(1, -1), b["w"].reshape(1, -1)

    fn = jax.jit(shard_map(per_rank, mesh=mesh, check_vma=False,
                           in_specs=P("hvd"), out_specs=(P("hvd"),) * 2))
    wa, wb = fn(targets)
    np.testing.assert_allclose(np.asarray(wa), np.asarray(wb),
                               rtol=2e-5, atol=1e-6)


def test_random_data_parity_close(mesh):
    """Random (non-integer) gradients: summation order may differ, so
    the bar is tight allclose, not bit equality."""
    on = hvd.DistributedOptimizer(optax.sgd(0.1), axis_name="hvd",
                                  overlap=True)
    off = hvd.DistributedOptimizer(optax.sgd(0.1), axis_name="hvd",
                                   overlap=False)
    rng = np.random.default_rng(3)
    grads = jnp.asarray(rng.standard_normal((N, 300)), jnp.float32)

    def per_rank(g):
        params = {"w": jnp.zeros((300,), jnp.float32)}
        sa, sb = on.init(params), off.init(params)
        ua, _ = on.update({"w": g[0]}, sa, params)
        ub, _ = off.update({"w": g[0]}, sb, params)
        return ua["w"].reshape(1, -1), ub["w"].reshape(1, -1)

    a, b = jax.jit(shard_map(per_rank, mesh=mesh, check_vma=False,
                             in_specs=P("hvd"),
                             out_specs=(P("hvd"),) * 2))(grads)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6,
                               atol=1e-7)


def test_mixed_dtypes_and_hierarchical(mesh, hmesh):
    """bf16 + fp32 leaves ride separate fused ring buffers; under
    hierarchical the two-level decomposition still holds (ICI
    psum_scatter + cross ppermute ring), result equal to the flat
    reduction."""
    params = {"a": jnp.ones((10,), jnp.float32),
              "h": jnp.ones((6,), jnp.bfloat16)}
    opt = hvd.DistributedOptimizer(optax.sgd(0.5), axis_name="hvd",
                                   overlap=True)

    def per_rank(t):
        state = opt.init(params)
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        upd, _ = opt.update(grads, state, params)
        new = optax.apply_updates(params, upd)
        return new["a"].reshape(1, -1), new["h"].reshape(1, -1)

    a, h = jax.jit(shard_map(per_rank, mesh=mesh, check_vma=False,
                             in_specs=P("hvd"),
                             out_specs=(P("hvd"),) * 2))(
        jnp.zeros((N, 1), jnp.float32))
    assert h.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(a), np.full((N, 10), 0.5),
                               rtol=1e-6)

    # hierarchical: flat vs two-level overlapped reduce agree exactly
    _config.set_knob("hierarchical_allreduce", True)
    try:
        buf = _int_valued((N, 48), seed=5)

        def body(b):
            two, _ = ovl.overlapped_flat_reduce(
                b[0], ("cross", "local"), op=coll.Sum, chunks=3)
            return two.reshape(1, -1)

        got = jax.jit(shard_map(body, mesh=hmesh, check_vma=False,
                                in_specs=P(("cross", "local")),
                                out_specs=P(("cross", "local"))))(buf)
    finally:
        _config.set_knob("hierarchical_allreduce", False)
    for r in range(N):
        np.testing.assert_array_equal(np.asarray(got)[r],
                                      np.asarray(buf).sum(0))


# ---------------------------------------------------------------------------
# Composition: int8 error feedback + hierarchical quantization split
# ---------------------------------------------------------------------------


def test_int8_ef_telescoping_under_overlap(mesh):
    """The EF acceptance bar under overlap: with fixed per-rank
    gradients the residual telescopes — after k steps the overlapped
    sharded-int8 trajectory is within ~one quantization bound of the
    exact one, not k bounds (same bar as the non-overlap test in
    test_sharded_optimizer.py)."""
    lr, steps = 0.01, 5
    q = hvd.DistributedOptimizer(optax.sgd(lr), axis_name="hvd",
                                 sharded=True, overlap=True,
                                 compression=hvd.Compression.int8)
    exact = hvd.DistributedOptimizer(optax.sgd(lr), axis_name="hvd",
                                     sharded=True, overlap=True)
    rng = np.random.default_rng(7)
    per_rank_g = jnp.asarray(rng.standard_normal((N, 512)), jnp.float32)

    def body(g):
        pq = {"w": jnp.zeros((512,), jnp.float32)}
        pe = dict(pq)
        sq, se = q.init(pq), exact.init(pe)
        for _ in range(steps):
            uq, sq = q.update({"w": g[0]}, sq, pq)
            pq = optax.apply_updates(pq, uq)
            ue, se = exact.update({"w": g[0]}, se, pe)
            pe = optax.apply_updates(pe, ue)
        return pq["w"].reshape(1, -1), pe["w"].reshape(1, -1)

    got, ref = jax.jit(shard_map(body, mesh=mesh, check_vma=False,
                                 in_specs=P("hvd"),
                                 out_specs=(P("hvd"),) * 2))(per_rank_g)
    gmax = float(np.abs(np.asarray(per_rank_g)).max())
    one_step_bound = lr * (N * gmax / (127 // N)) / 2 / N + 1e-7
    err = np.abs(np.asarray(got) - np.asarray(ref)).max()
    assert err <= 2.5 * one_step_bound, (err, one_step_bound)


def test_int8_ef_replicated_under_overlap(mesh):
    """Non-sharded int8 EF (the _FeedbackState path) through the
    overlapped grouped quantized allreduce: residuals stay
    bucket-aligned and the telescoping bound holds."""
    lr, steps = 0.01, 5
    q = hvd.DistributedOptimizer(optax.sgd(lr), axis_name="hvd",
                                 overlap=True,
                                 compression=hvd.Compression.int8)
    exact = hvd.DistributedOptimizer(optax.sgd(lr), axis_name="hvd",
                                     overlap=True)
    rng = np.random.default_rng(11)
    per_rank_g = jnp.asarray(rng.standard_normal((N, 384)), jnp.float32)

    def body(g):
        pq = {"w": jnp.zeros((384,), jnp.float32)}
        pe = dict(pq)
        sq, se = q.init(pq), exact.init(pe)
        for _ in range(steps):
            uq, sq = q.update({"w": g[0]}, sq, pq)
            pq = optax.apply_updates(pq, uq)
            ue, se = exact.update({"w": g[0]}, se, pe)
            pe = optax.apply_updates(pe, ue)
        return pq["w"].reshape(1, -1), pe["w"].reshape(1, -1)

    got, ref = jax.jit(shard_map(body, mesh=mesh, check_vma=False,
                                 in_specs=P("hvd"),
                                 out_specs=(P("hvd"),) * 2))(per_rank_g)
    gmax = float(np.abs(np.asarray(per_rank_g)).max())
    one_step_bound = lr * (N * gmax / (127 // N)) / 2 / N + 1e-7
    err = np.abs(np.asarray(got) - np.asarray(ref)).max()
    assert err <= 2.5 * one_step_bound, (err, one_step_bound)


def test_int8_hier_overlap_quantizes_cross_only(hmesh):
    """EQuARX split survives the ring: every i8 collective (now a
    ppermute) names only the cross axis; the local (ICI) hops stay
    fp32."""
    _config.set_knob("hierarchical_allreduce", True)
    try:
        opt = hvd.DistributedOptimizer(
            optax.sgd(0.1), axis_name=("cross", "local"), sharded=True,
            overlap=True, compression=hvd.Compression.int8)
        params = {"w": jnp.zeros((N * 256,), jnp.float32)}

        def per_rank(t):
            state = opt.init(params)
            grads = {"w": jnp.full((N * 256,), t[0, 0])}
            upd, _ = opt.update(grads, state, params)
            return upd["w"].reshape(1, -1)

        jaxpr = str(jax.make_jaxpr(shard_map(
            per_rank, mesh=hmesh, check_vma=False,
            in_specs=P(("cross", "local")),
            out_specs=P(("cross", "local"))))(
                jnp.zeros((N, 1), jnp.float32)))
    finally:
        _config.set_knob("hierarchical_allreduce", False)
    i8_colls = re.findall(r"i8\[[\d,]*\] = (\w+)\[([^\]]*)\]", jaxpr)
    assert i8_colls, jaxpr
    for prim, args in i8_colls:
        if "axis" in args or "perm" in args:
            assert "'cross'" in args and "'local'" not in args, \
                (prim, args)
    # the int8 payload rides the ring, not a psum-family collective
    assert "ppermute" in {p for p, _ in i8_colls}
    # a full-precision reduce-scatter still rides the local (ICI) axis
    local_rs = [args for prim, args in
                re.findall(r"f32\[[\d,]*\] = (reduce_scatter)\[([^\]]*)\]",
                           jaxpr) if "'local'" in args]
    assert local_rs, jaxpr


def test_grouped_reducescatter_overlap_parity(mesh):
    """Public in-trace reducescatter under the knob: same shards as the
    monolithic path, pad guard intact."""
    a = _int_valued((N, 11), seed=2)
    b = _int_valued((N, 16, 2), seed=3)

    def body(ba, bb):
        on = coll.grouped_reducescatter([ba[0], bb[0]], axis_name="hvd",
                                        op=coll.Sum, overlap=True)
        off = coll.grouped_reducescatter([ba[0], bb[0]], axis_name="hvd",
                                         op=coll.Sum, overlap=False)
        return tuple(on) + tuple(off)

    o = jax.jit(shard_map(body, mesh=mesh, check_vma=False,
                          in_specs=(P("hvd"),) * 2,
                          out_specs=(P("hvd"),) * 4))(a, b)
    np.testing.assert_array_equal(np.asarray(o[0]), np.asarray(o[2]))
    np.testing.assert_array_equal(np.asarray(o[1]), np.asarray(o[3]))


def test_backward_passes_per_step_composes(mesh):
    """k=3 accumulation drives the overlapped sharded core; the third
    step applies the mean exactly like the non-overlap path."""
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), axis_name="hvd",
                                   sharded=True, overlap=True,
                                   backward_passes_per_step=3)

    def per_rank(t):
        w = jnp.zeros((2,))
        state = opt.init(w)
        outs = []
        for g in (3.0, 6.0, 9.0):
            upd, state = opt.update(jnp.full((2,), g), state, w)
            w = optax.apply_updates(w, upd)
            outs.append(w)
        return jnp.stack(outs).reshape(1, 3, 2)

    out = np.asarray(jax.jit(shard_map(
        per_rank, mesh=mesh, check_vma=False, in_specs=P("hvd"),
        out_specs=P("hvd")))(jnp.zeros((N, 1), jnp.float32)))
    np.testing.assert_allclose(out[:, 0], 0.0)
    np.testing.assert_allclose(out[:, 1], 0.0)
    np.testing.assert_allclose(out[:, 2], -6.0)


def test_adasum_ignores_overlap(mesh):
    """Adasum never overlaps (the projection needs the full reduction):
    the knob on must not change its result or route it to the ring."""
    x = _int_valued((N, 12), seed=9)

    def body(b):
        on = coll.allreduce(b[0], axis_name="hvd", op=coll.Adasum,
                            overlap=True)
        off = coll.allreduce(b[0], axis_name="hvd", op=coll.Adasum,
                            overlap=False)
        return on.reshape(1, -1), off.reshape(1, -1)

    a, b_ = jax.jit(shard_map(body, mesh=mesh, check_vma=False,
                              in_specs=P("hvd"),
                              out_specs=(P("hvd"),) * 2))(x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-6)


# ---------------------------------------------------------------------------
# Knob surfaces: eager cache keys, timeline, platform flags
# ---------------------------------------------------------------------------


def test_eager_overlap_cache_key_and_size1(hvd_single, monkeypatch):
    """Toggling HOROVOD_OVERLAP / HOROVOD_OVERLAP_CHUNKS changes the
    eager program cache key (programs rebuild instead of silently
    reusing the monolithic one); size-1 results unchanged."""
    from horovod_tpu.ops import xla_exec as _exec

    monkeypatch.delenv("HOROVOD_OVERLAP", raising=False)
    assert _exec.overlap_cfg() is None
    monkeypatch.setenv("HOROVOD_OVERLAP", "1")
    monkeypatch.setenv("HOROVOD_OVERLAP_CHUNKS", "6")
    assert _exec.overlap_cfg() == 6
    out = hvd.allreduce(jnp.arange(7.0), op=hvd.Sum, name="ovl.sz1")
    np.testing.assert_array_equal(np.asarray(out), np.arange(7.0))
    rs = hvd.reducescatter(jnp.arange(6.0).reshape(3, 2), name="ovl.rs1")
    np.testing.assert_array_equal(np.asarray(rs),
                                  np.arange(6.0).reshape(3, 2))


def test_timeline_overlap_phase_events(tmp_path):
    """Per-bucket overlap/rs|compute|ag ticks land in the Chrome trace
    on <name>/bucket<k> rows (HOROVOD_TIMELINE satellite)."""
    import json

    from horovod_tpu.runtime.timeline import Timeline

    path = tmp_path / "tl.json"
    tl = Timeline(str(path))
    for b in range(3):
        for phase in ("rs", "compute", "ag"):
            tl.overlap_phase("grad_buffer.f32", b, phase, elems=128)
    tl.close()
    events = json.loads(path.read_text())
    names = {e["name"] for e in events if e.get("ph") == "i"}
    assert {"overlap/rs", "overlap/compute", "overlap/ag"} <= names
    rows = {e["args"]["name"] for e in events if e.get("ph") == "M"}
    assert "grad_buffer.f32/bucket2" in rows
    buckets = {e["args"]["bucket"] for e in events if e.get("ph") == "i"}
    assert buckets == {0, 1, 2}


def test_platform_exports_libtpu_flags(monkeypatch):
    """HOROVOD_OVERLAP=1 wires the async collective-permute +
    latency-hiding-scheduler libtpu flags before backend init, without
    clobbering operator-pinned values."""
    from horovod_tpu.common import platform as _platform

    monkeypatch.setenv(
        "LIBTPU_INIT_ARGS",
        "--xla_tpu_enable_latency_hiding_scheduler=false")
    _platform._enable_overlap_xla_flags()
    args = _platform.os.environ["LIBTPU_INIT_ARGS"]
    # operator's pin survives
    assert "--xla_tpu_enable_latency_hiding_scheduler=false" in args
    assert args.count("xla_tpu_enable_latency_hiding_scheduler") == 1
    # the missing flag is appended
    assert "--xla_tpu_enable_async_collective_permute=true" in args
    # idempotent
    _platform._enable_overlap_xla_flags()
    assert _platform.os.environ["LIBTPU_INIT_ARGS"] == args


# ---------------------------------------------------------------------------
# Multi-process: the negotiated eager wire + handshake agreement
# ---------------------------------------------------------------------------


@pytest.mark.multiprocess
def test_eager_overlap_parity_2proc():
    """HOROVOD_OVERLAP=1 on the negotiated wire: allreduce /
    reducescatter / sharded-optimizer results match the exact values
    (integer data -> exact), proving the overlapped programs agree
    across ranks."""
    from tests.test_multiprocess import run_ranks

    run_ranks("""
        import jax, optax
        out = hvd.allreduce(jnp.arange(10.0) * (rank + 1), op=hvd.Sum,
                            name="ovl.ar")
        assert np.array_equal(np.asarray(out), np.arange(10.0) * 3), out
        rs = hvd.reducescatter(jnp.arange(8.0).reshape(4, 2) * (rank + 1),
                               op=hvd.Sum, name="ovl.rs")
        exp = (np.arange(8.0).reshape(4, 2) * 3)[rank * 2:(rank + 1) * 2]
        assert np.array_equal(np.asarray(rs), exp), rs
        # sharded optimizer over the negotiated overlapped wire
        params = {"w": jnp.linspace(-1.0, 1.0, 5), "b": jnp.zeros((3,))}
        sh = hvd.DistributedOptimizer(optax.adam(0.1), sharded=True)
        rep = hvd.DistributedOptimizer(optax.adam(0.1), sharded=False)
        ps, pr = dict(params), dict(params)
        ss, sr = sh.init(ps), rep.init(pr)
        for i in range(3):
            g = jax.tree_util.tree_map(lambda p: 2.0 * (p - rank), ps)
            u, ss = sh.update(g, ss, ps)
            ps = optax.apply_updates(ps, u)
            g = jax.tree_util.tree_map(lambda p: 2.0 * (p - rank), pr)
            u, sr = rep.update(g, sr, pr)
            pr = optax.apply_updates(pr, u)
        for k in ps:
            assert np.allclose(np.asarray(ps[k]), np.asarray(pr[k]),
                               rtol=1e-5, atol=1e-7), (k, ps[k], pr[k])
    """, extra_env={"HOROVOD_OVERLAP": "1",
                    "HOROVOD_OVERLAP_CHUNKS": "3"})


@pytest.mark.multiprocess
def test_overlap_handshake_mismatch_2proc():
    """One rank overlapping, the other not: the round-0 cfg handshake
    must fail fast instead of deadlocking in mismatched collectives."""
    from tests.test_multiprocess import run_ranks

    run_ranks("""
        import os
        os.environ["HOROVOD_OVERLAP"] = "1" if rank == 0 else "0"
        try:
            hvd.allreduce(jnp.ones(2), op=hvd.Sum, name="hs")
            raise SystemExit("expected a handshake mismatch error")
        except Exception as e:
            assert "HOROVOD_OVERLAP" in str(e), e
    """)
