"""Timeline negotiation diagnostics.

Reference ``common/timeline.h:85-88``: the NEGOTIATE phase records when
each rank's request reached the coordinator, so a trace shows *who* was
late for a collective, not just that negotiation took long.
"""
import json

import pytest

from test_multiprocess import run_ranks

pytestmark = pytest.mark.multiprocess


def test_timeline_per_rank_ready_ticks(tmp_path):
    """Staggered 2-proc allreduce: the coordinator's trace must carry a
    per-rank ready tick for each rank on the tensor's row, and the
    straggler's tick must be visibly later."""
    trace = tmp_path / "tl.json"
    outs = run_ranks("""
        import time
        if rank == 1:
            time.sleep(2)
        out = hvd.allreduce(jnp.ones(3), op=hvd.Sum, name="tickme")
        assert np.allclose(np.asarray(out), 2.0), out
        print("COMPLETED", flush=True)
    """, extra_env={"HOROVOD_TIMELINE": str(trace)}, timeout=300)
    assert all("COMPLETED" in o for o in outs)

    data = json.loads(trace.read_text())
    rows = {e["args"]["name"]: e["tid"] for e in data
            if e.get("ph") == "M" and e.get("name") == "thread_name"}
    assert "tickme" in rows, rows
    ticks = {e["name"]: e for e in data
             if e.get("ph") == "i" and e.get("tid") == rows["tickme"]}
    assert "RANK0_READY" in ticks, sorted(ticks)
    assert "RANK1_READY" in ticks, sorted(ticks)
    # rank 1 slept 2s before submitting: its tick is the straggler
    assert ticks["RANK1_READY"]["ts"] - ticks["RANK0_READY"]["ts"] > 1e6
