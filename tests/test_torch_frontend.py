"""Torch frontend tests — the TPU analog of reference ``test/test_torch.py``
(1671 LoC, 46 tests): op correctness over a dtype matrix, in-place
semantics, autograd of allreduce/allgather/broadcast, the hook-driven
DistributedOptimizer, and state broadcast roundtrips.  Single-process
cases run against the LocalController; 2-process cases go through the
same spawn harness as test_multiprocess (the reference runs the same
file under ``horovodrun -np 2``)."""

import numpy as np
import pytest
import torch

from test_multiprocess import run_ranks

pytestmark = pytest.mark.multiprocess


@pytest.fixture()
def thvd():
    import horovod_tpu.torch as thvd

    thvd.init()
    yield thvd
    thvd.shutdown()


DTYPES = [torch.float32, torch.float16, torch.bfloat16, torch.float64,
          torch.int32, torch.int64, torch.uint8]


def test_allreduce_dtype_matrix_single(thvd):
    for dtype in DTYPES:
        for dims in [(17,), (3, 4), (2, 3, 4)]:
            if dtype.is_floating_point:
                t = torch.rand(*dims).to(dtype)
            else:
                t = torch.randint(0, 100, dims, dtype=dtype)
            out = thvd.allreduce(t.clone(), op=thvd.Sum)
            assert out.dtype == dtype
            assert torch.allclose(out.float(), t.float()), dtype


def test_allreduce_average_and_inplace_single(thvd):
    t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    out = thvd.allreduce(t.clone(), op=thvd.Average)
    assert torch.allclose(out, t)
    buf = t.clone()
    ret = thvd.allreduce_(buf, op=thvd.Sum)
    assert ret is buf
    assert torch.allclose(buf, t)


def test_allreduce_autograd_single(thvd):
    x = torch.rand(5, requires_grad=True)
    y = thvd.allreduce(x, op=thvd.Average)
    y.pow(2).sum().backward()
    assert torch.allclose(x.grad, 2 * x.detach())


def test_allgather_broadcast_alltoall_single(thvd):
    t = torch.rand(4, 3)
    assert torch.allclose(thvd.allgather(t), t)
    assert torch.allclose(thvd.broadcast(t, root_rank=0), t)
    assert torch.allclose(thvd.alltoall(t), t)


def test_broadcast_autograd_single(thvd):
    x = torch.rand(4, requires_grad=True)
    y = thvd.broadcast(x, root_rank=0)
    y.sum().backward()
    assert torch.allclose(x.grad, torch.ones(4))


def test_compression_fp16_single(thvd):
    t = torch.rand(32) + 1.0
    out = thvd.allreduce(t.clone(), op=thvd.Sum,
                         compression=thvd.Compression.fp16)
    assert out.dtype == torch.float32
    assert torch.allclose(out, t, atol=1e-2)


def test_distributed_optimizer_single_matches_plain(thvd):
    torch.manual_seed(0)
    model = torch.nn.Linear(4, 2)
    ref = torch.nn.Linear(4, 2)
    ref.load_state_dict(model.state_dict())
    x, y = torch.rand(8, 4), torch.rand(8, 2)

    opt = thvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1)
    for o, m in ((opt, model), (ref_opt, ref)):
        o.zero_grad()
        torch.nn.functional.mse_loss(m(x), y).backward()
        o.step()
    for a, b in zip(model.parameters(), ref.parameters()):
        assert torch.allclose(a, b)


def test_broadcast_parameters_and_object_single(thvd):
    model = torch.nn.Linear(3, 3)
    before = {k: v.clone() for k, v in model.state_dict().items()}
    thvd.broadcast_parameters(model.state_dict(), root_rank=0)
    for k, v in model.state_dict().items():
        assert torch.allclose(v, before[k])
    obj = thvd.broadcast_object({"epoch": 3, "lr": 0.1}, root_rank=0)
    assert obj == {"epoch": 3, "lr": 0.1}


def test_broadcast_optimizer_state_single(thvd):
    model = torch.nn.Linear(3, 3)
    opt = torch.optim.SGD(model.parameters(), lr=0.25, momentum=0.9,
                          weight_decay=1e-4)
    model(torch.rand(2, 3)).sum().backward()
    opt.step()
    thvd.broadcast_optimizer_state(opt, root_rank=0)
    assert opt.param_groups[0]["lr"] == 0.25
    assert opt.param_groups[0]["momentum"] == 0.9
    sd = opt.state_dict()
    assert any("momentum_buffer" in s for s in sd["state"].values())


@pytest.mark.parametrize("opt_ctor", [
    lambda p: torch.optim.SGD(p, lr=0.1, momentum=0.9),
    lambda p: torch.optim.Adam(p, lr=1e-3, amsgrad=True),
    lambda p: torch.optim.AdamW(p, lr=1e-3),
    lambda p: torch.optim.Adamax(p, lr=1e-3),
    lambda p: torch.optim.Adadelta(p, lr=0.5),
    lambda p: torch.optim.Adagrad(p, lr=0.1),
    lambda p: torch.optim.ASGD(p, lr=0.1),
    lambda p: torch.optim.RMSprop(p, lr=0.01, momentum=0.9,
                                  centered=True),
    lambda p: torch.optim.Rprop(p, lr=0.01),
], ids=["sgd", "adam-amsgrad", "adamw", "adamax", "adadelta",
        "adagrad", "asgd", "rmsprop-centered", "rprop"])
def test_broadcast_optimizer_state_matrix(thvd, opt_ctor):
    """State broadcast round-trips every torch optimizer's state shape
    — per-param tensors, python scalars, step counters (the reference's
    all-optimizer grid, ``test_torch.py:914-1131``).  Size-1 broadcast
    is the identity, so the value under test is the state traversal /
    wire serialization, checked by stepping again afterwards."""
    model = torch.nn.Linear(3, 3)
    opt = opt_ctor(model.parameters())
    model(torch.rand(2, 3)).sum().backward()
    opt.step()
    before = {k: v.clone() for k, v in model.state_dict().items()}
    thvd.broadcast_optimizer_state(opt, root_rank=0)
    for k, v in model.state_dict().items():
        assert torch.equal(v, before[k]), k  # params untouched
    sd = opt.state_dict()
    assert sd["state"], "optimizer state empty after broadcast"
    for s in sd["state"].values():
        for val in s.values():
            if torch.is_tensor(val):
                assert torch.isfinite(val.float()).all()
    # the optimizer still works after its state rode the wire
    opt.zero_grad()
    model(torch.rand(2, 3)).sum().backward()
    opt.step()


def test_broadcast_optimizer_state_weight_decay_keeps_params(thvd):
    # the state-materializing dummy step must not move parameters even
    # when weight_decay makes a zero-grad step a real update
    model = torch.nn.Linear(3, 3)
    opt = torch.optim.SGD(model.parameters(), lr=0.5, momentum=0.9,
                          weight_decay=0.1)
    before = {k: v.clone() for k, v in model.state_dict().items()}
    thvd.broadcast_optimizer_state(opt, root_rank=0)
    for k, v in model.state_dict().items():
        assert torch.equal(v, before[k]), k


def test_bf16_rides_wire_as_bf16(thvd):
    t = torch.rand(8, dtype=torch.bfloat16)
    out = thvd.allreduce(t.clone(), op=thvd.Sum)
    assert out.dtype == torch.bfloat16
    assert torch.equal(out, t)
    # compression to bf16 halves the wire without changing result dtype
    f = torch.rand(8) + 1.0
    cout = thvd.allreduce(f.clone(), op=thvd.Sum,
                          compression=thvd.Compression.bf16)
    assert cout.dtype == torch.float32
    assert torch.allclose(cout, f, atol=1e-2)


def test_lbfgs_rejected(thvd):
    model = torch.nn.Linear(2, 2)
    opt = torch.optim.LBFGS(model.parameters())
    with pytest.raises(ValueError):
        thvd.broadcast_optimizer_state(opt, root_rank=0)


def test_allreduce_int64_exact_single(thvd):
    # values beyond 2^31 must survive (exact byte-wire path; a 32-bit
    # wire would wrap them)
    t = torch.tensor([3_000_000_000, -5_000_000_000], dtype=torch.int64)
    out = thvd.allreduce(t.clone(), op=thvd.Sum)
    assert out.dtype == torch.int64
    assert torch.equal(out, t)
    f = torch.tensor([1.0 + 2**-40], dtype=torch.float64)
    fout = thvd.allreduce(f.clone(), op=thvd.Sum)
    assert fout.dtype == torch.float64
    assert torch.equal(fout, f)
    g = thvd.allgather(t)
    assert g.dtype == torch.int64 and torch.equal(g, t)
    b = thvd.broadcast(t, root_rank=0)
    assert b.dtype == torch.int64 and torch.equal(b, t)


def test_int64_average_truncates_toward_zero():
    """Negative int64 averages must truncate like the reference's C++
    ``/`` (toward zero), not numpy's floor (ADVICE r1: -7 // 2 == -4
    but the reference computes -3)."""
    from horovod_tpu.torch.mpi_ops import _int64_trunc_average

    summed = np.array([-7, 7, -8, 5, 0], dtype=np.int64)
    out = _int64_trunc_average(summed, 2)
    assert out.tolist() == [-3, 3, -4, 2, 0]
    # INT64_MIN must not overflow through np.abs
    edge = np.array([np.iinfo(np.int64).min], dtype=np.int64)
    assert _int64_trunc_average(edge, 2).tolist() == [-(2 ** 62)]


# ---------------------------------------------------------------------------
# 2-process distributed correctness
# ---------------------------------------------------------------------------

# Importing torch (~5 s of GIL-holding native init on the 1-core image)
# after hvd.init() starved the heartbeat publisher past its 20 s
# default and flaked these tests with false dead-peer aborts: pre-warm
# the import before init and loosen the deadline as a backstop.
_TORCH_2PROC = dict(prewarm="import torch",
                    extra_env={"HOROVOD_HEARTBEAT_TIMEOUT_SECONDS": "120"})


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_torch_collectives_2proc():
    run_ranks("""
        import torch
        import horovod_tpu.torch as thvd
        t = torch.full((4,), float(rank + 1))
        out = thvd.allreduce(t.clone(), op=thvd.Sum)
        assert torch.allclose(out, torch.full((4,), 3.0)), out
        avg = thvd.allreduce(t.clone(), op=thvd.Average)
        assert torch.allclose(avg, torch.full((4,), 1.5)), avg
        buf = torch.full((4,), float(rank))
        thvd.allreduce_(buf, op=thvd.Sum)
        assert torch.allclose(buf, torch.full((4,), 1.0)), buf
        g = thvd.allgather(torch.full((rank + 1, 2), float(rank)))
        assert g.shape == (3, 2), g.shape
        assert torch.allclose(g[0], torch.zeros(2))
        assert torch.allclose(g[1:], torch.ones((2, 2)))
        b = thvd.broadcast(torch.full((3,), float(rank * 7)), root_rank=1)
        assert torch.allclose(b, torch.full((3,), 7.0)), b
        obj = thvd.broadcast_object([1, "two"] if rank == 0 else None, 0)
        assert obj == [1, "two"]
        # exact 64-bit sum across ranks (wraps if the wire were 32-bit)
        big = torch.tensor([2_000_000_000], dtype=torch.int64)
        s = thvd.allreduce(big, op=thvd.Sum)
        assert s.item() == 4_000_000_000, s
        # negative int64 average truncates toward zero like the
        # reference's C++ division: sum = -7, avg over 2 ranks = -3
        neg = torch.tensor([-3 - rank], dtype=torch.int64)  # -3, -4
        a = thvd.allreduce(neg, op=thvd.Average)
        assert a.item() == -3, a  # trunc(-7/2) = -3; floor would be -4
    """, **_TORCH_2PROC)


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_torch_optimizer_hooks_2proc():
    run_ranks("""
        import torch
        import horovod_tpu.torch as thvd
        torch.manual_seed(0)
        model = torch.nn.Linear(4, 1, bias=False)
        thvd.broadcast_parameters(model.state_dict(), root_rank=0)
        w0 = model.weight.detach().clone()
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.5),
            named_parameters=model.named_parameters())
        # rank-dependent input; averaged grad must be identical on both
        x = torch.full((2, 4), float(rank + 1))
        model(x).sum().backward()
        opt.step()
        # grad per rank = sum over batch of x = 2*(rank+1) per weight
        # averaged: (2*1 + 2*2)/2 = 3
        expect = w0 - 0.5 * 3.0
        assert torch.allclose(model.weight.detach(), expect, atol=1e-5), \\
            (model.weight, expect)
        opt.zero_grad()
        # state broadcast keeps ranks in sync
        thvd.broadcast_optimizer_state(opt, root_rank=0)
    """, **_TORCH_2PROC)


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_torch_allgather_backward_2proc():
    run_ranks("""
        import torch
        import horovod_tpu.torch as thvd
        x = torch.full((rank + 1, 2), 1.0, requires_grad=True)
        y = thvd.allgather(x)
        assert y.shape == (3, 2)
        # d/dx of sum(w * y) where w marks rows: every rank's slice of
        # the summed upstream grad
        w = torch.arange(6, dtype=torch.float32).reshape(3, 2)
        (y * w).sum().backward()
        start = 0 if rank == 0 else 1
        expect = 2 * w[start:start + rank + 1]
        assert torch.allclose(x.grad, expect), (x.grad, expect)
    """, **_TORCH_2PROC)


def test_torch_mismatch_errors_2proc():
    """Cross-rank shape and dtype mismatches must surface the
    coordinator's error on every rank and leave the runtime usable
    (reference test_torch.py:334-443 error-injection matrix)."""
    run_ranks("""
        import torch
        import horovod_tpu.torch as thvd
        from horovod_tpu.common.types import HorovodTpuError
        # shape mismatch
        try:
            thvd.allreduce(torch.ones(rank + 2), name="bad.shape")
            raise SystemExit("no shape error on rank %d" % rank)
        except HorovodTpuError as e:
            assert "Mismatched shapes" in str(e), e
        # dtype mismatch
        try:
            t = (torch.ones(3, dtype=torch.float32) if rank == 0
                 else torch.ones(3, dtype=torch.int32))
            thvd.allreduce(t, name="bad.dtype")
            raise SystemExit("no dtype error on rank %d" % rank)
        except HorovodTpuError as e:
            assert "Mismatched data types" in str(e), e
        # op mismatch
        try:
            thvd.allreduce(torch.ones(3),
                           op=thvd.Sum if rank == 0 else thvd.Average,
                           name="bad.op")
            raise SystemExit("no op error on rank %d" % rank)
        except HorovodTpuError as e:
            assert "Mismatched reduce ops" in str(e), e
        # runtime still fully usable afterwards
        ok = thvd.allreduce(torch.ones(3), op=thvd.Sum, name="good")
        assert torch.allclose(ok, torch.full((3,), 2.0)), ok
    """, **_TORCH_2PROC)


@pytest.mark.parametrize("opt_ctor", [
    lambda ps: torch.optim.Adam(ps, lr=1e-3),
    lambda ps: torch.optim.AdamW(ps, lr=1e-3, weight_decay=1e-2),
    lambda ps: torch.optim.RMSprop(ps, lr=1e-3, momentum=0.9),
    lambda ps: torch.optim.Adagrad(ps, lr=1e-2),
    lambda ps: torch.optim.Adadelta(ps, lr=1.0),
    lambda ps: torch.optim.ASGD(ps, lr=1e-2),
    lambda ps: torch.optim.Adamax(ps, lr=1e-3),
], ids=["adam", "adamw", "rmsprop", "adagrad", "adadelta", "asgd",
        "adamax"])
def test_broadcast_optimizer_state_all_optimizers(thvd, opt_ctor):
    """Reference ``test_torch.py:914-1131`` broadcasts optimizer state
    across every torch optimizer family: hyperparameters and per-param
    state tensors (exp_avg, square_avg, acc_delta, ...) must survive
    the wire round-trip bit-exactly at size 1."""
    model = torch.nn.Linear(3, 2)
    opt = opt_ctor(model.parameters())
    model(torch.rand(4, 3)).sum().backward()
    opt.step()
    before_groups = [{k: v for k, v in g.items() if k != "params"}
                     for g in opt.param_groups]
    before_state = {p: {k: (v.clone() if torch.is_tensor(v) else v)
                        for k, v in s.items()}
                    for p, s in opt.state.items()}
    thvd.broadcast_optimizer_state(opt, root_rank=0)
    for g, bg in zip(opt.param_groups, before_groups):
        for k, v in bg.items():
            assert g[k] == v, (k, g[k], v)
    for p, s in opt.state.items():
        for k, v in s.items():
            if torch.is_tensor(v):
                assert torch.equal(v, before_state[p][k]), k
            else:
                assert v == before_state[p][k], k
