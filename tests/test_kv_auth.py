"""KV-wire authentication (VERDICT r2 missing #2).

The reference HMAC-signs every launcher-service message
(``horovod/run/common/util/secret.py:26``); here the native KV store
authenticates each TCP connection with an HMAC-SHA256
challenge-response before serving any op.  These tests prove:
an authenticated client works, a wrong-secret client is rejected,
and a raw unauthenticated socket cannot SET (the round-1 finding:
any stray process could poison negotiation state).
"""

from __future__ import annotations

import hmac
import hashlib
import socket
import struct

import pytest

from horovod_tpu.runtime import kvstore


@pytest.fixture()
def server():
    try:
        srv = kvstore.KVStoreServer(secret=b"job-secret-123")
    except Exception as exc:
        pytest.skip(f"native KV store unavailable ({exc})")
    yield srv
    srv.stop()


def test_authenticated_client_roundtrip(server):
    c = kvstore.KVStoreClient("127.0.0.1", server.port,
                              connect_timeout_s=5,
                              secret=b"job-secret-123")
    c.set("k", "v")
    assert c.try_get("k") == "v"
    c.delete("k")
    assert c.try_get("k") is None
    assert c.ping()
    c.close()


def test_wrong_secret_rejected(server):
    with pytest.raises(OSError, match="SECRET_KEY mismatch|could not reach"):
        kvstore.KVStoreClient("127.0.0.1", server.port,
                              connect_timeout_s=2, secret=b"wrong")


def test_unauthenticated_raw_socket_cannot_set(server):
    """A client that skips the handshake and fires a SET frame must not
    mutate the store."""
    s = socket.create_connection(("127.0.0.1", server.port), timeout=5)
    # server speaks first (challenge); ignore it and send a raw SET
    key, val = b"poison", b"1"
    frame = (struct.pack("<BI", 1, len(key)) + key +
             struct.pack("<I", len(val)) + val)
    s.sendall(frame)
    # server reads our frame bytes as a (wrong) MAC and closes
    s.settimeout(5)
    leftover = b""
    try:
        while True:
            chunk = s.recv(4096)
            if not chunk:
                break
            leftover += chunk
    except (ConnectionResetError, socket.timeout):
        pass
    s.close()
    # only the 20-byte challenge may have been sent — never an auth-ok
    # byte followed by op responses
    assert len(leftover) <= 20
    good = kvstore.KVStoreClient("127.0.0.1", server.port,
                                 connect_timeout_s=5,
                                 secret=b"job-secret-123")
    assert good.try_get("poison") is None
    good.close()


def _authed_socket(server, secret: bytes = b"job-secret-123"):
    """Open a raw socket and complete the HVK2 challenge-response with
    Python's hmac — the single place the wire handshake is spelled out
    test-side."""
    s = socket.create_connection(("127.0.0.1", server.port), timeout=5)
    challenge = b""
    while len(challenge) < 20:
        chunk = s.recv(20 - len(challenge))
        assert chunk, "server closed during challenge"
        challenge += chunk
    assert challenge[:4] == b"HVK2"
    mac = hmac.new(secret, challenge[4:], hashlib.sha256)
    s.sendall(mac.digest())
    ok = s.recv(1)
    assert ok == b"\x00", "python-computed HMAC rejected by C++ verifier"
    return s


def test_cpp_hmac_matches_python_hmac(server):
    """Speak the wire protocol from Python with hashlib/hmac — proves
    the C++ HMAC-SHA256 is the real RFC 2104 construction, not an
    ad-hoc hash."""
    s = _authed_socket(server)
    # a real op over the hand-authenticated connection
    key, val = b"from-python", b"yes"
    s.sendall(struct.pack("<BI", 1, len(key)) + key +
              struct.pack("<I", len(val)) + val)
    status = s.recv(1)
    assert status == b"\x00"
    s.close()


def test_malformed_frames_after_auth_do_not_kill_server(server):
    """Garbage frames on an authenticated connection must only drop
    that connection; the server keeps serving others (native-code
    robustness, like the wire-codec fuzz tests)."""
    import random

    rng = random.Random(0)
    for trial in range(10):
        s = _authed_socket(server)
        # shove random garbage at the op parser
        s.sendall(bytes(rng.randrange(256)
                        for _ in range(rng.randrange(1, 64))))
        s.close()
    good = kvstore.KVStoreClient("127.0.0.1", server.port,
                                 connect_timeout_s=5,
                                 secret=b"job-secret-123")
    good.set("alive", "yes")
    assert good.try_get("alive") == "yes"
    good.close()


def test_no_secret_server_accepts_any_client():
    """Empty secret = auth disabled (unit-test mode) — existing tests
    and single-process flows keep working without env setup."""
    try:
        srv = kvstore.KVStoreServer(secret=b"")
    except Exception as exc:
        pytest.skip(f"native KV store unavailable ({exc})")
    try:
        c = kvstore.KVStoreClient("127.0.0.1", srv.port,
                                  connect_timeout_s=5, secret=b"")
        c.set("a", "b")
        assert c.try_get("a") == "b"
        c.close()
    finally:
        srv.stop()
