"""Block-scaled int8 quantized allreduce (EQuARX-style).

Covers the quantization wire format round trip, the scale-aware
quantized psum/reducescatter, the hierarchical ICI-full-precision /
DCN-int8 split (including a jaxpr proof that the cross-axis psum rides
int8), error-feedback convergence, and the DistributedOptimizer /
Compression surface — all on the 8-device virtual CPU mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.common import config as _config
from horovod_tpu.common.types import HorovodTpuError
from horovod_tpu.ops import collectives as coll
from horovod_tpu.ops import quantization as q
from horovod_tpu.ops.compression import Compression, is_quantized

N, CROSS, LOCAL = 8, 2, 4


@pytest.fixture(scope="module")
def hmesh():
    devs = jax.devices()
    assert len(devs) >= N
    return Mesh(np.array(devs[:N]).reshape(CROSS, LOCAL),
                ("cross", "local"))


def run2d(hmesh, body, x, out_specs=P()):
    fn = jax.jit(shard_map(body, mesh=hmesh, check_vma=False,
                           in_specs=P(("cross", "local")),
                           out_specs=out_specs))
    return fn(x)


# ---------------------------------------------------------------------------
# Wire format: local quantize -> dequantize round trip
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("shape", [(1024,), (3, 333), (7,)])
def test_roundtrip_within_halfscale(shape):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    qv, scales, meta = q.quantize_block_scaled(x)
    assert qv.dtype == jnp.int8
    back = q.dequantize_block_scaled(qv, scales, meta)
    assert back.shape == x.shape and back.dtype == x.dtype
    # |x - dq(q(x))| <= scale/2 per element, scale = blockmax/127
    err = np.abs(np.asarray(back) - np.asarray(x))
    assert err.max() <= float(scales.max()) / 2 + 1e-7


def test_roundtrip_preserves_dtype_and_ints_pass_through():
    x16 = jnp.asarray(np.arange(512, dtype=np.float32)).astype(jnp.bfloat16)
    qv, scales, meta = q.quantize_block_scaled(x16)
    back = q.dequantize_block_scaled(qv, scales, meta)
    assert back.dtype == jnp.bfloat16
    # integer / bool tensors bypass quantization entirely
    for t in (jnp.arange(8, dtype=jnp.int32),
              jnp.asarray([True, False, True])):
        wire, ctx = Compression.int8.compress(t)
        assert wire is t and ctx is None
        assert Compression.int8.decompress(wire, ctx) is t


def test_pallas_interpret_matches_jnp():
    """Forced-Pallas (interpret mode on CPU) and the jnp fallback must
    produce bit-identical int8 payloads and dequantized values."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((5, 300)).astype(np.float32))
    results = {}
    for mode in ("0", "1"):
        _config.set_knob("quant_pallas", mode)
        try:
            results[mode] = q.quantize_block_scaled(x, block_size=256)
        finally:
            _config.set_knob("quant_pallas", "auto")
    (q0, s0, m0), (q1, s1, m1) = results["0"], results["1"]
    np.testing.assert_array_equal(np.asarray(q0), np.asarray(q1))
    np.testing.assert_array_equal(np.asarray(s0), np.asarray(s1))
    assert m0 == m1


def test_sum_safe_qmax():
    for n in (1, 2, 3, 4, 8, 127):
        qm = q.sum_safe_qmax(n)
        assert qm >= 1 and n * qm <= 127
    # past 127 ranks no headroom exists — must refuse, never wrap
    with pytest.raises(ValueError, match="sum-safe"):
        q.sum_safe_qmax(128)
    with pytest.raises(ValueError, match="HIERARCHICAL"):
        q.sum_safe_qmax(200)


# ---------------------------------------------------------------------------
# Quantized reductions on the mesh
# ---------------------------------------------------------------------------


def _bound(x, n, block=256):
    """Documented per-element bound for an n-rank quantized sum:
    n * shared_scale / 2, shared_scale = pmax(blockmax) / (127 // n)."""
    flat = np.asarray(x, np.float32).reshape(N, -1)
    pad = (-flat.shape[1]) % block
    if pad:
        flat = np.concatenate([flat, np.zeros((N, pad), np.float32)], 1)
    blockmax = np.abs(flat.reshape(N, -1, block)).max(axis=(0, 2))
    scale = blockmax / (127 // n)
    return np.repeat(n * scale / 2, block)[:flat.shape[1] - pad or None]


@pytest.mark.parametrize("size", [4096, 1000])  # 1000: padding path
def test_quantized_psum_within_bound(hmesh, size):
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((N, size)).astype(np.float32))
    out = run2d(hmesh, lambda b: coll.quantized_allreduce(
        b[0], axis_name=("cross", "local"), op=coll.Sum), x)
    exact = np.asarray(x).sum(0)
    err = np.abs(np.asarray(out) - exact)
    assert (err <= _bound(x, N)[:size] + 1e-6).all(), err.max()


def test_quantized_psum_exact_on_scale_grid(hmesh):
    """Integer-valued inputs with per-block absmax 127//N make the
    shared scale exactly 1.0 — quantization is lossless."""
    qm = 127 // N
    base = (np.arange(N * 512) % (2 * qm + 1) - qm).astype(np.float32)
    x = jnp.asarray(base.reshape(N, 512))
    out = run2d(hmesh, lambda b: coll.quantized_allreduce(
        b[0], axis_name=("cross", "local"), op=coll.Sum), x)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(x).sum(0))


@pytest.mark.parametrize("hier", [False, True])
def test_hierarchical_quantized_matches_flat_psum(hmesh, hier):
    """Quantized allreduce (flat int8 and ICI-fp32/DCN-int8) stays
    within the documented bound of the flat full-precision psum."""
    _config.set_knob("hierarchical_allreduce", hier)
    try:
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((N, 2048)).astype(np.float32))
        out = run2d(hmesh, lambda b: coll.quantized_allreduce(
            b[0], axis_name=("cross", "local"), op=coll.Average), x)
        exact = run2d(hmesh, lambda b: coll.allreduce(
            b[0], axis_name=("cross", "local"), op=coll.Average), x)
        if hier:
            # only the CROSS hop quantizes, and what it quantizes are
            # the local-group partial sums (post ICI reduce-scatter) —
            # bound from THEIR per-block absmax
            parts = np.asarray(x).reshape(CROSS, LOCAL, -1).sum(1)
            blockmax = np.abs(parts).max(0).reshape(-1, 256).max(1)
            scale = blockmax / (127 // CROSS)
            bound = np.repeat(CROSS * scale / 2, 256) / N + 1e-6
        else:
            # the full 8-rank sum rides int8
            bound = _bound(x, N)[:2048] / N + 1e-6
        err = np.abs(np.asarray(out) - np.asarray(exact))
        assert (err <= bound).all(), (err.max(), bound.max())
    finally:
        _config.set_knob("hierarchical_allreduce", False)


def test_hierarchical_sends_int8_on_cross_axis_only(hmesh):
    """EQuARX two-level proof by jaxpr inspection: the cross-axis psum
    payload is int8; every local-axis collective stays float32."""
    _config.set_knob("hierarchical_allreduce", True)
    try:
        jaxpr = jax.make_jaxpr(shard_map(
            lambda b: coll.quantized_allreduce(
                b[0], axis_name=("cross", "local"), op=coll.Sum),
            mesh=hmesh, check_vma=False,
            in_specs=P(("cross", "local")), out_specs=P()))(
                jnp.zeros((N, 1024), jnp.float32))
    finally:
        _config.set_knob("hierarchical_allreduce", False)
    import re

    text = str(jaxpr)
    # the full-payload cross-axis psum carries i8
    i8_cross = re.findall(
        r"i8\[[\d,]+\] = psum\[axes=\('cross',\)", text)
    assert i8_cross, text
    # no int8 ever rides the local (ICI) axis
    assert not re.findall(r"i8\[[\d,]+\] = \w+\[axes=\('local',\)", text)
    # the intra-slice reduce-scatter and all-gather stay f32
    assert re.findall(r"f32\[[\d,]+\] = reduce_scatter\[", text)
    assert re.findall(r"f32\[[\d,]+\] = all_gather\[", text)
    # the only cross-axis f32 traffic is the per-block scale pmax
    # (1/block_size of the payload)
    f32_cross = re.findall(
        r"f32\[(\d+)\] = pmax\[axes=\('cross',\)", text)
    assert f32_cross and all(int(sz) <= 1024 // 256
                             for sz in f32_cross), text


def test_quantized_reducescatter_within_bound(hmesh):
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((N, 16 * N, 32))
                    .astype(np.float32))
    out = run2d(hmesh, lambda b: coll.reducescatter(
        b[0], axis_name=("cross", "local"),
        compression=Compression.int8), x,
        out_specs=P(("cross", "local")))
    exact = np.asarray(x).sum(0)
    assert out.shape == (16 * N, 32)
    blockmax = np.abs(np.asarray(x)).max()
    bound = N * (blockmax / (127 // N)) / 2 + 1e-6
    assert np.abs(np.asarray(out) - exact).max() <= bound


def test_grouped_quantized_allreduce_fuses_and_passes_ints(hmesh):
    rng = np.random.default_rng(5)
    a = rng.standard_normal((N, 40, 3)).astype(np.float32)
    b = rng.standard_normal((N, 17)).astype(np.float32)
    c = np.tile(np.arange(5, dtype=np.int32), (N, 1))

    def body(ba, bb, bc):
        outs, _ = coll.grouped_quantized_allreduce(
            [ba[0], bb[0], bc[0]],
            axis_name=("cross", "local"), op=coll.Sum)
        return tuple(outs)

    fn = shard_map(body, mesh=hmesh, check_vma=False,
                   in_specs=(P(("cross", "local")),) * 3,
                   out_specs=(P(), P(), P()))
    oa, ob, oc = jax.jit(fn)(jnp.asarray(a), jnp.asarray(b),
                             jnp.asarray(c))
    assert oa.shape == (40, 3) and ob.shape == (17,)
    # int leaf passes through uncompressed: exact
    np.testing.assert_array_equal(np.asarray(oc), c.sum(0))
    allx = np.concatenate([a.reshape(N, -1), b.reshape(N, -1)], 1)
    bound = N * (np.abs(allx).max() / (127 // N)) / 2 + 1e-6
    assert np.abs(np.asarray(oa) - a.sum(0)).max() <= bound
    assert np.abs(np.asarray(ob) - b.sum(0)).max() <= bound
    # ONE fused int8 psum for all float leaves (not one per tensor)
    import re

    text = str(jax.make_jaxpr(fn)(jnp.asarray(a), jnp.asarray(b),
                                  jnp.asarray(c)))
    assert len(re.findall(r"i8\[[\d,]+\] = psum\[", text)) == 1, text


# ---------------------------------------------------------------------------
# Error feedback
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("hier", [False, True])
def test_error_feedback_convergence(hmesh, hier):
    """On a fixed per-rank gradient, the running mean of the
    EF-compensated quantized reduction converges to the exact mean —
    the mean compression error goes to 0 over steps."""
    from horovod_tpu.optim import distributed as dist

    _config.set_knob("hierarchical_allreduce", hier)
    try:
        rng = np.random.default_rng(6)
        g = jnp.asarray(rng.standard_normal((N, 512)).astype(np.float32))
        exact = np.asarray(g).mean(0)

        def step(gl, res):
            out, new = dist.allreduce_gradients_with_feedback(
                {"w": gl}, res, op=coll.Average,
                axis_name=("cross", "local"))
            return out["w"], new

        fn = jax.jit(shard_map(
            step, mesh=hmesh, check_vma=False,
            in_specs=(P(("cross", "local")),
                      {"w": P(("cross", "local"))}),
            out_specs=(P(), {"w": P(("cross", "local"))})))
        res = {"w": jnp.zeros((N, 512), jnp.float32)}
        acc = np.zeros(512)
        errs = []
        for i in range(24):
            out, res = fn(g, res)
            acc += np.asarray(out)[0]
            errs.append(np.abs(acc / (i + 1) - exact).max())
        # running-mean error shrinks by >5x over 24 steps (measured
        # ~30x flat / ~30x hierarchical; without EF it would not
        # shrink at all — the per-step quantization error is fixed)
        assert errs[-1] < errs[0] / 5, (errs[0], errs[-1])
    finally:
        _config.set_knob("hierarchical_allreduce", False)


def test_error_feedback_helpers():
    params = {"a": jnp.zeros((3, 2), jnp.bfloat16), "b": jnp.ones(4)}
    res = q.init_error_feedback(params)
    assert res["a"].dtype == jnp.float32 and res["a"].shape == (3, 2)
    g = {"a": jnp.ones((3, 2), jnp.bfloat16), "b": jnp.ones(4)}
    out = q.apply_error_feedback(g, res)
    assert out["a"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(out["b"]), np.ones(4))


def test_distributed_optimizer_int8_carries_feedback_state(hmesh):
    """DistributedOptimizer(compression=int8) wraps the inner optax
    state in _FeedbackState and its updates track full-precision SGD
    within the quantization bound."""
    optax = pytest.importorskip("optax")
    from horovod_tpu.optim import distributed as dist

    opt = dist.DistributedOptimizer(optax.sgd(0.1),
                                    compression=Compression.int8,
                                    op=coll.Average,
                                    axis_name=("cross", "local"))
    params = {"w": jnp.zeros(256, jnp.float32)}
    state = opt.init(params)
    assert isinstance(state, dist._FeedbackState)
    assert state.residual["w"].shape == (256,)

    rng = np.random.default_rng(7)
    g = rng.standard_normal((N, 256)).astype(np.float32)

    def step(gl, res, inner):
        st = dist._FeedbackState({"w": res[0]}, inner)
        upd, new = opt.update({"w": gl[0]}, st, params)
        return upd["w"], new.residual["w"][None], new.inner_state

    fn = jax.jit(shard_map(
        step, mesh=hmesh, check_vma=False,
        in_specs=(P(("cross", "local")), P(("cross", "local")), P()),
        out_specs=(P(), P(("cross", "local")), P())))
    res = jnp.zeros((N, 256), jnp.float32)
    inner = state.inner_state
    upd, res, inner = fn(jnp.asarray(g), res, inner)
    exact_upd = -0.1 * g.mean(0)
    bound = 0.1 * _bound(g, N)[:256] / N + 1e-6
    assert (np.abs(np.asarray(upd) - exact_upd) <= bound).all()
    # second step re-injects the residual (it is nonzero after step 1)
    assert float(jnp.abs(res).max()) > 0
    fn(jnp.asarray(g), res, inner)


# ---------------------------------------------------------------------------
# API surface / guard rails
# ---------------------------------------------------------------------------


def test_compression_lookup_and_knob():
    assert Compression.lookup("int8") is Compression.int8
    assert Compression.lookup("none") is Compression.none
    assert is_quantized(Compression.int8)
    assert not is_quantized(Compression.bf16)
    assert Compression.lookup("int4") is Compression.int4
    assert Compression.lookup("topk") is Compression.topk
    assert is_quantized(Compression.int4)
    assert is_quantized(Compression.topk)
    with pytest.raises(ValueError):
        Compression.lookup("int2")
    from horovod_tpu.ops.compression import active_compression

    _config.set_knob("compression", "int8")
    try:
        assert active_compression() is Compression.int8
    finally:
        _config.set_knob("compression", "none")
    assert active_compression() is Compression.none


def test_int8_adasum_rejected(hmesh):
    with pytest.raises(HorovodTpuError, match="Adasum"):
        run2d(hmesh, lambda b: coll.allreduce(
            b[0], axis_name=("cross", "local"), op=coll.Adasum,
            compression=Compression.int8),
            jnp.ones((N, 256), jnp.float32))


def test_eager_per_call_int8_rejected(hvd_single):
    hvd = hvd_single
    from horovod_tpu.ops import eager

    with pytest.raises(HorovodTpuError, match="HOROVOD_COMPRESSION"):
        eager.allreduce_async(jnp.ones(8), op=hvd.Sum,
                              compression=Compression.int8,
                              name="q.reject")
