"""Wire-codec tests: roundtrip through both codecs and byte-identity
between the native (C++) and pure-Python implementations on randomized
messages — the analog of the reference's FlatBuffers schema staying in
sync with message.h (horovod/common/wire/message.fbs)."""

import random

import pytest

from horovod_tpu.runtime import wire


def _rand_rank_msg(rng, with_cfg=False):
    reqs = []
    for i in range(rng.randint(0, 5)):
        reqs.append({
            "n": f"tensor.{i}." + "x" * rng.randint(0, 40),
            "k": rng.choice(["allreduce", "allgather", "broadcast",
                             "alltoall"]),
            "o": rng.randint(0, 3),
            "d": rng.randint(0, 10),
            "s": [rng.randint(0, 2 ** 40) for _ in range(rng.randint(0, 4))],
            "r": rng.choice([-1, 0, 3]),
        })
    m = {"b": sorted(rng.sample(range(64), rng.randint(0, 8))),
         "i": sorted(rng.sample(range(64), rng.randint(0, 4))),
         "req": reqs,
         "j": rng.random() < 0.3,
         "x": rng.random() < 0.1}
    if with_cfg:
        # count-prefixed list: exercise the round-0 4-knob shape plus
        # shorter/longer variants
        m["cfg"] = [rng.randint(0, 2 ** 50)
                    for _ in range(rng.randint(1, 6))]
    return m


def _rand_resp_msg(rng, fast=False, tune=False):
    m = {}
    if tune:
        m["t"] = {"fusion_threshold": rng.randint(0, 2 ** 30),
                  "cache_enabled": True}
    if fast:
        m["f"] = sorted(rng.sample(range(64), rng.randint(0, 10)))
        return m
    resps = []
    for i in range(rng.randint(0, 4)):
        kind = rng.choice(["allreduce", "allgather", "broadcast",
                           "alltoall", "join", "error"])
        nn = rng.randint(0, 3)
        resps.append({
            "k": kind,
            "n": [f"t.{i}.{j}" for j in range(nn)],
            "o": rng.randint(0, 3),
            "r": rng.choice([-1, 2]),
            "d": rng.randint(0, 10),
            "s": [[rng.randint(0, 2 ** 40) for _ in
                   range(rng.randint(0, 3))] for _ in range(nn)],
            "e": None if kind != "error" else "boom: mismatch × unicode",
            "j": rng.choice([-1, 1]),
            "fd": ([rng.randint(0, 2 ** 40)
                    for _ in range(rng.randint(1, 5))]
                   if kind == "allgather" else []),
        })
    m.update({"resp": resps,
              "i": sorted(rng.sample(range(64), rng.randint(0, 4))),
              "x": rng.random() < 0.1, "aj": rng.random() < 0.2,
              "lj": rng.choice([-1, 0, 7])})
    return m


def _canon_rank(m):
    out = {"j": bool(m.get("j")), "x": bool(m.get("x")),
           "b": list(m.get("b") or []), "i": list(m.get("i") or []),
           "req": [dict(q) for q in m.get("req") or []]}
    if m.get("cfg") is not None:
        out["cfg"] = list(m["cfg"])
    for q in out["req"]:
        q["s"] = list(q["s"])
    return out


def test_rank_msg_roundtrip_python():
    rng = random.Random(0)
    for trial in range(50):
        m = _rand_rank_msg(rng, with_cfg=trial % 5 == 0)
        out = wire._py_decode_rank_msg(wire._py_encode_rank_msg(m))
        assert _canon_rank(out) == _canon_rank(m)


def test_resp_msg_roundtrip_python():
    rng = random.Random(1)
    for trial in range(50):
        m = _rand_resp_msg(rng, fast=trial % 3 == 0, tune=trial % 4 == 0)
        out = wire._py_decode_resp_msg(wire._py_encode_resp_msg(m))
        if "f" in m:
            assert out["f"] == m["f"]
            assert out.get("t") == m.get("t")
        else:
            assert out["x"] == bool(m["x"]) and out["aj"] == bool(m["aj"])
            assert out["lj"] == m["lj"] and out["i"] == m["i"]
            assert out["resp"] == m["resp"]


@pytest.fixture()
def native():
    n = wire._load_native()
    if n is None:
        pytest.skip("native wire codec unavailable (no g++?)")
    return n


def test_native_byte_identity(native):
    rng = random.Random(2)
    for trial in range(50):
        m = _rand_rank_msg(rng, with_cfg=trial % 5 == 0)
        assert native.encode_rank_msg(m) == wire._py_encode_rank_msg(m)
        p = _rand_resp_msg(rng, fast=trial % 3 == 0, tune=trial % 4 == 0)
        assert native.encode_resp_msg(p) == wire._py_encode_resp_msg(p)


def test_native_decode_matches_python(native):
    rng = random.Random(3)
    for trial in range(50):
        m = _rand_rank_msg(rng, with_cfg=trial % 7 == 0)
        blob = wire._py_encode_rank_msg(m)
        assert native.decode_rank_msg(blob) == wire._py_decode_rank_msg(blob)
        p = _rand_resp_msg(rng, fast=trial % 3 == 0, tune=trial % 4 == 0)
        blob = wire._py_encode_resp_msg(p)
        assert native.decode_resp_msg(blob) == wire._py_decode_resp_msg(blob)


def test_native_rejects_garbage(native):
    with pytest.raises(Exception):
        native.decode_rank_msg(b"Rxx")
    with pytest.raises(Exception):
        native.decode_resp_msg(b"")
    with pytest.raises(Exception):
        native.decode_resp_msg(b"Q\x00\x00\x00\x00\x00")


def test_wire_smaller_than_json():
    import json

    rng = random.Random(4)
    m = _rand_rank_msg(rng)
    m["req"] = m["req"] * 8
    assert len(wire.dumps_rank(m)) < len(json.dumps(m))


def test_corrupt_counts_fail_cleanly(native):
    # a u32 count field of 0xFFFFFFFF must raise, not allocate ~34GB
    for blob in (b"R\x00" + b"\xff\xff\xff\xff",
                 b"P\x00\xff\xff\xff\xff" + b"\xff\xff\xff\xff"):
        with pytest.raises(Exception):
            native.decode_rank_msg(blob) if blob[0:1] == b"R" \
                else native.decode_resp_msg(blob)


def test_native_u32_list_overflow_raises(native):
    # values/lengths that don't fit u32 must raise like the Python
    # codec's struct.pack does — not silently truncate on the wire
    # (ADVICE r1: unchecked (uint32_t) casts in put_u32_list)
    base = {"b": [], "i": [], "j": False, "x": False, "req": []}
    with pytest.raises(OverflowError):
        native.encode_rank_msg({**base, "b": [1 << 33]})
    with pytest.raises(OverflowError):
        native.encode_rank_msg({**base, "i": [-1]})
    with pytest.raises(Exception):  # Python codec agrees (struct.error)
        wire._py_encode_rank_msg({**base, "b": [1 << 33]})


def test_python_codec_raises_valueerror_on_truncation():
    with pytest.raises(ValueError):
        wire._py_decode_rank_msg(b"R\x00\xff")
    with pytest.raises(ValueError):
        wire._py_decode_resp_msg(b"P\x00")


def test_python_codec_corrupt_record_header_raises_valueerror():
    # truncation inside a request record header and a bad kind code
    # must raise ValueError, matching the native codec
    good = wire._py_encode_rank_msg(
        {"b": [], "i": [], "j": False, "x": False,
         "req": [{"n": "t", "k": "allreduce", "o": 2, "d": 8,
                  "s": [4], "r": -1}]})
    with pytest.raises(ValueError):
        wire._py_decode_rank_msg(good[:15])        # header truncated
    bad_kind = bytearray(good)
    bad_kind[14] = 99                              # kind byte
    with pytest.raises(ValueError):
        wire._py_decode_rank_msg(bytes(bad_kind))
