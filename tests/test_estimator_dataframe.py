"""Estimator DataFrame ingestion (reference
``horovod/spark/common/util.py:360-608``: ``prepare_data`` materializes
a DataFrame's feature/label columns into the Store; estimators train
from the materialized shards)."""
import numpy as np
import pytest

pd = pytest.importorskip("pandas")

from horovod_tpu.estimator.dataframe import (assemble_columns,  # noqa: E402
                                             materialize_dataframe)
from horovod_tpu.estimator.store import LocalStore  # noqa: E402


def _df(n=12):
    rng = np.random.RandomState(0)
    return pd.DataFrame({
        "f1": rng.rand(n).astype(np.float32),
        "f2": rng.rand(n).astype(np.float32),
        "label": rng.randint(0, 3, n),
        "img": [rng.rand(4, 4).astype(np.float32) for _ in range(n)],
    })


def test_assemble_scalar_columns_stack():
    df = _df()
    x = assemble_columns(df, ["f1", "f2"])
    assert x.shape == (12, 2)
    assert np.allclose(x[:, 0], df["f1"].to_numpy())


def test_assemble_tensor_column_keeps_shape():
    df = _df()
    x = assemble_columns(df, ["img"])
    assert x.shape == (12, 4, 4)


def test_tensor_column_must_stand_alone():
    df = _df()
    with pytest.raises(ValueError, match="tensor column"):
        assemble_columns(df, ["img", "f1"])


def test_missing_column_named():
    with pytest.raises(KeyError, match="nope"):
        assemble_columns(_df(), ["nope"])


def test_ragged_cells_rejected():
    df = pd.DataFrame({"r": [np.zeros(2), np.zeros(3)], "y": [0, 1]})
    with pytest.raises(ValueError, match="ragged"):
        assemble_columns(df, ["r"])


def test_materialize_shards_and_metadata(tmp_path):
    store = LocalStore(str(tmp_path))
    path = store.get_train_data_path("run1")
    meta = materialize_dataframe(store, path, _df(), ["f1", "f2"],
                                 ["label"], num_proc=3)
    assert meta["train_rows"] == 12
    assert meta["avg_row_size"] > 0
    assert set(meta["schema"]) == {"f1", "f2", "label"}
    # every rank's shard exists and the union is the full dataset
    total = 0
    for r in range(3):
        with np.load(f"{path}/part.{r}.npz") as z:
            assert z["x"].shape[1] == 2
            assert len(z["x"]) == len(z["y"])
            total += len(z["x"])
    assert total == 12


class _MeteredStore(LocalStore):
    """Records the largest single blob written — the observable proxy
    for driver-side peak memory during streaming ingest."""

    def __init__(self, prefix):
        super().__init__(prefix)
        self.max_blob = 0
        self.writes = 0

    def write_bytes(self, path, data):
        self.max_blob = max(self.max_blob, len(data))
        self.writes += 1
        super().write_bytes(path, data)


def test_chunked_ingest_bounded_and_exact(tmp_path):
    """Streaming ingest (VERDICT r4 #4): a frame much larger than one
    chunk materializes in bounded pieces — every blob is a small
    fraction of the dataset — and the rank-side reader reassembles
    exactly the rows the one-shot path would deliver (same striping
    within each chunk, no shuffle)."""
    from horovod_tpu.estimator.estimator import _load_shard

    n, num_proc, rows_per_chunk = 4096, 2, 256
    rng = np.random.RandomState(3)
    df = pd.DataFrame({
        "f1": rng.rand(n).astype(np.float32),
        "f2": rng.rand(n).astype(np.float32),
        "label": rng.randint(0, 5, n),
    })
    store = _MeteredStore(str(tmp_path))
    path = store.get_train_data_path("chunked")
    meta = materialize_dataframe(store, path, df, ["f1", "f2"],
                                 ["label"], num_proc,
                                 rows_per_chunk=rows_per_chunk)
    assert meta["train_rows"] == n
    # memory cap: no single write held more than ~one chunk's bytes
    full_bytes = n * 2 * 4 + n * 8
    assert store.max_blob < full_bytes / (n // rows_per_chunk - 1)
    assert store.writes >= (n // rows_per_chunk) * num_proc

    # exactness: chunked reassembly == per-chunk striping of the frame
    for r in range(num_proc):
        x_r, y_r = _load_shard(store, path, r)
        exp_x, exp_y = [], []
        for lo in range(0, n, rows_per_chunk):
            cdf = df.iloc[lo:lo + rows_per_chunk]
            exp_x.append(np.stack([cdf["f1"].to_numpy(),
                                   cdf["f2"].to_numpy()], 1)[r::num_proc])
            exp_y.append(cdf["label"].to_numpy()[r::num_proc])
        np.testing.assert_array_equal(x_r, np.concatenate(exp_x))
        np.testing.assert_array_equal(y_r, np.concatenate(exp_y))


def test_chunked_ingest_trains_end_to_end(tmp_path):
    """fit(df) with rows_per_chunk: 2-proc training reads the chunked
    layout through the manifest."""
    import flax.linen as nn

    from horovod_tpu.estimator import JaxEstimator

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(3)(x)

    n = 120
    rng = np.random.RandomState(4)
    df = pd.DataFrame({
        "f1": rng.rand(n).astype(np.float32),
        "f2": rng.rand(n).astype(np.float32),
        "label": rng.randint(0, 3, n),
    })
    store = LocalStore(str(tmp_path))
    est = JaxEstimator(model=MLP(), store=store, num_proc=2,
                       batch_size=16, epochs=1, lr=1e-2,
                       feature_cols=["f1", "f2"], label_cols=["label"],
                       rows_per_chunk=32, run_id="chunkrun")
    model = est.fit(df)
    assert est.data_meta_["train_rows"] == n
    preds = model.predict(np.stack([df["f1"], df["f2"]], 1))
    assert preds.shape == (n, 3)
    assert np.isfinite(model.history).all()


def test_chunk_smaller_than_ranks_rejected(tmp_path):
    store = LocalStore(str(tmp_path))
    with pytest.raises(ValueError, match="rows_per_chunk"):
        materialize_dataframe(store, store.get_train_data_path("r"),
                              _df(), ["f1"], ["label"], num_proc=4,
                              rows_per_chunk=2)


def test_empty_dataframe_rejected(tmp_path):
    store = LocalStore(str(tmp_path))
    with pytest.raises(ValueError, match="no rows"):
        materialize_dataframe(store, store.get_train_data_path("r"),
                              _df(0), ["f1"], ["label"], num_proc=2)


def test_keras_adapter_maps_reference_spellings(tmp_path):
    """spark.keras.KerasEstimator is a real adapter (VERDICT r3 flagged
    the old pure-alias): Keras loss names map, Petastorm-only params
    raise instead of silently no-oping."""
    flax = pytest.importorskip("flax")
    import flax.linen as nn

    from horovod_tpu.spark.keras import KerasEstimator

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(3)(x)

    est = KerasEstimator(model=Tiny(),
                         loss="sparse_categorical_crossentropy",
                         optimizer="sgd", store=str(tmp_path),
                         feature_cols=["a"], label_cols=["y"])
    assert est.loss == "softmax_cross_entropy"
    assert est.optimizer == "sgd"
    assert est.feature_cols == ["a"]
    with pytest.raises(NotImplementedError, match="sample_weight_col"):
        KerasEstimator(model=Tiny(), store=str(tmp_path),
                       sample_weight_col="w")
    with pytest.raises(ValueError, match="unsupported loss"):
        KerasEstimator(model=Tiny(), store=str(tmp_path), loss="huber")
    with pytest.raises(ValueError, match="optimizer"):
        KerasEstimator(model=Tiny(), store=str(tmp_path),
                       optimizer="rmsprop")


def test_torch_adapter_maps_reference_spellings(tmp_path):
    torch = pytest.importorskip("torch")

    from horovod_tpu.spark.torch import TorchEstimator

    model = torch.nn.Linear(2, 3)
    est = TorchEstimator(model=model, loss=torch.nn.functional.mse_loss,
                         optimizer="adamw", store=str(tmp_path),
                         feature_cols=["a", "b"], label_cols=["y"])
    assert est.loss_fn is torch.nn.functional.mse_loss
    assert est.optimizer == "adamw"
    with pytest.raises(NotImplementedError, match="transformation_fn"):
        TorchEstimator(model=model, store=str(tmp_path),
                       transformation_fn=lambda r: r)


def test_fit_df_without_columns_raises(tmp_path):
    flax = pytest.importorskip("flax")
    import flax.linen as nn

    from horovod_tpu.estimator import JaxEstimator

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(3)(x)

    est = JaxEstimator(model=Tiny(), store=str(tmp_path))
    with pytest.raises(ValueError, match="feature_cols"):
        est.fit(_df())


@pytest.mark.multiprocess
@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_torch_estimator_fit_dataframe(tmp_path):
    """spark.torch.TorchEstimator.fit(df): materialize + train through
    the torch frontend (reference TorchEstimator.fit(df))."""
    torch = pytest.importorskip("torch")

    from horovod_tpu.spark.torch import TorchEstimator

    rng = np.random.RandomState(2)
    df = pd.DataFrame({
        "a": rng.rand(16).astype(np.float32),
        "b": rng.rand(16).astype(np.float32),
        "y": rng.randint(0, 3, 16),
    })
    est = TorchEstimator(model=torch.nn.Linear(2, 3), optimizer="sgd",
                         store=str(tmp_path), num_proc=2, epochs=1,
                         batch_size=4, feature_cols=["a", "b"],
                         label_cols=["y"])
    trained = est.fit(df)
    assert len(trained.history) == 1 and np.isfinite(trained.history[0])
    preds = trained.predict(np.stack([df["a"], df["b"]], axis=1))
    assert preds.shape == (16, 3)


@pytest.mark.multiprocess
def test_jax_estimator_fit_dataframe(tmp_path):
    """End-to-end: fit(df) materializes shards into the Store and
    trains through the launcher (reference KerasEstimator.fit(df))."""
    flax = pytest.importorskip("flax")
    import flax.linen as nn

    from horovod_tpu.estimator import JaxEstimator

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(3)(x)

    rng = np.random.RandomState(1)
    df = pd.DataFrame({
        "a": rng.rand(24).astype(np.float32),
        "b": rng.rand(24).astype(np.float32),
        "y": rng.randint(0, 3, 24),
    })
    est = JaxEstimator(model=Tiny(), loss="softmax_cross_entropy",
                       store=str(tmp_path), num_proc=2, epochs=1,
                       batch_size=4, feature_cols=["a", "b"],
                       label_cols=["y"])
    trained = est.fit(df)
    assert len(trained.history) == 1 and np.isfinite(trained.history[0])
    preds = trained.predict(np.stack([df["a"], df["b"]], axis=1))
    assert preds.shape == (24, 3)
