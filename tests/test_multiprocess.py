"""Multi-process distributed correctness tests.

The TPU analog of the reference CI's ``horovodrun -np 2 pytest``
(``.buildkite/gen-pipeline.sh:210``): spawn 2 real processes on
localhost, each running the same assertions against the public API,
wired through jax.distributed + the KV negotiation controller.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_ranks(body: str, np_: int = 2, timeout: int = 240):
    """Run ``body`` (python source; sees hvd/jnp/np/rank/size) on np_
    local processes; returns per-rank stdout."""
    script = textwrap.dedent("""
        import os, sys
        import numpy as np
        import jax.numpy as jnp
        import horovod_tpu as hvd
        hvd.init()
        rank, size = hvd.rank(), hvd.size()
    """) + textwrap.dedent(body) + textwrap.dedent("""
        hvd.shutdown()
        print("RANK-%d-DONE" % rank, flush=True)
    """)
    port = _free_port()
    procs = []
    for r in range(np_):
        env = dict(os.environ)
        env.update({
            "HOROVOD_PLATFORM": "cpu",
            "HOROVOD_RANK": str(r),
            "HOROVOD_SIZE": str(np_),
            "HOROVOD_LOCAL_RANK": str(r),
            "HOROVOD_LOCAL_SIZE": str(np_),
            "HOROVOD_COORDINATOR_ADDR": f"localhost:{port}",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for r, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError(f"rank {r} timed out; output so far unknown")
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"RANK-{r}-DONE" in out, f"rank {r} incomplete:\n{out}"
    return outs


pytestmark = pytest.mark.multiprocess


def test_allreduce_allgather_broadcast_2proc():
    run_ranks("""
        out = hvd.allreduce(jnp.full((4,), float(rank + 1)), op=hvd.Sum)
        assert np.allclose(np.asarray(out), 3.0), out
        avg = hvd.allreduce(jnp.full((4,), float(rank)), op=hvd.Average)
        assert np.allclose(np.asarray(avg), 0.5), avg
        # out-of-order async submission (negotiation must reorder)
        if rank == 0:
            ha = hvd.allreduce_async(jnp.ones(8), op=hvd.Sum, name="a")
            hb = hvd.allreduce_async(jnp.ones(8) * 2, op=hvd.Sum, name="b")
        else:
            hb = hvd.allreduce_async(jnp.ones(8) * 2, op=hvd.Sum, name="b")
            ha = hvd.allreduce_async(jnp.ones(8), op=hvd.Sum, name="a")
        assert np.allclose(np.asarray(hvd.synchronize(ha)), 2.0)
        assert np.allclose(np.asarray(hvd.synchronize(hb)), 4.0)
        # ragged allgather
        g = hvd.allgather(jnp.full((rank + 1, 3), float(rank)))
        assert g.shape == (3, 3), g.shape
        assert np.allclose(np.asarray(g)[0], 0.0)
        assert np.allclose(np.asarray(g)[1:], 1.0)
        # broadcast from rank 1
        b = hvd.broadcast(jnp.full((5,), float(rank * 10)), root_rank=1)
        assert np.allclose(np.asarray(b), 10.0), b
        # object broadcast
        obj = hvd.broadcast_object({"x": 42} if rank == 0 else None, 0)
        assert obj["x"] == 42
    """)


def test_training_with_distributed_optimizer_2proc():
    run_ranks("""
        import jax, optax
        params = {"w": jnp.full((4,), float(rank + 1))}
        params = hvd.broadcast_parameters(params, root_rank=0)
        assert np.allclose(np.asarray(params["w"]), 1.0)
        opt = hvd.DistributedOptimizer(optax.sgd(0.1), op=hvd.Average)
        state = opt.init(params)
        # rank-dependent loss: grad_r = 2*(w - r); mean grad = 2*(w - 0.5)
        def loss(p):
            return jnp.sum((p["w"] - rank) ** 2)
        g = jax.grad(loss)(params)
        updates, state = opt.update(g, state, params)
        params = optax.apply_updates(params, updates)
        expected = 1.0 - 0.1 * 2.0 * (1.0 - 0.5)
        assert np.allclose(np.asarray(params["w"]), expected), params
    """)


def test_error_and_join_2proc():
    run_ranks("""
        # mismatched shape -> coordinator error on every rank
        try:
            hvd.allreduce(jnp.ones((4,) if rank == 0 else (5,)), name="bad")
            raise SystemExit("expected an error")
        except Exception as e:
            assert "Mismatched shapes" in str(e), e
        # runtime still usable
        ok = hvd.allreduce(jnp.ones(2), op=hvd.Sum, name="after")
        assert np.allclose(np.asarray(ok), 2.0)
        # join with uneven work
        if rank == 0:
            extra = hvd.allreduce(jnp.full((3,), 6.0), op=hvd.Sum, name="uneven")
            assert np.allclose(np.asarray(extra), 6.0), extra
        last = hvd.join()
        assert last == 0, last
    """)


def test_adasum_2proc():
    run_ranks("""
        from horovod_tpu.ops.adasum import adasum_reference
        v = np.arange(8, dtype=np.float32) + 1 + rank
        out = hvd.allreduce(jnp.asarray(v), op=hvd.Adasum, name="ada")
        ref = adasum_reference([np.arange(8, dtype=np.float32) + 1,
                                np.arange(8, dtype=np.float32) + 2])
        assert np.allclose(np.asarray(out), ref, rtol=1e-4), (out, ref)
    """)
