"""Multi-process distributed correctness tests.

The TPU analog of the reference CI's ``horovodrun -np 2 pytest``
(``.buildkite/gen-pipeline.sh:210``): spawn 2 real processes on
localhost, each running the same assertions against the public API,
wired through jax.distributed + the KV negotiation controller.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_ranks(body: str, np_: int = 2, timeout: int = 240,
              extra_env: dict | None = None, prewarm: str = ""):
    """Run ``body`` (python source; sees hvd/jnp/np/rank/size) on np_
    local processes; returns per-rank stdout.

    ``prewarm``: import statements executed BEFORE ``hvd.init()``.
    Heavy native imports (tensorflow ~12 s, torch ~5 s on the 1-core
    CI image) hold the GIL long enough to starve the background
    heartbeat publisher past its 20 s deadline when they run after
    init — pre-warming moves that stall before liveness tracking
    starts, so the frontend 2-proc tests stop flaking on false
    dead-peer aborts."""
    script = textwrap.dedent("""
        import os, sys
        import numpy as np
        import jax.numpy as jnp
    """) + (textwrap.dedent(prewarm) + "\n" if prewarm else "") + \
        textwrap.dedent("""
        import horovod_tpu as hvd
        hvd.init()
        rank, size = hvd.rank(), hvd.size()
    """) + textwrap.dedent(body) + textwrap.dedent("""
        hvd.shutdown()
        print("RANK-%d-DONE" % rank, flush=True)
    """)
    port = _free_port()
    procs = []
    for r in range(np_):
        env = dict(os.environ)
        env.update({
            "HOROVOD_PLATFORM": "cpu",
            "HOROVOD_RANK": str(r),
            "HOROVOD_SIZE": str(np_),
            "HOROVOD_LOCAL_RANK": str(r),
            "HOROVOD_LOCAL_SIZE": str(np_),
            "HOROVOD_COORDINATOR_ADDR": f"localhost:{port}",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        if extra_env:
            env.update(extra_env)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for r, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError(f"rank {r} timed out; output so far unknown")
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"RANK-{r}-DONE" in out, f"rank {r} incomplete:\n{out}"
    return outs


pytestmark = pytest.mark.multiprocess


def test_allreduce_allgather_broadcast_2proc():
    run_ranks("""
        out = hvd.allreduce(jnp.full((4,), float(rank + 1)), op=hvd.Sum)
        assert np.allclose(np.asarray(out), 3.0), out
        avg = hvd.allreduce(jnp.full((4,), float(rank)), op=hvd.Average)
        assert np.allclose(np.asarray(avg), 0.5), avg
        # out-of-order async submission (negotiation must reorder)
        if rank == 0:
            ha = hvd.allreduce_async(jnp.ones(8), op=hvd.Sum, name="a")
            hb = hvd.allreduce_async(jnp.ones(8) * 2, op=hvd.Sum, name="b")
        else:
            hb = hvd.allreduce_async(jnp.ones(8) * 2, op=hvd.Sum, name="b")
            ha = hvd.allreduce_async(jnp.ones(8), op=hvd.Sum, name="a")
        assert np.allclose(np.asarray(hvd.synchronize(ha)), 2.0)
        assert np.allclose(np.asarray(hvd.synchronize(hb)), 4.0)
        # ragged allgather
        g = hvd.allgather(jnp.full((rank + 1, 3), float(rank)))
        assert g.shape == (3, 3), g.shape
        assert np.allclose(np.asarray(g)[0], 0.0)
        assert np.allclose(np.asarray(g)[1:], 1.0)
        # broadcast from rank 1
        b = hvd.broadcast(jnp.full((5,), float(rank * 10)), root_rank=1)
        assert np.allclose(np.asarray(b), 10.0), b
        # object broadcast
        obj = hvd.broadcast_object({"x": 42} if rank == 0 else None, 0)
        assert obj["x"] == 42
    """)


def test_training_with_distributed_optimizer_2proc():
    run_ranks("""
        import jax, optax
        params = {"w": jnp.full((4,), float(rank + 1))}
        params = hvd.broadcast_parameters(params, root_rank=0)
        assert np.allclose(np.asarray(params["w"]), 1.0)
        opt = hvd.DistributedOptimizer(optax.sgd(0.1), op=hvd.Average)
        state = opt.init(params)
        # rank-dependent loss: grad_r = 2*(w - r); mean grad = 2*(w - 0.5)
        def loss(p):
            return jnp.sum((p["w"] - rank) ** 2)
        g = jax.grad(loss)(params)
        updates, state = opt.update(g, state, params)
        params = optax.apply_updates(params, updates)
        expected = 1.0 - 0.1 * 2.0 * (1.0 - 0.5)
        assert np.allclose(np.asarray(params["w"]), expected), params
    """)


def test_error_and_join_2proc():
    run_ranks("""
        # mismatched shape -> coordinator error on every rank
        try:
            hvd.allreduce(jnp.ones((4,) if rank == 0 else (5,)), name="bad")
            raise SystemExit("expected an error")
        except Exception as e:
            assert "Mismatched shapes" in str(e), e
        # runtime still usable
        ok = hvd.allreduce(jnp.ones(2), op=hvd.Sum, name="after")
        assert np.allclose(np.asarray(ok), 2.0)
        # join with uneven work
        if rank == 0:
            extra = hvd.allreduce(jnp.full((3,), 6.0), op=hvd.Sum, name="uneven")
            assert np.allclose(np.asarray(extra), 6.0), extra
        last = hvd.join()
        assert last == 0, last
    """)


def test_adasum_2proc():
    run_ranks("""
        from horovod_tpu.ops.adasum import adasum_reference
        v = np.arange(8, dtype=np.float32) + 1 + rank
        out = hvd.allreduce(jnp.asarray(v), op=hvd.Adasum, name="ada")
        ref = adasum_reference([np.arange(8, dtype=np.float32) + 1,
                                np.arange(8, dtype=np.float32) + 2])
        assert np.allclose(np.asarray(out), ref, rtol=1e-4), (out, ref)
    """)


def test_autotune_param_sync_2proc():
    """Rank 0's autotune proposals must reach every rank through the
    response payload (reference SynchronizeParameters semantics)."""
    import os

    prev = {k: os.environ.get(k) for k in (
        "HOROVOD_AUTOTUNE", "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE",
        "HOROVOD_AUTOTUNE_WARMUP_SAMPLES",
        "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES")}
    os.environ.update({
        "HOROVOD_AUTOTUNE": "1",
        "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": "1",
        "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "0",
        "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES": "3",
    })
    try:
        run_ranks("""
            from horovod_tpu.common import config as _config
            start = (_config.get("fusion_threshold"),
                     _config.get("cycle_time_ms"))
            changed = False
            # Every rank submits the SAME fixed collective set (SPMD):
            # breaking out early on first observed change would shut
            # down while peers still have pending tensors.
            for i in range(60):
                out = hvd.allreduce(jnp.ones(1024), op=hvd.Sum, name="t%d" % i)
                assert np.allclose(np.asarray(out), 2.0)
                now = (_config.get("fusion_threshold"),
                       _config.get("cycle_time_ms"))
                changed = changed or now != start
            assert changed, "autotune update never reached rank %d" % rank
        """)
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_keras_callbacks_2proc():
    """MetricAverageCallback averages across ranks; warmup LR stays
    rank-identical; broadcast callback syncs rank-divergent params."""
    run_ranks("""
        import optax
        from horovod_tpu.keras import (BroadcastGlobalVariablesCallback,
                                       CallbackList,
                                       LearningRateWarmupCallback,
                                       MetricAverageCallback, TrainingState,
                                       find_hyperparams)
        opt = hvd.DistributedOptimizer(
            optax.inject_hyperparams(optax.sgd)(learning_rate=0.2,
                                                momentum=0.9))
        params = {"w": jnp.full((4,), float(rank + 7))}
        state = TrainingState(params, opt.init(params))
        cbs = CallbackList([BroadcastGlobalVariablesCallback(0),
                            MetricAverageCallback(),
                            LearningRateWarmupCallback(warmup_epochs=2,
                                                       steps_per_epoch=3)],
                           state)
        cbs.on_train_begin()
        for epoch in range(2):
            cbs.on_epoch_begin(epoch)
            for b in range(3):
                cbs.on_batch_begin(b)
                cbs.on_batch_end(b)
            logs = {"loss": float(rank)}
            cbs.on_epoch_end(epoch, logs)
            assert abs(logs["loss"] - 0.5) < 1e-6, logs
        # broadcast happened once: both ranks hold rank-0's init
        assert np.allclose(np.asarray(state.params["w"]), 7.0)
        hp = find_hyperparams(state.opt_state)
        lr = float(np.asarray(hp["learning_rate"]))
        gathered = hvd.allgather(jnp.asarray([lr]))
        arr = np.asarray(gathered)
        assert np.allclose(arr, arr[0]), arr  # identical LR on all ranks
        assert abs(lr - 0.2) < 1e-6, lr       # warmup finished at full LR
    """)


def test_collectives_4proc():
    """World size beyond 2 — the negotiation/fusion/hierarchy logic
    must generalize (4 ranks: even-sized ring, power-of-2 Adasum)."""
    run_ranks("""
        out = hvd.allreduce(jnp.full((3,), float(rank + 1)), op=hvd.Sum)
        assert np.allclose(np.asarray(out), 10.0), out   # 1+2+3+4
        avg = hvd.allreduce(jnp.full((3,), float(rank)), op=hvd.Average)
        assert np.allclose(np.asarray(avg), 1.5), avg
        g = hvd.allgather(jnp.full((rank + 1, 2), float(rank)))
        assert g.shape == (10, 2), g.shape   # 1+2+3+4 rows
        b = hvd.broadcast(jnp.full((2,), float(rank)), root_rank=3)
        assert np.allclose(np.asarray(b), 3.0), b
        ad = hvd.allreduce(jnp.full((4,), 2.0), op=hvd.Adasum)
        assert np.isfinite(np.asarray(ad)).all()
        last = hvd.join()
        assert last in range(4)
    """, np_=4, timeout=360)
