"""Model-family smoke tests: the reference's benchmark trio
(ResNet / Inception V3 / VGG-16, ``docs/benchmarks.rst:11-13``) must
init, run forward in train+eval mode, and produce finite logits/grads
at small input sizes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models.inception import InceptionV3
from horovod_tpu.models.resnet import ResNet18, ResNet50
from horovod_tpu.models.vgg import VGG11, VGG16


@pytest.mark.parametrize("model_cls,size", [
    (ResNet18, 64), (ResNet50, 64), (VGG11, 64),
    # ~30 s of cold compile on the 1-core image: tier-1 keeps the
    # three cheap architectures, the full ci.sh suite runs all four
    pytest.param(InceptionV3, 96, marks=pytest.mark.slow),
])
def test_forward_shapes_and_finite(model_cls, size):
    model = model_cls(num_classes=10, dtype=jnp.float32)
    rng = {"params": jax.random.PRNGKey(0),
           "dropout": jax.random.PRNGKey(1)}
    x = jnp.asarray(np.random.RandomState(0).rand(2, size, size, 3),
                    jnp.float32)
    variables = model.init(rng, x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow  # ~18 s cold compile; full ci.sh suite covers it
def test_train_mode_grads_resnet():
    model = ResNet18(num_classes=5, dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(0).rand(2, 32, 32, 3),
                    jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    params, stats = variables["params"], variables["batch_stats"]

    def loss_fn(p):
        logits, mut = model.apply({"params": p, "batch_stats": stats},
                                  x, train=True,
                                  mutable=["batch_stats"])
        return jnp.mean(logits ** 2)

    g = jax.grad(loss_fn)(params)
    flat = jax.tree_util.tree_leaves(g)
    assert all(np.isfinite(np.asarray(leaf)).all() for leaf in flat)
    assert any(float(jnp.abs(leaf).sum()) > 0 for leaf in flat)


def test_vgg16_param_count():
    # the reference cites VGG-16's 138M dense params as the allreduce
    # stress case; make sure we actually built that model
    model = VGG16(num_classes=1000, dtype=jnp.float32)
    x = jnp.zeros((1, 224, 224, 3), jnp.float32)
    variables = model.init({"params": jax.random.PRNGKey(0)}, x,
                           train=False)
    n = sum(int(np.prod(p.shape))
            for p in jax.tree_util.tree_leaves(variables["params"]))
    assert 130e6 < n < 145e6, n


def test_inception_v3_canonical_topology():
    # pin the Szegedy table-1 topology: ~23.8M params at 1000 classes
    # (a dropped ReductionB or thinned MixedC shifts this far outside
    # the band), logits shape, and the 2048-wide pre-pool filter bank
    # via the final-Dense kernel fan-in.
    model = InceptionV3(num_classes=1000, dtype=jnp.float32)
    x = jnp.zeros((1, 299, 299, 3), jnp.float32)
    variables = jax.eval_shape(
        lambda: model.init({"params": jax.random.PRNGKey(0)}, x,
                           train=False))
    params = variables["params"]
    n = sum(int(np.prod(p.shape))
            for p in jax.tree_util.tree_leaves(params))
    assert 23e6 < n < 25e6, n
    dense = [v for k, v in params.items() if k.startswith("Dense")]
    assert dense and dense[0]["kernel"].shape == (2048, 1000)
    out = jax.eval_shape(lambda v: model.apply(v, x, train=False),
                         variables)
    assert out.shape == (1, 1000)
