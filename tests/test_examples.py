"""Examples-as-tests — the reference CI runs ``examples/*_mnist.py``
under both controllers as integration smoke tests
(``.buildkite/gen-pipeline.sh:138-227``); same idea here via the real
launcher."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.multiprocess


def run_example(script: str, *args: str, np_: int = 2,
                timeout: int = 300) -> str:
    env = dict(os.environ)
    env.update({"HOROVOD_PLATFORM": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", "")})
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", str(np_),
         sys.executable, os.path.join(REPO, "examples", script), *args],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    return proc.stdout


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_jax_mnist_example():
    out = run_example("jax_mnist.py", "--epochs", "1", "--steps", "3")
    assert "mean loss across ranks" in out


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_pytorch_mnist_example():
    out = run_example("pytorch_mnist.py", "--epochs", "1", "--steps", "3")
    assert "mean loss across ranks" in out


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_tensorflow2_mnist_example():
    pytest.importorskip("tensorflow")
    out = run_example("tensorflow2_mnist.py", "--epochs", "1",
                      "--steps", "3", timeout=420)
    assert "mean loss across ranks" in out


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_tensorflow2_keras_mnist_example():
    pytest.importorskip("tensorflow")
    out = run_example("tensorflow2_keras_mnist.py", "--epochs", "1",
                      "--samples", "128", timeout=420)
    assert "mean loss across ranks" in out


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_pytorch_synthetic_benchmark_example():
    out = run_example("pytorch_synthetic_benchmark.py",
                      "--batch-size", "2", "--num-iters", "1",
                      "--num-batches-per-iter", "1")
    assert "Total img/sec" in out


@pytest.mark.slow
def test_jax_synthetic_benchmark_example():
    """The flagship bench workload itself (VERDICT r2 weak #7: never
    executed as a script)."""
    out = run_example("jax_synthetic_benchmark.py",
                      "--model", "SmallCNN", "--batch-size", "1",
                      "--num-iters", "1", "--num-batches-per-iter", "1",
                      "--num-warmup-batches", "1", timeout=600)
    assert "Total img/sec" in out


@pytest.mark.slow
def test_transformer_lm_example():
    """Flagship 4D-parallel demo (dp*tp*sp over an 8-device CPU mesh),
    single process — the shape the driver's dryrun_multichip checks."""
    env = dict(os.environ)
    env.update({
        "HOROVOD_PLATFORM": "cpu",
        "HOROVOD_SIZE": "1",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples",
                                      "transformer_lm.py"),
         "--dp", "2", "--tp", "2", "--sp", "2", "--steps", "2",
         "--d-model", "32", "--seq", "16", "--batch", "4",
         "--n-layers", "1"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "loss" in proc.stdout.lower()


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_tensorflow2_synthetic_benchmark_example():
    """The reference's headline bench workload, on the real TF frontend
    (DistributedGradientTape over the negotiated wire)."""
    pytest.importorskip("tensorflow")
    out = run_example("tensorflow2_synthetic_benchmark.py",
                      "--model", "SmallCNN", "--batch-size", "2",
                      "--num-iters", "1", "--num-batches-per-iter", "1",
                      "--num-warmup-batches", "1", timeout=420)
    assert "Total img/sec" in out


@pytest.mark.slow
def test_jax_imagenet_resnet50_example(tmp_path):
    """The canonical real-training-job example: Goyal LR schedule,
    metrics averaging, per-epoch checkpoint + resume."""
    ckpt_dir = str(tmp_path / "ckpts")
    # ResNet18: the glue under test (Goyal LR, metric averaging,
    # checkpoint+resume) is model-independent, and tracing ResNet-50's
    # flax graph 4x (2 invocations x 2 ranks) dominated the suite's
    # wall time (~280 s of the 23 min)
    args = ["--synthetic", "--epochs", "1", "--steps-per-epoch", "2",
            "--batch-size", "2", "--val-batch-size", "2",
            "--image-size", "32", "--num-classes", "10",
            "--model", "ResNet18", "--checkpoint-dir", ckpt_dir]
    out = run_example("jax_imagenet_resnet50.py", *args, timeout=420)
    assert "epoch 0" in out and "done" in out
    # resume: second invocation continues from epoch 1
    resume_args = list(args)
    resume_args[2] = "2"  # --epochs 2
    out = run_example("jax_imagenet_resnet50.py", *resume_args,
                      timeout=420)
    assert "resumed from epoch 1" in out


@pytest.mark.slow  # tier-1 runtime trim: heaviest cold-compile/subprocess tests;
# ci.sh's full (unfiltered) suite still runs them
def test_estimator_dataframe_example(tmp_path):
    """Estimator-on-DataFrame example (reference Spark-estimator example
    shape): runs directly, not through hvdrun — fit() launches its own
    ranks via run-function mode."""
    env = dict(os.environ)
    env.update({"HOROVOD_PLATFORM": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH",
                                                          "")})
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples",
                                      "estimator_dataframe.py"),
         "--num-proc", "2", "--epochs", "10",
         "--store", str(tmp_path / "store")],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "train accuracy" in proc.stdout
