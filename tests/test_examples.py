"""Examples-as-tests — the reference CI runs ``examples/*_mnist.py``
under both controllers as integration smoke tests
(``.buildkite/gen-pipeline.sh:138-227``); same idea here via the real
launcher."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.multiprocess


def run_example(script: str, *args: str, np_: int = 2,
                timeout: int = 300) -> str:
    env = dict(os.environ)
    env.update({"HOROVOD_PLATFORM": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", "")})
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", str(np_),
         sys.executable, os.path.join(REPO, "examples", script), *args],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    return proc.stdout


def test_jax_mnist_example():
    out = run_example("jax_mnist.py", "--epochs", "1", "--steps", "3")
    assert "mean loss across ranks" in out


def test_pytorch_mnist_example():
    out = run_example("pytorch_mnist.py", "--epochs", "1", "--steps", "3")
    assert "mean loss across ranks" in out


def test_pytorch_synthetic_benchmark_example():
    out = run_example("pytorch_synthetic_benchmark.py",
                      "--batch-size", "2", "--num-iters", "1",
                      "--num-batches-per-iter", "1")
    assert "Total img/sec" in out
