"""Pallas-fused optimizer tail (HOROVOD_FUSED_UPDATE; docs/zero.md).

Acceptance matrix of the cold-path-speed PR: the fused tail must be
**bit-exact** against the unfused optax chain on dyadic data for every
dtype-group x optimizer x zero_stage cell (int8 error feedback on and
off), and the jnp fallback must match Pallas interpret mode
bit-for-bit.  Plus the fail-open contract: untagged optimizers,
schedules and unrecognized state layouts run the unfused chain with
one warning — the knob can never change results.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.optim import distributed as D
from horovod_tpu.optim import fused_update as F

N = 8

# Dyadic hyperparameters: every scale factor is a power of two, so the
# dyadic-data trajectories below are exact and parity can demand bit
# equality (the same trick as tests/test_zero23.py).
_LR, _MOM, _B1, _B2, _EPS = 0.5, 0.5, 0.5, 0.25, 2.0 ** -10

KINDS = ("sgd", "momentum", "adam")


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:N]), ("hvd",))


def _mk(kind: str):
    if kind == "sgd":
        return F.sgd(_LR)
    if kind == "momentum":
        return F.sgd(_LR, momentum=_MOM)
    return F.adam(_LR, b1=_B1, b2=_B2, eps=_EPS)


def _params(dtype=jnp.float32):
    # 21 + 9 = 30 elements: NOT divisible by 8 — exercises the pad path
    return {"w": jnp.arange(-10.0, 11.0, dtype=jnp.float32).astype(dtype),
            "b": jnp.ones((3, 3), dtype)}


def _run_steps(opt, params, t, steps=3):
    p = dict(params)
    state = opt.init(p)
    for _ in range(steps):
        g = jax.tree_util.tree_map(
            lambda x: (2.0 * (x.astype(jnp.float32) - t)).astype(x.dtype),
            p)
        upd, state = opt.update(g, state, p)
        p = optax.apply_updates(p, upd)
    return p


def _run_zero3_steps(opt, params, t, steps=3):
    zp = D.zero3_shard_params(params)
    state = opt.init(zp)
    keys = sorted(params)
    for _ in range(steps):
        def loss(z):
            full = D.zero3_full_params(z)
            return sum((i + 1.0) * (t - 3.0)
                       * jnp.sum(full[k].astype(jnp.float32))
                       for i, k in enumerate(keys))

        g = jax.grad(loss)(zp)
        upd, state = opt.update(g, state, zp)
        zp = optax.apply_updates(zp, upd)
    return D.zero3_full_params(zp)


def _pair(kind, stage, monkeypatch, dtype=jnp.float32,
          compression=None):
    """(fused optimizer, unfused optimizer) — SAME tagged transform,
    only the knob differs, so any trajectory divergence is the fused
    kernel's fault."""
    monkeypatch.setenv("HOROVOD_FUSED_UPDATE", "1")
    fo = hvd.DistributedOptimizer(_mk(kind), axis_name="hvd",
                                  zero_stage=stage,
                                  compression=compression)
    monkeypatch.setenv("HOROVOD_FUSED_UPDATE", "0")
    uo = hvd.DistributedOptimizer(_mk(kind), axis_name="hvd",
                                  zero_stage=stage,
                                  compression=compression)
    return fo, uo


def _assert_parity(mesh, fo, uo, dtype=jnp.float32, stage=0):
    params = _params(dtype)

    def body(t):
        if stage >= 3:
            pf = _run_zero3_steps(fo, params, t[0, 0])
            pu = _run_zero3_steps(uo, params, t[0, 0])
        else:
            pf = _run_steps(fo, params, t[0, 0])
            pu = _run_steps(uo, params, t[0, 0])
        return (pf["w"].reshape(1, -1), pu["w"].reshape(1, -1),
                pf["b"].reshape(1, -1), pu["b"].reshape(1, -1))

    outs = jax.jit(shard_map(body, mesh=mesh, check_vma=False,
                             in_specs=P("hvd"),
                             out_specs=(P("hvd"),) * 4))(
        jnp.arange(N, dtype=jnp.float32).reshape(N, 1))
    fw, uw, fb, ub = [np.asarray(o, np.float32) for o in outs]
    np.testing.assert_array_equal(fw, uw)
    np.testing.assert_array_equal(fb, ub)


# ---------------------------------------------------------------------------
# The parity matrix.  fp32 cells are the fast core; bf16 and int8-EF
# complete the acceptance grid (slow: each cell compiles two shard_map
# programs on the 1-core CI image).
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
@pytest.mark.parametrize("kind", KINDS)
def test_parity_fp32(mesh, monkeypatch, kind, stage):
    fo, uo = _pair(kind, stage, monkeypatch)
    _assert_parity(mesh, fo, uo, stage=stage)


@pytest.mark.slow
@pytest.mark.parametrize("stage", [0, 1, 2, 3])
@pytest.mark.parametrize("kind", KINDS)
def test_parity_bf16(mesh, monkeypatch, kind, stage):
    fo, uo = _pair(kind, stage, monkeypatch, dtype=jnp.bfloat16)
    _assert_parity(mesh, fo, uo, dtype=jnp.bfloat16, stage=stage)


@pytest.mark.slow
@pytest.mark.parametrize("stage", [0, 1, 2, 3])
@pytest.mark.parametrize("kind", KINDS)
def test_parity_int8_ef(mesh, monkeypatch, kind, stage):
    """int8 wire (EF carried in the optimizer state at stages 0-2; the
    stage-3 scatter quantizes without EF): the wire is identical on
    both sides, so the trajectories must still agree bit-for-bit."""
    fo, uo = _pair(kind, stage, monkeypatch,
                   compression=hvd.Compression.int8)
    _assert_parity(mesh, fo, uo, stage=stage)


# ---------------------------------------------------------------------------
# Kernel-vs-fallback bit identity, fail-open contract, eager regime
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
@pytest.mark.parametrize("kind", KINDS)
def test_jnp_fallback_matches_pallas_interpret(monkeypatch, kind, dtype):
    opt = _mk(kind)
    shards = [
        (jnp.arange(1000, dtype=jnp.float32) / 64.0 - 4.0).astype(dtype),
        (jnp.arange(257, dtype=jnp.float32) / 32.0).astype(dtype),
    ]
    raw = [s * 3 for s in shards]  # wire output, navg=2 below

    def run3(raw, state):
        outs = []
        for _ in range(3):
            u, state = F.fused_update_groups(
                opt.fused_spec, raw, state, 2,
                [s.dtype for s in shards])
            outs.append(u)
        return outs, state

    # Compared under jit — the production context (the update runs
    # inside the user's jitted step).  Eagerly, interpret mode compiles
    # the kernel body as ONE program while the jnp fallback dispatches
    # op by op, so LLVM's mul+add->fma contraction applies to one side
    # only (a last-ulp artifact of the comparison harness, not of the
    # kernels).
    monkeypatch.setenv("HOROVOD_QUANT_PALLAS", "1")  # interpret off-TPU
    up, sp = jax.jit(run3)(raw, opt.init(shards))
    monkeypatch.setenv("HOROVOD_QUANT_PALLAS", "0")  # jnp fallback
    uj, sj = jax.jit(run3)(raw, opt.init(shards))
    for a, b in zip(jax.tree_util.tree_leaves((up, sp)),
                    jax.tree_util.tree_leaves((uj, sj))):
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))


def test_untagged_optimizer_falls_back_with_warning(monkeypatch, caplog):
    monkeypatch.setenv("HOROVOD_FUSED_UPDATE", "1")
    F._warned.clear()
    opt = hvd.DistributedOptimizer(optax.adam(1e-2), axis_name="hvd")
    assert F._M_FUSED.value() == 0
    # and it still works (unfused chain)
    params = {"w": jnp.ones((4,), jnp.float32)}
    state = opt.init(params)
    mesh = Mesh(np.array(jax.devices()[:N]), ("hvd",))

    def body(_):
        g = {"w": jnp.ones((4,), jnp.float32)}
        upd, _ = opt.update(g, state, params)
        return upd["w"].reshape(1, -1)

    out = jax.jit(shard_map(body, mesh=mesh, check_vma=False,
                            in_specs=P("hvd"), out_specs=P("hvd")))(
        jnp.zeros((N, 1)))
    assert np.isfinite(np.asarray(out)).all()


def test_tagged_sets_gauge(monkeypatch):
    monkeypatch.setenv("HOROVOD_FUSED_UPDATE", "1")
    hvd.DistributedOptimizer(F.adam(1e-3), axis_name="hvd")
    assert F._M_FUSED.value() == 1


def test_schedules_are_rejected():
    sched = optax.exponential_decay(0.1, 10, 0.5)
    with pytest.raises(TypeError, match="schedule"):
        F.sgd(sched)
    with pytest.raises(TypeError, match="schedule"):
        F.adam(sched)


def test_unrecognized_state_falls_back(monkeypatch):
    F._warned.clear()
    spec = F.adam(1e-3).fused_spec
    shards = [jnp.arange(16.0)]
    wrong_state = (optax.EmptyState(),)  # not a ScaleByAdamState
    assert F.fused_update_groups(spec, shards, wrong_state, 1,
                                 [jnp.float32]) is None
    assert F._M_FUSED.value() == 0  # the gauge records the OUTCOME


def test_momentum_zero_is_fusable():
    """optax.sgd adds the trace transform for ANY non-None momentum —
    including 0.0 — and the spec kind must follow or fusion silently
    disables for the user who explicitly asked for it."""
    opt = F.sgd(0.5, momentum=0.0)
    assert opt.fused_spec.kind == "momentum"
    shards = [jnp.arange(32.0)]
    res = F.fused_update_groups(opt.fused_spec, shards,
                                opt.init(shards), 1, [jnp.float32])
    assert res is not None
    u_ref, _ = opt.update(shards, opt.init(shards))
    np.testing.assert_array_equal(np.asarray(res[0][0]),
                                  np.asarray(u_ref[0]))


def test_integer_group_falls_back(monkeypatch):
    """Float update math into an integer dtype group must run the
    unfused chain (fail-open), not crash the kernel or drift the
    state dtype."""
    F._warned.clear()
    opt = F.sgd(0.5, momentum=0.5)
    shards = [jnp.arange(16.0), jnp.arange(8, dtype=jnp.int32)]
    state = opt.init(shards)
    assert F.fused_update_groups(
        opt.fused_spec, shards, state, 1,
        [jnp.float32, jnp.int32]) is None
    assert F._M_FUSED.value() == 0
    grads = {"w": jnp.ones((4,)), "i": jnp.ones((4,), jnp.int32)}
    st = opt.init(grads)
    assert F.fused_update_tree(opt.fused_spec, grads, st) is None


def test_fusable_transformation_is_plain_optax_when_knob_off(
        monkeypatch):
    monkeypatch.delenv("HOROVOD_FUSED_UPDATE", raising=False)
    tagged = F.adam(1e-3)
    plain = optax.adam(1e-3)
    params = {"w": jnp.arange(8.0)}
    st_t, st_p = tagged.init(params), plain.init(params)
    g = {"w": jnp.ones((8,))}
    for _ in range(2):
        ut, st_t = tagged.update(g, st_t)
        up, st_p = plain.update(g, st_p)
        np.testing.assert_array_equal(np.asarray(ut["w"]),
                                      np.asarray(up["w"]))


def test_eager_size1_parity(hvd_single, monkeypatch):
    """Eager regime (concrete arrays, size-1 world): the fused tree
    path must walk the optax trajectory bit-for-bit."""
    monkeypatch.setenv("HOROVOD_FUSED_UPDATE", "1")
    fo = hvd.DistributedOptimizer(F.adam(1e-2), axis_name="hvd")
    monkeypatch.setenv("HOROVOD_FUSED_UPDATE", "0")
    uo = hvd.DistributedOptimizer(F.adam(1e-2), axis_name="hvd")
    pf = _run_steps(fo, _params(), 1.0)
    pu = _run_steps(uo, _params(), 1.0)
    for a, b in zip(jax.tree_util.tree_leaves(pf),
                    jax.tree_util.tree_leaves(pu)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("stage", [1, 3])
def test_sharded_eager_size1_parity(hvd_single, monkeypatch, stage):
    """Sharded eager regime at size 1 (shard == full buffer)."""
    monkeypatch.setenv("HOROVOD_FUSED_UPDATE", "1")
    fo = hvd.DistributedOptimizer(F.sgd(0.5, momentum=0.5),
                                  axis_name="hvd", zero_stage=stage)
    monkeypatch.setenv("HOROVOD_FUSED_UPDATE", "0")
    uo = hvd.DistributedOptimizer(F.sgd(0.5, momentum=0.5),
                                  axis_name="hvd", zero_stage=stage)
    if stage >= 3:
        pf = _run_zero3_steps(fo, _params(), 1.0)
        pu = _run_zero3_steps(uo, _params(), 1.0)
    else:
        pf = _run_steps(fo, _params(), 1.0)
        pu = _run_steps(uo, _params(), 1.0)
    for a, b in zip(jax.tree_util.tree_leaves(pf),
                    jax.tree_util.tree_leaves(pu)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
