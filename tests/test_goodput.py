"""Goodput ledger tests (docs/goodput.md).

The acceptance contract of the attribution layer: phases are exclusive
and conserve wall-clock (they sum to elapsed, ``unattributed`` being
the exact remainder), the honesty bucket stays bounded and nameable,
the fleet merge names the dominant bottleneck with per-rank evidence,
the SLO burn alert fires, and partial/aborted runs keep their
accounting.
"""

import json
import os
import socket
import subprocess
import sys
import time

import pytest

from horovod_tpu.perf import goodput as gp
from test_multiprocess import REPO, run_ranks


@pytest.fixture(autouse=True)
def _fresh_ledger():
    gp.reset()
    yield
    gp.reset()


def _fake_clock():
    t = [0.0]

    def clock():
        return t[0]

    clock.advance = lambda dt: t.__setitem__(0, t[0] + dt)
    return clock


# ---------------------------------------------------------------------------
# Ledger state machine
# ---------------------------------------------------------------------------


def test_phases_are_exclusive_and_conserve_wall_clock():
    clock = _fake_clock()
    led = gp.GoodputLedger(clock=clock)
    led.start()
    clock.advance(2.0)
    led.observe("init", 2.0)
    clock.advance(5.0)
    led.observe("compile", 4.0)          # 1 s gap -> unattributed
    clock.advance(3.0)
    led.observe_step(3.0, compute=2.0, comm_exposed=0.7, input_wait=0.3)
    snap = led.snapshot()
    assert snap["elapsed_s"] == pytest.approx(10.0)
    assert snap["phases"]["init"] == pytest.approx(2.0)
    assert snap["phases"]["compile"] == pytest.approx(4.0)
    assert snap["phases"]["compute"] == pytest.approx(2.0)
    assert snap["phases"]["comm_exposed"] == pytest.approx(0.7)
    assert snap["phases"]["input_wait"] == pytest.approx(0.3)
    assert snap["unattributed_s"] == pytest.approx(1.0)
    total = sum(snap["phases"].values()) + snap["unattributed_s"]
    assert total == pytest.approx(snap["elapsed_s"], rel=1e-9)
    assert snap["goodput_ratio"] == pytest.approx(0.2)
    assert snap["unattributed_ratio"] == pytest.approx(0.1)


def test_unstarted_ledger_is_empty_and_unknown_phase_rejected():
    led = gp.GoodputLedger(clock=_fake_clock())
    assert led.snapshot()["elapsed_s"] == 0.0
    with pytest.raises(ValueError):
        led.observe("naptime", 1.0)
    # "unattributed" is synthesized, never directly observable
    with pytest.raises(ValueError):
        led.observe("unattributed", 1.0)


def test_observe_step_budget_clamps_oversized_parts():
    """A step's parts can never exceed its wall: priority order
    input_wait -> comm_exposed -> compile -> compute, each clamped to
    the remaining budget."""
    clock = _fake_clock()
    led = gp.GoodputLedger(clock=clock)
    led.start()
    clock.advance(1.0)
    led.observe_step(1.0, compute=5.0, comm_exposed=0.8, input_wait=0.5)
    snap = led.snapshot()
    assert snap["phases"]["input_wait"] == pytest.approx(0.5)
    assert snap["phases"]["comm_exposed"] == pytest.approx(0.5)  # clamped
    assert snap["phases"]["compute"] == 0.0  # budget exhausted
    total = sum(snap["phases"].values()) + snap["unattributed_s"]
    assert total == pytest.approx(snap["elapsed_s"])


def test_overattribution_scales_down_to_conserve():
    """Hooks overshooting elapsed (nested spans, clock skew) must not
    break conservation: phases scale down and the overshoot is
    reported."""
    clock = _fake_clock()
    led = gp.GoodputLedger(clock=clock)
    led.start()
    clock.advance(4.0)
    led.observe("checkpoint", 3.0)
    led.observe("compile", 3.0)  # 6 s attributed in 4 s of wall
    snap = led.snapshot()
    total = sum(snap["phases"].values()) + snap["unattributed_s"]
    assert total == pytest.approx(snap["elapsed_s"])
    assert snap["overattributed_s"] == pytest.approx(2.0)
    # proportions preserved
    assert snap["phases"]["checkpoint"] == pytest.approx(2.0)
    assert snap["phases"]["compile"] == pytest.approx(2.0)


def test_span_contextmanager_times_into_phase():
    clock = _fake_clock()
    led = gp.GoodputLedger(clock=clock)
    led.start()
    with led.span("checkpoint"):
        clock.advance(1.5)
    clock.advance(0.5)
    snap = led.snapshot()
    assert snap["phases"]["checkpoint"] == pytest.approx(1.5)
    assert snap["unattributed_s"] == pytest.approx(0.5)


def test_out_of_step_compile_counter_recovered_from_unattributed():
    """Negotiated-program compile wall that happens between steps
    (eager warmup) is recovered from the hvd_compile_seconds_total
    delta — attributed to 'compile', clamped into unattributed wall."""
    from horovod_tpu.runtime import metrics as M

    clock = _fake_clock()
    led = gp.GoodputLedger(clock=clock)
    led.start()  # snapshots the counter baseline
    M.counter("hvd_compile_seconds_total").inc(3.0, path="cold")
    clock.advance(10.0)
    snap = led.snapshot()
    assert snap["phases"]["compile"] == pytest.approx(3.0)
    assert snap["unattributed_s"] == pytest.approx(7.0)
    # ...but it can never claim more than the unattributed gap
    M.counter("hvd_compile_seconds_total").inc(100.0, path="cold")
    snap = led.snapshot()
    assert snap["phases"]["compile"] == pytest.approx(10.0)
    assert snap["unattributed_s"] == pytest.approx(0.0)


def test_reform_split_consumes_its_compile_counter_share():
    """Compile seconds inside a re-form are wall already attributed
    under 'reform' — the counter-delta recovery must not claim
    unattributed wall for them a second time."""
    from horovod_tpu.runtime import metrics as M

    clock = _fake_clock()
    led = gp.GoodputLedger(clock=clock)
    led.start()
    M.counter("hvd_compile_seconds_total").inc(2.0, path="cold")
    clock.advance(8.0)
    led.observe("reform", 5.0, split={"teardown_s": 1.0,
                                      "compile_s": 2.0,
                                      "resync_s": 2.0})
    snap = led.snapshot()
    assert snap["phases"]["reform"] == pytest.approx(5.0)
    assert snap["phases"]["compile"] == pytest.approx(0.0)  # consumed
    assert snap["unattributed_s"] == pytest.approx(3.0)
    assert snap["reform_split"]["compile_s"] == pytest.approx(2.0)


def test_dominant_bottleneck_names_unattributed_too():
    clock = _fake_clock()
    led = gp.GoodputLedger(clock=clock)
    led.start()
    clock.advance(10.0)
    led.observe_step(3.0, compute=3.0, comm_exposed=0.0)
    dom = gp.dominant_bottleneck(led.snapshot())
    assert dom["phase"] == "unattributed"
    assert dom["seconds"] == pytest.approx(7.0)


# ---------------------------------------------------------------------------
# Publication round trip + fleet merge
# ---------------------------------------------------------------------------


def test_publish_and_from_metrics_snapshot_round_trip():
    from horovod_tpu.runtime import metrics as M

    clock = _fake_clock()
    led = gp.GoodputLedger(clock=clock)
    led.start()
    clock.advance(4.0)
    led.observe("init", 1.0)
    led.observe_step(2.0, compute=1.5, comm_exposed=0.5)
    led.publish()
    snap = {"meta": {"rank": 3, "host": "h", "time": time.time()},
            "metrics": M.registry().snapshot()}
    rec = gp.from_metrics_snapshot(snap)
    assert rec["rank"] == 3
    assert rec["elapsed_s"] == pytest.approx(4.0)
    assert rec["phases"]["compute"] == pytest.approx(1.5)
    assert rec["unattributed_s"] == pytest.approx(1.0)
    assert rec["goodput_ratio"] == pytest.approx(1.5 / 4.0)
    # the launcher's own snapshot (rank="launcher") is not a ledger
    snap["meta"]["rank"] = "launcher"
    assert gp.from_metrics_snapshot(snap) is None


def _rank_snap(rank, elapsed, phases, unattributed=0.0):
    return {"rank": rank, "elapsed_s": elapsed, "phases": dict(phases),
            "unattributed_s": unattributed,
            "unattributed_ratio": unattributed / elapsed,
            "goodput_ratio": phases.get("compute", 0.0) / elapsed}


def test_fleet_report_names_dominant_bottleneck_with_evidence():
    r0 = _rank_snap(0, 10.0, {"compute": 8.0, "comm_exposed": 1.0,
                              "init": 1.0})
    r1 = _rank_snap(1, 10.0, {"compute": 4.0, "comm_exposed": 5.0,
                              "init": 1.0})
    rep = gp.fleet_report([r1, r0])  # order-independent
    assert rep["world"] == 2
    assert rep["fleet_goodput"] == pytest.approx(12.0 / 20.0)
    dom = rep["dominant_bottleneck"]
    assert dom["phase"] == "comm_exposed"
    assert dom["rank"] == 1
    assert dom["fleet_seconds"] == pytest.approx(6.0)
    assert dom["rank_seconds"] == pytest.approx(5.0)
    line = gp.evidence_line(rep)
    assert "comm_exposed" in line and "rank 1" in line


def test_fleet_window_and_slo_burn_alert():
    clock = _fake_clock()
    fleet = gp.FleetGoodput(slo=0.5, window_s=60.0, clock=clock)
    base = [_rank_snap(0, 100.0, {"compute": 90.0, "comm_exposed": 5.0},
                       unattributed=5.0)]
    rep = fleet.update(base)
    # first sample: cumulative fallback, healthy
    assert rep["window"]["goodput"] == pytest.approx(0.9)
    assert rep["alert"]["firing"] is False
    assert rep["alert"]["reason"] == "none"
    clock.advance(30.0)
    # 30 s later: only 5 of the 30 new seconds were compute, the rest
    # ate comm_exposed -> windowed goodput collapses while cumulative
    # still looks fine
    cur = [_rank_snap(0, 130.0, {"compute": 95.0, "comm_exposed": 30.0},
                      unattributed=5.0)]
    rep = fleet.update(cur)
    assert rep["fleet_goodput"] == pytest.approx(95.0 / 130.0)
    assert rep["window"]["goodput"] == pytest.approx(5.0 / 30.0,
                                                     abs=1e-5)
    dom = rep["window"]["dominant_bottleneck"]
    assert dom["phase"] == "comm_exposed"
    assert dom["rank"] == 0
    assert dom["fleet_seconds"] == pytest.approx(25.0)
    alert = rep["alert"]
    assert alert["firing"] is True
    assert alert["reason"] == "comm_exposed"
    assert alert["burn_rate"] == pytest.approx(
        (1 - 5.0 / 30.0) / (1 - 0.5), abs=1e-3)


def test_fleet_window_trims_history():
    clock = _fake_clock()
    fleet = gp.FleetGoodput(slo=0.0, window_s=10.0, clock=clock)
    for i in range(20):
        fleet.update([_rank_snap(0, 10.0 + i, {"compute": 5.0 + i})])
        clock.advance(5.0)
    # at 5 s cadence and a 10 s window, the deque stays tiny
    assert len(fleet._hist) <= 4


def test_aggregate_render_carries_goodput_and_age_gauges():
    """The launcher merge path: per-rank published snapshots ->
    aggregate /metrics with fleet goodput, bottleneck evidence, the
    SLO alert, and the snapshot-age staleness gauges."""
    from horovod_tpu.runtime import metrics as M

    now = time.time()

    def _metrics_snap(rank, phases, elapsed, age_s):
        series = [{"labels": {"phase": k}, "value": v}
                  for k, v in phases.items()]
        series.append({"labels": {"phase": "unattributed"}, "value": 0.0})
        return json.dumps({
            "meta": {"rank": rank, "host": "h", "time": now - age_s},
            "metrics": {
                "hvd_wallclock_seconds_total": {
                    "kind": "gauge", "series": series},
                "hvd_goodput_elapsed_seconds": {
                    "kind": "gauge",
                    "series": [{"labels": {}, "value": elapsed}]},
                "hvd_goodput_ratio": {
                    "kind": "gauge",
                    "series": [{"labels": {},
                                "value": phases["compute"] / elapsed}]},
            }})

    store = {
        M.INDEX_KEY: json.dumps({"epoch": 1, "size": 2}),
        M._rank_key(1, 0): _metrics_snap(
            0, {"compute": 9.0, "comm_exposed": 1.0}, 10.0, age_s=0.5),
        M._rank_key(1, 1): _metrics_snap(
            1, {"compute": 2.0, "comm_exposed": 8.0}, 10.0, age_s=90.0),
    }
    fleet = gp.FleetGoodput(slo=0.9, window_s=60.0)
    text = M.aggregate_render(store.get, fleet=fleet)
    assert "hvd_goodput_fleet_ratio 0.55" in text
    assert 'hvd_goodput_bottleneck_seconds{phase="comm_exposed",' \
           'rank="1"} 9' in text
    assert 'hvd_goodput_alert{reason="comm_exposed"} 1' in text
    assert "hvd_goodput_burn_rate" in text
    # satellite: a wedged publisher is visible as snapshot age
    ages = {}
    for line in text.splitlines():
        if line.startswith("hvd_metrics_snapshot_age_seconds{"):
            label, val = line.rsplit(" ", 1)
            ages['rank="1"' in label] = float(val)
    assert ages[False] < 30.0      # rank 0 is fresh
    assert ages[True] >= 89.0      # rank 1's publisher is wedged
    assert fleet.last["dominant_bottleneck"]["rank"] == 1


# ---------------------------------------------------------------------------
# data_wait / input starvation
# ---------------------------------------------------------------------------


def test_data_wait_outside_steps_lands_on_ledger():
    import horovod_tpu as hvd

    with hvd.data_wait("unit"):
        time.sleep(0.05)
    snap = gp.ledger().snapshot()
    assert snap["phases"]["input_wait"] >= 0.04


def test_data_wait_noise_floor_filters_short_spans(monkeypatch):
    import horovod_tpu as hvd
    from horovod_tpu.runtime import metrics as M

    monkeypatch.setenv("HOROVOD_DATA_WAIT_MIN_SECONDS", "5")
    before = M.counter("hvd_data_wait_seconds_total").total()
    with hvd.data_wait("filtered"):
        time.sleep(0.01)
    assert M.counter("hvd_data_wait_seconds_total").total() == before
    assert gp.ledger().snapshot().get("phases", {}).get(
        "input_wait", 0.0) == 0.0


def test_input_starvation_dominates_report():
    """The blind-spot scenario: a slow iterator starves fast steps —
    the ledger (not the device observatory) names input_wait."""
    import jax.numpy as jnp

    import horovod_tpu as hvd

    def slow_loader():
        for _ in range(3):
            time.sleep(0.15)
            yield jnp.ones((4,))

    for i, batch in enumerate(hvd.wrap_data_loader(slow_loader(),
                                                   "starved")):
        with hvd.trace_step(step=i):
            (batch * 2).sum().block_until_ready()
    snap = gp.ledger().snapshot()
    assert snap["phases"]["input_wait"] >= 0.4
    assert snap["phases"]["input_wait"] > snap["phases"]["compute"]
    dom = gp.dominant_bottleneck(snap)
    assert dom["phase"] == "input_wait", snap
    rep = gp.fleet_report([snap])
    assert rep["phase_totals"]["input_wait"] >= 0.4


def test_trace_step_splits_in_step_data_wait():
    import horovod_tpu as hvd

    with hvd.trace_step(step=0):
        with hvd.data_wait("in_step"):
            time.sleep(0.1)
        time.sleep(0.05)
    snap = gp.ledger().snapshot()
    assert snap["phases"]["input_wait"] == pytest.approx(0.1, abs=0.05)
    assert snap["phases"]["compute"] == pytest.approx(0.05, abs=0.05)
    total = sum(snap["phases"].values()) + snap["unattributed_s"]
    assert total == pytest.approx(snap["elapsed_s"], rel=0.02)


# ---------------------------------------------------------------------------
# Dumps + CLI
# ---------------------------------------------------------------------------


def test_dump_and_cli_report_round_trip(tmp_path, capsys):
    from horovod_tpu.perf.__main__ import main as perf_main

    for rank, exposed in ((0, 1.0), (1, 6.0)):
        clock = _fake_clock()
        led = gp.GoodputLedger(clock=clock)
        led.start()
        clock.advance(10.0)
        led.observe_step(9.0, compute=9.0 - exposed,
                         comm_exposed=exposed)
        snap = led.snapshot()
        snap["rank"] = rank
        path = tmp_path / f"goodput-r{rank}-g1.json"
        path.write_text(json.dumps(snap))
    rc = perf_main(["goodput", str(tmp_path)])
    human = capsys.readouterr().out
    assert rc == 0
    assert "rank 0" in human and "rank 1" in human
    assert "dominant bottleneck: comm_exposed" in human
    rc = perf_main(["goodput", str(tmp_path), "--json", "--slo",
                    "0.9"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert rep["world"] == 2
    assert rep["dominant_bottleneck"]["rank"] == 1
    assert rep["alert"]["firing"] is True
    # empty dir exits 1 (nothing to report is a failure, not a pass)
    empty = tmp_path / "empty"
    empty.mkdir()
    assert perf_main(["goodput", str(empty)]) == 1
    capsys.readouterr()


def test_load_snapshots_dedupes_per_generation_dumps(tmp_path):
    """Regression: every elastic re-form's teardown dumps the SAME
    rank's cumulative ledger under a new generation — loading a dump
    dir must keep each rank's newest ledger, not sum the overlapping
    snapshots into a phantom world."""
    for gen, elapsed in ((1, 100.0), (2, 200.0)):
        snap = {"rank": 0, "generation": gen, "elapsed_s": elapsed,
                "phases": {"compute": 0.75 * elapsed},
                "unattributed_s": 0.25 * elapsed,
                "unattributed_ratio": 0.25, "goodput_ratio": 0.75}
        (tmp_path / f"goodput-r0-g{gen}.json").write_text(
            json.dumps(snap))
    snaps = gp.load_snapshots(str(tmp_path))
    assert len(snaps) == 1, snaps
    assert snaps[0]["generation"] == 2
    rep = gp.fleet_report(snaps)
    assert rep["world"] == 1
    assert rep["elapsed_s"] == pytest.approx(200.0)
    assert rep["fleet_goodput"] == pytest.approx(0.75)


def test_fleet_window_label_covers_actual_span():
    """Regression: with sparse updates the retained delta base is
    older than window_s — the reported window seconds must state the
    span the deltas actually cover, not the configured window."""
    clock = _fake_clock()
    fleet = gp.FleetGoodput(slo=0.0, window_s=300.0, clock=clock)
    fleet.update([_rank_snap(0, 100.0, {"compute": 90.0})])
    clock.advance(1200.0)
    rep = fleet.update([_rank_snap(0, 1300.0, {"compute": 1000.0})])
    assert rep["window"]["seconds"] == pytest.approx(1200.0)


def test_ledger_dump_api_writes_named_file(tmp_path):
    clock = _fake_clock()
    led = gp.GoodputLedger(clock=clock)
    led.start()
    clock.advance(2.0)
    led.observe("compile", 1.0)
    path = led.dump("unit-test", directory=str(tmp_path))
    assert path is not None and os.path.exists(path)
    assert os.path.basename(path).startswith("goodput-r")
    snaps = gp.load_snapshots(str(tmp_path))
    assert len(snaps) == 1
    assert snaps[0]["phases"]["compile"] == pytest.approx(1.0)
    assert snaps[0]["reason"] == "unit-test"


def test_flight_dump_carries_goodput_event(tmp_path):
    from horovod_tpu.runtime import flight

    flight.reset()
    clock = _fake_clock()
    led = gp.GoodputLedger(clock=clock)
    # swap the global ledger so flight's sys.modules lookup finds it
    gp._ledger = led
    led.start()
    clock.advance(3.0)
    led.observe("compile", 2.0)
    flight.record("unit", x=1)
    out = flight.dump("unit-test", directory=str(tmp_path))
    assert out is not None
    events = [json.loads(line)
              for line in open(out).read().splitlines()[1:]]
    good = [e for e in events if e["kind"] == "goodput"]
    assert good, events
    assert good[0]["compile_s"] == pytest.approx(2.0)
    assert good[0]["elapsed_s"] == pytest.approx(3.0)
    flight.reset()


def test_bench_result_extras_feed_cli(tmp_path, capsys):
    from horovod_tpu.perf.__main__ import main as perf_main

    result = {"metric": "m", "value": 1.0, "extra": {
        "goodput_ratio": 0.25,
        "goodput": {"init_s": 1.0, "compile_s": 5.0, "compute_s": 2.5,
                    "input_wait_s": 0.5, "comm_exposed_s": 0.0,
                    "checkpoint_s": 0.0, "reform_s": 0.0,
                    "unattributed_s": 1.0, "elapsed_s": 10.0,
                    "unattributed_ratio": 0.1}}}
    p = tmp_path / "bench.json"
    p.write_text(json.dumps(result))
    rc = perf_main(["goodput", str(p), "--json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert rep["ranks"][0]["phases"]["compile"] == pytest.approx(5.0)
    assert rep["dominant_bottleneck"]["phase"] == "compile"


# ---------------------------------------------------------------------------
# 2-proc acceptance: the fleet report names the straggler's phase+rank
# ---------------------------------------------------------------------------


@pytest.mark.multiprocess
@pytest.mark.slow  # ~25 s fault-injected 2-proc run (ci.sh full suite)
def test_2proc_delay_fault_names_rank1_comm_exposed():
    """Acceptance: with delay@rank1 control-plane faults, the fleet
    goodput report names rank 1 / comm_exposed as the dominant
    bottleneck, and every rank's ledger conserves wall-clock."""
    outs = run_ranks("""
        import json as _json
        for i in range(2):
            with hvd.trace_step(step=i):
                out = hvd.allreduce(jnp.ones((8,)) * (i + 1),
                                    op=hvd.Sum, name="gp%d" % i)
            assert np.allclose(np.asarray(out), 2.0 * (i + 1))
        from horovod_tpu.perf import goodput as gp
        snap = gp.ledger().snapshot()
        tot = sum(snap["phases"].values()) + snap["unattributed_s"]
        assert abs(tot - snap["elapsed_s"]) \\
            <= 0.02 * snap["elapsed_s"] + 1e-6, (tot, snap)
        print("GOODPUT-JSON:" + _json.dumps(snap), flush=True)
    """, extra_env={
        # q-delay makes rank 1 submit late (both ranks wait the round
        # out); the p-delay hits only rank 1's response reads, so its
        # exposed-comm wall is strictly the larger — the evidence the
        # fleet report must surface.
        "HOROVOD_FAULT_SPEC": ("delay@rank1:q/*:0.5s,"
                               "delay@rank1:p/*:0.5s"),
        "HOROVOD_HEARTBEAT_TIMEOUT_SECONDS": "120",
    })
    snaps = []
    for out in outs:
        lines = [ln for ln in out.splitlines()
                 if ln.startswith("GOODPUT-JSON:")]
        assert lines, out
        snaps.append(json.loads(lines[0].split(":", 1)[1]))
    rep = gp.fleet_report(snaps)
    assert rep["world"] == 2
    dom = rep["dominant_bottleneck"]
    assert dom["phase"] == "comm_exposed", rep
    assert dom["rank"] == 1, rep
    by_rank = {s["rank"]: s for s in snaps}
    assert by_rank[1]["phases"]["comm_exposed"] \
        > by_rank[0]["phases"]["comm_exposed"], by_rank
    assert by_rank[1]["phases"]["comm_exposed"] > 0.8


# ---------------------------------------------------------------------------
# Bench smoke: a fault-killed run still stamps its partial ledger
# ---------------------------------------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.multiprocess
@pytest.mark.slow  # ~60 s 2-proc bench with an injected death
def test_bench_partial_run_keeps_goodput_ledger(tmp_path):
    """Satellite: a bench run ending by abort (die:rank1 fault) still
    stamps the partial goodput ledger into extras — phase accounting
    must survive exactly the runs where it matters."""
    port = _free_port()
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.update({
            "HOROVOD_PLATFORM": "cpu",
            "JAX_PLATFORMS": "cpu",
            "HOROVOD_RANK": str(r),
            "HOROVOD_SIZE": "2",
            "HOROVOD_LOCAL_RANK": str(r),
            "HOROVOD_LOCAL_SIZE": "2",
            "HOROVOD_COORDINATOR_ADDR": f"localhost:{port}",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
            # eager section only: the negotiated data plane raises
            # RanksDownError promptly when the peer dies (an in-trace
            # model step would ride out the slow gloo deadline instead)
            "BENCH_MODELS": "none",
            "BENCH_EAGER": "1",
            "BENCH_PROBE_ATTEMPTS": "1",
            # round1: rank 1 dies at the first DATA round, after the
            # round-0 handshake completed — plain die:rank1 could fire
            # before rank 1 ever published a heartbeat, leaving rank 0
            # to ride out the handshake wire deadline instead of the
            # prompt staleness abort
            "HOROVOD_FAULT_SPEC": "die:rank1:round1",
            "HOROVOD_HEARTBEAT_INTERVAL": "0.5",
            "HOROVOD_HEARTBEAT_TIMEOUT_SECONDS": "5",
        })
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, cwd=str(tmp_path), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    for r, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError(f"bench rank {r} timed out")
        outs.append(out)
    assert procs[1].returncode == 137, outs[1][-1000:]
    result = None
    for line in reversed(outs[0].strip().splitlines()):
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            result = obj
            break
    assert result is not None, outs[0][-2000:]
    extra = result["extra"]
    # the run is partial (no headline) but the ledger survived
    assert result["value"] is None
    good = extra.get("goodput")
    assert good and good["elapsed_s"] > 0, extra
    assert "goodput_ratio" in extra
    total = (sum(v for k, v in good.items()
                 if k.endswith("_s") and k not in ("elapsed_s",
                                                   "unattributed_s"))
             + good["unattributed_s"])
    assert total == pytest.approx(good["elapsed_s"], rel=0.03), good
