"""Contract-faithful FAKE of the pyspark surfaces `horovod_tpu.spark`
uses — test infrastructure, NOT pyspark code.

pyspark cannot be installed in this image, so the barrier-execution
path of ``horovod_tpu.spark.run`` could previously only be unit-tested
through its pure functions.  This fake honors the *contract* the real
library provides, with the same process model:

- each barrier task runs in its OWN python process (Spark's python
  workers are processes; hvd ranks need process isolation for
  jax.distributed),
- ``BarrierTaskContext.allGather`` is a real synchronizing collective
  across those processes (backed by the repo's own KV store),
- ``mapPartitions(...).collect()`` ships the task closure to the
  workers by cloudpickle, like Spark does, and fails the job if any
  task fails.

What it does NOT fake: scheduling, data partitioning, shuffle, or a
real multi-host cluster — a run against genuine Spark remains
unvalidated (docs/spark.md says so).
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile

__fake__ = True


class _TaskInfo:
    def __init__(self, address: str):
        self.address = address


class BarrierTaskContext:
    """Per-task context; in a worker process `get()` returns the one
    instance wired to the job's KV store."""

    _current: "BarrierTaskContext | None" = None

    def __init__(self, rank: int, n: int, kv_addr: str, kv_port: int):
        self._rank = rank
        self._n = n
        self._kv_addr = kv_addr
        self._kv_port = kv_port
        self._round = 0
        self._kv = None

    @classmethod
    def get(cls) -> "BarrierTaskContext":
        if cls._current is None:
            raise RuntimeError("not inside a barrier task")
        return cls._current

    def partitionId(self) -> int:
        return self._rank

    def getTaskInfos(self):
        # all tasks on this host, like a single-executor local cluster
        return [_TaskInfo(f"127.0.0.1:{41000 + r}")
                for r in range(self._n)]

    def _client(self):
        if self._kv is None:
            from horovod_tpu.runtime.kvstore import KVStoreClient

            self._kv = KVStoreClient(self._kv_addr, self._kv_port,
                                     secret=b"")
        return self._kv

    def allGather(self, message: str = "") -> list:
        """Synchronizing all-gather of one string per task (the real
        API's semantics: returns all tasks' messages, in partition
        order, after every task has arrived)."""
        kv = self._client()
        kv.set(f"barrier/ag/{self._round}/{self._rank}", message)
        out = [kv.get_blocking(f"barrier/ag/{self._round}/{r}",
                               timeout_s=120.0)
               for r in range(self._n)]
        self._round += 1
        return out

    def barrier(self) -> None:
        self.allGather("")


class _BarrierRDD:
    def __init__(self, n: int):
        self._n = n

    def mapPartitions(self, task):
        return _BarrierJob(self._n, task)


class _BarrierJob:
    def __init__(self, n: int, task):
        self._n = n
        self._task = task

    def collect(self) -> list:
        import cloudpickle

        from horovod_tpu.runtime.kvstore import KVStoreServer

        server = KVStoreServer(port=0, secret=b"")
        tmp = tempfile.mkdtemp(prefix="fake_spark_")
        payload = os.path.join(tmp, "task.pkl")
        with open(payload, "wb") as f:
            cloudpickle.dump(self._task, f)
        procs = []
        try:
            for r in range(self._n):
                env = dict(os.environ)
                env.update({
                    "FAKE_SPARK_RANK": str(r),
                    "FAKE_SPARK_NP": str(self._n),
                    "FAKE_SPARK_KV": f"127.0.0.1:{server.port}",
                    "FAKE_SPARK_PAYLOAD": payload,
                    "FAKE_SPARK_RESULT":
                        os.path.join(tmp, f"result.{r}.pkl"),
                    "PYTHONPATH": os.pathsep.join(
                        [os.path.dirname(__file__),
                         env.get("PYTHONPATH", "")]),
                })
                procs.append(subprocess.Popen(
                    [sys.executable, "-c",
                     "import pyspark; pyspark._task_main()"],
                    env=env))
            rcs = [p.wait(timeout=180) for p in procs]
            if any(rcs):
                raise RuntimeError(
                    f"barrier stage failed: task exit codes {rcs}")
            out = []
            for r in range(self._n):
                with open(os.path.join(tmp, f"result.{r}.pkl"),
                          "rb") as f:
                    out.extend(pickle.load(f))
            return out
        finally:
            import shutil

            for p in procs:
                if p.poll() is None:
                    p.kill()
            server.stop()
            shutil.rmtree(tmp, ignore_errors=True)


class _RDD:
    def __init__(self, n: int):
        self._n = n

    def barrier(self) -> _BarrierRDD:
        return _BarrierRDD(self._n)


class SparkContext:
    _active_spark_context: "SparkContext | None" = None

    def __init__(self, defaultParallelism: int = 2):
        self.defaultParallelism = defaultParallelism
        SparkContext._active_spark_context = self

    def parallelize(self, data, numSlices: int):
        return _RDD(numSlices)

    def stop(self) -> None:
        SparkContext._active_spark_context = None


def _task_main() -> None:
    """Worker-process entry: build the context, run the shipped task,
    persist its yielded items."""
    rank = int(os.environ["FAKE_SPARK_RANK"])
    n = int(os.environ["FAKE_SPARK_NP"])
    addr, port = os.environ["FAKE_SPARK_KV"].rsplit(":", 1)
    BarrierTaskContext._current = BarrierTaskContext(
        rank, n, addr, int(port))
    with open(os.environ["FAKE_SPARK_PAYLOAD"], "rb") as f:
        task = pickle.load(f)
    out = list(task(iter(())))
    with open(os.environ["FAKE_SPARK_RESULT"], "wb") as f:
        pickle.dump(out, f)
